"""scripts/ingest.py end-to-end: the bulk-indexing CLI over real manager
stacks on CPU, including the chunked caption path (dense sweep of chunk
k+1 overlaps chunk k's captions) where row order and whole-run stats must
survive chunking."""

from __future__ import annotations

import json
import os
import sys

import pytest

from tests.clip_fixtures import make_clip_model_dir, png_bytes
from tests.test_vlm import make_vlm_model_dir

_SCRIPTS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)
if _SCRIPTS_DIR not in sys.path:
    sys.path.insert(0, _SCRIPTS_DIR)
import ingest as ingest_cli  # noqa: E402

pytestmark = pytest.mark.integration


@pytest.fixture(scope="module")
def cache(tmp_path_factory):
    root = tmp_path_factory.mktemp("ingestcli")
    make_clip_model_dir(root)
    make_vlm_model_dir(root)  # writes <root>/models/TinyVLM directly
    photos = root / "photos"
    photos.mkdir()
    for i in range(80):  # chunk size floors at 64 -> two chunks (64 + 16)
        (photos / f"p{i:03d}.png").write_bytes(png_bytes(seed=i % 5))
    (root / "cfg.yaml").write_text(f"""
metadata:
  version: "1.0.0"
  region: other
  cache_dir: {root}
deployment:
  mode: hub
  services: [clip, vlm]
server:
  port: 50933
  host: 127.0.0.1
  mdns:
    enabled: false
services:
  clip:
    enabled: true
    package: lumen_tpu.serving.services.clip_service
    import_info:
      registry_class: lumen_tpu.serving.services.clip_service.ClipService
    backend_settings: {{dtype: float32, batch_size: 4}}
    models:
      clip: {{model: TinyCLIP, runtime: jax, dataset: Tiny}}
  vlm:
    enabled: true
    package: lumen_tpu.serving.services.vlm_service
    import_info:
      registry_class: lumen_tpu.serving.services.vlm_service.VlmService
    backend_settings: {{dtype: float32, batch_size: 2}}
    models:
      vlm: {{model: TinyVLM, runtime: jax}}
""")
    return root


class TestIngestCli:
    def test_chunked_caption_run_preserves_order_and_stats(self, cache, capsys):
        out = cache / "idx.jsonl"
        rc = ingest_cli.main([
            "--config", str(cache / "cfg.yaml"),
            "--input", str(cache / "photos"),
            "--output", str(out),
            "--families", "clip,vlm",
            "--caption-max-tokens", "2",
            "--batch-size", "8",  # divisible by the 8-device test mesh
            "--platform", "cpu",
        ])
        assert rc == 0
        rows = [json.loads(l) for l in open(out)]
        assert len(rows) == 80
        paths = [r["path"] for r in rows]
        assert paths == sorted(paths)
        assert all(r.get("caption") for r in rows)
        assert all("clip_embedding" in r for r in rows)
        stats_line = [l for l in capsys.readouterr().out.splitlines() if "stage stats" in l][-1]
        stats = json.loads(stats_line.split("stage stats: ")[1])
        assert stats["items"] == 80

    def test_resume_skips_recorded_rows_and_drops_torn_tail(self, cache, capsys):
        """An interrupted index (complete rows + one torn line) resumes:
        finished rows are kept verbatim, the torn tail is truncated, and
        only the remaining images are processed and appended."""
        photos = cache / "photos"
        all_paths = sorted(str(photos / n) for n in os.listdir(photos))
        out = cache / "resume.jsonl"
        # Simulate the interruption: first 70 rows complete, then a torn line.
        with open(out, "w") as f:
            for p in all_paths[:70]:
                f.write(json.dumps({"path": p, "clip_embedding": "kept"}) + "\n")
            f.write('{"path": "' + all_paths[70] + '", "clip_emb')  # no newline
        args = [
            "--config", str(cache / "cfg.yaml"),
            "--input", str(photos),
            "--output", str(out),
            "--families", "clip",
            "--batch-size", "8",
            "--platform", "cpu",
            "--resume",
        ]
        assert ingest_cli.main(args) == 0
        rows = [json.loads(l) for l in open(out)]
        assert len(rows) == 80
        assert [r["path"] for r in rows] == all_paths[:70] + all_paths[70:]
        # Pre-existing rows were kept verbatim, not regenerated.
        assert all(r["clip_embedding"] == "kept" for r in rows[:70])
        assert all(r["clip_embedding"] != "kept" for r in rows[70:])
        assert "resume: 70 image(s) already indexed, 10 to go" in capsys.readouterr().out
        # A second resume over a complete index is a no-op exiting 0.
        assert ingest_cli.main(args) == 0
        assert "nothing to do" in capsys.readouterr().out
        assert len(open(out).read().splitlines()) == 80
