"""Weight-only int8 decoder quantization tests.

The quantized model must stay close to the fp model (per-channel symmetric
int8 keeps relative weight error ~0.4%) and serve through the same manager
surface. No reference equivalent — the reference's quantization story is
picking fp16 ONNX files (``packages/lumen-clip/src/lumen_clip/backends/
onnxrt_backend.py:245-289``); this is a TPU bandwidth optimization for the
autoregressive decode path.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lumen_tpu.models.vlm import ChatMessage, VLMManager
from lumen_tpu.models.vlm.convert import quantize_decoder_int8
from tests.test_vlm import make_vlm_model_dir


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return make_vlm_model_dir(tmp_path_factory.mktemp("vlmq"))


def _mgr(model_dir, quantize):
    mgr = VLMManager(
        model_dir,
        dtype="float32",
        max_seq=128,
        max_new_cap=8,
        prefill_buckets=(16, 32),
        quantize=quantize,
    )
    mgr.initialize()
    return mgr


class TestQuantTransform:
    def test_kernels_become_q_and_scale(self, model_dir):
        mgr = _mgr(model_dir, None)
        try:
            params = jax.tree.map(np.asarray, mgr.params)
            qparams = quantize_decoder_int8(params)
            attn = qparams["decoder"]["layers_0"]["attn"]["q_proj"]
            assert attn["q"].dtype == np.int8
            assert attn["scale"].dtype == np.float32
            assert "kernel" not in attn
            assert "bias" in attn  # biases untouched
            # embeddings + norms untouched
            assert "embedding" in qparams["decoder"]["embed_tokens"]
            assert "scale" in qparams["decoder"]["final_norm"]
            # reconstruction error bounded by one quantization step
            w = params["decoder"]["layers_0"]["attn"]["q_proj"]["kernel"]
            rec = attn["q"].astype(np.float32) * attn["scale"]
            step = np.abs(w).max(axis=0) / 127.0
            assert np.all(np.abs(rec - w) <= step[None, :] * 0.51 + 1e-8)
        finally:
            mgr.close()

    def test_moe_banks_stay_fp(self):
        qparams = quantize_decoder_int8(
            {
                "decoder": {
                    "layers_0": {
                        "mlp": {
                            "w_gate": np.ones((2, 4, 8), np.float32),
                            "router": np.ones((4, 2), np.float32),
                            "shared": {"gate_proj": {"kernel": np.ones((4, 8), np.float32)}},
                        }
                    }
                }
            }
        )
        mlp = qparams["decoder"]["layers_0"]["mlp"]
        assert mlp["w_gate"].dtype == np.float32  # bank untouched
        assert mlp["router"].dtype == np.float32
        assert mlp["shared"]["gate_proj"]["q"].dtype == np.int8  # shared expert quantized


class TestQuantServing:
    @pytest.mark.parametrize("kernel", ["dequant", "dynamic"])
    def test_quantized_manager_close_to_fp(self, model_dir, kernel, monkeypatch):
        monkeypatch.setenv("LUMEN_Q8_KERNEL", kernel)
        fp = _mgr(model_dir, None)
        q8 = _mgr(model_dir, "int8")
        assert q8.cfg.decoder.weight_quant_kernel == kernel
        try:
            # int8 params loaded where expected
            attn = q8.params["decoder"]["layers_0"]["attn"]["q_proj"]
            assert attn["q"].dtype == jnp.int8
            msgs = [ChatMessage(role="user", content="describe")]
            out_fp = fp.generate(msgs, max_new_tokens=6)
            out_q8 = q8.generate(msgs, max_new_tokens=6)
            assert len(out_q8.tokens) > 0 and out_fp.tokens
            # Greedy token agreement on a tiny random model is not
            # guaranteed under quantization noise; logit closeness is the
            # right gate.
            ids = np.asarray([[5, 9, 3, 7]], np.int32)
            lf = np.asarray(fp.model.apply({"params": fp.params}, jnp.asarray(ids), None), np.float32)
            lq = np.asarray(q8.model.apply({"params": q8.params}, jnp.asarray(ids), None), np.float32)
            cos = (lf * lq).sum() / (np.linalg.norm(lf) * np.linalg.norm(lq))
            assert cos > 0.98, cos
        finally:
            fp.close()
            q8.close()

    def test_invalid_quantize_rejected(self, model_dir):
        with pytest.raises(ValueError, match="quantize"):
            VLMManager(model_dir, quantize="int4")

    def test_invalid_q8_kernel_rejected(self, model_dir, monkeypatch):
        monkeypatch.setenv("LUMEN_Q8_KERNEL", "magic")
        with pytest.raises(ValueError, match="LUMEN_Q8_KERNEL"):
            VLMManager(model_dir, quantize="int8")

    def test_dynamic_kernel_matches_dequant_logits(self):
        """Same q+scale params through both formulations: activation
        rounding is the only difference, so logits stay close."""
        import dataclasses

        import jax

        from lumen_tpu.models.vlm.modeling import DecoderConfig, QDense

        x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 32), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
        scale = np.maximum(np.abs(np.asarray(w)).max(axis=0) / 127.0, 1e-8)
        q = np.clip(np.round(np.asarray(w) / scale), -127, 127).astype(np.int8)
        params = {
            "params": {
                "q": jnp.asarray(q),
                "scale": jnp.asarray(scale, jnp.float32),
                "bias": jnp.zeros((16,), jnp.float32),
            }
        }
        y_deq = QDense(16, kernel_mode="dequant").apply(params, x)
        y_dyn = QDense(16, kernel_mode="dynamic").apply(params, x)
        ref = x @ w
        # both track the fp product; dynamic adds only activation rounding
        for y in (y_deq, y_dyn):
            cos = float(
                (np.asarray(y) * np.asarray(ref)).sum()
                / (np.linalg.norm(np.asarray(y)) * np.linalg.norm(np.asarray(ref)))
            )
            assert cos > 0.999, cos
        np.testing.assert_allclose(
            np.asarray(y_dyn), np.asarray(y_deq), rtol=0.05, atol=0.05
        )
        # the factory actually threads the mode into the module it builds
        from lumen_tpu.models.vlm.modeling import _dense

        cfg = dataclasses.replace(
            DecoderConfig(), weight_quant="int8", weight_quant_kernel="dynamic"
        )
        mod = _dense(cfg, 16, name="p", use_bias=True, dtype=jnp.float32)
        assert isinstance(mod, QDense) and mod.kernel_mode == "dynamic"
        # unknown modes raise instead of silently running dequant
        with pytest.raises(ValueError, match="kernel_mode"):
            QDense(16, kernel_mode="dyanmic").apply(params, x)


class TestQ8RouteGate:
    """ISSUE 5 satellite: the VLM decode route gets the same warmup A/B
    auto-fallback the CLIP q8 route has — q8 only engages when it wins."""

    def test_bf16_pin_skips_quantization(self, model_dir, monkeypatch):
        monkeypatch.setenv("LUMEN_VLM_Q8_ROUTE", "bf16")
        mgr = _mgr(model_dir, "int8")
        try:
            assert mgr.quant_route == "bf16"
            assert mgr.cfg.decoder.weight_quant is None
            # No (q, scale) leaves anywhere: quantization never ran.
            attn = mgr.params["decoder"]["layers_0"]["attn"]["q_proj"]
            assert "q" not in attn and "kernel" in attn
            out = mgr.generate([ChatMessage(role="user", content="describe")], max_new_tokens=4)
            assert out.tokens
        finally:
            mgr.close()

    def test_auto_without_warmup_honors_opt_in(self, model_dir, monkeypatch):
        monkeypatch.delenv("LUMEN_VLM_Q8_ROUTE", raising=False)
        mgr = _mgr(model_dir, "int8")  # warmup=False: nothing to time against
        try:
            assert mgr.quant_route == "int8"
            attn = mgr.params["decoder"]["layers_0"]["attn"]["q_proj"]
            assert attn["q"].dtype == jnp.int8
        finally:
            mgr.close()

    @pytest.mark.parametrize("q8_tps,expect_route", [(50.0, "bf16"), (400.0, "int8")])
    def test_warmup_ab_picks_winner(self, model_dir, monkeypatch, q8_tps, expect_route):
        """The A/B verdict follows the measurement (timing monkeypatched
        for determinism: bf16 pinned at 100 tokens/s)."""
        import os

        monkeypatch.setenv("LUMEN_VLM_Q8_ROUTE", "auto")
        # The verdict persists to disk so real boots skip the probe; THIS
        # test measures the probe itself, so clear any cached verdict a
        # sibling parametrization left behind.
        verdict_path = os.path.join(model_dir, ".lumen_q8_verdict.json")
        if os.path.exists(verdict_path):
            os.unlink(verdict_path)

        def fake_time(self, model, cfg, params, quantized):
            return q8_tps if quantized else 100.0

        monkeypatch.setattr(VLMManager, "_time_decode_route", fake_time)
        mgr = VLMManager(
            model_dir, dtype="float32", max_seq=128, max_new_cap=8,
            prefill_buckets=(16, 32), quantize="int8", warmup=True,
        )
        mgr.initialize()
        try:
            assert mgr.quant_route == expect_route
            assert mgr.quant_speedup == pytest.approx(q8_tps / 100.0)
            from lumen_tpu.utils.metrics import metrics

            gauge = metrics.snapshot()["gauges"][f"vlm-quant:{mgr.model_id}"]
            assert gauge["int8_active"] == (1 if expect_route == "int8" else 0)
            assert gauge["q8_speedup_pct"] == pytest.approx(q8_tps, abs=0.2)
            # The capability surface reflects the real route.
            from lumen_tpu.serving.services.vlm_service import VlmService

            cap = VlmService(mgr).capability()
            assert ("int8" in list(cap.precisions)) == (expect_route == "int8")
            assert cap.extra["quant_route"] == expect_route
            out = mgr.generate([ChatMessage(role="user", content="describe")], max_new_tokens=4)
            assert out.tokens
        finally:
            mgr.close()
        # close() unregisters the route gauge.
        from lumen_tpu.utils.metrics import metrics

        assert f"vlm-quant:{mgr.model_id}" not in metrics.snapshot().get("gauges", {})

    def test_verdict_persists_and_skips_reprobe(self, model_dir, monkeypatch):
        """BENCH_r05 measured q8 decode at 0.03x bf16, yet every boot
        re-ran the losing probe: the verdict now lands on disk next to the
        weights (keyed model@revision) and the next auto+warmup boot skips
        the A/B entirely. An explicit pin still bypasses the cache."""
        import json as _json
        import os

        monkeypatch.setenv("LUMEN_VLM_Q8_ROUTE", "auto")
        verdict_path = os.path.join(model_dir, ".lumen_q8_verdict.json")
        if os.path.exists(verdict_path):
            os.unlink(verdict_path)
        probes = []

        def fake_time(self, model, cfg, params, quantized):
            probes.append(quantized)
            return 50.0 if quantized else 100.0  # q8 loses -> bf16

        monkeypatch.setattr(VLMManager, "_time_decode_route", fake_time)

        def boot():
            mgr = VLMManager(
                model_dir, dtype="float32", max_seq=128, max_new_cap=8,
                prefill_buckets=(16, 32), quantize="int8", warmup=True,
            )
            mgr.initialize()
            return mgr

        mgr1 = boot()
        try:
            assert mgr1.quant_route == "bf16" and len(probes) == 2
            with open(verdict_path, encoding="utf-8") as f:
                saved = _json.load(f)
            assert saved["route"] == "bf16"
            assert saved["model"] == f"{mgr1.info.name}@{mgr1.info.version}"
        finally:
            mgr1.close()
        mgr2 = boot()  # cached verdict: no new probes
        try:
            assert mgr2.quant_route == "bf16" and len(probes) == 2
            assert mgr2.quant_speedup == pytest.approx(0.5)
        finally:
            mgr2.close()
        # A mangled cache falls through to a fresh probe, not a crash.
        with open(verdict_path, "w", encoding="utf-8") as f:
            f.write("{not json")
        mgr3 = boot()
        try:
            assert mgr3.quant_route == "bf16" and len(probes) == 4
        finally:
            mgr3.close()
        # An explicit pin never consults the cache.
        with open(verdict_path, "w", encoding="utf-8") as f:
            _json.dump({"model": f"{mgr3.info.name}@{mgr3.info.version}", "route": "bf16"}, f)
        monkeypatch.setenv("LUMEN_VLM_Q8_ROUTE", "int8")
        mgr4 = boot()
        try:
            assert mgr4.quant_route == "int8" and len(probes) == 4
        finally:
            mgr4.close()
        os.unlink(verdict_path)


class TestUntiedLmHead:
    def test_untied_lm_head_quantizes_and_gates(self):
        """tie_word_embeddings=False ships an lm_head kernel; the quantized
        init tree must expect q+scale there (review finding: plain nn.Dense
        made every untied + int8 load crash at the shape gate)."""
        import dataclasses

        from lumen_tpu.models.vlm.modeling import VLMConfig, VLMModel
        from lumen_tpu.runtime.weights import assert_tree_shapes

        base = VLMConfig.tiny()
        fp_cfg = dataclasses.replace(
            base, decoder=dataclasses.replace(base.decoder, tie_word_embeddings=False)
        )
        q_cfg = dataclasses.replace(
            fp_cfg,
            decoder=dataclasses.replace(fp_cfg.decoder, weight_quant="int8"),
        )
        dummy = (jnp.zeros((1, 4), jnp.int32),)
        fp_params = VLMModel(fp_cfg).init(jax.random.PRNGKey(0), *dummy)["params"]
        q_init = jax.eval_shape(
            lambda: VLMModel(q_cfg).init(jax.random.PRNGKey(0), *dummy)["params"]
        )
        quantized = quantize_decoder_int8(jax.tree.map(np.asarray, fp_params))
        assert quantized["decoder"]["lm_head"]["q"].dtype == np.int8
        assert_tree_shapes(quantized, q_init)  # must not raise

        # and the quantized untied model actually runs
        logits = VLMModel(q_cfg).apply(
            {"params": quantized}, jnp.asarray([[1, 2, 3]], jnp.int32), None
        )
        assert logits.shape == (1, 3, q_cfg.decoder.vocab_size)
        assert bool(jnp.isfinite(logits).all())
