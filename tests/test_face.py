"""Face family tests: decode math, conversion layout, manager pipeline,
and the gRPC service."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.clip_fixtures import png_bytes, random_variables as _random_variables


def make_face_model_dir(tmp_path, det_size=64, rec_size=32):
    """Tiny face model dir with NATIVE checkpoints (random weights)."""
    from safetensors.numpy import save_file

    from lumen_tpu.models.face import (
        DetectorConfig,
        FaceDetector,
        IResNet,
        IResNetConfig,
        flatten_variables,
    )

    model_dir = tmp_path / "models" / "TinyFace"
    model_dir.mkdir(parents=True, exist_ok=True)
    det_cfg = DetectorConfig(input_size=det_size, width=8, fpn_width=8)
    rec_cfg = IResNetConfig(layers=(1, 1, 1, 1), width=8, input_size=rec_size, embed_dim=64)
    # eval_shape + host-side random fill instead of a real flax init: the
    # tests only need plausibly-random weights of the right structure, and
    # skipping the two init compiles saves ~20s of fixture setup on CPU.
    det_vars = _random_variables(
        lambda: FaceDetector(det_cfg).init(jax.random.PRNGKey(0), jnp.zeros((1, det_size, det_size, 3)))
    )
    rec_vars = _random_variables(
        lambda: IResNet(rec_cfg).init(jax.random.PRNGKey(1), jnp.zeros((1, rec_size, rec_size, 3))),
        seed=1,
    )
    save_file(flatten_variables(dict(det_vars)), str(model_dir / "detection.safetensors"))
    save_file(flatten_variables(dict(rec_vars)), str(model_dir / "recognition.safetensors"))
    info = {
        "name": "TinyFace",
        "version": "1.0.0",
        "description": "tiny test face pack",
        "model_type": "face",
        "embedding_dim": 64,
        "source": {"format": "custom", "repo_id": "LumilioPhotos/TinyFace"},
        "runtimes": {
            "jax": {"available": True, "files": ["detection.safetensors", "recognition.safetensors"]}
        },
        "extra_metadata": {
            "insightface": {"det_size": det_size, "rec_size": rec_size},
            "detector": {"input_size": det_size, "width": 8, "fpn_width": 8},
            "embedder": {"layers": [1, 1, 1, 1], "width": 8, "input_size": rec_size, "embed_dim": 64},
        },
    }
    (model_dir / "model_info.json").write_text(json.dumps(info))
    return str(model_dir), det_cfg, rec_cfg


@pytest.fixture(scope="module")
def face_setup(tmp_path_factory):
    from lumen_tpu.models.face import FaceManager

    tmp = tmp_path_factory.mktemp("face")
    model_dir, det_cfg, rec_cfg = make_face_model_dir(tmp)
    mgr = FaceManager(model_dir, dtype="float32", batch_size=4, detector_cfg=det_cfg, embedder_cfg=rec_cfg)
    mgr.initialize()
    yield mgr
    mgr.close()


class TestDecodeMath:
    def test_distance2bbox(self):
        from lumen_tpu.models.face import distance2bbox

        centers = jnp.array([[100.0, 50.0]])
        dist = jnp.array([[10.0, 5.0, 20.0, 15.0]])
        box = np.asarray(distance2bbox(centers, dist))
        np.testing.assert_allclose(box, [[90, 45, 120, 65]])

    def test_distance2kps(self):
        from lumen_tpu.models.face import distance2kps

        centers = jnp.array([[10.0, 20.0]])
        dist = jnp.array([[1.0, 2.0, -1.0, -2.0]])  # 2 kps
        kps = np.asarray(distance2kps(centers, dist))
        np.testing.assert_allclose(kps, [[[11, 22], [9, 18]]])

    def test_anchor_centers_layout(self):
        from lumen_tpu.models.face import anchor_centers

        c = np.asarray(anchor_centers(64, 32, 2))
        assert c.shape == (8, 2)  # 2x2 grid x 2 anchors
        np.testing.assert_allclose(c[0], [0, 0])
        np.testing.assert_allclose(c[1], [0, 0])  # duplicated per anchor
        np.testing.assert_allclose(c[2], [32, 0])

    def test_decode_detections_shapes(self):
        from lumen_tpu.models.face import DetectorConfig, FaceDetector, decode_detections

        cfg = DetectorConfig.tiny()
        det = FaceDetector(cfg)
        x = jnp.zeros((2, cfg.input_size, cfg.input_size, 3))
        variables = det.init(jax.random.PRNGKey(0), x)
        outs = det.apply(variables, x)
        boxes, kps, scores = decode_detections(outs, cfg.input_size, cfg.num_anchors, max_detections=32)
        assert boxes.shape == (2, 32, 4)
        assert kps.shape == (2, 32, 5, 2)
        assert scores.shape == (2, 32)


class TestIResNet:
    def test_embedding_shape(self):
        from lumen_tpu.models.face import IResNet, IResNetConfig

        cfg = IResNetConfig.tiny()
        model = IResNet(cfg)
        x = jnp.zeros((2, cfg.input_size, cfg.input_size, 3))
        variables = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(variables, x)
        assert out.shape == (2, cfg.embed_dim)

    def test_fc_kernel_permute_matches_torch_flatten(self):
        from lumen_tpu.models.face.convert import fc_kernel_from_torch

        c, h, w = 3, 2, 2
        rng = np.random.default_rng(0)
        x_nhwc = rng.standard_normal((1, h, w, c)).astype(np.float32)
        weight = rng.standard_normal((5, c * h * w)).astype(np.float32)
        torch_out = weight @ x_nhwc.transpose(0, 3, 1, 2).reshape(1, -1).T  # torch flatten order
        jax_out = x_nhwc.reshape(1, -1) @ fc_kernel_from_torch(weight, c, h, w)
        np.testing.assert_allclose(jax_out.T, torch_out, atol=1e-5)

    def test_torch_iresnet_conversion_tree(self):
        # Synthetic torch-layout state dict for the tiny config must convert
        # into exactly the module's variable tree.
        from lumen_tpu.models.face import IResNet, IResNetConfig
        from lumen_tpu.models.face.convert import convert_iresnet
        from lumen_tpu.runtime import flatten
        from lumen_tpu.runtime.weights import assert_tree_shapes

        cfg = IResNetConfig(layers=(1, 1, 1, 1), width=8, input_size=32, embed_dim=64)
        model = IResNet(cfg)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
        state = {}
        state["conv1.weight"] = np.zeros((8, 3, 3, 3), np.float32)
        def bn(src, n):
            state[f"{src}.weight"] = np.zeros((n,), np.float32)
            state[f"{src}.bias"] = np.zeros((n,), np.float32)
            state[f"{src}.running_mean"] = np.zeros((n,), np.float32)
            state[f"{src}.running_var"] = np.ones((n,), np.float32)
            state[f"{src}.num_batches_tracked"] = np.zeros((), np.int64)
        bn("bn1", 8)
        state["prelu.weight"] = np.full((8,), 0.25, np.float32)
        widths = [8, 16, 32, 64]
        in_w = 8
        for s, wd in enumerate(widths, start=1):
            bn(f"layer{s}.0.bn1", in_w)
            state[f"layer{s}.0.conv1.weight"] = np.zeros((wd, in_w, 3, 3), np.float32)
            bn(f"layer{s}.0.bn2", wd)
            state[f"layer{s}.0.prelu.weight"] = np.full((wd,), 0.25, np.float32)
            state[f"layer{s}.0.conv2.weight"] = np.zeros((wd, wd, 3, 3), np.float32)
            bn(f"layer{s}.0.bn3", wd)
            state[f"layer{s}.0.downsample.0.weight"] = np.zeros((wd, in_w, 1, 1), np.float32)
            bn(f"layer{s}.0.downsample.1", wd)
            in_w = wd
        bn("bn2", 64)
        final_hw = 32 // 16
        state["fc.weight"] = np.zeros((64, 64 * final_hw * final_hw), np.float32)
        state["fc.bias"] = np.zeros((64,), np.float32)
        bn("features", 64)
        converted = convert_iresnet(state, final_c=64, final_hw=final_hw)
        assert_tree_shapes(converted["params"], jax.tree.map(np.asarray, variables["params"]))
        assert_tree_shapes(converted["batch_stats"], jax.tree.map(np.asarray, variables["batch_stats"]))


class TestManagerPipeline:
    def test_detect_returns_list(self, face_setup):
        faces = face_setup.detect_faces(png_bytes(size=100), conf_threshold=0.0, max_faces=5)
        assert isinstance(faces, list) and len(faces) <= 5
        for f in faces:
            assert f.bbox.shape == (4,)
            x1, y1, x2, y2 = f.bbox
            assert 0 <= x1 <= x2 <= 100 and 0 <= y1 <= y2 <= 100
            assert f.landmarks.shape == (5, 2)

    def test_letterbox_unmap(self, face_setup, monkeypatch):
        # Inject a synthetic detection at a known letterboxed position and
        # check it maps back to original image coordinates.
        det_size = face_setup.det_cfg.input_size  # 64
        # Image 100x200 -> scale 64/200=0.32, pad_top=(64-32)//2=16
        boxes = np.full((128, 4), 0, np.float32)
        boxes[0] = [0 + 0, 16 + 3.2, 32, 16 + 16]  # letterboxed coords
        kps = np.zeros((128, 5, 2), np.float32)
        scores = np.full((128,), -np.inf, np.float32)
        scores[0] = 0.9
        keep = np.zeros((128,), bool)
        keep[0] = True
        monkeypatch.setattr(face_setup, "_det_batcher", lambda img: (boxes, kps, scores, keep))
        img = np.zeros((100, 200, 3), np.uint8)
        import cv2

        ok, buf = cv2.imencode(".png", img)
        faces = face_setup.detect_faces(buf.tobytes())
        assert len(faces) == 1
        scale = 64 / 200
        np.testing.assert_allclose(
            faces[0].bbox, [0, 3.2 / scale, 32 / scale, 16 / scale], atol=1e-3
        )

    def test_embedding_unit_norm(self, face_setup):
        emb = face_setup.extract_embedding(png_bytes(size=50))
        assert emb.shape == (64,)
        assert np.linalg.norm(emb) == pytest.approx(1.0, abs=1e-5)

    def test_embedding_with_landmarks_alignment(self, face_setup):
        lm = np.array([[15, 20], [35, 20], [25, 30], [18, 40], [32, 40]], np.float32)
        emb = face_setup.extract_embedding(png_bytes(size=50), landmarks=lm)
        assert np.linalg.norm(emb) == pytest.approx(1.0, abs=1e-5)

    def test_compare_and_match(self, face_setup):
        e1 = face_setup.extract_embedding(png_bytes(1, size=40))
        e2 = face_setup.extract_embedding(png_bytes(1, size=40))
        assert face_setup.compare_faces(e1, e2) == pytest.approx(1.0, abs=1e-4)
        gallery = np.stack([e1, -e1])
        idx, sim = face_setup.find_best_match(e2, gallery)
        assert idx == 0 and sim > 0.9
        assert face_setup.find_best_match(e2, np.zeros((0, 64))) is None


@pytest.mark.integration
class TestFaceServiceGrpc:
    @pytest.fixture(scope="class")
    def stub(self, tmp_path_factory):
        import grpc
        from concurrent import futures

        from lumen_tpu.models.face import FaceManager
        from lumen_tpu.serving.proto.ml_service_pb2_grpc import (
            InferenceStub,
            add_InferenceServicer_to_server,
        )
        from lumen_tpu.serving.router import HubRouter
        from lumen_tpu.serving.services.face_service import FaceService

        tmp = tmp_path_factory.mktemp("facesvc")
        model_dir, det_cfg, rec_cfg = make_face_model_dir(tmp)
        mgr = FaceManager(model_dir, dtype="float32", batch_size=4, detector_cfg=det_cfg, embedder_cfg=rec_cfg)
        mgr.initialize()
        svc = FaceService(mgr)
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        add_InferenceServicer_to_server(HubRouter({"face": svc}), server)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        yield InferenceStub(channel)
        channel.close()
        server.stop(0)
        svc.close()

    def _infer(self, stub, task, payload, meta=None):
        from lumen_tpu.serving.proto import ml_service_pb2 as pb

        (resp,) = stub.Infer(
            iter(
                [
                    pb.InferRequest(
                        correlation_id="f1", task=task, payload=payload,
                        meta=meta or {}, payload_mime="image/png",
                    )
                ]
            )
        )
        return resp

    def test_face_detect(self, stub):
        resp = self._infer(stub, "face_detect", png_bytes(size=80), meta={"conf_threshold": "0.0", "max_faces": "3"})
        assert not resp.HasField("error"), resp.error
        body = json.loads(resp.result)
        assert body["count"] == len(body["faces"]) <= 3

    def test_face_embed(self, stub):
        resp = self._infer(stub, "face_embed", png_bytes(size=40))
        body = json.loads(resp.result)
        assert len(body["faces"][0]["embedding"]) == 64

    def test_face_detect_and_embed(self, stub):
        resp = self._infer(stub, "face_detect_and_embed", png_bytes(size=80), meta={"conf_threshold": "0.0"})
        body = json.loads(resp.result)
        for f in body["faces"]:
            assert f["embedding"] is None or len(f["embedding"]) == 64

    def test_invalid_landmarks_meta(self, stub):
        resp = self._infer(stub, "face_embed", png_bytes(size=40), meta={"landmarks": "[[1,2]]"})
        assert resp.HasField("error")


class TestPackSpecs:
    def test_known_pack_overrides(self):
        from lumen_tpu.models.face.packs import pack_overrides

        spec = pack_overrides("buffalo_l")
        assert spec["rec_color"] == "bgr"
        assert spec["det_size"] == 640
        assert spec["min_face"] == 32 and spec["max_face"] == 1000
        assert pack_overrides("AntelopeV2")  # case-insensitive exact match
        # Substrings must NOT match — unrelated models containing a pack
        # name would silently inherit BGR preprocessing.
        assert pack_overrides("waterbuffalo_small") == {}
        assert pack_overrides("SomeOtherFaceModel") == {}

    def test_pack_overrides_win_over_manifest(self):
        """Reference parity: ``_apply_pack_overrides`` runs AFTER manifest
        extras, so pack constants win for stock pack names."""
        from lumen_tpu.models.face.manager import FaceSpec
        from lumen_tpu.models.face.packs import pack_overrides

        merged = {"score_threshold": 0.7, **pack_overrides("buffalo_s")}
        spec = FaceSpec.from_extra(merged)
        assert spec.score_threshold == 0.4  # pack wins (reference behavior)
        assert spec.rec_color == "bgr"

    def test_size_gate_defaults_from_spec(self):
        from lumen_tpu.models.face.manager import FaceSpec

        spec = FaceSpec.from_extra({"min_face": 32, "max_face": 1000})
        assert spec.min_face == 32 and spec.max_face == 1000
        # unknown models keep the permissive defaults
        assert FaceSpec.from_extra(None).min_face == 0.0
