"""Parameters shared by the golden-fixture recorder and its replay tests.

Single source of truth so ``scripts/record_golden.py`` and
``tests/test_golden.py`` cannot drift apart: a parameter tweak in one
place is automatically the other's, and a golden mismatch then always
means a genuine behavior change (array-valued inputs/outputs live in the
``.npz`` fixtures themselves).
"""

FACE_NMS_THRESHOLD = 0.4
FACE_MAX_DETECTIONS = 672  # keep every anchor: parity covers the full set

DB_POSTPROCESS = dict(
    det_threshold=0.3,
    box_threshold=0.5,
    unclip_ratio=1.5,
    max_candidates=100,
    min_size=5.0,
    dest_hw=(320, 480),
    scale=0.5,
    pad_top=0,
    pad_left=0,
)

CTC_VOCAB = ["<blank>", "a", "b", "c", "d"]

CLIP_TOP_K = 5
