"""Golden parity: our VLM decoder vs HF transformers Qwen2, same weights.

The reference's VLM language model is a Qwen2 (FastVLM exports a Qwen2
decoder to ONNX; reference serves it via onnxruntime,
``packages/lumen-vlm/src/lumen_vlm/backends/onnxrt_backend.py:55-812``).
This test builds a REAL ``Qwen2ForCausalLM`` through the HF reference
implementation, converts its checkpoint with ``convert_vlm_checkpoint``,
and asserts:

1. prefill logits match HF forward logits (fp32, atol 2e-4), and
2. greedy generation produces token-for-token identical output to
   ``model.generate(do_sample=False)`` — through the fused while_loop
   decode AND the streaming step path.

That is the "load a real checkpoint and get the same answers" bar from
the round-1 verdict, checked at the family's numerical core.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from lumen_tpu.models.vlm.convert import convert_vlm_checkpoint  # noqa: E402
from lumen_tpu.models.vlm.generate import Generator  # noqa: E402
from lumen_tpu.models.vlm.modeling import VLMConfig, VLMModel  # noqa: E402

VOCAB = 128
HIDDEN = 32
LAYERS = 2
HEADS = 4
KV_HEADS = 2
EOS = 2


@pytest.fixture(scope="module")
def qwen2():
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    cfg = Qwen2Config(
        vocab_size=VOCAB,
        hidden_size=HIDDEN,
        intermediate_size=64,
        num_hidden_layers=LAYERS,
        num_attention_heads=HEADS,
        num_key_value_heads=KV_HEADS,
        max_position_embeddings=128,
        rope_theta=10_000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        bos_token_id=1,
        eos_token_id=EOS,
        pad_token_id=0,
        attention_dropout=0.0,
    )
    model = Qwen2ForCausalLM(cfg)
    model.eval()
    return cfg, model


@pytest.fixture(scope="module")
def ours(qwen2):
    hf_cfg, hf_model = qwen2
    cfg = VLMConfig.from_hf(
        {
            "text_config": {
                "vocab_size": VOCAB,
                "hidden_size": HIDDEN,
                "intermediate_size": 64,
                "num_hidden_layers": LAYERS,
                "num_attention_heads": HEADS,
                "num_key_value_heads": KV_HEADS,
                "max_position_embeddings": 128,
                "rope_theta": 10_000.0,
                "rms_norm_eps": 1e-6,
                "tie_word_embeddings": True,
                "bos_token_id": 1,
                "eos_token_id": EOS,
                "pad_token_id": 0,
            },
            # tiny vision tower: unused in the text-only parity paths but
            # required by the module tree
            "vision_config": {
                "image_size": 32,
                "patch_size": 16,
                "hidden_size": 48,
                "num_hidden_layers": 1,
                "num_attention_heads": 4,
            },
            "image_token_index": VOCAB - 1,
        }
    )
    model = VLMModel(cfg)
    init = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, 4), jnp.int32),
        jnp.zeros((1, cfg.vision.image_size, cfg.vision.image_size, 3), jnp.float32),
    )["params"]
    state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = convert_vlm_checkpoint(state, init_params=None, tie_word_embeddings=True)
    # The HF checkpoint carries no vision tower; graft the init one (text
    # parity paths never touch it).
    params["vision"] = init["vision"]
    return cfg, model, params


def _prompt():
    rng = np.random.RandomState(7)
    return rng.randint(3, VOCAB - 2, size=(1, 9)).astype(np.int32)


class TestQwen2GoldenParity:
    def test_prefill_logits_match_hf(self, qwen2, ours):
        _, hf_model = qwen2
        cfg, model, params = ours
        ids = _prompt()
        with torch.no_grad():
            want = hf_model(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
        got = np.asarray(
            model.apply({"params": params}, jnp.asarray(ids), None), np.float32
        )
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)

    def _hf_greedy(self, hf_model, ids, n):
        with torch.no_grad():
            out = hf_model.generate(
                torch.from_numpy(ids.astype(np.int64)),
                max_new_tokens=n,
                do_sample=False,
                eos_token_id=EOS,
                pad_token_id=0,
            )
        return [int(t) for t in out[0][ids.shape[1] :]]

    def _prepare_text(self, cfg, model, params, ids):
        embeds = model.apply({"params": params}, jnp.asarray(ids), method=VLMModel.embed_tokens)
        b, s = ids.shape
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        lengths = jnp.asarray([s], jnp.int32)
        return embeds, positions, lengths

    def test_fused_greedy_matches_hf_generate(self, qwen2, ours):
        _, hf_model = qwen2
        cfg, model, params = ours
        ids = _prompt()
        n = 12
        want = self._hf_greedy(hf_model, ids, n)

        gen = Generator(model, cfg, max_seq=64, max_new_cap=16, cache_dtype=jnp.float32)
        embeds, positions, lengths = self._prepare_text(cfg, model, params, ids)
        out = gen.generate(
            params, embeds, positions, lengths, jnp.asarray(ids), jax.random.PRNGKey(0),
            max_new_tokens=n,
        )
        n_gen = int(out.n_generated[0])
        got = [int(t) for t in np.asarray(out.tokens[0][:n_gen])]
        assert got == want

    def test_streaming_matches_hf_generate(self, qwen2, ours):
        _, hf_model = qwen2
        cfg, model, params = ours
        ids = _prompt()
        n = 8
        want = self._hf_greedy(hf_model, ids, n)

        gen = Generator(model, cfg, max_seq=64, max_new_cap=16, cache_dtype=jnp.float32)
        embeds, positions, lengths = self._prepare_text(cfg, model, params, ids)
        got = list(
            gen.stream(
                params, embeds, positions, lengths, jnp.asarray(ids),
                jax.random.PRNGKey(0), max_new_tokens=n,
            )
        )
        # stream yields EOS if hit; HF strips nothing — both keep EOS
        assert got == want

    def test_batched_rows_match_hf(self, qwen2, ours):
        """Two different prompts decoded as one [B=2] program each match
        their HF greedy continuation (the batched-serving correctness the
        reference can't express)."""
        _, hf_model = qwen2
        cfg, model, params = ours
        rng = np.random.RandomState(11)
        ids = rng.randint(3, VOCAB - 2, size=(2, 7)).astype(np.int32)
        n = 8
        want = [self._hf_greedy(hf_model, ids[i : i + 1], n) for i in range(2)]

        gen = Generator(model, cfg, max_seq=64, max_new_cap=16, cache_dtype=jnp.float32)
        embeds = model.apply({"params": params}, jnp.asarray(ids), method=VLMModel.embed_tokens)
        positions = jnp.broadcast_to(jnp.arange(7), (2, 7))
        lengths = jnp.asarray([7, 7], jnp.int32)
        out = gen.generate(
            params, embeds, positions, lengths, jnp.asarray(ids), jax.random.PRNGKey(0),
            max_new_tokens=n,
        )
        for i in range(2):
            n_gen = int(out.n_generated[i])
            got = [int(t) for t in np.asarray(out.tokens[i][:n_gen])]
            assert got == want[i], i
