"""Training subsystem tests on the simulated 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lumen_tpu.models.clip.modeling import CLIPConfig, TowerConfig
from lumen_tpu.runtime import build_mesh
from lumen_tpu.training import ClipTrainer, TrainConfig, contrastive_loss

pytestmark = pytest.mark.multichip


def tiny_cfg():
    return CLIPConfig(
        embed_dim=16,
        image_size=32,
        patch_size=16,
        vision=TowerConfig(32, 1, 2),
        text=TowerConfig(32, 1, 2),
        vocab_size=64,
        context_length=8,
    )


def make_batch(n, cfg, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "pixel_values": jnp.asarray(rng.rand(n, cfg.image_size, cfg.image_size, 3), jnp.float32),
        "input_ids": jnp.asarray(rng.randint(1, cfg.vocab_size, (n, cfg.context_length)), jnp.int32),
    }


class TestContrastiveLoss:
    def test_perfect_alignment_low_loss(self):
        emb = jnp.eye(4)
        aligned = contrastive_loss(emb, emb, jnp.log(jnp.asarray(100.0)))
        shuffled = contrastive_loss(emb, emb[::-1], jnp.log(jnp.asarray(100.0)))
        assert float(aligned) < 0.01 < float(shuffled)


class TestClipTrainer:
    def test_dp_tp_train_step_decreases_loss(self):
        mesh = build_mesh({"data": -1, "model": 2})
        cfg = tiny_cfg()
        trainer = ClipTrainer(cfg, TrainConfig(learning_rate=1e-3, warmup_steps=1, total_steps=50), mesh)
        params, opt_state = trainer.init_state(jax.random.PRNGKey(0))
        step = trainer.make_train_step()
        batch = make_batch(8, cfg)
        losses = []
        for _ in range(8):
            params, opt_state, metrics = step(params, opt_state, batch)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        # Overfitting one tiny batch must reduce the loss.
        assert losses[-1] < losses[0]

    def test_tp_params_actually_sharded(self):
        mesh = build_mesh({"data": 4, "model": 2})
        cfg = tiny_cfg()
        trainer = ClipTrainer(cfg, TrainConfig(), mesh)
        params, _ = trainer.init_state(jax.random.PRNGKey(0))
        qk = params["vision"]["blocks_0"]["attn"]["q_proj"]["kernel"]
        shard_shapes = {s.data.shape for s in qk.addressable_shards}
        assert shard_shapes == {(32, 16)}  # output dim split across model=2

    def test_dryrun_entrypoint(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)


class TestCheckpoint:
    def test_save_restore_roundtrip_with_shardings(self, tmp_path):
        from lumen_tpu.training import TrainCheckpointer

        mesh = build_mesh({"data": 4, "model": 2})
        cfg = tiny_cfg()
        trainer = ClipTrainer(cfg, TrainConfig(warmup_steps=1, total_steps=20), mesh)
        params, opt_state = trainer.init_state(jax.random.PRNGKey(0))
        step_fn = trainer.make_train_step()
        batch = make_batch(8, cfg)
        params, opt_state, m1 = step_fn(params, opt_state, batch)

        ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), async_save=False)
        ckpt.save(1, params, opt_state, wait=True)
        assert ckpt.latest_step() == 1

        step, params_r, opt_r = ckpt.restore(
            params_like=jax.tree.map(lambda x: x, params),
            opt_state_like=jax.tree.map(lambda x: x, opt_state),
        )
        assert step == 1
        # Values identical and shardings preserved.
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params,
            params_r,
        )
        qk = params_r["vision"]["blocks_0"]["attn"]["q_proj"]["kernel"]
        assert {s.data.shape for s in qk.addressable_shards} == {(32, 16)}

        # Training continues from the restored state without error.
        params2, opt2, m2 = step_fn(params_r, opt_r, batch)
        assert np.isfinite(float(m2["loss"]))
        ckpt.close()

    def test_retention_keeps_newest(self, tmp_path):
        from lumen_tpu.training import TrainCheckpointer

        mesh = build_mesh({"data": -1})
        cfg = tiny_cfg()
        trainer = ClipTrainer(cfg, TrainConfig(), mesh)
        params, opt_state = trainer.init_state(jax.random.PRNGKey(0))
        ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), max_to_keep=2, async_save=False)
        for s in (1, 2, 3):
            ckpt.save(s, params, opt_state, wait=True)
        assert ckpt.all_steps() == [2, 3]
        ckpt.close()

    def test_restore_empty_dir_raises(self, tmp_path):
        from lumen_tpu.training import TrainCheckpointer

        ckpt = TrainCheckpointer(str(tmp_path / "none"), async_save=False)
        with pytest.raises(FileNotFoundError):
            ckpt.restore()
        ckpt.close()


class TestRemat:
    def test_remat_step_matches_plain(self):
        """remat=True must change memory behavior only: same loss, same
        updated params as the plain step for identical inputs."""
        import numpy as np

        from lumen_tpu.models.clip.modeling import CLIPConfig, TowerConfig
        from lumen_tpu.runtime.mesh import build_mesh
        from lumen_tpu.training import ClipTrainer, TrainConfig

        cfg = CLIPConfig(
            embed_dim=16,
            image_size=32,
            patch_size=16,
            vision=TowerConfig(32, 2, 4),
            text=TowerConfig(32, 2, 4),
            vocab_size=64,
            context_length=8,
        )
        mesh = build_mesh({"data": -1})
        batch = {
            "pixel_values": jnp.asarray(
                np.random.RandomState(0).rand(8, 32, 32, 3), jnp.float32
            ),
            "input_ids": jnp.asarray(
                np.random.RandomState(1).randint(0, 64, (8, 8)), jnp.int32
            ),
        }
        results = []
        for remat in (False, True):
            tr = ClipTrainer(cfg, TrainConfig(total_steps=4, warmup_steps=1, remat=remat), mesh)
            params, opt = tr.init_state(jax.random.PRNGKey(0))
            step = tr.make_train_step()
            params, opt, metrics = step(params, opt, batch)
            results.append((float(metrics["loss"]), params))
        assert results[0][0] == pytest.approx(results[1][0], rel=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6
            ),
            results[0][1],
            results[1][1],
        )
