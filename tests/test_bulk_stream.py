"""Bulk streaming lane (ISSUE 5 tentpole): N tagged items on ONE Infer
stream fan out concurrently, come back tagged (out of order is fine), and
preserve the per-item cache / quarantine / error-isolation semantics of
the unary path. A client disconnect mid-stream cancels the not-yet-started
remainder of the fan-out.
"""

import json
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor

import grpc
import numpy as np
import pytest

from lumen_tpu.runtime.batcher import MicroBatcher
from lumen_tpu.runtime.quarantine import get_quarantine, guarded_key
from lumen_tpu.runtime.result_cache import (
    get_result_cache,
    make_key,
    reset_result_cache,
)
from lumen_tpu.serving import (
    BaseService,
    HubRouter,
    ServiceError,
    TaskDefinition,
    TaskRegistry,
)
from lumen_tpu.serving.proto import ml_service_pb2 as pb
from lumen_tpu.serving.proto.ml_service_pb2_grpc import (
    InferenceStub,
    add_InferenceServicer_to_server,
)


@pytest.fixture()
def cache_on(monkeypatch):
    monkeypatch.setenv("LUMEN_CACHE_BYTES", str(64 << 20))
    reset_result_cache()
    yield
    monkeypatch.setenv("LUMEN_CACHE_BYTES", "0")
    reset_result_cache()


class EmbedService(BaseService):
    """Manager-shaped test service: content-addressed cache + quarantine
    gate + a real MicroBatcher behind the handler, so the bulk lane is
    proven against the semantics that matter, not an echo stub."""

    def __init__(self, name="bulk"):
        registry = TaskRegistry(name)
        registry.register(TaskDefinition(name=f"{name}_embed", handler=self._embed))
        super().__init__(registry)
        self.ns = f"bulktest/embed/m@{uuid.uuid4().hex[:8]}"
        self.batcher = MicroBatcher(
            self._fn, max_batch=8, max_latency_ms=10, name=f"bulk-{uuid.uuid4().hex[:6]}"
        ).start()
        self.batch_sizes: list[int] = []
        self.device_payloads: list[bytes] = []
        self._lock = threading.Lock()

    def capability(self):
        return self.registry.build_capability(model_ids=["bulk-v0"], runtime="jax-cpu")

    def close(self):
        self.batcher.close()

    def _fn(self, tree, n):
        self.batch_sizes.append(n)
        return tree

    def _embed(self, payload, mime, meta):
        key = guarded_key(self.ns, None, payload)  # quarantine gate, ONE hash

        def compute():
            arr = np.frombuffer(payload.ljust(8, b"\0")[:8], np.uint8).astype(np.float32)
            row = self.batcher(arr, fingerprint=key)
            with self._lock:
                self.device_payloads.append(bytes(payload))
            return row

        out = get_result_cache().get_or_compute(
            self.ns, None, payload, compute, clone=np.copy, key=key
        )
        body = json.dumps({"v": np.asarray(out).tolist()}).encode()
        return body, "application/json", {}


@pytest.fixture()
def bulk_hub(cache_on):
    svc = EmbedService("bulk")
    server = grpc.server(ThreadPoolExecutor(max_workers=4))
    add_InferenceServicer_to_server(HubRouter({"bulk": svc}), server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield InferenceStub(channel), svc
    channel.close()
    server.stop(0)
    svc.close()


def expected_vec(payload: bytes) -> list[float]:
    return np.frombuffer(payload.ljust(8, b"\0")[:8], np.uint8).astype(np.float32).tolist()


@pytest.mark.integration
class TestBulkStream:
    def test_poison_and_cache_hit_interleaved(self, bulk_hub):
        """ISSUE 5 acceptance: N items, one pre-quarantined poison and one
        cache hit interleaved — tagged correct results, the poison fails
        ALONE (INVALID_ARGUMENT + quarantined meta), and the hit never
        reaches the batcher."""
        from lumen_tpu.client import infer_bulk

        stub, svc = bulk_hub
        payloads = [f"item-{i}".encode() for i in range(8)]
        poison, hit = payloads[2], payloads[5]
        get_quarantine().add(make_key(svc.ns, None, poison), "test poison")
        # Warm ONE unary request so payload[5] is a cache hit inside bulk.
        resps = list(stub.Infer(iter([pb.InferRequest(
            correlation_id="warm", task="bulk_embed", payload=hit,
            payload_mime="application/octet-stream",
        )])))
        assert not resps[-1].HasField("error")
        with svc._lock:
            svc.device_payloads.clear()

        results = dict(infer_bulk(stub, "bulk_embed", payloads))
        assert set(results) == set(range(8))  # every item answered, tagged
        for i, payload in enumerate(payloads):
            if i == 2:
                err = results[i]
                assert isinstance(err, ServiceError)
                assert err.code == pb.ERROR_CODE_INVALID_ARGUMENT
                assert "quarantined" in str(err)
            else:
                data, _mime, meta = results[i]
                assert json.loads(data)["v"] == expected_vec(payload)
                if i == 5:
                    assert meta.get("cache_hit") == "1"
        with svc._lock:
            seen = list(svc.device_payloads)
        assert hit not in seen  # the hit never touched the batcher
        assert poison not in seen  # rejected before the device
        assert sorted(seen) == sorted(p for i, p in enumerate(payloads) if i not in (2, 5))

    def test_bulk_coalesces_into_batches(self, bulk_hub):
        """The whole point of the lane: concurrent fan-out must feed the
        MicroBatcher multi-item batches, not 16 singletons."""
        from lumen_tpu.client import infer_bulk

        stub, svc = bulk_hub
        payloads = [f"co-{i}".encode() for i in range(16)]
        results = dict(infer_bulk(stub, "bulk_embed", payloads))
        assert set(results) == set(range(16))
        assert sum(svc.batch_sizes) == 16
        assert max(svc.batch_sizes) >= 2  # real coalescing happened
        assert len(svc.batch_sizes) <= 12

    def test_mixed_unary_stream_unaffected(self, bulk_hub):
        """A stream WITHOUT the bulk meta keeps the sequential unary path."""
        stub, _svc = bulk_hub
        payload = b"unary-1"
        resps = list(stub.Infer(iter([pb.InferRequest(
            correlation_id="u1", task="bulk_embed", payload=payload,
            payload_mime="application/octet-stream",
        )])))
        assert len(resps) == 1 and resps[0].is_final
        assert json.loads(resps[0].result)["v"] == expected_vec(payload)


class TestBulkCancellation:
    def test_disconnect_cancels_remaining_fanout(self, monkeypatch, cache_on):
        """Client disconnect mid-stream (the request iterator raising, which
        is what gRPC surfaces) cancels the not-yet-started remainder: with
        a 1-worker pool, items queued behind a blocked first item must
        never run their handlers."""
        from lumen_tpu.serving import base_service

        pool = ThreadPoolExecutor(1, thread_name_prefix="bulk-cancel-t")
        monkeypatch.setattr(base_service, "_bulk_pool", pool)
        started: list[str] = []
        release = threading.Event()

        class BlockingService(BaseService):
            def __init__(self):
                registry = TaskRegistry("blk")
                registry.register(TaskDefinition(name="blk_slow", handler=self._slow))
                super().__init__(registry)

            def capability(self):
                return self.registry.build_capability(model_ids=["blk"], runtime="jax-cpu")

            def _slow(self, payload, mime, meta):
                started.append(bytes(payload).decode())
                release.wait(10)
                return payload, "application/octet-stream", {}

        svc = BlockingService()
        raised = threading.Event()

        def requests():
            for i in range(4):
                yield pb.InferRequest(
                    correlation_id=str(i), task="blk_slow",
                    payload=f"p{i}".encode(), meta={"bulk": "1"},
                )
            raised.set()
            raise RuntimeError("client disconnected")

        responses: list = []
        consumer = threading.Thread(
            target=lambda: responses.extend(svc.Infer(requests(), None)), daemon=True
        )
        consumer.start()
        assert raised.wait(5)
        # Give the reader's except-path a beat to latch the stop flag
        # (a few bytecodes after `raised` fires), then let item 0 finish.
        time.sleep(0.2)
        release.set()
        consumer.join(timeout=10)
        assert not consumer.is_alive()
        # Item 0 ran; items 1-3 were fanned out but cancelled before start.
        assert started == ["p0"]
        # After the disconnect nothing is yielded — even the completed
        # item's response goes nowhere (the client is gone).
        assert responses == []
        pool.shutdown(wait=False)
