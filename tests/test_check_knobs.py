"""Tier-1 gate: every LUMEN_* env knob referenced in the package is
documented (docs/ or README.md). See scripts/check_knobs.py."""

import importlib.util
import os

_SPEC = importlib.util.spec_from_file_location(
    "check_knobs",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "check_knobs.py"),
)
check_knobs = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(check_knobs)


def test_every_referenced_knob_is_documented():
    missing = check_knobs.undocumented()
    assert not missing, (
        f"undocumented LUMEN_* knobs {missing}: add each to a knob table in "
        "docs/ (RESILIENCE.md / PERFORMANCE.md / MODELS.md) or, for a "
        "deliberate non-operator toggle, to the ALLOWLIST in "
        "scripts/check_knobs.py with a justification"
    )


def test_scan_finds_known_knobs():
    # Sanity that the scan actually sees through both sides — a regex typo
    # must not turn the gate into a silent pass.
    refs = check_knobs.referenced_knobs()
    assert "LUMEN_BATCH_QUEUE_DEPTH" in refs
    assert "LUMEN_BISECT_DEPTH" in refs
    docs = check_knobs.documented_knobs()
    assert "LUMEN_BATCH_QUEUE_DEPTH" in docs
