"""Golden parity tests for the ONNX bridge executor.

Strategy: export small torch models to real ``.onnx`` files (the same
serialization path that produced the reference's served graphs — InsightFace
SCRFD/ArcFace and PP-OCR det/rec are all torch/paddle exports consumed by
onnxruntime in ``packages/lumen-face/.../onnxrt_backend.py`` and
``packages/lumen-ocr/.../onnxrt_backend.py``), then run the exported graph
through ``lumen_tpu.onnx_bridge.OnnxModule`` and assert numeric parity with
the torch forward. This exercises the executor exactly the way production
does: real protobuf bytes, real op attribute encodings, real initializers.

The ``onnx`` pip package is not installed in this image; torch's legacy
exporter only imports it for custom-op (onnxscript) injection, so a no-op
shim satisfies it for the plain aten models used here.
"""

from __future__ import annotations

import sys
import types

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from lumen_tpu.onnx_bridge import OnnxModule  # noqa: E402


def _install_onnx_shim():
    """torch.onnx.export imports ``onnx`` only in ``_add_onnxscript_fn`` to
    splice custom onnxscript functions into the proto; with no custom ops a
    model whose graph iterates empty satisfies it."""
    if "onnx" in sys.modules:
        return
    import importlib.machinery

    shim = types.ModuleType("onnx")
    # a real ModuleSpec so later importlib.util.find_spec("onnx") probes
    # (e.g. transformers' availability checks) don't explode
    shim.__spec__ = importlib.machinery.ModuleSpec("onnx", loader=None)

    class _Graph:
        node = ()

    class _Model:
        graph = _Graph()

    shim.load_model_from_string = lambda b: _Model()
    sys.modules["onnx"] = shim


def export_onnx(model: nn.Module, args, path: str, opset: int = 17, **kw) -> str:
    _install_onnx_shim()
    model.eval()
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        torch.onnx.export(model, args, path, opset_version=opset, dynamo=False, **kw)
    return path


def assert_bridge_matches(model: nn.Module, args, tmp_path, atol=1e-4, rtol=1e-4, opset=17):
    """Export, run both sides, compare every output."""
    path = str(tmp_path / "m.onnx")
    export_onnx(model, tuple(args), path, opset=opset)
    with torch.no_grad():
        ref = model(*args)
    if isinstance(ref, torch.Tensor):
        ref = (ref,)
    mod = OnnxModule.from_path(path)
    feeds = {name: np.asarray(a) for name, a in zip(mod.input_names, args)}
    outs = mod(mod.params, feeds)
    assert len(outs) == len(ref)
    for got, want in zip(outs, ref):
        np.testing.assert_allclose(
            np.asarray(got, np.float32), want.numpy(), atol=atol, rtol=rtol
        )
    return mod


# -- CNN building blocks (SCRFD / ArcFace / DBNet territory) -----------------


class ResBlock(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.c1 = nn.Conv2d(c, c, 3, 1, 1)
        self.b1 = nn.BatchNorm2d(c)
        self.c2 = nn.Conv2d(c, c, 3, 1, 1)
        self.b2 = nn.BatchNorm2d(c)

    def forward(self, x):
        y = F.relu(self.b1(self.c1(x)))
        return F.relu(x + self.b2(self.c2(y)))


def test_conv_bn_relu_pool_gemm(tmp_path):
    torch.manual_seed(0)
    m = nn.Sequential(
        nn.Conv2d(3, 8, 3, 2, 1),
        nn.BatchNorm2d(8),
        nn.ReLU(),
        ResBlock(8),
        nn.MaxPool2d(2, ceil_mode=True),
        nn.AvgPool2d(2),
        nn.AdaptiveAvgPool2d(1),
        nn.Flatten(),
        nn.Linear(8, 5),
    )
    assert_bridge_matches(m, (torch.randn(2, 3, 63, 63),), tmp_path)


def test_depthwise_and_grouped_conv(tmp_path):
    torch.manual_seed(1)
    m = nn.Sequential(
        nn.Conv2d(8, 8, 3, 1, 1, groups=8),  # depthwise (MobileNet backbones)
        nn.ReLU6(),
        nn.Conv2d(8, 16, 1),
        nn.Conv2d(16, 16, 3, 2, 1, groups=4),
    )
    assert_bridge_matches(m, (torch.randn(1, 8, 32, 32),), tmp_path)


def test_conv_transpose_upsample(tmp_path):
    """DBNet's prob head upsamples with ConvTranspose (stride-2 ×2)."""
    torch.manual_seed(2)
    m = nn.Sequential(
        nn.Conv2d(4, 8, 3, 1, 1),
        nn.ConvTranspose2d(8, 8, 2, 2),
        nn.BatchNorm2d(8),
        nn.ReLU(),
        nn.ConvTranspose2d(8, 1, 2, 2),
        nn.Sigmoid(),
    )
    assert_bridge_matches(m, (torch.randn(1, 4, 16, 24),), tmp_path)


def test_mobilenetv3_se_block(tmp_path):
    """PP-OCR backbones: hardswish/hardsigmoid squeeze-excite."""

    class SE(nn.Module):
        def __init__(self, c):
            super().__init__()
            self.fc1 = nn.Conv2d(c, c // 2, 1)
            self.fc2 = nn.Conv2d(c // 2, c, 1)

        def forward(self, x):
            s = F.adaptive_avg_pool2d(x, 1)
            s = F.hardsigmoid(self.fc2(F.relu(self.fc1(s))))
            return F.hardswish(x * s)

    torch.manual_seed(3)
    m = nn.Sequential(nn.Conv2d(3, 8, 3, 2, 1), SE(8), nn.Conv2d(8, 8, 1))
    assert_bridge_matches(m, (torch.randn(1, 3, 32, 32),), tmp_path)


def test_fpn_resize_concat(tmp_path):
    """DBNet neck: nearest-upsample + add + concat across pyramid levels."""

    class FPN(nn.Module):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Conv2d(8, 4, 1)
            self.l2 = nn.Conv2d(16, 4, 1)

        def forward(self, c1, c2):
            p2 = self.l2(c2)
            p1 = self.l1(c1) + F.interpolate(p2, scale_factor=2, mode="nearest")
            return torch.cat([p1, F.interpolate(p2, scale_factor=2, mode="nearest")], 1)

    torch.manual_seed(4)
    assert_bridge_matches(
        FPN(), (torch.randn(1, 8, 16, 16), torch.randn(1, 16, 8, 8)), tmp_path
    )


def test_bilinear_resize(tmp_path):
    class Up(nn.Module):
        def forward(self, x):
            return F.interpolate(x, scale_factor=2.0, mode="bilinear", align_corners=False)

    assert_bridge_matches(Up(), (torch.randn(1, 3, 7, 9),), tmp_path)


# -- transformer blocks (SVTR recognizer / ViT territory) --------------------


class MiniAttention(nn.Module):
    def __init__(self, d, h):
        super().__init__()
        self.h = h
        self.qkv = nn.Linear(d, 3 * d)
        self.proj = nn.Linear(d, d)

    def forward(self, x):
        b, n, d = x.shape
        qkv = self.qkv(x).reshape(b, n, 3, self.h, d // self.h).permute(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        att = torch.softmax(q @ k.transpose(-2, -1) / (d // self.h) ** 0.5, dim=-1)
        return self.proj((att @ v).transpose(1, 2).reshape(b, n, d))


class MiniBlock(nn.Module):
    def __init__(self, d=16, h=4):
        super().__init__()
        self.n1 = nn.LayerNorm(d)
        self.att = MiniAttention(d, h)
        self.n2 = nn.LayerNorm(d)
        self.mlp = nn.Sequential(nn.Linear(d, 4 * d), nn.GELU(), nn.Linear(4 * d, d))

    def forward(self, x):
        x = x + self.att(self.n1(x))
        return x + self.mlp(self.n2(x))


def test_transformer_block(tmp_path):
    torch.manual_seed(5)
    assert_bridge_matches(MiniBlock(), (torch.randn(2, 12, 16),), tmp_path, atol=5e-4)


def test_svtr_style_recognizer(tmp_path):
    """Conv stem -> flatten HxW to sequence -> transformer -> per-step vocab
    logits + log_softmax (the CTC head shape of the PP-OCR recognizer)."""

    class MiniSVTR(nn.Module):
        def __init__(self, vocab=17, d=16):
            super().__init__()
            self.stem = nn.Sequential(
                nn.Conv2d(3, d, 3, (4, 2), 1), nn.BatchNorm2d(d), nn.ReLU()
            )
            self.block = MiniBlock(d)
            self.head = nn.Linear(d, vocab)

        def forward(self, x):
            f = self.stem(x)  # [B,d,H',W']
            f = f.mean(2).transpose(1, 2)  # [B,W',d]
            return torch.log_softmax(self.head(self.block(f)), dim=-1)

    torch.manual_seed(6)
    assert_bridge_matches(MiniSVTR(), (torch.randn(1, 3, 16, 40),), tmp_path, atol=5e-4)


# -- multi-output detector heads (SCRFD shape) -------------------------------


class MiniSCRFD(nn.Module):
    """3-stride anchor-free head emitting [scores×3, bbox×3, kps×3] grouped
    by TYPE — the reference's output contract (``insightface_specs.py``)."""

    def __init__(self, na=2, nk=5):
        super().__init__()
        self.backbone = nn.Sequential(nn.Conv2d(3, 8, 3, 2, 1), nn.ReLU())
        self.downs = nn.ModuleList(
            [nn.Conv2d(8, 8, 3, 2, 1), nn.Conv2d(8, 8, 3, 2, 1), nn.Conv2d(8, 8, 3, 2, 1)]
        )
        self.score = nn.ModuleList([nn.Conv2d(8, na, 1) for _ in range(3)])
        self.bbox = nn.ModuleList([nn.Conv2d(8, 4 * na, 1) for _ in range(3)])
        self.kps = nn.ModuleList([nn.Conv2d(8, 2 * nk * na, 1) for _ in range(3)])

    def forward(self, x):
        f = self.backbone(x)
        feats = []
        for d in self.downs:
            f = F.relu(d(f))
            feats.append(f)
        scores = [torch.sigmoid(s(f)).flatten(1) for s, f in zip(self.score, feats)]
        bboxes = [b(f).permute(0, 2, 3, 1).reshape(x.shape[0], -1, 4) for b, f in zip(self.bbox, feats)]
        kpss = [k(f).permute(0, 2, 3, 1).reshape(x.shape[0], -1, 10) for k, f in zip(self.kps, feats)]
        return tuple(scores) + tuple(bboxes) + tuple(kpss)


def test_scrfd_style_multioutput(tmp_path):
    torch.manual_seed(7)
    mod = assert_bridge_matches(MiniSCRFD(), (torch.randn(1, 3, 64, 64),), tmp_path)
    assert len(mod.output_names) == 9


# -- executor mechanics ------------------------------------------------------


def test_params_are_separable_and_jittable(tmp_path):
    """Weights come out as a params pytree usable under jax.jit — the property
    that makes bridge graphs shardable/castable like native Flax state."""
    import jax
    import jax.numpy as jnp

    torch.manual_seed(8)
    m = nn.Sequential(nn.Conv2d(3, 4, 3, 1, 1), nn.ReLU(), nn.Conv2d(4, 2, 1))
    path = str(tmp_path / "m.onnx")
    export_onnx(m, (torch.randn(1, 3, 8, 8),), path)
    mod = OnnxModule.from_path(path)
    assert mod.param_bytes() > 0

    fn, params = mod.bind()
    x = np.random.RandomState(0).randn(1, 3, 8, 8).astype(np.float32)
    jitted = jax.jit(fn)
    out = jitted(params, x)[0]
    with torch.no_grad():
        want = m(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4, rtol=1e-4)

    # bf16-cast params still execute (serving dtype policy)
    fn16, params16 = mod.bind(dtype=jnp.bfloat16)
    out16 = jax.jit(fn16)(params16, x)[0]
    assert np.asarray(out16, np.float32).shape == want.shape


def test_unsupported_op_raises_at_load(tmp_path):
    """Loading (not inference time) reports unsupported node types."""

    class Weird(nn.Module):
        def forward(self, x):
            return torch.det(x)  # exports to a 'Det' node, unsupported

    path = str(tmp_path / "w.onnx")
    try:
        export_onnx(Weird(), (torch.randn(1, 3, 3),), path)
    except Exception:
        pytest.skip("torch cannot export Det in this version")
    with pytest.raises(NotImplementedError):
        OnnxModule.from_path(path)


def test_input_shapes_and_dynamic_axes(tmp_path):
    m = nn.Conv2d(3, 4, 3, 1, 1)
    path = str(tmp_path / "m.onnx")
    export_onnx(
        m,
        (torch.randn(1, 3, 8, 8),),
        path,
        input_names=["pixels"],
        dynamic_axes={"pixels": {0: "batch"}},
    )
    mod = OnnxModule.from_path(path)
    shapes = mod.input_shapes()
    assert "pixels" in shapes
    # dynamic batch dim comes back non-int; spatial dims static
    assert shapes["pixels"][2] == 8 and shapes["pixels"][3] == 8
    # executes at a batch size other than the export example
    out = mod(mod.params, {"pixels": np.zeros((3, 3, 8, 8), np.float32)})[0]
    assert np.asarray(out).shape == (3, 4, 8, 8)


def test_reduce_arg_and_topk(tmp_path):
    class Heads(nn.Module):
        def forward(self, x):
            v, i = torch.topk(x, 3, dim=-1)
            return (
                x.norm(dim=-1),
                x.argmax(-1),
                x.mean(1),
                v,
                i.to(torch.int32),
            )

    torch.manual_seed(9)
    x = torch.randn(4, 10)
    path = str(tmp_path / "m.onnx")
    export_onnx(Heads(), (x,), path)
    mod = OnnxModule.from_path(path)
    outs = mod(mod.params, {mod.input_names[0]: x.numpy()})
    with torch.no_grad():
        want = Heads()(x)
    for got, w in zip(outs, want):
        np.testing.assert_allclose(
            np.asarray(got, np.float32), w.numpy().astype(np.float32), atol=1e-5, rtol=1e-5
        )
