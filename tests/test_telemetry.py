"""Capacity-telemetry layer (ISSUE 10): rolling windows, duty cycles,
the SLO burn-rate engine, the incident flight recorder, the sidecar's
/stats-/slo-/events-/incidents endpoints, the hardened gauge-provider
scrape, and the always-on overhead guard."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from lumen_tpu.utils import telemetry as tele
from lumen_tpu.utils.metrics import metrics
from lumen_tpu.utils.telemetry import (
    DutyMeter,
    RollingCounter,
    RollingHistogram,
    SLOEngine,
    TelemetryHub,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture()
def hub():
    """A fake-clock hub installed as the process hub, removed after."""
    clock = FakeClock()
    h = TelemetryHub(clock=clock)
    h.clock_handle = clock
    tele.install_hub(h)
    yield h
    tele.reset_hub()


# -- rolling primitives ------------------------------------------------------


class TestRollingPrimitives:
    def test_counter_windows_and_expiry(self):
        c = RollingCounter(bucket_s=5.0, slots=12)
        c.add(3, now=100.0)
        c.add(2, now=104.0)   # same bucket
        c.add(5, now=131.0)
        assert c.total(60, now=131.0) == 10
        assert c.total(10, now=131.0) == 5          # old bucket outside
        # Ring reuse: 12 slots x 5s = 60s of history; writes a full ring
        # later lazily retire the stale epoch.
        c.add(1, now=100.0 + 12 * 5.0)
        assert c.total(5, now=160.0) == 1

    def test_histogram_windowed_quantiles(self):
        h = RollingHistogram(bucket_s=5.0, slots=12)
        for _ in range(95):
            h.observe(1.0, now=100.0)
        for _ in range(5):
            h.observe(500.0, now=100.0)
        snap = h.window(60, now=101.0)
        assert snap["count"] == 100
        assert snap["p50_ms"] < 10
        assert snap["p99_ms"] > 100
        # The same traffic falls out of a window that excludes its bucket.
        assert h.window(60, now=300.0)["count"] == 0

    def test_duty_sum_mode(self):
        d = DutyMeter(bucket_s=5.0, slots=12, capacity=4.0)
        # Two workers each busy 2s in the same window: busy sums.
        d.add(100.0, 102.0)
        d.add(100.5, 102.5)
        w = d.window(10, now=104.0)
        assert w["busy_s"] == pytest.approx(4.0)
        assert w["fraction"] == pytest.approx(4.0 / 40.0)

    def test_duty_union_mode_clamps_pipelined_overlap(self):
        d = DutyMeter(bucket_s=5.0, slots=12, capacity=1.0, union=True)
        # Pipelined dispatch->settle envelopes: [100,103] and [101,105]
        # overlap; union busy is 5s, never 7.
        d.add(100.0, 103.0)
        d.add(101.0, 105.0)
        w = d.window(10, now=105.0)
        assert w["busy_s"] == pytest.approx(5.0)
        # A fully-contained report adds nothing.
        d.add(102.0, 104.0)
        assert d.window(10, now=105.0)["busy_s"] == pytest.approx(5.0)
        # Fraction never exceeds 1 even over a tiny window.
        assert d.window(2, now=105.0)["fraction"] <= 1.0

    def test_duty_interval_split_across_buckets(self):
        d = DutyMeter(bucket_s=5.0, slots=12)
        d.add(98.0, 107.0)  # spans three buckets
        assert d.window(20, now=107.0)["busy_s"] == pytest.approx(9.0)
        # Only the tail lands in a window starting at the last bucket.
        assert d.window(5, now=107.0)["busy_s"] <= 9.0


# -- hub + /stats payload ----------------------------------------------------


class TestHub:
    def test_window_stats_shape(self, hub):
        clock = hub.clock_handle
        hub.observe("clip_image_embed", 12.0)
        hub.count("batch_items:clip-image", 8)
        hub.count("batch_padded:clip-image", 2)
        hub.count("batch_bucket:clip-image:8", 1)
        hub.count("transfer_h2d:clip-image", 1024)
        hub.count("transfer_d2h:clip-image", 256)
        hub.set_capacity("device:clip-image", 1.0, union=True)
        hub.busy("device:clip-image", clock.t - 2.0, clock.t)
        out = tele.capacity_stats(60)
        assert out["tasks"]["clip_image_embed"]["count"] == 1
        assert out["duty"]["device:clip-image"]["busy_s"] == pytest.approx(2.0)
        b = out["batch"]["clip-image"]
        assert b["items"] == 8 and b["padded"] == 2
        assert b["padding_waste_pct"] == pytest.approx(20.0)
        assert b["distinct_buckets"] == 1
        assert out["transfer"]["clip-image"] == {"h2d_bytes": 1024, "d2h_bytes": 256}
        assert out["compile"]["compiles"] == 0
        assert "device_memory" in out and "slo" in out

    def test_metrics_tee_feeds_windows(self, hub):
        metrics.observe("tee_task", 7.0)
        metrics.count("tee_counter", 3)
        metrics.count_error("tee_task")
        out = hub.window_stats(60)
        assert out["tasks"]["tee_task"]["count"] == 1
        assert out["counters"]["tee_counter"] == 3
        assert out["counters"]["errors:tee_task"] == 1

    def test_disabled_feed_is_noop(self, monkeypatch):
        monkeypatch.setenv("LUMEN_TELEMETRY", "0")
        tele.reset_hub()
        try:
            tele.observe("gone", 1.0)
            tele.count("gone")
            tele.busy("gone", 0.0, 1.0)
            assert tele.get_hub().window_stats(60)["tasks"] == {}
        finally:
            monkeypatch.delenv("LUMEN_TELEMETRY")
            tele.reset_hub()

    def test_name_cap_collapses_to_other(self, hub):
        hub.MAX_NAMES  # document the cap exists
        for i in range(TelemetryHub.MAX_NAMES + 10):
            hub.count(f"spray:{i}")
        with hub._lock:
            assert len(hub._counters) <= TelemetryHub.MAX_NAMES + 1
            assert "_other" in hub._counters


class TestAlwaysOnOverhead:
    def test_per_request_footprint_under_2us(self, monkeypatch):
        """ISSUE 10 acceptance: with all telemetry knobs unset (the
        layer default-ON), the per-request footprint — the one rolling
        observe the metrics tee adds — stays <2µs, same method as the
        PR 6 trace guard."""
        import gc

        for k in ("LUMEN_TELEMETRY", "LUMEN_TELEMETRY_BUCKET_S"):
            monkeypatch.delenv(k, raising=False)
        tele.reset_hub()
        tele.observe("overhead_guard", 1.0)  # warm the hub + name slot
        # Many SHORT timed windows, best-of: a window of a few ms usually
        # fits between scheduler preemptions on a loaded 1-core CI box,
        # so the min reflects the code's cost, not aggregated steal time
        # (one long window absorbs every preemption into the average).
        n = 4000
        best = float("inf")
        # gc paused during the timed loops: a mid-suite collection pass
        # (the suite accretes plenty of garbage by this point) is noise
        # about the test runner, not about the per-request footprint.
        gc.disable()
        try:
            for _ in range(12):
                t0 = time.perf_counter()
                for _ in range(n):
                    tele.observe("overhead_guard", 1.0)
                best = min(best, (time.perf_counter() - t0) / n)
        finally:
            gc.enable()
        tele.reset_hub()
        assert best < 2e-6, f"always-on cost {best * 1e6:.2f}µs/request"


# -- SLO engine --------------------------------------------------------------


class TestSLOEngine:
    def _engine(self, monkeypatch, clock):
        monkeypatch.setenv("LUMEN_SLO_CLIP_IMAGE_EMBED_P95_MS", "100")
        return SLOEngine(clock=clock)

    def test_objective_parsing(self, monkeypatch):
        monkeypatch.setenv("LUMEN_SLO_CLIP_IMAGE_EMBED_P95_MS", "250")
        monkeypatch.setenv("LUMEN_SLO_OCR_P95_MS", "bogus")
        monkeypatch.setenv("LUMEN_SLO_AVAILABILITY", "0.999")
        assert tele.slo_objectives() == {"clip_image_embed": 250.0}
        assert tele.slo_availability() == 0.999

    def test_breach_and_recover_fake_clock(self, monkeypatch):
        clock = FakeClock()
        eng = self._engine(monkeypatch, clock)
        for _ in range(100):
            eng.feed("clip_image_embed", 10.0)
        st = eng.status()["clip_image_embed"]
        assert st["state"] == "ok" and st["burn_5m"] == 0.0
        # 20% of requests over the objective: burn = 0.2 / 0.05 = 4.
        for _ in range(20):
            eng.feed("clip_image_embed", 900.0)
        before = metrics.counter_value("slo_breaches")
        st = eng.status()["clip_image_embed"]
        assert st["state"] == "breach"
        assert st["burn_5m"] == pytest.approx(20 / 120 / 0.05, rel=0.05)
        assert metrics.counter_value("slo_breaches") == before + 1
        # Re-evaluating in breach does NOT double-count the transition.
        eng.status()
        assert metrics.counter_value("slo_breaches") == before + 1
        # Load drops; the slow tail ages out of the 5m window -> recover.
        clock.advance(360.0)
        for _ in range(50):
            eng.feed("clip_image_embed", 10.0)
        st = eng.status()["clip_image_embed"]
        assert st["state"] == "ok"
        assert metrics.counter_value("slo_breaches") == before + 1

    def test_availability_burn(self, monkeypatch):
        monkeypatch.setenv("LUMEN_SLO_AVAILABILITY", "0.99")
        clock = FakeClock()
        eng = SLOEngine(clock=clock)
        for _ in range(90):
            eng.feed("ocr", 5.0)
        for _ in range(10):
            eng.feed_error("ocr")
        st = eng.status()["ocr"]
        # 10% errors against a 1% budget: burn 10.
        assert st["availability_burn_5m"] == pytest.approx(10.0, rel=0.05)
        assert st["state"] == "breach"

    def test_no_objectives_means_empty_status(self):
        assert SLOEngine(clock=FakeClock()).status() == {}

    def test_availability_ignores_internal_names(self, monkeypatch):
        # Internal instrumentation histograms (per-stage trace series,
        # XLA compile durations) must not become bogus SLO "tasks" just
        # because an availability objective is configured.
        monkeypatch.setenv("LUMEN_SLO_AVAILABILITY", "0.999")
        eng = SLOEngine(clock=FakeClock())
        eng.feed("stage:echo/batch.device", 1.0)
        eng.feed("xla_compile_ms", 250.0)
        eng.feed("echo", 1.0)
        assert set(eng.status()) == {"echo"}

    def test_exact_classification_below_bucket_bounds(self, monkeypatch):
        # Exact slow/fast classification at feed time: an objective BELOW
        # the shared histogram's first bucket bound (0.1ms) — or between
        # any two log-spaced bounds — must still see its slow requests;
        # the engine does not inherit the buckets' ~47% quantization.
        monkeypatch.setenv("LUMEN_SLO_FINE_TASK_P95_MS", "0.05")
        eng = SLOEngine(clock=FakeClock())
        for _ in range(10):
            eng.feed("fine_task", 0.08)  # over objective, inside bucket 0
        assert eng.status()["fine_task"]["state"] == "breach"

    def test_breach_captures_incident(self, monkeypatch):
        monkeypatch.setenv("LUMEN_SLO_CLIP_IMAGE_EMBED_P95_MS", "50")
        clock = FakeClock()
        hub = TelemetryHub(clock=clock)
        tele.install_hub(hub)
        try:
            for _ in range(30):
                hub.observe("clip_image_embed", 500.0)
            hub.slo.status()
            bundles = tele.export_incidents()["incidents"]
            assert bundles and bundles[-1]["kind"] == "slo_breach"
        finally:
            tele.reset_hub()


class TestHealthSLOKey:
    def _health_trailing(self):
        from google.protobuf import empty_pb2

        from lumen_tpu.serving.echo import EchoService
        from lumen_tpu.serving.router import HubRouter

        router = HubRouter({"echo": EchoService()})
        captured = {}

        class Ctx:
            def set_trailing_metadata(self, md):
                captured.update(dict(md))

            def abort(self, code, msg):
                raise AssertionError(f"unexpected abort: {code} {msg}")

        router.Health(empty_pb2.Empty(), Ctx())
        return captured

    def test_slo_status_flips_health_metadata(self, monkeypatch):
        monkeypatch.setenv("LUMEN_SLO_ECHO_P95_MS", "100")
        clock = FakeClock()
        hub = TelemetryHub(clock=clock)
        tele.install_hub(hub)
        try:
            for _ in range(10):
                hub.observe("echo", 1.0)
            state = json.loads(self._health_trailing()["lumen-slo-status"])
            assert state["echo"]["state"] == "ok"
            for _ in range(90):
                hub.observe("echo", 5000.0)
            state = json.loads(self._health_trailing()["lumen-slo-status"])
            assert state["echo"]["state"] == "breach"
            assert state["echo"]["burn_5m"] > 1.0
            # Recovery: the bad minute ages out, fresh traffic is fast.
            clock.advance(360.0)
            for _ in range(10):
                hub.observe("echo", 1.0)
            state = json.loads(self._health_trailing()["lumen-slo-status"])
            assert state["echo"]["state"] == "ok"
        finally:
            tele.reset_hub()

    def test_no_objectives_omits_key(self):
        tele.reset_hub()
        assert "lumen-slo-status" not in self._health_trailing()


# -- flight recorder ---------------------------------------------------------


class TestFlightRecorder:
    def test_event_shape_and_bounds(self, hub):
        for i in range(hub.events.capacity + 50):
            hub.events.record("shed", f"b{i}", "queue full")
        events = hub.events.export()
        assert len(events) == hub.events.capacity
        e = events[-1]
        assert e["kind"] == "shed" and "unix_ms" in e and "seq" in e

    def test_event_carries_tenant_and_trace_id(self, hub, monkeypatch):
        from lumen_tpu.utils import qos as uqos
        from lumen_tpu.utils import trace as utrace

        monkeypatch.setenv("LUMEN_TRACE_SAMPLE", "1")
        tr = utrace.begin_request("evt_task")
        token = utrace.activate(tr)
        qtok = uqos.activate("acme", uqos.LANE_INTERACTIVE)
        try:
            e = tele.record_event("quarantine_add", "q", "poison")
        finally:
            uqos.deactivate(qtok)
            utrace.deactivate(token)
        assert e["tenant"] == "acme"
        assert e["trace_id"] == tr.trace_id

    def test_export_negative_n_is_not_an_inverted_slice(self, hub):
        for i in range(10):
            hub.events.record("shed", f"c{i}", "x")
        assert len(hub.events.export(3)) == 3
        assert len(hub.events.export(-3)) == 10   # "everything", not [3:]
        assert len(hub.events.export(0)) == 10

    def test_rate_limited_kinds(self, hub):
        assert hub.events.record("shed", "b", "x", min_interval_s=60.0)
        assert hub.events.record("shed", "b", "x", min_interval_s=60.0) is None
        # A different component keeps its own limiter.
        assert hub.events.record("shed", "b2", "x", min_interval_s=60.0)

    def test_incident_capture_and_debounce(self, hub, monkeypatch):
        before = metrics.counter_value("incidents_captured")
        e = tele.record_event("breaker_open", "clip", "tripped")
        assert e is not None
        bundles = tele.export_incidents()["incidents"]
        assert bundles
        b = bundles[-1]
        assert b["kind"] == "breaker_open"
        assert b["trigger"]["message"] == "tripped"
        assert "device_memory" in b and "gauges" in b and "trace_ids" in b
        assert any(ev["kind"] == "breaker_open" for ev in b["events"])
        assert metrics.counter_value("incidents_captured") == before + 1
        # Debounced: a second trigger of the same kind inside the
        # cooldown records the event but captures no second bundle.
        tele.record_event("breaker_open", "clip", "tripped again")
        assert len(tele.export_incidents()["incidents"]) == len(bundles)

    def test_incident_includes_retained_trace_ids(self, hub, monkeypatch):
        from lumen_tpu.utils import trace as utrace

        monkeypatch.setenv("LUMEN_TRACE_SAMPLE", "1")
        utrace.reset_recorder()
        tr = utrace.begin_request("incident_task")
        utrace.finish_request(tr, error="boom")  # errors are always retained
        try:
            tele.record_event("replica_down", "clip/r1", "wedged")
            b = tele.export_incidents()["incidents"][-1]
            assert tr.trace_id in b["trace_ids"]
        finally:
            utrace.reset_recorder()

    def test_events_disabled_by_ring_zero(self, monkeypatch):
        monkeypatch.setenv("LUMEN_EVENTS_RING", "0")
        tele.reset_hub()
        try:
            assert tele.record_event("breaker_open", "x", "y") is None
            assert tele.export_incidents()["incidents"] == []
        finally:
            monkeypatch.delenv("LUMEN_EVENTS_RING")
            tele.reset_hub()


# -- component wiring --------------------------------------------------------


class TestComponentWiring:
    def test_batcher_feeds_duty_and_batch_counters(self, hub):
        from lumen_tpu.runtime.batcher import MicroBatcher

        b = MicroBatcher(lambda tree, n: tree, max_batch=4, name="tele-b").start()
        try:
            assert b([1.0]) is not None
            assert b([2.0]) is not None
        finally:
            b.close()
        # The hub's fake clock never advances, so everything lands in
        # bucket 0 of... no: busy() uses time.monotonic from the BATCHER,
        # while the hub clock is fake. The counters below use hub.count
        # via telemetry.count -> hub clock, so they land at clock.t.
        out = hub.window_stats(3600)
        assert out["counters"].get("batch_items:tele-b", 0) >= 2
        assert "device:tele-b" in out["duty"]

    def test_decode_pool_feeds_duty(self, hub):
        from lumen_tpu.runtime.decode_pool import DecodePool

        pool = DecodePool(workers=2, name="tele-pool")
        try:
            assert pool.run(lambda: sum(range(1000))) == sum(range(1000))
        finally:
            pool.close()
        assert "decode:tele-pool" in hub.window_stats(3600)["duty"]
        assert hub.window_stats(3600)["duty"]["decode:tele-pool"]["capacity"] == 2

    def test_breaker_open_records_event(self, hub):
        from lumen_tpu.serving.breaker import CircuitBreaker

        br = CircuitBreaker("tele-brk", failures=2, window_s=30, reset_s=5)
        try:
            br.record_failure()
            br.record_failure()
            kinds = [e["kind"] for e in hub.events.export()]
            assert "breaker_open" in kinds
            assert tele.export_incidents()["incidents"][-1]["kind"] == "breaker_open"
        finally:
            br.close()

    def test_compile_listener_counts_compiles(self, hub):
        from lumen_tpu.runtime import compile_cache

        assert compile_cache.install_compile_listener()
        compile_cache._on_jax_event(
            "/jax/core/compile/backend_compile_duration", 0.25
        )
        compile_cache._on_jax_event("/jax/core/compile/jaxpr_trace_duration", 0.1)
        out = tele.capacity_stats(3600)
        assert out["compile"]["compiles"] == 1
        assert out["compile"]["ms"]["count"] == 1


# -- hardened gauge providers (satellite) ------------------------------------


class TestGaugeProviderHardening:
    def test_raising_provider_skipped_logged_counted(self, caplog):
        calls = {"bad": 0}

        def bad() -> dict:
            calls["bad"] += 1
            raise RuntimeError("provider exploded")

        metrics.register_gauges("good-provider", lambda: {"v": 1})
        metrics.register_gauges("bad-provider", bad)
        before = metrics.counter_value("gauge_provider_errors")
        try:
            snap = metrics.snapshot()
            assert snap["gauges"]["good-provider"] == {"v": 1}
            assert "bad-provider" not in snap.get("gauges", {})
            assert metrics.counter_value("gauge_provider_errors") == before + 1
            # Prometheus exposition survives too (the 500 regression).
            text = "\n".join(metrics.prometheus_lines())
            assert 'provider="good-provider"' in text
            # Logged once, not once per scrape.
            n_logs = sum(
                "bad-provider" in r.message for r in caplog.records
            )
            assert metrics.counter_value("gauge_provider_errors") == before + 2
            assert n_logs == 1
        finally:
            metrics.unregister_gauges("good-provider")
            metrics.unregister_gauges("bad-provider")

    def test_scrape_returns_200_with_throwing_provider(self):
        from lumen_tpu.serving.observability import MetricsServer

        def bad() -> dict:
            raise ValueError("scrape-time failure")

        metrics.register_gauges("http-bad-provider", bad)
        server = MetricsServer(port=0)
        port = server.start()
        try:
            for path in ("/metrics", "/metrics.json"):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10
                ) as resp:
                    assert resp.status == 200
                    resp.read()
        finally:
            server.stop()
            metrics.unregister_gauges("http-bad-provider")


# -- sidecar endpoints -------------------------------------------------------


@pytest.fixture()
def sidecar(hub):
    from lumen_tpu.serving.observability import MetricsServer

    server = MetricsServer(port=0)
    port = server.start()
    yield port
    server.stop()


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read().decode())


class TestSidecarEndpoints:
    def test_stats_endpoint(self, hub, sidecar):
        hub.observe("side_task", 5.0)
        out = _get(sidecar, "/stats?window=30")
        assert out["window_s"] == 30.0
        assert out["tasks"]["side_task"]["count"] == 1

    def test_stats_bad_window_degrades(self, hub, sidecar):
        out = _get(sidecar, "/stats?window=bogus")
        assert out["window_s"] == 60.0

    def test_slo_events_incidents_endpoints(self, hub, sidecar):
        tele.record_event("watchdog", "b", "hung")
        tele.record_event("breaker_open", "b", "tripped")
        slo = _get(sidecar, "/slo")
        assert "objectives" in slo and "tasks" in slo
        events = _get(sidecar, "/events?n=5")
        assert [e["kind"] for e in events["events"]].count("watchdog") == 1
        incidents = _get(sidecar, "/incidents")
        assert incidents["incidents"][-1]["kind"] == "breaker_open"

    def test_concurrent_scrapes_and_profiler_control(self, hub, sidecar, monkeypatch):
        """Satellite: ThreadingHTTPServer is threaded but nothing
        asserted it — parallel GET /metrics + /stats + POST
        /profiler/start|stop from many threads must neither deadlock nor
        interleave partial bodies (every response parses clean)."""
        from lumen_tpu.serving import observability as obs

        # The profiler control path minus the real jax.profiler (which
        # claims a backend): state transitions + 200/409 mapping intact.
        monkeypatch.setattr(
            obs._ProfilerState, "start",
            lambda self, d: (True, d), raising=True,
        )
        monkeypatch.setattr(
            obs._ProfilerState, "stop",
            lambda self: (True, "/tmp/x"), raising=True,
        )
        hub.observe("conc_task", 3.0)
        errors: list[BaseException] = []
        barrier = threading.Barrier(8)

        def worker(i: int) -> None:
            try:
                barrier.wait(timeout=10)
                for j in range(12):
                    if i % 4 == 0:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{sidecar}/metrics", timeout=10
                        ) as r:
                            body = r.read().decode()
                            assert body.endswith("\n")
                            assert "lumen_task_requests_total" in body
                    elif i % 4 == 1:
                        out = _get(sidecar, "/stats?window=30")
                        assert out["window_s"] == 30.0
                    elif i % 4 == 2:
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{sidecar}/profiler/start",
                            method="POST",
                        )
                        with urllib.request.urlopen(req, timeout=10) as r:
                            json.loads(r.read().decode())
                    else:
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{sidecar}/profiler/stop",
                            method="POST",
                        )
                        with urllib.request.urlopen(req, timeout=10) as r:
                            json.loads(r.read().decode())
            except BaseException as e:  # noqa: BLE001 - reported after join
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "sidecar worker deadlocked"
        assert not errors, errors[0]


# -- client stats subcommand (satellite) -------------------------------------


class TestClientStats:
    def test_get_stats_and_cli_against_fake_sidecar(self, capsys):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from lumen_tpu import client

        payload = {
            "window_s": 30.0,
            "enabled": True,
            "tasks": {"clip_image_embed": {
                "count": 42, "rps": 1.4, "p50_ms": 10.0, "p95_ms": 40.0,
                "p99_ms": 90.0, "sum_ms": 420.0, "mean_ms": 10.0,
            }},
            "duty": {
                "device:clip-image": {"busy_s": 12.0, "fraction": 0.4, "capacity": 1},
                "decode:decode_pool": {"busy_s": 30.0, "fraction": 0.25, "capacity": 4},
            },
            "batch": {"clip-image": {
                "items": 40, "padded": 8, "padding_waste_pct": 16.7,
                "distinct_buckets": 2,
            }},
            "compile": {"compiles": 3, "ms": None},
            "device_memory": {"0": {
                "bytes_in_use": 2 << 30, "bytes_limit": 16 << 30,
                "headroom_bytes": 14 << 30, "occupancy_pct": 12.5,
            }},
            "slo": {"clip_image_embed": {
                "state": "ok", "burn_5m": 0.2, "burn_1h": 0.1,
            }},
        }
        seen = {}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: A002
                pass

            def do_GET(self):  # noqa: N802
                seen["path"] = self.path
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            out = client.get_stats(f"127.0.0.1:{port}", window=30)
            assert out["tasks"]["clip_image_embed"]["count"] == 42
            assert seen["path"] == "/stats?window=30"
            rc = client.main(["stats", "--metrics-addr", f"127.0.0.1:{port}",
                              "--window", "30"])
            assert rc == 0
            printed = capsys.readouterr().out
            assert "clip_image_embed" in printed
            assert "p95=40.0ms" in printed
            assert "40.0% busy" in printed          # device duty line
            assert "HBM 12.5% used" in printed      # headroom line
            assert "burn_5m=0.2" in printed         # SLO line
            rc = client.main(["stats", "--metrics-addr", f"127.0.0.1:{port}",
                              "--json"])
            assert rc == 0
            assert json.loads(capsys.readouterr().out)["window_s"] == 30.0
        finally:
            httpd.shutdown()
            httpd.server_close()


# -- arrival-rate trend extraction (predictive autopilot sensor) -------------


class TestForecastRate:
    """Fake-clock trend fits: the forecast the predictive scale loop acts
    on must follow the arrival-rate slope, never go negative, and answer
    None whenever there is no sensor — a blind controller stays reactive."""

    def _feed(self, hub, name, per_bucket):
        """One count burst per telemetry bucket, then step into a fresh
        bucket so every fed bucket is COMPLETE (series() excludes the
        current partial bucket by design)."""
        for n in per_bucket:
            if n:
                hub.count(name, n)
            hub.clock_handle.advance(hub.bucket_s)

    def test_rising_trend_forecasts_above_current_rate(self, hub):
        self._feed(hub, "batch_items:vlm", [5, 10, 15, 20, 25, 30])
        f = hub.forecast_rate("batch_items:vlm", 30.0, 60.0)
        assert f is not None
        newest_rate = 30 / hub.bucket_s
        assert f > newest_rate, (f, newest_rate)

    def test_falling_trend_forecasts_below_and_floors_at_zero(self, hub):
        self._feed(hub, "batch_items:vlm", [30, 25, 20, 15, 10, 5])
        f = hub.forecast_rate("batch_items:vlm", 30.0, 30.0)
        assert f is not None
        assert f < 5 / hub.bucket_s
        # A long horizon extrapolates past zero arrivals — floored, never
        # a negative rate.
        far = hub.forecast_rate("batch_items:vlm", 30.0, 600.0)
        assert far == 0.0

    def test_flat_trend_forecasts_the_current_rate(self, hub):
        self._feed(hub, "batch_items:vlm", [10, 10, 10, 10, 10, 10])
        f = hub.forecast_rate("batch_items:vlm", 30.0, 120.0)
        assert f is not None
        assert abs(f - 10 / hub.bucket_s) < 1e-9

    def test_bursty_window_is_finite_and_nonnegative(self, hub):
        self._feed(hub, "batch_items:vlm", [40, 0, 35, 0, 45, 0])
        f = hub.forecast_rate("batch_items:vlm", 30.0, 60.0)
        assert f is not None
        assert f >= 0.0
        assert f < 1000.0

    def test_no_sensor_means_no_forecast(self, hub):
        assert hub.forecast_rate("batch_items:nope", 30.0, 60.0) is None

    def test_module_facade_gates_on_hub(self, monkeypatch):
        # No hub installed: the module function must answer None without
        # building one (the unconfigured path allocates nothing).
        tele.reset_hub()
        assert tele.forecast_rate("batch_items:x", 30.0, 60.0) is None
        assert tele.device_duty(30.0) is None

    def test_device_duty_none_without_meters_then_worst(self, hub):
        assert hub.device_duty(30.0) is None
        hub.set_capacity("device:a", 1.0)
        hub.set_capacity("device:b", 1.0)
        t = hub.clock_handle.t
        hub.busy("device:a", t, t + 3.0)
        hub.busy("device:b", t, t + 0.5)
        hub.clock_handle.advance(5.0)
        duty = hub.device_duty(30.0)
        assert duty is not None
        # max over meters: device:a's fraction dominates.
        assert duty > 0.05
