"""Shared fixture helpers: build a tiny, fully self-contained CLIP model
directory (random HF weights, tokenizer, manifest, dataset) so manager and
service tests run offline end-to-end."""

import json

import numpy as np


#: Normalizer stat leaves get deterministic fills: a random ``var`` or
#: ``scale`` can be ≤ 0 and would NaN the normalizer's rsqrt/division.
#: ``mean``/``bias`` are sign-safe and stay random (keeping the rng draw
#: order — and therefore every downstream fixture weight — stable).
_ONES_LEAVES = frozenset({"var", "scale"})
#: Leaf names allowed under a stats collection (``batch_stats`` etc.).
#: Anything else fails loudly: a future stat leaf silently filled with
#: random (possibly ≤ 0) values is exactly the bug this guard prevents.
_KNOWN_STAT_LEAVES = frozenset({"var", "scale", "mean", "bias"})
_STATS_COLLECTIONS = frozenset({"batch_stats"})


def _path_keys(path) -> list:
    """Concrete key names along a tree_map_with_path keypath."""
    keys = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                keys.append(getattr(entry, attr))
                break
    return keys


def random_variables(init_fn, scale=0.05, seed=0):
    """Shape-only flax init: ``eval_shape`` the init, fill host-side.

    Tests only need plausibly-random weights with the right tree structure;
    skipping the real ``Module.init`` avoids an XLA compile (~10s each on
    CPU). Normalizer stats are matched by explicit leaf name (``var`` /
    ``scale`` -> ones) rather than a string-suffix heuristic, and an
    unrecognized leaf under a stats collection raises instead of silently
    receiving values that could be ≤ 0 and NaN the normalizer.
    """
    import jax

    rng = np.random.default_rng(seed)

    def fill(path, a):
        keys = _path_keys(path)
        leaf = keys[-1] if keys else ""
        if any(k in _STATS_COLLECTIONS for k in keys[:-1]) and leaf not in _KNOWN_STAT_LEAVES:
            raise ValueError(
                f"unknown normalizer stat leaf {leaf!r} at {jax.tree_util.keystr(path)}; "
                f"add it to clip_fixtures with a sign-safe fill"
            )
        if not np.issubdtype(a.dtype, np.floating):
            return np.zeros(a.shape, a.dtype)
        if leaf in _ONES_LEAVES:
            return np.ones(a.shape, a.dtype)
        return (rng.standard_normal(a.shape) * scale).astype(a.dtype)

    return jax.tree_util.tree_map_with_path(fill, jax.eval_shape(init_fn))


def make_tiny_hf_clip(seed: int = 0):
    import torch
    from transformers import CLIPConfig as HFCLIPConfig, CLIPModel as HFCLIPModel

    cfg = HFCLIPConfig(
        projection_dim=32,
        text_config={
            "hidden_size": 48,
            "num_hidden_layers": 2,
            "num_attention_heads": 4,
            "vocab_size": 128,
            "max_position_embeddings": 16,
            "intermediate_size": 192,
            "hidden_act": "quick_gelu",
            "eos_token_id": 127,
        },
        vision_config={
            "hidden_size": 64,
            "num_hidden_layers": 2,
            "num_attention_heads": 4,
            "image_size": 32,
            "patch_size": 16,
            "intermediate_size": 256,
            "hidden_act": "quick_gelu",
        },
    )
    torch.manual_seed(seed)
    return HFCLIPModel(cfg).eval()


def write_tiny_tokenizer(path: str):
    from tokenizers import Tokenizer, models, pre_tokenizers
    from tokenizers.processors import TemplateProcessing

    vocab = {"<unk>": 0, "a": 1, "photo": 2, "of": 3, "cat": 4, "dog": 5, "car": 6, "<eot>": 127}
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.post_processor = TemplateProcessing(
        single="$A <eot>", special_tokens=[("<eot>", 127)]
    )
    tok.save(path)


def make_clip_model_dir(tmp_path, with_dataset: bool = True) -> str:
    """Build <tmp>/models/TinyCLIP with weights/config/tokenizer/manifest."""
    from safetensors.numpy import save_file

    hf = make_tiny_hf_clip()
    model_dir = tmp_path / "models" / "TinyCLIP"
    model_dir.mkdir(parents=True, exist_ok=True)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    state = {k: v for k, v in state.items() if "position_ids" not in k}
    save_file(state, str(model_dir / "model.safetensors"))
    (model_dir / "config.json").write_text(json.dumps(hf.config.to_dict()))
    write_tiny_tokenizer(str(model_dir / "tokenizer.json"))
    info = {
        "name": "TinyCLIP",
        "version": "1.0.0",
        "description": "tiny test model",
        "model_type": "clip",
        "embedding_dim": 32,
        "source": {"format": "huggingface", "repo_id": "LumilioPhotos/TinyCLIP"},
        "runtimes": {"jax": {"available": True, "files": ["model.safetensors"]}},
    }
    if with_dataset:
        info["datasets"] = {
            "Tiny": {"labels": "datasets/tiny/labels.json", "embeddings": "datasets/tiny/embeddings.npy"}
        }
        ds = model_dir / "datasets" / "tiny"
        ds.mkdir(parents=True, exist_ok=True)
        (ds / "labels.json").write_text(json.dumps(["cat", "dog", "car"]))
        # embeddings .npy intentionally absent -> computed at startup
    (model_dir / "model_info.json").write_text(json.dumps(info))
    return str(model_dir)


def png_bytes(seed: int = 0, size: int = 40) -> bytes:
    import cv2

    rng = np.random.default_rng(seed)
    img = rng.integers(0, 255, (size, size, 3), np.uint8)
    ok, buf = cv2.imencode(".png", img)
    assert ok
    return buf.tobytes()
