"""Tier-1 gate: every counter/gauge/histogram name published in the
package appears in the docs/OBSERVABILITY.md cookbook, so the metric
surface can't silently drift. See scripts/check_metrics.py."""

import importlib.util
import os

_SPEC = importlib.util.spec_from_file_location(
    "check_metrics",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "check_metrics.py"),
)
check_metrics = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(check_metrics)


def test_every_published_metric_is_documented():
    missing = check_metrics.undocumented()
    assert not missing, (
        f"metric names published in code but missing from "
        f"docs/OBSERVABILITY.md: {missing} — add each to the cookbook "
        "(counter table / gauge-provider table / histogram section)"
    )


def test_scan_finds_known_names():
    # Sanity that the scan sees through each pattern family — a regex typo
    # must not turn the gate into a silent pass.
    names = check_metrics.published_names()
    assert "sheds" in names                 # metrics.count literal
    assert "deadline_drops:" in names       # metrics.count f-string prefix
    assert "cache_hits" in names            # result_cache _count indirection
    assert "stage:" in names                # trace-fed histogram prefix
    assert "batcher:" in names              # register_gauges f-string prefix
    assert "result_cache" in names          # name-variable provider
    doc = check_metrics.documented_text()
    assert "lumen_events_total" in doc
