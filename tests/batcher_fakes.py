"""Shared fakes for exercising the pipelined MicroBatcher without a device."""

import time

import numpy as np


class SlowFetch:
    """Stand-in for an un-fetched device result: the batcher's fetch worker
    hits ``jax.device_get`` -> ``np.asarray`` -> ``__array__``, which is
    where a real device->host transfer would block."""

    def __init__(self, arr, delay: float):
        self.arr = np.asarray(arr)
        self.delay = delay

    def __array__(self, dtype=None, copy=None):
        time.sleep(self.delay)
        return self.arr
