"""Runtime core tests: mesh resolution, batcher semantics, weight loading."""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lumen_tpu.runtime import (
    MicroBatcher,
    apply_rules,
    assert_tree_shapes,
    bucket_for,
    build_mesh,
    conv_kernel,
    default_buckets,
    flatten,
    get_policy,
    linear_kernel,
    load_state_dict,
    resolve_axes,
    unflatten,
)
from lumen_tpu.runtime.weights import WeightLoadError


class TestMesh:
    def test_resolve_wildcard(self):
        assert resolve_axes({"data": -1}, 8) == {"data": 8}
        assert resolve_axes({"data": -1, "model": 2}, 8) == {"data": 4, "model": 2}

    def test_resolve_exact(self):
        assert resolve_axes({"data": 4, "model": 2}, 8) == {"data": 4, "model": 2}

    def test_resolve_mismatch(self):
        # A data axis that does not fit degrades to the largest size that
        # does (ISSUE 7 satellite: LUMEN_REPLICAS=8 on a 4-chip host must
        # serve 4 ways, not fail boot) ...
        assert resolve_axes({"data": 8}, 4) == {"data": 4}
        assert resolve_axes({"data": 3}, 8) == {"data": 2}
        assert resolve_axes({"data": 6, "model": 2}, 8) == {"data": 4, "model": 2}
        # Exact-divisor under-cover serves on the device prefix (same
        # graceful policy as the non-dividing case above, which also
        # lands on a 4-of-8 mesh).
        assert resolve_axes({"data": 4}, 8) == {"data": 4}
        # ... but a non-data axis (TP) still raises: silently shrinking it
        # would change which checkpoints even fit.
        with pytest.raises(ValueError):
            resolve_axes({"data": -1, "model": 3}, 8)

    @pytest.mark.multichip
    def test_build_mesh_8_devices(self):
        mesh = build_mesh({"data": -1, "model": 2})
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    @pytest.mark.multichip
    def test_data_parallel_psum(self):
        # Sanity: a shard_map psum over the data axis actually reduces.
        from jax.sharding import PartitionSpec as P

        from lumen_tpu.parallel.compat import shard_map

        mesh = build_mesh({"data": -1})
        x = np.arange(8, dtype=np.float32)
        f = shard_map(
            lambda v: jax.lax.psum(v, "data"), mesh=mesh,
            in_specs=P("data"), out_specs=P(),
        )
        out = jax.jit(f)(x)
        assert float(out[0]) == x.sum()


class TestPolicy:
    def test_bf16_policy_casts_floats_only(self):
        p = get_policy("bfloat16")
        tree = {"w": jnp.ones((2, 2), jnp.float32), "idx": jnp.ones((2,), jnp.int32)}
        out = p.cast_params(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["idx"].dtype == jnp.int32

    def test_unknown_policy(self):
        with pytest.raises(ValueError):
            get_policy("fp8")


class TestBatcher:
    def test_buckets(self):
        assert default_buckets(8) == [1, 2, 4, 8]
        assert default_buckets(6) == [1, 2, 4, 6]
        assert bucket_for(3, [1, 2, 4, 8]) == 4
        assert bucket_for(9, [1, 2, 4, 8]) == 8

    def test_single_item(self):
        calls = []

        def fn(tree, n):
            calls.append((tree["x"].shape, n))
            return {"y": tree["x"] * 2}

        b = MicroBatcher(fn, max_batch=4, max_latency_ms=1).start()
        try:
            out = b({"x": np.array([1.0, 2.0])})
            assert np.allclose(out["y"], [2.0, 4.0])
            assert calls[0] == ((1, 2), 1)
        finally:
            b.close()

    def test_close_never_started_spares_live_gauges(self):
        """ADVICE r3: closing a never-started same-name batcher must not
        evict a live batcher's gauge provider (gauges register at start();
        an unstarted instance has none to unregister)."""
        from lumen_tpu.utils.metrics import metrics

        fn = lambda tree, n: tree  # noqa: E731
        live = MicroBatcher(fn, max_batch=2, max_latency_ms=1, name="gauge-t").start()
        try:
            stale = MicroBatcher(fn, max_batch=2, max_latency_ms=1, name="gauge-t")
            stale.close()  # never started
            assert "batcher:gauge-t" in (metrics.snapshot().get("gauges") or {})
        finally:
            live.close()
        assert "batcher:gauge-t" not in (metrics.snapshot().get("gauges") or {})

    def test_concurrent_submissions_batch_together(self):
        seen_batches = []

        def fn(tree, n):
            time.sleep(0.01)
            seen_batches.append(n)
            return tree * 10

        b = MicroBatcher(fn, max_batch=8, max_latency_ms=50).start()
        try:
            results = [None] * 8
            def worker(i):
                results[i] = b(np.array([float(i)]))
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert [float(r[0]) for r in results] == [i * 10.0 for i in range(8)]
            # With a 50ms window, the 8 near-simultaneous items should land
            # in far fewer than 8 batches.
            assert sum(seen_batches) == 8 and len(seen_batches) <= 4
        finally:
            b.close()

    def test_padding_to_bucket(self):
        shapes = []

        def fn(tree, n):
            shapes.append((tree.shape[0], n))
            return tree + 1

        b = MicroBatcher(fn, max_batch=8, max_latency_ms=30).start()
        try:
            futs = [b.submit(np.zeros((3,))) for _ in range(3)]
            outs = [f.result(timeout=5) for f in futs]
            assert all(o.shape == (3,) for o in outs)
            assert shapes[0] == (4, 3)  # 3 items padded to bucket 4
            assert b.stats["padded"] == 1
        finally:
            b.close()

    def test_error_fans_out(self):
        def fn(tree, n):
            raise RuntimeError("device on fire")

        b = MicroBatcher(fn, max_batch=2, max_latency_ms=1).start()
        try:
            with pytest.raises(RuntimeError, match="device on fire"):
                b(np.zeros((1,)))
        finally:
            b.close()

    def test_submit_after_close(self):
        b = MicroBatcher(lambda t, n: t, max_batch=2).start()
        b.close()
        with pytest.raises(RuntimeError):
            b.submit(np.zeros((1,)))

    def test_jitted_fn_with_static_buckets_compiles_once_per_bucket(self):
        traces = []

        @jax.jit
        def model(x):
            traces.append(x.shape)
            return x * 2.0

        b = MicroBatcher(lambda t, n: model(t), max_batch=4, max_latency_ms=5).start()
        try:
            for _ in range(3):
                b(np.ones((2, 2), np.float32))
            # All single-item calls hit bucket 1 -> one trace only.
            assert traces == [(1, 2, 2)]
        finally:
            b.close()


class TestWeights:
    def test_layout_helpers(self):
        w = np.arange(6).reshape(2, 3)
        assert linear_kernel(w).shape == (3, 2)
        c = np.zeros((8, 4, 3, 3))
        assert conv_kernel(c).shape == (3, 3, 4, 8)

    def test_apply_rules_and_unflatten(self):
        state = {
            "visual.blocks.0.attn.weight": np.zeros((4, 4)),
            "visual.blocks.0.attn.bias": np.zeros((4,)),
            "logit_scale": np.array(4.6),
            "ignored.num_batches_tracked": np.array(0),
        }
        rules = [
            (r"visual\.blocks\.(\d+)\.attn\.weight", r"vision/block_\1/attn/kernel", linear_kernel),
            (r"visual\.blocks\.(\d+)\.attn\.bias", r"vision/block_\1/attn/bias", None),
            (r"logit_scale", r"logit_scale", None),
        ]
        flat = apply_rules(state, rules, drop=[r"num_batches_tracked"])
        tree = unflatten(flat)
        assert tree["vision"]["block_0"]["attn"]["kernel"].shape == (4, 4)
        assert "logit_scale" in tree

    def test_apply_rules_strict_unmatched(self):
        with pytest.raises(WeightLoadError):
            apply_rules({"mystery": np.zeros(1)}, [], strict=True)

    def test_tree_shape_gate(self):
        good = {"a": {"w": np.zeros((2, 2))}}
        assert_tree_shapes(good, {"a": {"w": np.ones((2, 2))}})
        with pytest.raises(WeightLoadError):
            assert_tree_shapes(good, {"a": {"w": np.ones((3, 2))}})
        with pytest.raises(WeightLoadError):
            assert_tree_shapes(good, {"a": {"w": np.ones((2, 2)), "b": np.ones(1)}})

    def test_flatten_roundtrip(self):
        tree = {"a": {"b": np.ones(1), "c": {"d": np.zeros(2)}}}
        assert unflatten(flatten(tree)).keys() == tree.keys()

    def test_load_safetensors_roundtrip(self, tmp_path):
        from safetensors.numpy import save_file

        save_file({"x": np.arange(4, dtype=np.float32)}, str(tmp_path / "model.safetensors"))
        state = load_state_dict(str(tmp_path))
        assert np.allclose(state["x"], np.arange(4))

    def test_load_torch_checkpoint(self, tmp_path):
        import torch

        torch.save({"w": torch.ones(2, 2, dtype=torch.bfloat16)}, str(tmp_path / "model.bin"))
        state = load_state_dict(str(tmp_path))
        assert state["w"].dtype == np.float32 and state["w"].shape == (2, 2)

    def test_no_checkpoint_raises(self, tmp_path):
        with pytest.raises(WeightLoadError):
            load_state_dict(str(tmp_path))


class TestMeshBatching:
    def test_mesh_buckets_multiples(self):
        from lumen_tpu.runtime.batcher import mesh_buckets

        assert mesh_buckets(8, 1) == [1, 2, 4, 8]
        assert mesh_buckets(32, 8) == [8, 16, 32]
        assert mesh_buckets(8, 8) == [8]
        # max_batch rounded up to a dp multiple
        assert mesh_buckets(12, 8) == [8, 16]

    def test_mesh_sharded_places_on_data_axis(self):
        import jax
        import numpy as np

        from lumen_tpu.runtime.batcher import mesh_sharded
        from lumen_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"data": -1})
        seen = {}

        def fn(x, n):
            seen["spec"] = x.sharding.spec
            return np.asarray(x)

        wrapped = mesh_sharded(fn, mesh)
        out = wrapped(np.zeros((8, 4), np.float32), 8)
        assert seen["spec"][0] == "data"
        assert out.shape == (8, 4)


class TestCompileCache:
    def test_enable_points_jax_at_dir(self, tmp_path, monkeypatch):
        import jax

        from lumen_tpu.runtime import enable_persistent_cache

        monkeypatch.delenv("LUMEN_COMPILE_CACHE", raising=False)
        monkeypatch.delenv("LUMEN_COMPILE_CACHE_DIR", raising=False)
        target = str(tmp_path / "xla")
        prev = jax.config.jax_compilation_cache_dir
        try:
            got = enable_persistent_cache(target)
            assert got == target
            assert os.path.isdir(target)
            assert jax.config.jax_compilation_cache_dir == target
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_env_opt_out(self, tmp_path, monkeypatch):
        from lumen_tpu.runtime import enable_persistent_cache

        monkeypatch.setenv("LUMEN_COMPILE_CACHE", "0")
        assert enable_persistent_cache(str(tmp_path / "x")) is None
        assert not os.path.exists(str(tmp_path / "x"))

    def test_env_dir_override(self, tmp_path, monkeypatch):
        import jax

        from lumen_tpu.runtime import enable_persistent_cache

        monkeypatch.delenv("LUMEN_COMPILE_CACHE", raising=False)
        target = str(tmp_path / "envdir")
        prev = jax.config.jax_compilation_cache_dir
        monkeypatch.setenv("LUMEN_COMPILE_CACHE_DIR", target)
        try:
            assert enable_persistent_cache() == target
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
