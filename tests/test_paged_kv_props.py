"""Property tests for the refcounted page pool's sharing invariants.

``test_paged_attention.py::TestPagedKVPool*`` pins specific scenarios;
this module sweeps RANDOM interleavings of every reference-creating and
reference-dropping operation the engine performs — admission, shared
(prefix-hit) admission, exact (spill-resume) admission, growth with
copy-on-write, cache-style holds, release — against a model of what the
reference counts must be:

- **conservation** — the pool's refcount map always equals the model's;
  ``pages_live`` equals the total outstanding references (reference-
  granular accounting), and free + held partitions the usable pool;
- **no double free** — dropping a dead reference raises instead of
  corrupting the free list; a freed page cannot be resurrected by
  incref;
- **write isolation** — a page with more than one holder is never the
  append frontier after ``grow`` returns (CoW swapped it);
- **balance at drain** — releasing every slot and dropping every hold
  returns the pool to zero live pages with allocated == freed, no matter
  which interleaving produced the state.

The fixed-seed walks below always run; when the optional ``hypothesis``
dev dependency is present, the same walker also sweeps minimized random
seeds (shrinking turns a failing walk into a short repro).
"""

from __future__ import annotations

import numpy as np
import pytest

from lumen_tpu.models.vlm.paged_kv import PagedKVPool, PoolExhausted

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - optional dev dependency
    HAVE_HYPOTHESIS = False

SLOTS, PAGE, MAXP = 5, 4, 6
USABLE = 24  # pages_total - 1 (dump page never granted)


class _Model:
    """Reference-count oracle mirrored op-by-op alongside the pool."""

    def __init__(self):
        self.refs: dict[int, int] = {}  # page -> outstanding references
        self.rows: dict[int, list[int]] = {}  # slot -> pages in table order
        self.shared: dict[int, int] = {}  # slot -> shared prefix length
        self.holds: list[int] = []  # cache/spill-style extra references

    def add(self, pages):
        for p in pages:
            self.refs[p] = self.refs.get(p, 0) + 1

    def drop(self, pages):
        for p in pages:
            self.refs[p] -= 1
            if not self.refs[p]:
                del self.refs[p]


def _check(pool: PagedKVPool, m: _Model) -> None:
    assert pool._ref == m.refs, "refcount map diverged from the model"
    assert pool.pages_live == sum(m.refs.values())
    assert pool.pages_free == USABLE - len(m.refs)
    assert 0 not in m.refs, "dump page acquired a reference"
    for slot, pages in m.rows.items():
        got = list(pool.block_tables[slot][: len(pages)])
        assert got == pages, f"slot {slot} table diverged"
        # Write isolation: the append frontier is private unless the slot
        # has not grown past its shared prefix yet (the engine's shared
        # admissions always grant >= 1 private page, modeled below).
        assert m.refs[pages[-1]] >= 1


def _walk(rng: np.random.Generator, steps: int = 300) -> None:
    pool = PagedKVPool(
        pages_total=USABLE + 1, page_size=PAGE, slots=SLOTS, max_pages=MAXP
    )
    m = _Model()
    for _ in range(steps):
        op = rng.integers(0, 6)
        if op == 0 and len(m.rows) < SLOTS:  # plain admission
            slot = next(i for i in range(SLOTS) if i not in m.rows)
            tokens = int(rng.integers(1, MAXP * PAGE - 1))
            if pool.can_admit(tokens):
                pool.admit(slot, tokens)
                m.rows[slot] = pool.owned_pages(slot)
                m.add(m.rows[slot])
        elif op == 1 and m.rows and len(m.rows) < SLOTS:  # prefix-hit admission
            donor = int(rng.choice(list(m.rows)))
            slot = next(i for i in range(SLOTS) if i not in m.rows)
            # Shared coverage: full pages of the donor, capped one token
            # short of the new prompt (the hit path's frontier rule).
            tokens = int(rng.integers(1, MAXP * PAGE - 1))
            n_share = min(len(m.rows[donor]), (tokens - 1) // PAGE)
            if n_share < 1:
                continue
            shared = m.rows[donor][:n_share]
            try:
                pool.admit_shared(slot, shared, tokens)
            except PoolExhausted:
                continue
            m.rows[slot] = pool.owned_pages(slot)
            m.shared[slot] = n_share
            m.add(m.rows[slot])
        elif op == 2 and m.rows and len(m.rows) < SLOTS:  # spill-resume admission
            donor = int(rng.choice(list(m.rows)))
            slot = next(i for i in range(SLOTS) if i not in m.rows)
            n_share = int(rng.integers(0, len(m.rows[donor]) + 1))
            n_share = min(n_share, MAXP - 1)
            shared = m.rows[donor][:n_share]
            n_fresh = int(rng.integers(1, MAXP - n_share + 1))
            try:
                pool.admit_exact(slot, n_fresh, shared_pages=shared or None)
            except PoolExhausted:
                continue
            m.rows[slot] = pool.owned_pages(slot)
            if n_share:
                m.shared[slot] = n_share
            m.add(m.rows[slot])
        elif op == 3 and m.rows:  # growth (with CoW sink)
            slot = int(rng.choice(list(m.rows)))
            before = list(m.rows[slot])
            cow: list = []
            grew = pool.grow(slot, int(rng.integers(1, MAXP * PAGE + 8)), cow)
            after = pool.owned_pages(slot)
            for old, new in cow:
                m.drop([old])
                m.add([new])
            m.add(after[len(before):])
            m.rows[slot] = after
            if cow:
                # CoW only ever swaps the frontier, and only when shared.
                assert len(cow) == 1
                assert cow[0][0] == before[-1]
                assert m.refs.get(cow[0][0], 0) >= 1  # other holder survives
            if not grew:
                assert pool.pages_free == 0  # dry free list is the only False
        elif op == 4 and m.rows:  # release
            slot = int(rng.choice(list(m.rows)))
            pool.release(slot)
            m.drop(m.rows.pop(slot))
            m.shared.pop(slot, None)
        elif op == 5:  # cache/spill-record style hold churn
            if m.holds and rng.integers(0, 2):
                i = int(rng.integers(0, len(m.holds)))
                page = m.holds.pop(i)
                pool.decref([page])
                m.drop([page])
            elif m.refs:
                page = int(rng.choice(list(m.refs)))
                pool.incref([page])
                m.holds.append(page)
                m.add([page])
        _check(pool, m)
    # Drain: every interleaving must balance exactly.
    for slot in list(m.rows):
        pool.release(slot)
        m.drop(m.rows.pop(slot))
    for page in m.holds:
        pool.decref([page])
        m.drop([page])
    assert not m.refs
    assert pool.pages_live == 0
    assert pool.pages_free == USABLE
    assert pool.allocated_total == pool.freed_total


class TestRefcountInvariants:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 17, 99, 1234, 777777])
    def test_random_walk_fixed_seeds(self, seed):
        _walk(np.random.default_rng(seed))

    def test_double_free_and_resurrection_raise(self):
        pool = PagedKVPool(pages_total=8, page_size=4, slots=2, max_pages=4)
        pool.admit(0, prompt_tokens=3)
        page = pool.owned_pages(0)[0]
        pool.release(0)
        with pytest.raises(RuntimeError):
            pool.decref([page])
        with pytest.raises(RuntimeError):
            pool.incref([page])

    def test_release_preserves_lifo_reuse_order(self):
        """Refcounting must not perturb the pre-sharing allocator's LIFO
        reuse order: release returns a row's pages so its FIRST page is
        the next page granted (hot-page HBM reuse, and what keeps the
        golden paging traces stable across the refcount change)."""
        pool = PagedKVPool(pages_total=16, page_size=4, slots=2, max_pages=4)
        pool.admit(0, prompt_tokens=10)
        first = pool.owned_pages(0)[0]
        pool.release(0)
        pool.admit(1, prompt_tokens=1)
        assert pool.owned_pages(1)[0] == first


if HAVE_HYPOTHESIS:

    class TestRefcountInvariantsHypothesis:
        @settings(max_examples=25, deadline=None)
        @given(seed=st.integers(min_value=0, max_value=2**32 - 1))
        def test_random_walk_swept_seeds(self, seed):
            _walk(np.random.default_rng(seed), steps=120)
