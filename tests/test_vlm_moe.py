"""MoE decoder golden parity: our sparse VLM decoder vs HF Qwen2-MoE.

Extends the Qwen2 parity bar (``tests/test_vlm_golden.py``) to the
mixture-of-experts decoder: builds a REAL ``Qwen2MoeForCausalLM`` through
the HF reference implementation (router + per-expert SwiGLU + sigmoid-gated
shared expert, ``norm_topk_prob=False``), converts its checkpoint with
``convert_vlm_checkpoint`` (expert banks stacked to ``[E, ...]``), and
asserts prefill logits and greedy generation match token-for-token.

Our routed compute goes through ``parallel.moe.moe_ffn`` with EXACT
capacity, so the GShard dispatch/combine einsums must reproduce HF's
dense-gather loop bit-for-bit at fp32 tolerances.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from lumen_tpu.models.vlm.convert import convert_vlm_checkpoint  # noqa: E402
from lumen_tpu.models.vlm.generate import Generator  # noqa: E402
from lumen_tpu.models.vlm.modeling import VLMConfig, VLMModel  # noqa: E402

VOCAB = 128
HIDDEN = 32
LAYERS = 2
HEADS = 4
KV_HEADS = 2
EXPERTS = 4
TOP_K = 2
MOE_INTER = 48
SHARED_INTER = 40
EOS = 2


@pytest.fixture(scope="module")
def hf_moe():
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    torch.manual_seed(0)
    cfg = Qwen2MoeConfig(
        vocab_size=VOCAB,
        hidden_size=HIDDEN,
        intermediate_size=64,
        num_hidden_layers=LAYERS,
        num_attention_heads=HEADS,
        num_key_value_heads=KV_HEADS,
        max_position_embeddings=128,
        rope_theta=10_000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        num_experts=EXPERTS,
        num_experts_per_tok=TOP_K,
        moe_intermediate_size=MOE_INTER,
        shared_expert_intermediate_size=SHARED_INTER,
        decoder_sparse_step=1,
        norm_topk_prob=False,
        mlp_only_layers=[],
        output_router_logits=False,
        bos_token_id=1,
        eos_token_id=EOS,
        pad_token_id=0,
        attention_dropout=0.0,
    )
    model = Qwen2MoeForCausalLM(cfg)
    model.eval()
    return cfg, model


@pytest.fixture(scope="module")
def ours(hf_moe):
    _, hf_model = hf_moe
    cfg = VLMConfig.from_hf(
        {
            "text_config": {
                "vocab_size": VOCAB,
                "hidden_size": HIDDEN,
                "intermediate_size": 64,
                "num_hidden_layers": LAYERS,
                "num_attention_heads": HEADS,
                "num_key_value_heads": KV_HEADS,
                "max_position_embeddings": 128,
                "rope_theta": 10_000.0,
                "rms_norm_eps": 1e-6,
                "tie_word_embeddings": True,
                "num_experts": EXPERTS,
                "num_experts_per_tok": TOP_K,
                "moe_intermediate_size": MOE_INTER,
                "shared_expert_intermediate_size": SHARED_INTER,
                "decoder_sparse_step": 1,
                "norm_topk_prob": False,
                "bos_token_id": 1,
                "eos_token_id": EOS,
                "pad_token_id": 0,
            },
            "vision_config": {
                "image_size": 32,
                "patch_size": 16,
                "hidden_size": 48,
                "num_hidden_layers": 1,
                "num_attention_heads": 4,
            },
            "image_token_index": VOCAB - 1,
        }
    )
    assert cfg.decoder.moe_experts == EXPERTS
    assert cfg.decoder.moe_norm_topk is False
    model = VLMModel(cfg)
    init = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, 4), jnp.int32),
        jnp.zeros((1, cfg.vision.image_size, cfg.vision.image_size, 3), jnp.float32),
    )["params"]
    state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = convert_vlm_checkpoint(state, init_params=None, tie_word_embeddings=True)
    params["vision"] = init["vision"]
    return cfg, model, params


def _prompt(seed=7, b=1, s=9):
    rng = np.random.RandomState(seed)
    return rng.randint(3, VOCAB - 2, size=(b, s)).astype(np.int32)


class TestMoEConfig:
    def test_dense_config_has_no_moe_layers(self):
        cfg = VLMConfig.tiny()
        assert not any(cfg.decoder.is_moe_layer(i) for i in range(cfg.decoder.layers))

    def test_sparse_step_selects_layers(self):
        from dataclasses import replace

        d = replace(VLMConfig.tiny().decoder, moe_experts=4, moe_every=2)
        assert [d.is_moe_layer(i) for i in range(4)] == [False, True, False, True]

    def test_mlp_only_layers_force_dense(self):
        from dataclasses import replace

        d = replace(
            VLMConfig.tiny().decoder, moe_experts=4, moe_every=1, moe_dense_layers=(0, 2)
        )
        assert [d.is_moe_layer(i) for i in range(4)] == [False, True, False, True]
        cfg = VLMConfig.from_hf(
            {"num_experts": 4, "mlp_only_layers": [1], "num_hidden_layers": 3}
        )
        assert cfg.decoder.moe_dense_layers == (1,)
        assert not cfg.decoder.is_moe_layer(1) and cfg.decoder.is_moe_layer(0)

    def test_converted_param_shapes(self, ours):
        _, _, params = ours
        mlp = params["decoder"]["layers_0"]["mlp"]
        assert mlp["router"].shape == (HIDDEN, EXPERTS)
        assert mlp["w_gate"].shape == (EXPERTS, HIDDEN, MOE_INTER)
        assert mlp["w_up"].shape == (EXPERTS, HIDDEN, MOE_INTER)
        assert mlp["w_down"].shape == (EXPERTS, MOE_INTER, HIDDEN)
        assert mlp["shared"]["gate_proj"]["kernel"].shape == (HIDDEN, SHARED_INTER)
        assert mlp["shared_gate"]["kernel"].shape == (HIDDEN, 1)


class TestQwen2MoeGoldenParity:
    def test_prefill_logits_match_hf(self, hf_moe, ours):
        _, hf_model = hf_moe
        cfg, model, params = ours
        ids = _prompt()
        with torch.no_grad():
            want = hf_model(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
        got = np.asarray(
            model.apply({"params": params}, jnp.asarray(ids), None), np.float32
        )
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)

    def test_fused_greedy_matches_hf_generate(self, hf_moe, ours):
        _, hf_model = hf_moe
        cfg, model, params = ours
        ids = _prompt()
        n = 12
        with torch.no_grad():
            out = hf_model.generate(
                torch.from_numpy(ids.astype(np.int64)),
                max_new_tokens=n,
                do_sample=False,
                eos_token_id=EOS,
                pad_token_id=0,
            )
        want = [int(t) for t in out[0][ids.shape[1] :]]

        gen = Generator(model, cfg, max_seq=64, max_new_cap=16, cache_dtype=jnp.float32)
        embeds = model.apply({"params": params}, jnp.asarray(ids), method=VLMModel.embed_tokens)
        b, s = ids.shape
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        lengths = jnp.asarray([s], jnp.int32)
        out = gen.generate(
            params, embeds, positions, lengths, jnp.asarray(ids), jax.random.PRNGKey(0),
            max_new_tokens=n,
        )
        n_gen = int(out.n_generated[0])
        got = [int(t) for t in np.asarray(out.tokens[0][:n_gen])]
        assert got == want
