"""End-to-end gRPC serving tests: real server + real client channel,
exercising routing, chunk reassembly, streaming, errors, capabilities and
health — with an echo service standing in for model services (the
reference's dummy-backend test pattern, SURVEY.md §4)."""

import json

import grpc
import pytest
from google.protobuf import empty_pb2

from lumen_tpu.serving import (
    BaseService,
    HubRouter,
    InvalidArgument,
    TaskDefinition,
    TaskRegistry,
)
from lumen_tpu.serving.proto import ml_service_pb2 as pb
from lumen_tpu.serving.proto.ml_service_pb2_grpc import (
    InferenceStub,
    add_InferenceServicer_to_server,
)


class EchoService(BaseService):
    """Test stand-in service: echo, fail, and a streaming counter."""

    def __init__(self, name="echo"):
        registry = TaskRegistry(name)
        registry.register(TaskDefinition(name=f"{name}_echo", handler=self._echo))
        registry.register(TaskDefinition(name=f"{name}_fail", handler=self._fail))
        registry.register(
            TaskDefinition(name=f"{name}_stream", handler=self._stream)
        )
        registry.register(
            TaskDefinition(name=f"{name}_tiny", handler=self._echo, max_payload_bytes=4)
        )
        super().__init__(registry)
        self._healthy = True

    def capability(self):
        return self.registry.build_capability(
            model_ids=["echo-v0"], runtime="jax-cpu", precisions=["bf16"]
        )

    def healthy(self):
        return self._healthy

    def _echo(self, payload, mime, meta):
        return payload, mime or "application/octet-stream", {"echoed": "1", **meta}

    def _fail(self, payload, mime, meta):
        raise InvalidArgument("bad input", detail="test-detail")

    def _stream(self, payload, mime, meta):
        for i in range(int(meta.get("n", "3"))):
            yield (f"chunk{i}".encode(), "text/plain", {"i": str(i)})


@pytest.fixture()
def hub():
    server = grpc.server(
        __import__("concurrent.futures", fromlist=["ThreadPoolExecutor"]).ThreadPoolExecutor(
            max_workers=4
        )
    )
    router = HubRouter({"echo": EchoService("echo"), "echo2": EchoService("echo2")})
    add_InferenceServicer_to_server(router, server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield InferenceStub(channel), router
    channel.close()
    server.stop(0)


def one_request(task, payload=b"hello", meta=None, cid="c1"):
    return pb.InferRequest(
        correlation_id=cid, task=task, payload=payload, meta=meta or {}, payload_mime="text/plain"
    )


@pytest.mark.integration
class TestInferRouting:
    def test_echo_roundtrip(self, hub):
        stub, _ = hub
        resps = list(stub.Infer(iter([one_request("echo_echo")])))
        assert len(resps) == 1
        r = resps[0]
        assert r.is_final and r.result == b"hello"
        assert r.correlation_id == "c1"
        assert "lat_ms" in r.meta and r.meta["echoed"] == "1"

    def test_routing_to_second_service(self, hub):
        stub, _ = hub
        (r,) = stub.Infer(iter([one_request("echo2_echo")]))
        assert r.result == b"hello" and not r.HasField("error")

    def test_unknown_task(self, hub):
        stub, _ = hub
        (r,) = stub.Infer(iter([one_request("nope")]))
        assert r.error.code == pb.ERROR_CODE_INVALID_ARGUMENT
        assert "no service handles" in r.error.message

    def test_handler_service_error(self, hub):
        stub, _ = hub
        (r,) = stub.Infer(iter([one_request("echo_fail")]))
        assert r.error.code == pb.ERROR_CODE_INVALID_ARGUMENT
        assert r.error.detail == "test-detail"

    def test_chunked_reassembly(self, hub):
        stub, _ = hub
        chunks = [
            pb.InferRequest(
                correlation_id="cx",
                task="echo_echo",
                payload=p,
                seq=i,
                total=3,
                payload_mime="text/plain",
            )
            for i, p in enumerate([b"aa", b"bb", b"cc"])
        ]
        (r,) = stub.Infer(iter(chunks))
        assert r.result == b"aabbcc"

    def test_payload_limit(self, hub):
        stub, _ = hub
        (r,) = stub.Infer(iter([one_request("echo_tiny", payload=b"too-long")]))
        assert r.error.code == pb.ERROR_CODE_INVALID_ARGUMENT
        assert "exceeds limit" in r.error.message

    def test_streaming_task(self, hub):
        stub, _ = hub
        resps = list(stub.Infer(iter([one_request("echo_stream", meta={"n": "4"})])))
        assert len(resps) == 4
        assert [r.is_final for r in resps] == [False, False, False, True]
        assert resps[0].result == b"chunk0" and resps[3].result == b"chunk3"
        assert resps[3].total == 4
        assert "lat_ms" in resps[3].meta

    def test_multiple_correlations_one_stream(self, hub):
        stub, _ = hub
        reqs = [one_request("echo_echo", cid="a"), one_request("echo_echo", cid="b", payload=b"x")]
        resps = list(stub.Infer(iter(reqs)))
        assert {r.correlation_id for r in resps} == {"a", "b"}


@pytest.mark.integration
class TestCapabilitiesAndHealth:
    def test_get_capabilities_aggregates(self, hub):
        stub, _ = hub
        cap = stub.GetCapabilities(empty_pb2.Empty())
        assert cap.service_name == "hub"
        names = {t.name for t in cap.tasks}
        assert "echo_echo" in names and "echo2_stream" in names

    def test_stream_capabilities_per_service(self, hub):
        stub, _ = hub
        caps = list(stub.StreamCapabilities(empty_pb2.Empty()))
        assert {c.service_name for c in caps} == {"echo", "echo2"}
        assert all(c.protocol_version == "1.0.0" for c in caps)

    def test_health_ok(self, hub):
        stub, _ = hub
        stub.Health(empty_pb2.Empty())  # no exception

    def test_health_fans_out(self, hub):
        stub, router = hub
        router.services["echo2"]._healthy = False
        with pytest.raises(grpc.RpcError) as ei:
            stub.Health(empty_pb2.Empty())
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE


class TestRegistry:
    def test_duplicate_task_rejected(self):
        reg = TaskRegistry("s")
        t = TaskDefinition(name="x", handler=lambda p, m, me: (p, m, {}))
        reg.register(t)
        with pytest.raises(ValueError):
            reg.register(t)

    def test_duplicate_across_services_rejected(self):
        with pytest.raises(ValueError):
            HubRouter({"a": EchoService("echo"), "b": EchoService("echo")})

    def test_capability_io_tasks(self):
        svc = EchoService("echo")
        cap = svc.capability()
        t = {x.name: x for x in cap.tasks}["echo_tiny"]
        assert t.limits["max_payload_bytes"] == "4"


class TestLoader:
    def test_resolve(self):
        from lumen_tpu.serving.loader import resolve

        assert resolve("lumen_tpu.serving.registry.TaskRegistry") is TaskRegistry

    def test_resolve_errors(self):
        from lumen_tpu.serving.loader import ServiceLoadError, resolve

        with pytest.raises(ServiceLoadError):
            resolve("nonexistent_mod.Thing")
        with pytest.raises(ServiceLoadError):
            resolve("lumen_tpu.serving.registry.Nope")
        with pytest.raises(ServiceLoadError):
            resolve("bare")


class TestMdnsPackets:
    def test_name_codec_roundtrip(self):
        from lumen_tpu.serving.mdns import _decode_name, _encode_name

        raw = _encode_name("_lumen._tcp.local.")
        name, off = _decode_name(raw, 0)
        assert name == "_lumen._tcp.local." and off == len(raw)

    def test_query_matching(self):
        import struct

        from lumen_tpu.serving.mdns import MdnsAdvertiser, _encode_name

        adv = MdnsAdvertiser("lumen-hub", 50051, ip="127.0.0.1")
        q = struct.pack("!HHHHHH", 0, 0, 1, 0, 0, 0) + _encode_name("_lumen._tcp.local.") + struct.pack("!HH", 12, 1)
        assert adv._matches_query(q)
        q2 = struct.pack("!HHHHHH", 0, 0, 1, 0, 0, 0) + _encode_name("_other._tcp.local.") + struct.pack("!HH", 12, 1)
        assert not adv._matches_query(q2)
        # responses must be ignored
        r = struct.pack("!HHHHHH", 0, 0x8400, 1, 0, 0, 0)
        assert not adv._matches_query(r)

    def test_response_packet_parses(self):
        from lumen_tpu.serving.mdns import MdnsAdvertiser

        adv = MdnsAdvertiser("lumen-hub", 50051, ip="192.168.1.10")
        pkt = adv._response_packet()
        import struct

        tid, flags, qd, an, ns, ar = struct.unpack("!HHHHHH", pkt[:12])
        assert flags == 0x8400 and an == 4


class TestChunkedResponses:
    """Response-side seq/total/offset chunking (proto carries the fields
    on InferResponse, reference ``ml_service.proto:60-73``; the reference
    itself never splits results — it relies on the 64 MB cap)."""

    def test_large_result_is_chunked(self, hub):
        from lumen_tpu.serving import reassemble_result

        stub, router = hub
        svc = router.services["echo"]
        old = svc.RESPONSE_CHUNK_BYTES
        svc.RESPONSE_CHUNK_BYTES = 16  # instance override; class default untouched
        try:
            payload = bytes(range(256)) * 2  # 512 B -> 32 chunks
            resps = list(stub.Infer(iter([one_request("echo_echo", payload=payload)])))
        finally:
            svc.RESPONSE_CHUNK_BYTES = old
        assert len(resps) == 32
        for i, r in enumerate(resps):
            assert r.seq == i
            assert r.total == 32
            assert r.offset == i * 16
            assert r.is_final == (i == 31)
            assert r.result_mime  # mime rides every chunk
            assert r.meta["echoed"] == "1"
        data, mime, meta = reassemble_result(resps)
        assert data == payload
        assert meta["echoed"] == "1"

    def test_small_result_single_message(self, hub):
        from lumen_tpu.serving import reassemble_result

        stub, _ = hub
        resps = list(stub.Infer(iter([one_request("echo_echo", payload=b"hi")])))
        assert len(resps) == 1
        assert resps[0].seq == 0 and resps[0].total == 1 and resps[0].is_final
        data, _, _ = reassemble_result(resps)
        assert data == b"hi"

    def test_reassemble_raises_on_wire_error(self, hub):
        from lumen_tpu.serving import ServiceError, reassemble_result

        stub, _ = hub
        resps = list(stub.Infer(iter([one_request("echo_fail")])))
        with pytest.raises(ServiceError):
            reassemble_result(resps)

    def test_reassemble_raises_on_incomplete_stream(self, hub):
        from lumen_tpu.serving import ServiceError, reassemble_result

        stub, router = hub
        svc = router.services["echo"]
        old = svc.RESPONSE_CHUNK_BYTES
        svc.RESPONSE_CHUNK_BYTES = 16
        try:
            payload = bytes(64)
            resps = list(stub.Infer(iter([one_request("echo_echo", payload=payload)])))
        finally:
            svc.RESPONSE_CHUNK_BYTES = old
        assert len(resps) == 4
        with pytest.raises(ServiceError, match="incomplete"):
            reassemble_result(resps[:-1])  # stream cut short before is_final

    @pytest.mark.parametrize("size", [0, 1, 15, 16, 17, 31, 48, 512])
    def test_roundtrip_at_boundary_sizes(self, hub, size):
        """Chunk-boundary sweep: payloads at, below, and above multiples
        of the chunk size all reassemble byte-identically."""
        from lumen_tpu.serving import reassemble_result

        stub, router = hub
        svc = router.services["echo"]
        old = svc.RESPONSE_CHUNK_BYTES
        svc.RESPONSE_CHUNK_BYTES = 16
        try:
            payload = bytes(i % 251 for i in range(size))
            resps = list(stub.Infer(iter([one_request("echo_echo", payload=payload)])))
        finally:
            svc.RESPONSE_CHUNK_BYTES = old
        data, _mime, meta = reassemble_result(resps)
        assert data == payload
        assert resps[-1].is_final
        expect_msgs = max(1, -(-size // 16))
        assert len(resps) == expect_msgs
