"""Multi-host smoke test: two ``jax.distributed`` processes, one logical
deployment (round-1 verdict item 10 — ``parallel/distributed.py`` had no
multi-process test).

Each worker process:

1. joins the process group through ``lumen_tpu.parallel.distributed``
   (coordinator over DCN-equivalent loopback, 4 simulated CPU devices per
   process -> 8 global devices),
2. participates in a global-mesh computation built from process-local
   shards (the cross-host collective path every pjit program rides), and
3. runs a per-host gRPC frontend (hub router + echo service) and drives a
   client round-trip against it — the per-host-frontend serving layout of
   SURVEY.md §7 step 10.

The parent asserts both workers saw the same global topology and the same
all-host reduction result.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, sys

port, pid, out_path, clip_dir, img_path = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4], sys.argv[5]
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["LUMEN_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["LUMEN_NUM_PROCESSES"] = "2"
os.environ["LUMEN_PROCESS_ID"] = str(pid)
sys.path.insert(0, %(root)r)

# Site hooks may import jax at interpreter start (latching a TPU platform
# before this script's env is read); re-point the config like
# tests/conftest.py does.
import jax

jax.config.update("jax_platforms", "cpu")

from lumen_tpu.parallel import distributed

multi = distributed.initialize()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

devices = np.asarray(jax.devices())
mesh = Mesh(devices, ("data",))

# Global batch assembled from process-local shards: each host contributes
# rows [4*local_start, ...) so the reduction checks cross-host data really
# met on the mesh.
local = (
    np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
    + 1000.0 * jax.process_index()
)
sharding = NamedSharding(mesh, P("data"))
garr = jax.make_array_from_process_local_data(sharding, local, (8, 3))

total = float(jax.jit(lambda x: jnp.sum(x * 2.0))(garr))

# Per-host gRPC frontend: every process serves, every process's client
# round-trips through its own frontend.
import grpc
from concurrent import futures
from lumen_tpu.serving.echo import EchoService
from lumen_tpu.serving.router import HubRouter
from lumen_tpu.serving.proto import ml_service_pb2_grpc
from lumen_tpu.serving.proto.ml_service_pb2 import InferRequest

server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
ml_service_pb2_grpc.add_InferenceServicer_to_server(
    HubRouter({"echo": EchoService()}), server
)
grpc_port = server.add_insecure_port("127.0.0.1:0")
server.start()
stub = ml_service_pb2_grpc.InferenceStub(grpc.insecure_channel(f"127.0.0.1:{grpc_port}"))
payload = f"host-{jax.process_index()}".encode()
resps = list(stub.Infer(iter([InferRequest(correlation_id="c", task="echo", payload=payload, seq=0, total=1)])))
echo_ok = resps[-1].result == payload
server.stop(0)

# Per-host CLIP frontend: a REAL model service behind the hub router on
# every host (SURVEY §7 step 10's per-host serving layout). Same weights
# on both hosts, same image -> the parent asserts the two frontends
# return the SAME embedding (cross-host serving consistency).
from lumen_tpu.models.clip.manager import CLIPManager
from lumen_tpu.serving.services.clip_service import ClipService

mgr = CLIPManager(clip_dir, dtype="float32", batch_size=2)
mgr.initialize()
clip_server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
ml_service_pb2_grpc.add_InferenceServicer_to_server(
    HubRouter({"clip": ClipService({"clip": mgr})}), clip_server
)
clip_port = clip_server.add_insecure_port("127.0.0.1:0")
clip_server.start()
clip_stub = ml_service_pb2_grpc.InferenceStub(
    grpc.insecure_channel(f"127.0.0.1:{clip_port}")
)
img = open(img_path, "rb").read()
(clip_resp,) = clip_stub.Infer(iter([InferRequest(
    correlation_id="e", task="clip_image_embed", payload=img,
    payload_mime="image/png", seq=0, total=1,
)]))
if clip_resp.HasField("error"):
    embedding = None
    embed_error = f"{clip_resp.error.code}: {clip_resp.error.message} / {clip_resp.error.detail}"
else:
    embedding = json.loads(clip_resp.result)
    embed_error = None
clip_server.stop(0)
mgr.close()

# All hosts reach the end before teardown (DCN barrier).
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("smoke-done")

json.dump(
    {
        "multi": bool(multi),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "total": total,
        "primary": distributed.is_primary(),
        "echo_ok": bool(echo_ok),
        "embedding": embedding,
        "embed_error": embed_error,
    },
    open(out_path, "w"),
)
""" % {"root": _ROOT}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_group_serves_and_reduces(tmp_path):
    from tests.clip_fixtures import make_clip_model_dir, png_bytes

    clip_dir = make_clip_model_dir(tmp_path)
    img_path = str(tmp_path / "img.png")
    with open(img_path, "wb") as f:
        f.write(png_bytes(seed=3))
    port = _free_port()
    outs = [str(tmp_path / f"out{i}.json") for i in range(2)]
    procs = []
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    for pid in range(2):
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER, str(port), str(pid), outs[pid],
                 clip_dir, img_path],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = []
    for pid, p in enumerate(procs):
        try:
            stdout, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"worker {pid} timed out")
        assert p.returncode == 0, f"worker {pid} failed:\n{stderr[-3000:]}"
        with open(outs[pid]) as f:
            results.append(json.load(f))

    for pid, r in enumerate(results):
        assert r["multi"] is True
        assert r["process_index"] == pid
        assert r["process_count"] == 2
        assert r["global_devices"] == 8
        assert r["local_devices"] == 4
        assert r["echo_ok"] is True
    assert results[0]["primary"] is True
    assert results[1]["primary"] is False
    # Both hosts computed the same global reduction over each other's rows:
    # sum(2x) over host0 rows (0..11) + host1 rows (+1000 each)
    base = sum(range(12)) * 2
    want = float(base + base + 2 * 1000.0 * 12)
    assert results[0]["total"] == results[1]["total"] == want
    # Both per-host CLIP frontends served the embed, and identically:
    # same weights + same image must give the same vector on every host.
    e0, e1 = results[0]["embedding"], results[1]["embedding"]
    assert e0 is not None and e1 is not None, (
        results[0]["embed_error"], results[1]["embed_error"]
    )
    assert e0["dim"] == 32 and len(e0["vector"]) == 32
    assert e0["vector"] == e1["vector"]
