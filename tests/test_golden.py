"""Golden record-and-replay fixtures (SURVEY.md §4; round-1 verdict item 9).

Replays the committed ``tests/golden/*.npz`` pairs through today's code;
a behavioral change in any of these math layers fails loudly instead of
shipping silently. Regenerate deliberately with
``python scripts/record_golden.py`` and review the diff.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from tests.golden_params import (
    CLIP_TOP_K,
    CTC_VOCAB,
    DB_POSTPROCESS,
    FACE_MAX_DETECTIONS,
    FACE_NMS_THRESHOLD,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def load(name):
    path = os.path.join(GOLDEN, name)
    if not os.path.exists(path):
        pytest.fail(f"missing fixture {name}; run scripts/record_golden.py")
    return np.load(path, allow_pickle=False)


class TestFaceDecodeGolden:
    def test_decode_and_nms_replay(self):
        import jax

        from lumen_tpu.models.face.modeling import decode_detections
        from lumen_tpu.ops.nms import nms_jax

        fx = load("face_decode.npz")
        outputs = {
            s: {
                "scores": fx[f"scores_{s}"],
                "bbox": fx[f"bbox_{s}"],
                "kps": fx[f"kps_{s}"],
            }
            for s in (8, 16, 32)
        }
        boxes, kps, scores = decode_detections(
            outputs,
            int(fx["input_size"]),
            int(fx["num_anchors"]),
            max_detections=FACE_MAX_DETECTIONS,
            scores_are_logits=False,
        )
        keep = jax.vmap(lambda b, s: nms_jax(b, s, FACE_NMS_THRESHOLD))(boxes, scores)
        np.testing.assert_allclose(np.asarray(boxes), fx["boxes"], atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(kps), fx["kps"], atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(scores), fx["scores"], atol=1e-5, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(keep), fx["keep"])


class TestOcrPostprocessGolden:
    def test_db_boxes_replay(self):
        from lumen_tpu.models.ocr.postprocess import boxes_from_prob_map

        fx = load("ocr_postprocess.npz")
        found = boxes_from_prob_map(fx["prob"], **DB_POSTPROCESS)
        quads = np.stack([q for q, _ in found]).astype(np.float32)
        scores = np.asarray([s for _, s in found], np.float32)
        assert quads.shape == fx["quads"].shape
        np.testing.assert_allclose(quads, fx["quads"], atol=1e-3)
        np.testing.assert_allclose(scores, fx["quad_scores"], atol=1e-5)

    def test_ctc_collapse_replay(self):
        from lumen_tpu.ops.ctc import ctc_collapse_rows

        fx = load("ocr_postprocess.npz")
        collapsed = ctc_collapse_rows(fx["ctc_ids"], fx["ctc_confs"], CTC_VOCAB)
        assert [t for t, _ in collapsed] == list(fx["ctc_texts"])
        np.testing.assert_allclose(
            [c for _, c in collapsed], fx["ctc_text_confs"], atol=1e-6
        )


class TestClipClassifyGolden:
    def test_scoring_replay(self):
        """Cosine + temperature softmax + top-k through the PRODUCTION
        scoring path (``CLIPManager._classify_vector``), pinned to the
        recorded reference-semantics numbers."""
        import types

        import jax.numpy as jnp

        from lumen_tpu.models.clip.manager import CLIPManager

        fx = load("clip_classify.npz")
        names = [f"label{i}" for i in range(fx["matrix"].shape[0])]
        mgr = types.SimpleNamespace(classify_mode="softmax")
        res = CLIPManager._classify_vector(
            mgr,
            fx["vec"],
            names,
            jnp.asarray(fx["matrix"]),
            top_k=CLIP_TOP_K,
            temperature=float(fx["temperature"]),
        )
        got_idx = [names.index(label) for label, _ in res.labels]
        np.testing.assert_array_equal(got_idx, fx["top_idx"])
        np.testing.assert_allclose(
            [s for _, s in res.labels], fx["top_probs"], atol=1e-5
        )


class TestVlmSpliceGolden:
    def test_merge_replay(self):
        import jax.numpy as jnp

        from lumen_tpu.models.vlm.modeling import merge_image_embeddings

        fx = load("vlm_splice.npz")
        merged, positions, out_len = merge_image_embeddings(
            jnp.asarray(fx["text"]),
            jnp.asarray(fx["vis"]),
            jnp.asarray(fx["ids"]),
            int(fx["image_token"]),
            jnp.asarray(fx["lengths"]),
        )
        np.testing.assert_allclose(np.asarray(merged), fx["merged"], atol=1e-6)
        np.testing.assert_array_equal(np.asarray(positions), fx["positions"])
        np.testing.assert_array_equal(np.asarray(out_len), fx["out_lengths"])
