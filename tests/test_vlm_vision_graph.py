"""FastVLM-style serving: a torch-exported ``vision.onnx`` tower (hybrid
conv/SE/attention, FastViT-flavored) runs through the ONNX bridge while the
decoder runs as native Flax — the split that serves real FastVLM repos
(reference three-session layout, ``packages/lumen-vlm/src/lumen_vlm/
backends/onnxrt_backend.py:107-140``; round-1 gap: FastViTHD towers had no
conversion path)."""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from lumen_tpu.models.vlm import ChatMessage, VLMManager  # noqa: E402
from tests.test_onnx_bridge import export_onnx  # noqa: E402
from tests.test_vlm import make_vlm_model_dir, png_bytes  # noqa: E402

HIDDEN = 32  # TinyVLM decoder hidden size
IMG = 32  # TinyVLM vision image size


class FastVitStyleTower(nn.Module):
    """Conv stem + SE + self-attention mixer + projector: the hybrid op mix
    of FastViTHD, shrunk. [B,3,32,32] -> [B,16,32] splice-ready tokens."""

    def __init__(self):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2d(3, 16, 3, 2, 1),
            nn.BatchNorm2d(16),
            nn.GELU(),
            nn.Conv2d(16, 16, 3, 1, 1, groups=16),  # depthwise
            nn.Conv2d(16, 24, 1),
            nn.GELU(),
            nn.AvgPool2d(2),
        )
        self.se_fc1 = nn.Conv2d(24, 8, 1)
        self.se_fc2 = nn.Conv2d(24 // 3, 24, 1) if False else nn.Conv2d(8, 24, 1)
        self.pool = nn.AvgPool2d(2)  # -> 4x4 = 16 tokens
        self.qkv = nn.Linear(24, 3 * 24)
        self.proj = nn.Linear(24, HIDDEN)

    def forward(self, x):
        f = self.stem(x)  # [B,24,8,8]
        s = torch.sigmoid(self.se_fc2(torch.relu(self.se_fc1(f.mean((2, 3), keepdim=True)))))
        f = self.pool(f * s)  # [B,24,4,4]
        b = f.shape[0]
        t = f.flatten(2).transpose(1, 2)  # [B,16,24]
        qkv = self.qkv(t).reshape(b, 16, 3, 4, 6).permute(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        att = torch.softmax(q @ k.transpose(-2, -1) / 6**0.5, dim=-1)
        t = t + (att @ v).transpose(1, 2).reshape(b, 16, 24)
        return self.proj(t)


def make_fastvlm_style_dir(tmp_path, backend="graph"):
    import json

    model_dir = make_vlm_model_dir(tmp_path)  # expects a pathlib.Path
    torch.manual_seed(3)
    tower = FastVitStyleTower()
    export_onnx(
        tower,
        (torch.randn(1, 3, IMG, IMG),),
        model_dir + "/vision.onnx",
        input_names=["pixel_values"],
        dynamic_axes={"pixel_values": {0: "b"}},
    )
    torch.save(tower.state_dict(), model_dir + "/vision_state.pt")
    # The TinyVLM fixture ships a complete native vision tower too; a real
    # FastVLM repo would not, so its manifest pins the graph backend.
    info_path = model_dir + "/model_info.json"
    info = json.loads(open(info_path).read())
    if backend is not None:
        info["extra_metadata"] = {**info.get("extra_metadata", {}), "vision_backend": backend}
        open(info_path, "w").write(json.dumps(info))
    return model_dir


@pytest.fixture(scope="module")
def graph_vlm(tmp_path_factory):
    model_dir = make_fastvlm_style_dir(tmp_path_factory.mktemp("gvlm"))
    mgr = VLMManager(
        model_dir, dtype="float32", max_seq=128, max_new_cap=16, prefill_buckets=(16, 32)
    )
    mgr.initialize()
    yield mgr
    mgr.close()


class TestVisionGraphServing:
    def test_probe_found_graph_tokens(self, graph_vlm):
        # graph emits 16 tokens, not the Flax tower's (32/16)^2 = 4
        assert graph_vlm.vision_tokens == 16

    def test_generate_with_image(self, graph_vlm):
        out = graph_vlm.generate(
            [ChatMessage(role="user", content="describe <image>")],
            image_bytes=png_bytes(IMG),
            max_new_tokens=4,
        )
        assert len(out.tokens) == 4
        assert out.finish_reason in ("length", "eos_token")

    def test_image_changes_generation(self, graph_vlm):
        """The graph tower's output actually conditions the decode."""
        text_only = graph_vlm.generate(
            [ChatMessage(role="user", content="describe")], max_new_tokens=6
        )
        with_img = graph_vlm.generate(
            [ChatMessage(role="user", content="describe")],
            image_bytes=png_bytes(IMG, seed=1),
            max_new_tokens=6,
        )
        assert text_only.tokens != with_img.tokens

    def test_vision_embeddings_match_torch(self, graph_vlm):
        """Spliced image-position embeddings == torch tower forward."""
        import cv2

        rng = np.random.RandomState(5)
        img = rng.randint(0, 256, (IMG, IMG, 3)).astype(np.uint8)
        ok, enc = cv2.imencode(".png", img[..., ::-1])
        assert ok

        msgs = [ChatMessage(role="user", content="hi <image>")]
        ids = graph_vlm._encode_prompt(msgs, has_image=True)
        pos = ids.index(graph_vlm.cfg.image_token_id)
        embeds, _, _, _, _ = graph_vlm._prepare_inputs(msgs, enc.tobytes())
        got = np.asarray(embeds[0, pos : pos + 16], np.float32)

        tower = FastVitStyleTower()
        tower.load_state_dict(torch.load(graph_vlm.model_dir + "/vision_state.pt"))
        tower.eval()
        mean = np.asarray(graph_vlm.cfg.vision.mean, np.float32)
        std = np.asarray(graph_vlm.cfg.vision.std, np.float32)
        x = (img.astype(np.float32) / 255.0 - mean) / std
        with torch.no_grad():
            want = tower(torch.from_numpy(x.transpose(2, 0, 1)[None])).numpy()[0]
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)

    def test_auto_prefers_complete_native_vision(self, tmp_path):
        """An auxiliary vision.onnx must not hijack a model dir whose
        checkpoint ships a complete converted vision tower (review
        finding: no-fallback startup failures)."""
        model_dir = make_fastvlm_style_dir(tmp_path, backend=None)  # auto
        mgr = VLMManager(
            model_dir, dtype="float32", max_seq=128, max_new_cap=16, prefill_buckets=(16, 32)
        )
        mgr.initialize()
        try:
            # native Flax tower: (32/16)^2 = 4 tokens, not the graph's 16
            assert mgr.vision_tokens == 4
        finally:
            mgr.close()

    def test_bad_width_rejected(self, tmp_path):
        """A vision export missing the projector (wrong width) fails loudly
        at initialize, not with silent garbage at serve time."""

        import json

        class NoProjector(nn.Module):
            def forward(self, x):
                b = x.shape[0]
                return x.flatten(2).transpose(1, 2)[:, :4, :24]

        model_dir = make_vlm_model_dir(tmp_path)
        export_onnx(
            NoProjector(),
            (torch.randn(1, 3, IMG, IMG),),
            model_dir + "/vision.onnx",
        )
        info_path = model_dir + "/model_info.json"
        info = json.loads(open(info_path).read())
        info["extra_metadata"] = {"vision_backend": "graph"}
        open(info_path, "w").write(json.dumps(info))
        mgr = VLMManager(model_dir, dtype="float32", max_seq=128, max_new_cap=16, prefill_buckets=(16,))
        with pytest.raises(ValueError, match="projector|width"):
            mgr.initialize()
