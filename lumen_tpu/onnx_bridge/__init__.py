"""ONNX -> JAX bridge: load the reference's ONNX model zoo (InsightFace
SCRFD/ArcFace packs, PP-OCR det/rec) as jittable XLA programs with a real
params pytree — no onnxruntime, no foreign runtime in the serving path."""

from .discovery import find_onnx_exports
from .executor import OnnxModule
from .proto import OnnxGraph, load_onnx, parse_onnx

__all__ = ["OnnxModule", "OnnxGraph", "load_onnx", "parse_onnx", "find_onnx_exports"]
