"""Minimal ONNX protobuf reader (hand-rolled wire-format decoder).

The reference ships its face/OCR model zoo as ONNX graphs and runs them
through onnxruntime (e.g. ``packages/lumen-face/src/lumen_face/backends/
onnxrt_backend.py:485-745``). This image has neither ``onnx`` nor
``onnxruntime``, and depending on them would defeat the point anyway — we
want the weights *inside* XLA, not behind a foreign runtime. So this module
decodes the small subset of the ONNX protobuf schema the bridge needs
(graph topology, node attributes, initializer tensors) straight from the
wire format: ~200 lines instead of a protobuf toolchain.

Field numbers follow the public ``onnx.proto3`` schema. Only fields the
executor consumes are decoded; unknown fields are skipped by wire type.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

# -- wire-format primitives --------------------------------------------------


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _skip_field(buf: memoryview, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire_type == 1:
        return pos + 8
    if wire_type == 2:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire_type == 5:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire_type}")


def _iter_fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over a message buffer.
    Length-delimited values come back as memoryview; varints as int;
    fixed32/64 as raw bytes."""
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            val = bytes(buf[pos : pos + 8])
            pos += 8
        elif wt == 2:
            n, pos = _read_varint(buf, pos)
            val = buf[pos : pos + n]
            pos += n
        elif wt == 5:
            val = bytes(buf[pos : pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, wt, val


def _packed_ints(val, wt) -> list[int]:
    """Repeated int field: packed (length-delimited) or a single varint."""
    if wt == 0:
        return [val]
    out = []
    pos = 0
    while pos < len(val):
        v, pos = _read_varint(val, pos)
        out.append(v)
    return out


def _zigzag_signed(v: int, bits: int = 64) -> int:
    """Interpret a varint as two's-complement signed (ONNX ints are int64
    encoded as plain varints, negatives use 10 bytes)."""
    if v >= 1 << (bits - 1):
        v -= 1 << bits
    return v


# -- decoded message types ---------------------------------------------------

# TensorProto.DataType -> numpy dtype
TENSOR_DTYPES = {
    1: np.float32,
    2: np.uint8,
    3: np.int8,
    4: np.uint16,
    5: np.int16,
    6: np.int32,
    7: np.int64,
    9: np.bool_,
    10: np.float16,
    11: np.float64,
    12: np.uint32,
    13: np.uint64,
}
BFLOAT16_DTYPE = 16  # handled specially (numpy has no bfloat16)


@dataclass
class Attribute:
    name: str
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: "TensorValue | None" = None
    floats: list[float] = field(default_factory=list)
    ints: list[int] = field(default_factory=list)
    strings: list[bytes] = field(default_factory=list)

    @property
    def value(self):
        # AttributeProto.AttributeType: FLOAT=1 INT=2 STRING=3 TENSOR=4
        # FLOATS=6 INTS=7 STRINGS=8
        return {
            1: self.f,
            2: self.i,
            3: self.s.decode(errors="replace"),
            4: self.t,
            6: self.floats,
            7: self.ints,
            8: [s.decode(errors="replace") for s in self.strings],
        }.get(self.type)


@dataclass
class TensorValue:
    name: str
    array: np.ndarray


@dataclass
class Node:
    op_type: str
    name: str
    inputs: list[str]
    outputs: list[str]
    attrs: dict[str, Attribute]

    def attr(self, name: str, default=None):
        a = self.attrs.get(name)
        return a.value if a is not None else default


@dataclass
class ValueInfo:
    name: str
    dtype: int | None = None  # TensorProto.DataType
    shape: list[int | str | None] = field(default_factory=list)


@dataclass
class OnnxGraph:
    name: str
    nodes: list[Node]
    initializers: dict[str, np.ndarray]
    inputs: list[ValueInfo]  # graph inputs EXCLUDING initializers
    outputs: list[ValueInfo]
    opset: int


# -- message decoders --------------------------------------------------------


def _decode_tensor(buf: memoryview) -> TensorValue:
    dims: list[int] = []
    data_type = 1
    raw: bytes | None = None
    float_data: list[float] = []
    int32_data: list[int] = []
    int64_data: list[int] = []
    double_data: list[float] = []
    uint64_data: list[int] = []
    name = ""
    for fnum, wt, val in _iter_fields(buf):
        if fnum == 1:
            dims.extend(_zigzag_signed(v) for v in _packed_ints(val, wt))
        elif fnum == 2:
            data_type = val
        elif fnum == 4:  # packed floats
            float_data.extend(struct.unpack(f"<{len(val) // 4}f", bytes(val)))
        elif fnum == 5:
            int32_data.extend(_zigzag_signed(v, 32) for v in _packed_ints(val, wt))
        elif fnum == 7:
            int64_data.extend(_zigzag_signed(v) for v in _packed_ints(val, wt))
        elif fnum == 8:
            name = bytes(val).decode()
        elif fnum == 9:
            raw = bytes(val)
        elif fnum == 10:
            double_data.extend(struct.unpack(f"<{len(val) // 8}d", bytes(val)))
        elif fnum == 11:
            uint64_data.extend(_packed_ints(val, wt))
        elif fnum == 13:
            raise ValueError(f"tensor {name!r} uses external data (unsupported)")
    shape = tuple(dims)
    if raw is not None:
        if data_type == BFLOAT16_DTYPE:
            # decode bfloat16 -> float32 via bit-shift
            u16 = np.frombuffer(raw, dtype=np.uint16)
            arr = (u16.astype(np.uint32) << 16).view(np.float32).reshape(shape)
        else:
            np_dtype = TENSOR_DTYPES.get(data_type)
            if np_dtype is None:
                raise ValueError(f"tensor {name!r}: unsupported data_type {data_type}")
            arr = np.frombuffer(raw, dtype=np_dtype).reshape(shape)
    elif float_data:
        arr = np.asarray(float_data, np.float32).reshape(shape)
    elif int64_data:
        arr = np.asarray(int64_data, np.int64).reshape(shape)
    elif int32_data:
        np_dtype = TENSOR_DTYPES.get(data_type, np.int32)
        arr = np.asarray(int32_data).astype(np_dtype).reshape(shape)
    elif double_data:
        arr = np.asarray(double_data, np.float64).reshape(shape)
    elif uint64_data:
        arr = np.asarray(uint64_data, np.uint64).reshape(shape)
    else:
        np_dtype = TENSOR_DTYPES.get(data_type, np.float32)
        arr = np.zeros(shape, np_dtype)
    return TensorValue(name=name, array=arr)


def _decode_attribute(buf: memoryview) -> Attribute:
    a = Attribute(name="")
    for fnum, wt, val in _iter_fields(buf):
        if fnum == 1:
            a.name = bytes(val).decode()
        elif fnum == 2:
            a.f = struct.unpack("<f", val)[0]
        elif fnum == 3:
            a.i = _zigzag_signed(val)
        elif fnum == 4:
            a.s = bytes(val)
        elif fnum == 5:
            a.t = _decode_tensor(val)
        elif fnum == 7:
            if wt == 2:
                a.floats.extend(struct.unpack(f"<{len(val) // 4}f", bytes(val)))
            else:
                a.floats.append(struct.unpack("<f", val)[0])
        elif fnum == 8:
            a.ints.extend(_zigzag_signed(v) for v in _packed_ints(val, wt))
        elif fnum == 9:
            a.strings.append(bytes(val))
        elif fnum == 20:
            a.type = val
    if a.type == 0:
        # Exporters may omit type; infer from populated field.
        if a.floats:
            a.type = 6
        elif a.ints:
            a.type = 7
        elif a.strings:
            a.type = 8
        elif a.t is not None:
            a.type = 4
        elif a.s:
            a.type = 3
        elif a.f:
            a.type = 1
        else:
            a.type = 2
    return a


def _decode_node(buf: memoryview) -> Node:
    inputs: list[str] = []
    outputs: list[str] = []
    name = ""
    op_type = ""
    attrs: dict[str, Attribute] = {}
    for fnum, _wt, val in _iter_fields(buf):
        if fnum == 1:
            inputs.append(bytes(val).decode())
        elif fnum == 2:
            outputs.append(bytes(val).decode())
        elif fnum == 3:
            name = bytes(val).decode()
        elif fnum == 4:
            op_type = bytes(val).decode()
        elif fnum == 5:
            a = _decode_attribute(val)
            attrs[a.name] = a
    return Node(op_type=op_type, name=name, inputs=inputs, outputs=outputs, attrs=attrs)


def _decode_value_info(buf: memoryview) -> ValueInfo:
    vi = ValueInfo(name="")
    for fnum, _wt, val in _iter_fields(buf):
        if fnum == 1:
            vi.name = bytes(val).decode()
        elif fnum == 2:  # TypeProto
            for f2, _w2, v2 in _iter_fields(val):
                if f2 == 1:  # tensor_type
                    for f3, _w3, v3 in _iter_fields(v2):
                        if f3 == 1:
                            vi.dtype = v3
                        elif f3 == 2:  # TensorShapeProto
                            for f4, _w4, v4 in _iter_fields(v3):
                                if f4 == 1:  # Dimension
                                    dim_val: int | str | None = None
                                    for f5, _w5, v5 in _iter_fields(v4):
                                        if f5 == 1:
                                            dim_val = _zigzag_signed(v5)
                                        elif f5 == 2:
                                            dim_val = bytes(v5).decode()
                                    vi.shape.append(dim_val)
    return vi


def _decode_graph(buf: memoryview, opset: int) -> OnnxGraph:
    nodes: list[Node] = []
    initializers: dict[str, np.ndarray] = {}
    inputs: list[ValueInfo] = []
    outputs: list[ValueInfo] = []
    name = ""
    for fnum, _wt, val in _iter_fields(buf):
        if fnum == 1:
            nodes.append(_decode_node(val))
        elif fnum == 2:
            name = bytes(val).decode()
        elif fnum == 5:
            t = _decode_tensor(val)
            initializers[t.name] = t.array
        elif fnum == 11:
            inputs.append(_decode_value_info(val))
        elif fnum == 12:
            outputs.append(_decode_value_info(val))
    inputs = [vi for vi in inputs if vi.name not in initializers]
    return OnnxGraph(
        name=name, nodes=nodes, initializers=initializers, inputs=inputs, outputs=outputs, opset=opset
    )


def parse_onnx(data: bytes) -> OnnxGraph:
    """Decode a serialized ``ModelProto`` into an :class:`OnnxGraph`."""
    buf = memoryview(data)
    graph_buf: memoryview | None = None
    opset = 13
    for fnum, _wt, val in _iter_fields(buf):
        if fnum == 7:
            graph_buf = val
        elif fnum == 8:  # OperatorSetIdProto
            for f2, _w2, v2 in _iter_fields(val):
                if f2 == 1 and bytes(v2):  # non-default domain
                    break
                if f2 == 2:
                    opset = v2
    if graph_buf is None:
        raise ValueError("no graph in ONNX model")
    return _decode_graph(graph_buf, opset)


def load_onnx(path: str) -> OnnxGraph:
    with open(path, "rb") as f:
        return parse_onnx(f.read())
