"""ONNX graph executor: parse once -> a jittable ``params, inputs -> outputs``
function XLA compiles for TPU.

This replaces the reference's onnxruntime sessions (e.g. the SCRFD/ArcFace
sessions of ``packages/lumen-face/src/lumen_face/backends/onnxrt_backend.py:
485-745`` and the PP-OCR sessions of ``packages/lumen-ocr/src/lumen_ocr/
backends/onnxrt_backend.py:43-633``) with a graph *bridge*: node ops lower
to jax/lax, float weights become a params pytree (castable to bf16,
replicable over a mesh, shardable like any other model state), and the
whole forward is one XLA program — no foreign runtime in the serving path.

Static-vs-traced value split: integer/shape tensors stay numpy so Reshape/
Slice targets are compile-time constants; dense arrays are jax values. See
``ops.py``.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from .ops import OP_REGISTRY
from .proto import OnnxGraph, load_onnx, parse_onnx

logger = logging.getLogger(__name__)


class _Ctx:
    def __init__(self, opset: int):
        self.opset = opset


class OnnxModule:
    """A loaded ONNX graph, executable under ``jax.jit``.

    ``params``: float initializers (the model weights) as a flat
    ``{name: np.ndarray}`` pytree — pass (optionally dtype-cast / device-
    placed / sharded) to :meth:`__call__`. Integer/bool initializers are
    compile-time constants and live inside the module.
    """

    def __init__(self, graph: OnnxGraph):
        self.graph = graph
        self.opset = graph.opset
        self.params: dict[str, np.ndarray] = {}
        self.constants: dict[str, np.ndarray] = {}
        for name, arr in graph.initializers.items():
            if np.issubdtype(arr.dtype, np.floating) and arr.ndim > 0:
                self.params[name] = np.asarray(arr, np.float32)
            else:
                self.constants[name] = arr
        self.input_names = [vi.name for vi in graph.inputs]
        self.output_names = [vi.name for vi in graph.outputs]
        self._validate_ops()

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_path(cls, path: str) -> "OnnxModule":
        return cls(load_onnx(path))

    @classmethod
    def from_bytes(cls, data: bytes) -> "OnnxModule":
        return cls(parse_onnx(data))

    # -- introspection -----------------------------------------------------

    def _validate_ops(self) -> None:
        missing = sorted(
            {n.op_type for n in self.graph.nodes if n.op_type not in OP_REGISTRY}
        )
        if missing:
            raise NotImplementedError(
                f"ONNX graph {self.graph.name!r} uses unsupported ops: {missing} "
                f"(supported: {len(OP_REGISTRY)} op types)"
            )

    def input_shapes(self) -> dict[str, tuple]:
        """Declared input shapes; dynamic dims come back as None/str."""
        return {vi.name: tuple(vi.shape) for vi in self.graph.inputs}

    def param_bytes(self) -> int:
        return sum(a.nbytes for a in self.params.values())

    def release_weights(self) -> None:
        """Drop the host-RAM weight arrays once a device/mesh copy exists.
        Clearing ``params`` alone frees nothing: for fp32 exports the
        entries are no-copy aliases of ``graph.initializers``, which the
        jitted closures keep alive through the module — both references
        must go."""
        for name in list(self.params):
            self.graph.initializers.pop(name, None)
        self.params.clear()

    # -- execution ---------------------------------------------------------

    def __call__(self, params: dict, inputs: dict):
        """Execute the graph. ``inputs``: {input_name: array} (a single
        positional array is accepted for single-input graphs). Returns a
        list of output arrays (jax or numpy depending on reachability)."""
        env: dict[str, object] = {}
        env.update(self.constants)
        env.update(params)
        env.update(inputs)
        ctx = _Ctx(self.opset)
        for node in self.graph.nodes:
            vals = [env[i] if i else None for i in node.inputs]
            fn = OP_REGISTRY[node.op_type]
            try:
                outs = fn(node, vals, ctx)
            except NotImplementedError:
                raise
            except Exception as e:
                raise RuntimeError(
                    f"ONNX node {node.name!r} ({node.op_type}) failed: {e}"
                ) from e
            for name, val in zip(node.outputs, outs):
                if name:
                    env[name] = val
        return [env[name] for name in self.output_names]

    def bind(self, dtype=None):
        """Convenience: returns ``(fn, params)`` where ``fn(params, *arrays)``
        maps positional inputs to a tuple of outputs — the natural shape to
        hand to ``jax.jit`` / ``shard_map``. ``dtype`` casts params (e.g.
        ``jnp.bfloat16`` for MXU-friendly serving)."""
        params = self.params
        if dtype is not None:
            params = {k: jnp.asarray(v, dtype) for k, v in params.items()}

        names = self.input_names

        def fn(p, *arrays):
            if len(arrays) != len(names):
                raise ValueError(f"expected inputs {names}, got {len(arrays)} arrays")
            if dtype is not None:
                # keep float inputs in the params dtype so mixed-precision
                # serving doesn't trip dtype-strict primitives (conv)
                arrays = tuple(
                    jnp.asarray(a, dtype)
                    if np.issubdtype(np.asarray(a).dtype if not isinstance(a, jax.Array) else a.dtype, np.floating)
                    else a
                    for a in arrays
                )
            outs = self(p, dict(zip(names, arrays)))
            return tuple(jnp.asarray(o) for o in outs)

        return fn, params
