"""Shared export-file discovery: locate ``<prefix>*.onnx`` components in a
model dir with the reference's precision-preference chain
(``{component}.{precision}.onnx`` -> fp32 -> fp16,
``packages/lumen-clip/src/lumen_clip/backends/onnxrt_backend.py:245-289``).
One implementation for every family's graph loader (clip/ocr/face/vlm
previously each carried a near-verbatim copy)."""

from __future__ import annotations

import os

PRECISION_ORDER = ["fp32", "fp16"]


def find_onnx_exports(
    model_dir: str,
    kinds: dict[str, str],
    precision: str | None = None,
) -> dict[str, str]:
    """``kinds``: {result_key: filename_prefix}. Scans the dir and its
    ``onnx/`` runtime subdir (reference layout, ``resources/loader.py:164``);
    within a component, prefers the requested precision, then fp32, then
    fp16, then bare ``<prefix>.onnx``."""
    names = sorted(os.listdir(model_dir)) if os.path.isdir(model_dir) else []
    sub = os.path.join(model_dir, "onnx")
    if os.path.isdir(sub):
        names += [os.path.join("onnx", n) for n in sorted(os.listdir(sub))]

    order = [precision] if precision else []
    order += [p for p in PRECISION_ORDER if p not in order]
    found: dict[str, str] = {}
    for kind, prefix in kinds.items():
        candidates = [
            n for n in names
            if n.endswith(".onnx") and os.path.basename(n).startswith(prefix)
        ]
        if not candidates:
            continue

        def rank(name: str) -> tuple:
            base = os.path.basename(name)
            for i, prec in enumerate(order):
                if f".{prec}." in base:
                    return (i, base)
            return (len(order), base)

        found[kind] = os.path.join(model_dir, sorted(candidates, key=rank)[0])
    return found
