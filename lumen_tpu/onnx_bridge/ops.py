"""ONNX op set -> JAX lowering rules for the bridge executor.

Coverage target: the CNN/transformer op mix of the reference's model zoo —
InsightFace SCRFD detectors + ArcFace embedders and PP-OCR det/rec graphs
(consumed by onnxruntime in the reference, ``packages/lumen-face/.../
onnxrt_backend.py``, ``packages/lumen-ocr/.../onnxrt_backend.py``) — plus
everything torch.onnx emits for the golden-test models.

Execution model: values flowing through the graph are either *static*
(numpy arrays — shapes, axes, constants folded at trace time) or *traced*
(jax arrays). Shape-carrying subgraphs (Shape -> Gather -> Concat ->
Reshape ...) must stay static for XLA, so element-wise/indexing ops run in
numpy whenever every input is static. Dense compute always lowers to jax.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .proto import Node

OP_REGISTRY: dict = {}


def register(name: str):
    def deco(fn):
        OP_REGISTRY[name] = fn
        return fn

    return deco


def _static(*vals) -> bool:
    return all(not isinstance(v, jax.Array) for v in vals if v is not None)


def _xp(*vals):
    """numpy for all-static inputs, jnp otherwise."""
    return np if _static(*vals) else jnp


def _int_list(v) -> list[int]:
    return [int(x) for x in np.asarray(v).reshape(-1)]


# -- elementwise -------------------------------------------------------------

_UNARY = {
    "Relu": lambda xp, x: xp.maximum(x, 0),
    "Sigmoid": lambda xp, x: 1.0 / (1.0 + xp.exp(-x)),
    "Tanh": lambda xp, x: xp.tanh(x),
    "Exp": lambda xp, x: xp.exp(x),
    "Log": lambda xp, x: xp.log(x),
    "Sqrt": lambda xp, x: xp.sqrt(x),
    "Neg": lambda xp, x: -x,
    "Abs": lambda xp, x: xp.abs(x),
    "Floor": lambda xp, x: xp.floor(x),
    "Ceil": lambda xp, x: xp.ceil(x),
    "Round": lambda xp, x: xp.round(x),
    "Reciprocal": lambda xp, x: 1.0 / x,
    "Not": lambda xp, x: ~x,
    "Erf": lambda xp, x: jax.scipy.special.erf(x) if xp is jnp else _np_erf(x),
    "Softplus": lambda xp, x: xp.logaddexp(x, 0.0),
    "Identity": lambda xp, x: x,
}


def _np_erf(x):
    from math import erf

    return np.vectorize(erf)(np.asarray(x, np.float64)).astype(np.asarray(x).dtype)


for _name, _fn in _UNARY.items():

    def _make(fn):
        def op(node: Node, vals, ctx):
            return [fn(_xp(vals[0]), vals[0])]

        return op

    OP_REGISTRY[_name] = _make(_fn)


_BINARY = {
    "Add": lambda xp, a, b: a + b,
    "Sub": lambda xp, a, b: a - b,
    "Mul": lambda xp, a, b: a * b,
    "Div": lambda xp, a, b: a / b if np.issubdtype(np.asarray(a).dtype if xp is np else a.dtype, np.floating) or np.issubdtype(np.asarray(b).dtype if xp is np else b.dtype, np.floating) else a // b,
    "Pow": lambda xp, a, b: xp.power(a, b),
    "Min": lambda xp, a, b: xp.minimum(a, b),
    "Max": lambda xp, a, b: xp.maximum(a, b),
    "Equal": lambda xp, a, b: a == b,
    "Greater": lambda xp, a, b: a > b,
    "GreaterOrEqual": lambda xp, a, b: a >= b,
    "Less": lambda xp, a, b: a < b,
    "LessOrEqual": lambda xp, a, b: a <= b,
    "And": lambda xp, a, b: a & b,
    "Or": lambda xp, a, b: a | b,
    "Mod": lambda xp, a, b: a % b,
}

for _name, _fn in _BINARY.items():

    def _make2(fn):
        def op(node: Node, vals, ctx):
            a, b = vals[0], vals[1]
            xp = _xp(a, b)
            if len(vals) > 2:  # Min/Max are variadic
                out = fn(xp, a, b)
                for v in vals[2:]:
                    out = fn(xp, out, v)
                return [out]
            return [fn(xp, a, b)]

        return op

    OP_REGISTRY[_name] = _make2(_fn)


@register("Sum")
def op_sum(node, vals, ctx):
    out = vals[0]
    for v in vals[1:]:
        out = out + v
    return [out]


@register("LeakyRelu")
def op_leaky(node, vals, ctx):
    alpha = node.attr("alpha", 0.01)
    x = vals[0]
    xp = _xp(x)
    return [xp.where(x >= 0, x, alpha * x)]


@register("PRelu")
def op_prelu(node, vals, ctx):
    x, slope = vals
    xp = _xp(x, slope)
    s = xp.asarray(slope)
    # ONNX slope broadcasts per channel: [C] / [C,1,1] against NCHW input.
    if s.ndim and s.ndim < np.ndim(x):
        s = s.reshape((1, -1) + (1,) * (np.ndim(x) - 2))
    return [xp.where(x >= 0, x, s * x)]


@register("HardSigmoid")
def op_hardsigmoid(node, vals, ctx):
    alpha = node.attr("alpha", 0.2)
    beta = node.attr("beta", 0.5)
    x = vals[0]
    xp = _xp(x)
    return [xp.clip(alpha * x + beta, 0.0, 1.0)]


@register("HardSwish")
def op_hardswish(node, vals, ctx):
    x = vals[0]
    xp = _xp(x)
    return [x * xp.clip(x / 6.0 + 0.5, 0.0, 1.0)]


@register("Mish")
def op_mish(node, vals, ctx):
    x = vals[0]
    xp = _xp(x)
    return [x * xp.tanh(xp.logaddexp(x, 0.0))]


@register("Gelu")
def op_gelu(node, vals, ctx):
    x = vals[0]
    if node.attr("approximate", "none") == "tanh":
        return [jax.nn.gelu(x, approximate=True)]
    return [jax.nn.gelu(x, approximate=False)]


@register("Clip")
def op_clip(node, vals, ctx):
    x = vals[0]
    lo = vals[1] if len(vals) > 1 and vals[1] is not None else node.attr("min")
    hi = vals[2] if len(vals) > 2 and vals[2] is not None else node.attr("max")
    xp = _xp(x)
    if lo is not None:
        x = xp.maximum(x, xp.asarray(lo, dtype=np.asarray(x).dtype if xp is np else x.dtype))
    if hi is not None:
        x = xp.minimum(x, xp.asarray(hi, dtype=np.asarray(x).dtype if xp is np else x.dtype))
    return [x]


@register("Where")
def op_where(node, vals, ctx):
    c, a, b = vals
    return [_xp(c, a, b).where(c, a, b)]


@register("Cast")
def op_cast(node, vals, ctx):
    from .proto import TENSOR_DTYPES

    to = node.attr("to")
    np_dtype = TENSOR_DTYPES.get(to, np.float32)
    x = vals[0]
    if _static(x):
        return [np.asarray(x).astype(np_dtype)]
    if np_dtype == np.int64:
        np_dtype = np.int32  # x64 disabled under jit
    elif np_dtype == np.float64:
        np_dtype = np.float32
    return [x.astype(np_dtype)]


# -- normalization -----------------------------------------------------------


@register("BatchNormalization")
def op_batchnorm(node, vals, ctx):
    x, scale, bias, mean, var = vals[:5]
    eps = node.attr("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = 1.0 / np.sqrt(np.asarray(var, np.float32) + eps) if _static(var) else jax.lax.rsqrt(var + eps)
    return [(x - mean.reshape(shape)) * (inv * scale).reshape(shape) + bias.reshape(shape)]


@register("LayerNormalization")
def op_layernorm(node, vals, ctx):
    x = vals[0]
    scale = vals[1]
    bias = vals[2] if len(vals) > 2 else None
    axis = node.attr("axis", -1)
    eps = node.attr("epsilon", 1e-5)
    axes = tuple(range(axis if axis >= 0 else x.ndim + axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps) * scale
    if bias is not None:
        out = out + bias
    return [out]


@register("InstanceNormalization")
def op_instancenorm(node, vals, ctx):
    x, scale, bias = vals
    eps = node.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return [(x - mean) * jax.lax.rsqrt(var + eps) * scale.reshape(shape) + bias.reshape(shape)]


@register("Dropout")
def op_dropout(node, vals, ctx):
    return [vals[0]]  # inference


@register("Softmax")
def op_softmax(node, vals, ctx):
    x = vals[0]
    axis = node.attr("axis", -1 if ctx.opset >= 13 else 1)
    if ctx.opset >= 13:
        return [jax.nn.softmax(x, axis=axis)]
    # legacy semantics: flatten from axis, softmax, reshape back
    shape = x.shape
    flat = x.reshape(int(np.prod(shape[:axis])) if axis else 1, -1)
    return [jax.nn.softmax(flat, axis=-1).reshape(shape)]


@register("LogSoftmax")
def op_logsoftmax(node, vals, ctx):
    return [jax.nn.log_softmax(vals[0], axis=node.attr("axis", -1))]


# -- conv / pool -------------------------------------------------------------


def _conv_pads(node, spatial: int, x_shape, k_shape, strides, dilations):
    auto_pad = node.attr("auto_pad", "NOTSET")
    if isinstance(auto_pad, bytes):
        auto_pad = auto_pad.decode()
    pads = node.attr("pads")
    if auto_pad in ("NOTSET", "", None):
        if pads is None:
            pads = [0] * (2 * spatial)
        return [(pads[i], pads[i + spatial]) for i in range(spatial)]
    if auto_pad == "VALID":
        return [(0, 0)] * spatial
    # SAME_UPPER / SAME_LOWER
    out = []
    for i in range(spatial):
        in_dim = x_shape[2 + i]
        eff_k = (k_shape[i] - 1) * dilations[i] + 1
        out_dim = -(-in_dim // strides[i])
        total = max(0, (out_dim - 1) * strides[i] + eff_k - in_dim)
        lo = total // 2 if auto_pad == "SAME_UPPER" else total - total // 2
        out.append((lo, total - lo))
    return out


@register("Conv")
def op_conv(node, vals, ctx):
    x, w = vals[0], vals[1]
    b = vals[2] if len(vals) > 2 else None
    spatial = x.ndim - 2
    strides = node.attr("strides", [1] * spatial)
    dilations = node.attr("dilations", [1] * spatial)
    group = node.attr("group", 1)
    k_shape = node.attr("kernel_shape", list(w.shape[2:]))
    pads = _conv_pads(node, spatial, x.shape, k_shape, strides, dilations)
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCW", "OIW", "NCW")
    out = lax.conv_general_dilated(
        jnp.asarray(x),
        jnp.asarray(w),
        window_strides=strides,
        padding=pads,
        rhs_dilation=dilations,
        feature_group_count=group,
        dimension_numbers=dn,
    )
    if b is not None:
        out = out + jnp.asarray(b).reshape((1, -1) + (1,) * spatial)
    return [out]


@register("ConvTranspose")
def op_conv_transpose(node, vals, ctx):
    x, w = vals[0], vals[1]
    b = vals[2] if len(vals) > 2 else None
    spatial = x.ndim - 2
    strides = node.attr("strides", [1] * spatial)
    dilations = node.attr("dilations", [1] * spatial)
    group = node.attr("group", 1)
    pads_attr = node.attr("pads", [0] * (2 * spatial))
    out_pad = node.attr("output_padding", [0] * spatial)
    if node.attr("output_shape") is not None:
        raise NotImplementedError("ConvTranspose with explicit output_shape")
    # ONNX weight layout [C_in, C_out/group, kH, kW]; the fractionally-
    # strided equivalent convolves the lhs-dilated input with the flipped
    # kernel in [O, I, kH, kW] layout.
    w = jnp.asarray(w)
    if group != 1:
        ci, co_g = w.shape[0], w.shape[1]
        w = w.reshape(group, ci // group, co_g, *w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(group * co_g, ci // group, *w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    w = jnp.flip(w, axis=tuple(range(2, 2 + spatial)))
    pads = []
    for i in range(spatial):
        k_eff = (w.shape[2 + i] - 1) * dilations[i] + 1
        lo = k_eff - 1 - pads_attr[i]
        hi = k_eff - 1 - pads_attr[spatial + i] + out_pad[i]
        pads.append((lo, hi))
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCW", "OIW", "NCW")
    out = lax.conv_general_dilated(
        jnp.asarray(x),
        w,
        window_strides=[1] * spatial,
        padding=pads,
        lhs_dilation=strides,
        rhs_dilation=dilations,
        feature_group_count=group,
        dimension_numbers=dn,
    )
    if b is not None:
        out = out + jnp.asarray(b).reshape((1, -1) + (1,) * spatial)
    return [out]


def _pool(node, x, reducer, init, is_avg=False):
    spatial = x.ndim - 2
    k = node.attr("kernel_shape")
    strides = node.attr("strides", [1] * spatial)
    dilations = node.attr("dilations", [1] * spatial)
    pads = _conv_pads(node, spatial, x.shape, k, strides, dilations)
    if node.attr("ceil_mode", 0):
        # extend high padding so the last (partial) window is included
        new_pads = []
        for i in range(spatial):
            in_dim = x.shape[2 + i] + pads[i][0] + pads[i][1]
            eff_k = (k[i] - 1) * dilations[i] + 1
            rem = (in_dim - eff_k) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem else 0
            new_pads.append((pads[i][0], pads[i][1] + extra))
        pads = new_pads
    window = (1, 1) + tuple(k)
    ws = (1, 1) + tuple(strides)
    wd = (1, 1) + tuple(dilations)
    pad_full = [(0, 0), (0, 0)] + pads
    x = jnp.asarray(x)
    out = lax.reduce_window(x, init, reducer, window, ws, pad_full, window_dilation=wd)
    if is_avg:
        if node.attr("count_include_pad", 0):
            out = out / float(np.prod(k))
        else:
            ones = jnp.ones(x.shape[2:], x.dtype)[None, None]
            counts = lax.reduce_window(ones, 0.0, lax.add, window, ws, pad_full, window_dilation=wd)
            out = out / counts
    return out


@register("MaxPool")
def op_maxpool(node, vals, ctx):
    if len(node.outputs) > 1:
        raise NotImplementedError("MaxPool with indices output")
    return [_pool(node, vals[0], lax.max, -jnp.inf)]


@register("AveragePool")
def op_avgpool(node, vals, ctx):
    return [_pool(node, vals[0], lax.add, 0.0, is_avg=True)]


@register("GlobalAveragePool")
def op_gap(node, vals, ctx):
    x = vals[0]
    return [jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)]


@register("GlobalMaxPool")
def op_gmp(node, vals, ctx):
    x = vals[0]
    return [jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)]


# -- matmul ------------------------------------------------------------------


@register("MatMul")
def op_matmul(node, vals, ctx):
    return [jnp.matmul(jnp.asarray(vals[0]), jnp.asarray(vals[1]))]


@register("Gemm")
def op_gemm(node, vals, ctx):
    a, b = jnp.asarray(vals[0]), jnp.asarray(vals[1])
    c = vals[2] if len(vals) > 2 else None
    if node.attr("transA", 0):
        a = a.T
    if node.attr("transB", 0):
        b = b.T
    out = node.attr("alpha", 1.0) * (a @ b)
    if c is not None:
        out = out + node.attr("beta", 1.0) * c
    return [out]


@register("Einsum")
def op_einsum(node, vals, ctx):
    return [jnp.einsum(node.attr("equation"), *[jnp.asarray(v) for v in vals])]


# -- shape / indexing --------------------------------------------------------


@register("Shape")
def op_shape(node, vals, ctx):
    shape = np.asarray(np.shape(vals[0]), np.int64)
    start = node.attr("start", 0)
    end = node.attr("end")
    return [shape[start:end]]


@register("Size")
def op_size(node, vals, ctx):
    return [np.asarray(np.size(vals[0]), np.int64)]


@register("Reshape")
def op_reshape(node, vals, ctx):
    x, shape = vals
    if isinstance(shape, jax.Array):
        raise NotImplementedError(
            f"dynamic Reshape target at node {node.name!r} (shape must be static)"
        )
    target = _int_list(shape)
    x_shape = np.shape(x)
    # ONNX: 0 copies the input dim (unless allowzero), -1 infers.
    if not node.attr("allowzero", 0):
        target = [x_shape[i] if t == 0 else t for i, t in enumerate(target)]
    return [_xp(x).reshape(x, tuple(target))]


@register("Transpose")
def op_transpose(node, vals, ctx):
    x = vals[0]
    perm = node.attr("perm")
    if perm is None:
        perm = list(range(np.ndim(x)))[::-1]
    return [_xp(x).transpose(x, perm)]


@register("Flatten")
def op_flatten(node, vals, ctx):
    x = vals[0]
    axis = node.attr("axis", 1)
    shape = np.shape(x)
    lead = int(np.prod(shape[:axis])) if axis else 1
    return [_xp(x).reshape(x, (lead, -1))]


@register("Squeeze")
def op_squeeze(node, vals, ctx):
    x = vals[0]
    axes = _int_list(vals[1]) if len(vals) > 1 and vals[1] is not None else node.attr("axes")
    xp = _xp(x)
    if axes is None:
        return [xp.squeeze(x)]
    return [xp.squeeze(x, axis=tuple(int(a) for a in axes))]


@register("Unsqueeze")
def op_unsqueeze(node, vals, ctx):
    x = vals[0]
    axes = _int_list(vals[1]) if len(vals) > 1 and vals[1] is not None else node.attr("axes")
    xp = _xp(x)
    out = x
    for a in sorted(int(a) for a in axes):
        out = xp.expand_dims(out, a if a >= 0 else a + np.ndim(out) + 1)
    return [out]


@register("Concat")
def op_concat(node, vals, ctx):
    axis = node.attr("axis")
    xp = _xp(*vals)
    return [xp.concatenate([xp.asarray(v) for v in vals], axis=axis)]


@register("Gather")
def op_gather(node, vals, ctx):
    x, idx = vals
    axis = node.attr("axis", 0)
    xp = _xp(x, idx)
    return [xp.take(x, np.asarray(idx, np.int64) if xp is np else idx, axis=axis)]


@register("GatherElements")
def op_gather_elements(node, vals, ctx):
    x, idx = jnp.asarray(vals[0]), jnp.asarray(vals[1])
    axis = node.attr("axis", 0)
    return [jnp.take_along_axis(x, idx, axis=axis)]


@register("Slice")
def op_slice(node, vals, ctx):
    x = vals[0]
    if len(vals) > 1:  # opset >= 10: starts/ends/axes/steps are inputs
        starts = _int_list(vals[1])
        ends = _int_list(vals[2])
        axes = _int_list(vals[3]) if len(vals) > 3 and vals[3] is not None else list(range(len(starts)))
        steps = _int_list(vals[4]) if len(vals) > 4 and vals[4] is not None else [1] * len(starts)
    else:
        starts = node.attr("starts")
        ends = node.attr("ends")
        axes = node.attr("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    index = [slice(None)] * np.ndim(x)
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        # ONNX encodes "to the end" as INT64_MAX; clamp for python slices.
        lim = np.shape(x)[ax]
        st = max(min(st, lim), -lim) if st >= 0 else st
        en = min(en, lim) if en >= 0 else max(en, -lim - 1)
        index[ax] = slice(st, en, sp)
    return [x[tuple(index)]]


@register("Split")
def op_split(node, vals, ctx):
    x = vals[0]
    axis = node.attr("axis", 0)
    split = (
        _int_list(vals[1])
        if len(vals) > 1 and vals[1] is not None
        else node.attr("split")
    )
    xp = _xp(x)
    if split is None:
        n = len(node.outputs)
        return list(xp.split(x, n, axis=axis))
    idx = np.cumsum(split[:-1]).tolist()
    return list(xp.split(x, idx, axis=axis))


@register("Expand")
def op_expand(node, vals, ctx):
    x, shape = vals
    target = _int_list(shape)
    x_shape = list(np.shape(x))
    # ONNX Expand is bidirectional broadcast; result dim = max(x, target)
    ndim = max(len(target), len(x_shape))
    x_shape = [1] * (ndim - len(x_shape)) + x_shape
    target = [1] * (ndim - len(target)) + target
    out_shape = tuple(max(a, b) for a, b in zip(x_shape, target))
    xp = _xp(x)
    return [xp.broadcast_to(xp.reshape(x, tuple(x_shape)), out_shape)]


@register("Tile")
def op_tile(node, vals, ctx):
    x, reps = vals
    return [_xp(x).tile(x, tuple(_int_list(reps)))]


@register("Pad")
def op_pad(node, vals, ctx):
    x = vals[0]
    if len(vals) > 1 and vals[1] is not None:
        pads = _int_list(vals[1])
        cval = vals[2] if len(vals) > 2 and vals[2] is not None else 0.0
    else:
        pads = node.attr("pads")
        cval = node.attr("value", 0.0)
    mode = node.attr("mode", "constant")
    if isinstance(mode, bytes):
        mode = mode.decode()
    n = np.ndim(x)
    widths = [(pads[i], pads[i + n]) for i in range(n)]
    xp = _xp(x)
    if mode == "constant":
        return [xp.pad(x, widths, mode="constant", constant_values=float(np.asarray(cval)))]
    return [xp.pad(x, widths, mode={"reflect": "reflect", "edge": "edge"}[mode])]


@register("Constant")
def op_constant(node, vals, ctx):
    t = node.attr("value")
    if t is not None:
        return [t.array]
    for key in ("value_float", "value_int"):
        v = node.attr(key)
        if v is not None:
            return [np.asarray(v)]
    v = node.attr("value_floats")
    if v:
        return [np.asarray(v, np.float32)]
    v = node.attr("value_ints")
    if v:
        return [np.asarray(v, np.int64)]
    raise NotImplementedError(f"Constant node {node.name!r} without value")


@register("ConstantOfShape")
def op_constant_of_shape(node, vals, ctx):
    shape = tuple(_int_list(vals[0]))
    t = node.attr("value")
    if t is None:
        return [np.zeros(shape, np.float32)]
    return [np.full(shape, t.array.reshape(-1)[0], t.array.dtype)]


@register("Range")
def op_range(node, vals, ctx):
    start, limit, delta = [np.asarray(v).item() if _static(v) else v for v in vals]
    if _static(*vals):
        return [np.arange(start, limit, delta)]
    return [jnp.arange(start, limit, delta)]


@register("ArgMax")
def op_argmax(node, vals, ctx):
    x = vals[0]
    axis = node.attr("axis", 0)
    keepdims = node.attr("keepdims", 1)
    xp = _xp(x)
    out = xp.argmax(x, axis=axis)
    if keepdims:
        out = xp.expand_dims(out, axis)
    return [out.astype(np.int64) if xp is np else out.astype(jnp.int32)]


@register("ArgMin")
def op_argmin(node, vals, ctx):
    x = vals[0]
    axis = node.attr("axis", 0)
    keepdims = node.attr("keepdims", 1)
    xp = _xp(x)
    out = xp.argmin(x, axis=axis)
    if keepdims:
        out = xp.expand_dims(out, axis)
    return [out.astype(np.int64) if xp is np else out.astype(jnp.int32)]


@register("TopK")
def op_topk(node, vals, ctx):
    x = jnp.asarray(vals[0])
    k = int(np.asarray(vals[1]).item())
    axis = node.attr("axis", -1)
    if node.attr("largest", 1) == 0:
        raise NotImplementedError("TopK smallest")
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    v, i = jax.lax.top_k(x, k)
    if axis not in (-1, x.ndim - 1):
        v = jnp.moveaxis(v, -1, axis)
        i = jnp.moveaxis(i, -1, axis)
    return [v, i.astype(jnp.int32)]


def _reduce(node, vals, ctx, fn_np, fn_jnp):
    x = vals[0]
    if len(vals) > 1 and vals[1] is not None:
        axes = _int_list(vals[1])
    else:
        axes = node.attr("axes")
    keepdims = bool(node.attr("keepdims", 1))
    axes_t = tuple(int(a) for a in axes) if axes else None
    if axes_t is None and node.attr("noop_with_empty_axes", 0):
        return [x]
    if _static(x):
        return [fn_np(np.asarray(x), axis=axes_t, keepdims=keepdims)]
    return [fn_jnp(x, axis=axes_t, keepdims=keepdims)]


for _name, _np_fn, _jnp_fn in [
    ("ReduceMean", np.mean, jnp.mean),
    ("ReduceSum", np.sum, jnp.sum),
    ("ReduceMax", np.max, jnp.max),
    ("ReduceMin", np.min, jnp.min),
    ("ReduceProd", np.prod, jnp.prod),
]:

    def _maker(fnp, fjnp):
        def op(node, vals, ctx):
            return _reduce(node, vals, ctx, fnp, fjnp)

        return op

    OP_REGISTRY[_name] = _maker(_np_fn, _jnp_fn)


@register("ReduceL2")
def op_reduce_l2(node, vals, ctx):
    x = vals[0]
    axes = (
        tuple(_int_list(vals[1]))
        if len(vals) > 1 and vals[1] is not None
        else (tuple(node.attr("axes")) if node.attr("axes") else None)
    )
    keepdims = bool(node.attr("keepdims", 1))
    return [jnp.sqrt(jnp.sum(jnp.square(jnp.asarray(x)), axis=axes, keepdims=keepdims))]


# -- resize ------------------------------------------------------------------


def _resize_coords(out_size, in_size, scale, mode):
    """Output-pixel -> input-coordinate per ONNX coordinate_transformation_mode."""
    i = jnp.arange(out_size, dtype=jnp.float32)
    if mode == "align_corners":
        if out_size == 1:
            return jnp.zeros((1,), jnp.float32)
        return i * (in_size - 1) / (out_size - 1)
    if mode == "asymmetric":
        return i / scale
    if mode == "pytorch_half_pixel":
        return (i + 0.5) / scale - 0.5 if out_size > 1 else jnp.zeros((1,), jnp.float32)
    # default: half_pixel
    return (i + 0.5) / scale - 0.5


@register("Resize")
def op_resize(node, vals, ctx):
    x = jnp.asarray(vals[0])
    scales = vals[2] if len(vals) > 2 and vals[2] is not None and np.size(vals[2]) else None
    sizes = vals[3] if len(vals) > 3 and vals[3] is not None and np.size(vals[3]) else None
    mode = node.attr("mode", "nearest")
    coord_mode = node.attr("coordinate_transformation_mode", "half_pixel")
    nearest_mode = node.attr("nearest_mode", "round_prefer_floor")
    if isinstance(mode, bytes):
        mode = mode.decode()
    if isinstance(coord_mode, bytes):
        coord_mode = coord_mode.decode()
    if isinstance(nearest_mode, bytes):
        nearest_mode = nearest_mode.decode()

    in_shape = x.shape
    if sizes is not None:
        out_shape = tuple(_int_list(sizes))
        eff_scales = [o / i for o, i in zip(out_shape, in_shape)]
    else:
        eff_scales = [float(s) for s in np.asarray(scales).reshape(-1)]
        out_shape = tuple(
            int(math.floor(i * s)) for i, s in zip(in_shape, eff_scales)
        )
    out = x
    for axis in range(x.ndim):
        if out_shape[axis] == in_shape[axis]:
            continue
        coords = _resize_coords(out_shape[axis], in_shape[axis], eff_scales[axis], coord_mode)
        if mode == "nearest":
            if nearest_mode == "floor":
                idx = jnp.floor(coords)
            elif nearest_mode == "ceil":
                idx = jnp.ceil(coords)
            elif nearest_mode == "round_prefer_ceil":
                idx = jnp.floor(coords + 0.5)
            else:  # round_prefer_floor
                idx = jnp.ceil(coords - 0.5)
            idx = jnp.clip(idx, 0, in_shape[axis] - 1).astype(jnp.int32)
            out = jnp.take(out, idx, axis=axis)
        elif mode == "linear":
            c = jnp.clip(coords, 0.0, in_shape[axis] - 1)
            lo = jnp.floor(c).astype(jnp.int32)
            hi = jnp.clip(lo + 1, 0, in_shape[axis] - 1)
            w = (c - lo).astype(x.dtype)
            shape = [1] * out.ndim
            shape[axis] = -1
            w = w.reshape(shape)
            out = jnp.take(out, lo, axis=axis) * (1 - w) + jnp.take(out, hi, axis=axis) * w
        else:
            raise NotImplementedError(f"Resize mode {mode!r}")
    return [out]


@register("Upsample")
def op_upsample(node, vals, ctx):
    # Legacy (opset<10) alias of Resize with scales input/attr, asymmetric.
    scales = vals[1] if len(vals) > 1 else np.asarray(node.attr("scales"), np.float32)
    fake = Node(
        op_type="Resize",
        name=node.name,
        inputs=node.inputs,
        outputs=node.outputs,
        attrs={},
    )
    fake.attrs = dict(node.attrs)
    from .proto import Attribute

    fake.attrs["coordinate_transformation_mode"] = Attribute(
        name="coordinate_transformation_mode", type=3, s=b"asymmetric"
    )
    fake.attrs["nearest_mode"] = Attribute(name="nearest_mode", type=3, s=b"floor")
    return op_resize(fake, [vals[0], None, scales], ctx)


@register("DepthToSpace")
def op_depth_to_space(node, vals, ctx):
    x = jnp.asarray(vals[0])
    bs = node.attr("blocksize")
    b, c, h, w = x.shape
    if node.attr("mode", "DCR") == "DCR":
        x = x.reshape(b, bs, bs, c // (bs * bs), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
    else:  # CRD
        x = x.reshape(b, c // (bs * bs), bs, bs, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
    return [x.reshape(b, c // (bs * bs), h * bs, w * bs)]


@register("SpaceToDepth")
def op_space_to_depth(node, vals, ctx):
    x = jnp.asarray(vals[0])
    bs = node.attr("blocksize")
    b, c, h, w = x.shape
    x = x.reshape(b, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return [x.reshape(b, c * bs * bs, h // bs, w // bs)]
