"""jax version-skew shims for the parallel package.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace (and pallas-TPU renamed ``TPUCompilerParams``
to ``CompilerParams``) across jax 0.4 -> 0.5. The serving stack must
import — and its CPU test tier must run — on both sides of that skew:
the pinned CI image and the TPU runtime image are rarely the same jax.
"""

from __future__ import annotations

try:  # jax >= 0.5
    from jax import shard_map
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]

__all__ = ["shard_map"]
