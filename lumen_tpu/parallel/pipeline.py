"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.8 — its models are
small enough to replicate), but the driver contract for this framework
treats PP as first-class alongside DP/TP/SP/EP. The TPU-native shape of PP
is NOT a process-per-stage runtime with send/recv threads (the GPU
pattern): it is ONE ``shard_map``-traced program in which

1. the layer stack's parameters carry a leading ``[n_stages, ...]`` dim
   sharded over ``stage`` — each device holds only its stage's weights;
2. a ``lax.scan`` runs ``n_micro + n_stages - 1`` ticks; every tick each
   stage applies its layers to its current activation and hands the result
   to the next stage with a single ring ``ppermute`` (riding ICI);
3. stage 0 injects a fresh microbatch each tick, the last stage's outputs
   are masked/psum'd back to every device.

Because the whole schedule is traced, ``jax.grad`` through this function
yields the reverse pipeline (ppermutes transpose to the opposite ring
direction) with no extra code — PP training falls out of autodiff.

Bubble fraction is the usual ``(n_stages-1)/(n_micro+n_stages-1)``; pick
``n_micro >= 4*n_stages`` to amortize.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from .compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime.mesh import STAGE_AXIS


def stack_stage_params(per_stage_params: list):
    """Stack one pytree per stage into a single pytree whose leaves carry a
    leading ``[n_stages, ...]`` dim (shard it with :func:`stage_sharding`)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def stage_sharding(mesh: Mesh, axis_name: str = STAGE_AXIS) -> NamedSharding:
    """Sharding for stacked stage params: leading dim over ``stage``."""
    return NamedSharding(mesh, P(axis_name))


def _pipeline_local(
    stage_params,
    microbatches: jnp.ndarray,
    *,
    stage_fn: Callable,
    n_stages: int,
    axis_name: str,
) -> jnp.ndarray:
    """Per-device body. ``stage_params`` leaves are ``[1, ...]`` (this
    stage's slice); ``microbatches`` is the full ``[n_micro, mb, ...]``
    (replicated — activations are small relative to weights, and this keeps
    the schedule free of gather logic)."""
    params = jax.tree.map(lambda l: l[0], stage_params)
    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n_stages - 1

    # Pad the injection stream with zeros for the drain ticks.
    pad = jnp.zeros((n_stages - 1,) + microbatches.shape[1:], microbatches.dtype)
    inject = jnp.concatenate([microbatches, pad], axis=0)

    fwd_ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, mb_in):
        # Stage 0 consumes the injected microbatch; later stages consume
        # whatever the previous stage handed them last tick.
        x = jnp.where(stage == 0, mb_in, carry)
        y = stage_fn(params, x)
        handoff = lax.ppermute(y, axis_name, fwd_ring)
        return handoff, y

    carry0 = jnp.zeros_like(microbatches[0])
    _, ys = lax.scan(tick, carry0, inject)

    # Microbatch m leaves the last stage at tick m + n_stages - 1.
    outs = lax.slice_in_dim(ys, n_stages - 1, n_stages - 1 + n_micro, axis=0)
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    # Replicate the last stage's outputs to every device so callers see a
    # plain (unsharded) result.
    return lax.psum(outs, axis_name)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    n_microbatches: int,
    axis_name: str = STAGE_AXIS,
) -> jax.Array:
    """Run ``x`` through ``n_stages`` pipelined applications of ``stage_fn``.

    - ``stage_fn(params, mb) -> mb_out`` applies ONE stage's layers to one
      microbatch; input and output must have identical shape/dtype (the
      activation format that flows between stages).
    - ``stacked_params``: pytree with leading ``[n_stages, ...]`` leaves
      (see :func:`stack_stage_params`), sharded over ``axis_name``.
    - ``x``: global batch ``[B, ...]`` with ``B % n_microbatches == 0``.

    Differentiable end-to-end; compose with DP/TP by nesting inside an
    outer pjit whose mesh carries the extra axes.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}; axes: {mesh.axis_names}")
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by n_microbatches {n_microbatches}")
    n_leading = {l.shape[0] for l in jax.tree.leaves(stacked_params)}
    if n_leading != {n_stages}:
        raise ValueError(
            f"stacked params leading dims {n_leading} != n_stages {n_stages}; "
            "build them with stack_stage_params (one entry per stage)"
        )
    mbs = x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])
    inner = functools.partial(
        _pipeline_local, stage_fn=stage_fn, n_stages=n_stages, axis_name=axis_name
    )
    out = shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, mbs)
    return out.reshape((b,) + out.shape[2:])
