"""Multi-host process-group initialization.

The reference's "distributed backend" is gRPC between microservices on one
LAN (SURVEY.md §2.8); the TPU equivalent is a JAX distributed runtime: one
process per host, DCN for control, ICI for collectives. This wrapper keeps
single-host development zero-config while making pod slices a flag change.

Env convention (matches TPU VM metadata/launchers):
``LUMEN_COORDINATOR`` (host:port), ``LUMEN_NUM_PROCESSES``,
``LUMEN_PROCESS_ID`` — explicit args win over env.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger(__name__)

_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the multi-host runtime if configured; returns True when a
    multi-process group is live, False for single-host operation."""
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("LUMEN_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("LUMEN_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid_env = os.environ.get("LUMEN_PROCESS_ID")
        process_id = int(pid_env) if pid_env is not None else None

    if not coordinator_address or num_processes <= 1:
        logger.info("single-host mode (%d local devices)", jax.local_device_count())
        return False

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "multi-host runtime up: process %d/%d, %d global / %d local devices",
        jax.process_index(),
        jax.process_count(),
        jax.device_count(),
        jax.local_device_count(),
    )
    return True


def is_primary() -> bool:
    """True on the process that should bind user-facing servers / write
    checkpoints."""
    return jax.process_index() == 0
