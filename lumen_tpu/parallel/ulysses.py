"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to :mod:`.ring_attention` (the
reference has neither — SURVEY.md §5 notes no SP/CP anywhere). Where ring
attention rotates K/V blocks around the mesh and keeps an online-softmax
accumulator, the all-to-all layout swap re-shards the *heads* instead:

1. Q/K/V arrive sequence-sharded ``[B, H, S/n, D]`` per device;
2. one ``all_to_all`` per tensor swaps the sharded dim — each device now
   holds ``[B, H/n, S, D]``: the FULL sequence for a subset of heads;
3. plain (flash-eligible) attention runs locally per head group — no
   per-step collectives, no online-softmax bookkeeping;
4. one ``all_to_all`` back returns the sequence-sharded layout.

Trade-off vs ring: 2 collectives total (vs n-1 ppermutes) and the local
compute is a dense attention XLA already knows how to fuse — but heads
must be divisible by the axis size, and each device needs O(S) K/V memory
for its head group (ring keeps O(S/n)). Pick per workload: many-head
models with moderate S -> all-to-all; extreme S -> ring.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .compat import shard_map

from ..ops.attention import attention
from ..runtime.mesh import SEQ_AXIS


def _ulysses_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool,
    scale: float | None,
) -> jnp.ndarray:
    # [B, H, S/n, D] -> [B, H/n, S, D]: split heads, gather sequence.
    gather = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )
    qh, kh, vh = gather(q), gather(k), gather(v)
    out = attention(qh, kh, vh, causal=causal, scale=scale)
    # [B, H/n, S, D] -> [B, H, S/n, D]: split sequence, regather heads.
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    scale: float | None = None,
    axis_name: str = SEQ_AXIS,
) -> jax.Array:
    """Exact attention with Q/K/V sequence-sharded over ``axis_name``,
    computed via the all-to-all head/sequence layout swap.

    Requires ``S % n == 0`` and ``H % n == 0`` for ``n =
    mesh.shape[axis_name]`` (pad sequence / replicate-repeat KV heads
    upstream; GQA callers should ``repeat_kv`` first so K/V carry the same
    head count as Q). Batch stays unsharded here; nest inside an outer
    ``shard_map``/``pjit`` to combine with data/tensor parallelism.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}; axes: {mesh.axis_names}")
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(
            f"heads ({q.shape[1]}) must divide by mesh axis {axis_name!r} size {n} "
            "for all-to-all sequence parallelism; use ring_attention otherwise"
        )
    spec = P(None, None, axis_name, None)
    inner = functools.partial(
        _ulysses_local, axis_name=axis_name, causal=causal, scale=scale
    )
    return shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
