"""Ring attention: exact attention over sequences sharded across a mesh axis.

Long-context support (absent from the reference — SURVEY.md §5 notes no
SP/CP anywhere; here it is first-class). Each device holds a sequence shard
of Q/K/V; K/V blocks rotate around the ring via ``ppermute`` over ICI while
a blockwise online-softmax accumulator keeps the math exact — memory per
device is O(seq/n_devices), communication overlaps with compute.

Layout: ``[batch, heads, seq_shard, head_dim]`` inside ``shard_map``; the
public wrapper takes globally-sharded ``[B, H, S, D]`` arrays.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .compat import shard_map

from ..runtime.mesh import SEQ_AXIS

NEG_INF = -1e30


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool,
    scale: float | None,
) -> jnp.ndarray:
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * sm_scale

    q_pos = my_idx * sq + jnp.arange(sq)  # global positions of local queries

    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    m = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        k_blk, v_blk, acc, m, l = carry
        # After i rotations we hold the block originally on device (my-i) mod n.
        src = (my_idx - i) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        # Last step's rotation would be discarded; skip the collective.
        k_next, v_next = jax.lax.cond(
            i < n - 1,
            lambda kv: (
                jax.lax.ppermute(kv[0], axis_name, perm),
                jax.lax.ppermute(kv[1], axis_name, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return k_next, v_next, acc_new, m_new, l_new

    _, _, acc, m, l = jax.lax.fori_loop(0, n, step, (k, v, acc, m, l))
    # Fully-masked rows (causal with padding) have l=0; emit zeros.
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    scale: float | None = None,
    axis_name: str = SEQ_AXIS,
) -> jax.Array:
    """Exact attention with Q/K/V sequence-sharded over ``axis_name``.

    Requires ``S % mesh.shape[axis_name] == 0`` (pad upstream). Batch and
    head dims stay unsharded here; combine with data/tensor parallelism by
    nesting this inside an outer ``shard_map``/``pjit``.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}; axes: {mesh.axis_names}")
    spec = P(None, None, axis_name, None)
    inner = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    return shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
