"""Parallelism: sharding rules, ring + all-to-all sequence parallelism, multi-host runtime."""

from .distributed import initialize, is_primary
from .ring_attention import ring_attention
from .ulysses import ulysses_attention
from .sharding import TRANSFORMER_TP_RULES, replicate, shard_params, spec_for

__all__ = [
    "initialize",
    "is_primary",
    "ring_attention",
    "ulysses_attention",
    "shard_params",
    "replicate",
    "spec_for",
    "TRANSFORMER_TP_RULES",
]
