"""Parallelism: sharding rules, ring + all-to-all sequence parallelism,
pipeline + expert parallelism, multi-host runtime."""

from .distributed import initialize, is_primary
from .moe import MoEParams, init_moe_params, moe_ffn, moe_sharding
from .pipeline import pipeline_apply, stack_stage_params, stage_sharding
from .ring_attention import ring_attention
from .ulysses import ulysses_attention
from .sharding import (
    MOE_EP_RULES,
    TRANSFORMER_TP_RULES,
    replicate,
    shard_params,
    spec_for,
)

__all__ = [
    "initialize",
    "is_primary",
    "ring_attention",
    "ulysses_attention",
    "pipeline_apply",
    "stack_stage_params",
    "stage_sharding",
    "MoEParams",
    "init_moe_params",
    "moe_ffn",
    "moe_sharding",
    "shard_params",
    "replicate",
    "spec_for",
    "TRANSFORMER_TP_RULES",
    "MOE_EP_RULES",
]
