"""Expert parallelism: a top-k routed mixture-of-experts FFN with GShard
all-to-all dispatch over an ``expert`` mesh axis.

Absent from the reference (SURVEY.md §2.8 lists EP as N/A there); built
here because the driver contract treats EP as a first-class sharding and
because the obvious growth path for the VLM family is an MoE decoder
(Qwen/Mixtral-style). TPU-native shape:

- tokens arrive sharded over the ``expert`` axis (the axis doubles as the
  data axis for the MoE block — the standard TPU layout, so the dispatch
  rides the same ICI ring in both directions);
- routing is capacity-based: each expert processes at most ``C`` tokens
  per shard, overflow drops (GShard semantics) — this keeps every shape
  static for XLA, no data-dependent gather sizes;
- dispatch/combine are einsums against a one-hot dispatch mask plus ONE
  ``all_to_all`` each way; expert FFNs run as a batched einsum over the
  device's local expert slice (dense, MXU-friendly).

Everything is differentiable; ``jax.grad`` transposes the all-to-alls
automatically.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from .compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime.mesh import EXPERT_AXIS


class MoEParams(NamedTuple):
    """Weights for a routed SwiGLU expert bank.

    ``router``: [D, E] — token -> expert logits (kept fp32 for stable
    softmax, as every production MoE does).
    ``w_gate``/``w_up``: [E, D, F]; ``w_down``: [E, F, D].
    """

    router: jax.Array
    w_gate: jax.Array
    w_up: jax.Array
    w_down: jax.Array


def init_moe_params(
    key: jax.Array, d_model: int, d_ff: int, n_experts: int, dtype=jnp.float32
) -> MoEParams:
    kr, kg, ku, kd = jax.random.split(key, 4)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    return MoEParams(
        router=(jax.random.normal(kr, (d_model, n_experts)) * scale_in).astype(
            jnp.float32
        ),
        w_gate=(jax.random.normal(kg, (n_experts, d_model, d_ff)) * scale_in).astype(dtype),
        w_up=(jax.random.normal(ku, (n_experts, d_model, d_ff)) * scale_in).astype(dtype),
        w_down=(jax.random.normal(kd, (n_experts, d_ff, d_model)) * scale_out).astype(dtype),
    )


def moe_sharding(mesh: Mesh, axis_name: str = EXPERT_AXIS) -> MoEParams:
    """Shardings matching :func:`moe_ffn`: expert banks split their leading
    (expert) dim over the axis; the router is replicated."""
    ex = NamedSharding(mesh, P(axis_name))
    return MoEParams(
        router=NamedSharding(mesh, P()), w_gate=ex, w_up=ex, w_down=ex
    )


def _topk_gates(x: jnp.ndarray, router: jnp.ndarray, k: int, norm_topk: bool):
    """Softmax-then-top-k routing: ``[T, k]`` gate values + expert ids."""
    probs = jax.nn.softmax(x.astype(jnp.float32) @ router, axis=-1)  # [T, E]
    gate_vals, gate_idx = lax.top_k(probs, k)  # [T, k]
    if norm_topk:
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)
    return gate_vals, gate_idx


def _moe_exact_local(
    params: MoEParams, x: jnp.ndarray, *, n_experts: int, k: int, norm_topk: bool
) -> jnp.ndarray:
    """Exact (zero-drop) single-device MoE via grouped GEMM.

    Sorts the ``T*k`` (token, choice) assignments by expert and runs the
    expert bank as three ``lax.ragged_dot`` calls — O(T*k) dispatch work
    and O(T*k*D*F) FLOPs, vs the capacity formulation whose exact variant
    needs an ``[E, T, D]`` buffer and O(T^2*E*D) one-hot einsums. This is
    the inference path that reproduces dense-gather references (HF MoE)
    token-for-token.
    """
    t, d = x.shape
    gate_vals, gate_idx = _topk_gates(x, params.router, k, norm_topk)
    e_flat = gate_idx.reshape(-1)  # [N], N = T*k; index t*k+j = (token t, choice j)
    order = jnp.argsort(e_flat, stable=True)
    inv = jnp.argsort(order)
    xs = jnp.repeat(x, k, axis=0)[order].astype(params.w_gate.dtype)  # [N, D]
    group_sizes = jnp.bincount(e_flat, length=n_experts).astype(jnp.int32)
    hg = lax.ragged_dot(xs, params.w_gate, group_sizes)
    hu = lax.ragged_dot(xs, params.w_up, group_sizes)
    ys = lax.ragged_dot(jax.nn.silu(hg) * hu, params.w_down, group_sizes)  # [N, D]
    ys = ys[inv].reshape(t, k, d).astype(jnp.float32)
    return (ys * gate_vals[..., None]).sum(axis=1).astype(x.dtype)


def _route(
    x: jnp.ndarray,
    router: jnp.ndarray,
    n_experts: int,
    k: int,
    capacity: int,
    norm_topk: bool = True,
):
    """Top-k capacity-limited routing for ``x: [T, D]``.

    Returns ``dispatch: [T, E, C]`` one-hot (token t occupies slot c of
    expert e) and ``combine: [T, E, C]`` (same support, scaled by the
    router probability — renormalized over the top-k iff ``norm_topk``,
    matching HF's ``norm_topk_prob``).
    """
    gate_vals, gate_idx = _topk_gates(x, router, k, norm_topk)

    # Slot assignment: all rank-0 choices across tokens claim slots before
    # any rank-1 choice (primary routes never lose capacity to secondaries).
    sel = jax.nn.one_hot(gate_idx, n_experts, dtype=jnp.float32)  # [T, k, E]
    flat = sel.transpose(1, 0, 2).reshape(k * x.shape[0], n_experts)
    pos = jnp.cumsum(flat, axis=0) - 1.0  # slot index per (choice, expert)
    pos = pos.reshape(k, x.shape[0], n_experts).transpose(1, 0, 2)  # [T, k, E]
    slot = (pos * sel).sum(-1)  # [T, k] slot within the chosen expert
    fits = (slot < capacity) & (sel.sum(-1) > 0)

    slot_oh = jax.nn.one_hot(slot.astype(jnp.int32), capacity, dtype=jnp.float32)  # [T, k, C]
    choice = sel * fits[..., None]  # [T, k, E]
    dispatch = jnp.einsum("tke,tkc->tec", choice, slot_oh)
    combine = jnp.einsum("tke,tkc,tk->tec", choice, slot_oh, gate_vals)
    return dispatch, combine


def _expert_ffn(params: MoEParams, xs: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU over a local expert bank: ``xs: [E_local, N, D]``."""
    gate = jnp.einsum("end,edf->enf", xs, params.w_gate)
    up = jnp.einsum("end,edf->enf", xs, params.w_up)
    act = jax.nn.silu(gate) * up
    return jnp.einsum("enf,efd->end", act, params.w_down)


def _moe_local(
    params: MoEParams,
    x: jnp.ndarray,
    *,
    n_experts: int,
    k: int,
    capacity: int,
    n_shards: int,
    axis_name: str | None,
    norm_topk: bool = True,
) -> jnp.ndarray:
    t = x.shape[0]
    dispatch, combine = _route(x, params.router, n_experts, k, capacity, norm_topk)
    buf = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)  # [E, C, D]
    buf = buf.astype(params.w_gate.dtype)

    if axis_name is not None:
        # [E, C, D] -> every device holds its E/n local experts with the
        # slots from ALL n shards: [E/n, n*C, D].
        e_local = n_experts // n_shards
        buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0, tiled=True)
        buf = buf.reshape(n_shards, e_local, capacity, buf.shape[-1])
        buf = buf.transpose(1, 0, 2, 3).reshape(e_local, n_shards * capacity, -1)
        out = _expert_ffn(params, buf)
        out = out.reshape(e_local, n_shards, capacity, -1).transpose(1, 0, 2, 3)
        out = out.reshape(n_experts, capacity, -1)
        out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0, tiled=True)
    else:
        out = _expert_ffn(params, buf)

    y = jnp.einsum("ecd,tec->td", out.astype(jnp.float32), combine)
    return y.astype(x.dtype).reshape(t, -1)


def moe_ffn(
    params: MoEParams,
    x: jax.Array,
    mesh: Mesh | None = None,
    *,
    k: int = 2,
    capacity_factor: float | None = 1.25,
    axis_name: str = EXPERT_AXIS,
    norm_topk: bool = True,
) -> jax.Array:
    """Apply the routed expert FFN to ``x: [T, D]`` (flatten [B, S, D]
    upstream).

    With a mesh, tokens and expert banks are sharded over ``axis_name``
    (``T`` and ``E`` must divide by its size) and dispatch runs via
    all-to-all; without one, the same math runs single-device (the unit
    test oracle and the 1-chip serving path).

    ``capacity_factor=None`` means EXACT routing (nothing drops) for
    parity with dense-gather implementations (HF). Single-device this
    runs the grouped-GEMM path (``lax.ragged_dot`` over expert-sorted
    assignments, O(T*k) dispatch); sharded it sets per-shard capacity to
    the local token count — the worst per-expert load, since a token's
    top-k choices are distinct experts — at an ``[E, T_local, D]`` buffer
    memory cost, so prefer a finite factor at scale.
    """
    n_experts = params.w_gate.shape[0]
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        if capacity_factor is None:
            return _moe_exact_local(
                params, x, n_experts=n_experts, k=k, norm_topk=norm_topk
            )
        t = x.shape[0]
        capacity = max(1, int(capacity_factor * k * t / n_experts))
        return _moe_local(
            params, x, n_experts=n_experts, k=k, capacity=capacity,
            n_shards=1, axis_name=None, norm_topk=norm_topk,
        )
    n = mesh.shape[axis_name]
    if x.shape[0] % n or n_experts % n:
        raise ValueError(
            f"tokens ({x.shape[0]}) and experts ({n_experts}) must divide by "
            f"mesh axis {axis_name!r} size {n}"
        )
    t_local = x.shape[0] // n
    capacity = t_local if capacity_factor is None else max(
        1, int(capacity_factor * k * t_local / n_experts)
    )
    inner = functools.partial(
        _moe_local, n_experts=n_experts, k=k, capacity=capacity,
        n_shards=n, axis_name=axis_name, norm_topk=norm_topk,
    )
    param_specs = MoEParams(
        router=P(), w_gate=P(axis_name), w_up=P(axis_name), w_down=P(axis_name)
    )
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(param_specs, P(axis_name)),
        out_specs=P(axis_name),
        check_vma=False,
    )(params, x)
