"""Sharding rules: map parameter-tree paths to PartitionSpecs.

The reference scales by running whole-model replicas behind a thread pool;
here scaling is declarative: regex rules assign each parameter a
``PartitionSpec`` over the named mesh axes (``data``/``model``/``seq``) and
XLA inserts the collectives (scaling-book recipe: pick a mesh, annotate
shardings, let the compiler do the rest).
"""

from __future__ import annotations

import logging
import math
import re
from typing import Iterable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

#: (path regex, PartitionSpec); first match wins, default = replicated
ShardingRule = tuple[str, P]

# Standard tensor-parallel rules for transformer blocks: attention QKV and
# MLP-up kernels shard their output dim, attention-out and MLP-down shard
# their input dim (Megatron layout -> one all-reduce per block).
TRANSFORMER_TP_RULES: list[ShardingRule] = [
    (r".*(q_proj|k_proj|v_proj|qkv|fc1|gate_proj|up_proj)/kernel$", P(None, "model")),
    (r".*(q_proj|k_proj|v_proj|qkv|fc1|gate_proj|up_proj)/bias$", P("model")),
    (r".*(o_proj|out_proj|fc2|down_proj)/kernel$", P("model", None)),
    (r".*embedding$", P(None, "model")),
]

# Tensor-parallel rules for int8-quantized projections (``ops/quant.QDense``:
# ``q [in, out] int8`` + per-output-channel ``scale [out]``), shared by the
# VLM decoder and the CLIP towers (their projection names match the same
# patterns). Same Megatron layout as the kernel rules above — the
# scale vector shards along the SAME output axis as its q matrix, and an
# input-sharded projection's scale/bias stay replicated (their dim is the
# unsharded output). Token-identity of the TP decode vs replicated int8
# depends on the kernel mode: with ``LUMEN_Q8_KERNEL=dynamic`` (W8A8,
# int8 x int8 -> int32 dot) the sharded partials accumulate exactly in
# int32, so identity is guaranteed; the default ``dequant`` mode does a
# float dot where contraction-dim sharding reorders accumulation, so its
# identity is empirical — pinned on a small CPU mesh by
# tests/test_serving_tp.py, not a bit-exactness guarantee at scale.
# lm_head q/scale replicate, matching the bf16 rules (no lm_head entry).
# Prepend to TRANSFORMER_TP_RULES so the shared embedding rule applies.
INT8_TP_RULES: list[ShardingRule] = [
    (r".*(q_proj|k_proj|v_proj|qkv|fc1|gate_proj|up_proj)/q$", P(None, "model")),
    (r".*(q_proj|k_proj|v_proj|qkv|fc1|gate_proj|up_proj)/(scale|bias)$", P("model")),
    (r".*(o_proj|out_proj|fc2|down_proj)/q$", P("model", None)),
]

# Expert parallelism for MoE decoder layers (``models/vlm/modeling.MoEFFN``):
# stacked expert banks [E, ...] split their leading dim over ``expert``; the
# router stays replicated (it's tiny and every token needs it). Prepend to
# TP rules when the mesh carries both axes.
MOE_EP_RULES: list[ShardingRule] = [
    (r".*mlp/(w_gate|w_up|w_down)$", P("expert")),
    (r".*mlp/router$", P()),
]


def spec_for(path: str, rules: Iterable[ShardingRule]) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return P()


def keypath_str(keypath) -> str:
    """One canonical '/'-joined string for a pytree keypath (dict keys,
    sequence indices, and attribute names of registered dataclasses)."""
    parts = []
    for k in keypath:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def sanitize_spec(spec: P, shape: tuple[int, ...], mesh: Mesh, path: str = "") -> P:
    """Degrade a PartitionSpec so it is valid for a concrete leaf: axes not
    in the mesh replicate, specs longer than the leaf's rank replicate, and
    a sharded dim must divide evenly (else that dim replicates). Tuple
    entries (multi-axis sharding of one dim) are supported. Degradations
    are logged so a typo'd axis or odd dim doesn't silently disable TP."""
    if len(spec) > len(shape):
        if len(spec) > 0:
            logger.debug("spec %s has higher rank than leaf %s%s; replicating", spec, shape, f" at {path}" if path else "")
        return P()
    out = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        axes = entry if isinstance(entry, tuple) else (entry,)
        if entry is None:
            out.append(None)
            continue
        known = [a for a in axes if a in mesh.axis_names]
        size = math.prod(mesh.shape[a] for a in known)
        if len(known) != len(axes) or dim % size != 0:
            logger.warning(
                "degrading sharding %s for dim %d%s (unknown axis or indivisible); replicating that dim",
                entry, dim, f" at {path!r}" if path else "",
            )
            out.append(None)
        else:
            out.append(entry)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def shard_params(params, mesh: Mesh, rules: Iterable[ShardingRule] | None = None):
    """Place a parameter tree onto the mesh according to the rules (specs
    that don't fit a leaf's rank/shape or the mesh degrade to replication)."""
    rules = list(rules or [])

    def place(keypath, leaf):
        path = keypath_str(keypath)
        spec = sanitize_spec(spec_for(path, rules), leaf.shape, mesh, path=path)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def replicate(tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )
