"""Sharding rules: map parameter-tree paths to PartitionSpecs.

The reference scales by running whole-model replicas behind a thread pool;
here scaling is declarative: regex rules assign each parameter a
``PartitionSpec`` over the named mesh axes (``data``/``model``/``seq``) and
XLA inserts the collectives (scaling-book recipe: pick a mesh, annotate
shardings, let the compiler do the rest).
"""

from __future__ import annotations

import logging
import re
from typing import Iterable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

#: (path regex, PartitionSpec); first match wins, default = replicated
ShardingRule = tuple[str, P]

# Standard tensor-parallel rules for transformer blocks: attention QKV and
# MLP-up kernels shard their output dim, attention-out and MLP-down shard
# their input dim (Megatron layout -> one all-reduce per block).
TRANSFORMER_TP_RULES: list[ShardingRule] = [
    (r".*(q_proj|k_proj|v_proj|qkv|fc1|gate_proj|up_proj)/kernel$", P(None, "model")),
    (r".*(q_proj|k_proj|v_proj|qkv|fc1|gate_proj|up_proj)/bias$", P("model")),
    (r".*(o_proj|out_proj|fc2|down_proj)/kernel$", P("model", None)),
    (r".*embedding$", P(None, "model")),
]


def spec_for(path: str, rules: Iterable[ShardingRule]) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return P()


def keypath_str(keypath) -> str:
    """One canonical '/'-joined string for a pytree keypath (dict keys,
    sequence indices, and attribute names of registered dataclasses)."""
    parts = []
    for k in keypath:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def shard_params(params, mesh: Mesh, rules: Iterable[ShardingRule] | None = None):
    """Place a parameter tree onto the mesh according to the rules (axes a
    rule names that are absent from the mesh degrade to replication)."""
    rules = list(rules or [])
    available = set(mesh.axis_names)

    def _sanitize(spec: P) -> P:
        return P(*[a if a in available else None for a in spec])

    def place(keypath, leaf):
        spec = _sanitize(spec_for(keypath_str(keypath), rules))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def replicate(tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )
