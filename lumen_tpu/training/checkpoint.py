"""Training checkpoint/resume on orbax.

The reference is inference-only — its "checkpointing" is the on-disk model
cache (SURVEY.md §5 "Checkpoint/resume"); the training subsystem here adds
real state checkpointing: params + optimizer state + step, async-capable,
retention-managed, restored with the SAME shardings the trainer placed
(orbax records and re-applies the mesh layout, so resume works across
restarts of a multi-chip job).

Multi-host: orbax coordinates all processes internally; every process must
call save/restore collectively (do NOT gate on ``is_primary``).
"""

from __future__ import annotations

import logging
import os
from typing import Any

import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class TrainCheckpointer:
    """Save/restore ``{params, opt_state, step}`` bundles under a directory.

    Thin policy wrapper over ``ocp.CheckpointManager``: keep the newest
    ``max_to_keep`` steps, optionally keep one checkpoint every
    ``keep_period`` steps forever.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        keep_period: int | None = None,
        async_save: bool = True,
    ):
        self.directory = os.path.abspath(os.path.expanduser(directory))
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            keep_period=keep_period,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # -- save -------------------------------------------------------------

    def save(self, step: int, params: Any, opt_state: Any, wait: bool = False) -> None:
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
        )
        if wait:
            self._mgr.wait_until_finished()

    # -- restore ----------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(
        self, step: int | None = None, params_like: Any = None, opt_state_like: Any = None
    ) -> tuple[int, Any, Any]:
        """Restore (step, params, opt_state). Pass ``*_like`` abstract
        targets (e.g. the freshly-initialized state) so arrays come back
        with the trainer's shardings; without them orbax restores the
        layouts recorded at save time."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")

        def as_restore(tree):
            return ocp.args.StandardRestore(tree) if tree is not None else ocp.args.StandardRestore()

        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=as_restore(params_like),
                opt_state=as_restore(opt_state_like),
            ),
        )
        logger.info("restored checkpoint step %d from %s", step, self.directory)
        return step, out["params"], out["opt_state"]

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
