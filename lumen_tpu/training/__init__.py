"""Training: contrastive CLIP fine-tuning with sharded train steps.

The reference is inference-only (SURVEY.md: "NOT a training framework");
this subsystem is additive TPU-native capability: fine-tune the embedding
towers on a device mesh (DP batch sharding + Megatron TP on the transformer
blocks), with the same checkpoint conversion used for serving.
"""

from .checkpoint import TrainCheckpointer
from .clip_trainer import ClipTrainer, TrainConfig, contrastive_loss

__all__ = ["ClipTrainer", "TrainCheckpointer", "TrainConfig", "contrastive_loss"]
