"""Contrastive (CLIP) training on a device mesh.

A complete, minimal fine-tuning loop: InfoNCE over the global batch, AdamW
with weight-decay masking, parameters sharded by the tensor-parallel rules
and batches sharded over ``data`` — XLA inserts the gradient all-reduces.
``make_train_step`` is what the driver's multi-chip dry-run compiles.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.clip.modeling import CLIPConfig, CLIPModel
from ..parallel.sharding import TRANSFORMER_TP_RULES, keypath_str, shard_params
from ..runtime.mesh import DATA_AXIS

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 1e-5
    weight_decay: float = 0.2
    b1: float = 0.9
    b2: float = 0.98
    eps: float = 1e-6
    max_grad_norm: float = 1.0
    warmup_steps: int = 100
    total_steps: int = 10_000
    #: Rematerialize the forward during backward (``jax.checkpoint``) —
    #: trades ~1/3 more FLOPs for dropping activation HBM, the standard
    #: TPU lever when the per-chip batch is memory-bound. Matmul outputs
    #: without batch dims stay saved (XLA's recommended policy) so the MXU
    #: work isn't naively doubled.
    remat: bool = False


def contrastive_loss(img_emb: jax.Array, txt_emb: jax.Array, logit_scale: jax.Array) -> jax.Array:
    """Symmetric InfoNCE over the (global) batch; embeddings unit-norm.

    The temperature is clamped to ln(100) inside the loss as well as after
    each update, so even a corrupted checkpoint can't overflow exp()."""
    scale = jnp.exp(jnp.clip(logit_scale, max=jnp.log(100.0)))
    logits = scale * img_emb @ txt_emb.T  # [B, B]
    labels = jnp.arange(logits.shape[0])
    li = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    lt = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels).mean()
    return (li + lt) / 2


def _decay_mask(params) -> Any:
    """No weight decay on biases, norms, embeddings, or scalars."""

    def mask(keypath, leaf):
        path = keypath_str(keypath)
        if leaf.ndim <= 1:
            return False  # biases, norm scales, scalars
        return "embedding" not in path

    return jax.tree_util.tree_map_with_path(mask, params)


class ClipTrainer:
    def __init__(self, cfg: CLIPConfig, train_cfg: TrainConfig, mesh: Mesh):
        self.model = CLIPModel(cfg)
        self.cfg = cfg
        self.train_cfg = train_cfg
        self.mesh = mesh
        schedule = optax.warmup_cosine_decay_schedule(
            0.0,
            train_cfg.learning_rate,
            train_cfg.warmup_steps,
            train_cfg.total_steps,
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(train_cfg.max_grad_norm),
            optax.adamw(
                schedule,
                b1=train_cfg.b1,
                b2=train_cfg.b2,
                eps=train_cfg.eps,
                weight_decay=train_cfg.weight_decay,
                mask=_decay_mask,
            ),
        )

    # -- state ------------------------------------------------------------

    def init_state(self, rng: jax.Array):
        params = self.model.init(
            rng,
            jnp.zeros((1, self.cfg.image_size, self.cfg.image_size, 3), jnp.float32),
            jnp.zeros((1, self.cfg.context_length), jnp.int32),
        )["params"]
        params = self._place_params(params)
        opt_state = jax.jit(self.optimizer.init)(params)
        return params, opt_state

    def _place_params(self, params):
        return shard_params(params, self.mesh, TRANSFORMER_TP_RULES)

    # -- step -------------------------------------------------------------

    def make_train_step(self):
        """jitted (params, opt_state, batch) -> (params, opt_state, metrics).

        ``batch``: {"pixel_values": [B,H,W,3] float32, "input_ids": [B,S]
        int32} with B a multiple of the ``data`` axis size; batch arrays are
        sharded over ``data``, parameters keep their TP placement (donated).
        """
        model = self.model
        optimizer = self.optimizer
        data_sharding = NamedSharding(self.mesh, P(DATA_AXIS))

        def forward(params, pixel_values, input_ids):
            return model.apply({"params": params}, pixel_values, input_ids)

        if self.train_cfg.remat:
            forward = jax.checkpoint(
                forward,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )

        def loss_fn(params, batch):
            out = forward(params, batch["pixel_values"], batch["input_ids"])
            return contrastive_loss(
                out["image_embeds"], out["text_embeds"], params["logit_scale"]
            )

        def step(params, opt_state, batch):
            batch = jax.lax.with_sharding_constraint(
                batch, {"pixel_values": data_sharding, "input_ids": data_sharding}
            )
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # CLIP convention: clamp the temperature so exp() cannot
            # overflow during long fine-tunes (open_clip clamps to ln 100).
            params["logit_scale"] = jnp.clip(params["logit_scale"], max=jnp.log(100.0))
            gnorm = optax.global_norm(grads)
            return params, opt_state, {"loss": loss, "grad_norm": gnorm}

        return jax.jit(step, donate_argnums=(0, 1))
