"""OCR models in Flax: DBNet text detector + SVTR-style CTC recognizer.

The reference runs PaddleOCR ONNX graphs opaquely and implements the
pipeline logic around them
(``packages/lumen-ocr/src/lumen_ocr/backends/onnxrt_backend.py:43-633``).
Here both nets are explicit Flax modules designed for the MXU:

- :class:`DBNet` — differentiable-binarization detector: ResNet-ish
  backbone (strides 4/8/16/32), FPN fusion to stride 4, head with two 2x
  transposed convs back to full resolution, sigmoid probability map. Only
  the probability branch is needed at inference (the reference's
  postprocess consumes just the prob map, ``onnxrt_backend.py:380-432``).
- :class:`SVTRRecognizer` — attention-based text recognizer: conv patch
  embedding collapses height 48 -> 12 and width /4, global-mixing
  transformer blocks, mean-pool over height, per-timestep vocab logits for
  CTC decode (blank 0). Attention beats the CRNN's LSTM recurrence on TPU:
  every timestep is one big batched matmul instead of a sequential chain.

All BatchNorms run in inference mode (serving framework).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.attention import attention


@dataclass(frozen=True)
class DBNetConfig:
    width: int = 64  # backbone base width
    fpn_width: int = 256
    head_width: int = 64

    @classmethod
    def tiny(cls) -> "DBNetConfig":
        return cls(width=8, fpn_width=16, head_width=8)


class ConvBnAct(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    act: bool = True

    @nn.compact
    def __call__(self, x):
        # Explicit k//2 padding, not "SAME": identical for stride 1 (odd
        # kernels) but torch-compatible at stride 2 — see the IResNet
        # parity note in models/face/modeling.py (run_arch_parity.py).
        p = self.kernel // 2
        x = nn.Conv(
            self.features,
            (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            padding=((p, p), (p, p)),
            use_bias=False,
            name="conv",
            dtype=x.dtype,
        )(x)
        x = nn.BatchNorm(use_running_average=True, name="bn", dtype=x.dtype)(x)
        if self.act:
            x = nn.relu(x)
        return x


class ResBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBnAct(self.features, stride=self.stride, name="conv1")(x)
        y = ConvBnAct(self.features, act=False, name="conv2")(y)
        if self.stride != 1 or x.shape[-1] != self.features:
            residual = ConvBnAct(self.features, kernel=1, stride=self.stride, act=False, name="down")(x)
        return nn.relu(y + residual)


class DBNet(nn.Module):
    """[B, H, W, 3] normalized floats -> [B, H, W] probability map in [0, 1].

    H and W must be multiples of 32 (the manager's resize buckets guarantee
    it, mirroring the reference's x32 rounding at ``onnxrt_backend.py:
    338-378``).
    """

    cfg: DBNetConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        w = c.width
        x = ConvBnAct(w, stride=2, name="stem")(x)  # /2
        feats = []
        x = ResBlock(w, stride=2, name="stage1")(x)  # /4
        feats.append(x)
        x = ResBlock(w * 2, stride=2, name="stage2")(x)  # /8
        feats.append(x)
        x = ResBlock(w * 4, stride=2, name="stage3")(x)  # /16
        feats.append(x)
        x = ResBlock(w * 8, stride=2, name="stage4")(x)  # /32
        feats.append(x)
        # FPN: lateral 1x1 to fpn_width, top-down nearest-up add.
        laterals = [
            ConvBnAct(c.fpn_width, kernel=1, name=f"lateral{i}")(f) for i, f in enumerate(feats)
        ]
        for i in range(len(laterals) - 2, -1, -1):
            up = jax.image.resize(
                laterals[i + 1],
                laterals[i].shape[:3] + laterals[i + 1].shape[3:],
                method="nearest",
            )
            laterals[i] = laterals[i] + up
        # Smooth each level to fpn_width/4 and concat at stride 4.
        quarter = max(c.fpn_width // 4, 1)
        target = laterals[0].shape
        merged = []
        for i, lat in enumerate(laterals):
            p = ConvBnAct(quarter, name=f"smooth{i}")(lat)
            if p.shape[1:3] != target[1:3]:
                p = jax.image.resize(p, (p.shape[0],) + target[1:3] + (quarter,), method="nearest")
            merged.append(p)
        fuse = jnp.concatenate(merged, axis=-1)  # [B, H/4, W/4, 4*quarter]
        # DB probability head: conv + 2x (transposed conv x2) -> full res.
        h = ConvBnAct(c.head_width, name="head_conv")(fuse)
        h = nn.ConvTranspose(
            c.head_width, (2, 2), strides=(2, 2), use_bias=False, name="head_up1", dtype=h.dtype
        )(h)
        h = nn.BatchNorm(use_running_average=True, name="head_bn1", dtype=h.dtype)(h)
        h = nn.relu(h)
        h = nn.ConvTranspose(1, (2, 2), strides=(2, 2), name="head_up2", dtype=h.dtype)(h)
        return jax.nn.sigmoid(h[..., 0].astype(jnp.float32))


# -- recognizer -------------------------------------------------------------


@dataclass(frozen=True)
class SVTRConfig:
    vocab_size: int = 6625  # ppocr_keys_v1 (6623) + blank + space
    height: int = 48
    max_width: int = 640  # widest rec bucket; pos embed is sized for it
    width: int = 64  # embed dim
    heads: int = 4
    layers: int = 4
    hidden_act: str = "gelu"
    eps: float = 1e-6

    @classmethod
    def tiny(cls, vocab_size: int = 40) -> "SVTRConfig":
        return cls(vocab_size=vocab_size, height=32, max_width=64, width=16, heads=2, layers=1)


class _MixBlock(nn.Module):
    width: int
    heads: int
    hidden_act: str
    eps: float

    @nn.compact
    def __call__(self, x):
        # Pre-LN residual transformer block, global token mixing.
        b, s, w = x.shape
        h = nn.LayerNorm(epsilon=self.eps, name="ln1", dtype=x.dtype)(x)
        head_dim = w // self.heads
        dense = lambda name: nn.Dense(w, name=name, dtype=x.dtype)
        q = dense("q_proj")(h).reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)
        k = dense("k_proj")(h).reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)
        v = dense("v_proj")(h).reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)
        attn = attention(q, k, v).transpose(0, 2, 1, 3).reshape(b, s, w)
        x = x + nn.Dense(w, name="out_proj", dtype=x.dtype)(attn)
        h = nn.LayerNorm(epsilon=self.eps, name="ln2", dtype=x.dtype)(x)
        h = nn.Dense(w * 4, name="fc1", dtype=x.dtype)(h)
        h = jax.nn.gelu(h, approximate=True)
        return x + nn.Dense(w, name="fc2", dtype=x.dtype)(h)


class SVTRRecognizer(nn.Module):
    """[B, height, W, 3] normalized crops -> [B, W//4, vocab] CTC logits.

    Timestep count is W//4 (two stride-2 stages in the patch embed), so a
    320-wide crop yields 80 CTC steps — same order as the reference's
    recognizer (``_rec_preprocess`` height-48 resize, ``onnxrt_backend.py:
    557-594``).
    """

    cfg: SVTRConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        x = ConvBnAct(c.width // 2, stride=2, name="patch1")(x)  # H/2, W/2
        x = ConvBnAct(c.width, stride=2, name="patch2")(x)  # H/4, W/4
        b, h, w, d = x.shape
        tokens = x.reshape(b, h * w, d)
        # 2D positional grid sized for the widest bucket, sliced per actual
        # width so every bucket shares the same (prefix of) positions.
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, c.height // 4, c.max_width // 4, d),
        )
        tokens = tokens + pos[:, :h, :w].reshape(1, h * w, d).astype(tokens.dtype)
        for i in range(c.layers):
            tokens = _MixBlock(c.width, c.heads, c.hidden_act, c.eps, name=f"block{i}")(tokens)
        tokens = nn.LayerNorm(epsilon=c.eps, name="ln_out", dtype=tokens.dtype)(tokens)
        feat = tokens.reshape(b, h, w, d).mean(axis=1)  # pool height -> [B, T, d]
        return nn.Dense(c.vocab_size, name="ctc_head", dtype=feat.dtype)(feat)


# -- textline orientation classifier ----------------------------------------


@dataclass(frozen=True)
class ClsConfig:
    """PP-OCR ``cls`` model shape: 3x48x192 crops -> 2 classes (0, 180).
    The reference declares the slot but never runs it (``lumen_ocr/
    backends/onnxrt_backend.py:73`` keeps ``cls_sess = None``); here a
    native Flax classifier (or a real ``cls*.onnx`` via the bridge) backs
    the wire contract's ``use_angle_cls`` knob for real."""

    height: int = 48
    width: int = 192
    channels: tuple[int, ...] = (16, 32, 64)

    @classmethod
    def tiny(cls) -> "ClsConfig":
        return cls(height=32, width=64, channels=(8, 16))


class TextlineClassifier(nn.Module):
    """[B, H, W, 3] normalized crops -> [B, 2] orientation logits
    (class 0 = upright, class 1 = rotated 180deg)."""

    cfg: ClsConfig

    @nn.compact
    def __call__(self, x):
        for i, c in enumerate(self.cfg.channels):
            x = ConvBnAct(c, stride=2, name=f"conv{i}")(x)
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(2, name="head", dtype=x.dtype)(x)
