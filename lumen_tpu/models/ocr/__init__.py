"""OCR model family: DBNet detector + SVTR-style CTC recognizer."""

from .convert import convert_ocr_checkpoint, flatten_variables
from .manager import OcrManager, OcrResult, OcrSpec
from .modeling import (
    ClsConfig,
    DBNet,
    DBNetConfig,
    SVTRConfig,
    SVTRRecognizer,
    TextlineClassifier,
)
from .postprocess import (
    box_score_fast,
    boxes_from_prob_map,
    order_quad,
    rotate_crop,
    sorted_boxes,
    unclip_rect,
)

__all__ = [
    "OcrManager",
    "OcrResult",
    "OcrSpec",
    "DBNet",
    "DBNetConfig",
    "SVTRRecognizer",
    "SVTRConfig",
    "TextlineClassifier",
    "ClsConfig",
    "convert_ocr_checkpoint",
    "flatten_variables",
    "boxes_from_prob_map",
    "box_score_fast",
    "unclip_rect",
    "order_quad",
    "sorted_boxes",
    "rotate_crop",
]
