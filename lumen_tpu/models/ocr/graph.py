"""Graph-backed OCR models: real PP-OCR ONNX exports on TPU.

The reference serves PP-OCRv4/v5 det+rec ``.onnx`` files through onnxruntime
(``packages/lumen-ocr/src/lumen_ocr/backends/onnxrt_backend.py:43-633``).
Here the same files load through ``lumen_tpu.onnx_bridge`` into jittable XLA
programs, so ``ocr`` produces the same answers as the reference with the
same weights. File discovery follows the reference naming convention
(``_find_model_file``, ``onnxrt_backend.py:210-233``):
``detection.{precision}.onnx`` / ``recognition.{precision}.onnx`` with a
bare ``detection.onnx`` fallback, plus the stock PaddleOCR export names
(``det*.onnx`` / ``rec*.onnx``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from ...onnx_bridge import OnnxModule, find_onnx_exports

logger = logging.getLogger(__name__)


def find_onnx_models(model_dir: str, precision: str | None = None) -> dict[str, str]:
    """Locate det/rec/cls ``.onnx`` files (shared precision-chain discovery).
    Returns a dict with any of the keys ``detection`` / ``recognition`` /
    ``classification`` (PP-OCR textline orientation, ``cls*.onnx``)."""
    return find_onnx_exports(
        model_dir,
        {"detection": "det", "recognition": "rec", "classification": "cls"},
        precision,
    )


def _ends_in_softmax(module: OnnxModule, output_name: str) -> bool:
    """True when the graph output is produced by a Softmax node (PP-OCR rec
    exports emit probabilities; torch CTC heads emit logits)."""
    for node in module.graph.nodes:
        if output_name in node.outputs:
            return node.op_type in ("Softmax", "LogSoftmax")
    return False


@dataclass
class DBNetGraph:
    """Detection graph: [B,3,H,W] normalized floats -> [B,H,W] prob map.

    PP-OCR det exports return a [B,1,H,W] sigmoid probability map; the
    adapter squeezes the channel to match the native Flax DBNet contract
    (``modeling.py:82``).
    """

    module: OnnxModule

    @classmethod
    def from_path(cls, path: str) -> "DBNetGraph":
        return cls(module=OnnxModule.from_path(path))

    def __call__(self, params: dict, x_nchw):
        import jax.numpy as jnp

        out = jnp.asarray(self.module(params, {self.module.input_names[0]: x_nchw})[0])
        if out.ndim == 4:  # [B,1,H,W] or rarely [B,H,W,1]
            out = out[:, 0] if out.shape[1] == 1 else out[..., 0]
        return out.astype(jnp.float32)


@dataclass
class RecGraph:
    """Recognition graph: [B,3,H,W] normalized crops -> [B,T,V] CTC frames
    plus whether they are already softmax probabilities."""

    module: OnnxModule
    outputs_probs: bool

    @classmethod
    def from_path(cls, path: str) -> "RecGraph":
        module = OnnxModule.from_path(path)
        return cls(
            module=module,
            outputs_probs=_ends_in_softmax(module, module.output_names[0]),
        )

    def __call__(self, params: dict, x_nchw):
        import jax.numpy as jnp

        return jnp.asarray(self.module(params, {self.module.input_names[0]: x_nchw})[0])


@dataclass
class ClsGraph:
    """Textline-orientation graph: [B,3,H,W] normalized crops -> [B,2]
    probabilities over (0deg, 180deg). PP-OCR's ``cls`` model (the
    reference declares the slot but never executes it —
    ``onnxrt_backend.py:73`` keeps ``cls_sess = None``; here it runs)."""

    module: OnnxModule
    outputs_probs: bool

    @classmethod
    def from_path(cls, path: str) -> "ClsGraph":
        module = OnnxModule.from_path(path)
        return cls(
            module=module,
            outputs_probs=_ends_in_softmax(module, module.output_names[0]),
        )

    def __call__(self, params: dict, x_nchw):
        import jax

        import jax.numpy as jnp

        out = jnp.asarray(self.module(params, {self.module.input_names[0]: x_nchw})[0])
        if not self.outputs_probs:
            out = jax.nn.softmax(out.astype(jnp.float32), axis=-1)
        return out.astype(jnp.float32)
