"""Host-side OCR postprocessing (control-flow-heavy CV stays on CPU).

Semantics mirror the reference's DBNet postprocess and crop pipeline
(``packages/lumen-ocr/src/lumen_ocr/backends/onnxrt_backend.py:380-533``):
probability map -> contours -> minAreaRect quads -> region score gate ->
polygon unclip -> rescale to original coordinates; reading-order box sort;
perspective-warp crops with rot90 for vertical text.

One deliberate substitution: the reference offsets arbitrary contour
polygons with pyclipper/shapely (``_unclip:470-476``). This image has
neither, and the offset is only ever applied to a ``minAreaRect``
*rectangle*, for which the Minkowski offset is exact: grow both rect sides
by ``2 * d`` where ``d = area * unclip_ratio / perimeter``.
"""

from __future__ import annotations

import numpy as np


def boxes_from_prob_map(
    prob: np.ndarray,
    det_threshold: float = 0.3,
    box_threshold: float = 0.6,
    unclip_ratio: float = 1.5,
    max_candidates: int = 1000,
    min_size: float = 3.0,
    dest_hw: tuple[int, int] | None = None,
    scale: float = 1.0,
    pad_top: int = 0,
    pad_left: int = 0,
) -> list[tuple[np.ndarray, float]]:
    """Probability map [H, W] -> list of (quad [4, 2] float32, score).

    ``scale``/``pad_*`` undo the manager's letterbox so boxes land in
    original-image coordinates clipped to ``dest_hw`` (h, w).
    """
    import cv2

    binary = (prob > det_threshold).astype(np.uint8)
    contours, _ = cv2.findContours(binary, cv2.RETR_LIST, cv2.CHAIN_APPROX_SIMPLE)
    results: list[tuple[np.ndarray, float]] = []
    for contour in contours[:max_candidates]:
        rect = cv2.minAreaRect(contour)
        if min(rect[1]) < min_size:
            continue
        score = box_score_fast(prob, cv2.boxPoints(rect))
        if score < box_threshold:
            continue
        rect = unclip_rect(rect, unclip_ratio)
        if min(rect[1]) < min_size + 2:
            continue
        box = order_quad(cv2.boxPoints(rect))
        # Undo letterbox: subtract padding, divide by scale.
        box[:, 0] = (box[:, 0] - pad_left) / scale
        box[:, 1] = (box[:, 1] - pad_top) / scale
        if dest_hw is not None:
            h, w = dest_hw
            box[:, 0] = np.clip(box[:, 0], 0, w - 1)
            box[:, 1] = np.clip(box[:, 1], 0, h - 1)
        results.append((box.astype(np.float32), float(score)))
    return results


def box_score_fast(prob: np.ndarray, quad: np.ndarray) -> float:
    """Mean probability inside the quad (reference ``_box_score_fast``)."""
    import cv2

    h, w = prob.shape
    xs = np.clip(np.floor(quad[:, 0]).astype(int), 0, w - 1)
    ys = np.clip(np.floor(quad[:, 1]).astype(int), 0, h - 1)
    xmin, xmax = xs.min(), min(int(np.ceil(quad[:, 0].max())), w - 1)
    ymin, ymax = ys.min(), min(int(np.ceil(quad[:, 1].max())), h - 1)
    mask = np.zeros((ymax - ymin + 1, xmax - xmin + 1), np.uint8)
    shifted = quad.copy()
    shifted[:, 0] -= xmin
    shifted[:, 1] -= ymin
    cv2.fillPoly(mask, [np.round(shifted).astype(np.int32)], 1)
    region = prob[ymin : ymax + 1, xmin : xmax + 1]
    if mask.sum() == 0:
        return 0.0
    return float(cv2.mean(region, mask)[0])


def unclip_rect(rect, unclip_ratio: float):
    """Offset a cv2 RotatedRect outward by ``d = area * ratio / perimeter``
    (exact Minkowski offset for rectangles; see module docstring)."""
    (cx, cy), (rw, rh), angle = rect
    area = rw * rh
    perimeter = 2.0 * (rw + rh)
    if perimeter <= 0:
        return rect
    d = area * unclip_ratio / perimeter
    return ((cx, cy), (rw + 2.0 * d, rh + 2.0 * d), angle)


def order_quad(pts: np.ndarray) -> np.ndarray:
    """Order 4 points clockwise from top-left (reference ``_get_mini_boxes``
    index juggling, ``onnxrt_backend.py:434-453``)."""
    pts = pts[np.argsort(pts[:, 0])]
    left, right = pts[:2], pts[2:]
    left = left[np.argsort(left[:, 1])]  # tl, bl
    right = right[np.argsort(right[:, 1])]  # tr, br
    return np.array([left[0], right[0], right[1], left[1]], dtype=np.float32)


def sorted_boxes(boxes: list[np.ndarray], line_tolerance: float = 10.0) -> list[int]:
    """Reading order: top-down, then left-right within a ~line_tolerance px
    band (reference ``_sorted_boxes:478-494``). Returns index permutation."""
    order = sorted(range(len(boxes)), key=lambda i: (boxes[i][0][1], boxes[i][0][0]))
    for j in range(len(order) - 1):
        for k in range(j, -1, -1):
            a, b = boxes[order[k]], boxes[order[k + 1]]
            if abs(b[0][1] - a[0][1]) < line_tolerance and b[0][0] < a[0][0]:
                order[k], order[k + 1] = order[k + 1], order[k]
            else:
                break
    return order


def rotate_crop(img: np.ndarray, quad: np.ndarray) -> np.ndarray:
    """Perspective-warp the quad to an upright crop; rotate 90° when the
    crop is tall (vertical text), matching ``_get_rotate_crop_image``."""
    import cv2

    w = int(max(np.linalg.norm(quad[0] - quad[1]), np.linalg.norm(quad[2] - quad[3])))
    h = int(max(np.linalg.norm(quad[0] - quad[3]), np.linalg.norm(quad[1] - quad[2])))
    w, h = max(w, 1), max(h, 1)
    dst = np.array([[0, 0], [w, 0], [w, h], [0, h]], np.float32)
    matrix = cv2.getPerspectiveTransform(quad.astype(np.float32), dst)
    crop = cv2.warpPerspective(
        img, matrix, (w, h), borderMode=cv2.BORDER_REPLICATE, flags=cv2.INTER_CUBIC
    )
    if h * 1.0 / w >= 1.5:
        crop = np.rot90(crop)
    return crop
