"""OCR checkpoint conversion.

Native lumen-tpu checkpoints (``params/...`` / ``batch_stats/...`` flat
safetensors) load directly. Paddle-format checkpoints have no torch-style
state dict to convert mechanically — the reference consumes them as opaque
ONNX graphs (``lumen_ocr/backends/onnxrt_backend.py``) — so non-native
files get a clear re-export error instead of a silent wrong-weights load.
"""

from __future__ import annotations

import numpy as np

from ...runtime.weights import (
    WeightLoadError,
    flatten_variables,
    is_native_checkpoint,
    split_collections,
)

__all__ = ["convert_ocr_checkpoint", "flatten_variables"]


def convert_ocr_checkpoint(state: dict[str, np.ndarray]) -> dict:
    """-> {'params': ..., 'batch_stats': ...} variable collections."""
    if is_native_checkpoint(state):
        return split_collections(state)
    raise WeightLoadError(
        "no conversion rules for non-native OCR checkpoint "
        f"(keys like {sorted(state)[:4]}); re-export in the native format "
        "(flatten_variables + safetensors)"
    )
