"""OCR pipeline manager: detect text regions -> crop -> recognize on TPU.

Business logic of the reference's ``OcrModelManager`` + ONNX backend
(``packages/lumen-ocr/src/lumen_ocr/general_ocr/ocr_model.py:27-214``,
``backends/onnxrt_backend.py:43-633``) restructured for XLA:

- the reference resizes each image to an arbitrary x32 multiple
  (``limit_side_len=960``), which on TPU would compile a program per unique
  shape. Here detection letterboxes into a small set of square **static
  buckets** (default 320/640/960) — one compiled program per bucket;
- recognition crops are height-``rec_h``, padded into **width buckets** and
  run as one batched device call per bucket (the reference loops crops one
  by one through the recognizer);
- CTC argmax + per-step confidence run on device (`ops.ctc`), the
  collapse-to-string on host;
- contours/unclip/warps stay host-side cv2 (control-flow CV, not MXU work).
"""

from __future__ import annotations

import copy
import logging
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...core.model_info import dataclass_from_extra, load_model_info
from ...utils import telemetry
from ...ops.ctc import ctc_collapse_rows, ctc_greedy_device, load_ctc_vocab
from ...ops.image import letterbox_numpy
from ...runtime.batcher import bucket_for
from ...runtime.decode_pool import get_decode_pool
from ...runtime.quarantine import guarded_key
from ...runtime.result_cache import get_result_cache, make_namespace
from ...runtime.policy import get_policy
from ...runtime.weights import load_safetensors
from .convert import convert_ocr_checkpoint
from .modeling import (
    ClsConfig,
    DBNet,
    DBNetConfig,
    SVTRConfig,
    SVTRRecognizer,
    TextlineClassifier,
)
from .postprocess import boxes_from_prob_map, rotate_crop, sorted_boxes

logger = logging.getLogger(__name__)

# PaddleOCR preprocessing conventions (reference defaults at
# ``onnxrt_backend.py:242-268``): detection uses ImageNet stats, the
# recognizer uses symmetric (x/255 - 0.5) / 0.5.
DET_MEAN = (0.485, 0.456, 0.406)
DET_STD = (0.229, 0.224, 0.225)
REC_MEAN = (0.5, 0.5, 0.5)
REC_STD = (0.5, 0.5, 0.5)


class _DirectLane:
    """Minimal dispatch unit standing in for a batcher/engine in the OCR
    family's :class:`~lumen_tpu.runtime.fleet.EngineFleet`. OCR dispatches
    ragged det/rec shapes directly (no queue to measure, nothing to
    close), so the unit exists to give the family a chip claim in the
    autopilot's ledger and a ``device:{name}`` duty meter the scale loop
    can read. A 1-unit fleet can never be parked (the floor of 1), which
    is the honest posture until the ragged-batching rework gives OCR real
    replicas."""

    def __init__(self, name: str):
        self.name = name

    def load(self) -> int:
        return 0

    def close(self) -> None:
        return None


@dataclass
class OcrResult:
    box: np.ndarray  # [4, 2] quad, original-image coords
    text: str
    confidence: float


@dataclass
class OcrSpec:
    """Pipeline knobs; defaults match the reference's det/rec configs.
    Overridable via model_info ``extra_metadata.ocr``."""

    det_buckets: tuple[int, ...] = (320, 640, 960)
    det_threshold: float = 0.3
    box_threshold: float = 0.6
    unclip_ratio: float = 1.5
    max_candidates: int = 1000
    min_size: float = 3.0
    rec_height: int = 48
    rec_width_buckets: tuple[int, ...] = (80, 160, 320, 640)
    rec_batch_buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32)
    det_mean: tuple[float, ...] = DET_MEAN
    det_std: tuple[float, ...] = DET_STD
    rec_mean: tuple[float, ...] = REC_MEAN
    rec_std: tuple[float, ...] = REC_STD
    rec_threshold: float = 0.5
    drop_rec_below_threshold: bool = True
    charset_file: str = "ppocr_keys_v1.txt"
    use_space_char: bool = True
    # Textline-orientation classifier (PP-OCR ``cls``): flip a crop 180deg
    # only above this confidence (PaddleOCR's cls_thresh default).
    cls_thresh: float = 0.9
    cls_height: int = 48
    cls_width: int = 192

    @classmethod
    def from_extra(cls, extra: dict | None) -> "OcrSpec":
        return dataclass_from_extra(
            cls,
            extra,
            tuple_keys=(
                "det_buckets",
                "rec_width_buckets",
                "rec_batch_buckets",
                "det_mean",
                "det_std",
                "rec_mean",
                "rec_std",
            ),
        )


class OcrManager:
    def __init__(
        self,
        model_dir: str,
        dtype: str = "bfloat16",
        batch_size: int = 8,
        det_cfg: DBNetConfig | None = None,
        rec_cfg: SVTRConfig | None = None,
        warmup: bool = False,
        allow_random_init: bool = False,
        det_buckets: tuple[int, ...] | None = None,
    ):
        self.model_dir = model_dir
        self.info = load_model_info(model_dir)
        self.model_id = self.info.name
        self.spec = OcrSpec.from_extra(self.info.extra("ocr"))
        if det_buckets:  # deployment preset overrides the manifest default
            self.spec.det_buckets = tuple(sorted(det_buckets))
        self.policy = get_policy(dtype)
        self.warmup = warmup
        self.batch_size = batch_size
        self.vocab = self._load_vocab()
        self.det_cfg = det_cfg or self._det_cfg_from_info()
        self.rec_cfg = rec_cfg or self._rec_cfg_from_info()
        self.detector = DBNet(self.det_cfg)
        self.recognizer = SVTRRecognizer(self.rec_cfg)
        self.allow_random_init = allow_random_init
        self._initialized = False

    def _load_vocab(self) -> list[str]:
        path = os.path.join(self.model_dir, self.spec.charset_file)
        if os.path.exists(path):
            return load_ctc_vocab(path, self.spec.use_space_char)
        # Printable-ASCII fallback so tests and charset-less dirs still run.
        logger.warning("charset file %s missing; using ASCII fallback vocab", path)
        chars = [chr(c) for c in range(33, 127)]
        return ["<blank>"] + chars + ([" "] if self.spec.use_space_char else [])

    def _det_cfg_from_info(self) -> DBNetConfig:
        return dataclass_from_extra(DBNetConfig, self.info.extra("detector"))

    def _rec_cfg_from_info(self) -> SVTRConfig:
        return dataclass_from_extra(
            SVTRConfig,
            self.info.extra("recognizer"),
            defaults={
                "vocab_size": len(self.vocab),
                "height": self.spec.rec_height,
                "max_width": max(self.spec.rec_width_buckets),
            },
        )

    # -- init -------------------------------------------------------------

    def _load_variables(self, filename: str, module, example_shape: tuple, kind: str):
        path = os.path.join(self.model_dir, filename)
        if os.path.exists(path):
            variables = convert_ocr_checkpoint(load_safetensors(path))
        elif self.allow_random_init:
            logger.warning("%s missing in %s; RANDOM INIT (allow_random_init=True, tests only)", filename, self.model_dir)
            variables = dict(module.init(jax.random.PRNGKey(0), jnp.zeros(example_shape, jnp.float32)))
        else:
            # A missing checkpoint must hard-fail: serving random weights
            # returns confident garbage with HTTP 200s (round-1 verdict).
            raise FileNotFoundError(
                f"no {kind} weights in {self.model_dir}: expected {filename} "
                f"or a {kind} .onnx graph; pass allow_random_init=True only in tests"
            )
        variables["params"] = self.policy.cast_params(variables["params"])
        if "batch_stats" in variables:
            variables["batch_stats"] = self.policy.cast_params(variables["batch_stats"])
        return jax.device_put(variables)

    def initialize(self) -> None:
        if self._initialized:
            return
        s = self.spec
        compute = self.policy.compute_dtype
        det_mean, det_std = jnp.asarray(s.det_mean), jnp.asarray(s.det_std)
        rec_mean, rec_std = jnp.asarray(s.rec_mean), jnp.asarray(s.rec_std)

        from .graph import DBNetGraph, RecGraph, find_onnx_models

        onnx_models = find_onnx_models(self.model_dir)

        if "detection" in onnx_models:
            # Real PP-OCR det export: run the actual DBNet graph via the
            # ONNX->JAX bridge (reference runs the same file through
            # onnxruntime, ``onnxrt_backend.py:122-126``).
            graph_det = DBNetGraph.from_path(onnx_models["detection"])
            self.det_vars = jax.device_put(dict(graph_det.module.params))
            logger.info("ocr detector: DBNet graph %s (%d MB params)",
                        onnx_models["detection"], graph_det.module.param_bytes() >> 20)
            graph_det.module.release_weights()  # device holds the weights now

            @jax.jit
            def run_detector(variables, images_u8):
                x = (images_u8.astype(jnp.float32) / 255.0 - det_mean) / det_std
                return graph_det(variables, x.transpose(0, 3, 1, 2))

        else:
            self.det_vars = self._load_variables(
                "detection.safetensors",
                self.detector,
                (1, s.det_buckets[0], s.det_buckets[0], 3),
                "detection",
            )

            @jax.jit
            def run_detector(variables, images_u8):
                x = (images_u8.astype(jnp.float32) / 255.0 - det_mean) / det_std
                return self.detector.apply(variables, x.astype(compute))

        def _mask_padding(ids, conf, crop_w: int, t: int, widths):
            # Mask timesteps past each crop's true width (padding region):
            # force blank id 0 / confidence 1 so collapse ignores them.
            downsample = max(crop_w // t, 1)
            steps = jnp.arange(t)[None, :] * downsample
            valid = steps < widths[:, None]
            return jnp.where(valid, ids, 0), jnp.where(valid, conf, 1.0)

        if "recognition" in onnx_models:
            graph_rec = RecGraph.from_path(onnx_models["recognition"])
            self.rec_vars = jax.device_put(dict(graph_rec.module.params))
            graph_rec.module.release_weights()  # device holds the weights now
            logger.info("ocr recognizer: graph %s (softmax output: %s)",
                        onnx_models["recognition"], graph_rec.outputs_probs)

            @jax.jit
            def run_recognizer(variables, crops_u8, widths):
                x = (crops_u8.astype(jnp.float32) / 255.0 - rec_mean) / rec_std
                frames = graph_rec(variables, x.transpose(0, 3, 1, 2))
                if graph_rec.outputs_probs:
                    # Graph already ends in Softmax — re-softmaxing would
                    # flatten confidences (argmax unchanged, conf wrong).
                    probs = frames.astype(jnp.float32)
                    ids, conf = jnp.argmax(probs, -1), jnp.max(probs, -1)
                else:
                    ids, conf = ctc_greedy_device(frames)
                return _mask_padding(ids, conf, crops_u8.shape[2], frames.shape[1], widths)

        else:
            self.rec_vars = self._load_variables(
                "recognition.safetensors",
                self.recognizer,
                (1, self.rec_cfg.height, s.rec_width_buckets[0], 3),
                "recognition",
            )

            @jax.jit
            def run_recognizer(variables, crops_u8, widths):
                x = (crops_u8.astype(jnp.float32) / 255.0 - rec_mean) / rec_std
                logits = self.recognizer.apply(variables, x.astype(compute))
                ids, conf = ctc_greedy_device(logits)
                return _mask_padding(ids, conf, crops_u8.shape[2], logits.shape[1], widths)

        # Optional textline-orientation classifier. Unlike det/rec, a
        # missing cls is NOT an error: the backend contract marks it
        # optional ("if available", reference ``lumen_ocr/backends/
        # base.py:63-136``) and the reference itself never executes one
        # (``onnxrt_backend.py:73``). Precedence mirrors det/rec: real
        # ONNX export first, then a native Flax checkpoint.
        run_cls = None
        if "classification" in onnx_models:
            from .graph import ClsGraph

            graph_cls = ClsGraph.from_path(onnx_models["classification"])
            self.cls_vars = jax.device_put(dict(graph_cls.module.params))
            graph_cls.module.release_weights()
            self._cls_hw = (s.cls_height, s.cls_width)
            logger.info("ocr cls: graph %s", onnx_models["classification"])

            @jax.jit
            def run_cls(variables, crops_u8):
                x = (crops_u8.astype(jnp.float32) / 255.0 - rec_mean) / rec_std
                return graph_cls(variables, x.transpose(0, 3, 1, 2))

        elif os.path.exists(os.path.join(self.model_dir, "classification.safetensors")):
            self.cls_cfg = dataclass_from_extra(ClsConfig, self.info.extra("classifier"))
            self.classifier = TextlineClassifier(self.cls_cfg)
            self.cls_vars = self._load_variables(
                "classification.safetensors",
                self.classifier,
                (1, self.cls_cfg.height, self.cls_cfg.width, 3),
                "classification",
            )
            self._cls_hw = (self.cls_cfg.height, self.cls_cfg.width)

            @jax.jit
            def run_cls(variables, crops_u8):
                x = (crops_u8.astype(jnp.float32) / 255.0 - rec_mean) / rec_std
                logits = self.classifier.apply(variables, x.astype(compute))
                return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        self._run_detector = run_detector
        self._run_recognizer = run_recognizer
        self._run_cls = run_cls
        if self.warmup:
            import time as _time

            t0 = _time.perf_counter()
            # Compile the common shapes up front: every det bucket, plus the
            # smallest rec width x batch bucket (the long tail of rec shapes
            # compiles on demand).
            for b in s.det_buckets:
                np.asarray(self._run_detector(self.det_vars, jnp.zeros((1, b, b, 3), jnp.uint8)))
            rw, rb = min(s.rec_width_buckets), min(s.rec_batch_buckets)
            jax.tree_util.tree_map(
                np.asarray,
                self._run_recognizer(
                    self.rec_vars,
                    jnp.zeros((rb, self.rec_cfg.height, rw, 3), jnp.uint8),
                    jnp.zeros((rb,), jnp.int32),
                ),
            )
            logger.info("ocr warmup in %.1fs", _time.perf_counter() - t0)
        # Chip-ledger + duty coverage: a 1-unit engine fleet so the
        # autopilot's scale loop sees OCR's device claim and duty like
        # every other family (it can never park the last unit — the
        # family is counted, not scalable, until OCR grows replicas).
        from ...runtime.fleet import EngineFleet

        self._lane = _DirectLane(self.info.name)
        telemetry.set_capacity(f"device:{self._lane.name}", 1.0, union=True)
        self._fleet = EngineFleet(
            self.info.name, [self._lane], devices_per_replica=1
        )
        self._initialized = True
        logger.info(
            "ocr manager ready: %s (det buckets %s, rec h=%d, vocab %d)",
            self.model_id, s.det_buckets, self.rec_cfg.height, len(self.vocab),
        )

    def close(self) -> None:
        fleet = getattr(self, "_fleet", None)
        if fleet is not None:
            fleet.close()
            self._fleet = None
        self._initialized = False

    def topology(self) -> dict[str, str]:
        """Device topology for the capability ``extra``. OCR dispatches
        ragged det/rec shapes directly (no MicroBatcher, no mesh), so it
        reports a single replica on the default device — a fleet for this
        family needs the ragged-batching rework first (ROADMAP item 2's
        paged/ragged lane is the natural vehicle)."""
        from ...runtime.fleet import topology_extra

        return topology_extra(None)

    # -- detection --------------------------------------------------------

    def detect(
        self,
        img: np.ndarray,
        det_threshold: float | None = None,
        box_threshold: float | None = None,
        unclip_ratio: float | None = None,
    ) -> list[tuple[np.ndarray, float]]:
        """[H, W, 3] RGB -> list of (quad [4, 2], det score), reading order."""
        self._ensure_ready()
        s = self.spec
        h, w = img.shape[:2]
        bucket = bucket_for(max(h, w), list(s.det_buckets))
        boxed, scale, pad_top, pad_left = letterbox_numpy(img, bucket)
        # One jax.device_get per detector call (np.asarray on a jax array
        # is also one transfer, but device_get is the batched-fetch idiom
        # the clip/face fetch lane uses — and returns host numpy for the
        # cv2 postprocess either way).
        t0 = time.monotonic()
        prob = jax.device_get(self._run_detector(self.det_vars, boxed[None]))[0]
        telemetry.busy(f"device:{self.info.name}", t0, time.monotonic())
        return self.boxes_from_det_output(
            prob,
            image_hw=(h, w),
            scale=scale,
            pad_top=pad_top,
            pad_left=pad_left,
            det_threshold=det_threshold,
            box_threshold=box_threshold,
            unclip_ratio=unclip_ratio,
        )

    def boxes_from_det_output(
        self,
        prob: np.ndarray,
        *,
        image_hw: tuple[int, int],
        scale: float,
        pad_top: int,
        pad_left: int,
        det_threshold: float | None = None,
        box_threshold: float | None = None,
        unclip_ratio: float | None = None,
    ) -> list[tuple[np.ndarray, float]]:
        """Host half of detection: prob map -> ordered (quad, score) list.
        Shared by the per-request path above and the batch-ingest pipeline."""
        s = self.spec
        found = boxes_from_prob_map(
            prob,
            det_threshold=s.det_threshold if det_threshold is None else det_threshold,
            box_threshold=s.box_threshold if box_threshold is None else box_threshold,
            unclip_ratio=s.unclip_ratio if unclip_ratio is None else unclip_ratio,
            max_candidates=s.max_candidates,
            min_size=s.min_size,
            dest_hw=image_hw,
            scale=scale,
            pad_top=pad_top,
            pad_left=pad_left,
        )
        if not found:
            return []
        order = sorted_boxes([b for b, _ in found])
        return [found[i] for i in order]

    # -- recognition ------------------------------------------------------

    def recognize_crops(self, crops: list[np.ndarray]) -> list[tuple[str, float]]:
        """Height-``rec_h`` resize, width-bucket pad, one device call per
        bucket group, device CTC argmax, host collapse."""
        self._ensure_ready()
        import cv2

        rec_h = self.rec_cfg.height
        prepared: list[tuple[int, np.ndarray, int]] = []  # (bucket, padded, width)
        for crop in crops:
            ch, cw = crop.shape[:2]
            new_w = max(int(round(cw * rec_h / max(ch, 1))), 1)
            bucket = bucket_for(new_w, list(self.spec.rec_width_buckets))
            new_w = min(new_w, bucket)
            resized = cv2.resize(crop, (new_w, rec_h), interpolation=cv2.INTER_LINEAR)
            padded = np.zeros((rec_h, bucket, 3), np.uint8)
            padded[:, :new_w] = resized
            prepared.append((bucket, padded, new_w))
        results: list[tuple[str, float] | None] = [None] * len(crops)
        by_bucket: dict[int, list[int]] = {}
        for i, (bucket, _, _) in enumerate(prepared):
            by_bucket.setdefault(bucket, []).append(i)
        max_bb = max(self.spec.rec_batch_buckets)
        for bucket, idxs in by_bucket.items():
            # Pad the batch dim to a static bucket too — otherwise every
            # distinct crop count compiles a fresh program. Padding rows
            # carry width 0, so every timestep masks to blank.
            for start in range(0, len(idxs), max_bb):
                chunk = idxs[start : start + max_bb]
                bb = bucket_for(len(chunk), list(self.spec.rec_batch_buckets))
                batch = np.zeros((bb, self.rec_cfg.height, bucket, 3), np.uint8)
                widths = np.zeros((bb,), np.int32)
                for row, i in enumerate(chunk):
                    batch[row] = prepared[i][1]
                    widths[row] = prepared[i][2]
                # ONE blocking device->host transfer for the whole (ids,
                # conf) result tree — the old per-leaf np.asarray pair
                # round-tripped the device once per leaf on the rec hot
                # path (same fix PR 2 applied to the clip/face fetch lane).
                t0 = time.monotonic()
                ids, conf = jax.device_get(
                    self._run_recognizer(self.rec_vars, batch, widths)
                )
                telemetry.busy(f"device:{self.info.name}", t0, time.monotonic())
                # Slice off batch-bucket padding rows before the host collapse.
                ids = ids[: len(chunk)]
                conf = conf[: len(chunk)]
                collapsed = ctc_collapse_rows(ids, conf, self.vocab)
                for row, i in enumerate(chunk):
                    results[i] = collapsed[row]
        return results  # type: ignore[return-value]

    # -- textline orientation ---------------------------------------------

    @property
    def has_angle_cls(self) -> bool:
        return getattr(self, "_run_cls", None) is not None

    def classify_angles(self, crops: list[np.ndarray]) -> list[bool]:
        """True where a crop is upside-down (class 180 above ``cls_thresh``).
        One batched device call on letterboxed ``cls_height x cls_width``
        crops — the PP-OCR cls contract the reference declares but never
        executes (``onnxrt_backend.py:73``)."""
        self._ensure_ready()
        if not self.has_angle_cls or not crops:
            return [False] * len(crops)
        import cv2

        h, w = self._cls_hw
        prepared = np.zeros((len(crops), h, w, 3), np.uint8)
        for i, crop in enumerate(crops):
            ch, cw = crop.shape[:2]
            new_w = min(max(int(round(cw * h / max(ch, 1))), 1), w)
            prepared[i, :, :new_w] = cv2.resize(
                crop, (new_w, h), interpolation=cv2.INTER_LINEAR
            )
        # Batch-bucket like recognize_crops: without padding to a static
        # bucket every distinct crop count compiles a fresh XLA program.
        # Padding rows are all-zero crops; their predictions are discarded.
        probs = np.zeros((len(crops), 2), np.float32)
        max_bb = max(self.spec.rec_batch_buckets)
        for start in range(0, len(crops), max_bb):
            chunk = prepared[start : start + max_bb]
            bb = bucket_for(len(chunk), list(self.spec.rec_batch_buckets))
            batch = np.zeros((bb, h, w, 3), np.uint8)
            batch[: len(chunk)] = chunk
            out = jax.device_get(self._run_cls(self.cls_vars, batch))
            probs[start : start + len(chunk)] = out[: len(chunk)]
        # PaddleOCR semantics: rotate only when 180 wins the argmax AND
        # clears cls_thresh — below it, leaving the crop alone is safer.
        return [bool(p.argmax() == 1 and p[1] > self.spec.cls_thresh) for p in probs]

    # -- end-to-end -------------------------------------------------------

    def _cache_ns(self, task: str) -> str:
        """Result-cache namespace, dtype-qualified (see
        :func:`~lumen_tpu.runtime.result_cache.make_namespace`)."""
        return make_namespace(
            "ocr", task, self.model_id, self.info.version,
            jnp.dtype(self.policy.compute_dtype).name,
        )

    def predict(
        self,
        image_bytes: bytes,
        det_threshold: float | None = None,
        rec_threshold: float | None = None,
        box_threshold: float | None = None,
        unclip_ratio: float | None = None,
        use_angle_cls: bool = False,
    ) -> list[OcrResult]:
        """Full pipeline on raw image bytes (reference ``predict`` contract,
        ``lumen_ocr/backends/base.py:63-136``, including ``use_angle_cls``).
        Content-addressed result cache first — the sha256 runs on the raw
        payload, so a repeated page skips decode, BOTH device programs and
        all the contour/warp CV work; concurrent identical requests
        coalesce onto one flight. On a miss, decode runs on the shared
        pool, keeping the gRPC handler thread out of CPU-bound image
        work."""
        self._ensure_ready()
        options = {
            "det_threshold": det_threshold,
            "rec_threshold": rec_threshold,
            "box_threshold": box_threshold,
            "unclip_ratio": unclip_ratio,
            "use_angle_cls": use_angle_cls,
        }
        payload = bytes(image_bytes)
        ns = self._cache_ns("predict")
        # Quarantine gate on the same content address the cache uses: a
        # page that previously broke the OCR path (decode bomb, pathological
        # contour explosion isolated by the ingest salvage) is rejected
        # before the decode pool and both device programs.
        key = guarded_key(ns, options, payload)
        return get_result_cache().get_or_compute(
            ns,
            options,
            payload,
            lambda: self._predict_uncached(
                image_bytes, det_threshold, rec_threshold, box_threshold,
                unclip_ratio, use_angle_cls,
            ),
            clone=copy.deepcopy,
            key=key,
        )

    def _predict_uncached(
        self,
        image_bytes: bytes,
        det_threshold: float | None,
        rec_threshold: float | None,
        box_threshold: float | None,
        unclip_ratio: float | None,
        use_angle_cls: bool,
    ) -> list[OcrResult]:
        decoded = get_decode_pool().run_decode("decode", image_bytes, {"color": "rgb"})
        try:
            img = decoded.array
            boxes = self.detect(
                img,
                det_threshold=det_threshold,
                box_threshold=box_threshold,
                unclip_ratio=unclip_ratio,
            )
            if not boxes:
                return []
            return self.recognize_boxes(
                img, boxes, rec_threshold=rec_threshold, use_angle_cls=use_angle_cls
            )
        finally:
            decoded.release()

    def predict_tensor(
        self,
        pixels: np.ndarray,
        raw: bytes | None = None,
        det_threshold: float | None = None,
        rec_threshold: float | None = None,
        box_threshold: float | None = None,
        unclip_ratio: float | None = None,
        use_angle_cls: bool = False,
    ) -> list[OcrResult]:
        """Pre-decoded RGB tensor (the ``tensor/raw`` wire path): the full
        OCR pipeline with ZERO decode-pool hops. Cached on the raw pixel
        buffer (one sha256) under a tensor-qualified namespace — raw
        pixels and encoded bytes of one page must never answer for each
        other."""
        self._ensure_ready()
        if pixels.dtype != np.uint8 or pixels.ndim != 3 or pixels.shape[2] != 3:
            raise ValueError(
                "tensor input must be a uint8 HWC RGB image (H, W, 3); "
                f"got {pixels.dtype} {tuple(pixels.shape)}"
            )
        pixels = np.ascontiguousarray(pixels)
        options = {
            "det_threshold": det_threshold,
            "rec_threshold": rec_threshold,
            "box_threshold": box_threshold,
            "unclip_ratio": unclip_ratio,
            "use_angle_cls": use_angle_cls,
        }
        payload = raw if raw is not None else pixels.tobytes()
        ns = self._cache_ns("predict_tensor")
        key = guarded_key(ns, options, payload)

        def _compute() -> list[OcrResult]:
            boxes = self.detect(
                pixels,
                det_threshold=det_threshold,
                box_threshold=box_threshold,
                unclip_ratio=unclip_ratio,
            )
            if not boxes:
                return []
            return self.recognize_boxes(
                pixels, boxes, rec_threshold=rec_threshold,
                use_angle_cls=use_angle_cls,
            )

        return get_result_cache().get_or_compute(
            ns, options, payload, _compute, clone=copy.deepcopy, key=key
        )

    def recognize_boxes(
        self,
        img: np.ndarray,
        boxes: list[tuple[np.ndarray, float]],
        rec_threshold: float | None = None,
        use_angle_cls: bool = False,
    ) -> list[OcrResult]:
        """Crop each detected quad, recognize, and apply the rec-confidence
        drop policy. Shared with the batch-ingest pipeline."""
        crops = [rotate_crop(img, quad) for quad, _ in boxes]
        if use_angle_cls:
            if self.has_angle_cls:
                flips = self.classify_angles(crops)
                crops = [
                    np.ascontiguousarray(c[::-1, ::-1]) if f else c
                    for c, f in zip(crops, flips)
                ]
            else:
                # Contract says "if available" — absent model degrades to
                # a no-op (exactly the reference's permanent behavior).
                logger.debug("use_angle_cls requested but no cls model in %s", self.model_dir)
        texts = self.recognize_crops(crops)
        thr = self.spec.rec_threshold if rec_threshold is None else rec_threshold
        out: list[OcrResult] = []
        for (quad, _), (text, conf) in zip(boxes, texts):
            if self.spec.drop_rec_below_threshold and (not text or conf < thr):
                continue
            out.append(OcrResult(box=quad, text=text, confidence=conf))
        return out

    def _ensure_ready(self) -> None:
        if not self._initialized:
            raise RuntimeError("OcrManager.initialize() not called")
