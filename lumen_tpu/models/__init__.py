"""Model families: clip, face, ocr, vlm."""
