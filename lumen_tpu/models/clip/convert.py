"""Checkpoint conversion: HF transformers CLIP / OpenCLIP -> Flax params.

Covers the two checkpoint families the reference loads (HF ``CLIPModel`` in
``torch_backend.py:340-393``, OpenCLIP ``open_clip_pytorch_model.bin`` in
``torch_backend.py:183-251``). The converted tree is validated against the
module's init-time tree by ``assert_tree_shapes`` — names AND shapes must
match exactly before any weight is served.
"""

from __future__ import annotations

import logging

import numpy as np

from ...runtime.weights import (
    apply_rules,
    assert_tree_shapes,
    conv_kernel,
    linear_kernel,
    unflatten,
)

logger = logging.getLogger(__name__)

_ATTN = r"(q_proj|k_proj|v_proj)"

HF_RULES = [
    # text tower
    (r"text_model\.embeddings\.token_embedding\.weight", r"text/token_embedding/embedding", None),
    (r"text_model\.embeddings\.position_embedding\.weight", r"text/position_embedding", None),
    (rf"text_model\.encoder\.layers\.(\d+)\.self_attn\.{_ATTN}\.weight", r"text/blocks_\1/attn/\2/kernel", linear_kernel),
    (rf"text_model\.encoder\.layers\.(\d+)\.self_attn\.{_ATTN}\.bias", r"text/blocks_\1/attn/\2/bias", None),
    (r"text_model\.encoder\.layers\.(\d+)\.self_attn\.out_proj\.weight", r"text/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"text_model\.encoder\.layers\.(\d+)\.self_attn\.out_proj\.bias", r"text/blocks_\1/attn/out_proj/bias", None),
    (r"text_model\.encoder\.layers\.(\d+)\.layer_norm1\.weight", r"text/blocks_\1/ln1/scale", None),
    (r"text_model\.encoder\.layers\.(\d+)\.layer_norm1\.bias", r"text/blocks_\1/ln1/bias", None),
    (r"text_model\.encoder\.layers\.(\d+)\.layer_norm2\.weight", r"text/blocks_\1/ln2/scale", None),
    (r"text_model\.encoder\.layers\.(\d+)\.layer_norm2\.bias", r"text/blocks_\1/ln2/bias", None),
    (r"text_model\.encoder\.layers\.(\d+)\.mlp\.fc1\.weight", r"text/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"text_model\.encoder\.layers\.(\d+)\.mlp\.fc1\.bias", r"text/blocks_\1/mlp/fc1/bias", None),
    (r"text_model\.encoder\.layers\.(\d+)\.mlp\.fc2\.weight", r"text/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"text_model\.encoder\.layers\.(\d+)\.mlp\.fc2\.bias", r"text/blocks_\1/mlp/fc2/bias", None),
    (r"text_model\.final_layer_norm\.weight", r"text/final_ln/scale", None),
    (r"text_model\.final_layer_norm\.bias", r"text/final_ln/bias", None),
    (r"text_projection\.weight", r"text/projection/kernel", linear_kernel),
    # vision tower ("pre_layrnorm" is HF's actual key spelling)
    (r"vision_model\.embeddings\.class_embedding", r"vision/class_embedding", None),
    (r"vision_model\.embeddings\.patch_embedding\.weight", r"vision/patch_embed/kernel", conv_kernel),
    (r"vision_model\.embeddings\.position_embedding\.weight", r"vision/position_embedding", None),
    (r"vision_model\.pre_layrnorm\.weight", r"vision/pre_ln/scale", None),
    (r"vision_model\.pre_layrnorm\.bias", r"vision/pre_ln/bias", None),
    (rf"vision_model\.encoder\.layers\.(\d+)\.self_attn\.{_ATTN}\.weight", r"vision/blocks_\1/attn/\2/kernel", linear_kernel),
    (rf"vision_model\.encoder\.layers\.(\d+)\.self_attn\.{_ATTN}\.bias", r"vision/blocks_\1/attn/\2/bias", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.self_attn\.out_proj\.weight", r"vision/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"vision_model\.encoder\.layers\.(\d+)\.self_attn\.out_proj\.bias", r"vision/blocks_\1/attn/out_proj/bias", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.layer_norm1\.weight", r"vision/blocks_\1/ln1/scale", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.layer_norm1\.bias", r"vision/blocks_\1/ln1/bias", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.layer_norm2\.weight", r"vision/blocks_\1/ln2/scale", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.layer_norm2\.bias", r"vision/blocks_\1/ln2/bias", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.mlp\.fc1\.weight", r"vision/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"vision_model\.encoder\.layers\.(\d+)\.mlp\.fc1\.bias", r"vision/blocks_\1/mlp/fc1/bias", None),
    (r"vision_model\.encoder\.layers\.(\d+)\.mlp\.fc2\.weight", r"vision/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"vision_model\.encoder\.layers\.(\d+)\.mlp\.fc2\.bias", r"vision/blocks_\1/mlp/fc2/bias", None),
    (r"vision_model\.post_layernorm\.weight", r"vision/post_ln/scale", None),
    (r"vision_model\.post_layernorm\.bias", r"vision/post_ln/bias", None),
    (r"visual_projection\.weight", r"vision/projection/kernel", linear_kernel),
    (r"logit_scale", r"logit_scale", None),
]

HF_DROP = [r"position_ids$", r"logit_bias"]


def convert_hf_clip(state: dict[str, np.ndarray]) -> dict:
    flat = apply_rules(state, HF_RULES, drop=HF_DROP)
    return unflatten(flat)


# -- ChineseCLIP (CN-CLIP) --------------------------------------------------
# BERT text encoder ("encoder.layer.N.attention.self.query" naming) + the
# standard HF CLIP vision tower. Reference loads these through the
# ChineseCLIPModel torch path (``torch_backend.py:340-393``).

_VISION_AND_SCALE_RULES = [r for r in HF_RULES if r[0].startswith(("vision", "visual", "logit"))]

CNCLIP_RULES = [
    (r"text_model\.embeddings\.word_embeddings\.weight", r"text/word_embeddings/embedding", None),
    (r"text_model\.embeddings\.position_embeddings\.weight", r"text/position_embedding", None),
    (r"text_model\.embeddings\.token_type_embeddings\.weight", r"text/token_type_embedding", None),
    (r"text_model\.embeddings\.LayerNorm\.weight", r"text/embed_ln/scale", None),
    (r"text_model\.embeddings\.LayerNorm\.bias", r"text/embed_ln/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.self\.query\.weight", r"text/blocks_\1/attn/q_proj/kernel", linear_kernel),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.self\.query\.bias", r"text/blocks_\1/attn/q_proj/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.self\.key\.weight", r"text/blocks_\1/attn/k_proj/kernel", linear_kernel),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.self\.key\.bias", r"text/blocks_\1/attn/k_proj/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.self\.value\.weight", r"text/blocks_\1/attn/v_proj/kernel", linear_kernel),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.self\.value\.bias", r"text/blocks_\1/attn/v_proj/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.output\.dense\.weight", r"text/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.output\.dense\.bias", r"text/blocks_\1/attn/out_proj/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.output\.LayerNorm\.weight", r"text/blocks_\1/ln1/scale", None),
    (r"text_model\.encoder\.layer\.(\d+)\.attention\.output\.LayerNorm\.bias", r"text/blocks_\1/ln1/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.intermediate\.dense\.weight", r"text/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"text_model\.encoder\.layer\.(\d+)\.intermediate\.dense\.bias", r"text/blocks_\1/mlp/fc1/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.output\.dense\.weight", r"text/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"text_model\.encoder\.layer\.(\d+)\.output\.dense\.bias", r"text/blocks_\1/mlp/fc2/bias", None),
    (r"text_model\.encoder\.layer\.(\d+)\.output\.LayerNorm\.weight", r"text/blocks_\1/ln2/scale", None),
    (r"text_model\.encoder\.layer\.(\d+)\.output\.LayerNorm\.bias", r"text/blocks_\1/ln2/bias", None),
    (r"text_projection\.weight", r"text/projection/kernel", linear_kernel),
] + _VISION_AND_SCALE_RULES

CNCLIP_DROP = [r"position_ids$", r"text_model\.pooler\."]


def convert_cnclip(state: dict[str, np.ndarray]) -> dict:
    flat = apply_rules(state, CNCLIP_RULES, drop=CNCLIP_DROP)
    return unflatten(flat)


# -- OpenCLIP ---------------------------------------------------------------

OPENCLIP_RULES = [
    (r"visual\.class_embedding", r"vision/class_embedding", None),
    (r"visual\.conv1\.weight", r"vision/patch_embed/kernel", conv_kernel),
    (r"visual\.positional_embedding", r"vision/position_embedding", None),
    (r"visual\.ln_pre\.weight", r"vision/pre_ln/scale", None),
    (r"visual\.ln_pre\.bias", r"vision/pre_ln/bias", None),
    (rf"visual\.transformer\.resblocks\.(\d+)\.attn\.{_ATTN}\.weight", r"vision/blocks_\1/attn/\2/kernel", linear_kernel),
    (rf"visual\.transformer\.resblocks\.(\d+)\.attn\.{_ATTN}\.bias", r"vision/blocks_\1/attn/\2/bias", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.attn\.out_proj\.weight", r"vision/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"visual\.transformer\.resblocks\.(\d+)\.attn\.out_proj\.bias", r"vision/blocks_\1/attn/out_proj/bias", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.ln_1\.weight", r"vision/blocks_\1/ln1/scale", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.ln_1\.bias", r"vision/blocks_\1/ln1/bias", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.ln_2\.weight", r"vision/blocks_\1/ln2/scale", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.ln_2\.bias", r"vision/blocks_\1/ln2/bias", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.mlp\.c_fc\.weight", r"vision/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"visual\.transformer\.resblocks\.(\d+)\.mlp\.c_fc\.bias", r"vision/blocks_\1/mlp/fc1/bias", None),
    (r"visual\.transformer\.resblocks\.(\d+)\.mlp\.c_proj\.weight", r"vision/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"visual\.transformer\.resblocks\.(\d+)\.mlp\.c_proj\.bias", r"vision/blocks_\1/mlp/fc2/bias", None),
    (r"visual\.ln_post\.weight", r"vision/post_ln/scale", None),
    (r"visual\.ln_post\.bias", r"vision/post_ln/bias", None),
    # [width, embed_dim] already in jax orientation
    (r"visual\.proj", r"vision/projection/kernel", None),
    (r"token_embedding\.weight", r"text/token_embedding/embedding", None),
    (r"positional_embedding", r"text/position_embedding", None),
    (rf"transformer\.resblocks\.(\d+)\.attn\.{_ATTN}\.weight", r"text/blocks_\1/attn/\2/kernel", linear_kernel),
    (rf"transformer\.resblocks\.(\d+)\.attn\.{_ATTN}\.bias", r"text/blocks_\1/attn/\2/bias", None),
    (r"transformer\.resblocks\.(\d+)\.attn\.out_proj\.weight", r"text/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"transformer\.resblocks\.(\d+)\.attn\.out_proj\.bias", r"text/blocks_\1/attn/out_proj/bias", None),
    (r"transformer\.resblocks\.(\d+)\.ln_1\.weight", r"text/blocks_\1/ln1/scale", None),
    (r"transformer\.resblocks\.(\d+)\.ln_1\.bias", r"text/blocks_\1/ln1/bias", None),
    (r"transformer\.resblocks\.(\d+)\.ln_2\.weight", r"text/blocks_\1/ln2/scale", None),
    (r"transformer\.resblocks\.(\d+)\.ln_2\.bias", r"text/blocks_\1/ln2/bias", None),
    (r"transformer\.resblocks\.(\d+)\.mlp\.c_fc\.weight", r"text/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"transformer\.resblocks\.(\d+)\.mlp\.c_fc\.bias", r"text/blocks_\1/mlp/fc1/bias", None),
    (r"transformer\.resblocks\.(\d+)\.mlp\.c_proj\.weight", r"text/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"transformer\.resblocks\.(\d+)\.mlp\.c_proj\.bias", r"text/blocks_\1/mlp/fc2/bias", None),
    (r"ln_final\.weight", r"text/final_ln/scale", None),
    (r"ln_final\.bias", r"text/final_ln/bias", None),
    (r"text_projection", r"text/projection/kernel", None),
    (r"logit_scale", r"logit_scale", None),
]

OPENCLIP_DROP = [r"attn_mask", r"\.attn\.in_proj_(weight|bias)$"]


def _split_fused_qkv(state: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """OpenCLIP fuses qkv as ``attn.in_proj_weight`` [3*width, width]; split
    into the separate projections our module (and HF) use."""
    out = dict(state)
    for key in list(state):
        if key.endswith("attn.in_proj_weight"):
            w = state[key]
            prefix = key[: -len("in_proj_weight")]
            wq, wk, wv = np.split(w, 3, axis=0)
            out[prefix + "q_proj.weight"] = wq
            out[prefix + "k_proj.weight"] = wk
            out[prefix + "v_proj.weight"] = wv
        elif key.endswith("attn.in_proj_bias"):
            b = state[key]
            prefix = key[: -len("in_proj_bias")]
            bq, bk, bv = np.split(b, 3, axis=0)
            out[prefix + "q_proj.bias"] = bq
            out[prefix + "k_proj.bias"] = bk
            out[prefix + "v_proj.bias"] = bv
    return out


def convert_openclip(state: dict[str, np.ndarray]) -> dict:
    flat = apply_rules(_split_fused_qkv(state), OPENCLIP_RULES, drop=OPENCLIP_DROP)
    return unflatten(flat)


def convert_clip_checkpoint(state: dict[str, np.ndarray], init_params: dict | None = None) -> dict:
    """Sniff the checkpoint family, convert, and (optionally) gate against
    the module's initialized tree."""
    if any(k.startswith("text_model.encoder.layer.") for k in state):
        # BERT-style text encoder ("layer", not "layers") = ChineseCLIP.
        params = convert_cnclip(state)
    elif any(k.startswith(("text_model.", "vision_model.")) for k in state):
        params = convert_hf_clip(state)
    elif any(k.startswith(("visual.", "transformer.")) for k in state):
        params = convert_openclip(state)
    else:
        raise ValueError(
            f"unrecognized CLIP checkpoint family (keys like: {sorted(state)[:5]})"
        )
    if init_params is not None:
        assert_tree_shapes(params, init_params)
    return params


#: block projections QDense replaces when ``weight_quant="int8"`` — one
#: template, parameterized by tower, so the projection set can't drift
#: between the full and vision-only variants (must stay in sync with
#: ``modeling._block_dense`` call sites).
def _clip_quant_pattern(towers: str) -> "re.Pattern":
    import re

    return re.compile(
        rf"^({towers})/blocks_\d+/(attn/(q|k|v|out)_proj|mlp/fc[12])/kernel$"
    )


_CLIP_QUANT_KERNEL = _clip_quant_pattern("vision|text")
_CLIP_QUANT_KERNEL_VISION_ONLY = _clip_quant_pattern("vision")


def quantize_clip_int8(params: dict, include_text: bool = True) -> dict:
    """W8A8-ready int8 tree for the CLIP towers' block projections
    (per-output-channel scales; see ``CLIPConfig.weight_quant``).
    ``include_text=False`` for BERT-text models (ChineseCLIP) whose text
    tower stays full precision."""
    from ...ops.quant import quantize_tree_int8

    pat = _CLIP_QUANT_KERNEL if include_text else _CLIP_QUANT_KERNEL_VISION_ONLY
    return quantize_tree_int8(params, pat, "clip block")
