"""CLIP model manager: embeddings + zero-shot classification on TPU.

Business-logic layer mirroring the reference's ``CLIPModelManager``
(``packages/lumen-clip/src/lumen_clip/general_clip/clip_model.py:48-403``)
and ``BioCLIPModelManager`` (``expert_bioclip/bioclip_model.py:45-375``),
rebuilt around jitted Flax towers behind micro-batchers:

- image/text encode are batched device calls (bucketed static shapes), not
  per-request session runs;
- classification is a device-side matmul against a resident label-embedding
  matrix (softmax mode for curated label sets; raw-cosine mode for huge
  taxonomies, the BioCLIP behavior at ``bioclip_model.py:310-316``);
- label embeddings load from the dataset's precomputed ``.npy`` or are
  computed on startup from labels via prompt templates
  (``clip_model.py:145-172``).
"""

from __future__ import annotations

import json
import logging
import os
import time
import weakref
from dataclasses import dataclass
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np

from ...core.model_info import ModelInfo, load_model_info
from ...ops.image import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    OPENAI_CLIP_MEAN,
    OPENAI_CLIP_STD,
    decode_image_bytes,
)
from ...runtime.batcher import MicroBatcher, mesh_buckets, mesh_sharded, warmup_batcher
from ...runtime.decode_pool import get_decode_pool
from ...runtime.fleet import (
    batcher_name,
    build_fleet,
    each_batcher,
    plan_replicas,
    replicate_all,
    topology_extra,
)
from ...runtime.quarantine import guarded_key
from ...runtime.result_cache import get_result_cache, make_namespace
from ...runtime.policy import get_policy
from ...runtime.weights import load_state_dict
from ...utils.metrics import metrics
from .convert import convert_clip_checkpoint
from .modeling import CLIPConfig, CLIPModel
from .tokenizer import ClipTokenizer

logger = logging.getLogger(__name__)

# Scene-classify contract: the reference's 8 hardcoded prompts and its
# label derivation (prompt minus "a photo of " minus "an ") are part of the
# observable output (``clip_model.py:90-99`` builds them, ``:355-357``
# derives the label), so clients see identical scene buckets on both
# stacks. These short strings are wire-contract constants, like proto
# field names.
SCENE_PROMPTS = [
    "a photo of a person",
    "a photo of an animal",
    "a photo of a vehicle",
    "a photo of food",
    "a photo of a building",
    "a photo of nature",
    "a photo of an object",
    "a photo of a landscape",
]
SCENE_LABELS = [
    p.replace("a photo of ", "").replace("an ", "") for p in SCENE_PROMPTS
]
DEFAULT_PROMPT_TEMPLATE = "a photo of a {}"


@dataclass
class ClassifyResult:
    labels: list[tuple[str, float]]  # (label, score) best-first


class CLIPManager:
    """One loaded CLIP model + its datasets, ready to serve."""

    def __init__(
        self,
        model_dir: str,
        dataset: str | None = None,
        dtype: str = "bfloat16",
        batch_size: int = 8,
        max_batch_latency_ms: float = 5.0,
        mesh_axes: dict[str, int] | None = None,
        classify_mode: Literal["softmax", "cosine"] = "softmax",
        warmup: bool = False,
        quantize: str | None = None,  # None | "int8" (W8A8 tower blocks)
        name_prefix: str = "clip",
    ):
        if quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
        self.quantize = quantize
        # Batcher/gauge name scope: "clip" for the default manager (the
        # historical names — dashboards don't move), the config alias for
        # siblings (a bioclip manager's batchers are "bioclip-image"/
        # "bioclip-text", so two managers in one service never collide on
        # gauges or replica-fleet state keys).
        self.name_prefix = name_prefix
        self.model_dir = model_dir
        self.dataset_name = dataset
        self.classify_mode = classify_mode
        self.policy = get_policy(dtype)
        self.batch_size = batch_size
        self.max_batch_latency_ms = max_batch_latency_ms
        # Replica fleet (LUMEN_REPLICAS / LUMEN_REPLICAS_CLIP): the host's
        # devices partition into N slices, one mesh per replica; the plan
        # is the single all-device mesh of every pre-fleet PR when N=1.
        # ``self.mesh`` stays the primary (replica-0) mesh — shape logic,
        # quant-route timing and label embedding all run there.
        self.fleet_plan = plan_replicas("clip", mesh_axes)
        self.mesh = self.fleet_plan.meshes[0]
        from ...ops.quant_matmul import note_mesh_model_axis

        # TP x int8: pl.pallas_call has no GSPMD sharding rule, so a
        # model-axis mesh must keep QDense on the XLA dequant fallback.
        note_mesh_model_axis(dict(self.mesh.shape).get("model", 1))
        self.warmup = warmup
        self.info: ModelInfo = load_model_info(model_dir)
        # (vision, text) ClipTowerGraph when graph-served; the probed flag
        # memoizes a negative probe so non-graph models scan the dir once.
        self._graphs = None
        self._graphs_probed = False
        self.cfg = self._build_config(model_dir)
        # Deployment override for the serving-side text pad length (e.g. a
        # BERT-text model whose queries are known-short).
        tsl = self.info.extra("text_serving_length")
        if tsl:
            import dataclasses

            self.cfg = dataclasses.replace(self.cfg, text_serving_length=int(tsl))
        if self.quantize:
            import dataclasses

            from ...ops.quant import resolve_q8_kernel

            # Unlike the VLM decoder (bandwidth-bound -> dequant default),
            # batch embedding is MXU-compute-bound: default to the W8A8
            # "dynamic" kernel, which runs a native int8 dot at ~2x the
            # bf16 MXU rate. Same env knob for on-chip A/Bs.
            self.cfg = dataclasses.replace(
                self.cfg,
                weight_quant=self.quantize,
                weight_quant_kernel=resolve_q8_kernel("dynamic"),
            )
        self.model = CLIPModel(self.cfg)
        self.model_id = self.info.name
        # Serving route actually in use ("bf16" | "int8"): int8 is opt-in
        # via `quantize` AND verified — BENCH_r05 measured q8 at 0.923x
        # bf16 on v5e, so a warmup-timed A/B may fall the route back.
        self.quant_route = "bf16"
        self.quant_speedup: float | None = None  # measured q8/bf16, when timed
        self._initialized = False
        self._image_batcher: MicroBatcher | None = None  # or ReplicaSet (fleet)
        self._text_batcher: MicroBatcher | None = None
        self._fleet_params: list | None = None  # per-replica param placements
        self.label_names: list[str] = []
        self._label_matrix: jax.Array | None = None  # [L, D] unit-norm fp32

    # -- configuration ----------------------------------------------------

    def _build_config(self, model_dir: str) -> CLIPConfig:
        cfg_path = os.path.join(model_dir, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path, "r", encoding="utf-8") as f:
                raw = json.load(f)
            if "vision_config" in raw:
                return CLIPConfig.from_hf(raw)
        # openclip-style config (open_clip_config.json) — reference loader
        # distinguishes the two the same way (resources/loader.py:186-204).
        oc_path = os.path.join(model_dir, "open_clip_config.json")
        if os.path.exists(oc_path):
            with open(oc_path, "r", encoding="utf-8") as f:
                raw = json.load(f).get("model_cfg", {})
            from .modeling import TowerConfig

            v, t = raw.get("vision_cfg", {}), raw.get("text_cfg", {})
            return CLIPConfig(
                embed_dim=raw.get("embed_dim", 512),
                image_size=v.get("image_size", 224),
                patch_size=v.get("patch_size", 32),
                vision=TowerConfig(v.get("width", 768), v.get("layers", 12), v.get("width", 768) // 64),
                text=TowerConfig(t.get("width", 512), t.get("layers", 12), t.get("heads", 8)),
                vocab_size=t.get("vocab_size", 49408),
                context_length=t.get("context_length", 77),
            )
        # No tower config at all: an exported-ONNX repo (e.g. MobileCLIP2
        # exports, the region=other default — reference serves these as its
        # primary dual-session path, ``onnxrt_backend.py:72-745``). Derive
        # the serving shapes from the graphs themselves.
        graphs = self._load_graphs(model_dir)
        if graphs is not None:
            vision_graph, text_graph = graphs
            vshape = next(iter(vision_graph.module.input_shapes().values()), ())
            size = vshape[-1] if len(vshape) == 4 and isinstance(vshape[-1], int) and vshape[-1] > 0 else 224
            return CLIPConfig(
                embed_dim=int(self.info.embedding_dim or 512),
                image_size=int(size),
                context_length=text_graph.context_length(77),
            )
        raise FileNotFoundError(
            f"no config.json / open_clip_config.json / onnx towers in {model_dir}"
        )

    def _load_graphs(self, model_dir: str):
        """Probe for exported vision+text towers; memoized on self (both
        outcomes, so a non-graph model scans the directory only once)."""
        if self._graphs_probed:
            return self._graphs
        self._graphs_probed = True
        from .graph import ClipTowerGraph, find_clip_onnx

        found = find_clip_onnx(model_dir, precision=self.info.extra("precision"))
        if "vision" in found and "text" in found:
            self._graphs = (
                ClipTowerGraph.from_path(found["vision"]),
                ClipTowerGraph.from_path(found["text"]),
            )
        return self._graphs

    @property
    def norm_stats(self) -> tuple[tuple[float, ...], tuple[float, ...]]:
        """Normalization stats; OpenAI-CLIP defaults unless the model name
        suggests ImageNet stats (reference heuristic, loader.py:101-139)."""
        name = self.info.name.lower()
        if "bioclip" in name or "imagenet" in (self.info.extra("norm", "") or ""):
            return IMAGENET_MEAN, IMAGENET_STD
        return OPENAI_CLIP_MEAN, OPENAI_CLIP_STD

    # -- initialization ---------------------------------------------------

    def initialize(self) -> None:
        if self._initialized:
            return
        from ...parallel.sharding import replicate

        mean, std = self.norm_stats
        compute_dtype = self.policy.compute_dtype
        backend = str(self.info.extra("clip_backend", "auto") or "auto")

        state = None
        if backend != "graph" and (self._graphs is None or backend == "native"):
            # clip_backend=native must reach for a real checkpoint even when
            # _build_config already derived a graph config (export-only dir).
            try:
                logger.info("loading CLIP weights from %s", self.model_dir)
                state = load_state_dict(self.model_dir)
            except FileNotFoundError:
                if backend == "native" or self._load_graphs(self.model_dir) is None:
                    raise
                logger.info("no native CLIP checkpoint; serving onnx towers")
        if backend == "graph" and self._load_graphs(self.model_dir) is None:
            raise FileNotFoundError(
                f"clip_backend=graph but no vision/text onnx in {self.model_dir}"
            )
        if state is None and self.quantize:
            # Covers EVERY graph-served route (export-only dirs probed at
            # config build, clip_backend=graph, and the no-checkpoint
            # fallback above): an operator who set int8 must not attribute
            # full-precision ONNX numbers to the quantized path.
            logger.warning(
                "quantize=%s ignored: the ONNX graph path runs the exported "
                "precision as-is", self.quantize,
            )

        if state is not None:
            # The shape gate runs against the UNQUANTIZED module tree
            # (checkpoints carry kernels); quantization rewrites matching
            # kernels to (q, scale) afterwards, on the cast weights.
            import dataclasses

            base_model = (
                CLIPModel(dataclasses.replace(self.cfg, weight_quant=None))
                if self.quantize else self.model
            )
            init = jax.eval_shape(
                lambda: base_model.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((1, self.cfg.image_size, self.cfg.image_size, 3), jnp.float32),
                    jnp.zeros((1, self.cfg.context_length), jnp.int32),
                )["params"]
            )
            params = convert_clip_checkpoint(state, init)
            params = self.policy.cast_params(params)
            qparams = None
            if self.quantize == "int8":
                # A bf16 route pin skips quantization entirely — an
                # operator who pinned away from the q8 regression must not
                # pay a full-checkpoint quantization at every boot just to
                # discard it.
                if os.environ.get("LUMEN_CLIP_Q8_ROUTE", "auto").lower() == "bf16":
                    logger.info(
                        "CLIP quantize=int8 overridden to bf16 "
                        "(LUMEN_CLIP_Q8_ROUTE); skipping quantization"
                    )
                else:
                    from .convert import quantize_clip_int8

                    qparams = quantize_clip_int8(
                        params, include_text=self.cfg.text_arch != "bert"
                    )

            def place(p, quantized: bool, mesh=None):
                # DP serving: params replicated over the mesh; micro-batches
                # are data-sharded so one batched call spreads across every
                # device (trivial placement on a 1-device mesh). A mesh with
                # a ``model`` axis additionally tensor-parallelizes the
                # towers (both towers are standard transformers, so the
                # shared TP rules apply — SURVEY §2.8). Replica fleets call
                # this once per replica mesh: every slice gets its own full
                # (or TP-sharded) copy of the winning params.
                mesh = self.mesh if mesh is None else mesh
                if dict(mesh.shape).get("model", 1) > 1:
                    from ...parallel.sharding import (
                        INT8_TP_RULES,
                        TRANSFORMER_TP_RULES,
                        shard_params,
                    )

                    rules = (INT8_TP_RULES if quantized else []) + TRANSFORMER_TP_RULES
                    return shard_params(p, mesh, rules)
                return replicate(p, mesh)

            def make_encoders(model):
                @jax.jit
                def encode_images(params, pixels_u8):
                    # pixels_u8: [B, S, S, 3] uint8 (resized on host or
                    # device-resized upstream); normalize + cast on device.
                    x = pixels_u8.astype(jnp.float32) / 255.0
                    x = (x - jnp.asarray(mean)) / jnp.asarray(std)
                    z = model.apply(
                        {"params": params},
                        x.astype(compute_dtype),
                        method=lambda m, px: m.encode_image(px),
                    )
                    return z  # fp32 unit-norm

                @jax.jit
                def encode_texts(params, ids):
                    return model.apply(
                        {"params": params}, ids, method=lambda m, i: m.encode_text(i)
                    )

                return encode_images, encode_texts

            if qparams is None:
                self.model = base_model
                self.params = place(params, quantized=False)
                self._fleet_params = [self.params] + [
                    place(params, quantized=False, mesh=m)
                    for m in self.fleet_plan.meshes[1:]
                ]
                encode_images, encode_texts = make_encoders(base_model)
            else:
                encode_images, encode_texts = self._pick_quant_route(
                    base_model, params, qparams, place, make_encoders
                )

        else:
            # Graph towers: the exporter's own weights as XLA programs; the
            # manager normalizes outputs host-of-device-side exactly like
            # the reference session path (``onnxrt_backend.py:486-489``).
            import dataclasses

            vision_graph, text_graph = self._graphs
            # Reconcile serving shapes with the exports' STATIC shapes even
            # when a config.json supplied the cfg (a text export built at
            # 52 tokens cannot run 77-padded ids; the vision export's input
            # side fixes the resize target).
            vshape = next(iter(vision_graph.module.input_shapes().values()), ())
            updates: dict = {}
            if len(vshape) == 4 and isinstance(vshape[-1], int) and vshape[-1] > 0:
                updates["image_size"] = int(vshape[-1])
            ctx = text_graph.context_length(self.cfg.context_length)
            updates["context_length"] = ctx
            # A static export runs at exactly its built length — any pad
            # cap (config- OR model_info-supplied) shorter than that would
            # feed shapes the graph's fixed ops can't take.
            updates["text_serving_length"] = None
            dim = vision_graph.probe_dim(
                np.zeros(
                    (1, 3, updates.get("image_size", self.cfg.image_size),
                     updates.get("image_size", self.cfg.image_size)), np.float32
                )
            )
            if dim != self.cfg.embed_dim:
                logger.info("graph towers emit %d-d embeddings (config said %d)", dim, self.cfg.embed_dim)
                updates["embed_dim"] = dim
            if updates:
                self.cfg = dataclasses.replace(self.cfg, **updates)
            host_tree = {
                "vision": dict(vision_graph.module.params),
                "text": dict(text_graph.module.params),
            }
            self.params = replicate(host_tree, self.mesh)
            # Every replica mesh gets its copy BEFORE the host weights are
            # released (there is nothing to re-place from afterwards).
            self._fleet_params = replicate_all(
                host_tree, self.fleet_plan, primary=self.params
            )
            # The jitted closures only need the graph TOPOLOGY; drop the
            # host-RAM weight copies (params AND the aliasing initializers)
            # now that the mesh holds them.
            vision_graph.module.release_weights()
            text_graph.module.release_weights()

            @jax.jit
            def encode_images(params, pixels_u8):
                x = pixels_u8.astype(jnp.float32) / 255.0
                x = (x - jnp.asarray(mean)) / jnp.asarray(std)
                z = vision_graph(params["vision"], x.transpose(0, 3, 1, 2))
                z = z.astype(jnp.float32)
                return z / jnp.maximum(jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-12)

            @jax.jit
            def encode_texts(params, ids):
                z = text_graph(params["text"], ids).astype(jnp.float32)
                return z / jnp.maximum(jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-12)

        self.tokenizer = ClipTokenizer.from_model_dir(self.model_dir, self.cfg.serving_text_length)
        self._encode_images = encode_images
        self._encode_texts = encode_texts

        dp = self.mesh.shape.get("data", 1)
        buckets = mesh_buckets(self.batch_size, dp)

        # Batcher fns DISPATCH and return the un-fetched device array: the
        # MicroBatcher's fetch worker does the one blocking device->host
        # transfer per batch, so the next batch stacks/transfers/dispatches
        # while this one computes (the pipelined serving data path). Each
        # replica closes over ITS mesh slice's param placement; build_fleet
        # hands back the plain single batcher (today's exact path) when the
        # fleet plan is one replica, a routed ReplicaSet otherwise. The
        # closures double as the fleet's revive hook: a wedged replica gets
        # a fresh batcher over the same placed params.
        def build_image(rid, mesh):
            params = self._fleet_params[rid or 0]
            return MicroBatcher(
                mesh_sharded(
                    lambda pixels, n, _p=params: self._encode_images(_p, pixels),
                    mesh,
                ),
                max_batch=buckets[-1],
                max_latency_ms=self.max_batch_latency_ms,
                buckets=buckets,
                name=batcher_name(f"{self.name_prefix}-image", rid),
                replica=None if rid is None else f"r{rid}",
            ).start()

        def build_text(rid, mesh):
            params = self._fleet_params[rid or 0]
            return MicroBatcher(
                mesh_sharded(
                    lambda ids, n, _p=params: self._encode_texts(_p, ids),
                    mesh,
                ),
                max_batch=buckets[-1],
                max_latency_ms=self.max_batch_latency_ms,
                buckets=buckets,
                name=batcher_name(f"{self.name_prefix}-text", rid),
                replica=None if rid is None else f"r{rid}",
            ).start()

        self._image_batcher = build_fleet(
            self.fleet_plan, f"{self.name_prefix}-image", build_image
        )
        self._text_batcher = build_fleet(
            self.fleet_plan, f"{self.name_prefix}-text", build_text
        )

        self._load_label_embeddings()
        if self.warmup:
            self._warmup(buckets)
        if self.quantize:
            # The chosen route is operator-visible state, not a log line:
            # "is this deployment actually serving int8?" must be
            # answerable from /metrics (gauge ``int8_active``, plus the
            # measured ``q8_speedup_pct`` when the warmup A/B ran).
            ref = weakref.ref(self)

            def _route_gauges() -> dict:
                m = ref()
                if m is None:
                    return {}
                out = {"int8_active": 1 if m.quant_route == "int8" else 0}
                if m.quant_speedup is not None:
                    out["q8_speedup_pct"] = round(m.quant_speedup * 100, 1)
                return out

            self._route_gauge_fn = _route_gauges
            metrics.register_gauges(f"clip-quant:{self.model_id}", _route_gauges)
        self._initialized = True
        logger.info(
            "CLIP ready: %s embed_dim=%d labels=%d",
            self.model_id,
            self.cfg.embed_dim,
            len(self.label_names),
        )

    def _warmup(self, buckets: list[int]) -> None:
        """Compile every batch bucket at startup so first requests don't pay
        compile time (SURVEY.md §7 hard part 2: the reference's "load time"
        becomes our "compile time" — spend it before serving). Runs through
        the batchers' own callables so the cache is guaranteed to hit."""
        t0 = time.perf_counter()
        size = self.cfg.image_size
        for b in each_batcher(self._image_batcher):
            warmup_batcher(b, lambda n: np.zeros((n, size, size, 3), np.uint8))
        for b in each_batcher(self._text_batcher):
            warmup_batcher(
                b, lambda n: np.zeros((n, self.cfg.serving_text_length), np.int32)
            )
        logger.info("warmup: %d bucket(s) compiled in %.1fs", len(buckets), time.perf_counter() - t0)

    def close(self) -> None:
        if self._image_batcher:
            self._image_batcher.close()
        if self._text_batcher:
            self._text_batcher.close()
        if fn := getattr(self, "_route_gauge_fn", None):
            metrics.unregister_gauges(f"clip-quant:{self.model_id}", fn)
        self._initialized = False

    def topology(self) -> dict[str, str]:
        """Device topology + replica layout for the capability ``extra``
        (fleet-internal clients pick endpoints from this, not by probing)."""
        return topology_extra(self.mesh, self._image_batcher, self._text_batcher)

    # -- quantization route ------------------------------------------------

    def _pick_quant_route(self, base_model, params, qparams, place, make_encoders):
        """Decide whether the explicit int8 opt-in actually serves int8.

        BENCH_r05 measured the W8A8 dynamic kernel at 0.923x bf16 on v5e —
        a *regression* the operator opting into "int8" almost certainly
        did not want. So when warmup is on, the two routes run a one-shot
        timed A/B at the top serving bucket and the loser's params are
        dropped; int8 only survives when it measures at least even. With
        warmup off there is nothing to time against, so the explicit
        config wins as-is. ``LUMEN_CLIP_Q8_ROUTE=int8|bf16`` pins the
        route (skips the A/B); ``auto`` (default) is the behavior above.
        Returns the chosen ``(encode_images, encode_texts)`` pair and sets
        ``self.model`` / ``self.params`` / ``self.quant_route``.
        """
        q_model = self.model  # built with weight_quant in __init__
        # A "bf16" pin never reaches here — initialize() skips the
        # quantization entirely in that case, so qparams is None and the
        # non-quantized path runs instead.
        route = os.environ.get("LUMEN_CLIP_Q8_ROUTE", "auto").lower()
        if route not in ("auto", "int8"):
            logger.warning("ignoring malformed LUMEN_CLIP_Q8_ROUTE=%r", route)
            route = "auto"
        if route == "auto" and not self.warmup:
            route = "int8"  # no warmup pass to time against: honor the opt-in
        if route == "int8":
            self.quant_route = "int8"
            self.params = place(qparams, quantized=True)
            self._fleet_params = [self.params] + [
                place(qparams, quantized=True, mesh=m)
                for m in self.fleet_plan.meshes[1:]
            ]
            return make_encoders(q_model)

        # One-shot warmup A/B, timed SEQUENTIALLY so peak HBM stays at one
        # tower set plus activations — memory-tight deployments quantize
        # precisely because bf16 barely fits, and a transient 2x at boot
        # would OOM exactly them. The loser's placement is freed before
        # the winner's (the q8 measurement's placement is reused when q8
        # wins; a bf16 win pays one extra host->device transfer).
        enc_bf16 = make_encoders(base_model)
        enc_q8 = make_encoders(q_model)
        params_bf16 = place(params, quantized=False)
        t_bf16 = self._time_image_encode(enc_bf16[0], params_bf16)
        del params_bf16  # free the bf16 placement before placing q8
        params_q8 = place(qparams, quantized=True)
        t_q8 = self._time_image_encode(enc_q8[0], params_q8)
        self.quant_speedup = t_bf16 / max(t_q8, 1e-9)
        if self.quant_speedup >= 1.0:
            logger.info(
                "CLIP int8 route confirmed: %.3fx bf16 at batch bucket",
                self.quant_speedup,
            )
            self.quant_route = "int8"
            self.params = params_q8
            self._fleet_params = [self.params] + [
                place(qparams, quantized=True, mesh=m)
                for m in self.fleet_plan.meshes[1:]
            ]
            return enc_q8
        logger.warning(
            "CLIP int8 route DISABLED: warmup A/B measured q8 at %.3fx bf16 "
            "(a regression); serving bf16 instead. Pin LUMEN_CLIP_Q8_ROUTE="
            "int8 to force.",
            self.quant_speedup,
        )
        metrics.count("clip_q8_fallbacks")
        self.quant_route = "bf16"
        self.model = base_model
        del params_q8
        self.params = place(params, quantized=False)
        self._fleet_params = [self.params] + [
            place(params, quantized=False, mesh=m)
            for m in self.fleet_plan.meshes[1:]
        ]
        return enc_bf16

    def _time_image_encode(self, encode, placed_params) -> float:
        """Best-of-3 wall time for one image-encode at the top serving
        bucket, inputs placed exactly like serving traffic (data-sharded)
        so the compiles land in the same cache the batcher warmup hits."""
        from ...runtime.mesh import data_sharding

        dp = self.mesh.shape.get("data", 1)
        bucket = mesh_buckets(self.batch_size, dp)[-1]
        size = self.cfg.image_size
        x = jax.device_put(
            np.zeros((bucket, size, size, 3), np.uint8), data_sharding(self.mesh)
        )
        jax.block_until_ready(encode(placed_params, x))  # compile off the clock
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(encode(placed_params, x))
            times.append(time.perf_counter() - t0)
        return min(times)

    # -- datasets ---------------------------------------------------------

    def _load_label_embeddings(self) -> None:
        if not self.dataset_name or not self.info.datasets:
            return
        ds = self.info.datasets.get(self.dataset_name)
        if ds is None:
            logger.warning("dataset %r not in model_info; classify disabled", self.dataset_name)
            return
        labels_path = os.path.join(self.model_dir, ds.labels)
        with open(labels_path, "r", encoding="utf-8") as f:
            raw_labels = json.load(f)
        self.label_names = [self._label_text(entry) for entry in raw_labels]
        emb_path = os.path.join(self.model_dir, ds.embeddings)
        if os.path.exists(emb_path):
            mat = np.load(emb_path, mmap_mode="r")
            mat = np.asarray(mat, np.float32)
            # Axis-order autodetect (reference: bioclip_model.py:287-309).
            if mat.shape[0] != len(self.label_names) and mat.shape[-1] == len(self.label_names):
                mat = mat.T
            if mat.shape[0] != len(self.label_names):
                raise ValueError(
                    f"label embedding shape {mat.shape} does not match "
                    f"{len(self.label_names)} labels"
                )
        else:
            logger.info("no precomputed label embeddings; encoding %d labels", len(self.label_names))
            mat = self._compute_label_embeddings(self.label_names)
        mat = mat / np.maximum(np.linalg.norm(mat, axis=-1, keepdims=True), 1e-12)
        self._label_matrix = jnp.asarray(mat)

    @staticmethod
    def _label_text(entry) -> str:
        """Dataset label entries are either plain strings or BioCLIP-style
        ``[[taxonomy...], common_name]`` pairs (reference name extraction,
        ``bioclip_model.py:192-217``)."""
        if isinstance(entry, str):
            return entry
        if isinstance(entry, (list, tuple)) and len(entry) == 2:
            taxonomy, common = entry
            if isinstance(common, str) and common:
                return common
            if isinstance(taxonomy, (list, tuple)) and taxonomy:
                return str(taxonomy[-1])
        return str(entry)

    def _compute_label_embeddings(self, labels: list[str], template: str = DEFAULT_PROMPT_TEMPLATE) -> np.ndarray:
        out = []
        bs = max(self.batch_size, 16)
        for i in range(0, len(labels), bs):
            chunk = [template.format(l) for l in labels[i : i + bs]]
            ids = self.tokenizer.encode_batch(chunk)
            out.append(np.asarray(self._encode_texts(self.params, jnp.asarray(ids))))
        return np.concatenate(out, axis=0)

    # -- inference API ----------------------------------------------------

    def _cache_ns(self, task: str, *qualifiers: str) -> str:
        """Result-cache namespace (see
        :func:`~lumen_tpu.runtime.result_cache.make_namespace`). Qualified
        by the compute dtype AND the resolved quant route — the warmup A/B
        can pick a different route across restarts, and disk-tier entries
        from one precision must not answer for another. Image tasks add
        the decode-policy qualifier (scaled decode changes resampling
        numerics across deploy generations)."""
        return make_namespace(
            "clip", task, self.model_id, self.info.version,
            jnp.dtype(self.policy.compute_dtype).name, self.quant_route,
            *qualifiers,
        )

    def encode_image(self, image_bytes: bytes) -> np.ndarray:
        """Single image bytes -> unit-norm fp32 embedding (batched under the
        hood with concurrent callers). Content-addressed cache first: the
        sha256 runs on the RAW bytes, so a hit (or a coalesced duplicate
        in flight) skips decode pool AND batcher entirely — identical
        re-index / duplicate-burst traffic costs one device call total.
        The same content address is the poison-quarantine gate: bytes that
        previously made a batch fail are rejected HERE — before the decode
        pool, admission queue and device — and it rides the batcher submit
        as the fingerprint bisection quarantines on.
        On a miss, decode+resize run on the shared decode pool — the
        calling (gRPC handler) thread only waits, so decode concurrency is
        bounded by ``LUMEN_DECODE_WORKERS``, not by however many handler
        threads pile in. Every hit returns a private copy: a caller
        mutating "its" embedding in place must not poison the store."""
        self._ensure_ready()
        from ...ops.image import DECODE_POLICY

        payload = bytes(image_bytes)
        ns = self._cache_ns("image_embed", DECODE_POLICY)
        key = guarded_key(ns, None, payload)
        return get_result_cache().get_or_compute(
            ns,
            None,
            payload,
            lambda: self._encode_image_uncached(image_bytes, fingerprint=key),
            clone=np.copy,
            key=key,
        )

    def _encode_image_uncached(
        self, image_bytes: bytes, fingerprint: str | None = None
    ) -> np.ndarray:
        # The "clip_resize" decode spec (scaled decode + square squash,
        # lumen_tpu.utils.host_decode) runs on the shared pool — in
        # process mode that is a worker process writing into a
        # shared-memory arena slot, and `decoded.array` is a zero-copy
        # view the batcher's collector stacks from directly; release()
        # recycles the slot once the batcher has settled (the collector
        # copied the row into its staging arena before dispatch).
        size = self.cfg.image_size
        decoded = get_decode_pool().run_decode(
            "clip_resize", image_bytes, {"size": size}
        )
        try:
            vec = self._image_batcher(decoded.array, fingerprint=fingerprint)
        finally:
            decoded.release()
        return self._check_vector(vec)

    def tensor_input_shape(self) -> tuple[int, int, int]:
        """The pre-decoded pixel tensor this manager accepts on the
        ``tensor/raw`` wire path: exactly what the ``clip_resize`` decode
        spec produces, so tensor- and JPEG-path results are identical."""
        size = self.cfg.image_size
        return (size, size, 3)

    def encode_image_tensor(self, pixels: np.ndarray, raw: bytes | None = None) -> np.ndarray:
        """Pre-decoded tensor -> unit-norm embedding: the zero-decode
        serving path. ``pixels`` must be the uint8 (size, size, 3) tensor
        the capability's input spec advertises; it goes STRAIGHT to the
        batcher — no decode pool, no resize. ``raw`` is the wire payload
        backing ``pixels`` (the same buffer, so passing it avoids a
        re-serialization); the result cache keys on sha256 of that raw
        buffer, hashed exactly once — the same single-hash guarantee the
        JPEG path has, under a ``tensor``-qualified namespace (raw pixels
        and JPEG bytes of one image are different byte strings and must
        never answer for each other)."""
        self._ensure_ready()
        size = self.cfg.image_size
        if pixels.dtype != np.uint8 or tuple(pixels.shape) != (size, size, 3):
            raise ValueError(
                f"tensor input must be uint8 of shape ({size}, {size}, 3); "
                f"got {pixels.dtype} {tuple(pixels.shape)}"
            )
        payload = raw if raw is not None else pixels.tobytes()
        ns = self._cache_ns("image_embed", "tensor")
        key = guarded_key(ns, None, payload)
        return get_result_cache().get_or_compute(
            ns,
            None,
            payload,
            lambda: self._check_vector(
                self._image_batcher(np.ascontiguousarray(pixels), fingerprint=key)
            ),
            clone=np.copy,
            key=key,
        )

    def encode_text(self, text: str) -> np.ndarray:
        self._ensure_ready()
        payload = text.encode("utf-8")
        ns = self._cache_ns("text_embed")
        key = guarded_key(ns, None, payload)
        return get_result_cache().get_or_compute(
            ns,
            None,
            payload,
            lambda: self._encode_text_uncached(text, fingerprint=key),
            clone=np.copy,
            key=key,
        )

    def _encode_text_uncached(self, text: str, fingerprint: str | None = None) -> np.ndarray:
        ids = self.tokenizer.encode_batch([text])[0]
        vec = self._text_batcher(ids, fingerprint=fingerprint)
        return self._check_vector(vec)

    def classify_image(self, image_bytes: bytes, top_k: int = 5) -> ClassifyResult:
        self._ensure_ready()
        if self._label_matrix is None:
            raise RuntimeError("no dataset loaded; classification unavailable")
        vec = self.encode_image(image_bytes)
        return self._classify_vector(vec, self.label_names, self._label_matrix, top_k)

    def classify_scene(self, image_bytes: bytes, top_k: int = 3) -> ClassifyResult:
        self._ensure_ready()
        if not hasattr(self, "_scene_matrix"):
            # The full prompts embed verbatim (template already baked in);
            # labels are their reference-derived short forms.
            mat = self._compute_label_embeddings(SCENE_PROMPTS, template="{}")
            mat = mat / np.maximum(np.linalg.norm(mat, axis=-1, keepdims=True), 1e-12)
            self._scene_matrix = jnp.asarray(mat)
        vec = self.encode_image(image_bytes)
        # Reference scene scoring is a plain softmax over raw cosine
        # similarities (``clip_model.py:344-350``) — no logit-scale
        # temperature, unlike classify_image.
        return self._classify_vector(
            vec, SCENE_LABELS, self._scene_matrix, top_k, temperature=1.0
        )

    def _classify_vector(
        self,
        vec: np.ndarray,
        names: list[str],
        matrix: jax.Array,
        top_k: int,
        temperature: float | None = None,
    ) -> ClassifyResult:
        sims = np.asarray(matrix @ jnp.asarray(vec))  # cosine: both unit-norm
        top_k = min(top_k, len(names))
        idx = np.argpartition(-sims, top_k - 1)[:top_k]
        idx = idx[np.argsort(-sims[idx])]
        if self.classify_mode == "cosine" and temperature is None:
            # Raw similarity scores (BioCLIP large-taxonomy behavior). An
            # explicitly pinned temperature (the scene path's 1.0) always
            # means softmax — even on a cosine-mode manager.
            scores = sims[idx]
        else:
            # Temperature-scaled stable softmax over ALL labels
            # (reference: clip_model.py:232-317; temperature = logit scale
            # unless the caller pins one, e.g. the scene path's 1.0).
            if temperature is None:
                temperature = self.temperature()
            logits = sims * temperature
            logits -= logits.max()
            probs = np.exp(logits)
            probs /= probs.sum()
            scores = probs[idx]
        return ClassifyResult(labels=[(names[i], float(s)) for i, s in zip(idx, scores)])

    # -- utils ------------------------------------------------------------

    def _ensure_ready(self) -> None:
        if not self._initialized:
            raise RuntimeError("CLIPManager.initialize() not called")

    @staticmethod
    def _check_vector(vec: np.ndarray) -> np.ndarray:
        vec = np.asarray(vec, np.float32)
        if not np.isfinite(vec).all():
            raise ValueError("model produced non-finite embedding")
        n = np.linalg.norm(vec)
        if n < 1e-6:
            raise ValueError("model produced zero-norm embedding")
        return vec / n

    def temperature(self) -> float:
        """Exported logit scale (exp'd). Graph-served towers carry no
        logit_scale param — ONNX exports don't ship the temperature, same
        as the reference's session path whose ``get_temperature`` is
        optional (``base.py:254-270``) — so the fallback chain is
        model_info ``extra.logit_scale`` then the CLIP-standard 100."""
        if "logit_scale" in self.params:
            return float(np.exp(np.asarray(self.params["logit_scale"], np.float32)))
        extra = self.info.extra("logit_scale")
        if extra is not None:
            return float(np.exp(float(extra)))
        return 100.0
