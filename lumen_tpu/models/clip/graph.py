"""Graph-backed CLIP towers: exported ``vision.onnx`` / ``text.onnx`` on TPU.

The reference's PRIMARY CLIP path is a dual-session onnxruntime backend
over exactly these exports (``packages/lumen-clip/src/lumen_clip/backends/
onnxrt_backend.py:72-745``: precision-aware ``{component}.{precision}.onnx``
file pick, input-dtype autodetect, context length read from the text
input's shape). Serving them through the ONNX->JAX bridge means model
families whose towers have no conversion rules — MobileCLIP2's FastViT
hybrid vision tower (the region=other config default), distilled/exported
variants — run as XLA programs with the exporter's own weights.

Contract (reference ``image_to_vector``/``text_to_vector``): vision takes
``[B, 3, S, S]`` normalized pixels, text takes ``[B, L]`` token ids; both
emit ``[B, D]`` embeddings which the manager L2-normalizes host-side
(reference ``:486-489``).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import numpy as np

from ...onnx_bridge import OnnxModule

logger = logging.getLogger(__name__)

_PRECISION_ORDER = ["fp32", "fp16"]  # reference preference chain (:245-289)


def find_clip_onnx(model_dir: str, precision: str | None = None) -> dict[str, str]:
    """Locate ``vision*.onnx`` / ``text*.onnx`` (bare dir or ``onnx/``
    subdir), preferring the requested precision then fp32 then fp16 —
    the reference's file-pick chain."""
    names = sorted(os.listdir(model_dir)) if os.path.isdir(model_dir) else []
    sub = os.path.join(model_dir, "onnx")
    if os.path.isdir(sub):
        names += [os.path.join("onnx", n) for n in sorted(os.listdir(sub))]

    order = [precision] if precision else []
    order += [p for p in _PRECISION_ORDER if p not in order]
    found: dict[str, str] = {}
    for kind, prefix in (("vision", "vision"), ("text", "text")):
        candidates = [
            n for n in names
            if n.endswith(".onnx") and os.path.basename(n).startswith(prefix)
        ]
        if not candidates:
            continue

        def rank(name: str) -> tuple:
            base = os.path.basename(name)
            for i, prec in enumerate(order):
                if f".{prec}." in base:
                    return (i, base)
            return (len(order), base)  # bare vision.onnx / text.onnx

        found[kind] = os.path.join(model_dir, sorted(candidates, key=rank)[0])
    return found


@dataclass
class ClipTowerGraph:
    """One exported tower as a jittable program."""

    module: OnnxModule

    @classmethod
    def from_path(cls, path: str) -> "ClipTowerGraph":
        mod = OnnxModule.from_path(path)
        logger.info(
            "clip tower graph %s: %d MB params, inputs %s",
            os.path.basename(path), mod.param_bytes() >> 20, mod.input_shapes(),
        )
        return cls(module=mod)

    def __call__(self, params: dict, x):
        import jax.numpy as jnp

        out = jnp.asarray(self.module(params, {self.module.input_names[0]: x})[0])
        if out.ndim != 2:
            raise ValueError(f"CLIP tower must emit [B, D], got {out.shape}")
        return out

    def context_length(self, default: int) -> int:
        """Static text length from the export's input shape (reference
        detects it the same way, ``onnxrt_backend.py:212-217``)."""
        shape = next(iter(self.module.input_shapes().values()), ())
        if len(shape) == 2 and isinstance(shape[1], int) and shape[1] > 0:
            return int(shape[1])
        return default

    def probe_dim(self, example: np.ndarray) -> int:
        """Output dim via shape-only tracing — no FLOPs, no compile."""
        import jax

        out = jax.eval_shape(
            lambda p, x: self(p, x), self.module.params, np.asarray(example)
        )
        return int(out.shape[1])
