"""Graph-backed CLIP towers: exported ``vision.onnx`` / ``text.onnx`` on TPU.

The reference's PRIMARY CLIP path is a dual-session onnxruntime backend
over exactly these exports (``packages/lumen-clip/src/lumen_clip/backends/
onnxrt_backend.py:72-745``: precision-aware ``{component}.{precision}.onnx``
file pick, input-dtype autodetect, context length read from the text
input's shape). Serving them through the ONNX->JAX bridge means model
families whose towers have no conversion rules — MobileCLIP2's FastViT
hybrid vision tower (the region=other config default), distilled/exported
variants — run as XLA programs with the exporter's own weights.

Contract (reference ``image_to_vector``/``text_to_vector``): vision takes
``[B, 3, S, S]`` normalized pixels, text takes ``[B, L]`` token ids; both
emit ``[B, D]`` embeddings which the manager L2-normalizes host-side
(reference ``:486-489``).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import numpy as np

from ...onnx_bridge import OnnxModule, find_onnx_exports

logger = logging.getLogger(__name__)


def find_clip_onnx(model_dir: str, precision: str | None = None) -> dict[str, str]:
    """Locate ``vision*.onnx`` / ``text*.onnx`` with the reference's
    precision-preference chain (shared discovery helper)."""
    return find_onnx_exports(
        model_dir, {"vision": "vision", "text": "text"}, precision
    )


@dataclass
class ClipTowerGraph:
    """One exported tower as a jittable program."""

    module: OnnxModule

    @classmethod
    def from_path(cls, path: str) -> "ClipTowerGraph":
        mod = OnnxModule.from_path(path)
        logger.info(
            "clip tower graph %s: %d MB params, inputs %s",
            os.path.basename(path), mod.param_bytes() >> 20, mod.input_shapes(),
        )
        return cls(module=mod)

    def __call__(self, params: dict, x):
        import jax.numpy as jnp

        out = jnp.asarray(self.module(params, {self.module.input_names[0]: x})[0])
        if out.ndim != 2:
            raise ValueError(f"CLIP tower must emit [B, D], got {out.shape}")
        return out

    def context_length(self, default: int) -> int:
        """Static text length from the export's input shape (reference
        detects it the same way, ``onnxrt_backend.py:212-217``)."""
        shape = next(iter(self.module.input_shapes().values()), ())
        if len(shape) == 2 and isinstance(shape[1], int) and shape[1] > 0:
            return int(shape[1])
        return default

    def probe_dim(self, example: np.ndarray) -> int:
        """Output dim via shape-only tracing — no FLOPs, no compile."""
        import jax

        out = jax.eval_shape(
            lambda p, x: self(p, x), self.module.params, np.asarray(example)
        )
        return int(out.shape[1])
