"""CLIP model family: Flax towers, checkpoint conversion, manager."""

from .convert import convert_clip_checkpoint, convert_hf_clip, convert_openclip
from .manager import CLIPManager, SCENE_LABELS
from .modeling import CLIPConfig, CLIPModel, TowerConfig
from .tokenizer import ClipTokenizer

__all__ = [
    "CLIPConfig",
    "CLIPModel",
    "TowerConfig",
    "CLIPManager",
    "SCENE_LABELS",
    "ClipTokenizer",
    "convert_clip_checkpoint",
    "convert_hf_clip",
    "convert_openclip",
]
