"""Text tokenization for CLIP-family models.

Wraps an HF ``tokenizers`` fast tokenizer (``tokenizer.json`` in the model
dir — same artifact the reference loads, ``onnxrt_backend.py:307-376``) and
produces fixed-length right-padded id batches for the text tower.
"""

from __future__ import annotations

import os

import numpy as np


class ClipTokenizer:
    def __init__(self, tokenizer, context_length: int, pad_id: int = 0):
        self._tok = tokenizer
        self.context_length = context_length
        self.pad_id = pad_id

    @classmethod
    def from_model_dir(cls, model_dir: str, context_length: int) -> "ClipTokenizer":
        from tokenizers import Tokenizer

        path = os.path.join(model_dir, "tokenizer.json")
        vocab_txt = os.path.join(model_dir, "vocab.txt")
        if os.path.exists(path):
            tok = Tokenizer.from_file(path)
            pad_id = 0
            if tok.padding is not None and "pad_id" in tok.padding:
                pad_id = tok.padding["pad_id"]
        elif os.path.exists(vocab_txt):
            # BERT wordpiece repos (CN-CLIP) ship vocab.txt instead of a
            # fast-tokenizer JSON; same fallback chain as the reference
            # (``onnxrt_backend.py:307-376`` tries AutoTokenizer last).
            tok = cls._bert_from_vocab(model_dir, vocab_txt)
            # BERT pads with [PAD]'s actual id (validated present by
            # _bert_from_vocab), not an assumed 0.
            pad_id = tok.get_vocab()["[PAD]"]
        else:
            raise FileNotFoundError(f"no tokenizer.json or vocab.txt in {model_dir}")
        tok.no_padding()  # we pad ourselves to the static context length
        tok.enable_truncation(max_length=context_length)
        return cls(tok, context_length, pad_id)

    @staticmethod
    def _bert_from_vocab(model_dir: str, vocab_txt: str):
        """Assemble a BERT wordpiece tokenizer from vocab.txt via the
        public ``tokenizers`` components (the legacy BertWordPieceTokenizer
        wrapper only exposes the assembled ``Tokenizer`` through a private
        attribute). Casing honors the repo's ``tokenizer_config.json``
        ``do_lower_case`` (default True, the BERT/CN-CLIP norm)."""
        import json

        from tokenizers import Tokenizer, decoders, normalizers, pre_tokenizers
        from tokenizers.models import WordPiece
        from tokenizers.processors import TemplateProcessing

        lower = True
        tc_path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(tc_path):
            try:
                with open(tc_path, "r", encoding="utf-8") as f:
                    lower = bool(json.load(f).get("do_lower_case", True))
            except (OSError, ValueError):
                pass
        tok = Tokenizer(WordPiece.from_file(vocab_txt, unk_token="[UNK]"))
        tok.normalizer = normalizers.BertNormalizer(
            lowercase=lower, strip_accents=lower
        )
        tok.pre_tokenizer = pre_tokenizers.BertPreTokenizer()
        tok.decoder = decoders.WordPiece(prefix="##")
        vocab = tok.get_vocab()
        missing = [t for t in ("[CLS]", "[SEP]", "[UNK]", "[PAD]") if t not in vocab]
        if missing:
            raise ValueError(
                f"vocab.txt at {vocab_txt} lacks required special tokens "
                f"{missing}; refusing to guess bert-base ids for a "
                "nonstandard vocab"
            )
        cls_id, sep_id = vocab["[CLS]"], vocab["[SEP]"]
        tok.post_processor = TemplateProcessing(
            single="[CLS] $A [SEP]",
            pair="[CLS] $A [SEP] $B [SEP]",
            special_tokens=[("[CLS]", cls_id), ("[SEP]", sep_id)],
        )
        return tok

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        """-> [B, context_length] int32, right-padded."""
        out = np.full((len(texts), self.context_length), self.pad_id, np.int32)
        for i, enc in enumerate(self._tok.encode_batch(list(texts))):
            ids = enc.ids[: self.context_length]
            out[i, : len(ids)] = ids
        return out
