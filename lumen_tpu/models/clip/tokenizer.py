"""Text tokenization for CLIP-family models.

Wraps an HF ``tokenizers`` fast tokenizer (``tokenizer.json`` in the model
dir — same artifact the reference loads, ``onnxrt_backend.py:307-376``) and
produces fixed-length right-padded id batches for the text tower.
"""

from __future__ import annotations

import os

import numpy as np


class ClipTokenizer:
    def __init__(self, tokenizer, context_length: int, pad_id: int = 0):
        self._tok = tokenizer
        self.context_length = context_length
        self.pad_id = pad_id

    @classmethod
    def from_model_dir(cls, model_dir: str, context_length: int) -> "ClipTokenizer":
        from tokenizers import Tokenizer

        path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.exists(path):
            raise FileNotFoundError(f"tokenizer.json not found in {model_dir}")
        tok = Tokenizer.from_file(path)
        pad_id = 0
        if tok.padding is not None and "pad_id" in tok.padding:
            pad_id = tok.padding["pad_id"]
        tok.no_padding()  # we pad ourselves to the static context length
        tok.enable_truncation(max_length=context_length)
        return cls(tok, context_length, pad_id)

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        """-> [B, context_length] int32, right-padded."""
        out = np.full((len(texts), self.context_length), self.pad_id, np.int32)
        for i, enc in enumerate(self._tok.encode_batch(list(texts))):
            ids = enc.ids[: self.context_length]
            out[i, : len(ids)] = ids
        return out
