"""CLIP in Flax: ViT image tower + causal text transformer.

Replaces the reference's opaque ONNX graph pair (vision.onnx + text.onnx,
``packages/lumen-clip/src/lumen_clip/backends/onnxrt_backend.py:72-745``)
and its torch/OpenCLIP path (``torch_backend.py:78-883``) with explicit
modules whose parameter names line up with HF checkpoints (q/k/v/out proj,
fc1/fc2) so weight conversion is mechanical and the tensor-parallel rules in
``lumen_tpu.parallel.sharding`` apply unchanged.

Layout notes: images are NHWC (TPU-native); HF/torch NCHW checkpoints only
affect the patch-embed kernel layout, handled in ``convert.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.attention import attention
from ...ops.quant import QDense


@dataclass(frozen=True)
class TowerConfig:
    width: int
    layers: int
    heads: int


@dataclass(frozen=True)
class CLIPConfig:
    embed_dim: int = 512
    image_size: int = 224
    patch_size: int = 32
    vision: TowerConfig = field(default_factory=lambda: TowerConfig(768, 12, 12))
    text: TowerConfig = field(default_factory=lambda: TowerConfig(512, 12, 8))
    vocab_size: int = 49408
    context_length: int = 77
    hidden_act: str = "quick_gelu"
    layer_norm_eps: float = 1e-5
    #: EOT/EOS token id for text pooling; None = argmax convention (OpenAI
    #: CLIP's EOT is the highest vocab id, so argmax finds it).
    eot_token_id: int | None = None
    #: Text tower architecture: "clip" (causal pre-LN transformer, EOT
    #: pooling) or "bert" (ChineseCLIP: bidirectional post-LN BERT with
    #: padding mask, CLS pooling — reference loads these via the
    #: ChineseCLIPModel torch path, ``torch_backend.py:340-393``).
    text_arch: str = "clip"
    text_hidden_act: str | None = None  # None -> hidden_act ("gelu" for bert)
    text_layer_norm_eps: float | None = None  # None -> layer_norm_eps
    pad_token_id: int = 0  # bert padding-mask id ([PAD]=0 for BERT vocabs)
    #: Serving-side text length cap. BERT checkpoints carry a 512-row
    #: position table (kept full size for checkpoint parity) but queries
    #: are short — running every encode at 512 would pay ~100x the
    #: attention FLOPs. The tower slices positions to the actual input
    #: length, so the tokenizer/batcher pad to this instead.
    text_serving_length: int | None = None
    #: W8A8 int8 for the transformer blocks' projections (q/k/v/out,
    #: fc1/fc2): batch image embedding is MXU-compute-bound, and TPU int8
    #: peak is ~2x bf16 (v5e: 394.7 TOPS vs 197.1 TFLOP/s) — unlike the
    #: VLM decoder, where int8 buys bandwidth, here it buys FLOPs. Patch
    #: embed, position/class embeddings, layernorms, and the final
    #: projection stay full precision. Set by the serving layer
    #: (backend_settings.quantize); ``quantize_clip_int8`` in convert.py
    #: builds the (q, scale) tree. The BERT text tower (ChineseCLIP) is
    #: not quantized (its text encode is a tiny fraction of serve cost).
    weight_quant: str | None = None  # None | "int8"
    weight_quant_kernel: str = "dynamic"  # "dynamic" (W8A8 MXU) | "dequant"

    @property
    def serving_text_length(self) -> int:
        return min(self.text_serving_length or self.context_length, self.context_length)

    @classmethod
    def tiny(cls) -> "CLIPConfig":
        """Small config for tests (fast CPU parity runs)."""
        return cls(
            embed_dim=32,
            image_size=32,
            patch_size=16,
            vision=TowerConfig(64, 2, 4),
            text=TowerConfig(48, 2, 4),
            vocab_size=128,
            context_length=16,
        )

    @classmethod
    def from_hf(cls, cfg: dict[str, Any]) -> "CLIPConfig":
        """Build from an HF ``CLIPConfig``-style dict (``config.json``).
        ChineseCLIP (CN-CLIP) configs are recognized by their BERT-shaped
        text_config and mapped to the ``bert`` text arch."""
        v, t = cfg["vision_config"], cfg["text_config"]
        is_cnclip = (
            cfg.get("model_type") == "chinese_clip"
            or t.get("model_type") == "chinese_clip_text_model"
        )
        is_bert = is_cnclip or "type_vocab_size" in t
        return cls(
            embed_dim=cfg.get("projection_dim", 512),
            image_size=v.get("image_size", 224),
            patch_size=v.get("patch_size", 32),
            vision=TowerConfig(
                v.get("hidden_size", 768),
                v.get("num_hidden_layers", 12),
                v.get("num_attention_heads", 12),
            ),
            text=TowerConfig(
                t.get("hidden_size", 768 if is_bert else 512),
                t.get("num_hidden_layers", 12),
                t.get("num_attention_heads", 12 if is_bert else 8),
            ),
            vocab_size=t.get("vocab_size", 21128 if is_bert else 49408),
            context_length=t.get("max_position_embeddings", 512 if is_bert else 77),
            eot_token_id=t.get("eos_token_id"),
            hidden_act=v.get("hidden_act", "quick_gelu"),
            layer_norm_eps=v.get("layer_norm_eps", 1e-5),
            text_arch="bert" if is_bert else "clip",
            # Only meaningful for the bert tower; left None for plain CLIP
            # (TextTower uses the shared hidden_act/layer_norm_eps).
            text_hidden_act=t.get("hidden_act", "gelu") if is_bert else None,
            text_layer_norm_eps=t.get("layer_norm_eps", 1e-12) if is_bert else None,
            pad_token_id=t.get("pad_token_id", 0),
            # CN-CLIP's published context is 52 tokens; pad to that, not to
            # the checkpoint's 512-row position table. Generic BERT-text
            # CLIPs keep their full context (overridable via model_info
            # extra.text_serving_length in the manager).
            text_serving_length=52 if is_cnclip else None,
        )


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    if name == "gelu":
        # HF "gelu" is the exact erf form (BERT/ChineseCLIP text parity).
        return lambda x: jax.nn.gelu(x, approximate=False)
    if name in ("gelu_new", "gelu_pytorch_tanh"):
        return lambda x: jax.nn.gelu(x, approximate=True)
    return getattr(jax.nn, name)


def _block_dense(width: int, name: str, dtype, quant: str | None, quant_kernel: str):
    """Projection factory for transformer blocks: QDense when int8."""
    if quant == "int8":
        return QDense(width, kernel_mode=quant_kernel, name=name)
    return nn.Dense(width, name=name, dtype=dtype)


class Attention(nn.Module):
    width: int
    heads: int
    quant: str | None = None
    quant_kernel: str = "dynamic"

    @nn.compact
    def __call__(
        self, x: jax.Array, causal: bool = False, mask: jax.Array | None = None
    ) -> jax.Array:
        b, s, _ = x.shape
        head_dim = self.width // self.heads
        dense = lambda name: _block_dense(
            self.width, name, x.dtype, self.quant, self.quant_kernel
        )
        q = dense("q_proj")(x).reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)
        k = dense("k_proj")(x).reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)
        v = dense("v_proj")(x).reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)
        out = attention(q, k, v, causal=causal, mask=mask)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, self.width)
        return dense("out_proj")(out)


class Mlp(nn.Module):
    width: int
    hidden_act: str
    quant: str | None = None
    quant_kernel: str = "dynamic"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = _block_dense(
            self.width * 4, "fc1", x.dtype, self.quant, self.quant_kernel
        )(x)
        h = _act(self.hidden_act)(h)
        return _block_dense(
            self.width, "fc2", x.dtype, self.quant, self.quant_kernel
        )(h)


class Block(nn.Module):
    width: int
    heads: int
    hidden_act: str
    eps: float
    quant: str | None = None
    quant_kernel: str = "dynamic"

    @nn.compact
    def __call__(self, x: jax.Array, causal: bool = False) -> jax.Array:
        # Pre-LN residual blocks (CLIP layout).
        x = x + Attention(
            self.width, self.heads, self.quant, self.quant_kernel, name="attn"
        )(
            nn.LayerNorm(epsilon=self.eps, name="ln1", dtype=x.dtype)(x), causal=causal
        )
        x = x + Mlp(self.width, self.hidden_act, self.quant, self.quant_kernel, name="mlp")(
            nn.LayerNorm(epsilon=self.eps, name="ln2", dtype=x.dtype)(x)
        )
        return x


class BertBlock(nn.Module):
    """Post-LN residual block (BERT layout, used by ChineseCLIP's text
    encoder): LayerNorm AFTER each residual add, biased projections,
    bidirectional attention with a padding mask."""

    width: int
    heads: int
    hidden_act: str
    eps: float

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        h = Attention(self.width, self.heads, name="attn")(x, mask=mask)
        x = nn.LayerNorm(epsilon=self.eps, name="ln1", dtype=x.dtype)(x + h)
        h = Mlp(self.width, self.hidden_act, name="mlp")(x)
        return nn.LayerNorm(epsilon=self.eps, name="ln2", dtype=x.dtype)(x + h)


class BertTextTower(nn.Module):
    """ChineseCLIP text tower: BERT encoder + CLS pooling + projection
    (HF ``ChineseCLIPModel.get_text_features`` takes the last hidden
    state's [CLS] through ``text_projection`` — the reference works around
    the same model's pooler bug identically, ``torch_backend.py:340-393``)."""

    cfg: CLIPConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> jax.Array:
        c = self.cfg
        t = c.text
        eps = c.text_layer_norm_eps or c.layer_norm_eps
        act = c.text_hidden_act or "gelu"
        s = input_ids.shape[1]
        x = nn.Embed(c.vocab_size, t.width, name="word_embeddings")(input_ids)
        pos = self.param(
            "position_embedding", nn.initializers.normal(0.02), (c.context_length, t.width)
        )
        # Single-segment inputs: token type 0 everywhere (the table is kept
        # 2-row for checkpoint parity).
        tt = self.param(
            "token_type_embedding", nn.initializers.normal(0.02), (2, t.width)
        )
        x = x + pos[:s].astype(x.dtype) + tt[0].astype(x.dtype)
        x = nn.LayerNorm(epsilon=eps, name="embed_ln", dtype=x.dtype)(x)
        # Bidirectional with right-padding masked out: [B, 1, 1, S].
        mask = (input_ids != c.pad_token_id)[:, None, None, :]
        for i in range(t.layers):
            x = BertBlock(t.width, t.heads, act, eps, name=f"blocks_{i}")(x, mask)
        pooled = x[:, 0]  # [CLS]
        return nn.Dense(c.embed_dim, use_bias=False, name="projection", dtype=x.dtype)(pooled)


class PatchEmbed(nn.Module):
    """Non-overlapping patch embedding as reshape + matmul, NOT a conv.

    A patch-stride PxP conv IS patch extraction followed by a [P*P*C, W]
    matmul; spelling it that way hands XLA one large MXU-shaped dot
    instead of a stride-32 convolution window to tile (round-4 verdict:
    CLIP MFU attribution flagged the patch-embed conv lowering). The
    parameter keeps the conv's HWIO layout and ``<name>/kernel`` path, so
    converted checkpoints (``clip/convert.py`` ``conv_kernel``) load
    unchanged. Identity with the conv formulation is pinned by
    ``scripts/run_arch_parity.py`` (HF CLIP runs the conv)."""

    width: int
    patch: int
    use_bias: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        p, w = self.patch, self.width
        b, h, ww, ch = x.shape
        k = self.param(
            "kernel", nn.initializers.lecun_normal(), (p, p, ch, w)
        )
        x = x.reshape(b, h // p, p, ww // p, p, ch)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, (h // p) * (ww // p), p * p * ch)
        out = x @ k.reshape(p * p * ch, w).astype(x.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (w,))
            out = out + bias.astype(out.dtype)
        return out


class VisionTower(nn.Module):
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, pixel_values: jax.Array) -> jax.Array:
        """[B, H, W, 3] preprocessed floats -> [B, embed_dim] (unnormalized)."""
        c = self.cfg
        v = c.vision
        x = PatchEmbed(v.width, c.patch_size, name="patch_embed")(pixel_values)
        b = x.shape[0]
        cls_tok = self.param("class_embedding", nn.initializers.normal(0.02), (v.width,))
        x = jnp.concatenate([jnp.broadcast_to(cls_tok, (b, 1, v.width)).astype(x.dtype), x], axis=1)
        n_pos = x.shape[1]
        pos = self.param("position_embedding", nn.initializers.normal(0.02), (n_pos, v.width))
        x = x + pos.astype(x.dtype)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="pre_ln", dtype=x.dtype)(x)
        for i in range(v.layers):
            x = Block(v.width, v.heads, c.hidden_act, c.layer_norm_eps,
                      c.weight_quant, c.weight_quant_kernel, name=f"blocks_{i}")(x)
        pooled = x[:, 0]
        pooled = nn.LayerNorm(epsilon=c.layer_norm_eps, name="post_ln", dtype=x.dtype)(pooled)
        return nn.Dense(c.embed_dim, use_bias=False, name="projection", dtype=x.dtype)(pooled)


class TextTower(nn.Module):
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> jax.Array:
        """[B, S] token ids (right-padded) -> [B, embed_dim] pooled at the
        EOT position (= argmax of token id, the CLIP convention: EOT has the
        highest id in the vocab)."""
        c = self.cfg
        t = c.text
        emb = nn.Embed(c.vocab_size, t.width, name="token_embedding")
        x = emb(input_ids)
        pos = self.param("position_embedding", nn.initializers.normal(0.02), (c.context_length, t.width))
        s = input_ids.shape[1]
        x = x + pos[:s].astype(x.dtype)
        for i in range(t.layers):
            x = Block(t.width, t.heads, c.hidden_act, c.layer_norm_eps,
                      c.weight_quant, c.weight_quant_kernel, name=f"blocks_{i}")(
                x, causal=True
            )
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="final_ln", dtype=x.dtype)(x)
        if c.eot_token_id is not None:
            # First occurrence of the configured EOT id (HF convention).
            eot = jnp.argmax((input_ids == c.eot_token_id).astype(jnp.int32), axis=-1)
        else:
            eot = jnp.argmax(input_ids, axis=-1)
        pooled = x[jnp.arange(x.shape[0]), eot]
        return nn.Dense(c.embed_dim, use_bias=False, name="projection", dtype=x.dtype)(pooled)


class CLIPModel(nn.Module):
    """Dual-tower CLIP; ``logit_scale`` is the exported temperature
    (reference extracts it via ``get_temperature()``,
    ``torch_backend.py:830-856``)."""

    cfg: CLIPConfig

    def setup(self):
        self.vision = VisionTower(self.cfg, name="vision")
        text_cls = BertTextTower if self.cfg.text_arch == "bert" else TextTower
        self.text = text_cls(self.cfg, name="text")
        self.logit_scale = self.param(
            "logit_scale", nn.initializers.constant(jnp.log(1 / 0.07)), ()
        )

    def encode_image(self, pixel_values: jax.Array, normalize: bool = True) -> jax.Array:
        z = self.vision(pixel_values)
        return _maybe_normalize(z, normalize)

    def encode_text(self, input_ids: jax.Array, normalize: bool = True) -> jax.Array:
        z = self.text(input_ids)
        return _maybe_normalize(z, normalize)

    def __call__(self, pixel_values: jax.Array, input_ids: jax.Array):
        img = self.encode_image(pixel_values)
        txt = self.encode_text(input_ids)
        scale = jnp.exp(self.logit_scale)
        logits_per_image = scale * img @ txt.T
        return {
            "image_embeds": img,
            "text_embeds": txt,
            "logits_per_image": logits_per_image,
            "logits_per_text": logits_per_image.T,
        }


def _maybe_normalize(z: jax.Array, normalize: bool) -> jax.Array:
    if not normalize:
        return z
    # fp32 norm for stability regardless of compute dtype; unit-norm output
    # is the backend contract (reference base.py:15-19).
    z32 = z.astype(jnp.float32)
    return z32 / jnp.maximum(jnp.linalg.norm(z32, axis=-1, keepdims=True), 1e-12)
