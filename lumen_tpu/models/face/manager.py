"""Face pipeline manager: detect -> align -> embed on TPU.

Business logic of the reference's ``FaceModelManager``
(``packages/lumen-face/src/lumen_face/general_face/face_model.py:45-515``)
with the hot math moved on-device:

- detection decode (anchors, distance2bbox/kps, top-k, NMS) is one jitted
  program per image-batch (the reference does all of it in numpy per image,
  ``onnxrt_backend.py:882-1290``);
- recognition embeds N aligned crops as ONE batched call (the reference
  loops faces sequentially, SURVEY.md §3.4 note);
- host side keeps the CV parts: JPEG decode, letterbox, coordinate unmap,
  similarity-transform alignment (``_align_face_5points``,
  ``onnxrt_backend.py:1382-1416``).
"""

from __future__ import annotations

import copy
import logging
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...core.model_info import dataclass_from_extra, load_model_info
from ...ops.image import letterbox_numpy
from ...ops.nms import nms_jax
from ...runtime.batcher import (
    MicroBatcher,
    batch_wait_timeout,
    mesh_buckets,
    mesh_sharded,
    warmup_batcher,
)
from ...runtime.decode_pool import get_decode_pool
from ...runtime.fleet import (
    batcher_name,
    build_fleet,
    each_batcher,
    plan_replicas,
    replicate_all,
    topology_extra,
)
from ...runtime.quarantine import guarded_key
from ...runtime.result_cache import get_result_cache, make_namespace
from ...runtime.policy import get_policy
from ...runtime.weights import load_safetensors
from .convert import convert_face_checkpoint
from .modeling import (
    ARCFACE_TEMPLATE,
    DetectorConfig,
    FaceDetector,
    IResNet,
    IResNetConfig,
    decode_detections,
)

logger = logging.getLogger(__name__)


@dataclass
class FaceSpec:
    """Pack spec: preprocessing + thresholds. Defaults match the InsightFace
    pack constants (reference ``insightface_specs.py:11-159``); overridable
    via model_info ``extra_metadata.insightface``."""

    det_size: int = 640
    det_mean: float = 127.5
    det_std: float = 128.0
    score_threshold: float = 0.4
    nms_threshold: float = 0.4
    rec_size: int = 112
    rec_mean: float = 127.5
    rec_std: float = 127.5
    rec_color: str = "rgb"  # some packs want bgr crops
    max_detections: int = 128
    # Default detection size gate (max(face w, h) in px); stock packs set
    # 32/1000 (reference insightface_specs min_face/max_face).
    min_face: float = 0.0
    max_face: float = float("inf")

    @classmethod
    def from_extra(cls, extra: dict | None) -> "FaceSpec":
        spec = cls()
        for key, value in (extra or {}).items():
            if hasattr(spec, key):
                setattr(spec, key, value)
        return spec


@dataclass
class FaceDetection:
    bbox: np.ndarray  # [4] x1 y1 x2 y2 (original image coords)
    confidence: float
    landmarks: np.ndarray | None = None  # [5, 2]
    embedding: np.ndarray | None = None  # [512] unit-norm


class FaceManager:
    def __init__(
        self,
        model_dir: str,
        dtype: str = "bfloat16",
        batch_size: int = 8,
        max_batch_latency_ms: float = 5.0,
        detector_cfg: DetectorConfig | None = None,
        embedder_cfg: IResNetConfig | None = None,
        mesh_axes: dict[str, int] | None = None,
        warmup: bool = False,
        allow_random_init: bool = False,
    ):
        self.model_dir = model_dir
        self.info = load_model_info(model_dir)
        self.model_id = self.info.name
        # Spec precedence matches the reference (``_apply_pack_overrides``,
        # onnxrt_backend.py:266-285): known-pack overrides are applied ON TOP
        # of the manifest's extras — for a stock pack name the pack constants
        # win, so the same model dir behaves identically on both stacks.
        from .packs import pack_overrides

        merged_extra = {**(self.info.extra("insightface") or {}), **pack_overrides(self.info.name)}
        self.spec = FaceSpec.from_extra(merged_extra)
        self.policy = get_policy(dtype)
        self.batch_size = batch_size
        self.max_batch_latency_ms = max_batch_latency_ms
        # Replica fleet (LUMEN_REPLICAS / LUMEN_REPLICAS_FACE): one mesh
        # slice per replica; the single all-device mesh when N=1.
        self.fleet_plan = plan_replicas("face", mesh_axes)
        self.mesh = self.fleet_plan.meshes[0]
        self.warmup = warmup
        # Architecture comes from the model dir's manifest
        # (extra_metadata.detector / .embedder), explicit args win (tests).
        self.det_cfg = detector_cfg or self._detector_cfg_from_info()
        self.rec_cfg = embedder_cfg or self._embedder_cfg_from_info()
        self.detector = FaceDetector(self.det_cfg)
        self.embedder = IResNet(self.rec_cfg)
        self.allow_random_init = allow_random_init
        self._initialized = False

    def _detector_cfg_from_info(self) -> DetectorConfig:
        return dataclass_from_extra(
            DetectorConfig,
            self.info.extra("detector"),
            defaults={"input_size": self.spec.det_size},
            tuple_keys=("strides",),
        )

    def _embedder_cfg_from_info(self) -> IResNetConfig:
        defaults = {"input_size": self.spec.rec_size}
        if self.info.embedding_dim:
            defaults["embed_dim"] = self.info.embedding_dim
        return dataclass_from_extra(
            IResNetConfig, self.info.extra("embedder"), defaults=defaults, tuple_keys=("layers",)
        )

    # -- init -------------------------------------------------------------

    def _load_variables(self, filename: str, module, example_shape, kind: str):
        path = os.path.join(self.model_dir, filename)
        if os.path.exists(path):
            state = load_safetensors(path)
            kw = {}
            if kind == "recognition":
                final_hw = self.rec_cfg.input_size // 16
                kw = {"final_c": self.rec_cfg.width * 8, "final_hw": final_hw}
            variables = convert_face_checkpoint(state, kind, **kw)
        elif self.allow_random_init:
            logger.warning("%s missing in %s; RANDOM INIT (allow_random_init=True, tests only)", filename, self.model_dir)
            variables = module.init(jax.random.PRNGKey(0), jnp.zeros(example_shape, jnp.float32))
            variables = dict(variables)
        else:
            # A missing checkpoint must hard-fail: serving random weights
            # returns confident garbage with HTTP 200s (round-1 verdict).
            raise FileNotFoundError(
                f"no {kind} weights in {self.model_dir}: expected {filename} "
                f"or a {kind} .onnx graph; pass allow_random_init=True only in tests"
            )
        variables["params"] = self.policy.cast_params(variables["params"])
        if "batch_stats" in variables:
            variables["batch_stats"] = self.policy.cast_params(variables["batch_stats"])
        # One placement per replica mesh ([0] is the primary); a 1-replica
        # plan is exactly the old single replicate().
        return replicate_all(variables, self.fleet_plan)

    def initialize(self) -> None:
        if self._initialized:
            return
        s = self.spec
        compute = self.policy.compute_dtype
        det_cfg = self.det_cfg
        from .graph import ArcFaceGraph, ScrfdGraph, find_onnx_models

        onnx_models = find_onnx_models(self.model_dir)

        if "detection" in onnx_models:
            # Real InsightFace pack: run the actual SCRFD graph via the
            # ONNX->JAX bridge (reference runs the same file through
            # onnxruntime, ``onnxrt_backend.py:485-745``).
            graph_det = ScrfdGraph.from_path(onnx_models["detection"], num_anchors=det_cfg.num_anchors)
            self._det_vars_fleet = replicate_all(dict(graph_det.module.params), self.fleet_plan)
            self.det_vars = self._det_vars_fleet[0]
            logger.info("face detector: SCRFD graph %s (%d MB params)", onnx_models["detection"], graph_det.module.param_bytes() >> 20)
            graph_det.module.release_weights()  # the meshes hold the weights now

            @jax.jit
            def run_detector(variables, images_u8):
                x = (images_u8.astype(jnp.float32) - s.det_mean) / s.det_std
                outs = graph_det(variables, x.transpose(0, 3, 1, 2))
                boxes, kps, scores = decode_detections(
                    outs,
                    det_cfg.input_size,
                    det_cfg.num_anchors,
                    max_detections=s.max_detections,
                    scores_are_logits=False,  # SCRFD graphs end in Sigmoid
                )
                keep = jax.vmap(lambda b, sc: nms_jax(b, sc, s.nms_threshold))(boxes, scores)
                return boxes, kps, scores, keep

        else:
            det_shape = (1, det_cfg.input_size, det_cfg.input_size, 3)
            self._det_vars_fleet = self._load_variables("detection.safetensors", self.detector, det_shape, "detection")
            self.det_vars = self._det_vars_fleet[0]

            @jax.jit
            def run_detector(variables, images_u8):
                x = (images_u8.astype(jnp.float32) - s.det_mean) / s.det_std
                outs = self.detector.apply(variables, x.astype(compute))
                boxes, kps, scores = decode_detections(
                    outs, det_cfg.input_size, det_cfg.num_anchors, max_detections=s.max_detections
                )
                # NMS over the full top-k candidate set; the confidence cut
                # happens host-side so a per-request conf_threshold below the
                # pack default still widens the result (NMS processes in score
                # order, so low-score candidates never suppress higher ones).
                keep = jax.vmap(lambda b, sc: nms_jax(b, sc, s.nms_threshold))(boxes, scores)
                return boxes, kps, scores, keep

        if "recognition" in onnx_models:
            graph_rec = ArcFaceGraph.from_path(onnx_models["recognition"])
            self._rec_vars_fleet = replicate_all(dict(graph_rec.module.params), self.fleet_plan)
            self.rec_vars = self._rec_vars_fleet[0]
            logger.info("face embedder: ArcFace graph %s", onnx_models["recognition"])
            graph_rec.module.release_weights()  # the meshes hold the weights now

            @jax.jit
            def run_embedder(variables, crops_u8):
                x = (crops_u8.astype(jnp.float32) - s.rec_mean) / s.rec_std
                emb = graph_rec(variables, x.transpose(0, 3, 1, 2)).astype(jnp.float32)
                return emb / jnp.maximum(jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)

        else:
            rec_shape = (1, self.rec_cfg.input_size, self.rec_cfg.input_size, 3)
            self._rec_vars_fleet = self._load_variables("recognition.safetensors", self.embedder, rec_shape, "recognition")
            self.rec_vars = self._rec_vars_fleet[0]

            @jax.jit
            def run_embedder(variables, crops_u8):
                x = (crops_u8.astype(jnp.float32) - s.rec_mean) / s.rec_std
                emb = self.embedder.apply(variables, x.astype(compute)).astype(jnp.float32)
                return emb / jnp.maximum(jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)

        self._run_detector = run_detector
        self._run_embedder = run_embedder
        dp = self.mesh.shape.get("data", 1)
        det_buckets = mesh_buckets(self.batch_size, dp)
        rec_buckets = mesh_buckets(max(self.batch_size, 16), dp)
        # Batcher fns dispatch async and return un-fetched device trees;
        # the MicroBatcher fetch worker makes the one blocking transfer
        # per batch (pipelined executor — batch k+1 stacks while k runs).
        def build_det(rid, mesh):
            variables = self._det_vars_fleet[rid or 0]
            return MicroBatcher(
                mesh_sharded(
                    lambda imgs, n, _v=variables: self._run_detector(_v, imgs),
                    mesh,
                ),
                max_batch=det_buckets[-1],
                max_latency_ms=self.max_batch_latency_ms,
                buckets=det_buckets,
                name=batcher_name("face-det", rid),
                replica=None if rid is None else f"r{rid}",
            ).start()

        def build_rec(rid, mesh):
            variables = self._rec_vars_fleet[rid or 0]
            return MicroBatcher(
                mesh_sharded(
                    lambda crops, n, _v=variables: self._run_embedder(_v, crops),
                    mesh,
                ),
                max_batch=rec_buckets[-1],
                max_latency_ms=self.max_batch_latency_ms,
                buckets=rec_buckets,
                name=batcher_name("face-rec", rid),
                replica=None if rid is None else f"r{rid}",
            ).start()

        self._det_batcher = build_fleet(self.fleet_plan, "face-det", build_det)
        self._rec_batcher = build_fleet(self.fleet_plan, "face-rec", build_rec)
        if self.warmup:
            t0 = time.perf_counter()
            ds, rs = self.det_cfg.input_size, self.rec_cfg.input_size
            for b in each_batcher(self._det_batcher):
                warmup_batcher(b, lambda n: np.zeros((n, ds, ds, 3), np.uint8))
            for b in each_batcher(self._rec_batcher):
                warmup_batcher(b, lambda n: np.zeros((n, rs, rs, 3), np.uint8))
            logger.info(
                "face warmup: %d+%d buckets in %.1fs",
                len(det_buckets), len(rec_buckets), time.perf_counter() - t0,
            )
        self._initialized = True
        logger.info("face manager ready: %s (det %d, rec %d)", self.model_id, self.det_cfg.input_size, self.rec_cfg.input_size)

    def close(self) -> None:
        if self._initialized:
            self._det_batcher.close()
            self._rec_batcher.close()
            self._initialized = False

    def topology(self) -> dict[str, str]:
        """Device topology + replica layout for the capability ``extra``."""
        return topology_extra(
            self.mesh,
            getattr(self, "_det_batcher", None),
            getattr(self, "_rec_batcher", None),
        )

    # -- caching ----------------------------------------------------------

    def _cache_ns(self, task: str) -> str:
        """Result-cache namespace, dtype-qualified (see
        :func:`~lumen_tpu.runtime.result_cache.make_namespace`) plus the
        decode-policy qualifier: every face task consumes decoded pixels,
        and scaled decode shifts thresholded detections at the margin —
        a disk-tier entry from another decode generation must miss."""
        from ...ops.image import DECODE_POLICY

        return make_namespace(
            "face", task, self.model_id, self.info.version,
            jnp.dtype(self.policy.compute_dtype).name, DECODE_POLICY,
        )

    # -- detection --------------------------------------------------------

    def detect_faces(
        self,
        image: bytes | np.ndarray,
        conf_threshold: float | None = None,
        size_min: float | None = None,
        size_max: float | None = None,
        max_faces: int | None = None,
        nms_threshold: float | None = None,
    ) -> list[FaceDetection]:
        """Detect faces in raw image bytes (or a pre-decoded array).

        Byte inputs route through the content-addressed result cache
        keyed on the raw payload + the detection options, BEFORE the
        decode pool — a repeated image skips decode and the device batch
        entirely. Array inputs (callers that already decoded, e.g.
        :meth:`detect_and_extract`) are never cached here; the byte-level
        caller owns the cache entry. Cached detections are deep-copied on
        every hit so callers may mutate their results freely."""
        self._ensure_ready()
        if isinstance(image, (bytes, bytearray)):
            options = {
                "conf_threshold": conf_threshold,
                "size_min": size_min,
                "size_max": size_max,
                "max_faces": max_faces,
                "nms_threshold": nms_threshold,
            }
            payload = bytes(image)
            ns = self._cache_ns("detect")
            key = guarded_key(ns, options, payload)
            return get_result_cache().get_or_compute(
                ns,
                options,
                payload,
                lambda: self._detect_faces_scaled(
                    image, conf_threshold, size_min, size_max, max_faces,
                    nms_threshold, fingerprint=key,
                ),
                clone=copy.deepcopy,
                key=key,
            )
        return self._detect_faces_impl(
            np.asarray(image), conf_threshold, size_min, size_max,
            max_faces, nms_threshold,
        )

    @staticmethod
    def _check_tensor(pixels: np.ndarray) -> np.ndarray:
        if pixels.dtype != np.uint8 or pixels.ndim != 3 or pixels.shape[2] != 3:
            raise ValueError(
                "tensor input must be a uint8 HWC RGB image (H, W, 3); "
                f"got {pixels.dtype} {tuple(pixels.shape)}"
            )
        return np.ascontiguousarray(pixels)

    def detect_faces_tensor(
        self, pixels: np.ndarray, raw: bytes | None = None, **det_kw
    ) -> list[FaceDetection]:
        """Pre-decoded RGB tensor (the ``tensor/raw`` wire path): zero
        decode-pool hops — the letterbox runs on the caller's thread and
        the pixels go straight to the detector batcher. Cached on the RAW
        pixel buffer (one sha256, same single-hash guarantee as the JPEG
        path) under a tensor-qualified namespace; coordinates come back
        in the tensor's own frame (decode_scale is 1 by definition —
        whoever decoded chose the resolution)."""
        self._ensure_ready()
        pixels = self._check_tensor(pixels)
        options = {
            "conf_threshold": None, "size_min": None, "size_max": None,
            "max_faces": None, "nms_threshold": None, **det_kw,
        }
        payload = raw if raw is not None else pixels.tobytes()
        ns = self._cache_ns("detect_tensor")
        key = guarded_key(ns, options, payload)
        return get_result_cache().get_or_compute(
            ns,
            options,
            payload,
            lambda: self._detect_faces_impl(
                pixels, options["conf_threshold"], options["size_min"],
                options["size_max"], options["max_faces"],
                options["nms_threshold"], fingerprint=key,
            ),
            clone=copy.deepcopy,
            key=key,
        )

    def detect_and_extract_tensor(
        self, pixels: np.ndarray, raw: bytes | None = None,
        max_faces: int | None = None, **det_kw
    ) -> list[FaceDetection]:
        """Tensor twin of :meth:`detect_and_extract`: detections WITH
        embeddings from a pre-decoded RGB tensor, no decode pool."""
        self._ensure_ready()
        pixels = self._check_tensor(pixels)
        options = {
            "conf_threshold": None, "size_min": None, "size_max": None,
            "nms_threshold": None, **det_kw, "max_faces": max_faces,
        }
        payload = raw if raw is not None else pixels.tobytes()
        ns = self._cache_ns("detect_and_embed_tensor")
        key = guarded_key(ns, options, payload)

        def _compute() -> list[FaceDetection]:
            faces = self._detect_faces_impl(
                pixels, options["conf_threshold"], options["size_min"],
                options["size_max"], max_faces, options["nms_threshold"],
                fingerprint=key,
            )
            if faces:
                self.embed_detections(pixels, faces)
            return faces

        return get_result_cache().get_or_compute(
            ns, options, payload, _compute, clone=copy.deepcopy, key=key
        )

    def _detect_faces_scaled(
        self, image_bytes: bytes, conf_threshold, size_min, size_max,
        max_faces, nms_threshold, fingerprint: str | None = None,
    ) -> list[FaceDetection]:
        """Bytes path with SCALED decode: an oversized photo decodes at
        reduced scale (never below the detector's input size), the decode
        factor is folded into the letterbox unmap, and results come back
        in ORIGINAL image coordinates — identical contract, ~4x less
        decode work."""
        decoded = get_decode_pool().run_decode(
            "decode_scaled", image_bytes,
            {"color": "rgb", "max_edge": self.det_cfg.input_size},
        )
        try:
            dscale, oh, ow = decoded.extras
            return self._detect_faces_impl(
                decoded.array, conf_threshold, size_min, size_max, max_faces,
                nms_threshold, fingerprint=fingerprint,
                decode_scale=dscale, orig_hw=(oh, ow),
            )
        finally:
            decoded.release()

    def _detect_faces_impl(
        self,
        img: np.ndarray,
        conf_threshold: float | None,
        size_min: float | None,
        size_max: float | None,
        max_faces: int | None,
        nms_threshold: float | None,
        fingerprint: str | None = None,
        decode_scale: float = 1.0,
        orig_hw: tuple[int, int] | None = None,
    ) -> list[FaceDetection]:
        """``decode_scale``/``orig_hw`` carry scaled-decode provenance: the
        letterbox unmap divides by ``letterbox_scale * decode_scale`` so
        boxes/landmarks (and the size gates) are in ORIGINAL coordinates
        no matter what resolution the host actually decoded."""
        h, w = orig_hw if orig_hw is not None else img.shape[:2]
        boxed, scale, pad_top, pad_left = letterbox_numpy(img, self.det_cfg.input_size)
        boxes, kps, scores, keep = self._det_batcher(boxed, fingerprint=fingerprint)
        return self.detections_from_outputs(
            boxes, kps, scores, keep,
            scale=scale * decode_scale, pad_top=pad_top, pad_left=pad_left,
            image_hw=(h, w),
            conf_threshold=conf_threshold, size_min=size_min, size_max=size_max,
            max_faces=max_faces, nms_threshold=nms_threshold,
        )

    def detections_from_outputs(
        self,
        boxes: np.ndarray,
        kps: np.ndarray,
        scores: np.ndarray,
        keep: np.ndarray,
        *,
        scale: float,
        pad_top: int,
        pad_left: int,
        image_hw: tuple[int, int],
        conf_threshold: float | None = None,
        size_min: float | None = None,
        size_max: float | None = None,
        max_faces: int | None = None,
        nms_threshold: float | None = None,
    ) -> list[FaceDetection]:
        """Host half of detection: score/keep filtering + letterbox unmap.
        Shared by the per-request path above and the batch-ingest pipeline
        (``lumen_tpu/pipeline/photo.py``), so threshold semantics can't drift."""
        h, w = image_hw
        if nms_threshold is not None and nms_threshold != self.spec.nms_threshold:
            # The device program bakes the pack's NMS threshold into its
            # compiled keep-mask; a per-request override (reference meta
            # ``nms_threshold``, ``face_service.py:441``) re-suppresses the
            # full decoded candidate set host-side instead of recompiling
            # per distinct value.
            from ...ops.nms import nms_numpy

            finite = np.where(np.isfinite(scores))[0]
            keep = np.zeros(np.shape(scores), bool)
            if finite.size:
                kept = finite[
                    np.asarray(
                        nms_numpy(
                            np.asarray(boxes)[finite].astype(np.float32),
                            np.asarray(scores)[finite].astype(np.float32),
                            float(nms_threshold),
                        )
                    )
                ]
                keep[kept] = True
        conf = self.spec.score_threshold if conf_threshold is None else conf_threshold
        # Size gate defaults come from the pack spec (min_face/max_face);
        # explicit request values still win.
        size_min = self.spec.min_face if size_min is None else size_min
        size_max = self.spec.max_face if size_max is None else size_max
        results: list[FaceDetection] = []
        for i in np.argsort(-scores):
            if not keep[i] or not np.isfinite(scores[i]) or scores[i] < conf:
                continue
            # Undo letterbox: subtract padding, divide by scale, clip.
            box = boxes[i].astype(np.float64)
            box[[0, 2]] = (box[[0, 2]] - pad_left) / scale
            box[[1, 3]] = (box[[1, 3]] - pad_top) / scale
            box = np.clip(box, [0, 0, 0, 0], [w, h, w, h])
            bw, bh = box[2] - box[0], box[3] - box[1]
            if bw <= 0 or bh <= 0:  # degenerate prediction
                continue
            side = max(bw, bh)
            if not (size_min <= side <= size_max):
                continue
            lm = kps[i].astype(np.float64)
            lm[:, 0] = (lm[:, 0] - pad_left) / scale
            lm[:, 1] = (lm[:, 1] - pad_top) / scale
            results.append(
                FaceDetection(bbox=box.astype(np.float32), confidence=float(scores[i]), landmarks=lm.astype(np.float32))
            )
            if max_faces is not None and len(results) >= max_faces:
                break
        return results

    # -- recognition ------------------------------------------------------

    def align_crop(self, img: np.ndarray, landmarks: np.ndarray) -> np.ndarray:
        """5-point similarity-transform alignment to the canonical ArcFace
        112x112 template (reference ``_align_face_5points``). 68-point
        (iBUG) landmark sets reduce to the canonical 5 first — the
        reference contract accepts 68 but silently skips alignment for
        them (``onnxrt_backend.py:1327-1332``); deriving the 5 keeps the
        embedding aligned either way."""
        import cv2

        landmarks = np.asarray(landmarks, np.float32)
        if landmarks.shape == (68, 2):
            landmarks = np.stack(
                [
                    landmarks[36:42].mean(0),  # left eye center
                    landmarks[42:48].mean(0),  # right eye center
                    landmarks[30],  # nose tip
                    landmarks[48],  # left mouth corner
                    landmarks[54],  # right mouth corner
                ]
            )
        template = np.asarray(ARCFACE_TEMPLATE, np.float32) * (self.rec_cfg.input_size / 112.0)
        matrix, _ = cv2.estimateAffinePartial2D(landmarks, template, method=cv2.LMEDS)
        if matrix is None:
            return self._center_crop(img)
        return cv2.warpAffine(img, matrix, (self.rec_cfg.input_size, self.rec_cfg.input_size))

    def _center_crop(self, img: np.ndarray) -> np.ndarray:
        import cv2

        return cv2.resize(img, (self.rec_cfg.input_size, self.rec_cfg.input_size))

    def extract_embedding(
        self, face_image: bytes | np.ndarray, landmarks: np.ndarray | None = None
    ) -> np.ndarray:
        self._ensure_ready()
        if isinstance(face_image, (bytes, bytearray)):
            # Cache on the raw crop bytes + landmarks, before the decode
            # pool; hits return private copies (in-place caller mutation
            # must not poison the store).
            options = {
                "landmarks": None if landmarks is None
                else np.asarray(landmarks, np.float32).tolist()
            }
            payload = bytes(face_image)
            ns = self._cache_ns("embed")
            key = guarded_key(ns, options, payload)
            def _decode_and_embed():
                decoded = get_decode_pool().run_decode(
                    "decode", face_image, {"color": "rgb"}
                )
                try:
                    return self._extract_embedding_impl(
                        decoded.array, landmarks, fingerprint=key
                    )
                finally:
                    decoded.release()

            return get_result_cache().get_or_compute(
                ns,
                options,
                payload,
                _decode_and_embed,
                clone=np.copy,
                key=key,
            )
        return self._extract_embedding_impl(np.asarray(face_image), landmarks)

    def _extract_embedding_impl(
        self, img: np.ndarray, landmarks: np.ndarray | None, fingerprint: str | None = None
    ) -> np.ndarray:
        crop = self.align_crop(img, landmarks) if landmarks is not None else self._center_crop(img)
        if self.spec.rec_color == "bgr":
            crop = crop[:, :, ::-1]
        return self._rec_batcher(np.ascontiguousarray(crop), fingerprint=fingerprint)

    def detect_and_extract(
        self, image_bytes: bytes, max_faces: int | None = None, **det_kw
    ) -> list[FaceDetection]:
        # Whole-pipeline cache entry (detections WITH embeddings), keyed on
        # the raw payload + every detection knob. Knobs are normalized to
        # the full explicit set (same shape detect_faces keys with) so an
        # omitted kwarg and an explicit None — identical semantics — hash
        # to ONE entry instead of two.
        self._ensure_ready()
        options = {
            "conf_threshold": None,
            "size_min": None,
            "size_max": None,
            "nms_threshold": None,
            **det_kw,
            "max_faces": max_faces,
        }
        payload = bytes(image_bytes)
        ns = self._cache_ns("detect_and_embed")
        key = guarded_key(ns, options, payload)
        return get_result_cache().get_or_compute(
            ns,
            options,
            payload,
            lambda: self._detect_and_extract_impl(image_bytes, max_faces, det_kw),
            clone=copy.deepcopy,
            key=key,
        )

    def _detect_and_extract_impl(
        self, image_bytes: bytes, max_faces: int | None, det_kw: dict
    ) -> list[FaceDetection]:
        # Decode once (on the shared pool — never on the gRPC handler
        # thread), SCALED: the detector never needs more than its input
        # size, and embedding crops are resized to the recognizer's input
        # anyway. Detection results stay in original coordinates; the
        # decode factor maps them back onto the decoded array for crops.
        decoded = get_decode_pool().run_decode(
            "decode_scaled", image_bytes,
            {"color": "rgb", "max_edge": self.det_cfg.input_size},
        )
        try:
            dscale, oh, ow = decoded.extras
            img = decoded.array
            faces = self._detect_faces_impl(
                img, det_kw.get("conf_threshold"), det_kw.get("size_min"),
                det_kw.get("size_max"), max_faces, det_kw.get("nms_threshold"),
                decode_scale=dscale, orig_hw=(oh, ow),
            )
            if not faces:
                return faces
            self.embed_detections(img, faces, coord_scale=dscale)
            return faces
        finally:
            decoded.release()

    def embed_detections(
        self, img: np.ndarray, faces: list[FaceDetection], coord_scale: float = 1.0
    ) -> None:
        """Fill ``embedding`` on each detection: align-crop (or bbox-crop
        fallback), per-spec color order, ONE coalesced embedder call. Shared
        with the batch-ingest pipeline. ``coord_scale`` maps detections in
        ORIGINAL coordinates onto a scaled-decoded ``img`` (decoded/original
        edge ratio; 1.0 = full decode)."""
        crops = []
        for f in faces:
            lm = (
                np.asarray(f.landmarks, np.float32) * coord_scale
                if f.landmarks is not None
                else None
            )
            crop = self.align_crop(img, lm) if lm is not None else None
            if crop is None:
                x1, y1, x2, y2 = [int(round(v * coord_scale)) for v in f.bbox]
                crop = self._center_crop(img[max(y1, 0) : y2, max(x1, 0) : x2])
            if self.spec.rec_color == "bgr":
                crop = crop[:, :, ::-1]
            crops.append(np.ascontiguousarray(crop))
        # Concurrent submits coalesce into one batched device call. The
        # wait shares the compile-tolerant default — a cold rec-bucket
        # compile through the tunnel can exceed a fixed 60s.
        futures = [self._rec_batcher.submit(c) for c in crops]
        wait = batch_wait_timeout()
        for f, fut in zip(faces, futures):
            f.embedding = fut.result(timeout=wait)

    # -- comparisons (reference face_model.py:371-429) --------------------

    @staticmethod
    def compare_faces(emb1: np.ndarray, emb2: np.ndarray) -> float:
        return float(np.dot(emb1, emb2))

    @staticmethod
    def find_best_match(
        query: np.ndarray, gallery: np.ndarray, threshold: float = 0.35
    ) -> tuple[int, float] | None:
        if len(gallery) == 0:
            return None
        sims = gallery @ query
        idx = int(np.argmax(sims))
        if sims[idx] < threshold:
            return None
        return idx, float(sims[idx])

    @staticmethod
    def crop_face(image_bytes: bytes, bbox: np.ndarray, margin: float = 0.0) -> np.ndarray:
        decoded = get_decode_pool().run_decode("decode", image_bytes, {"color": "rgb"})
        try:
            img = decoded.array
            h, w = img.shape[:2]
            x1, y1, x2, y2 = bbox
            mw, mh = (x2 - x1) * margin, (y2 - y1) * margin
            x1, y1 = max(int(x1 - mw), 0), max(int(y1 - mh), 0)
            x2, y2 = min(int(x2 + mw), w), min(int(y2 + mh), h)
            # Copy out unconditionally: the decoded array may be a
            # shared-memory arena view whose slot is recycled on release,
            # and ascontiguousarray would return a full-width slice AS the
            # view — a returned crop must own its pixels.
            return np.array(img[y1:y2, x1:x2], copy=True)
        finally:
            decoded.release()

    def _ensure_ready(self) -> None:
        if not self._initialized:
            raise RuntimeError("FaceManager.initialize() not called")
