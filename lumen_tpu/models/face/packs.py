"""Known InsightFace model-pack specs.

Role of the reference's hardcoded pack table
(``packages/lumen-face/src/lumen_face/backends/insightface_specs.py:11-159``):
a model dir named after a stock InsightFace pack works without any
``extra_metadata`` — the spec defaults are filled from this table, then
overridden by whatever the manifest declares.

Values are the public InsightFace pack conventions: SCRFD detector at
640x640 with mean/std 127.5/128 and score/NMS thresholds 0.4, ArcFace-style
recognizer at 112x112 BGR with mean/std 127.5/127.5.
"""

from __future__ import annotations

_SCRFD_ARC = {
    "det_size": 640,
    "det_mean": 127.5,
    "det_std": 128.0,
    "score_threshold": 0.4,
    "nms_threshold": 0.4,
    "min_face": 32,
    "max_face": 1000,
    "rec_size": 112,
    "rec_mean": 127.5,
    "rec_std": 127.5,
    "rec_color": "bgr",
}

#: pack name -> spec overrides (merged under model_info extras)
PACK_SPECS: dict[str, dict] = {
    "antelopev2": dict(_SCRFD_ARC),
    "buffalo_l": dict(_SCRFD_ARC),
    "buffalo_m": dict(_SCRFD_ARC),
    "buffalo_s": dict(_SCRFD_ARC),
    "buffalo_sc": dict(_SCRFD_ARC),
}


def pack_overrides(model_id: str) -> dict:
    """Spec overrides for a known pack (EXACT match on the lowered model id,
    like the reference's ``PACK_SPECS.get(pack_key)`` — substring matching
    would silently flip preprocessing for unrelated models whose name merely
    contains a pack name); empty dict for unknown models."""
    return dict(PACK_SPECS.get(model_id.lower(), {}))
