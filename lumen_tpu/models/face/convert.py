"""Face checkpoint conversion.

Two sources:

- **native** checkpoints (the lumen-tpu ``jax`` runtime format): safetensors
  whose keys are '/'-separated Flax paths prefixed with the variable
  collection (``params/...`` or ``batch_stats/...``) — loaded directly;
- **torch IResNet** state dicts (InsightFace ArcFace layout: ``conv1``,
  ``bn1``, ``prelu``, ``layer{1-4}.{i}.{bn1,conv1,bn2,prelu,conv2,bn3,
  downsample.0,downsample.1}``, final ``bn2``, ``fc``, ``features``) —
  converted by rules. The FC kernel needs an NCHW->NHWC flatten permute
  because torch flattens [C, H, W] and flax flattens [H, W, C].
"""

from __future__ import annotations

import numpy as np

from ...runtime.weights import (
    WeightLoadError,
    apply_rules,
    conv_kernel,
    flatten_variables,
    is_native_checkpoint,
    split_collections,
)

__all__ = [
    "convert_face_checkpoint",
    "convert_iresnet",
    "fc_kernel_from_torch",
    "flatten_variables",
    "is_native_checkpoint",
    "split_collections",
]


def fc_kernel_from_torch(w: np.ndarray, c: int, h: int, ww: int) -> np.ndarray:
    """torch FC weight [out, C*H*W] -> flax Dense kernel [(H*W*C), out]."""
    out_dim = w.shape[0]
    return np.ascontiguousarray(
        w.reshape(out_dim, c, h, ww).transpose(0, 2, 3, 1).reshape(out_dim, h * ww * c).T
    )


def _bn(src: str, dst: str):
    return [
        (rf"{src}\.weight", rf"params/{dst}/scale", None),
        (rf"{src}\.bias", rf"params/{dst}/bias", None),
        (rf"{src}\.running_mean", rf"batch_stats/{dst}/mean", None),
        (rf"{src}\.running_var", rf"batch_stats/{dst}/var", None),
    ]


def iresnet_rules(final_c: int, final_hw: int):
    rules = [
        (r"conv1\.weight", r"params/stem_conv/kernel", conv_kernel),
        *_bn("bn1", "stem_bn"),
        (r"prelu\.weight", r"params/stem_prelu/alpha", None),
        (
            r"fc\.weight",
            r"params/fc/kernel",
            lambda w: fc_kernel_from_torch(w, final_c, final_hw, final_hw),
        ),
        (r"fc\.bias", r"params/fc/bias", None),
        *_bn("bn2", "final_bn"),
        *_bn("features", "features"),
    ]
    # layerS.I.* -> layer{S}_{I}/*
    rules += [
        (r"layer(\d+)\.(\d+)\.conv1\.weight", r"params/layer\1_\2/conv1/kernel", conv_kernel),
        (r"layer(\d+)\.(\d+)\.conv2\.weight", r"params/layer\1_\2/conv2/kernel", conv_kernel),
        (r"layer(\d+)\.(\d+)\.prelu\.weight", r"params/layer\1_\2/prelu/alpha", None),
        (r"layer(\d+)\.(\d+)\.downsample\.0\.weight", r"params/layer\1_\2/down_conv/kernel", conv_kernel),
    ]
    for bn_name in ("bn1", "bn2", "bn3"):
        rules += [
            (rf"layer(\d+)\.(\d+)\.{bn_name}\.weight", rf"params/layer\1_\2/{bn_name}/scale", None),
            (rf"layer(\d+)\.(\d+)\.{bn_name}\.bias", rf"params/layer\1_\2/{bn_name}/bias", None),
            (rf"layer(\d+)\.(\d+)\.{bn_name}\.running_mean", rf"batch_stats/layer\1_\2/{bn_name}/mean", None),
            (rf"layer(\d+)\.(\d+)\.{bn_name}\.running_var", rf"batch_stats/layer\1_\2/{bn_name}/var", None),
        ]
    rules += _bn(r"layer(\d+)\.(\d+)\.downsample\.1", r"layer\1_\2/down_bn")
    return rules


def convert_iresnet(state: dict[str, np.ndarray], final_c: int, final_hw: int) -> dict:
    flat = apply_rules(
        state,
        iresnet_rules(final_c, final_hw),
        drop=[r"num_batches_tracked"],
    )
    return split_collections(flat)


def convert_face_checkpoint(state: dict[str, np.ndarray], kind: str, **kw) -> dict:
    """-> {'params': ..., 'batch_stats': ...} variable collections."""
    if is_native_checkpoint(state):
        return split_collections(state)
    if kind == "recognition":
        return convert_iresnet(state, **kw)
    raise WeightLoadError(
        f"no conversion rules for non-native {kind!r} checkpoint "
        f"(keys like {sorted(state)[:4]}); re-export in the native format"
    )
