"""Face models in Flax: SCRFD-style detector + ArcFace (IResNet) embedder.

The reference runs InsightFace ONNX packs as opaque graphs and implements
the interesting logic around them (SCRFD decode, alignment —
``packages/lumen-face/src/lumen_face/backends/onnxrt_backend.py:485-1417``).
Here the nets are explicit Flax modules:

- :class:`FaceDetector` — anchor-free multi-stride detector with SCRFD
  output semantics: per stride s in {8, 16, 32}, ``num_anchors=2`` per cell,
  sigmoid scores, bbox distances (l, t, r, b) and 5-point kps distances,
  decoded by ``distance2bbox``/``distance2kps`` against anchor centers
  (reference decode: ``onnxrt_backend.py:425-470, 882-1154``).
- :class:`IResNet` — InsightFace's ArcFace recognition backbone (r18/r34/
  r50/r100): 3x3 stem, IBasicBlocks with BN-conv-BN-PReLU-conv-BN, final
  BN-dropout-FC-BN to a 512-d embedding; parameter names line up with the
  torch checkpoints for mechanical conversion.

All BatchNorms run in inference mode (serving framework; training face
models is out of scope for parity).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

# Canonical 5-point ArcFace alignment template for a 112x112 crop
# (lfw/"arcface_src" landmark positions, public InsightFace constant).
ARCFACE_TEMPLATE = (
    (38.2946, 51.6963),
    (73.5318, 51.5014),
    (56.0252, 71.7366),
    (41.5493, 92.3655),
    (70.7299, 92.2041),
)


@dataclass(frozen=True)
class DetectorConfig:
    input_size: int = 640
    strides: tuple[int, ...] = (8, 16, 32)
    num_anchors: int = 2
    num_kps: int = 5
    width: int = 64  # backbone base width
    fpn_width: int = 64

    @classmethod
    def tiny(cls) -> "DetectorConfig":
        return cls(input_size=64, width=8, fpn_width=8)


class ConvBnAct(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    act: bool = True

    @nn.compact
    def __call__(self, x):
        # Explicit k//2 padding, not "SAME": identical for stride 1 (odd
        # kernels) but torch-compatible at stride 2, where SAME pads (0, 1)
        # at even sizes vs torch's symmetric (1, 1) — the divergence that
        # broke converted-IResNet parity (scripts/run_arch_parity.py).
        p = self.kernel // 2
        x = nn.Conv(
            self.features,
            (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            padding=((p, p), (p, p)),
            use_bias=False,
            name="conv",
            dtype=x.dtype,
        )(x)
        x = nn.BatchNorm(use_running_average=True, name="bn", dtype=x.dtype)(x)
        if self.act:
            x = nn.relu(x)
        return x


class ResBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBnAct(self.features, stride=self.stride, name="conv1")(x)
        y = ConvBnAct(self.features, act=False, name="conv2")(y)
        if self.stride != 1 or x.shape[-1] != self.features:
            residual = ConvBnAct(self.features, kernel=1, stride=self.stride, act=False, name="down")(x)
        return nn.relu(y + residual)


class FaceDetector(nn.Module):
    """Multi-stride anchor-free face detector.

    Input: [B, S, S, 3] normalized floats. Output per stride: dict with
    ``scores`` [B, H*W*A], ``bbox`` [B, H*W*A, 4] (distances), ``kps``
    [B, H*W*A, 2*num_kps] (distances), flattened anchor-major like SCRFD.
    """

    cfg: DetectorConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        w = c.width
        # Backbone: stem + one res stage per stride level.
        x = ConvBnAct(w, stride=2, name="stem")(x)  # /2
        feats = []
        x = ResBlock(w, stride=2, name="stage1")(x)  # /4
        x = ResBlock(w * 2, stride=2, name="stage2")(x)  # /8
        feats.append(x)
        x = ResBlock(w * 4, stride=2, name="stage3")(x)  # /16
        feats.append(x)
        x = ResBlock(w * 8, stride=2, name="stage4")(x)  # /32
        feats.append(x)
        # FPN: top-down pathway.
        laterals = [
            ConvBnAct(c.fpn_width, kernel=1, name=f"lateral{i}")(f) for i, f in enumerate(feats)
        ]
        for i in range(len(laterals) - 2, -1, -1):
            up = jax.image.resize(
                laterals[i + 1],
                laterals[i].shape[:1] + laterals[i].shape[1:3] + laterals[i + 1].shape[3:],
                method="nearest",
            )
            laterals[i] = laterals[i] + up
        outs = {}
        head = _DetHead(c, name="head")  # shared across strides
        for stride, feat in zip(c.strides, laterals):
            outs[stride] = head(feat)
        return outs


class _DetHead(nn.Module):
    cfg: DetectorConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        a = c.num_anchors
        h = ConvBnAct(c.fpn_width, name="tower")(x)
        b, hh, ww, _ = h.shape
        scores = nn.Conv(a, (1, 1), name="cls", dtype=h.dtype)(h)
        bbox = nn.Conv(4 * a, (1, 1), name="reg", dtype=h.dtype)(h)
        kps = nn.Conv(2 * c.num_kps * a, (1, 1), name="kps", dtype=h.dtype)(h)
        return {
            "scores": scores.reshape(b, hh * ww * a),
            "bbox": bbox.reshape(b, hh * ww * a, 4),
            "kps": kps.reshape(b, hh * ww * a, 2 * c.num_kps),
        }


# -- ArcFace / IResNet ------------------------------------------------------


@dataclass(frozen=True)
class IResNetConfig:
    layers: tuple[int, ...] = (3, 4, 14, 3)  # r50
    embed_dim: int = 512
    input_size: int = 112
    width: int = 64

    @classmethod
    def r18(cls) -> "IResNetConfig":
        return cls(layers=(2, 2, 2, 2))

    @classmethod
    def r100(cls) -> "IResNetConfig":
        return cls(layers=(3, 13, 30, 3))

    @classmethod
    def tiny(cls) -> "IResNetConfig":
        return cls(layers=(1, 1, 1, 1), width=8, input_size=32, embed_dim=64)


class PReLU(nn.Module):
    """Channel-wise PReLU (torch-compatible)."""

    @nn.compact
    def __call__(self, x):
        alpha = self.param("alpha", nn.initializers.constant(0.25), (x.shape[-1],))
        return jnp.where(x >= 0, x, alpha.astype(x.dtype) * x)


class IBasicBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        bn = lambda name: nn.BatchNorm(use_running_average=True, epsilon=1e-5, name=name, dtype=x.dtype)
        # Explicit symmetric padding, NOT "SAME": torch Conv2d(3, s=2, p=1)
        # pads (1, 1) while SAME pads (0, 1) at even sizes — converted
        # InsightFace checkpoints diverge (cos 0.984) under SAME at the
        # stride-2 blocks. Caught by scripts/run_arch_parity.py (round 5).
        conv = lambda name, stride: nn.Conv(
            self.features, (3, 3), strides=(stride, stride), padding=((1, 1), (1, 1)),
            use_bias=False, name=name, dtype=x.dtype,
        )
        residual = x
        y = bn("bn1")(x)
        y = conv("conv1", 1)(y)
        y = bn("bn2")(y)
        y = PReLU(name="prelu")(y)
        y = conv("conv2", self.stride)(y)
        y = bn("bn3")(y)
        if self.stride != 1 or x.shape[-1] != self.features:
            residual = nn.Conv(
                self.features, (1, 1), strides=(self.stride, self.stride),
                use_bias=False, name="down_conv", dtype=x.dtype,
            )(x)
            residual = nn.BatchNorm(use_running_average=True, name="down_bn", dtype=x.dtype)(residual)
        return y + residual


class IResNet(nn.Module):
    """ArcFace recognition net: [B, 112, 112, 3] aligned crops (normalized
    (x-127.5)/128 upstream) -> [B, embed_dim] embeddings (unnormalized; the
    manager L2-normalizes, matching the backend contract)."""

    cfg: IResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = self.cfg
        x = nn.Conv(c.width, (3, 3), padding="SAME", use_bias=False, name="stem_conv", dtype=x.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, name="stem_bn", dtype=x.dtype)(x)
        x = PReLU(name="stem_prelu")(x)
        for stage, blocks in enumerate(c.layers):
            feats = c.width * (2**stage)
            for i in range(blocks):
                x = IBasicBlock(feats, stride=2 if i == 0 else 1, name=f"layer{stage + 1}_{i}")(x)
        x = nn.BatchNorm(use_running_average=True, name="final_bn", dtype=x.dtype)(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(c.embed_dim, name="fc", dtype=x.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=2e-5, name="features", dtype=x.dtype, use_scale=True, use_bias=True)(x)
        return x


# -- device-side SCRFD decode ----------------------------------------------


def anchor_centers(size: int, stride: int, num_anchors: int) -> jnp.ndarray:
    """[H*W*A, 2] pixel-space anchor centers for one stride (anchor-major
    per cell, matching the SCRFD flattening)."""
    n = size // stride
    ys, xs = jnp.meshgrid(jnp.arange(n), jnp.arange(n), indexing="ij")
    pts = jnp.stack([xs, ys], axis=-1).reshape(-1, 2) * stride
    pts = jnp.repeat(pts, num_anchors, axis=0)
    return pts.astype(jnp.float32)


def distance2bbox(centers: jnp.ndarray, distances: jnp.ndarray) -> jnp.ndarray:
    """(cx, cy) + (l, t, r, b) distances -> (x1, y1, x2, y2)."""
    x1 = centers[..., 0] - distances[..., 0]
    y1 = centers[..., 1] - distances[..., 1]
    x2 = centers[..., 0] + distances[..., 2]
    y2 = centers[..., 1] + distances[..., 3]
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def distance2kps(centers: jnp.ndarray, distances: jnp.ndarray) -> jnp.ndarray:
    """[..., 2K] kps distance offsets -> [..., K, 2] absolute points."""
    k = distances.shape[-1] // 2
    d = distances.reshape(*distances.shape[:-1], k, 2)
    return jnp.stack(
        [centers[..., None, 0] + d[..., 0], centers[..., None, 1] + d[..., 1]], axis=-1
    )


def decode_detections(
    outputs: dict[int, dict[str, jnp.ndarray]],
    input_size: int,
    num_anchors: int,
    stride_scale_distances: bool = True,
    max_detections: int = 128,
    scores_are_logits: bool = True,
):
    """Decode all strides to a fixed-size candidate set (jit-safe).

    Returns (boxes [B, N, 4], kps [B, N, K, 2], scores [B, N]) where N =
    ``max_detections``, selected by top-score across all strides; invalid
    slots carry score -inf. NMS runs afterwards (``ops.nms.nms_jax``).

    ``scores_are_logits``: the Flax detector head emits raw logits; real
    SCRFD ONNX graphs end in a Sigmoid, so their scores must pass through
    unchanged (reference consumes them directly, ``onnxrt_backend.py:
    882-1154``).
    """
    all_boxes, all_kps, all_scores = [], [], []
    for stride, out in outputs.items():
        centers = anchor_centers(input_size, stride, num_anchors)  # [M, 2]
        scale = float(stride) if stride_scale_distances else 1.0
        boxes = distance2bbox(centers[None], out["bbox"].astype(jnp.float32) * scale)
        kps = distance2kps(centers[None], out["kps"].astype(jnp.float32) * scale)
        scores = out["scores"].astype(jnp.float32)
        if scores_are_logits:
            scores = jax.nn.sigmoid(scores)
        all_boxes.append(boxes)
        all_kps.append(kps)
        all_scores.append(scores)
    boxes = jnp.concatenate(all_boxes, axis=1)
    kps = jnp.concatenate(all_kps, axis=1)
    scores = jnp.concatenate(all_scores, axis=1)
    k = min(max_detections, scores.shape[1])
    top_scores, idx = jax.lax.top_k(scores, k)
    boxes = jnp.take_along_axis(boxes, idx[..., None], axis=1)
    kps = jnp.take_along_axis(kps, idx[..., None, None], axis=1)
    return boxes, kps, top_scores
