"""Face model family: SCRFD-style detector + ArcFace embedder."""

from .convert import convert_face_checkpoint, flatten_variables
from .manager import FaceDetection, FaceManager, FaceSpec
from .modeling import (
    ARCFACE_TEMPLATE,
    DetectorConfig,
    FaceDetector,
    IResNet,
    IResNetConfig,
    anchor_centers,
    decode_detections,
    distance2bbox,
    distance2kps,
)

__all__ = [
    "FaceManager",
    "FaceDetection",
    "FaceSpec",
    "FaceDetector",
    "DetectorConfig",
    "IResNet",
    "IResNetConfig",
    "ARCFACE_TEMPLATE",
    "anchor_centers",
    "decode_detections",
    "distance2bbox",
    "distance2kps",
    "convert_face_checkpoint",
    "flatten_variables",
]
