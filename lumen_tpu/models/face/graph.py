"""Graph-backed face models: real InsightFace ONNX packs on TPU.

The reference serves actual buffalo_l / antelopev2 SCRFD + ArcFace graphs
through onnxruntime (``packages/lumen-face/src/lumen_face/backends/
onnxrt_backend.py:485-1290``). Here the same ``.onnx`` files load through
``lumen_tpu.onnx_bridge`` into jittable XLA programs, so ``face_detect`` /
``face_embed`` produce the *same answers* as the reference with the *same
weights* — no invented backbone, no conversion lossage.

SCRFD output contract (reference ``insightface_specs.py`` groups output
indices by TYPE): with ``fmc`` strides the graph emits
``[score_s0..score_s{fmc-1}, bbox_s0.., (kps_s0..)]``; scores are
post-sigmoid, bbox/kps are anchor distances in stride units. The adapter
regroups them per stride for ``decode_detections(scores_are_logits=False)``.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass


from ...onnx_bridge import OnnxModule

logger = logging.getLogger(__name__)

_DEFAULT_STRIDES = (8, 16, 32)


def find_onnx_models(model_dir: str) -> dict[str, str]:
    """Locate detector/recognizer ``.onnx`` files in a model dir (stock
    InsightFace pack layout: ``det_10g.onnx`` + ``w600k_r50.onnx``; native
    layout: ``detection.onnx`` + ``recognition.onnx``). Classification is
    by declared input size: recognizers take 112x112 crops."""
    found: dict[str, str] = {}
    for name in sorted(os.listdir(model_dir)):
        if not name.endswith(".onnx"):
            continue
        path = os.path.join(model_dir, name)
        stem = name.lower()
        if "rec" in stem or stem.startswith("w600k") or stem.startswith("glintr"):
            found.setdefault("recognition", path)
        elif "det" in stem or "scrfd" in stem:
            found.setdefault("detection", path)
        else:
            # fall back to input-shape sniffing
            try:
                mod = OnnxModule.from_path(path)
                shape = next(iter(mod.input_shapes().values()), ())
                hw = [d for d in shape[-2:] if isinstance(d, int)]
                key = "recognition" if hw and max(hw) <= 128 else "detection"
                found.setdefault(key, path)
            except Exception:  # noqa: BLE001 - unparseable file, skip
                logger.warning("skipping unparseable onnx file %s", path)
    return found


@dataclass
class ScrfdGraph:
    """SCRFD detector graph + output regrouping."""

    module: OnnxModule
    strides: tuple[int, ...]
    num_anchors: int
    with_kps: bool
    num_kps: int

    @classmethod
    def from_path(cls, path: str, num_anchors: int = 2) -> "ScrfdGraph":
        module = OnnxModule.from_path(path)
        n_out = len(module.output_names)
        if n_out % 3 == 0 and n_out >= 9:
            fmc = n_out // 3
            with_kps = True
        elif n_out % 2 == 0 and n_out >= 6:
            fmc = n_out // 2
            with_kps = False
        else:
            raise ValueError(
                f"unexpected SCRFD output count {n_out} in {path} "
                "(want fmc*2 or fmc*3 tensors)"
            )
        strides = _DEFAULT_STRIDES if fmc == 3 else tuple(8 * (2**i) for i in range(fmc))
        return cls(
            module=module,
            strides=strides,
            num_anchors=num_anchors,
            with_kps=with_kps,
            num_kps=5,
        )

    def __call__(self, params: dict, x_nchw) -> dict[int, dict]:
        """Run the graph; regroup outputs as ``{stride: {scores [B,M],
        bbox [B,M,4], kps [B,M,2K]}}`` for ``decode_detections``."""
        import jax.numpy as jnp

        outs = self.module(params, {self.module.input_names[0]: x_nchw})
        fmc = len(self.strides)
        b = x_nchw.shape[0]
        per_stride: dict[int, dict] = {}
        for i, stride in enumerate(self.strides):
            scores = jnp.asarray(outs[i]).reshape(b, -1)
            bbox = jnp.asarray(outs[fmc + i]).reshape(b, -1, 4)
            if self.with_kps:
                kps = jnp.asarray(outs[2 * fmc + i]).reshape(b, -1, 2 * self.num_kps)
            else:
                kps = jnp.zeros(bbox.shape[:2] + (2 * self.num_kps,), bbox.dtype)
            per_stride[stride] = {"scores": scores, "bbox": bbox, "kps": kps}
        return per_stride


@dataclass
class ArcFaceGraph:
    """Recognition graph: [B,3,112,112] -> [B,512] (normalization is the
    manager's job, matching the Flax path)."""

    module: OnnxModule

    @classmethod
    def from_path(cls, path: str) -> "ArcFaceGraph":
        return cls(module=OnnxModule.from_path(path))

    def __call__(self, params: dict, x_nchw):
        import jax.numpy as jnp

        out = self.module(params, {self.module.input_names[0]: x_nchw})[0]
        return jnp.asarray(out)
