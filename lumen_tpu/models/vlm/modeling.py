"""VLM in Flax: ViT vision encoder + Qwen2-style causal decoder with a
preallocated static KV cache.

Replaces the reference's three opaque ONNX sessions (vision.onnx +
embed.onnx + decoder.onnx, ``packages/lumen-vlm/src/lumen_vlm/backends/
onnxrt_backend.py:107-140``) with explicit modules. The decisive TPU change
is the cache: the reference grows numpy KV tensors by concat every step
(``onnxrt_backend.py:731-755``, ``:319-320``); here the cache is a
statically-shaped ``[B, kv_heads, max_seq, head_dim]`` buffer updated in
place with ``lax.dynamic_update_slice`` so the whole decode loop compiles
into one XLA program (see ``generate.py``).

Architecture notes (TPU-first, not a translation):
- decoder: RoPE + GQA + RMSNorm + SwiGLU — the Qwen2 family layout that
  FastVLM's language model uses (image token id 151646 is in the Qwen2
  vocab, reference ``onnxrt_backend.py:240-296``);
- vision: a plain ViT over large patches + 2-layer MLP projector
  (LLaVA-style). The reference's hybrid-conv FastViTHD exists to make CPUs
  fast; on TPU a patchified transformer keeps everything on the MXU;
- the image-token splice (reference ``_merge_embeddings:240-296``) is a
  fully jittable gather — no host round-trip, static output length.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.attention import attention, attention_cached, paged_attention, repeat_kv
from ...ops.quant import QDense


@dataclass(frozen=True)
class DecoderConfig:
    hidden_size: int = 896
    layers: int = 24
    heads: int = 14
    kv_heads: int = 2
    intermediate_size: int = 4864
    vocab_size: int = 151936
    head_dim: int | None = None  # None -> hidden_size // heads
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-6
    max_position_embeddings: int = 32768
    tie_word_embeddings: bool = True
    # --- Mixture-of-experts decoder (Qwen2-MoE layout; 0 experts = dense).
    # MoE layers replace the SwiGLU MLP with a top-k routed expert bank;
    # layer i is sparse iff (i+1) % moe_every == 0 (HF decoder_sparse_step
    # semantics). Routing uses exact capacity (no token drops) so outputs
    # match dense-gather reference implementations token-for-token.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_intermediate_size: int | None = None  # None -> intermediate_size
    moe_shared_intermediate: int = 0  # >0 adds Qwen2-MoE's shared expert
    moe_every: int = 1
    moe_norm_topk: bool = True
    moe_dense_layers: tuple[int, ...] = ()  # HF mlp_only_layers: force-dense
    # --- Weight-only int8 quantization for the decoder's attention + MLP
    # projections (per-output-channel scales). Decode at small batch is
    # HBM-bandwidth-bound: the per-step cost is streaming the weights, so
    # int8 halves the dominant traffic vs bf16. Embeddings (gather +
    # tied lm_head), norms, and MoE expert banks stay full precision.
    # Set by the serving layer (backend_settings.quantize), not by
    # checkpoints — see ``quantize_decoder_int8`` in convert.py.
    weight_quant: str | None = None  # None | "int8"
    # How the int8 projections execute:
    #   "dequant"  — y = (x @ q.astype(bf16)) * scale; relies on XLA fusing
    #                the convert into the dot's operand read.
    #   "dynamic"  — W8A8-dynamic: per-token symmetric activation quant
    #                feeds the MXU a NATIVE int8 x int8 -> int32 dot (no
    #                weight convert at all; v5e runs int8 at 2x bf16 rate).
    # The first on-chip measurement found "dequant" pathologically slow
    # (20 tok/s vs 3896 bf16 — the convert lowered to non-vectorized
    # code), so both formulations ship and the bench A/Bs them.
    weight_quant_kernel: str = "dequant"  # "dequant" | "dynamic"

    @property
    def dim_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.heads

    def is_moe_layer(self, i: int) -> bool:
        return (
            self.moe_experts > 0
            and i not in self.moe_dense_layers
            and (i + 1) % self.moe_every == 0
        )


@dataclass(frozen=True)
class VisionTowerConfig:
    image_size: int = 1024
    patch_size: int = 64
    width: int = 768
    layers: int = 12
    heads: int = 12
    mean: tuple[float, float, float] = (0.0, 0.0, 0.0)
    std: tuple[float, float, float] = (1.0, 1.0, 1.0)

    @property
    def num_tokens(self) -> int:
        return (self.image_size // self.patch_size) ** 2


@dataclass(frozen=True)
class VLMConfig:
    decoder: DecoderConfig = field(default_factory=DecoderConfig)
    vision: VisionTowerConfig = field(default_factory=VisionTowerConfig)
    #: Qwen2 `<image>` placeholder id (reference IMAGE_TOKEN_ID,
    #: ``onnxrt_backend.py:240-296``).
    image_token_id: int = 151646
    bos_token_id: int = 151643
    eos_token_id: int = 151645
    pad_token_id: int = 151643

    @classmethod
    def tiny(cls) -> "VLMConfig":
        """Small config for CPU tests."""
        return cls(
            decoder=DecoderConfig(
                hidden_size=32,
                layers=2,
                heads=4,
                kv_heads=2,
                intermediate_size=64,
                vocab_size=256,
                rope_theta=10_000.0,
                max_position_embeddings=128,
            ),
            vision=VisionTowerConfig(image_size=32, patch_size=16, width=48, layers=2, heads=4),
            image_token_id=250,
            bos_token_id=1,
            eos_token_id=2,
            pad_token_id=0,
        )

    @classmethod
    def from_hf(cls, cfg: dict[str, Any]) -> "VLMConfig":
        """Build from an HF LLaVA-style ``config.json`` (``text_config`` +
        ``vision_config``) or a flat Qwen2-style decoder config."""
        text = cfg.get("text_config", cfg)
        vis = cfg.get("vision_config", {})
        decoder = DecoderConfig(
            hidden_size=text.get("hidden_size", 896),
            layers=text.get("num_hidden_layers", 24),
            heads=text.get("num_attention_heads", 14),
            kv_heads=text.get("num_key_value_heads", text.get("num_attention_heads", 14)),
            intermediate_size=text.get("intermediate_size", 4864),
            vocab_size=text.get("vocab_size", 151936),
            head_dim=text.get("head_dim"),
            rope_theta=text.get("rope_theta", 1_000_000.0),
            rms_norm_eps=text.get("rms_norm_eps", 1e-6),
            max_position_embeddings=text.get("max_position_embeddings", 32768),
            tie_word_embeddings=text.get("tie_word_embeddings", cfg.get("tie_word_embeddings", True)),
            # Qwen2-MoE config keys (absent on dense checkpoints).
            moe_experts=text.get("num_experts", 0),
            # HF Qwen2MoeConfig defaults num_experts_per_tok to 4.
            moe_top_k=text.get("num_experts_per_tok", 4 if text.get("num_experts", 0) else 2),
            moe_intermediate_size=text.get("moe_intermediate_size"),
            moe_shared_intermediate=text.get("shared_expert_intermediate_size", 0),
            moe_every=text.get("decoder_sparse_step", 1),
            # HF Qwen2MoeConfig defaults norm_topk_prob to False.
            moe_norm_topk=text.get("norm_topk_prob", not text.get("num_experts", 0)),
            moe_dense_layers=tuple(text.get("mlp_only_layers", ())),
        )
        vision = VisionTowerConfig(
            image_size=vis.get("image_size", 1024),
            patch_size=vis.get("patch_size", 64),
            width=vis.get("hidden_size", 768),
            layers=vis.get("num_hidden_layers", 12),
            heads=vis.get("num_attention_heads", 12),
            mean=tuple(vis.get("image_mean", (0.0, 0.0, 0.0))),
            std=tuple(vis.get("image_std", (1.0, 1.0, 1.0))),
        )
        return cls(
            decoder=decoder,
            vision=vision,
            image_token_id=cfg.get("image_token_index", cfg.get("image_token_id", 151646)),
            bos_token_id=text.get("bos_token_id", 151643),
            eos_token_id=text.get("eos_token_id", 151645),
            pad_token_id=text.get("pad_token_id", text.get("bos_token_id", 151643)),
        )


# -- KV cache ---------------------------------------------------------------


def init_kv_cache(cfg: VLMConfig, batch: int, max_seq: int, dtype=jnp.bfloat16) -> list[dict]:
    """Preallocated per-layer cache: the reference's zero-length grow-by-
    concat cache (``onnxrt_backend.py:731-755``) becomes a fixed buffer."""
    d = cfg.decoder
    shape = (batch, d.kv_heads, max_seq, d.dim_per_head)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(d.layers)
    ]


def init_paged_kv_cache(
    cfg: VLMConfig, pages: int, page_size: int, dtype=jnp.bfloat16
) -> list[dict]:
    """Per-layer PAGED cache: a pool of ``pages`` fixed-size pages shared
    by every decode row, addressed through per-row block tables
    (``models/vlm/paged_kv.py``) instead of one contiguous ``max_seq``
    region per slot. Page 0 is the reserved dump page."""
    d = cfg.decoder
    shape = (pages, d.kv_heads, page_size, d.dim_per_head)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(d.layers)
    ]


# -- modules ----------------------------------------------------------------


def _dense(cfg: DecoderConfig, features: int, name: str, use_bias: bool, dtype):
    """Dense factory for decoder projections: honors ``weight_quant``."""
    if cfg.weight_quant == "int8":
        return QDense(
            features, use_bias=use_bias, kernel_mode=cfg.weight_quant_kernel, name=name
        )
    return nn.Dense(features, use_bias=use_bias, name=name, dtype=dtype)


class RMSNorm(nn.Module):
    eps: float

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (x32 * scale.astype(jnp.float32)).astype(x.dtype)


def rope_rotate(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, HF half-split convention. ``x``: [B, H, S, D],
    ``positions``: [B, S] absolute token positions."""
    d = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None, :, None].astype(jnp.float32) * inv_freq  # [B,1,S,D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


class DecoderAttention(nn.Module):
    cfg: DecoderConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: jax.Array,
        cache: dict | None,
        cache_offset: jax.Array | None,
        kv_valid_len: jax.Array,
        block_tables: jax.Array | None = None,
    ) -> tuple[jax.Array, dict | None]:
        """``x``: [B, S, hidden]. With a cache, new K/V are written at
        ``cache_offset`` (scalar slot index; prefill uses 0, decode uses the
        current length) and attention runs against the full cache buffer
        masked to ``kv_valid_len`` [B] live slots.

        With ``block_tables`` [B, max_pages], the cache is PAGED
        (``{"k"/"v": [num_pages, kv_heads, page, dh]}``): the single new
        token's K/V lands in the page+slot its row's table maps
        ``cache_offset`` to, and attention runs the ragged paged kernel
        (exact XLA gather reference off-TPU) over the row's pages only."""
        c = self.cfg
        b, s, _ = x.shape
        dh = c.dim_per_head
        q = _dense(c, c.heads * dh, "q_proj", True, x.dtype)(x)
        k = _dense(c, c.kv_heads * dh, "k_proj", True, x.dtype)(x)
        v = _dense(c, c.kv_heads * dh, "v_proj", True, x.dtype)(x)
        q = q.reshape(b, s, c.heads, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, c.kv_heads, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, c.kv_heads, dh).transpose(0, 2, 1, 3)
        q = rope_rotate(q, positions, c.rope_theta)
        k = rope_rotate(k, positions, c.rope_theta)

        if block_tables is not None:
            page = cache["k"].shape[2]
            off = jnp.asarray(cache_offset, jnp.int32)  # [B] write position
            bidx = jnp.arange(b)
            new_k, new_v = cache["k"], cache["v"]
            # Rows own their decode-frontier pages exclusively, so the
            # scatter indices are unique across live rows; free/done rows
            # dump into page 0. s > 1 is the speculative verify window:
            # token t of every row lands at its row's position off+t
            # (static unroll — W is small and fixed per program).
            for t in range(s):
                off_t = off + t
                page_idx = block_tables[bidx, off_t // page]  # [B] page ids
                slot = off_t % page
                new_k = new_k.at[page_idx, :, slot].set(k[:, :, t].astype(new_k.dtype))
                new_v = new_v.at[page_idx, :, slot].set(v[:, :, t].astype(new_v.dtype))
            cache = {"k": new_k, "v": new_v}
            if s == 1:
                out = paged_attention(
                    q[:, :, 0],
                    new_k.astype(x.dtype),
                    new_v.astype(x.dtype),
                    block_tables,
                    kv_valid_len,
                )[:, :, None, :]
            else:
                # [B, W, H, dh] query selects the variable-query-length
                # verify path; kv_valid_len stays the t=0 visibility and
                # window slot t sees kv_valid_len + t keys in-kernel.
                out = paged_attention(
                    q.transpose(0, 2, 1, 3),
                    new_k.astype(x.dtype),
                    new_v.astype(x.dtype),
                    block_tables,
                    kv_valid_len,
                ).transpose(0, 2, 1, 3)
            out = out.transpose(0, 2, 1, 3).reshape(b, s, c.heads * dh)
            return _dense(c, c.hidden_size, "o_proj", False, x.dtype)(out), cache

        if cache is not None:
            off = jnp.asarray(cache_offset, jnp.int32)
            if off.ndim == 0:
                # Prefill: one contiguous segment at a shared offset.
                zero = jnp.zeros((), jnp.int32)
                new_k = jax.lax.dynamic_update_slice(
                    cache["k"], k.astype(cache["k"].dtype), (zero, zero, off, zero)
                )
                new_v = jax.lax.dynamic_update_slice(
                    cache["v"], v.astype(cache["v"].dtype), (zero, zero, off, zero)
                )
            else:
                # Decode: one token per sample at a per-sample slot (prompts
                # in a batch have different lengths).
                assert s == 1, "per-sample cache offsets require a single-token segment"
                bidx = jnp.arange(b)
                new_k = cache["k"].at[bidx, :, off].set(k[:, :, 0].astype(cache["k"].dtype))
                new_v = cache["v"].at[bidx, :, off].set(v[:, :, 0].astype(cache["v"].dtype))
            cache = {"k": new_k, "v": new_v}
            keys, values = new_k.astype(x.dtype), new_v.astype(x.dtype)
            n_rep = c.heads // c.kv_heads
            # Key slot j is visible iff filled AND causally reachable:
            # j < kv_valid_len[b] (prefill garbage beyond the true prompt
            # length is excluded; decode overwrites slots in order) and
            # j <= absolute query position. ``positions`` rows are
            # contiguous (arange-offset), so the whole mask is carried by
            # two [B] scalars — on TPU this dispatches to the Pallas flash
            # kernel for prefill-size queries (mask computed in-kernel,
            # dead key blocks skipped) and plain XLA for 1-token decode.
            out = attention_cached(
                q,
                repeat_kv(keys, n_rep),
                repeat_kv(values, n_rep),
                q_offsets=positions[:, 0],
                kv_valid=kv_valid_len,
            )
        else:
            keys, values = k, v
            n_rep = c.heads // c.kv_heads
            # Cacheless forward: positions are arange rows (see
            # ``VLMModel.__call__`` / ``merge_image_embeddings``), so the
            # positions-pairwise mask is exactly the causal triangle.
            out = attention(q, repeat_kv(keys, n_rep), repeat_kv(values, n_rep), causal=True)

        out = out.transpose(0, 2, 1, 3).reshape(b, s, c.heads * dh)
        return _dense(c, c.hidden_size, "o_proj", False, x.dtype)(out), cache


class SwiGLU(nn.Module):
    cfg: DecoderConfig
    intermediate: int | None = None  # override cfg.intermediate_size

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        inter = self.intermediate or c.intermediate_size
        gate = _dense(c, inter, "gate_proj", False, x.dtype)(x)
        up = _dense(c, inter, "up_proj", False, x.dtype)(x)
        return _dense(c, c.hidden_size, "down_proj", False, x.dtype)(nn.silu(gate) * up)


class MoEFFN(nn.Module):
    """Qwen2-MoE sparse MLP: softmax router -> top-k routed SwiGLU expert
    bank (+ optional sigmoid-gated shared expert). The routed compute is
    :func:`lumen_tpu.parallel.moe.moe_ffn` with EXACT capacity, so outputs
    match HF's dense-gather reference (``Qwen2MoeSparseMoeBlock``)
    token-for-token; at pod scale the stacked ``w_*`` banks shard their
    leading dim over the ``expert`` mesh axis (``parallel.sharding``
    MOE_EP_RULES) or run through ``moe_ffn(mesh=...)`` explicitly."""

    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from ...parallel.moe import MoEParams, moe_ffn

        c = self.cfg
        e = c.moe_experts
        d = c.hidden_size
        f = c.moe_intermediate_size or c.intermediate_size
        init = nn.initializers.normal(0.02)
        router = self.param("router", init, (d, e), jnp.float32)
        w_gate = self.param("w_gate", init, (e, d, f), jnp.float32)
        w_up = self.param("w_up", init, (e, d, f), jnp.float32)
        w_down = self.param("w_down", init, (e, f, d), jnp.float32)
        b, s, _ = x.shape
        tokens = x.reshape(b * s, d)
        y = moe_ffn(
            MoEParams(
                router=router,
                w_gate=w_gate.astype(x.dtype),
                w_up=w_up.astype(x.dtype),
                w_down=w_down.astype(x.dtype),
            ),
            tokens,
            mesh=None,
            k=c.moe_top_k,
            capacity_factor=None,  # exact: no token drops at inference
            norm_topk=c.moe_norm_topk,
        ).reshape(b, s, d)
        if c.moe_shared_intermediate:
            shared = SwiGLU(c, intermediate=c.moe_shared_intermediate, name="shared")(x)
            gate = nn.Dense(1, use_bias=False, name="shared_gate", dtype=x.dtype)(x)
            y = y + jax.nn.sigmoid(gate) * shared
        return y


class DecoderLayer(nn.Module):
    cfg: DecoderConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, positions, cache, cache_offset, kv_valid_len, block_tables=None):
        h, cache = DecoderAttention(self.cfg, name="attn")(
            RMSNorm(self.cfg.rms_norm_eps, name="input_norm")(x),
            positions,
            cache,
            cache_offset,
            kv_valid_len,
            block_tables,
        )
        x = x + h
        mlp_cls = MoEFFN if self.cfg.is_moe_layer(self.layer_idx) else SwiGLU
        x = x + mlp_cls(self.cfg, name="mlp")(
            RMSNorm(self.cfg.rms_norm_eps, name="post_attn_norm")(x)
        )
        return x, cache


class Decoder(nn.Module):
    """Causal LM over input *embeddings* (not ids) so vision embeddings can
    be spliced upstream, mirroring the reference's embed/decoder session
    split (``onnxrt_backend.py:494-506``)."""

    cfg: DecoderConfig

    def setup(self):
        c = self.cfg
        self.embed_tokens = nn.Embed(c.vocab_size, c.hidden_size, name="embed_tokens")
        self.blocks = [
            DecoderLayer(c, layer_idx=i, name=f"layers_{i}") for i in range(c.layers)
        ]
        self.final_norm = RMSNorm(c.rms_norm_eps, name="final_norm")
        if not c.tie_word_embeddings:
            # _dense so weight_quant="int8" applies to the untied lm_head
            # (convert.quantize_decoder_int8 rewrites its kernel to q+scale).
            self.lm_head = _dense(c, c.vocab_size, "lm_head", False, None)

    def embed(self, input_ids: jax.Array) -> jax.Array:
        return self.embed_tokens(input_ids)

    def __call__(
        self,
        embeds: jax.Array,
        positions: jax.Array,
        caches: list[dict] | None,
        cache_offset: jax.Array | None,
        kv_valid_len: jax.Array,
        block_tables: jax.Array | None = None,
    ) -> tuple[jax.Array, list[dict] | None]:
        x = embeds
        new_caches: list[dict] = []
        for i, block in enumerate(self.blocks):
            layer_cache = caches[i] if caches is not None else None
            x, layer_cache = block(
                x, positions, layer_cache, cache_offset, kv_valid_len, block_tables
            )
            new_caches.append(layer_cache)
        x = self.final_norm(x)
        if self.cfg.tie_word_embeddings:
            logits = x @ self.embed_tokens.embedding.T.astype(x.dtype)
        else:
            logits = self.lm_head(x)
        return logits, (new_caches if caches is not None else None)


class VisionEncoder(nn.Module):
    """ViT over large patches -> [B, num_tokens, width] patch features, then
    a 2-layer GELU MLP projector into decoder hidden space (LLaVA layout)."""

    cfg: VLMConfig

    @nn.compact
    def __call__(self, pixel_values: jax.Array) -> jax.Array:
        v = self.cfg.vision
        from ..clip.modeling import PatchEmbed  # reshape+matmul, MXU-shaped

        x = PatchEmbed(v.width, v.patch_size, use_bias=True, name="patch_embed")(
            pixel_values
        )
        b = x.shape[0]
        pos = self.param("position_embedding", nn.initializers.normal(0.02), (v.num_tokens, v.width))
        x = x + pos.astype(x.dtype)
        from ..clip.modeling import Block  # same pre-LN transformer block

        for i in range(v.layers):
            x = Block(v.width, v.heads, "gelu", 1e-6, name=f"blocks_{i}")(x)
        x = nn.LayerNorm(epsilon=1e-6, name="post_ln", dtype=x.dtype)(x)
        h = nn.Dense(self.cfg.decoder.hidden_size, name="proj_fc1", dtype=x.dtype)(x)
        h = jax.nn.gelu(h, approximate=True)
        return nn.Dense(self.cfg.decoder.hidden_size, name="proj_fc2", dtype=x.dtype)(h)


class VLMModel(nn.Module):
    cfg: VLMConfig

    def setup(self):
        self.vision = VisionEncoder(self.cfg, name="vision")
        self.decoder = Decoder(self.cfg.decoder, name="decoder")

    def encode_vision(self, pixel_values: jax.Array) -> jax.Array:
        return self.vision(pixel_values)

    def embed_tokens(self, input_ids: jax.Array) -> jax.Array:
        return self.decoder.embed(input_ids)

    def decode(self, embeds, positions, caches, cache_offset, kv_valid_len):
        return self.decoder(embeds, positions, caches, cache_offset, kv_valid_len)

    def decode_paged(self, embeds, positions, caches, block_tables, cache_offset, kv_valid_len):
        """Single-token decode against the paged KV pool (continuous
        engine): ``caches`` from :func:`init_paged_kv_cache`,
        ``block_tables`` [B, max_pages] per-row page maps."""
        return self.decoder(
            embeds, positions, caches, cache_offset, kv_valid_len, block_tables
        )

    def __call__(self, input_ids: jax.Array, pixel_values: jax.Array | None = None):
        """Cacheless forward (tests / loss): embeds ids, optionally splices
        one image per sample at the image-token position, returns logits."""
        embeds = self.decoder.embed(input_ids)
        if pixel_values is not None:
            vis = self.vision(pixel_values)
            embeds, positions, _ = merge_image_embeddings(
                embeds, vis, input_ids, self.cfg.image_token_id
            )
        else:
            b, s = input_ids.shape
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        logits, _ = self.decoder(
            embeds, positions, None, None, jnp.full((embeds.shape[0],), embeds.shape[1])
        )
        return logits


def merge_image_embeddings(
    text_embeds: jax.Array,
    vision_embeds: jax.Array,
    input_ids: jax.Array,
    image_token_id: int,
    input_lengths: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """LLaVA-style splice with static shapes: replace the single ``<image>``
    placeholder token with the ``V`` vision tokens.

    The reference does this on host with a python list split + concat
    (``onnxrt_backend.py:240-296``); here it is a gather so it lives inside
    jit. Output length is static: ``S - 1 + V``.

    Returns ``(merged [B, L, H], positions [B, L], lengths [B])``.
    ``input_lengths`` [B] is the unpadded token count of each sample
    (defaults to S); ``lengths`` is the post-splice live token count —
    positions beyond it are right-padding the caller masks via kv_valid_len.
    """
    b, s = input_ids.shape
    v = vision_embeds.shape[1]
    l = s - 1 + v
    if input_lengths is None:
        input_lengths = jnp.full((b,), s)
    has_image = jnp.any(input_ids == image_token_id, axis=1)  # [B]
    # Index of the placeholder (first occurrence); samples without an image
    # get idx = s so every output position maps to a text token.
    idx = jnp.where(
        has_image, jnp.argmax((input_ids == image_token_id).astype(jnp.int32), axis=1), s
    )  # [B]
    pos = jnp.arange(l)[None, :]  # [1, L]
    idx_b = idx[:, None]
    in_image = (pos >= idx_b) & (pos < idx_b + v) & has_image[:, None]
    # text source index: before splice -> pos; after -> pos - (V - 1)
    text_src = jnp.where(pos < idx_b, pos, pos - (v - 1))
    text_src = jnp.clip(text_src, 0, s - 1)
    vis_src = jnp.clip(pos - idx_b, 0, v - 1)
    gathered_text = jnp.take_along_axis(text_embeds, text_src[:, :, None], axis=1)
    gathered_vis = jnp.take_along_axis(
        vision_embeds.astype(text_embeds.dtype), vis_src[:, :, None], axis=1
    )
    merged = jnp.where(in_image[:, :, None], gathered_vis, gathered_text)
    positions = jnp.broadcast_to(pos, (b, l))
    lengths = jnp.where(has_image, input_lengths - 1 + v, input_lengths)
    return merged, positions, lengths
