"""Chat prompt construction + tokenizer loading for the VLM.

Mirrors the reference's prompt utilities (``packages/lumen-vlm/src/
lumen_vlm/backends/base.py:344-430``): render the checkpoint's Jinja2
``chat_template`` from ``tokenizer_config.json`` when present, fall back to
a plain ``<|role|>`` transcript otherwise; tokenize with the HF
``tokenizers`` runtime from ``tokenizer.json``.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Sequence

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ChatMessage:
    role: str
    content: str

    def to_mapping(self) -> dict[str, str]:
        return {"role": self.role, "content": self.content}


def render_chat(
    messages: Sequence[ChatMessage],
    chat_template: str | None,
    add_generation_prompt: bool = True,
) -> str:
    """Template render with graceful fallback (reference semantics,
    ``base.py:344-378``)."""
    if not messages:
        raise ValueError("chat messages cannot be empty")
    if chat_template:
        try:
            import jinja2

            env = jinja2.Environment(
                trim_blocks=True, lstrip_blocks=True, undefined=jinja2.StrictUndefined
            )
            rendered = env.from_string(chat_template).render(
                messages=[m.to_mapping() for m in messages],
                add_generation_prompt=add_generation_prompt,
            )
            return rendered.strip()
        except ImportError:
            logger.warning("jinja2 unavailable; using fallback chat format")
        except Exception as e:  # noqa: BLE001 - bad template -> fallback
            logger.warning("chat template rendering failed (%s); using fallback", e)
    parts = [f"<|{m.role}|>\n{m.content.strip()}\n" for m in messages]
    if add_generation_prompt:
        parts.append("<|assistant|>\n")
    return "".join(parts)


class VlmTokenizer:
    """Thin wrapper over an HF ``tokenizers.Tokenizer`` plus the chat
    template pulled from ``tokenizer_config.json``."""

    def __init__(self, tokenizer, chat_template: str | None):
        self._tok = tokenizer
        self.chat_template = chat_template

    @classmethod
    def from_model_dir(cls, model_dir: str) -> "VlmTokenizer":
        from tokenizers import Tokenizer

        path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.exists(path):
            raise FileNotFoundError(f"tokenizer.json not found in {model_dir}")
        tok = Tokenizer.from_file(path)
        template = None
        cfg_path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path, "r", encoding="utf-8") as f:
                raw = json.load(f)
            t = raw.get("chat_template")
            if isinstance(t, str) and t.strip():
                template = t
        return cls(tok, template)

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def render(self, messages: Sequence[ChatMessage], add_generation_prompt: bool = True) -> str:
        return render_chat(messages, self.chat_template, add_generation_prompt)
