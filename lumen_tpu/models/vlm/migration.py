"""KV migration pack/unpack: ONE lease format, two sinks.

PR 16's spill tier packed a preempted row's pages + exact decode state
into an ad-hoc flat byte lease (shapes list + treedef + crc held out of
band). Disaggregated serving needs the SAME bytes to cross a host
boundary, where out-of-band Python objects cannot follow. This module
unifies the two: the payload is a self-describing
:func:`~lumen_tpu.utils.tensorwire.pack_bundle` frame train (per-layer
K/V page stacks in pool flatten order, then the row's ``seen`` vocab
mask) with one crc32 over the whole blob, and the decode scalars travel
as a flat string dict — request meta on the wire, record fields on the
spill ledger. Spill-to-RAM and migrate-to-peer are now one codepath with
two sinks:

- **spill sink** (:meth:`ContinuousScheduler._export_record`): the blob
  lands in the shm arena (or host bytes when the arena denies) and the
  crc gate at resume turns a torn/recycled lease into the degradation
  ladder instead of silent token corruption — exactly PR 16's contract,
  minus the bespoke layout;
- **wire sink** (``fed_kv_put``): the blob IS the gRPC payload
  (``tensor/bundle`` mime), the scalars ride request meta, and the crc
  rides ``crc`` — the decode host verifies before admitting via
  ``PagedKVPool.admit_exact``/``gen._resume``, zero re-prefill.

Shared-prefix pages migrate as content-hash REFERENCES first: the offer
leg ships the prompt's chain-key manifest (``prefix_cache.chunk_keys``
hex), the decode host answers how many leading pages its own prefix
cache already holds, and only the missed suffix rides the commit leg.

jax-free on purpose: numpy + tensorwire + zlib, importable by the
serving layer and the federation client without dragging in the engine.
"""

from __future__ import annotations

import zlib

import numpy as np

from ...utils.tensorwire import pack_bundle, unpack_bundle

#: wire format version — bumped on any layout change; a mismatch is an
#: INVALID_ARGUMENT on the decode host, never a silent misparse.
MIGRATE_VERSION = "1"


class ChunksMissing(ValueError):
    """Offer/commit race: prefix chunks the offer leg promised were
    evicted before the commit admitted. Retryable — the prefill host
    re-commits with the full page contents (or decodes locally)."""

#: scalar meta fields every commit leg must carry (gen params ride as
#: repr() strings so float round-trips are exact).
_INT_FIELDS = ("cur_tok", "cur_len", "n_gen", "prompt_len", "n_pages",
               "n_shared", "n_page_leaves", "max_new", "page_size")
_FLOAT_FIELDS = ("temperature", "top_p", "repetition_penalty")


def pack_payload(leaves: "list[np.ndarray]") -> tuple[bytes, int]:
    """Serialize record leaves into the one lease blob. Returns
    ``(blob, crc32)`` — both sinks store/ship exactly these bytes."""
    blob = pack_bundle(leaves)
    return blob, zlib.crc32(blob)


def unpack_payload(buf: "bytes | memoryview", crc: "int | None") -> "list[np.ndarray]":
    """Parse a lease blob back into leaves, crc-gated. ``crc=None``
    skips the check (caller already verified); any mismatch or malformed
    frame raises :class:`ValueError` loudly — the degradation ladder's
    entry point, never a silent corruption."""
    if crc is not None and zlib.crc32(buf) != crc:
        raise ValueError(
            "migration payload failed crc verification (torn lease or "
            "corrupt wire frame)"
        )
    return unpack_bundle(buf)


# -- content-hash manifests --------------------------------------------------


def manifest_csv(keys: "list[bytes]") -> str:
    """Chain-key manifest as wire text (comma-joined hex)."""
    return ",".join(k.hex() for k in keys)


def manifest_from_csv(text: str) -> "list[bytes]":
    """Inverse of :func:`manifest_csv`; malformed hex raises ValueError."""
    return [bytes.fromhex(part) for part in text.split(",") if part]


# -- wire meta codec ---------------------------------------------------------


def commit_meta(
    *,
    crc: int,
    n_page_leaves: int,
    n_pages: int,
    n_shared: int,
    page_size: int,
    cur_tok: int,
    cur_len: int,
    n_gen: int,
    prompt_len: int,
    max_new: int,
    temperature: float,
    top_p: float,
    do_sample: bool,
    repetition_penalty: float,
    manifest: "list[bytes]",
) -> dict:
    """Request meta for the commit leg: every scalar the decode host
    needs to rebuild the row, as strings (the gRPC meta map)."""
    meta = {
        "op": "commit",
        "ver": MIGRATE_VERSION,
        "crc": str(crc),
        "n_page_leaves": str(n_page_leaves),
        "n_pages": str(n_pages),
        "n_shared": str(n_shared),
        "page_size": str(page_size),
        "cur_tok": str(cur_tok),
        "cur_len": str(cur_len),
        "n_gen": str(n_gen),
        "prompt_len": str(prompt_len),
        "max_new": str(max_new),
        "temperature": repr(float(temperature)),
        "top_p": repr(float(top_p)),
        "do_sample": "1" if do_sample else "0",
        "repetition_penalty": repr(float(repetition_penalty)),
    }
    if manifest:
        meta["manifest"] = manifest_csv(manifest)
    return meta


def parse_commit_meta(meta) -> dict:
    """Validate + type the commit leg's meta. Raises :class:`ValueError`
    naming the exact field on any malformation (the decode host answers
    INVALID_ARGUMENT with the message verbatim)."""
    if meta.get("ver") != MIGRATE_VERSION:
        raise ValueError(
            f"fed_kv_put version {meta.get('ver')!r} unsupported "
            f"(this host speaks {MIGRATE_VERSION!r})"
        )
    out: dict = {}
    for key in _INT_FIELDS + ("crc",):
        raw = meta.get(key)
        if raw is None:
            raise ValueError(f"fed_kv_put commit missing meta key {key!r}")
        try:
            out[key] = int(raw)
        except ValueError:
            raise ValueError(
                f"fed_kv_put meta {key!r} must be an integer; got {raw!r}"
            ) from None
    for key in _FLOAT_FIELDS:
        raw = meta.get(key)
        if raw is None:
            raise ValueError(f"fed_kv_put commit missing meta key {key!r}")
        try:
            out[key] = float(raw)
        except ValueError:
            raise ValueError(
                f"fed_kv_put meta {key!r} must be a float; got {raw!r}"
            ) from None
    out["do_sample"] = meta.get("do_sample") == "1"
    try:
        out["manifest"] = manifest_from_csv(meta.get("manifest", ""))
    except ValueError:
        raise ValueError("fed_kv_put meta 'manifest' is not valid hex") from None
    if out["n_pages"] < 1:
        raise ValueError(f"fed_kv_put n_pages must be >= 1; got {out['n_pages']}")
    if not 0 <= out["n_shared"] < out["n_pages"]:
        raise ValueError(
            f"fed_kv_put n_shared {out['n_shared']} outside "
            f"[0, {out['n_pages']}) — at least one page must ride the wire"
        )
    if len(out["manifest"]) < out["n_shared"]:
        raise ValueError(
            f"fed_kv_put n_shared {out['n_shared']} exceeds the "
            f"{len(out['manifest'])}-key manifest"
        )
    return out


# -- page-stack helpers ------------------------------------------------------


def slice_pages(
    leaves: "list[np.ndarray]", n_page_leaves: int, skip: int,
    stop: "int | None" = None,
) -> "list[np.ndarray]":
    """Drop the first ``skip`` pages from every page leaf (the offer leg
    said the decode host already holds them) and everything past
    ``stop`` — the export gather pads page leaves up to a power of two
    for its compiled shape, and those pad rows are dump-page garbage
    that must never ride the wire (the decode host refuses a commit
    whose leaves disagree with the declared page count). Non-page
    trailing leaves pass through untouched."""
    if skip <= 0 and stop is None:
        return list(leaves)
    window = slice(max(0, skip), stop)
    return [
        leaf[window] if i < n_page_leaves else leaf
        for i, leaf in enumerate(leaves)
    ]


def pad_pages(
    leaves: "list[np.ndarray]", n_page_leaves: int, n_pad: int
) -> "list[np.ndarray]":
    """Zero-pad every page leaf's page dim up to ``n_pad`` (the resume
    scatter's power-of-2 compiled shape; padded rows target the dump
    page and are never read back)."""
    out: list[np.ndarray] = []
    for i, leaf in enumerate(leaves):
        if i < n_page_leaves and leaf.shape[0] < n_pad:
            pad = np.zeros((n_pad - leaf.shape[0],) + leaf.shape[1:], leaf.dtype)
            leaf = np.concatenate([np.asarray(leaf), pad], axis=0)
        out.append(leaf)
    return out
