"""VLM manager: multimodal caption/chat generation on TPU.

Business-logic layer mirroring the reference ``FastVLMModelManager``
(``packages/lumen-vlm/src/lumen_vlm/fastvlm/fastvlm_model.py:51-400``) over
the TPU-native stack: host does image decode + letterbox + tokenize; device
runs ONE compiled prepare program (normalize -> vision encode -> token embed
-> image-token splice) and ONE compiled generate program (prefill +
while_loop decode, ``generate.py``). Prompt lengths are padded to static
buckets so the number of distinct compiles is bounded.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.model_info import ModelInfo, load_model_info
from ...runtime.decode_pool import get_decode_pool
from ...runtime.policy import get_policy
from ...runtime.quarantine import guarded_key
from ...runtime.result_cache import get_result_cache, make_namespace
from ...runtime.weights import load_state_dict
from ...utils.metrics import metrics
from .chat import ChatMessage, VlmTokenizer
from .convert import convert_vlm_checkpoint
from .generate import Generator
from .modeling import VLMConfig, VLMModel, merge_image_embeddings

logger = logging.getLogger(__name__)

DEFAULT_PREFILL_BUCKETS = (64, 128, 256, 512, 1024)


@dataclass
class GenerationResult:
    text: str
    tokens: list[int]
    finish_reason: str  # stop | length | eos_token | stop_sequence | error
    input_tokens: int
    metadata: dict[str, Any] = field(default_factory=dict)


@dataclass
class GenerationChunk:
    text: str
    tokens: list[int]
    is_final: bool = False
    metadata: dict[str, Any] = field(default_factory=dict)


@dataclass
class _PendingGen:
    """One queued generation request inside the batcher."""

    embeds: Any  # [1, L, H]
    positions: Any  # [1, L]
    length: Any  # [1]
    prompt_ids: Any  # [1, S]
    max_new: int
    temperature: float
    top_p: float
    do_sample: bool
    repetition_penalty: float
    future: Any = None

    @property
    def key(self) -> tuple:
        # Only identically-shaped requests share one compiled program.
        return (self.embeds.shape[1], self.prompt_ids.shape[1])


class _GenBatcher:
    """Batched decode scheduler: collects concurrent ``generate`` requests
    with the same prompt-bucket shape and decodes them as one [B>1]
    program. Replaces the round-1 single-flight lock — the decoder's
    per-sample cache offsets (``modeling.py``) already support mixed
    positions, and per-sample sampling params (``ops/sampling.py``) support
    mixed request configs, so aggregate tokens/sec scales with batch.
    """

    def __init__(
        self, runner, max_batch: int = 4, max_latency_ms: float = 6.0,
        name: str = "vlm",
    ):
        from concurrent.futures import Future

        self._Future = Future
        self._runner = runner
        # Gauge provider id: per-model-name, matching the batcher's
        # ``batcher:{name}`` semantics — distinct models coexist; a
        # same-name replacement takes over the slot (last-writer-wins
        # register, ownership-guarded unregister).
        self.name = name
        self.max_batch = max_batch
        self.max_latency_s = max_latency_ms / 1e3
        self.batches_run = 0  # observability: how often we actually batched
        self.rows_run = 0
        self._queue: list[_PendingGen] = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, name="vlm-gen-batcher", daemon=True)
        self._thread.start()
        ref = weakref.ref(self)  # registry must not pin the runner/params

        def _gauges() -> dict:
            b = ref()
            if b is None:
                return {}
            return {
                "batches_run": b.batches_run,
                "rows_run": b.rows_run,
                "queue_depth": len(b._queue),
            }

        self._gauge_fn = _gauges
        metrics.register_gauges(f"vlm-coalesce:{self.name}", _gauges)

    def submit(self, item: _PendingGen):
        item.future = self._Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("generation batcher is closed")
            self._queue.append(item)
            self._cond.notify()
        return item.future

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=5)
        with self._cond:
            pending, self._queue = self._queue, []
        for item in pending:
            item.future.set_exception(RuntimeError("generation batcher closed"))
        if fn := getattr(self, "_gauge_fn", None):
            metrics.unregister_gauges(f"vlm-coalesce:{self.name}", fn)

    def _take_batch(self) -> list[_PendingGen]:
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait()
            if not self._queue:
                return []
            head = self._queue.pop(0)
        batch = [head]
        deadline = time.perf_counter() + self.max_latency_s
        while len(batch) < self.max_batch:
            with self._cond:
                take = [i for i, it in enumerate(self._queue) if it.key == head.key]
                for offset, i in enumerate(take[: self.max_batch - len(batch)]):
                    batch.append(self._queue.pop(i - offset))
            if len(batch) >= self.max_batch:
                break
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            with self._cond:
                self._cond.wait(timeout=remaining)
                if self._closed:
                    break
        return batch

    def _loop(self) -> None:
        try:
            while True:
                batch = self._take_batch()
                if not batch:
                    if self._closed:
                        return
                    continue
                # Count before the futures resolve so a caller that joins
                # its threads and immediately reads the counters sees this
                # batch.
                self.batches_run += 1
                self.rows_run += len(batch)
                try:
                    self._runner(batch)
                except Exception as e:  # noqa: BLE001 - fan the failure out
                    for item in batch:
                        if not item.future.done():
                            item.future.set_exception(e)
        finally:
            # Worker death for ANY reason (incl. BaseException like
            # KeyboardInterrupt) must not strand callers blocked on
            # futures: close the queue and fail everything pending.
            with self._cond:
                self._closed = True
                pending, self._queue = self._queue, []
            err = RuntimeError("generation batcher worker exited")
            for item in pending:
                if item.future is not None and not item.future.done():
                    item.future.set_exception(err)


class VLMManager:
    def __init__(
        self,
        model_dir: str,
        dtype: str = "bfloat16",
        max_seq: int = 2048,
        max_new_cap: int = 512,
        prefill_buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
        warmup: bool = False,
        gen_batch_size: int = 4,
        gen_batch_latency_ms: float = 6.0,
        scheduler: str = "continuous",  # or "coalesce"
        gen_slots: int = 8,
        gen_block: int = 8,
        quantize: str | None = None,  # None | "int8" (weight-only decoder quant)
        mesh_axes: dict[str, int] | None = None,
    ):
        if quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
        self.quantize = quantize
        # Decode route actually in use ("bf16" | "int8"): finalized at
        # initialize() — a warmup A/B (LUMEN_VLM_Q8_ROUTE=auto) may fall an
        # int8 opt-in back to bf16 when q8 measures slower (BENCH_r05:
        # q8 decode at 0.03x bf16 on v5e).
        self.quant_route = "int8" if quantize else "bf16"
        self.quant_speedup: float | None = None  # measured q8/bf16 decode ratio
        self.model_dir = model_dir
        from ...utils.env import env_choice

        # LUMEN_VLM_SCHEDULER overrides the constructor/config choice at
        # boot (one-shot warning on malformed values) — flipping engines
        # must not require a config rollout.
        env_sched = env_choice("LUMEN_VLM_SCHEDULER", None, ("coalesce", "continuous"))
        if env_sched is not None and env_sched != scheduler:
            logger.info(
                "VLM scheduler %r overridden to %r by LUMEN_VLM_SCHEDULER",
                scheduler, env_sched,
            )
            scheduler = env_sched
        from ...runtime.fleet import plan_replicas, replicas_for

        # Serving mesh: a ``model`` axis tensor-parallelizes the decoder, an
        # ``expert`` axis shards MoE expert banks (SURVEY §2.8); without
        # either the mesh is the trivial data mesh and weights replicate.
        # The continuous engine is built PER REPLICA through the fleet
        # plan (one engine + page pool per device slice, PR 7 semantics);
        # the coalescing batcher stays a singleton over the full mesh.
        if scheduler == "continuous":
            self.fleet_plan = plan_replicas("vlm", mesh_axes)
            self.mesh = self.fleet_plan.meshes[0]
        else:
            from ...runtime.mesh import build_mesh

            self.fleet_plan = None
            self.mesh = build_mesh(mesh_axes) if mesh_axes else build_mesh()
            if replicas_for("vlm") != 1:  # includes the "max" sentinel (-1)
                logger.warning(
                    "LUMEN_REPLICAS(_VLM) > 1 requested but the coalescing "
                    "VLM scheduler is not replica-fleeted; serving 1 replica "
                    "over the full mesh (use scheduler=continuous to fleet)"
                )
        from ...ops.quant_matmul import note_mesh_model_axis

        # TP x int8: pl.pallas_call has no GSPMD sharding rule, so a
        # model-axis mesh must keep decode on the XLA dequant fallback.
        note_mesh_model_axis(dict(self.mesh.shape).get("model", 1))
        self.policy = get_policy(dtype)
        self.warmup = warmup
        self.max_seq = max_seq
        self.max_new_cap = max_new_cap
        self.prefill_buckets = sorted(prefill_buckets)
        self.gen_batch_size = gen_batch_size
        self.gen_batch_latency_ms = gen_batch_latency_ms
        if scheduler not in ("coalesce", "continuous"):
            raise ValueError(f"scheduler must be 'coalesce' or 'continuous', got {scheduler!r}")
        self.scheduler = scheduler
        self.gen_slots = gen_slots
        self.gen_block = gen_block
        self.info: ModelInfo = load_model_info(model_dir)
        self.cfg = self._build_config(model_dir)
        if self.quantize:
            import dataclasses

            from ...ops.quant import resolve_q8_kernel

            # Kernel formulation for the int8 projections; "dynamic"
            # (W8A8, native MXU int8 dot) is the fallback for stacks where
            # the dequant convert doesn't fuse (see DecoderConfig).
            q8_kernel = resolve_q8_kernel("dequant")
            self.cfg = dataclasses.replace(
                self.cfg,
                decoder=dataclasses.replace(
                    self.cfg.decoder,
                    weight_quant=self.quantize,
                    weight_quant_kernel=q8_kernel,
                ),
            )
        self.model = VLMModel(self.cfg)
        self.model_id = self.info.name
        self._initialized = False
        # Overridden at initialize() when a vision.onnx graph is probed.
        self.vision_tokens = self.cfg.vision.num_tokens
        self._seed_lock = threading.Lock()
        self._seed = 0
        # Each live stream holds a full [1, max_seq] KV cache in device
        # memory; without a bound, N concurrent streams allocate N caches
        # and can exhaust HBM (batched generate() is already bounded by
        # the single batcher thread).
        self._stream_slots = threading.Semaphore(max(1, gen_batch_size))

    def _build_config(self, model_dir: str) -> VLMConfig:
        cfg_path = os.path.join(model_dir, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path, "r", encoding="utf-8") as f:
                return VLMConfig.from_hf(json.load(f))
        # model_info extra_metadata fallback (the reference's only source,
        # ``backends/base.py:472-480``).
        meta = self.info.extra_metadata or {}
        if "generation_config" in meta:
            gen = dict(meta["generation_config"])
            kv = dict(meta.get("kv_cache_config", {}))
            vis = dict(meta.get("vision_config", {}))
            text_cfg = {
                "vocab_size": gen.get("vocab_size"),
                "bos_token_id": gen.get("bos_token_id"),
                "eos_token_id": gen.get("eos_token_id"),
                "pad_token_id": gen.get("pad_token_id"),
                "max_position_embeddings": gen.get("max_position_embeddings"),
                "hidden_size": kv.get("hidden_size"),
                "num_hidden_layers": kv.get("num_hidden_layers"),
                "num_attention_heads": kv.get("num_attention_heads"),
                "num_key_value_heads": kv.get("num_key_value_heads"),
                "head_dim": kv.get("head_dim"),
            }
            vision_cfg = {
                "image_size": vis.get("image_size"),
                "patch_size": vis.get("patch_size"),
                "image_mean": vis.get("mean"),
                "image_std": vis.get("std"),
            }
            raw = {
                # Absent manifest keys must fall through to from_hf's
                # defaults, so drop None-valued entries instead of passing
                # them (dict.get(k, default) would return the None).
                "text_config": {k: v for k, v in text_cfg.items() if v is not None},
                "vision_config": {k: v for k, v in vision_cfg.items() if v is not None},
            }
            if gen.get("image_token_index") is not None:
                raw["image_token_index"] = gen["image_token_index"]
            return VLMConfig.from_hf(raw)
        raise FileNotFoundError(f"no config.json or generation_config metadata in {model_dir}")

    # -- initialization ----------------------------------------------------

    def _place_params(self, params, quantized: bool | None = None, mesh=None):
        """Place loaded weights on the serving mesh: TP rules when the mesh
        carries a ``model`` axis, EP rules first when it carries ``expert``
        (first-match-wins keeps expert banks on the expert axis), replicated
        otherwise. int8-quantized trees ship (q, scale) leaves with their
        own rules (``INT8_TP_RULES``: scales shard along the same output
        axis as their q matrices) — TP x int8 is the advertised deployment
        shape for a quantized 2B on a multi-chip host. ``quantized``
        overrides the config-derived default (the warmup route A/B places
        one tree of EACH kind); ``mesh`` overrides the primary mesh (the
        replica fleet places one tree per slice)."""
        from ...parallel.sharding import (
            INT8_TP_RULES,
            MOE_EP_RULES,
            TRANSFORMER_TP_RULES,
            shard_params,
        )

        if quantized is None:
            quantized = bool(self.quantize)
        mesh = mesh if mesh is not None else self.mesh
        shape = dict(mesh.shape)
        rules = []
        if shape.get("expert", 1) > 1:
            rules += MOE_EP_RULES
        if shape.get("model", 1) > 1:
            if quantized:
                rules += INT8_TP_RULES
            rules += TRANSFORMER_TP_RULES
        if rules:
            logger.info(
                "sharding VLM params over mesh %s (%d rules)", shape, len(rules)
            )
        # shard_params with no rules degrades every leaf to replication,
        # and NamedSharding placement on a 1-device mesh is device_put —
        # one call covers all cases.
        return shard_params(params, mesh, rules)

    # -- quantization route -------------------------------------------------

    def _resolve_q8_route(self, converted: dict) -> dict:
        """Decide whether the int8 decode opt-in actually serves int8 —
        the VLM twin of the CLIP route gate (PR 2). BENCH_r05 measured q8
        decode at 135 tok/s vs 4,498 bf16 (0.03x) on v5e: an operator who
        opted into "int8" for memory almost certainly did not want a 30x
        decode regression. ``LUMEN_VLM_Q8_ROUTE``:

        - ``bf16``  — pin: skip quantization entirely (no per-boot
          quantize pass just to discard it);
        - ``int8``  — pin: quantize and serve int8, no timing;
        - ``auto``  (default) — with warmup on, run a one-shot timed
          decode A/B (synthetic prompt through the real Generator path,
          sequential placements so peak HBM stays at one decoder set) and
          serve the winner; without warmup there is nothing to time
          against, so the explicit opt-in wins.

        Returns the route-matching decoder tree (decoder subtree cast to
        the serving dtype on BOTH routes; vision subtree untouched) and
        sets ``self.cfg``/``self.model``/``self.quant_route``; the verdict
        is exported as the ``vlm-quant:<model>`` gauge provider
        (``int8_active``, ``q8_speedup_pct``)."""
        import dataclasses

        from .convert import quantize_decoder_int8

        route = os.environ.get("LUMEN_VLM_Q8_ROUTE", "auto").lower()
        if route not in ("auto", "int8", "bf16"):
            logger.warning("ignoring malformed LUMEN_VLM_Q8_ROUTE=%r", route)
            route = "auto"
        vision_sub = converted.pop("vision", None)
        # Cast first so the int8 grid is computed from the bf16 weights
        # serving would otherwise stream; scales stay fp32 (the later
        # blanket cast is skipped for quantized trees). The vision subtree
        # sits out: never quantized, and cast later only if kept.
        cast = self.policy.cast_params(converted)
        base_cfg = dataclasses.replace(
            self.cfg,
            decoder=dataclasses.replace(
                self.cfg.decoder, weight_quant=None, weight_quant_kernel=None
            ),
        )
        if route == "bf16":
            logger.info(
                "VLM quantize=int8 overridden to bf16 (LUMEN_VLM_Q8_ROUTE); "
                "skipping quantization"
            )
            chosen, params = "bf16", cast
        else:
            # Disk-tier verdict cache (next to the weights, keyed by
            # model@revision): the warmup A/B measured q8 decode at 0.03x
            # bf16 on v5e (BENCH_r05) — re-running the losing probe every
            # boot costs two timed decode passes for a known answer. An
            # explicit pin (route != auto) still bypasses the cache, and
            # a cache miss (new revision) re-measures and re-persists.
            cached = self._load_q8_verdict() if route == "auto" and self.warmup else None
            if cached is not None:
                chosen = cached["route"]
                self.quant_speedup = cached.get("q8_speedup")
                logger.info(
                    "VLM q8 decode verdict for %s loaded from disk: %s "
                    "(%.3fx bf16, measured %s); skipping warmup probe — "
                    "delete %s or pin LUMEN_VLM_Q8_ROUTE to re-measure",
                    self._q8_verdict_key(), chosen,
                    self.quant_speedup if self.quant_speedup is not None else float("nan"),
                    cached.get("measured_at", "?"), self._q8_verdict_path(),
                )
                params = quantize_decoder_int8(cast) if chosen == "int8" else cast
            elif route == "int8" or not self.warmup:
                chosen, params = "int8", quantize_decoder_int8(cast)
            else:
                chosen, params = self._q8_decode_ab(
                    base_cfg, cast, quantize_decoder_int8(cast)
                )
                self._save_q8_verdict(chosen)
        if chosen == "bf16":
            self.cfg = base_cfg
            self.model = VLMModel(self.cfg)
        self.quant_route = chosen
        ref = weakref.ref(self)

        def _route_gauges() -> dict:
            m = ref()
            if m is None:
                return {}
            out = {"int8_active": 1 if m.quant_route == "int8" else 0}
            if m.quant_speedup is not None:
                out["q8_speedup_pct"] = round(m.quant_speedup * 100, 1)
            return out

        self._route_gauge_fn = _route_gauges
        metrics.register_gauges(f"vlm-quant:{self.model_id}", _route_gauges)
        if vision_sub is not None:
            params["vision"] = vision_sub
        return params

    def _q8_verdict_key(self) -> str:
        return f"{self.info.name}@{self.info.version}"

    def _q8_verdict_path(self) -> str:
        return os.path.join(self.model_dir, ".lumen_q8_verdict.json")

    def _load_q8_verdict(self) -> dict | None:
        """Cached warmup A/B verdict for THIS model@revision, or None on
        miss/mismatch/corruption (all of which fall through to a fresh
        probe — a stale or mangled file must never pin a route)."""
        try:
            with open(self._q8_verdict_path(), "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if (
            not isinstance(data, dict)
            or data.get("model") != self._q8_verdict_key()
            or data.get("route") not in ("int8", "bf16")
        ):
            return None
        return data

    def _save_q8_verdict(self, route: str) -> None:
        """Best-effort persist (read-only model dirs lose the cache, not
        the boot)."""
        data = {
            "model": self._q8_verdict_key(),
            "route": route,
            "q8_speedup": self.quant_speedup,
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        try:
            with open(self._q8_verdict_path(), "w", encoding="utf-8") as f:
                json.dump(data, f)
        except OSError as e:
            logger.debug("could not persist q8 verdict to %s: %s", self._q8_verdict_path(), e)

    def _q8_decode_ab(self, base_cfg, cast: dict, qtree: dict):
        """One-shot warmup decode A/B; returns ``(route, tree)``. Timed
        SEQUENTIALLY (place bf16, time, free; place q8, time, free) so the
        memory-tight deployments that quantize in the first place never
        hold two decoder placements at once."""
        tps_bf16 = self._time_decode_route(VLMModel(base_cfg), base_cfg, cast, quantized=False)
        tps_q8 = self._time_decode_route(self.model, self.cfg, qtree, quantized=True)
        self.quant_speedup = tps_q8 / max(tps_bf16, 1e-9)
        if self.quant_speedup >= 1.0:
            logger.info(
                "VLM int8 decode route confirmed: %.3fx bf16 tokens/s",
                self.quant_speedup,
            )
            return "int8", qtree
        logger.warning(
            "VLM int8 decode route DISABLED: warmup A/B measured q8 decode "
            "at %.3fx bf16 tokens/s (a regression); serving bf16 instead. "
            "Pin LUMEN_VLM_Q8_ROUTE=int8 to force.",
            self.quant_speedup,
        )
        metrics.count("vlm_q8_fallbacks")
        return "bf16", cast

    def _time_decode_route(self, model, cfg, params: dict, quantized: bool) -> float:
        """Decode tokens/sec for one route: a short synthetic prompt
        through a small dedicated :class:`Generator` (the REAL decode
        program — prefill + while_loop step — at a timing-sized KV), best
        of 2 after a compile pass. The placement is freed before return."""
        prompt_len, new_tokens = 16, 24
        batch = max(1, min(4, self.gen_batch_size))
        placed = self._place_params(params, quantized=quantized)
        gen = Generator(
            model, cfg,
            max_seq=prompt_len + new_tokens + 8,
            max_new_cap=new_tokens,
            cache_dtype=self.policy.compute_dtype,
        )
        hidden = cfg.decoder.hidden_size
        embeds = jnp.zeros((batch, prompt_len, hidden), self.policy.compute_dtype)
        positions = jnp.broadcast_to(jnp.arange(prompt_len)[None, :], (batch, prompt_len))
        lengths = jnp.full((batch,), prompt_len, jnp.int32)
        prompt_ids = jnp.ones((batch, prompt_len), jnp.int32)

        def run() -> int:
            out = gen.generate(
                placed, embeds, positions, lengths, prompt_ids,
                jax.random.PRNGKey(0), max_new_tokens=new_tokens,
            )
            return int(np.asarray(out.n_generated).sum())

        run()  # compile + settle off the clock
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            n = run()
            best = max(best, max(n, 1) / (time.perf_counter() - t0))
        del placed
        return best

    def initialize(self) -> None:
        if self._initialized:
            return
        from .graph import VisionGraph, find_vision_onnx

        logger.info("loading VLM weights from %s", self.model_dir)
        state = load_state_dict(self.model_dir)
        from ...runtime.weights import assert_tree_shapes

        # Vision backend selection. ``auto`` (default): prefer converted
        # Flax vision weights when the checkpoint ships a complete tower —
        # an auxiliary vision*.onnx (e.g. an optimum export without the
        # projector) must not break a previously-working model dir — and
        # fall back to the ONNX graph otherwise (FastVLM-style repos whose
        # FastViTHD tower has no conversion rules). ``graph``/``native``
        # in model_info extra_metadata force one path.
        backend = str((self.info.extra_metadata or {}).get("vision_backend", "auto"))
        converted = convert_vlm_checkpoint(
            state, None, tie_word_embeddings=self.cfg.decoder.tie_word_embeddings
        )
        if self.quantize == "int8":
            # Route resolution may rebuild self.cfg/self.model (bf16 pin or
            # a warmup A/B fallback), so it runs BEFORE the eval_shape gate
            # below — the gate must describe the tree actually served.
            converted = self._resolve_q8_route(converted)
        init = jax.eval_shape(
            lambda: self.model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 4), jnp.int32),
                jnp.zeros(
                    (1, self.cfg.vision.image_size, self.cfg.vision.image_size, 3), jnp.float32
                ),
            )["params"]
        )
        has_native_vision = _subtree_matches(converted.get("vision"), init["vision"])
        vision_onnx = find_vision_onnx(self.model_dir) if backend != "native" else None
        vision_graph: VisionGraph | None = None
        if vision_onnx is not None and (backend == "graph" or not has_native_vision):
            vision_graph = VisionGraph.from_path(vision_onnx)
            params = converted
            # The Flax vision subtree is never executed on this path; keep
            # the shape gate on the decoder half only and don't burn HBM
            # on a dead tower.
            params.pop("vision", None)
            gate = {k: v for k, v in init.items() if k != "vision"}
            assert_tree_shapes(params, gate)
        else:
            if vision_onnx is None and backend == "graph":
                raise FileNotFoundError(
                    f"vision_backend=graph but no vision*.onnx in {self.model_dir}"
                )
            params = converted
            assert_tree_shapes(params, init)
        if not self.quantize:
            params = self.policy.cast_params(params)
        elif "vision" in params:
            # Quantized decoder was cast pre-quantization; the kept native
            # vision tower still needs its (ordinary) dtype cast.
            params["vision"] = self.policy.cast_params(params["vision"])
        self.params = self._place_params(params)
        self.tokenizer = VlmTokenizer.from_model_dir(self.model_dir)
        if vision_graph is not None:
            self.vision_tokens = vision_graph.probe(
                self.cfg.vision.image_size, self.cfg.decoder.hidden_size
            )
            from ...parallel.sharding import replicate

            # The graph-served vision tower has no TP rules; replicate so
            # it composes with a sharded decoder on the same mesh (on a
            # 1-device mesh this is plain device placement).
            self._vision_params = replicate(
                dict(vision_graph.module.params), self.mesh
            )
            logger.info(
                "vlm vision tower: graph %s (%d MB params, %d tokens)",
                vision_onnx,
                vision_graph.module.param_bytes() >> 20,
                self.vision_tokens,
            )
            # The host fp32 copy is duplicated on device now; the compiled
            # program receives weights via the vparams argument, so free
            # the originals instead of pinning them in the closure.
            vision_graph.module.release_weights()
        # A prompt bucket is usable only if prompt + vision tokens + the
        # decode budget fit in the KV buffer.
        v = self.vision_tokens
        self.prefill_buckets = [
            b for b in self.prefill_buckets if b - 1 + v + self.max_new_cap + 1 <= self.max_seq
        ]
        if not self.prefill_buckets:
            raise ValueError(
                f"max_seq={self.max_seq} too small for any prompt bucket "
                f"(+{v} vision tokens, +{self.max_new_cap} decode budget)"
            )
        compute = self.policy.compute_dtype
        # One KV bucket per prompt bucket (merged length + decode budget,
        # rounded up to 64): a short caption request allocates a cache
        # sized for ITS prompt bucket, not worst-case max_seq — the KV
        # right-sizing half of the memory story (the continuous pool is
        # fixed-size by design; this covers the fused/coalescing path).
        seq_buckets = tuple(
            min(self.max_seq, -((b - 1 + v + self.max_new_cap + 1) // -64) * 64)
            for b in self.prefill_buckets
        )
        self.generator = Generator(
            self.model, self.cfg, self.max_seq, self.max_new_cap,
            cache_dtype=compute, seq_buckets=seq_buckets,
        )

        vis_cfg = self.cfg.vision
        mean = jnp.asarray(vis_cfg.mean)
        std = jnp.asarray(vis_cfg.std)

        if vision_graph is not None:

            @jax.jit
            def prepare_graph(params, vparams, pixels_u8, ids, length):
                x = pixels_u8.astype(jnp.float32) / 255.0
                x = (x - mean) / std
                vis = vision_graph(vparams, x.transpose(0, 3, 1, 2)).astype(compute)
                text = self.model.apply({"params": params}, ids, method=VLMModel.embed_tokens)
                return merge_image_embeddings(
                    text.astype(compute), vis, ids, self.cfg.image_token_id, length
                )

            def prepare(params, pixels_u8, ids, length):
                return prepare_graph(params, self._vision_params, pixels_u8, ids, length)

        else:

            @jax.jit
            def prepare(params, pixels_u8, ids, length):
                x = pixels_u8.astype(jnp.float32) / 255.0
                x = ((x - mean) / std).astype(compute)
                vis = self.model.apply({"params": params}, x, method=VLMModel.encode_vision)
                text = self.model.apply({"params": params}, ids, method=VLMModel.embed_tokens)
                return merge_image_embeddings(
                    text.astype(compute), vis, ids, self.cfg.image_token_id, length
                )

        @jax.jit
        def prepare_text(params, ids, length):
            text = self.model.apply({"params": params}, ids, method=VLMModel.embed_tokens)
            b, s = ids.shape
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
            return text.astype(compute), positions, length

        self._prepare = prepare
        self._prepare_text = prepare_text
        self._batcher = None
        self._continuous = None
        self._engines = []
        self._engine_fleet = None
        if self.scheduler == "continuous":
            from ...runtime.fleet import batcher_name
            from ...utils.env import env_int
            from .continuous import ContinuousScheduler
            from .paged_kv import DEFAULT_PAGE_SIZE, resolve_pool_pages

            self._page_size = env_int(
                "LUMEN_VLM_PAGE_SIZE", DEFAULT_PAGE_SIZE, minimum=8, maximum=256
            )
            self._pool_pages = resolve_pool_pages(
                self.cfg, self._page_size, self.gen_slots, self.max_seq,
                dtype_bytes=jnp.dtype(compute).itemsize,
            )
            plan = self.fleet_plan

            def build_engine(rid: int | None, mesh, placed) -> ContinuousScheduler:
                """Manager factory for one per-replica decode engine: its
                own page pool + block tables on the replica's mesh slice,
                per-replica gauge names (``vlm-continuous:<model>-rN``)."""
                return ContinuousScheduler(
                    self.generator, placed, slots=self.gen_slots,
                    block=self.gen_block,
                    name=batcher_name(self.info.name, rid),
                    page_size=self._page_size, pages=self._pool_pages,
                    mesh=mesh if plan.replicas > 1 else None,
                )

            self._engine_factory = build_engine
            self._engines = [
                build_engine(None if plan.replicas == 1 else 0, plan.meshes[0], self.params)
            ]
            for rid in range(1, plan.replicas):
                placed = self._place_params(params, mesh=plan.meshes[rid])
                self._engines.append(build_engine(rid, plan.meshes[rid], placed))
            self._continuous = self._engines[0]
            if plan.replicas > 1:
                from ...runtime.fleet import EngineFleet

                def rebuild_engine(rid: int) -> ContinuousScheduler:
                    """Unpark hook: re-place the (already device-resident)
                    params on the replica's original mesh slice and build
                    a fresh engine there. The migration dispatcher is
                    wired at server boot only, so copy it over from a
                    surviving sibling — a rebuilt engine in a role-tagged
                    fleet must keep exporting rows."""
                    placed = self._place_params(
                        self.params, mesh=plan.meshes[rid]
                    )
                    eng = build_engine(rid, plan.meshes[rid], placed)
                    fleet = self._engine_fleet
                    if fleet is not None:
                        for sib in fleet.serving_engines():
                            if sib.migrator is not None:
                                eng.migrator = sib.migrator
                                break
                    return eng

                self._engine_fleet = EngineFleet(
                    self.info.name, list(self._engines),
                    build=rebuild_engine,
                    devices_per_replica=plan.devices_per_replica,
                )
                logger.info(
                    "VLM continuous engine fleet: %d replicas x %d slots "
                    "(%d devices each)",
                    plan.replicas, self.gen_slots, plan.devices_per_replica,
                )
        else:
            self._batcher = _GenBatcher(
                self._run_gen_batch,
                max_batch=self.gen_batch_size,
                max_latency_ms=self.gen_batch_latency_ms,
                name=self.info.name,
            )
        self._initialized = True
        if self.warmup:
            # Compile the dominant path up front (smallest prompt bucket:
            # text embed + prefill + one decode step); the image-prefill
            # variant still compiles on its first request.
            t0 = time.perf_counter()
            self.generate([ChatMessage(role="user", content="hi")], max_new_tokens=1)
            logger.info("vlm warmup (text path) in %.1fs", time.perf_counter() - t0)
        logger.info(
            "VLM ready: %s layers=%d hidden=%d vision_tokens=%d",
            self.model_id,
            self.cfg.decoder.layers,
            self.cfg.decoder.hidden_size,
            self.vision_tokens,
        )

    def close(self) -> None:
        if self._initialized:
            if self._batcher is not None:
                self._batcher.close()
            fleet = getattr(self, "_engine_fleet", None)
            if fleet is not None:
                # The fleet is authoritative after any unpark rebuilt an
                # engine the boot-time _engines list has no reference to.
                fleet.close()
            else:
                for engine in getattr(self, "_engines", []) or (
                    [self._continuous] if self._continuous is not None else []
                ):
                    engine.close()
        if fn := getattr(self, "_route_gauge_fn", None):
            metrics.unregister_gauges(f"vlm-quant:{self.model_id}", fn)
        self._initialized = False

    def _pick_engine(self):
        """Least-loaded dispatch across the per-replica continuous
        engines (queue depth + live rows + prefill lane). With a fleet
        attached, only SERVING engines are candidates — a parked engine
        stops receiving work the moment the autopilot parks it."""
        fleet = self._engine_fleet
        if fleet is not None:
            live = fleet.serving_engines()
            if live:
                return min(live, key=lambda e: e.load())
        if len(self._engines) == 1:
            return self._engines[0]
        return min(self._engines, key=lambda e: e.load())

    def kv_layout(self) -> str:
        """KV cache layout on the wire (capability ``extra``): operators
        and clients can see whether decode is paged without reading logs."""
        if self._continuous is not None:
            kv = self._continuous.kv
            return (
                f"paged(page={kv.page_size},pages={kv.pages_total},"
                f"slots={self.gen_slots})"
            )
        return f"contiguous(max_seq={self.max_seq})"

    def topology(self) -> dict[str, str]:
        """Device topology for the capability ``extra``: the continuous
        engine fleet reports one replica per device slice (built through
        the manager factory); coalesce stays one replica over the full
        mesh."""
        from ...runtime.fleet import topology_extra

        out = topology_extra(self.mesh)
        if len(getattr(self, "_engines", [])) > 1:
            out["replicas"] = str(len(self._engines))
        return out

    # -- prompt prep -------------------------------------------------------

    def _encode_prompt(
        self, messages: Sequence[ChatMessage], has_image: bool, add_generation_prompt: bool = True
    ) -> list[int]:
        prompt = self.tokenizer.render(messages, add_generation_prompt=add_generation_prompt)
        ids = self.tokenizer.encode(prompt)
        if has_image and self.cfg.image_token_id not in ids:
            # Template without an <image> slot: splice the placeholder up
            # front (reference requires the token to appear in the prompt,
            # ``onnxrt_backend.py:240-296``).
            ids = [self.cfg.image_token_id] + ids
        return ids

    def _bucket_len(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds the largest bucket {self.prefill_buckets[-1]}")

    def _prepare_inputs(self, messages, image_bytes, add_generation_prompt: bool = True):
        has_image = bool(image_bytes)
        ids = self._encode_prompt(messages, has_image, add_generation_prompt)
        n = len(ids)
        bucket = self._bucket_len(n)
        padded = np.full((1, bucket), self.cfg.pad_token_id, np.int32)
        padded[0, :n] = ids
        length = jnp.asarray([n], jnp.int32)
        if has_image:
            decoded = get_decode_pool().run_decode(
                "vlm_canvas", image_bytes, {"size": self.cfg.vision.image_size}
            )
            try:
                # jnp.asarray copies host pixels onto the device before
                # returning, so the arena slot can recycle right after.
                embeds, positions, lengths = self._prepare(
                    self.params, jnp.asarray(decoded.array[None]),
                    jnp.asarray(padded), length,
                )
            finally:
                decoded.release()
        else:
            embeds, positions, lengths = self._prepare_text(
                self.params, jnp.asarray(padded), length
            )
        return embeds, positions, lengths, jnp.asarray(padded), n

    def _prefix_content(self, prompt_ids, n: int, image_bytes) -> "np.ndarray | None":
        """Content identity of the POST-SPLICE sequence for the prefix KV
        cache: text token ids, with the ``<image>`` placeholder expanded
        to ``V`` int64s derived from the image-bytes sha256 digest
        (``(1<<62) | digest<<14 | position`` — far above any vocab id, so
        a text prefix can never alias a vision prefix). Two requests get
        equal content exactly when their merged embedding sequences are
        byte-equal, which is what makes the cached KV pages reusable.
        None when the cache is unconfigured — no hashing on the hot path."""
        from .prefix_cache import prefix_cache_enabled

        if self._continuous is None or not prefix_cache_enabled():
            return None
        ids = np.asarray(prompt_ids)[0, :n].astype(np.int64)
        if not image_bytes:
            return ids
        pos = np.where(ids == self.cfg.image_token_id)[0]
        if pos.size == 0:
            return ids
        i = int(pos[0])
        v = self.cfg.vision.num_tokens
        digest = int.from_bytes(hashlib.sha256(image_bytes).digest()[:6], "big")
        vis = (1 << 62) + (digest << 14) + np.arange(v, dtype=np.int64)
        return np.concatenate([ids[:i], vis, ids[i + 1 :]])

    def _make_gen_request(
        self, embeds, positions, lengths, prompt_ids,
        max_new_tokens, temperature, top_p, do_sample, repetition_penalty,
        prefix_content=None,
    ):
        """One construction site for both schedulers' request objects —
        adding a generation parameter means touching exactly here."""
        common = dict(
            embeds=embeds,
            positions=positions,
            length=lengths,
            prompt_ids=prompt_ids,
            max_new=min(int(max_new_tokens), self.max_new_cap),
            temperature=float(temperature),
            top_p=float(top_p),
            do_sample=bool(do_sample),
            repetition_penalty=float(repetition_penalty),
        )
        if self._continuous is not None:
            from ...utils import disagg
            from .continuous import _Request

            req = _Request(
                rng=self._next_rng(), prefix_content=prefix_content, **common
            )
            owner = disagg.current()
            if owner:
                # Disaggregated serving: the front tier pinned this
                # request's decode to a decode-lane peer; the scheduler
                # migrates the row there right after prefill.
                req.migrate_to = owner
            return req
        return _PendingGen(**common)

    def _next_rng(self) -> jax.Array:
        with self._seed_lock:
            self._seed += 1
            seed = self._seed
        return jax.random.PRNGKey(seed)

    # -- batched decode ----------------------------------------------------

    def _run_gen_batch(self, items: list) -> None:
        """Decode a same-shape group of requests as one [B] program and
        fan the per-row results back out (runs on the batcher thread).

        The batch dim is padded up to a power-of-two bucket (1,2,4,...)
        so distinct compiled programs per prompt bucket stay bounded at
        log2(max_batch)+1 instead of one per observed batch size — a
        serving-time compile on the sole batcher thread stalls every
        queued request. Padding rows replay row 0 with a zero budget, so
        they exit the decode loop immediately."""
        b = len(items)
        bucket = 1
        while bucket < b:
            bucket *= 2
        pad = bucket - b

        def stack(rows, pad_row):
            return jnp.concatenate(list(rows) + [pad_row] * pad, axis=0)

        embeds = stack((it.embeds for it in items), items[0].embeds)
        positions = stack((it.positions for it in items), items[0].positions)
        lengths = stack((it.length for it in items), items[0].length)
        prompt_ids = stack((it.prompt_ids for it in items), items[0].prompt_ids)
        out = self.generator.generate(
            self.params,
            embeds,
            positions,
            lengths,
            prompt_ids,
            self._next_rng(),
            max_new_tokens=[it.max_new for it in items] + [0] * pad,
            temperature=[it.temperature for it in items] + [0.0] * pad,
            top_p=[it.top_p for it in items] + [1.0] * pad,
            do_sample=[it.do_sample for it in items] + [False] * pad,
            repetition_penalty=[it.repetition_penalty for it in items] + [1.0] * pad,
        )
        tokens = np.asarray(out.tokens)
        n_gen = np.asarray(out.n_generated)
        eos = np.asarray(out.stopped_eos)
        for i, item in enumerate(items):
            item.future.set_result((tokens[i], int(n_gen[i]), bool(eos[i])))

    # -- generation --------------------------------------------------------

    def _cache_ns(self) -> str:
        """Result-cache namespace, qualified by compute dtype and the
        RESOLVED decode route (see
        :func:`~lumen_tpu.runtime.result_cache.make_namespace`): the
        warmup A/B can pick a different route across restarts, and an
        int8 generation must not answer for bf16 via the disk tier. A
        bf16-fallback route shares the unquantized namespace — it runs
        the identical program."""
        from ...ops.image import DECODE_POLICY

        return make_namespace(
            "vlm", "generate", self.model_id, self.info.version,
            jnp.dtype(self.policy.compute_dtype).name,
            "int8" if self.quant_route == "int8" else "",
            DECODE_POLICY,
        )

    def generate(
        self,
        messages: Sequence[ChatMessage],
        image_bytes: bytes | None = None,
        max_new_tokens: int = 256,
        temperature: float = 0.0,
        top_p: float = 1.0,
        do_sample: bool = False,
        repetition_penalty: float = 1.0,
        stop_sequences: Sequence[str] | None = None,
        add_generation_prompt: bool = True,
    ) -> GenerationResult:
        """Generate a caption/chat completion.

        Deterministic requests (greedy: ``do_sample=False`` and
        ``temperature <= 0`` — the caption-ingest default) route through
        the content-addressed result cache keyed on the raw image bytes +
        the full prompt/knob set, so a re-captioned photo skips vision
        encode, prefill and the whole decode loop; concurrent identical
        requests coalesce onto one flight. Sampled requests BYPASS the
        cache entirely — they are meant to differ run to run. Cached hits
        replay the original result verbatim, including its
        ``generation_time_ms`` metadata (the time the real computation
        took), plus a ``cached: True`` marker."""
        self._ensure_ready()
        if do_sample or temperature > 0.0:
            return self._generate_uncached(
                messages, image_bytes, max_new_tokens, temperature, top_p,
                do_sample, repetition_penalty, stop_sequences,
                add_generation_prompt,
            )
        options = {
            "messages": [(m.role, m.content) for m in messages],
            "max_new_tokens": int(max_new_tokens),
            "top_p": float(top_p),
            "repetition_penalty": float(repetition_penalty),
            "stop_sequences": list(stop_sequences) if stop_sequences else None,
            "add_generation_prompt": bool(add_generation_prompt),
        }

        def clone(result: GenerationResult) -> GenerationResult:
            import dataclasses

            return dataclasses.replace(
                result,
                tokens=list(result.tokens),
                metadata={**result.metadata, "cached": True},
            )

        # Quarantine gate on the request's content address (image bytes +
        # full prompt/knob set): a prompt+image pair that previously broke
        # the generation path is rejected before vision encode and
        # prefill. Sampled requests bypass the cache above and skip the
        # gate too — their options differ per call, so no stable
        # fingerprint exists to quarantine on.
        ns = self._cache_ns()
        payload = image_bytes or b""
        key = guarded_key(ns, options, payload)
        return get_result_cache().get_or_compute(
            ns,
            options,
            payload,
            lambda: self._generate_uncached(
                messages, image_bytes, max_new_tokens, temperature, top_p,
                do_sample, repetition_penalty, stop_sequences,
                add_generation_prompt,
            ),
            clone=clone,
            key=key,
        )

    def _generate_uncached(
        self,
        messages: Sequence[ChatMessage],
        image_bytes: bytes | None = None,
        max_new_tokens: int = 256,
        temperature: float = 0.0,
        top_p: float = 1.0,
        do_sample: bool = False,
        repetition_penalty: float = 1.0,
        stop_sequences: Sequence[str] | None = None,
        add_generation_prompt: bool = True,
    ) -> GenerationResult:
        t0 = time.perf_counter()
        embeds, positions, lengths, prompt_ids, n_input = self._prepare_inputs(
            messages, image_bytes, add_generation_prompt
        )
        req = self._make_gen_request(
            embeds, positions, lengths, prompt_ids,
            max_new_tokens, temperature, top_p, do_sample, repetition_penalty,
            prefix_content=self._prefix_content(prompt_ids, n_input, image_bytes),
        )
        if self._continuous is not None:
            future = self._pick_engine().submit(req)
        else:
            future = self._batcher.submit(req)
        row_tokens, n_gen, stopped_eos = future.result()
        tokens = [int(t) for t in row_tokens[:n_gen]]
        text = self.tokenizer.decode(tokens)
        finish = "eos_token" if stopped_eos else "length"
        text, hit = _truncate_on_stop(text, stop_sequences)
        if hit:
            finish = "stop_sequence"
        dt_ms = (time.perf_counter() - t0) * 1e3
        meta = {
            "temperature": temperature,
            "top_p": top_p,
            "repetition_penalty": repetition_penalty,
            "do_sample": do_sample,
            "generation_time_ms": round(dt_ms, 2),
            "tokens_per_second": round(n_gen / max(dt_ms / 1e3, 1e-9), 2),
        }
        meta.update(_reuse_meta(req))
        return GenerationResult(
            text=text.strip(),
            tokens=tokens,
            finish_reason=finish,
            input_tokens=n_input,
            metadata=meta,
        )

    def generate_stream(
        self,
        messages: Sequence[ChatMessage],
        image_bytes: bytes | None = None,
        max_new_tokens: int = 256,
        temperature: float = 0.0,
        top_p: float = 1.0,
        do_sample: bool = False,
        repetition_penalty: float = 1.0,
        stop_sequences: Sequence[str] | None = None,
        add_generation_prompt: bool = True,
    ) -> Iterator[GenerationChunk]:
        """Incremental generation: yields text deltas as tokens arrive
        (true streaming — the reference collects all chunks into one
        response, ``fastvlm_service.py:492-506``)."""
        self._ensure_ready()
        t0 = time.perf_counter()
        # Hold back enough text that a stop sequence straddling a chunk
        # boundary can still be cut before emission.
        holdback = max((len(s) for s in stop_sequences), default=1) - 1 if stop_sequences else 0
        # No global lock: the generator's prefill/step programs carry all
        # state explicitly (caches are per-call values), so concurrent
        # streams and batched generates interleave safely. The semaphore
        # only bounds how many per-stream KV caches are live at once; the
        # continuous scheduler's memory is the fixed slot pool instead, so
        # its streams need no such bound.
        if self._continuous is not None:
            yield from self._stream_locked(
                messages, image_bytes, max_new_tokens, temperature, top_p,
                do_sample, repetition_penalty, stop_sequences, holdback, t0,
                add_generation_prompt,
            )
            return
        self._stream_slots.acquire()
        try:
            yield from self._stream_locked(
                messages, image_bytes, max_new_tokens, temperature, top_p,
                do_sample, repetition_penalty, stop_sequences, holdback, t0,
                add_generation_prompt,
            )
        finally:
            self._stream_slots.release()

    def _stream_locked(
        self, messages, image_bytes, max_new_tokens, temperature, top_p,
        do_sample, repetition_penalty, stop_sequences, holdback, t0,
        add_generation_prompt=True,
    ) -> Iterator[GenerationChunk]:
        embeds, positions, lengths, prompt_ids, n_input = self._prepare_inputs(
            messages, image_bytes, add_generation_prompt
        )
        tokens: list[int] = []
        emitted = ""
        finish = "length"
        final_text: str | None = None
        # Time-to-first-emitted-chunk + per-stream decode rate, observed
        # at the source (this generator feeds both the gRPC stream path
        # and direct callers): cumulative histograms for /metrics,
        # rolling-window twins via the telemetry tee inside observe().
        first_emit_s: float | None = None

        def _note_first_emit() -> None:
            nonlocal first_emit_s
            if first_emit_s is None:
                first_emit_s = time.perf_counter()
                metrics.observe("vlm.ttft", (first_emit_s - t0) * 1e3)
        req = None
        if self._continuous is not None:
            req = self._make_gen_request(
                embeds, positions, lengths, prompt_ids,
                max_new_tokens, temperature, top_p, do_sample, repetition_penalty,
                prefix_content=self._prefix_content(prompt_ids, n_input, image_bytes),
            )
            token_iter = self._pick_engine().submit_stream(req)
        else:
            token_iter = self.generator.stream(
                self.params,
                embeds,
                positions,
                lengths,
                prompt_ids,
                self._next_rng(),
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_p=top_p,
                do_sample=do_sample,
                repetition_penalty=repetition_penalty,
            )
        for tok in token_iter:
            tokens.append(tok)
            if tok == self.cfg.eos_token_id:
                finish = "eos_token"
                break
            text = self.tokenizer.decode(tokens)
            # Byte-level BPE can split a multi-byte character across
            # tokens: the partial decode ends in U+FFFD and is not a
            # prefix of the next decode. Emit only stable prefixes.
            if text.endswith("�"):
                continue
            if stop_sequences:
                truncated, hit = _truncate_on_stop(text, stop_sequences)
                if hit:
                    finish = "stop_sequence"
                    final_text = truncated
                    break
            if not text.startswith(emitted):
                continue  # transient divergence; wait for re-extension
            delta = text[len(emitted) : max(len(text) - holdback, len(emitted))]
            if delta:
                emitted += delta
                _note_first_emit()
                yield GenerationChunk(text=delta, tokens=[tok])
        if final_text is None:
            final_text = self.tokenizer.decode(tokens)
        # Flush the held-back tail so the stream equals generate().
        if final_text.startswith(emitted) and len(final_text) > len(emitted):
            tail = final_text[len(emitted) :]
            emitted = final_text
            _note_first_emit()
            yield GenerationChunk(text=tail, tokens=[])
        dt_ms = (time.perf_counter() - t0) * 1e3
        meta = {
            "finish_reason": finish,
            "generated_tokens": len(tokens),
            "input_tokens": n_input,
            "generation_time_ms": round(dt_ms, 2),
        }
        if tokens:
            tps = len(tokens) / max(dt_ms / 1e3, 1e-9)
            # Histogram buckets are ms-labeled but dimensionless; this
            # series carries tokens/s (documented in OBSERVABILITY.md).
            metrics.observe("vlm.decode_tps", tps)
            meta["tokens_per_second"] = round(tps, 2)
        if first_emit_s is not None:
            meta["ttft_ms"] = round((first_emit_s - t0) * 1e3, 2)
        if req is not None:
            meta.update(_reuse_meta(req))
        yield GenerationChunk(text="", tokens=[], is_final=True, metadata=meta)

    # -- utils -------------------------------------------------------------

    def _ensure_ready(self) -> None:
        if not self._initialized:
            raise RuntimeError("VLMManager.initialize() not called")


def _flat_shapes(tree, prefix=""):
    out = {}
    for k, v in (tree or {}).items():
        if isinstance(v, dict):
            out.update(_flat_shapes(v, f"{prefix}{k}/"))
        else:
            out[f"{prefix}{k}"] = tuple(v.shape)
    return out


def _subtree_matches(sub, ref) -> bool:
    """True when ``sub`` carries exactly the leaves/shapes of ``ref`` — the
    checkpoint genuinely ships this subtree (not a partial or absent one)."""
    if not isinstance(sub, dict) or not sub:
        return False
    return _flat_shapes(sub) == _flat_shapes(ref)


def _reuse_meta(req) -> dict:
    """Per-request prefix-reuse / speculation outcomes for response
    metadata. Keys appear only when the engine actually recorded the
    feature for this request — an unconfigured engine's metadata is
    byte-identical to the pre-feature build."""
    out: dict[str, Any] = {}
    hit = getattr(req, "prefix_hit", None)
    if hit is not None:
        out["prefix_hit"] = round(hit, 3)
    proposed = getattr(req, "spec_proposed", 0)
    if proposed > 0:
        out["spec_accept_rate"] = round(req.spec_accepted / proposed, 3)
    return out


def _truncate_on_stop(text: str, stop_sequences: Sequence[str] | None) -> tuple[str, bool]:
    """Cut at the earliest stop sequence (reference ``stop_on_sequences``,
    ``backends/base.py:530-541``)."""
    if not stop_sequences:
        return text, False
    best = -1
    for stop in stop_sequences:
        idx = text.find(stop)
        if idx != -1 and (best == -1 or idx < best):
            best = idx
    if best == -1:
        return text, False
    return text[:best], True
