"""Graph-backed VLM vision tower: FastVLM ``vision.onnx`` on TPU.

The reference serves FastVLM as three onnxruntime sessions — vision.onnx,
embed.onnx, decoder.onnx (``packages/lumen-vlm/src/lumen_vlm/backends/
onnxrt_backend.py:107-140``). The autoregressive decoder runs best as our
native Flax Qwen2 (fused while_loop decode, golden-tested against HF in
``tests/test_vlm_golden.py``), but the vision tower is a single static-
shape forward per image — exactly what the ONNX->JAX bridge serves well.
Running ``vision.onnx`` through the bridge means FastViTHD-style hybrid
conv/attention towers work with the exporter's own weights, no per-
architecture conversion rules (the round-1 gap: "real FastVLM vision
towers will not convert").

Contract (reference ``_run_vision_encoder:661-729``): input [B,3,S,S]
normalized pixels, output [B, N, H_decoder] projector-space embeddings
ready to splice at the image-token position.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

from ...onnx_bridge import OnnxModule

logger = logging.getLogger(__name__)


def find_vision_onnx(model_dir: str) -> str | None:
    """Locate a ``vision*.onnx`` export (bare dir or ``onnx/`` subdir)."""
    dirs = [model_dir, os.path.join(model_dir, "onnx")]
    for d in dirs:
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if name.startswith("vision") and name.endswith(".onnx"):
                return os.path.join(d, name)
    return None


@dataclass
class VisionGraph:
    """[B,3,S,S] normalized floats -> [B,N,H] splice-ready embeddings."""

    module: OnnxModule

    @classmethod
    def from_path(cls, path: str) -> "VisionGraph":
        return cls(module=OnnxModule.from_path(path))

    def __call__(self, params: dict, x_nchw):
        import jax.numpy as jnp

        out = jnp.asarray(self.module(params, {self.module.input_names[0]: x_nchw})[0])
        if out.ndim != 3:
            raise ValueError(
                f"vision graph output must be [B, N, H], got shape {out.shape}"
            )
        return out

    def probe(self, image_size: int, hidden_size: int) -> int:
        """Run once on zeros to learn the token count and validate the
        embedding width against the decoder's hidden size."""
        import numpy as np

        out = self(
            self.module.params,
            np.zeros((1, 3, image_size, image_size), np.float32),
        )
        n, h = int(out.shape[1]), int(out.shape[2])
        if h != hidden_size:
            raise ValueError(
                f"vision graph emits width {h}, decoder hidden is {hidden_size}: "
                "the export must include the multimodal projector"
            )
        return n
