"""Content-addressed prefix KV cache for the paged continuous engine.

Serving traffic repeats prompt PREFIXES far more than whole prompts: a
shared system prompt in front of every request, a per-tenant caption
template, the same image asked a different question. The result cache
(:mod:`~lumen_tpu.runtime.result_cache`) only absorbs byte-identical
whole requests; everything else re-prefills a prefix whose KV is already
resident in the page pool. This module closes that gap with the same
content-address idiom, one page at a time:

- the KEY for page ``i`` of a prompt is the sha256 CHAIN hash
  ``h_i = sha256(h_{i-1} || content[i*ps:(i+1)*ps])`` over the prompt's
  page-aligned *content identity* (token ids, with vision positions
  substituted by ints derived from the image-bytes digest — see
  ``VLMManager._prefix_content``). Chaining makes a page's key encode its
  entire prefix, so a lookup is a walk down one path of a prefix tree and
  two different prompts can never collide on a shared suffix.
- the VALUE is a physical page id in the :class:`~.paged_kv.PagedKVPool`;
  the cache holds ONE reference on it. A hit attaches the matched pages
  to a new row as a block-table copy (``PagedKVPool.admit_shared``) and
  only the uncovered suffix runs through prefill — the device work for a
  hot prefix is ~zero.

Eviction is LRU over LEAF entries (an interior entry's children would
become unreachable — wasted pages the walk can never find again), bounded
by a ``LUMEN_VLM_PREFIX_BYTES`` / ``LUMEN_VLM_PREFIX_ENTRIES`` budget;
``reclaim`` additionally frees sole-reference pages (refcount == 1 — the
cache is the only holder) when the pool itself runs dry, so cached
prefixes yield to live rows before any row is preempted. Unconfigured
(no budget set) no cache is built at all and the engine's admission path
is byte-identical to the cache-less build.

NOT thread-safe: owned by the continuous scheduler's single loop thread,
exactly like the page pool it holds references in.
"""

from __future__ import annotations

import hashlib
import logging
from collections import OrderedDict

import numpy as np

from ...utils.env import env_int
from ...utils.metrics import metrics
from .paged_kv import PagedKVPool

logger = logging.getLogger(__name__)

PREFIX_BYTES_ENV = "LUMEN_VLM_PREFIX_BYTES"
PREFIX_ENTRIES_ENV = "LUMEN_VLM_PREFIX_ENTRIES"

#: domain-separation seed for the chain hash (position 0 has no parent).
_CHAIN_SEED = b"lumen-vlm-prefix-v1"


def prefix_budget_bytes() -> int:
    """``LUMEN_VLM_PREFIX_BYTES`` — device bytes (page size x layer KV
    footprint) the cache may pin in the page pool. 0/unset disables
    prefix caching entirely."""
    return env_int(PREFIX_BYTES_ENV, 0, minimum=0)


def prefix_cache_enabled() -> bool:
    return prefix_budget_bytes() > 0


def chunk_keys(content: np.ndarray, page_size: int) -> list[bytes]:
    """Chain-hash keys for every FULL page of ``content`` (the prompt's
    content-identity array). Partial tail pages are never cached — their
    contents would be mutated by the first decode writes."""
    arr = np.ascontiguousarray(content, dtype=np.int64)
    keys: list[bytes] = []
    prev = _CHAIN_SEED
    for i in range(arr.shape[0] // page_size):
        h = hashlib.sha256(prev)
        h.update(arr[i * page_size : (i + 1) * page_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


class _Entry:
    __slots__ = ("page", "parent", "children")

    def __init__(self, page: int, parent: bytes | None):
        self.page = page
        self.parent = parent
        self.children = 0


class PrefixCache:
    """Bounded chain-hash map ``prefix key -> pooled page id``, holding
    one pool reference per entry."""

    def __init__(self, pool: PagedKVPool, page_nbytes: int):
        self._pool = pool
        self.page_nbytes = max(1, int(page_nbytes))
        budget = prefix_budget_bytes()
        by_bytes = max(1, budget // self.page_nbytes) if budget else 0
        explicit = env_int(PREFIX_ENTRIES_ENV, None, minimum=1)
        if explicit is not None:
            self.max_entries = min(explicit, by_bytes) if by_bytes else explicit
        else:
            self.max_entries = by_bytes or 1
        # OrderedDict = LRU order (move_to_end on touch); an entry's key
        # encodes its whole prefix, so this is a prefix tree flattened
        # into one map with parent/children links for leaf-only eviction.
        self._entries: OrderedDict[bytes, _Entry] = OrderedDict()
        self.evictions = 0

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_held(self) -> int:
        return len(self._entries) * self.page_nbytes

    def held_pages(self) -> list[int]:
        """Every page id the cache holds a reference on (tests/drain)."""
        return [e.page for e in self._entries.values()]

    def lookup(self, keys: list[bytes]) -> list[int]:
        """Longest cached prefix: page ids for the leading run of ``keys``
        present in the cache (LRU-touched). Stops at the first miss —
        chain keys make a gap unbridgeable by construction."""
        pages: list[int] = []
        for k in keys:
            e = self._entries.get(k)
            if e is None:
                break
            self._entries.move_to_end(k)
            pages.append(e.page)
        return pages

    def peek(self, keys: list[bytes]) -> int:
        """ADVISORY leading-run length for ``keys`` — no LRU touch, no
        reference taken, no mutation. Serves the migration offer leg
        from the gRPC thread while the loop thread owns the cache: a
        concurrent resize can at worst mis-size the answer, and the
        commit leg re-resolves authoritatively on the loop thread
        (:class:`~.migration.ChunksMissing` on a lost race). Callers
        off the loop thread must treat any exception as 0."""
        n = 0
        for k in keys:
            if k not in self._entries:
                break
            n += 1
        return n

    # -- mutation ----------------------------------------------------------

    def insert(self, keys: list[bytes], pages: list[int]) -> int:
        """Record a freshly installed row's full prompt pages. Existing
        entries are LRU-touched and KEPT (same content may live in two
        physical pages when two cold rows raced — the cached id is the
        one future hits attach); new entries take one pool reference.
        Returns how many entries were added."""
        added = 0
        parent: _Entry | None = None
        for i, (k, page) in enumerate(zip(keys, pages)):
            e = self._entries.get(k)
            if e is None:
                if not self._make_room():
                    break
                self._pool.incref([page])
                # Parent link is the PREVIOUS key (not the entry object)
                # so eviction can fix up children counts by lookup.
                e = _Entry(page, keys[i - 1] if i else None)
                self._entries[k] = e
                if parent is not None:
                    parent.children += 1
                added += 1
            else:
                self._entries.move_to_end(k)
            parent = e
        return added

    def _pop(self, key: bytes, entry: _Entry) -> int:
        """Drop one entry (must be a leaf) and its pool reference."""
        del self._entries[key]
        if entry.parent is not None:
            par = self._entries.get(entry.parent)
            if par is not None:
                par.children -= 1
        freed = self._pool.decref([entry.page])
        self.evictions += 1
        metrics.count("vlm_prefix_evictions")
        return freed

    def _evict_leaf(self, sole_only: bool) -> int | None:
        """Evict the least-recently-used LEAF entry; ``sole_only``
        restricts victims to pages the cache is the last holder of (the
        only evictions that actually free pool pages). Returns pages
        physically freed, or None when no eligible victim exists."""
        for k in list(self._entries):
            e = self._entries[k]
            if e.children:
                continue
            if sole_only and self._pool.refcount(e.page) != 1:
                continue
            return self._pop(k, e)
        return None

    def _make_room(self) -> bool:
        while len(self._entries) >= self.max_entries:
            if self._evict_leaf(sole_only=False) is None:
                return False
        return True

    def reclaim(self, n_pages: int) -> int:
        """Pool-pressure eviction: free up to ``n_pages`` pool pages by
        dropping sole-reference leaves, LRU-first. Called by the
        scheduler BEFORE it preempts a live row — cached history always
        yields to running work. Returns pages freed."""
        freed = 0
        while freed < n_pages:
            got = self._evict_leaf(sole_only=True)
            if got is None:
                break
            freed += got
        return freed

    def clear(self) -> int:
        """Drop every entry and reference (engine close / tests).
        Returns pages physically freed."""
        entries = self._entries
        self._entries = OrderedDict()
        return self._pool.decref([e.page for e in entries.values()])

    def gauges(self) -> dict:
        return {
            "prefix_entries": len(self._entries),
            "prefix_bytes": self.bytes_held,
            "prefix_budget_bytes": self.max_entries * self.page_nbytes,
            "prefix_evictions": self.evictions,
        }
