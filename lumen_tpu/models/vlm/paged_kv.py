"""Paged KV pool for continuous VLM decode: host-side page accounting.

The slot-era continuous scheduler gave every decode row a contiguous
``max_seq`` KV region, so an 8-slot pool paid ``8 x max_seq`` of HBM no
matter how short the generations were, and admission needed a same-shape
bucket. Here KV lives in fixed-size PAGES drawn from one shared pool
(device arrays: ``[num_pages, kv_heads, page_size, head_dim]`` per layer,
see ``generate.Generator.init_pool``); each sequence owns a BLOCK TABLE of
page ids that grows a page at a time as decode crosses page boundaries and
is returned to the free list at retire. Long and short generations share
the pool, and a request admits the moment a slot and its prompt's pages
are free — the Ragged Paged Attention recipe (PAPERS.md, arxiv 2604.15464)
with the O(1)-per-step cache discipline of arxiv 2603.09555 kept portable:
the same block tables drive the Pallas kernel on TPU and the exact XLA
gather reference on CPU (``ops.attention.paged_attention``).

This module is the HOST half: the free list, per-slot block tables, and
the allocated/freed/live accounting the bench asserts balances at drain.
Device-side page contents are owned by the scheduler's pool dict and only
ever addressed through these tables.

Page 0 is reserved as the DUMP page: unused block-table entries point at
it so device-side scatters always have a safe target (free rows and the
padded tail of a prompt scatter write garbage there; nothing ever reads
it back — attention masks by per-row length).

Pages are REFCOUNTED so block tables can share physical pages: the prefix
cache attaches a hot prompt prefix to a new row as a block-table copy
(``admit_shared``), every holder — rows, the prefix cache, parked spill
records — owns one reference, and a page returns to the free list only
when its last reference drops. Accounting is reference-granular: every
reference grant is one ``allocated_total`` tick and every drop one
``freed_total`` tick, so the balance-at-drain invariant (live == 0,
allocated == freed) survives sharing unchanged. Appending into a shared
page is a copy-on-write: ``grow`` swaps a fresh page into the frontier
slot and hands the (old, new) pair back so the caller can device-copy the
contents — by construction the engine never hits this (shared prefixes
are page-aligned and at least the prompt's last token always prefills
into a private page), but the allocator stays safe for any caller.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

logger = logging.getLogger(__name__)

#: default tokens per KV page. 16 keeps the page's [page, head_dim] tile
#: bf16-sublane aligned on TPU and the per-page waste (< page tokens per
#: row) small against prompt lengths in the hundreds.
DEFAULT_PAGE_SIZE = 16

#: fraction of free HBM the pool may claim when sized from device stats.
DEFAULT_HEADROOM_FRACTION = 0.6


class PoolExhausted(RuntimeError):
    """Raised by :meth:`PagedKVPool.grow` callers that cannot free pages
    (the scheduler catches this and preempts a row instead)."""


@dataclass
class PageStats:
    pages_total: int
    page_size: int
    pages_free: int
    pages_live: int
    allocated_total: int  # cumulative reference grants since boot
    freed_total: int  # cumulative reference drops since boot
    pages_held: int = 0  # physical pages out of the free list
    pages_shared: int = 0  # physical pages with more than one reference


class PagedKVPool:
    """Free-list page allocator with per-slot block tables.

    NOT thread-safe: the continuous scheduler owns it from its single
    loop thread. ``block_tables`` is the numpy source of truth shipped to
    the device programs each dispatch (a [slots, max_pages] int32 is a
    few hundred bytes — re-uploading per block is noise next to a decode
    step).
    """

    def __init__(self, pages_total: int, page_size: int, slots: int, max_pages: int):
        if pages_total < 2:
            raise ValueError(f"pages_total must be >= 2 (page 0 is the dump page), got {pages_total}")
        self.pages_total = pages_total
        self.page_size = page_size
        self.max_pages = max_pages
        # LIFO free list: hot pages are reused first (their HBM lines are
        # the most recently touched). Page 0 is never in the list.
        self._free = list(range(pages_total - 1, 0, -1))
        self._owned: dict[int, list[int]] = {}  # slot -> owned page ids
        # page id -> outstanding references, for every page out of the
        # free list. A slot's grant, a prefix-cache entry and a parked
        # spill record each hold ONE reference; the page is physically
        # freed when the count hits zero.
        self._ref: dict[int, int] = {}
        # slot -> how many LEADING pages of its grant were attached from
        # a shared prefix (never written by this row; the spill tier must
        # not export them and decode never lands a write in them).
        self._shared: dict[int, int] = {}
        self.block_tables = np.zeros((slots, max_pages), np.int32)
        self.allocated_total = 0
        self.freed_total = 0

    # -- queries -----------------------------------------------------------

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_live(self) -> int:
        return self.allocated_total - self.freed_total

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` KV slots."""
        return max(1, -(-int(tokens) // self.page_size))

    def row_capacity(self) -> int:
        """Max tokens one row's block table can address."""
        return self.max_pages * self.page_size

    def fits(self, tokens: int) -> bool:
        """Feasibility: could ``tokens`` EVER fit (full pool, one row)?
        Admission must reject what can never run; mid-flight shortage is
        handled by preemption instead."""
        return tokens <= self.row_capacity() and self.pages_for(tokens) <= self.pages_total - 1

    def can_admit(self, tokens: int, shared_pages: int = 0) -> bool:
        """Are enough pages free RIGHT NOW for a prompt of ``tokens``
        (plus the first decode write)? ``shared_pages`` leading pages
        attached from the prefix cache need no fresh grant."""
        return self.pages_for(tokens + 1) - shared_pages <= len(self._free)

    def refcount(self, page: int) -> int:
        """Outstanding references on ``page`` (0 = free / dump page)."""
        return self._ref.get(page, 0)

    def shared_prefix_len(self, slot: int) -> int:
        """How many leading pages of the slot's grant are attached shared
        prefix (read-only for this row)."""
        return self._shared.get(slot, 0)

    # -- transitions -------------------------------------------------------

    def _pop_fresh(self, n: int) -> list[int]:
        """Pop ``n`` fresh pages (one reference each, counted)."""
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self.allocated_total += n
        return pages

    def incref(self, pages: list[int]) -> None:
        """Take one additional reference on each page (a new holder —
        a sharing row, the prefix cache, or a parked spill record).
        Reference-granular accounting: each grant is an allocation."""
        for p in pages:
            ref = self._ref.get(p)
            if not ref:
                raise RuntimeError(f"incref of free page {p} (allocator bug)")
            self._ref[p] = ref + 1
        self.allocated_total += len(pages)

    def decref(self, pages: list[int]) -> int:
        """Drop one reference per page; pages reaching zero return to the
        free list. Returns how many pages were physically freed."""
        freed = 0
        for p in pages:
            ref = self._ref.get(p)
            if not ref:
                raise RuntimeError(f"decref of free page {p} (double free)")
            if ref > 1:
                self._ref[p] = ref - 1
            else:
                del self._ref[p]
                self._free.append(p)
                freed += 1
        self.freed_total += len(pages)
        return freed

    def _install(self, slot: int, pages: list[int], shared: int) -> np.ndarray:
        row = np.zeros((self.max_pages,), np.int32)
        row[: len(pages)] = pages
        self.block_tables[slot] = row
        self._owned[slot] = pages
        if shared:
            self._shared[slot] = shared
        return self.block_tables[slot]

    def admit(self, slot: int, prompt_tokens: int) -> np.ndarray:
        """Grant pages covering ``prompt_tokens`` + the first decode write
        and install the slot's block table row. Returns the row (view)."""
        if slot in self._owned:
            raise RuntimeError(f"slot {slot} already owns pages (allocator bug)")
        need = self.pages_for(prompt_tokens + 1)
        if need > len(self._free):
            raise PoolExhausted(f"need {need} pages, {len(self._free)} free")
        return self._install(slot, self._pop_fresh(need), 0)

    def admit_shared(
        self, slot: int, shared_pages: list[int], prompt_tokens: int
    ) -> np.ndarray:
        """Prefix-cache hit admission: attach ``shared_pages`` (one new
        reference each — their contents are the page-aligned prompt
        prefix, already resident) as the row's leading pages and grant
        fresh pages for the rest of the prompt + the first decode write.
        The shared prefix is strictly shorter than the prompt (the hit
        path caps coverage at ``prompt_tokens - 1``), so the write
        frontier always lands in a private page and the row never
        mutates shared contents."""
        if slot in self._owned:
            raise RuntimeError(f"slot {slot} already owns pages (allocator bug)")
        if len(shared_pages) * self.page_size > prompt_tokens:
            raise ValueError("shared prefix covers the whole prompt (hit-path bug)")
        need = self.pages_for(prompt_tokens + 1)
        fresh_need = need - len(shared_pages)
        if fresh_need < 1:
            raise ValueError("shared prefix leaves no private frontier page")
        if fresh_need > len(self._free):
            raise PoolExhausted(f"need {fresh_need} pages, {len(self._free)} free")
        self.incref(shared_pages)
        pages = list(shared_pages) + self._pop_fresh(fresh_need)
        return self._install(slot, pages, len(shared_pages))

    def owned_pages(self, slot: int) -> list[int]:
        """The slot's owned page ids in block-table order (grant order) —
        the spill tier exports page contents in exactly this order so a
        resume can re-install them into a fresh grant positionally."""
        return list(self._owned.get(slot, ()))

    def admit_exact(
        self, slot: int, n_pages: int, shared_pages: list[int] | None = None
    ) -> np.ndarray:
        """Grant exactly ``n_pages`` fresh pages and install the slot's
        block table row — the resume half of the spill tier, where the
        page count is the victim's exported PRIVATE grant, not a prompt
        length. ``shared_pages`` (a spilled row's shared prefix, kept
        alive by the spill record's reference) are re-attached ahead of
        the fresh grant. Returns the row (view); same accounting as
        :meth:`admit`."""
        shared = list(shared_pages or ())
        if slot in self._owned:
            raise RuntimeError(f"slot {slot} already owns pages (allocator bug)")
        if not 1 <= n_pages <= self.max_pages - len(shared):
            raise ValueError(
                f"resume grant of {n_pages} pages outside [1, {self.max_pages - len(shared)}]"
            )
        if n_pages > len(self._free):
            raise PoolExhausted(f"need {n_pages} pages, {len(self._free)} free")
        self.incref(shared)
        pages = shared + self._pop_fresh(n_pages)
        return self._install(slot, pages, len(shared))

    def grow(self, slot: int, tokens: int, cow_out: list | None = None) -> bool:
        """Ensure the slot's pages cover ``tokens`` KV slots; allocate as
        needed. False when the free list runs dry mid-growth (partial
        grants stand — accounting stays balanced; the caller preempts a
        row and retries). ``tokens`` beyond the block table's reach clamp
        to ``row_capacity()`` — the decode program clamps its writes the
        same way, so a full row keeps overwriting its last slot instead
        of the allocator indexing past the table.

        Copy-on-write: growth means the caller is about to APPEND into
        the current frontier page; if that page is shared (refcount > 1),
        it is swapped for a fresh private page first and the ``(old,
        new)`` id pair appended to ``cow_out`` so the caller can
        device-copy the contents before writing. The engine's page-
        aligned prefix sharing never triggers this (the frontier is
        always private by construction) — a trigger with no ``cow_out``
        to report through is therefore an allocator-contract bug."""
        pages = self._owned[slot]
        need = min(self.pages_for(tokens), self.max_pages)
        if need > len(pages) and pages and self._ref.get(pages[-1], 0) > 1:
            if not self._free:
                return False
            old = pages[-1]
            new = self._pop_fresh(1)[0]
            pages[-1] = new
            self.block_tables[slot, len(pages) - 1] = new
            self.decref([old])
            if self._shared.get(slot, 0) >= len(pages):
                self._shared[slot] = len(pages) - 1
            if cow_out is None:
                raise RuntimeError(
                    f"copy-on-write of shared frontier page {old} with no "
                    "copy sink (allocator-contract bug)"
                )
            cow_out.append((old, new))
        while len(pages) < need:
            if not self._free:
                return False
            page = self._pop_fresh(1)[0]
            self.block_tables[slot, len(pages)] = page
            pages.append(page)
        return True

    def release(self, slot: int) -> int:
        """Drop a retired slot's reference on each of its pages (last
        holder returns them to the free list); the block-table row resets
        to the dump page. Returns the reference count dropped."""
        pages = self._owned.pop(slot, [])
        self._shared.pop(slot, None)
        self.block_tables[slot] = 0
        # Reversed: the row's FIRST page ends on top of the LIFO free
        # list, preserving the pre-refcount reuse order exactly.
        self.decref(list(reversed(pages)))
        return len(pages)

    def stats(self) -> PageStats:
        return PageStats(
            pages_total=self.pages_total,
            page_size=self.page_size,
            pages_free=len(self._free),
            pages_live=self.pages_live,
            allocated_total=self.allocated_total,
            freed_total=self.freed_total,
            pages_held=len(self._ref),
            pages_shared=sum(1 for r in self._ref.values() if r > 1),
        )


def page_bytes(cfg, page_size: int, dtype_bytes: int) -> int:
    """HBM cost of ONE page id across every decoder layer (each page id
    indexes a [page_size, head_dim] K and V tile in all layers)."""
    d = cfg.decoder
    return 2 * d.layers * d.kv_heads * page_size * d.dim_per_head * dtype_bytes


def resolve_pool_pages(
    cfg,
    page_size: int,
    slots: int,
    max_seq: int,
    dtype_bytes: int = 2,
) -> int:
    """Pool size in pages: ``LUMEN_VLM_KV_PAGES`` pins it; otherwise size
    against live HBM headroom from ``metrics.device_memory()`` (the PR 9
    telemetry surface), claiming ``LUMEN_VLM_KV_HEADROOM`` of the free
    bytes on the tightest device. Backends without memory stats (CPU
    tier-1) fall back to the slot-era footprint — ``slots`` full-length
    rows — so tests and laptops behave exactly as the contiguous pool did
    memory-wise while still getting page sharing."""
    from ...utils.env import env_float, env_int
    from ...utils.metrics import metrics

    maxp = -(-max_seq // page_size)
    # Floor: every slot can hold at least one modest row (1/4 max_seq)
    # concurrently; below that the pool thrashes on preemption.
    floor = slots * max(1, maxp // 4) + 1
    fallback = slots * maxp + 1
    explicit = env_int("LUMEN_VLM_KV_PAGES", None, minimum=2)
    if explicit is not None:
        return max(explicit, 2)
    frac = env_float(
        "LUMEN_VLM_KV_HEADROOM", DEFAULT_HEADROOM_FRACTION, minimum=0.05, maximum=0.95
    )
    per_page = page_bytes(cfg, page_size, dtype_bytes)
    headroom = None
    for stats in metrics.device_memory().values():
        limit, in_use = stats.get("bytes_limit"), stats.get("bytes_in_use")
        if limit:
            free = max(0, int(limit) - int(in_use or 0))
            headroom = free if headroom is None else min(headroom, free)
    if headroom is None:
        return fallback
    pages = int(headroom * frac) // max(per_page, 1)
    # Cap at what block tables can even address (slots x max_pages) — a
    # bigger pool than addressable is pure waste.
    cap = slots * maxp + 1
    sized = max(floor, min(pages, cap))
    logger.info(
        "VLM paged-KV pool: %d pages x %d tokens (%.1f MB of %.1f MB headroom, cap %d)",
        sized, page_size, sized * per_page / 1e6, headroom / 1e6, cap,
    )
    return sized
