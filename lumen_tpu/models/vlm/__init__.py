"""VLM family: ViT vision encoder + Qwen2-style decoder, static-KV-cache
generation (reference: ``packages/lumen-vlm``)."""

from .chat import ChatMessage, VlmTokenizer, render_chat
from .generate import GenerateOutput, Generator
from .manager import GenerationChunk, GenerationResult, VLMManager
from .modeling import (
    DecoderConfig,
    VisionTowerConfig,
    VLMConfig,
    VLMModel,
    init_kv_cache,
    merge_image_embeddings,
)

__all__ = [
    "ChatMessage",
    "VlmTokenizer",
    "render_chat",
    "Generator",
    "GenerateOutput",
    "GenerationChunk",
    "GenerationResult",
    "VLMManager",
    "DecoderConfig",
    "VisionTowerConfig",
    "VLMConfig",
    "VLMModel",
    "init_kv_cache",
    "merge_image_embeddings",
]
