"""Fused autoregressive generation: prefill + ``lax.while_loop`` decode.

The reference decodes with a host python loop — one onnxruntime session
call per token, rebuilding the attention mask and renaming ``present.*``
outputs each step (``packages/lumen-vlm/src/lumen_vlm/backends/
onnxrt_backend.py:298-356``, ``:480-492``). Here the entire loop — embed,
decoder forward over the static KV cache, repetition penalty, temperature/
top-p sampling, EOS check — is ONE compiled XLA program; the host sees only
the final token buffer. Streaming keeps a host loop for chunk delivery but
each step is still a single compiled call (no mask rebuilds, no renames).

Sampling semantics follow the reference (``:508-533``): greedy when
``do_sample`` is false or temperature ~ 0, else temperature + nucleus.
Generation params are traced scalars, so one compiled program serves every
request config.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.sampling import apply_repetition_penalty, sample
from .modeling import VLMConfig, VLMModel, init_kv_cache, init_paged_kv_cache


@dataclass
class GenerateOutput:
    tokens: jax.Array  # [B, max_new_cap] generated ids, pad-filled after EOS
    n_generated: jax.Array  # [B] count of live tokens (EOS included)
    stopped_eos: jax.Array  # [B] bool: hit EOS (vs length cap)


class Generator:
    """Compiled generation programs for one ``VLMModel``.

    ``max_seq`` bounds prompt+vision+new tokens (the KV buffer size);
    ``max_new_cap`` is the static output-buffer size. Both are compile-time
    constants — the per-request ``max_new_tokens`` is a traced value bounded
    by the cap.
    """

    def __init__(
        self,
        model: VLMModel,
        cfg: VLMConfig,
        max_seq: int = 2048,
        max_new_cap: int = 512,
        cache_dtype=jnp.bfloat16,
        seq_buckets: tuple[int, ...] | None = None,
    ):
        self.model = model
        self.cfg = cfg
        self.max_seq = max_seq
        self.max_new_cap = max_new_cap
        self.cache_dtype = cache_dtype
        # KV right-sizing (round-4 verdict): the fused path allocates its
        # cache at the smallest bucket >= prompt + budget instead of
        # worst-case max_seq — a 32-token caption request in a
        # max_seq=2048 deployment gets a 32x smaller KV buffer AND a
        # proportionally cheaper decode attention. One compiled program
        # per bucket actually used.
        buckets = sorted(set(b for b in (seq_buckets or ()) if b <= max_seq))
        if not buckets or buckets[-1] != max_seq:
            buckets.append(max_seq)
        self.seq_buckets = tuple(buckets)
        self._generate = jax.jit(self._generate_impl, static_argnames=("kv_len",))
        self._prefill = jax.jit(self._prefill_impl, static_argnames=("kv_len",))
        self._step = jax.jit(self._step_impl)
        # The paged KV pool is the dominant buffer; donating it lets XLA
        # update in place instead of holding two copies across every
        # admit/block dispatch. Block tables are host-managed (numpy in
        # paged_kv.PagedKVPool) and ride in as a small fresh operand.
        self._admit = jax.jit(self._admit_impl, donate_argnames=("pool",))
        # KV spill tier programs: export gathers a victim row's live pages
        # + decode scalars for ONE fused device->host transfer (read-only,
        # no donation — a failed export must leave the pool intact);
        # resume scatters exported pages into a fresh grant and restores
        # the row's scalars, donating the pool exactly like _admit.
        self._export_row = jax.jit(self._export_row_impl)
        self._resume = jax.jit(self._resume_impl, donate_argnames=("pool",))
        self._step_block = jax.jit(
            self._step_block_impl, static_argnames=("block",), donate_argnames=("pool",)
        )
        # Chunked-prefill lane programs: one chunk of prompt through the
        # decoder into a donated contiguous scratch cache, and the finish
        # step that samples token 0 once the last live chunk ran.
        self._prefill_chunk = jax.jit(
            self._prefill_chunk_impl, donate_argnames=("caches",)
        )
        self._chunk_finish = jax.jit(self._chunk_finish_impl)
        # Prefix-reuse + speculative-decoding programs: seed a prefill
        # scratch from already-computed pool pages (prefix-cache hit), and
        # verify a W-token draft window in one decode forward.
        self._seed_prefix = jax.jit(self._seed_prefix_impl, donate_argnames=("caches",))
        self._verify = jax.jit(
            self._verify_impl, static_argnames=("width",), donate_argnames=("pool",)
        )

    # -- shared pieces ------------------------------------------------------

    def _decode(self, params, embeds, positions, caches, offset, kv_valid_len):
        return self.model.apply(
            {"params": params},
            embeds,
            positions,
            caches,
            offset,
            kv_valid_len,
            method=VLMModel.decode,
        )

    def _embed(self, params, ids):
        return self.model.apply({"params": params}, ids, method=VLMModel.embed_tokens)

    def _seen_from_prompt(self, prompt_ids: jax.Array, lengths: jax.Array) -> jax.Array:
        """[B, V] bool mask of tokens present in the (unpadded) prompt, for
        the repetition penalty."""
        b, s = prompt_ids.shape
        valid = jnp.arange(s)[None, :] < lengths[:, None]
        seen = jnp.zeros((b, self.cfg.decoder.vocab_size), bool)
        bidx = jnp.arange(b)[:, None]
        return seen.at[bidx, jnp.where(valid, prompt_ids, 0)].max(valid)

    def _sample_next(self, rng, logits, seen, temperature, top_p, do_sample, rep_penalty):
        logits = logits.astype(jnp.float32)
        logits = apply_repetition_penalty(logits, seen, rep_penalty)
        return sample(rng, logits, temperature, top_p, do_sample)

    def _prefill_core(self, params, embeds, positions, lengths, kv_len: int | None = None):
        b = embeds.shape[0]
        caches = init_kv_cache(self.cfg, b, kv_len or self.max_seq, self.cache_dtype)
        logits, caches = self._decode(
            params, embeds, positions, caches, jnp.zeros((), jnp.int32), lengths
        )
        last = logits[jnp.arange(b), lengths - 1]  # [B, V] next-token logits
        return caches, last

    # -- fused non-streaming path -------------------------------------------

    def _generate_impl(
        self,
        params,
        embeds,  # [B, L, H] merged prompt embeddings (right-padded)
        positions,  # [B, L]
        lengths,  # [B] live token count
        prompt_ids,  # [B, S_text] original text ids (for repetition penalty)
        rng,
        max_new_tokens,  # traced scalar or per-sample [B], <= max_new_cap
        temperature,  # sampling params: traced scalars or per-sample [B]
        top_p,
        do_sample,
        repetition_penalty,
        kv_len: int | None = None,  # static: KV bucket (defaults to max_seq)
    ):
        cfg = self.cfg
        b = embeds.shape[0]
        caches, last_logits = self._prefill_core(params, embeds, positions, lengths, kv_len)
        seen = self._seen_from_prompt(prompt_ids, lengths)
        rng, sub = jax.random.split(rng)
        tok0 = self._sample_next(
            sub, last_logits, seen, temperature, top_p, do_sample, repetition_penalty
        ).astype(jnp.int32)
        max_new = jnp.broadcast_to(jnp.asarray(max_new_tokens, jnp.int32), (b,))

        buf = jnp.full((b, self.max_new_cap), cfg.pad_token_id, jnp.int32)
        state = dict(
            caches=caches,
            cur_tok=tok0,
            cur_len=lengths.astype(jnp.int32),  # cache slots filled so far
            t=jnp.zeros((), jnp.int32),
            rng=rng,
            # A zero-budget row must emit nothing even when batched with
            # live rows (solo, cond already short-circuits).
            done=max_new <= 0,
            eos=jnp.zeros((b,), bool),
            buf=buf,
            seen=seen,
            n_gen=jnp.zeros((b,), jnp.int32),
        )

        def cond(s):
            return (s["t"] < jnp.max(max_new)) & ~jnp.all(s["done"])

        def body(s):
            active = ~s["done"]
            tok = jnp.where(active, s["cur_tok"], cfg.pad_token_id)
            buf = s["buf"].at[:, s["t"]].set(tok)
            n_gen = s["n_gen"] + active.astype(jnp.int32)
            seen = s["seen"].at[jnp.arange(b), s["cur_tok"]].max(active)
            eos = s["eos"] | (active & (s["cur_tok"] == cfg.eos_token_id))
            # A sample stops at its own cap (batched requests mix budgets).
            done = s["done"] | eos | (n_gen >= max_new)

            # Next-token forward (skipped work when everyone is done: the
            # while_loop cond stops the whole program instead).
            tok_embed = self._embed(params, s["cur_tok"][:, None])  # [B,1,H]
            logits, caches = self._decode(
                params,
                tok_embed.astype(embeds.dtype),
                s["cur_len"][:, None],
                s["caches"],
                s["cur_len"],
                s["cur_len"] + 1,
            )
            rng, sub = jax.random.split(s["rng"])
            nxt = self._sample_next(
                sub, logits[:, 0], seen, temperature, top_p, do_sample, repetition_penalty
            ).astype(jnp.int32)
            return dict(
                caches=caches,
                cur_tok=nxt,
                cur_len=s["cur_len"] + active.astype(jnp.int32),
                t=s["t"] + 1,
                rng=rng,
                done=done,
                eos=eos,
                buf=buf,
                seen=seen,
                n_gen=n_gen,
            )

        state = jax.lax.while_loop(cond, body, state)
        return state["buf"], state["n_gen"], state["eos"]

    def generate(
        self,
        params,
        embeds,
        positions,
        lengths,
        prompt_ids,
        rng,
        max_new_tokens=256,
        temperature=0.0,
        top_p=1.0,
        do_sample=False,
        repetition_penalty=1.0,
    ) -> GenerateOutput:
        """Each generation param may be a python scalar (shared by the whole
        batch) or a length-B sequence (batched serving with mixed request
        configs — the capability the reference's one-request-at-a-time
        backend lacks, ``onnxrt_backend.py:298-356``)."""
        cap = np.minimum(np.asarray(max_new_tokens, np.int32), self.max_new_cap)
        # KV bucket: smallest configured size covering prompt + budget.
        # embeds may be right-padded past the live length, and the decode
        # loop indexes the cache at cur_len positions that started from
        # lengths — the bucket must cover the PADDED prompt span.
        need = int(embeds.shape[1]) + int(np.max(cap))
        kv_len = next((b for b in self.seq_buckets if b >= need), self.max_seq)
        buf, n_gen, eos = self._generate(
            params,
            embeds,
            positions,
            lengths,
            prompt_ids,
            rng,
            jnp.asarray(cap, jnp.int32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_p, jnp.float32),
            jnp.asarray(do_sample, bool),
            jnp.asarray(repetition_penalty, jnp.float32),
            kv_len=kv_len,
        )
        return GenerateOutput(tokens=buf, n_generated=n_gen, stopped_eos=eos)

    # -- streaming path (host loop, one compiled call per step) -------------

    def _prefill_impl(
        self, params, embeds, positions, lengths, prompt_ids, rng,
        temperature, top_p, do_sample, repetition_penalty,
        kv_len: int | None = None,  # static KV bucket; None = max_seq.
        # The streaming path decodes INTO this cache, so it must keep the
        # full max_seq; continuous admission only needs the prompt span
        # (decode happens in the pool's own full-size cache) and passes
        # the smallest bucket covering the prompt.
    ):
        caches, last_logits = self._prefill_core(params, embeds, positions, lengths, kv_len)
        seen = self._seen_from_prompt(prompt_ids, lengths)
        tok0 = self._sample_next(
            rng, last_logits, seen, temperature, top_p, do_sample, repetition_penalty
        ).astype(jnp.int32)
        return caches, tok0, seen

    def _step_impl(
        self, params, caches, cur_tok, cur_len, seen, rng,
        temperature, top_p, do_sample, repetition_penalty,
    ):
        b = cur_tok.shape[0]
        seen = seen.at[jnp.arange(b), cur_tok].max(True)
        tok_embed = self._embed(params, cur_tok[:, None]).astype(self.cache_dtype)
        logits, caches = self._decode(
            params, tok_embed, cur_len[:, None], caches, cur_len, cur_len + 1
        )
        nxt = self._sample_next(
            rng, logits[:, 0], seen, temperature, top_p, do_sample, repetition_penalty
        ).astype(jnp.int32)
        return caches, nxt, seen

    # -- continuous-batching pool programs (paged KV) ------------------------
    #
    # A fixed pool of B decode slots advances together in k-step blocks;
    # requests are admitted into free slots between blocks and retired on
    # EOS/cap without stopping the others. KV lives in a shared PAGED pool
    # ([pages, kv_heads, page_size, dh] per layer) addressed through
    # host-managed per-row block tables (``paged_kv.PagedKVPool``):
    # admission scatters the prompt's prefill cache into freshly granted
    # pages, decode writes one slot per step through the row's table, and
    # retire returns the pages — long and short generations share the pool
    # instead of every slot paying a contiguous max_seq region.

    def _decode_paged(self, params, embeds, positions, caches, block_tables, offset, kv_len):
        return self.model.apply(
            {"params": params},
            embeds,
            positions,
            caches,
            block_tables,
            offset,
            kv_len,
            method=VLMModel.decode_paged,
        )

    def init_pool(self, slots: int, pages: int | None = None, page_size: int = 16) -> dict:
        """Fresh all-slots-free paged pool state (host-callable, device
        arrays). ``pages`` defaults to the slot-era footprint (every slot
        could hold max_seq) — serving sizes it from HBM headroom instead
        (``paged_kv.resolve_pool_pages``)."""
        cfg = self.cfg
        if pages is None:
            pages = slots * (-(-self.max_seq // page_size)) + 1
        return dict(
            caches=init_paged_kv_cache(cfg, pages, page_size, self.cache_dtype),
            cur_tok=jnp.zeros((slots,), jnp.int32),
            cur_len=jnp.zeros((slots,), jnp.int32),
            seen=jnp.zeros((slots, cfg.decoder.vocab_size), bool),
            n_gen=jnp.zeros((slots,), jnp.int32),
            eos=jnp.zeros((slots,), bool),
            done=jnp.ones((slots,), bool),  # free slot == done
            max_new=jnp.zeros((slots,), jnp.int32),
            temperature=jnp.zeros((slots,), jnp.float32),
            top_p=jnp.ones((slots,), jnp.float32),
            do_sample=jnp.zeros((slots,), bool),
            rep=jnp.ones((slots,), jnp.float32),
        )

    def _admit_impl(
        self, pool, slot, caches1, tok0, seen1, length, bt_row,
        max_new, temperature, top_p, do_sample, rep,
    ):
        """Write one prefilled request into ``slot``: scatter its prompt
        KV (contiguous [1, kvh, Lb, dh] prefill scratch, ``Lb`` a page
        multiple) into the pages ``bt_row`` grants, page by page. Entries
        past the prompt's live pages point at the dump page 0, so the
        scatter needs no masking."""
        z = jnp.zeros((), jnp.int32)
        s = jnp.asarray(slot, jnp.int32)
        page = pool["caches"][0]["k"].shape[2]
        lb = caches1[0]["k"].shape[2]
        nseg = lb // page
        kvh = pool["caches"][0]["k"].shape[1]
        dh = pool["caches"][0]["k"].shape[3]
        dst = bt_row[:nseg]

        def scatter(pages_arr, pre):
            seg = pre[0].reshape(kvh, nseg, page, dh).transpose(1, 0, 2, 3)
            return pages_arr.at[dst].set(seg.astype(pages_arr.dtype))

        caches = jax.tree.map(scatter, pool["caches"], caches1)
        return dict(
            caches=caches,
            cur_tok=pool["cur_tok"].at[s].set(tok0[0]),
            cur_len=pool["cur_len"].at[s].set(length[0].astype(jnp.int32)),
            seen=jax.lax.dynamic_update_slice(pool["seen"], seen1, (s, z)),
            n_gen=pool["n_gen"].at[s].set(0),
            eos=pool["eos"].at[s].set(False),
            done=pool["done"].at[s].set(max_new <= 0),
            max_new=pool["max_new"].at[s].set(jnp.asarray(max_new, jnp.int32)),
            temperature=pool["temperature"].at[s].set(jnp.asarray(temperature, jnp.float32)),
            top_p=pool["top_p"].at[s].set(jnp.asarray(top_p, jnp.float32)),
            do_sample=pool["do_sample"].at[s].set(jnp.asarray(do_sample, bool)),
            rep=pool["rep"].at[s].set(jnp.asarray(rep, jnp.float32)),
        )

    def _export_row_impl(self, pool, slot, page_ids):
        """Gather one decode row's spillable state: its live KV pages (in
        block-table order) plus the per-slot decode scalars. ``page_ids``
        is padded to a power-of-2 length with the dump page 0 so compiled
        export shapes stay at log2(max_pages) — pad gathers read garbage
        that the resume scatter writes straight back to the dump page.
        The caller ships the result host-side with ONE ``jax.device_get``
        (the spill tier's per-victim transfer budget)."""
        s = jnp.asarray(slot, jnp.int32)
        return dict(
            pages=jax.tree.map(lambda c: c[page_ids], pool["caches"]),
            seen=jax.lax.dynamic_slice_in_dim(pool["seen"], s, 1, axis=0)[0],
            cur_tok=pool["cur_tok"][s],
            cur_len=pool["cur_len"][s],
            n_gen=pool["n_gen"][s],
        )

    def _resume_impl(
        self, pool, slot, pages, page_ids, seen1, cur_tok, cur_len, n_gen,
        max_new, temperature, top_p, do_sample, rep,
    ):
        """Re-install a spilled row into ``slot``: scatter the exported
        pages into the fresh grant ``page_ids`` (same padded layout as
        :meth:`_export_row_impl` — pad entries land on the dump page) and
        restore the decode scalars exactly. ``cur_tok`` is the sampled
        but not-yet-emitted next token, so a resumed greedy row continues
        token-identically and a resumed sampled row continues its own
        draw without splicing."""
        s = jnp.asarray(slot, jnp.int32)
        z = jnp.zeros((), jnp.int32)
        caches = jax.tree.map(
            lambda dst, src: dst.at[page_ids].set(src.astype(dst.dtype)),
            pool["caches"], pages,
        )
        return dict(
            caches=caches,
            cur_tok=pool["cur_tok"].at[s].set(jnp.asarray(cur_tok, jnp.int32)),
            cur_len=pool["cur_len"].at[s].set(jnp.asarray(cur_len, jnp.int32)),
            seen=jax.lax.dynamic_update_slice(pool["seen"], seen1[None], (s, z)),
            n_gen=pool["n_gen"].at[s].set(jnp.asarray(n_gen, jnp.int32)),
            eos=pool["eos"].at[s].set(False),
            done=pool["done"].at[s].set(False),
            max_new=pool["max_new"].at[s].set(jnp.asarray(max_new, jnp.int32)),
            temperature=pool["temperature"].at[s].set(jnp.asarray(temperature, jnp.float32)),
            top_p=pool["top_p"].at[s].set(jnp.asarray(top_p, jnp.float32)),
            do_sample=pool["do_sample"].at[s].set(jnp.asarray(do_sample, bool)),
            rep=pool["rep"].at[s].set(jnp.asarray(rep, jnp.float32)),
        )

    def _step_block_impl(self, params, pool, block_tables, rng, *, block: int):
        """Advance every live slot ``block`` tokens; emission semantics are
        identical to ``_generate_impl``'s while-loop body (per-slot budgets,
        EOS, repetition penalty), with free/finished slots masked out. Each
        step's K/V write and attention go through ``block_tables`` — the
        host scheduler guarantees every live row's pages cover
        ``cur_len + block`` before dispatching."""
        cfg = self.cfg
        b = pool["cur_tok"].shape[0]
        capacity = block_tables.shape[1] * pool["caches"][0]["k"].shape[2]

        def body(carry, _):
            pool, rng = carry
            active = ~pool["done"]
            tok = jnp.where(active, pool["cur_tok"], cfg.pad_token_id)
            n_gen = pool["n_gen"] + active.astype(jnp.int32)
            seen = pool["seen"].at[jnp.arange(b), pool["cur_tok"]].max(active)
            eos = pool["eos"] | (active & (pool["cur_tok"] == cfg.eos_token_id))
            done = pool["done"] | eos | (n_gen >= pool["max_new"])
            tok_embed = self._embed(params, pool["cur_tok"][:, None]).astype(self.cache_dtype)
            # Free slots hold cur_len=0 and done rows stop advancing, so the
            # clamp only guards a full slot writing past its block table.
            pos = jnp.minimum(pool["cur_len"], capacity - 1)
            logits, caches = self._decode_paged(
                params, tok_embed, pos[:, None], pool["caches"], block_tables, pos, pos + 1
            )
            rng, sub = jax.random.split(rng)
            nxt = self._sample_next(
                sub, logits[:, 0], seen,
                pool["temperature"], pool["top_p"], pool["do_sample"], pool["rep"],
            ).astype(jnp.int32)
            new_pool = dict(
                pool,
                caches=caches,
                cur_tok=nxt,
                cur_len=pool["cur_len"] + active.astype(jnp.int32),
                seen=seen,
                n_gen=n_gen,
                eos=eos,
                done=done,
            )
            return (new_pool, rng), tok

        (pool, rng), toks = jax.lax.scan(body, (pool, rng), None, length=block)
        return pool, rng, toks.T  # [B, block]

    # -- chunked prefill lane ------------------------------------------------
    #
    # A long prompt prefilled in one shot would hold the scheduler loop
    # (and every in-flight decode row) hostage for the whole forward; the
    # chunk programs let the engine interleave one prompt chunk per decode
    # block instead. Chunks write into a CONTIGUOUS per-request scratch
    # cache (offset semantics identical to one-shot prefill — causal
    # attention over earlier chunks already in the scratch), and the
    # finished scratch admits into pages exactly like a one-shot prefill.

    def new_prefill_cache(self, kv_len: int):
        """Contiguous batch-1 scratch cache for one chunked prefill."""
        return init_kv_cache(self.cfg, 1, kv_len, self.cache_dtype)

    def _prefill_chunk_impl(self, params, caches, embeds, positions, offset, valid_len):
        """One prompt chunk through the decoder: writes K/V at ``offset``
        into the donated scratch, returns this chunk's logits."""
        return self._decode(params, embeds, positions, caches, offset, valid_len)

    def _chunk_finish_impl(
        self, chunk_logits, idx, prompt_ids, lengths, rng,
        temperature, top_p, do_sample, repetition_penalty,
    ):
        """Sample token 0 from the final live chunk's logits at in-chunk
        index ``idx`` [B] — the tail of ``_prefill_impl`` split out for
        the chunk lane (``idx`` is traced so tail positions don't compile
        one program each)."""
        b = chunk_logits.shape[0]
        last = chunk_logits[jnp.arange(b), idx]  # [B, V]
        seen = self._seen_from_prompt(prompt_ids, lengths)
        tok0 = self._sample_next(
            rng, last, seen, temperature, top_p, do_sample, repetition_penalty
        ).astype(jnp.int32)
        return tok0, seen

    # -- prefix reuse + speculative decoding ---------------------------------

    def _seed_prefix_impl(self, caches, pool_caches, page_ids):
        """Prefix-cache hit: seed a chunked-prefill scratch cache with the
        already-computed prefix KV gathered straight from the pool pages —
        the scratch then looks exactly as if the covered prefix chunks had
        run, so only the uncovered suffix pays device prefill. ``page_ids``
        is the row's shared prefix pages padded to the scratch's page count
        with the dump page 0; pad segments land on slots the suffix chunks
        overwrite (decode writes K/V before attending) or the valid-length
        mask hides."""
        nseg = page_ids.shape[0]

        def seed(dst, src):
            seg = src[page_ids]  # [nseg, kvh, page, dh]
            flat = seg.transpose(1, 0, 2, 3).reshape(
                1, seg.shape[1], nseg * seg.shape[2], seg.shape[3]
            )
            return flat.astype(dst.dtype)

        return jax.tree.map(seed, caches, pool_caches)

    def _verify_impl(self, params, pool, block_tables, rng, draft, q_lens, *, width):
        """Speculative verify: ONE decode forward over a ``width``-token
        window per row (width = K+1), then an accept scan whose emission
        semantics mirror ``_step_block_impl`` exactly. ``draft[:, 0]`` is
        overwritten with the row's pending ``cur_tok`` (every turn starts
        from the sampled, not-yet-emitted token); ``draft[:, 1:]`` are the
        drafter's proposals. ``q_lens`` [B] in 1..width caps how many
        window slots each row may consume — a row with no draft runs
        q_len=1, which reduces to the plain one-token step. Greedy output
        is token-identical to non-speculative decode because window slot t
        attends over exactly the KV a sequential step at that position
        would see (the varq kernel's per-slot causal mask), and rejected
        slots' KV writes land above the row's final ``cur_len`` where the
        valid-length mask hides them until real tokens overwrite them."""
        cfg = self.cfg
        b = pool["cur_tok"].shape[0]
        capacity = block_tables.shape[1] * pool["caches"][0]["k"].shape[2]
        toks_in = jnp.asarray(draft, jnp.int32).at[:, 0].set(pool["cur_tok"])
        # Same spirit as _step_block's clamp: the host never dispatches a
        # live row whose window would cross its block table's capacity.
        pos0 = jnp.minimum(pool["cur_len"], capacity - width)
        positions = pos0[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
        embeds = self._embed(params, toks_in).astype(self.cache_dtype)
        logits, caches = self._decode_paged(
            params, embeds, positions, pool["caches"], block_tables, pos0, pos0 + 1
        )

        cur_tok, cur_len = pool["cur_tok"], pool["cur_len"]
        seen, n_gen = pool["seen"], pool["n_gen"]
        eos, done = pool["eos"], pool["done"]
        accepting = jnp.ones((b,), bool)
        toks_out = jnp.full((b, width), cfg.pad_token_id, jnp.int32)
        for t in range(width):
            step_active = ~done & accepting
            tok = jnp.where(step_active, cur_tok, cfg.pad_token_id)
            toks_out = toks_out.at[:, t].set(tok)
            n_gen = n_gen + step_active.astype(jnp.int32)
            seen = seen.at[jnp.arange(b), cur_tok].max(step_active)
            eos = eos | (step_active & (cur_tok == cfg.eos_token_id))
            done = done | eos | (n_gen >= pool["max_new"])
            rng, sub = jax.random.split(rng)
            nxt = self._sample_next(
                sub, logits[:, t], seen,
                pool["temperature"], pool["top_p"], pool["do_sample"], pool["rep"],
            ).astype(jnp.int32)
            cur_len = cur_len + step_active.astype(jnp.int32)
            if t + 1 < width:
                # Slot t+1 survives only if its drafted token IS what the
                # target just sampled — then its precomputed logits are
                # exactly the sequential step's logits.
                accepting = step_active & ~done & (t + 1 < q_lens) & (toks_in[:, t + 1] == nxt)
            else:
                accepting = jnp.zeros((b,), bool)
            cur_tok = jnp.where(step_active, nxt, cur_tok)

        new_pool = dict(
            pool,
            caches=caches,
            cur_tok=cur_tok,
            cur_len=cur_len,
            seen=seen,
            n_gen=n_gen,
            eos=eos,
            done=done,
        )
        return new_pool, rng, toks_out

    def stream(
        self,
        params,
        embeds,
        positions,
        lengths,
        prompt_ids,
        rng,
        max_new_tokens: int = 256,
        temperature: float = 0.0,
        top_p: float = 1.0,
        do_sample: bool = False,
        repetition_penalty: float = 1.0,
    ):
        """Yield generated token ids one at a time (batch size 1 semantics:
        yields ints). Stops after EOS or ``max_new_tokens``."""
        t_ = jnp.asarray(temperature, jnp.float32)
        p_ = jnp.asarray(top_p, jnp.float32)
        s_ = jnp.asarray(do_sample, bool)
        r_ = jnp.asarray(repetition_penalty, jnp.float32)
        rng, sub = jax.random.split(rng)
        caches, tok, seen = self._prefill(
            params, embeds, positions, lengths, prompt_ids, sub, t_, p_, s_, r_
        )
        cur_len = lengths.astype(jnp.int32)
        cap = min(int(max_new_tokens), self.max_new_cap)
        for _ in range(cap):
            tok_host = int(tok[0])
            yield tok_host
            if tok_host == self.cfg.eos_token_id:
                return
            rng, sub = jax.random.split(rng)
            caches, tok, seen = self._step(
                params, caches, tok, cur_len, seen, sub, t_, p_, s_, r_
            )
            cur_len = cur_len + 1
