"""Continuous batching scheduler for VLM generation.

The coalescing batcher (``manager._GenBatcher``) groups only requests that
arrive within one small latency window AND share a prompt bucket; once a
fused generation program launches, everything behind it queues until the
longest row finishes. This scheduler removes that cliff:

- a fixed pool of ``slots`` decode rows advances together in ``block``-step
  compiled programs (``Generator._step_block_impl``);
- new requests are ADMITTED into free slots between blocks — a burst of
  same-shaped arrivals prefills as ONE batched forward (``ADMIT_BUCKETS``
  groups, so admission cost under load is ~1 prefill per bucket, not one
  per request) — and start decoding immediately next block, regardless of
  what the other slots are doing;
- rows retire on EOS / per-request cap without stopping the others.

This is the slot half of TPU continuous batching (the "ragged batch" of
paged attention with contiguous per-slot KV regions instead of pages).
Trade-off vs the fused ``lax.while_loop`` path: one host dispatch per
``block`` tokens instead of one per generation — pick ``block`` to
amortize dispatch overhead, and prefer the coalescing batcher when traffic
arrives in same-shaped bursts.

The reference serves one request at a time per process
(``packages/lumen-vlm/src/lumen_vlm/backends/onnxrt_backend.py:298-356``);
neither strategy has an upstream equivalent.
"""

from __future__ import annotations

import logging
import os
import queue as queue_mod
import threading
import weakref
from concurrent.futures import Future
from dataclasses import dataclass, field

import jax
import numpy as np

from ...utils.metrics import metrics
from .manager import _PendingGen

logger = logging.getLogger(__name__)

_STREAM_END = object()


def _fail(req: "_Request", err: BaseException) -> None:
    """Retire a request with an error: resolve its future (if a result
    hasn't already won) and unblock any stream consumer. Every retirement
    path MUST go through here or :func:`_retire` — a missed
    ``_STREAM_END`` strands the consumer on ``stream_q.get()`` forever."""
    if not req.future.done():
        req.future.set_exception(err)
    if req.stream_q is not None:
        req.stream_q.put(_STREAM_END)


def _retire(req: "_Request", tokens: list, eos: bool) -> None:
    """Retire a request successfully with whatever tokens it produced."""
    if not req.future.done():
        req.future.set_result((np.asarray(tokens, np.int64), len(tokens), eos))
    if req.stream_q is not None:
        req.stream_q.put(_STREAM_END)


@dataclass
class _Request(_PendingGen):
    """One continuous-batching request: the batcher's fields plus a
    per-request rng, an optional stream queue, and a cancel flag (set when
    a stream consumer goes away so the slot stops decoding)."""

    rng: object = None
    future: Future = field(default_factory=Future)
    stream_q: "queue_mod.SimpleQueue | None" = None
    cancelled: bool = False


@dataclass
class _Slot:
    request: _Request
    tokens: list = field(default_factory=list)
    delivered: int = 0


class ContinuousScheduler:
    """Slot-pool decode loop on a dedicated thread.

    ``submit`` returns a Future resolving to ``(tokens_np, n_gen, eos)`` —
    the same contract as the coalescing batcher — and optionally streams
    token ids into ``stream_q`` as blocks complete (``_STREAM_END``
    sentinel on retirement, exposed via :meth:`submit_stream`).
    """

    def __init__(
        self, generator, params, slots: int = 8, block: int = 8,
        name: str = "vlm",
    ):
        self.gen = generator
        self.params = params
        # Gauge provider id: per-model-name, matching the batcher's
        # ``batcher:{name}`` semantics — distinct models coexist; a
        # same-name replacement takes over the slot (last-writer-wins
        # register, ownership-guarded unregister).
        self.name = name
        self.n_slots = slots
        self.block = block
        self.pool = generator.init_pool(slots)
        # Decode sampling draws from one scheduler-level stream (sample()
        # takes a single key per batched step); entropy-seeded so sampled
        # continuations differ across processes. An admission group's
        # prefill sample is seeded from its FIRST request's key (one key
        # per batched sample call — the same group-granular semantics as
        # the coalescing batcher, which fuses mixed requests into one
        # generate under one key). Greedy requests are unaffected; a
        # sampled request's draw depends on its admission group.
        self._rng = jax.random.PRNGKey(int.from_bytes(os.urandom(4), "big"))
        self._slots: dict[int, _Slot] = {}  # slot idx -> live request
        self._pending: list[_Request] = []
        self._cond = threading.Condition()
        self._closed = False
        self.blocks_run = 0  # observability
        self.admitted = 0
        self._thread = threading.Thread(target=self._loop, name="vlm-continuous", daemon=True)
        self._thread.start()
        ref = weakref.ref(self)  # registry must not pin the pool/params

        def _gauges() -> dict:
            s = ref()
            if s is None:
                return {}
            return {
                "blocks_run": s.blocks_run,
                "admitted": s.admitted,
                "slots_total": s.n_slots,
                "slots_live": len(s._slots),
                "queue_depth": len(s._pending),
            }

        self._gauge_fn = _gauges
        metrics.register_gauges(f"vlm-continuous:{self.name}", _gauges)

    # -- public API --------------------------------------------------------

    def submit(self, req: _Request) -> Future:
        with self._cond:
            if self._closed:
                raise RuntimeError("continuous scheduler is closed")
            self._pending.append(req)
            self._cond.notify()
        return req.future

    def submit_stream(self, req: _Request):
        """Submit and iterate generated token ids as they decode."""
        req.stream_q = queue_mod.SimpleQueue()
        self.submit(req)

        def tokens():
            try:
                while True:
                    item = req.stream_q.get()
                    if item is _STREAM_END:
                        err = req.future.exception()
                        if err is not None:
                            raise err
                        return
                    yield item
            finally:
                # Consumer gone (stop sequence hit, client disconnect, or
                # normal end): tell the scheduler to free the slot instead
                # of decoding to the cap into an unread queue.
                req.cancelled = True

        return tokens()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=10)
        with self._cond:
            pending, self._pending = self._pending, []
            live, self._slots = list(self._slots.values()), {}
        err = RuntimeError("continuous scheduler closed")
        for req in pending + [s.request for s in live]:
            _fail(req, err)
        if fn := getattr(self, "_gauge_fn", None):
            metrics.unregister_gauges(f"vlm-continuous:{self.name}", fn)

    # -- scheduler loop ----------------------------------------------------

    def _take_work(self) -> list[_Request]:
        """Block until there is something to do; drain admissible requests."""
        with self._cond:
            while not self._closed and not self._pending and not self._slots:
                self._cond.wait()
            if self._closed:
                return []
            free = self.n_slots - len(self._slots)
            take, self._pending = self._pending[:free], self._pending[free:]
            return take

    def _loop(self) -> None:
        try:
            while True:
                admit = self._take_work()
                with self._cond:
                    closed = self._closed
                if closed:
                    # close() raced us after _take_work popped these off
                    # _pending — its sweep can no longer see them, so fail
                    # them here instead of stranding their callers.
                    err = RuntimeError("continuous scheduler closed")
                    for req in admit:
                        _fail(req, err)
                    return
                live = []
                for req in admit:
                    if req.cancelled:
                        # Stream consumer disconnected while queued: retire
                        # without wasting a prefill dispatch on a dead row.
                        _retire(req, [], eos=False)
                    else:
                        live.append(req)
                groups = self._admit_groups(live)
                for gpos, group in enumerate(groups):
                    try:
                        self._admit_group(group)
                    except Exception as e:  # noqa: BLE001 - fail ONE group
                        for req in group:
                            _fail(req, e)
                        if self._pool_invalid():
                            # The failure hit the donation-based _admit call
                            # after self.pool's buffers were consumed: the
                            # other slots' KV state is gone, so "fail one
                            # group" is impossible — escalate to the
                            # fail-everything handler below. That handler
                            # sweeps only _pending + _slots and this batch
                            # is already off _pending, so fail its
                            # unprocessed tail here first.
                            for later_group in groups[gpos + 1 :]:
                                for req in later_group:
                                    _fail(req, e)
                            raise RuntimeError(
                                "slot pool invalidated by failed admission"
                            ) from e
                if self._slots:
                    self._run_block()
        except BaseException as e:  # noqa: BLE001 - never strand callers
            logger.exception("continuous scheduler loop died")
            with self._cond:
                self._closed = True
                pending, self._pending = self._pending, []
                live, self._slots = list(self._slots.values()), {}
            for req in pending + [s.request for s in live]:
                _fail(req, RuntimeError(f"continuous scheduler died: {e!r}"))

    def _pool_invalid(self) -> bool:
        """True when the slot pool's buffers were deleted by a donation
        whose computation then failed (see ``Generator._admit``'s
        ``donate_argnames``)."""
        return any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(self.pool)
        )

    def _free_slot(self) -> int:
        for i in range(self.n_slots):
            if i not in self._slots:
                return i
        raise RuntimeError("no free slot (scheduler bug: admission overran pool)")

    #: Admission batch buckets: a burst of same-shaped arrivals prefills
    #: as ONE batched forward instead of K sequential batch-1 forwards
    #: (round-4 verdict: batch-1 admission serializes full-prompt prefills
    #: between decode blocks and starves the slot pool under load).
    #: Power-of-2 buckets bound the number of compiled prefill shapes.
    ADMIT_BUCKETS = (1, 2, 4, 8)

    def _admit_groups(self, reqs: list[_Request]) -> list[list[_Request]]:
        """Split admissible requests into batched-prefill groups: same
        (embeds len, prompt len) bucket, chunked to ADMIT_BUCKETS sizes."""
        by_shape: dict[tuple, list[_Request]] = {}
        for req in reqs:
            by_shape.setdefault(req.key, []).append(req)
        groups: list[list[_Request]] = []
        cap = max(self.ADMIT_BUCKETS)
        for group in by_shape.values():
            while group:
                # Largest bucket <= len(group), so a burst of 8 runs as one
                # prefill and a straggler of 3 runs as 2 + 1, not 8-padded.
                k = max(b for b in self.ADMIT_BUCKETS if b <= min(len(group), cap))
                groups.append(group[:k])
                group = group[k:]
        return groups

    def _admit_group(self, reqs: list[_Request]) -> None:
        """One batched prefill for the group, then per-row slot admission.
        The group shares one sampling key (same semantics as the
        coalescing batcher, which fuses mixed requests into one generate
        with one key); per-request generation params stay per-row."""
        import jax.numpy as jnp

        k = len(reqs)
        sub = jax.random.fold_in(reqs[0].rng, 0)
        if k == 1:
            req = reqs[0]
            embeds, positions = req.embeds, req.positions
            lengths, prompt_ids = req.length, req.prompt_ids
        else:
            embeds = jnp.concatenate([r.embeds for r in reqs], axis=0)
            positions = jnp.concatenate([r.positions for r in reqs], axis=0)
            lengths = jnp.concatenate([r.length for r in reqs], axis=0)
            prompt_ids = jnp.concatenate([r.prompt_ids for r in reqs], axis=0)
        # Right-size the admission prefill cache to the PROMPT span only:
        # decode happens in the pool's full-size per-slot cache, so the
        # prefill buffer never needs max_seq. Without this, a burst of 8
        # would transiently allocate a second pool-sized KV buffer
        # (8 x max_seq) — an OOM spike on exactly the load batched
        # admission exists for.
        kv_len = next(
            (b for b in self.gen.seq_buckets if b >= embeds.shape[1]),
            self.gen.max_seq,
        )
        caches, tok0, seen = self.gen._prefill(
            self.params, embeds, positions, lengths, prompt_ids, sub,
            jnp.asarray([r.temperature for r in reqs], jnp.float32),
            jnp.asarray([r.top_p for r in reqs], jnp.float32),
            jnp.asarray([r.do_sample for r in reqs]),
            jnp.asarray([r.repetition_penalty for r in reqs], jnp.float32),
            kv_len=kv_len,
        )
        group_slots: list[int] = []
        try:
            for i, req in enumerate(reqs):
                slot = self._free_slot()
                row = slice(i, i + 1)
                caches1 = jax.tree.map(lambda c, r=row: c[r], caches)
                self.pool = self.gen._admit(
                    self.pool, slot, caches1, tok0[row], seen[row], lengths[row],
                    req.max_new, req.temperature, req.top_p, req.do_sample,
                    req.repetition_penalty,
                )
                self._slots[slot] = _Slot(request=req)
                group_slots.append(slot)
                self.admitted += 1
        except Exception:
            # Mid-group failure with earlier rows already admitted: the
            # caller fails EVERY request in the group, so rows already in
            # _slots must be evicted too — otherwise they keep decoding to
            # max_new for futures that already errored, burning slots. If
            # the pool was invalidated (donation consumed), skip the
            # device write; the caller escalates to fail-everything.
            if group_slots and not self._pool_invalid():
                import jax.numpy as jnp

                idx = jnp.asarray(group_slots, jnp.int32)
                self.pool = dict(self.pool, done=self.pool["done"].at[idx].set(True))
            with self._cond:
                for slot in group_slots:
                    self._slots.pop(slot, None)
            raise

    def _run_block(self) -> None:
        cancelled = [
            i for i, slot in self._slots.items() if slot.request.cancelled
        ]
        if cancelled:
            import jax.numpy as jnp

            idx = jnp.asarray(cancelled, jnp.int32)
            self.pool = dict(self.pool, done=self.pool["done"].at[idx].set(True))
            for i in cancelled:
                slot = self._slots.pop(i)
                _retire(slot.request, slot.tokens, eos=False)
            if not self._slots:
                return
        self.pool, self._rng, toks = self.gen._step_block(
            self.params, self.pool, self._rng, block=self.block
        )
        self.blocks_run += 1
        # One fused device->host transfer for everything the bookkeeping
        # below needs (four separate np.asarray calls = four round trips
        # on the per-block hot path).
        toks_np, n_gen, done, eos = jax.device_get(
            (toks, self.pool["n_gen"], self.pool["done"], self.pool["eos"])
        )
        for idx in list(self._slots):
            slot = self._slots[idx]
            new = int(n_gen[idx]) - len(slot.tokens)
            if new > 0:
                slot.tokens.extend(int(t) for t in toks_np[idx, :new])
                if slot.request.stream_q is not None:
                    for t in slot.tokens[slot.delivered :]:
                        slot.request.stream_q.put(t)
                    slot.delivered = len(slot.tokens)
            if done[idx]:
                with self._cond:
                    del self._slots[idx]
                _retire(slot.request, slot.tokens, bool(eos[idx]))
