"""Continuous batching over a paged KV pool for VLM generation.

The coalescing batcher (``manager._GenBatcher``) groups only requests that
arrive within one small latency window AND share a prompt bucket; once a
fused generation program launches, everything behind it queues until the
longest row finishes. The slot-era version of this scheduler removed that
cliff but still gave every decode row a contiguous ``max_seq`` KV region —
the pool paid worst-case memory per slot and admission needed a same-shape
bucket. This engine is the paged rebuild:

- KV lives in a shared POOL OF PAGES (``paged_kv.PagedKVPool`` host
  accounting + ``Generator.init_pool`` device arrays); each row owns a
  block table that grows page by page as it decodes and returns its pages
  at retire, so long and short generations share memory and a request
  admits the moment a slot and its prompt's pages are free;
- decode attention is RAGGED PAGED ATTENTION (``ops.attention``): the
  Pallas kernel on TPU, the exact XLA gather reference on CPU — tier-1
  runs the same code path end to end;
- a burst of same-shaped arrivals still prefills as ONE batched forward
  (``ADMIT_BUCKETS``), and long prompts go through a CHUNKED PREFILL LANE
  — one prompt chunk per scheduler turn — so a 1k-token prompt never
  stalls in-flight decode steps;
- rows retire on EOS / per-request cap without stopping the others; if
  the pool runs dry mid-decode the newest row is PREEMPTED: its live KV
  pages are exported to a host SPILL TIER (one fused ``jax.device_get``
  into an shm-arena lease, ``utils/shm_arena.py``) together with the
  row's exact decode state, and re-admission scatters the pages back
  into a fresh grant — no re-prefill, greedy resume token-identical,
  sampled mid-stream rows resume their own draw instead of failing. The
  spill ledger is bounded (``LUMEN_VLM_SPILL_BYTES`` /
  ``LUMEN_VLM_SPILL_MAX``); any spill/resume failure — arena exhaustion,
  corrupt lease, export fault (``kv_spill``/``kv_resume`` fault points) —
  degrades to the pre-spill ladder: requeue-and-redo for rows whose
  restart is invisible (greedy, or nothing streamed yet), a typed
  retryable :class:`~lumen_tpu.utils.deadline.PreemptionShed` carrying
  the engine's drain estimate for sampled mid-stream rows. Lease and
  page accounting balance at drain, and every spill/resume lands a
  ``vlm_spill``/``vlm_resume`` flight-recorder event.

Per-step occupancy (active rows / pool pages) is published as gauges and
each decode block lands a ``batch.device`` span on every active request's
trace. The reference serves one request at a time per process
(``packages/lumen-vlm/src/lumen_vlm/backends/onnxrt_backend.py:298-356``);
neither strategy has an upstream equivalent.
"""

from __future__ import annotations

import logging
import os
import queue as queue_mod
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ...testing.faults import KV_RESUME, KV_SPILL, faults
from ...utils.deadline import PreemptionShed
from ...utils import telemetry
from ...utils.metrics import metrics
from ...utils.shm_arena import ShmArena
from ...utils.telemetry import record_event
from ...utils.trace import current_trace
from . import migration
from .manager import _PendingGen
from .paged_kv import DEFAULT_PAGE_SIZE, PagedKVPool, PoolExhausted, page_bytes
from .prefix_cache import PrefixCache, chunk_keys, prefix_cache_enabled

logger = logging.getLogger(__name__)

_STREAM_END = object()


def _fail(req: "_Request", err: BaseException) -> None:
    """Retire a request with an error: resolve its future (if a result
    hasn't already won) and unblock any stream consumer. Every retirement
    path MUST go through here or :func:`_retire` — a missed
    ``_STREAM_END`` strands the consumer on ``stream_q.get()`` forever."""
    if not req.future.done():
        req.future.set_exception(err)
    if req.stream_q is not None:
        req.stream_q.put(_STREAM_END)


def _retire(req: "_Request", tokens: list, eos: bool) -> None:
    """Retire a request successfully with whatever tokens it produced."""
    if not req.future.done():
        req.future.set_result((np.asarray(tokens, np.int64), len(tokens), eos))
    if req.stream_q is not None:
        req.stream_q.put(_STREAM_END)


@dataclass
class _Request(_PendingGen):
    """One continuous-batching request: the batcher's fields plus a
    per-request rng, an optional stream queue, a cancel flag (set when
    a stream consumer goes away so the slot stops decoding), and the
    submitter's trace (decode blocks land ``batch.device`` spans on it)."""

    rng: object = None
    future: Future = field(default_factory=Future)
    stream_q: "queue_mod.SimpleQueue | None" = None
    cancelled: bool = False
    trace: object = None
    #: carried across preemption so a resumed stream never re-delivers.
    delivered: int = 0
    #: parked :class:`_SpillRecord` while the request waits, preempted,
    #: at the queue head for pages to free — None on the normal path.
    spill: "object | None" = None
    #: [L] int64 content identity of the merged prompt (token ids, vision
    #: positions substituted by image-digest ints) — the prefix cache's
    #: key material. None when prefix caching is off.
    prefix_content: "object | None" = None
    #: fraction of the prompt served from shared prefix pages, set at
    #: admission when the cache is enabled (None = cache off) — surfaced
    #: in the final stream chunk metadata.
    prefix_hit: "float | None" = None
    #: per-request speculative decoding tally (stream metadata).
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: decode-lane peer address for disaggregated serving: the row is
    #: exported right after prefill and its decode migrates there.
    #: Cleared after one attempt — any failure decodes locally.
    migrate_to: "str | None" = None
    #: decode-host side of a migration: ``(manifest_keys, n_shared)``
    #: pending prefix-cache resolution at resume. None otherwise.
    migrate_in: "tuple | None" = None


@dataclass
class _Slot:
    request: _Request
    prompt_len: int = 0  # live prompt tokens (host mirror of pool cur_len base)
    seq: int = 0  # admission order; preemption evicts the newest first
    tokens: list = field(default_factory=list)
    #: host mirrors for the n-gram drafter (spec decoding only): the live
    #: TEXT prompt ids and the pending sampled-but-not-emitted token.
    text_toks: "list | None" = None
    pending_tok: "int | None" = None


@dataclass
class _PrefillJob:
    """One long prompt moving through the chunked prefill lane."""

    request: _Request
    caches: object = None  # contiguous [1, kvh, Lb, dh] scratch per layer
    scratch_len: int = 0  # Lb (page- and chunk-aligned)
    offset: int = 0  # prompt tokens already processed
    length: int = 0  # live prompt tokens (host int)
    last_logits: object = None  # logits of the most recent chunk
    last_off: int = 0  # offset of that chunk
    #: shared prefix pages seeded into the scratch; the JOB holds one
    #: reference on each until admission or cancellation.
    shared: list = field(default_factory=list)


@dataclass
class _SpillRecord:
    """Everything needed to resume a preempted row WITHOUT re-prefill.

    The page payload (per-layer K/V page stacks, padded to a power-of-2
    page count with dump-page garbage, plus the row's ``seen`` vocab
    mask) lives OUT of line: as a self-describing
    :func:`~lumen_tpu.models.vlm.migration.pack_payload` blob in an
    shm-arena lease when the arena had budget, else as plain host
    arrays (the "pickled spill" twin — same bytes, just not recyclable
    segments). The blob carries each leaf's shape/dtype in-band (the
    same frame train ``fed_kv_put`` ships to a decode peer), so only
    ``treedef`` stays out of band; ``crc`` (crc32 over the blob)
    catches a torn or recycled-out-from-under-us lease at resume time,
    turning silent token corruption into the degradation ladder.
    The decode scalars are exact state, not hints: ``cur_tok`` is the
    sampled-but-not-yet-written next token (it exists nowhere on the
    host side), and ``rng`` snapshots the request's PRNG key so the
    record is self-contained for cross-host migration.
    """

    n_pages: int            # live pages exported — the resume grant size
    n_pad: int              # power-of-2 padded page count in the payload
    nbytes: int             # payload bytes — ledger budget accounting
    treedef: object         # payload pytree structure
    crc: int                # crc32 over the lease's blob (0 = host arrays)
    cur_tok: int            # pending next token (sampled, not yet emitted)
    cur_len: int            # prompt + generated KV length
    n_gen: int              # tokens generated so far (== len(tokens))
    rng: object             # host snapshot of the request's PRNG key
    prompt_len: int = 0
    tokens: list = field(default_factory=list)
    lease: object = None    # ArenaSlot when the shm path won
    arrays: "list | None" = None  # host-array fallback payload
    #: shared prefix pages the row held at spill time. NOT exported —
    #: their contents stay resident in the pool; the RECORD holds one
    #: reference on each so eviction cannot free them while parked, and
    #: resume re-attaches them ahead of the fresh grant.
    shared_pages: list = field(default_factory=list)


class ContinuousScheduler:
    """Paged continuous-batching decode loop on a dedicated thread.

    ``submit`` returns a Future resolving to ``(tokens_np, n_gen, eos)`` —
    the same contract as the coalescing batcher — and optionally streams
    token ids into ``stream_q`` as blocks complete (``_STREAM_END``
    sentinel on retirement, exposed via :meth:`submit_stream`).
    """

    def __init__(
        self, generator, params, slots: int = 8, block: int = 8,
        name: str = "vlm", page_size: int | None = None,
        pages: int | None = None, prefill_chunk: int | None = None,
        mesh=None,
    ):
        from ...utils.env import env_int

        self.gen = generator
        self.params = params
        #: replica mesh slice (fleet mode): the page pool is pinned to it
        #: and submitted request tensors are transferred over (prepare
        #: programs run on replica 0's devices). None = legacy placement.
        self.mesh = mesh
        # Gauge provider id: per-model-name, matching the batcher's
        # ``batcher:{name}`` semantics — distinct models coexist; a
        # same-name replacement takes over the slot (last-writer-wins
        # register, ownership-guarded unregister).
        self.name = name
        # Same ``device:{name}`` duty meter the MicroBatcher declares —
        # the autopilot's scale loop (and the capacity gossip's duty
        # report) read engine fleets through the identical sensor name.
        telemetry.set_capacity(f"device:{self.name}", 1.0, union=True)
        self.n_slots = slots
        self.block = block
        self.page_size = page_size or env_int(
            "LUMEN_VLM_PAGE_SIZE", DEFAULT_PAGE_SIZE, minimum=8, maximum=256
        )
        max_pages = -(-generator.max_seq // self.page_size)
        if pages is None:
            pages = slots * max_pages + 1  # slot-era footprint fallback
        self.kv = PagedKVPool(pages, self.page_size, slots, max_pages)
        self.pool = generator.init_pool(slots, pages=pages, page_size=self.page_size)
        if mesh is not None:
            from ...parallel.sharding import replicate

            self.pool = replicate(self.pool, mesh)
        # Prompts longer than this (padded length) prefill through the
        # chunk lane, one chunk per scheduler turn; the chunk is rounded
        # to a page multiple so scratch caches scatter cleanly into pages.
        chunk = prefill_chunk or env_int(
            "LUMEN_VLM_PREFILL_CHUNK", 256, minimum=32, maximum=4096
        )
        self.prefill_chunk = -(-chunk // self.page_size) * self.page_size
        from ...utils.env import env_float

        # Decode pacing floor: minimum wall time per decode STEP (a block
        # sleeps out `block * floor - elapsed`). Off by default (0.0 = no
        # branch taken on the hot path); the disagg bench phase arms it so
        # decode throughput on a shared CPU box measures topology (slots x
        # hosts) instead of this box's core count — sleeps scale across
        # host processes the way real chips do, spins don't.
        self._step_floor_s = env_float(
            "LUMEN_GEN_STEP_FLOOR_MS", 0.0, minimum=0.0, maximum=1000.0
        ) / 1e3
        # Decode sampling draws from one scheduler-level stream (sample()
        # takes a single key per batched step); entropy-seeded so sampled
        # continuations differ across processes. An admission group's
        # prefill sample is seeded from its FIRST request's key (one key
        # per batched sample call — the same group-granular semantics as
        # the coalescing batcher, which fuses mixed requests into one
        # generate under one key). Greedy requests are unaffected; a
        # sampled request's draw depends on its admission group.
        self._rng = jax.random.PRNGKey(int.from_bytes(os.urandom(4), "big"))
        self._slots: dict[int, _Slot] = {}  # slot idx -> live request
        self._pending: list[_Request] = []
        self._prefill_jobs: deque[_PrefillJob] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._admit_seq = 0
        self.blocks_run = 0  # observability
        self.admitted = 0
        self.preemptions = 0
        self.chunks_run = 0
        # -- KV spill tier: preemption victims park their pages on the
        # host instead of re-prefilling. Bounded two ways: total payload
        # bytes (also the shm arena's budget, so the lease path and the
        # host-array fallback draw on ONE pool) and entry count.
        # LUMEN_VLM_SPILL_BYTES=0 disables the tier — preemption then
        # degrades exactly as the pre-spill engine did, minus the bare
        # RuntimeError (sampled victims get the typed retryable shed).
        self._spill_budget = env_int("LUMEN_VLM_SPILL_BYTES", 256 << 20, minimum=0)
        self._spill_max = env_int("LUMEN_VLM_SPILL_MAX", 32, minimum=0)
        self._spill_arena: ShmArena | None = None  # created on first spill
        self._spill_ledger: dict[int, _SpillRecord] = {}  # id(req) -> record
        self._spill_bytes_live = 0
        self.spills = 0
        self.spill_resumes = 0
        self.spill_fallbacks = 0  # arena denied -> host-array payload
        self.spill_denied = 0     # ledger full/disabled -> no spill attempt
        self.preempt_redone = 0   # victim restarted from the prompt
        self.preempt_failed = 0   # victim shed with the typed retryable error
        # -- disaggregated serving: the migration dispatcher hook. When a
        # federation with role-tagged peers is live, the serving layer
        # installs ``migrator(scheduler, req, rec, manifest, target)``
        # here; requests tagged ``migrate_to`` are then exported right
        # after prefill (the SAME record format as the spill tier) and
        # their decode runs on the target peer. None (the default, and
        # always when LUMEN_FED_ROLE is unset) never exports — the
        # unconfigured loop is byte-identical to the pre-disagg engine.
        self.migrator = None
        self.migrated_out = 0        # rows handed to the dispatcher
        self.migrate_out_failed = 0  # wire failed -> resumed/shed locally
        self.migrated_in = 0         # peer rows admitted with zero re-prefill
        self.migrate_in_rejected = 0 # bad commit (crc/manifest/pool) refused
        # -- copy-on-write prefix KV reuse: content-addressed cache of
        # page-aligned prompt prefixes. Off (None) unless
        # LUMEN_VLM_PREFIX_BYTES grants a budget — the unconfigured
        # engine allocates no cache and admission is byte-identical.
        self.prefix: PrefixCache | None = None
        if prefix_cache_enabled():
            dtype_bytes = jnp.dtype(generator.cache_dtype).itemsize
            self.prefix = PrefixCache(
                self.kv, page_bytes(generator.cfg, self.page_size, dtype_bytes)
            )
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_pages = 0  # shared pages attached across all hits
        # -- speculative decoding: host n-gram drafter + one-step verify.
        # LUMEN_VLM_SPEC_K=0 (default) builds no drafter and never touches
        # the verify program; acceptance below LUMEN_VLM_SPEC_MIN_RATE
        # after warmup disables drafting for the engine's lifetime (the
        # auto/off gate, like the q8 route).
        from ...utils.env import env_float

        self.spec_k = env_int("LUMEN_VLM_SPEC_K", 0, minimum=0, maximum=15)
        self.spec_ngram = env_int("LUMEN_VLM_SPEC_NGRAM", 3, minimum=1, maximum=8)
        self.spec_min_rate = env_float(
            "LUMEN_VLM_SPEC_MIN_RATE", 0.2, minimum=0.0, maximum=1.0
        )
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_turns = 0
        self.spec_disabled = False
        # Per-token decode pace (EWMA over block wall time) feeds the
        # retry-after hint on PreemptionShed — the same drain-estimate
        # idea as the batcher's queue-full hint.
        self._block_s_ewma = 0.0
        self._preempt_log_t = 0.0  # 1/s warning throttle (shed-log cadence)
        # Decode-step occupancy accumulators: active-row fill per block
        # (every step in a block shares the block-start row count).
        self._occ_rows = 0
        self._occ_blocks = 0
        self._thread = threading.Thread(target=self._loop, name="vlm-continuous", daemon=True)
        self._thread.start()
        ref = weakref.ref(self)  # registry must not pin the pool/params

        def _gauges() -> dict:
            s = ref()
            if s is None:
                return {}
            stats = s.kv.stats()
            out = {
                "blocks_run": s.blocks_run,
                "admitted": s.admitted,
                "preempted": s.preemptions,
                "prefill_chunks_run": s.chunks_run,
                "prefill_lane_depth": len(s._prefill_jobs),
                "slots_total": s.n_slots,
                "slots_live": len(s._slots),
                "queue_depth": len(s._pending),
                "page_size": stats.page_size,
                "pages_total": stats.pages_total,
                "pages_free": stats.pages_free,
                "pages_live": stats.pages_live,
                "pages_allocated_total": stats.allocated_total,
                "pages_freed_total": stats.freed_total,
                "pages_fill_pct": round(
                    100.0 * stats.pages_live / max(stats.pages_total - 1, 1), 1
                ),
                # Spill-tier occupancy + outcome split: resumed vs redone
                # vs failed must add up to preempted once in-flight spills
                # drain, and entries/bytes return to 0 — assertable
                # invariants, same discipline as the page accounting.
                "spill_entries": len(s._spill_ledger),
                "spill_bytes": s._spill_bytes_live,
                "spill_bytes_budget": s._spill_budget,
                "spill_max_entries": s._spill_max,
                "spilled": s.spills,
                "spill_resumed": s.spill_resumes,
                "spill_fallbacks": s.spill_fallbacks,
                "spill_denied": s.spill_denied,
                "preempt_redone": s.preempt_redone,
                "preempt_failed": s.preempt_failed,
                "migrated_out": s.migrated_out,
                "migrate_out_failed": s.migrate_out_failed,
                "migrated_in": s.migrated_in,
                "migrate_in_rejected": s.migrate_in_rejected,
            }
            if s._spill_arena is not None:
                arena = s._spill_arena.stats()
                out["spill_arena_segments"] = arena["segments"]
                out["spill_arena_bytes"] = arena["bytes"]
                out["spill_arena_live"] = arena["live"]
                out["spill_arena_denied"] = arena["denied"]
            if s.prefix is not None:
                out.update(s.prefix.gauges())
                out["prefix_hits"] = s.prefix_hits
                out["prefix_misses"] = s.prefix_misses
                out["prefix_hit_pages"] = s.prefix_hit_pages
                out["pages_shared"] = stats.pages_shared
            if s.spec_k > 0:
                out["spec_k"] = s.spec_k
                out["spec_turns"] = s.spec_turns
                out["spec_proposed"] = s.spec_proposed
                out["spec_accepted"] = s.spec_accepted
                out["spec_accept_rate"] = round(
                    s.spec_accepted / max(s.spec_proposed, 1), 3
                )
                out["spec_disabled"] = int(s.spec_disabled)
            if s._occ_blocks:
                out["occupancy_pct_mean"] = round(
                    100.0 * s._occ_rows / (s._occ_blocks * s.n_slots), 1
                )
            return out

        self._gauge_fn = _gauges
        metrics.register_gauges(f"vlm-continuous:{self.name}", _gauges)

    # -- public API --------------------------------------------------------

    def submit(self, req: _Request) -> Future:
        with self._cond:
            if self._closed:
                raise RuntimeError("continuous scheduler is closed")
        # Feasibility is checked at the door: a request whose prompt +
        # budget can NEVER fit the pool (even alone) must fail loudly now,
        # not deadlock the admission queue later.
        need = int(np.asarray(req.length)[0]) + int(req.max_new) + 1
        if not self.kv.fits(need):
            raise ValueError(
                f"request needs {need} KV tokens but the paged pool holds at "
                f"most {min(self.kv.row_capacity(), (self.kv.pages_total - 1) * self.kv.page_size)} "
                "per row; raise LUMEN_VLM_KV_PAGES or lower max_new_tokens"
            )
        if req.trace is None:
            req.trace = current_trace()
        if self.mesh is not None:
            # Fleet mode: prepare ran on replica 0's devices; move the
            # request tensors onto THIS engine's slice before its jitted
            # programs see them (same-placement transfers are no-ops).
            from ...parallel.sharding import replicate

            req.embeds, req.positions, req.length, req.prompt_ids = replicate(
                (req.embeds, req.positions, req.length, req.prompt_ids), self.mesh
            )
        with self._cond:
            if self._closed:
                raise RuntimeError("continuous scheduler is closed")
            self._pending.append(req)
            self._cond.notify()
        # Arrival counter under the batcher's ``batch_items:{name}`` key:
        # the predictive autopilot fits its trend over these buckets, so
        # engine families share the MicroBatcher sensor vocabulary.
        telemetry.count(f"batch_items:{self.name}")
        return req.future

    def load(self) -> int:
        """Dispatch weight for the manager's least-loaded engine pick."""
        return len(self._pending) + len(self._slots) + len(self._prefill_jobs)

    def submit_stream(self, req: _Request):
        """Submit and iterate generated token ids as they decode."""
        req.stream_q = queue_mod.SimpleQueue()
        self.submit(req)

        def tokens():
            try:
                while True:
                    item = req.stream_q.get()
                    if item is _STREAM_END:
                        err = req.future.exception()
                        if err is not None:
                            raise err
                        return
                    yield item
            finally:
                # Consumer gone (stop sequence hit, client disconnect, or
                # normal end): tell the scheduler to free the slot instead
                # of decoding to the cap into an unread queue.
                req.cancelled = True

        return tokens()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=10)
        with self._cond:
            pending, self._pending = self._pending, []
            live, self._slots = list(self._slots.values()), {}
            jobs, self._prefill_jobs = list(self._prefill_jobs), deque()
        err = RuntimeError("continuous scheduler closed")
        for req in pending + [s.request for s in live] + [j.request for j in jobs]:
            self._drop_spill(req)
            _fail(req, err)
        for job in jobs:
            self._drop_job_hold(job)
        if self.prefix is not None:
            self.prefix.clear()
        if self._spill_arena is not None:
            self._spill_arena.close()
        if fn := getattr(self, "_gauge_fn", None):
            metrics.unregister_gauges(f"vlm-continuous:{self.name}", fn)

    # -- scheduler loop ----------------------------------------------------

    def _take_work(self) -> list[_Request]:
        """Block until there is something to do; drain admissible requests.
        Chunk-lane jobs hold a slot reservation, so the drain never takes
        more requests than slots that will actually be free."""
        with self._cond:
            while (
                not self._closed
                and not self._pending
                and not self._slots
                and not self._prefill_jobs
            ):
                self._cond.wait()
            if self._closed:
                return []
            free = self.n_slots - len(self._slots) - len(self._prefill_jobs)
            if free <= 0:
                return []
            take, self._pending = self._pending[:free], self._pending[free:]
            return take

    def _requeue_front(self, reqs: list[_Request]) -> None:
        """Return unplaceable requests to the head of the queue in order."""
        if reqs:
            with self._cond:
                self._pending[:0] = reqs

    def _loop(self) -> None:
        try:
            while True:
                admit = self._take_work()
                with self._cond:
                    closed = self._closed
                if closed:
                    # close() raced us after _take_work popped these off
                    # _pending — its sweep can no longer see them, so fail
                    # them here instead of stranding their callers.
                    err = RuntimeError("continuous scheduler closed")
                    for req in admit:
                        _fail(req, err)
                    return
                live = []
                for req in admit:
                    if req.cancelled:
                        # Stream consumer disconnected while queued: retire
                        # without wasting a prefill dispatch on a dead row.
                        # A parked spill record's tokens are what the row
                        # produced — deliver them, and free the lease.
                        rec = self._drop_spill(req)
                        _retire(req, list(rec.tokens) if rec else [], eos=False)
                    else:
                        live.append(req)
                # Page gating: take requests in arrival order while the
                # free list covers their prompts; the rest go back to the
                # queue head and wait for retires to free pages. A
                # finished chunk-lane job waiting on pages gets its need
                # RESERVED out of the budget first — without that, a
                # sustained stream of short arrivals re-grants every
                # freed page each turn and starves the long prompt
                # forever.
                placeable, deferred = [], []
                budget = self.kv.pages_free - self._lane_reserved_pages()
                for req in live:
                    if req.spill is not None:
                        # A parked victim resumes into exactly its exported
                        # grant; growth past it is _ensure_growth's job.
                        need = req.spill.n_pages
                    else:
                        n = int(np.asarray(req.length)[0])
                        # A cached prefix needs no fresh grant — coverage
                        # is re-checked at admission (eviction between the
                        # peek and the attach degrades to a requeue).
                        covered = len(self._prefix_lookup(req, n))
                        need = self.kv.pages_for(n + 1) - covered
                    if need > budget and self.prefix is not None and not deferred:
                        # Cached history yields to live admissions before
                        # any request waits on retires.
                        budget += self.prefix.reclaim(need - budget)
                    if deferred or need > budget:
                        deferred.append(req)
                    else:
                        budget -= need
                        placeable.append(req)
                self._requeue_front(deferred)
                direct, hits = [], []
                for req in placeable:
                    if req.spill is not None:
                        # Re-admission scatters the spilled pages back in —
                        # no prefill group, no chunk lane, no device work
                        # proportional to the prompt.
                        self._resume_row(req)
                    elif req.embeds.shape[1] > self.prefill_chunk:
                        self._prefill_jobs.append(self._start_chunk_job(req))
                    elif self._prefix_lookup(req, int(np.asarray(req.length)[0])):
                        hits.append(req)
                    else:
                        direct.append(req)
                # Admission units: prefix hits go one by one (per-row
                # coverage), misses keep the batched-prefill groups. Both
                # fail like a group: the unit's requests on error, the
                # whole engine if the donation consumed the pool.
                units = [(self._admit_prefix_hit, req, [req]) for req in hits]
                units += [(self._admit_group, g, g) for g in self._admit_groups(direct)]
                for gpos, (admit_fn, arg, members) in enumerate(units):
                    try:
                        admit_fn(arg)
                    except Exception as e:  # noqa: BLE001 - fail ONE unit
                        for req in members:
                            _fail(req, e)
                        if self._pool_invalid():
                            # The failure hit the donation-based _admit call
                            # after self.pool's buffers were consumed: the
                            # other slots' KV state is gone, so "fail one
                            # group" is impossible — escalate to the
                            # fail-everything handler below. That handler
                            # sweeps only _pending + _slots and this batch
                            # is already off _pending, so fail its
                            # unprocessed tail here first.
                            for _, _, later in units[gpos + 1 :]:
                                for req in later:
                                    _fail(req, e)
                            raise RuntimeError(
                                "slot pool invalidated by failed admission"
                            ) from e
                self._advance_prefill_lane()
                if self.migrator is not None:
                    self._migrate_sweep()
                if self._slots:
                    self._run_block()
        except BaseException as e:  # noqa: BLE001 - never strand callers
            logger.exception("continuous scheduler loop died")
            with self._cond:
                self._closed = True
                pending, self._pending = self._pending, []
                live, self._slots = list(self._slots.values()), {}
                jobs, self._prefill_jobs = list(self._prefill_jobs), deque()
            for req in pending + [s.request for s in live] + [j.request for j in jobs]:
                self._drop_spill(req)
                _fail(req, RuntimeError(f"continuous scheduler died: {e!r}"))
            for job in jobs:
                self._drop_job_hold(job)

    def _pool_invalid(self) -> bool:
        """True when the page pool's buffers were deleted by a donation
        whose computation then failed (see ``Generator._admit``'s
        ``donate_argnames``)."""
        return any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(self.pool)
        )

    def _free_slot(self) -> int:
        for i in range(self.n_slots):
            if i not in self._slots:
                return i
        raise RuntimeError("no free slot (scheduler bug: admission overran pool)")

    #: Admission batch buckets: a burst of same-shaped arrivals prefills
    #: as ONE batched forward instead of K sequential batch-1 forwards
    #: (round-4 verdict: batch-1 admission serializes full-prompt prefills
    #: between decode blocks and starves the slot pool under load).
    #: Power-of-2 buckets bound the number of compiled prefill shapes.
    ADMIT_BUCKETS = (1, 2, 4, 8)

    def _admit_groups(self, reqs: list[_Request]) -> list[list[_Request]]:
        """Split admissible requests into batched-prefill groups: same
        (embeds len, prompt len) bucket, chunked to ADMIT_BUCKETS sizes."""
        by_shape: dict[tuple, list[_Request]] = {}
        for req in reqs:
            by_shape.setdefault(req.key, []).append(req)
        groups: list[list[_Request]] = []
        cap = max(self.ADMIT_BUCKETS)
        for group in by_shape.values():
            while group:
                # Largest bucket <= len(group), so a burst of 8 runs as one
                # prefill and a straggler of 3 runs as 2 + 1, not 8-padded.
                k = max(b for b in self.ADMIT_BUCKETS if b <= min(len(group), cap))
                groups.append(group[:k])
                group = group[k:]
        return groups

    def _admit_kv_len(self, span: int) -> int:
        """Prefill-scratch length for a prompt span: the generator's KV
        bucket rounded up to a page multiple (the scratch scatters into
        pages whole)."""
        kv_len = next((b for b in self.gen.seq_buckets if b >= span), self.gen.max_seq)
        kv_len = max(kv_len, span)
        return -(-kv_len // self.page_size) * self.page_size

    # -- prefix cache helpers -----------------------------------------------

    def _prefix_keys(self, req: _Request, n: int) -> list[bytes]:
        """Chain-hash keys over the request's live content identity,
        computed once per request (page-aligned, so a requeue reuses
        them)."""
        if self.prefix is None or req.prefix_content is None:
            return []
        keys = getattr(req, "_pfx_keys", None)
        if keys is None:
            content = np.asarray(req.prefix_content)[:n]
            keys = chunk_keys(content, self.page_size)
            req._pfx_keys = keys
        return keys

    def _prefix_lookup(self, req: _Request, n: int) -> list[int]:
        """Longest cached prefix for this request, capped one token short
        of the prompt so the write frontier always lands in a private
        page (``admit_shared``'s contract)."""
        keys = self._prefix_keys(req, n)
        if not keys:
            return []
        return self.prefix.lookup(keys)[: (n - 1) // self.page_size]

    def _prefix_insert(self, req: _Request, slot: int, n: int) -> None:
        """Record an installed row's full prompt pages (hit rows refresh
        their shared entries and extend coverage with the fresh suffix)."""
        keys = self._prefix_keys(req, n)
        if not keys:
            return
        pages = self.kv.owned_pages(slot)[: len(keys)]
        self.prefix.insert(keys[: len(pages)], pages)

    def _text_toks(self, req: _Request) -> list[int]:
        """Host copy of the live text prompt ids (drafter context)."""
        ids = [int(t) for t in np.asarray(req.prompt_ids)[0]]
        pad = self.gen.cfg.pad_token_id
        while ids and ids[-1] == pad:
            ids.pop()
        return ids

    def _install_row(
        self, req: _Request, caches1, tok0, seen1, length, shared_pages=None
    ) -> int:
        """Grant pages + write one prefilled row into a free slot. The
        device write donates the pool, so a failure here may invalidate
        it (callers escalate via ``_pool_invalid``). ``shared_pages``
        attaches a cached prefix ahead of the fresh grant — the device
        scatter then targets a DOCTORED table whose shared entries point
        at the dump page, so the scratch's prefix segments (already
        resident in the real pages) land harmlessly while the suffix
        segments fill the private pages."""
        slot = self._free_slot()
        n = int(np.asarray(length)[0])
        shared = list(shared_pages or ())
        if shared:
            bt_row = self.kv.admit_shared(slot, shared, n)
            bt_dev = bt_row.copy()
            bt_dev[: len(shared)] = 0
        else:
            bt_row = self.kv.admit(slot, n)
            bt_dev = bt_row
        try:
            self.pool = self.gen._admit(
                self.pool, slot, caches1, tok0, seen1, length,
                jnp.asarray(bt_dev), req.max_new, req.temperature,
                req.top_p, req.do_sample, req.repetition_penalty,
            )
        except Exception:
            self.kv.release(slot)
            raise
        self._admit_seq += 1
        slot_state = _Slot(request=req, prompt_len=n, seq=self._admit_seq)
        if self._spec_active():
            slot_state.text_toks = self._text_toks(req)
            slot_state.pending_tok = int(np.asarray(tok0)[0])
        with self._cond:
            self._slots[slot] = slot_state
        self.admitted += 1
        if self.prefix is not None:
            if shared:
                self.prefix_hits += 1
                self.prefix_hit_pages += len(shared)
                metrics.count("vlm_prefix_hits")
                req.prefix_hit = len(shared) * self.page_size / max(n, 1)
            else:
                self.prefix_misses += 1
                metrics.count("vlm_prefix_misses")
                req.prefix_hit = 0.0
            self._prefix_insert(req, slot, n)
        return slot

    def _admit_prefix_hit(self, req: _Request) -> None:
        """Admit one request whose prompt prefix is cached: attach the
        shared pages as a block-table copy, seed a prefill scratch with
        their contents (a device gather — no decoder forward), and run
        the decoder over the UNCOVERED SUFFIX only. The device prefill
        cost of a hot prefix is zero. Coverage is re-resolved here (an
        eviction since the admission peek shrinks it); losing the page
        race degrades to a requeue, losing coverage entirely to a plain
        batch-of-one admission."""
        pages = self._prefix_lookup(req, int(np.asarray(req.length)[0]))
        if not pages:
            self._admit_group([req])
            return
        n = int(np.asarray(req.length)[0])
        covered = len(pages) * self.page_size
        span = int(req.embeds.shape[1])
        scratch_len = self._admit_kv_len(span)
        nseg = scratch_len // self.page_size
        ids = np.zeros((nseg,), np.int32)
        ids[: len(pages)] = pages
        caches = self.gen.new_prefill_cache(scratch_len)
        caches = self.gen._seed_prefix(caches, self.pool["caches"], jnp.asarray(ids))
        c = span - covered
        chunk = req.embeds[:, covered:span]
        positions = jnp.broadcast_to(jnp.arange(covered, span)[None, :], (1, c))
        logits, caches = self.gen._prefill_chunk(
            self.params, caches, chunk, positions,
            jnp.asarray(covered, jnp.int32), jnp.asarray([n], jnp.int32),
        )
        sub = jax.random.fold_in(req.rng, 0)
        tok0, seen = self.gen._chunk_finish(
            logits, jnp.asarray([n - 1 - covered], jnp.int32),
            req.prompt_ids, req.length, sub,
            jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_p], jnp.float32),
            jnp.asarray([req.do_sample]),
            jnp.asarray([req.repetition_penalty], jnp.float32),
        )
        try:
            self._install_row(req, caches, tok0, seen, req.length, shared_pages=pages)
        except PoolExhausted:
            # Same-turn eviction shrank coverage and the fresh need no
            # longer fits — park at the queue head and retry next turn.
            self._requeue_front([req])

    def _admit_group(self, reqs: list[_Request]) -> None:
        """One batched prefill for the group, then per-row slot admission.
        The group shares one sampling key (same semantics as the
        coalescing batcher, which fuses mixed requests into one generate
        with one key); per-request generation params stay per-row."""
        k = len(reqs)
        sub = jax.random.fold_in(reqs[0].rng, 0)
        if k == 1:
            req = reqs[0]
            embeds, positions = req.embeds, req.positions
            lengths, prompt_ids = req.length, req.prompt_ids
        else:
            embeds = jnp.concatenate([r.embeds for r in reqs], axis=0)
            positions = jnp.concatenate([r.positions for r in reqs], axis=0)
            lengths = jnp.concatenate([r.length for r in reqs], axis=0)
            prompt_ids = jnp.concatenate([r.prompt_ids for r in reqs], axis=0)
        # Right-size the admission prefill cache to the PROMPT span only:
        # decode happens in the shared page pool, so the prefill buffer
        # never needs max_seq. Without this, a burst of 8 would
        # transiently allocate a second pool-sized KV buffer — an OOM
        # spike on exactly the load batched admission exists for.
        kv_len = self._admit_kv_len(embeds.shape[1])
        caches, tok0, seen = self.gen._prefill(
            self.params, embeds, positions, lengths, prompt_ids, sub,
            jnp.asarray([r.temperature for r in reqs], jnp.float32),
            jnp.asarray([r.top_p for r in reqs], jnp.float32),
            jnp.asarray([r.do_sample for r in reqs]),
            jnp.asarray([r.repetition_penalty for r in reqs], jnp.float32),
            kv_len=kv_len,
        )
        group_slots: list[int] = []
        try:
            for i, req in enumerate(reqs):
                row = slice(i, i + 1)
                caches1 = jax.tree.map(lambda c, r=row: c[r], caches)
                slot = self._install_row(req, caches1, tok0[row], seen[row], lengths[row])
                group_slots.append(slot)
        except Exception:
            # Mid-group failure with earlier rows already admitted: the
            # caller fails EVERY request in the group, so rows already in
            # _slots must be evicted too — otherwise they keep decoding to
            # max_new for futures that already errored, burning slots and
            # pages. If the pool was invalidated (donation consumed), skip
            # the device write; the caller escalates to fail-everything.
            if group_slots and not self._pool_invalid():
                idx = jnp.asarray(group_slots, jnp.int32)
                self.pool = dict(self.pool, done=self.pool["done"].at[idx].set(True))
            with self._cond:
                for slot in group_slots:
                    self._slots.pop(slot, None)
                    self.kv.release(slot)
            raise

    # -- chunked prefill lane ----------------------------------------------

    def _lane_reserved_pages(self) -> int:
        """Pages spoken for by the head chunk-lane job once its chunks
        have all run (it admits the moment the free list covers them)."""
        if not self._prefill_jobs:
            return 0
        job = self._prefill_jobs[0]
        if job.offset < job.length or job.request.cancelled:
            return 0
        return self.kv.pages_for(job.length + 1) - len(job.shared)

    def _start_chunk_job(self, req: _Request) -> _PrefillJob:
        n = int(np.asarray(req.length)[0])
        span = int(req.embeds.shape[1])
        # Sized to the padded span only (tail chunks shrink to fit): the
        # scratch must stay within what a block-table row can address.
        scratch_len = self._admit_kv_len(span)
        job = _PrefillJob(
            request=req,
            caches=self.gen.new_prefill_cache(scratch_len),
            scratch_len=scratch_len,
            length=n,
        )
        # Lane jobs reuse cached prefixes too: seed the scratch from the
        # shared pages and start chunking AFTER the covered span. The job
        # holds its own reference on the pages (``shared``) so eviction
        # during the multi-turn chunk run cannot free them mid-prefill;
        # _drop_job_hold releases it on every exit path.
        hit = self._prefix_lookup(req, n)
        if hit:
            self.kv.incref(hit)
            job.shared = list(hit)
            nseg = scratch_len // self.page_size
            ids = np.zeros((nseg,), np.int32)
            ids[: len(hit)] = hit
            job.caches = self.gen._seed_prefix(
                job.caches, self.pool["caches"], jnp.asarray(ids)
            )
            job.offset = len(hit) * self.page_size
        return job

    def _drop_job_hold(self, job: _PrefillJob) -> None:
        """Release a lane job's prefix-page hold (idempotent)."""
        if job.shared:
            self.kv.decref(job.shared)
            job.shared = []

    def _advance_prefill_lane(self) -> None:
        """Run ONE chunk of the head-of-lane prefill job (decode blocks
        interleave between chunks), admitting the job when its last live
        chunk has run and pages are free."""
        while self._prefill_jobs:
            job = self._prefill_jobs[0]
            req = job.request
            if req.cancelled:
                self._prefill_jobs.popleft()
                self._drop_job_hold(job)
                _retire(req, [], eos=False)
                continue
            if job.offset < job.length:
                off = job.offset
                # Tail chunks shrink to the padded span — off and the
                # chunk size are host ints, so each (span, off) pair is
                # one tiny compiled slice; counts are bounded by the
                # prompt buckets over the chunk size.
                c = min(self.prefill_chunk, int(req.embeds.shape[1]) - off)
                chunk = req.embeds[:, off : off + c]
                positions = jnp.broadcast_to(jnp.arange(off, off + c)[None, :], (1, c))
                valid = jnp.asarray([min(job.length, off + c)], jnp.int32)
                job.last_logits, job.caches = self.gen._prefill_chunk(
                    self.params, job.caches, chunk, positions,
                    jnp.asarray(off, jnp.int32), valid,
                )
                job.last_off = off
                job.offset = off + c
                self.chunks_run += 1
                return  # one chunk per turn: decode gets the next slice
            # All live chunks ran: admit when pages allow, else wait.
            # Shared prefix pages are already granted-by-reference, so
            # only the fresh suffix competes for the free list; cached
            # history yields (reclaim) before the job stalls.
            if not self.kv.can_admit(job.length, shared_pages=len(job.shared)):
                if self.prefix is not None:
                    short = (
                        self.kv.pages_for(job.length + 1)
                        - len(job.shared) - self.kv.pages_free
                    )
                    if short <= 0 or not self.prefix.reclaim(short):
                        return
                    if not self.kv.can_admit(job.length, shared_pages=len(job.shared)):
                        return
                else:
                    return
            sub = jax.random.fold_in(req.rng, 0)
            tok0, seen = self.gen._chunk_finish(
                job.last_logits, jnp.asarray([job.length - 1 - job.last_off], jnp.int32),
                req.prompt_ids, req.length, sub,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_p], jnp.float32),
                jnp.asarray([req.do_sample]),
                jnp.asarray([req.repetition_penalty], jnp.float32),
            )
            self._prefill_jobs.popleft()
            try:
                self._install_row(
                    req, job.caches, tok0, seen, req.length,
                    shared_pages=job.shared,
                )
            except Exception as e:  # noqa: BLE001
                _fail(req, e)
                if self._pool_invalid():
                    raise RuntimeError(
                        "slot pool invalidated by failed admission"
                    ) from e
            finally:
                self._drop_job_hold(job)
            return

    # -- decode blocks ------------------------------------------------------

    def _preempt_newest(self, protect: int) -> bool:
        """Evict the newest live row (except ``protect``): export its
        pages + decode state into the spill tier and park it at the queue
        head to RESUME (no re-prefill, token-identical continuation), or
        — when the tier is full, disabled, or the export itself fails —
        fall down the pre-spill ladder: requeue-and-redo for rows whose
        restart is invisible (greedy, or nothing streamed yet; greedy
        reproduces its tokens exactly and ``delivered`` is deliberately
        NOT reset so a resumed stream never re-sends its prefix), and a
        typed retryable :class:`PreemptionShed` for sampled mid-stream
        rows — splicing a fresh draw onto already-streamed tokens would
        emit a sequence no sampling run ever produced. With the spill
        tier those rows are preferred LAST as victims and, when they must
        go, usually resume instead of shedding."""
        victims = [i for i in self._slots if i != protect]
        if not victims:
            return False

        def redo_safe(i: int) -> bool:
            req = self._slots[i].request
            return not (req.do_sample and req.delivered > 0)

        clean = [i for i in victims if redo_safe(i)]
        idx = max(clean or victims, key=lambda i: self._slots[i].seq)
        record = spill_err = None
        try:
            # Export happens BEFORE the pop/release while the row still
            # owns its pages; _export_row does not donate the pool, so a
            # failed export leaves every other row intact.
            record = self._spill_victim(idx)
        except Exception as e:  # noqa: BLE001 - spill is best-effort
            spill_err = e
            logger.warning("KV spill of slot %d failed (%s); degrading", idx, e)
        self.pool = dict(
            self.pool, done=self.pool["done"].at[jnp.asarray([idx], jnp.int32)].set(True)
        )
        with self._cond:
            slot = self._slots.pop(idx)
        self.kv.release(idx)
        self.preemptions += 1
        metrics.count("vlm_paged_preemptions")
        now = time.monotonic()
        if now - self._preempt_log_t >= 1.0:
            # Throttled like the batcher's shed log: a preemption storm is
            # one line a second, not a flood.
            self._preempt_log_t = now
            logger.warning(
                "paged KV pool exhausted: preempting slot %d (%d tokens in, %s)",
                idx, len(slot.tokens),
                "spilled for resume" if record is not None else "restarts from prompt",
            )
        req = slot.request
        if record is not None:
            record.prompt_len = slot.prompt_len
            record.tokens = slot.tokens
            self._park_spill(req, record)
        elif not (req.do_sample and req.delivered > 0):
            self.preempt_redone += 1
            metrics.count("vlm_preempt_redone")
            self._requeue_front([req])
        else:
            self._fail_preempted(req, spill_err)
        return True

    # -- KV spill tier -------------------------------------------------------

    def _get_arena(self) -> ShmArena:
        """Lazily created so engines that never preempt never touch
        /dev/shm; budget shared with the ledger byte bound."""
        if self._spill_arena is None:
            tag = "".join(c if c.isalnum() else "-" for c in self.name)
            self._spill_arena = ShmArena(
                name=f"vlmspill-{tag}", max_bytes=self._spill_budget
            )
        return self._spill_arena

    def _export_state(self, idx: int, n_shared: int) -> tuple:
        """ONE export codepath for both migration sinks: gather slot
        ``idx``'s pages past the first ``n_shared`` block-table entries
        (power-of-2 padded — dump-page garbage fills the tail, bounding
        compiled export/resume shapes at log2(max_pages)) plus the row's
        exact decode scalars and rng, in ONE fused device->host
        transfer. Returns ``(record, shared_page_ids)`` with the payload
        as host-array leaves; the sink decides where the bytes live —
        the shm arena (spill), or the tensor wire (``fed_kv_put``).
        ``_export_row`` does not donate, so failure anywhere leaves the
        pool untouched."""
        owned = self.kv.owned_pages(idx)
        shared, private = owned[:n_shared], owned[n_shared:]
        n_pad = 1
        while n_pad < max(1, len(private)):
            n_pad *= 2
        ids = np.zeros((n_pad,), np.int32)
        ids[: len(private)] = private
        req = self._slots[idx].request
        exported = self.gen._export_row(self.pool, idx, jnp.asarray(ids))
        host, rng = jax.device_get((exported, req.rng))
        payload = {"pages": host["pages"], "seen": host["seen"]}
        leaves, treedef = jax.tree.flatten(payload)
        rec = _SpillRecord(
            n_pages=len(private), n_pad=n_pad,
            nbytes=sum(int(a.nbytes) for a in leaves),
            treedef=treedef, crc=0, cur_tok=int(host["cur_tok"]),
            cur_len=int(host["cur_len"]), n_gen=int(host["n_gen"]),
            rng=rng, arrays=leaves,
        )
        return rec, shared

    def _spill_victim(self, idx: int) -> "_SpillRecord | None":
        """Export slot ``idx``'s live pages + decode state into a spill
        record. ``None`` = tier disabled or ledger full (counted, caller
        degrades); raises on export/pack failure (incl. the ``kv_spill``
        fault point). Runs BEFORE the caller releases the pages, so
        failure leaves the pool untouched."""
        if self._spill_budget <= 0 or self._spill_max <= 0:
            return None
        if len(self._spill_ledger) >= self._spill_max:
            self.spill_denied += 1
            metrics.count("vlm_spill_denied")
            return None
        faults.check(KV_SPILL, f"{self.name}:{idx}")
        # A row that attached a cached prefix does not need its shared
        # pages exported — they stay resident under the cache's (and this
        # record's) reference and re-attach on resume as a block-table
        # copy. Only the PRIVATE suffix crosses to host memory.
        rec, shared = self._export_state(idx, self.kv.shared_prefix_len(idx))
        if self._spill_bytes_live + rec.nbytes > self._spill_budget:
            self.spill_denied += 1
            metrics.count("vlm_spill_denied")
            return None
        # Pack into the one migration lease blob (the same frame train
        # fed_kv_put ships) and park it in the shm arena when the budget
        # allows; else keep the plain host-array leaves — same bytes
        # against the same ledger budget, just not recyclable segments.
        blob, crc = migration.pack_payload(rec.arrays)
        got = self._get_arena().acquire(len(blob))
        if got is not None:
            np.frombuffer(got.buf, np.uint8, count=len(blob))[:] = np.frombuffer(
                blob, np.uint8
            )
            rec.lease, rec.crc, rec.nbytes, rec.arrays = got, crc, len(blob), None
        else:
            self.spill_fallbacks += 1
            metrics.count("vlm_spill_fallbacks")
        # The record's hold on the shared prefix is taken LAST — every
        # failure/denial path above returns before this line, so a record
        # exists iff the incref happened and _drop_spill's decref always
        # balances it. The caller's kv.release(idx) then drops the row's
        # own references without freeing the prefix out from under us.
        if shared:
            self.kv.incref(shared)
            rec.shared_pages = list(shared)
        return rec

    def _park_spill(self, req: _Request, record: "_SpillRecord") -> None:
        req.spill = record
        self._spill_ledger[id(req)] = record
        self._spill_bytes_live += record.nbytes
        self.spills += 1
        metrics.count("vlm_spills")
        record_event(
            "vlm_spill", self.name,
            f"row spilled for resume: {record.n_pages} pages, "
            f"{len(record.tokens)} tokens parked",
            min_interval_s=1.0,
            pages=record.n_pages, bytes=record.nbytes,
            entries=len(self._spill_ledger),
        )
        self._requeue_front([req])

    def _drop_spill(self, req: _Request) -> "_SpillRecord | None":
        """Detach and free a request's spill record (lease back to the
        arena, bytes off the ledger). Idempotent — every retirement path
        calls it, so accounting balances at drain no matter which path a
        spilled request leaves through."""
        rec = getattr(req, "spill", None)
        if rec is None:
            return None
        req.spill = None
        self._spill_ledger.pop(id(req), None)
        self._spill_bytes_live -= rec.nbytes
        if rec.lease is not None:
            rec.lease.release()
            rec.lease = None
        rec.arrays = None
        if rec.shared_pages:
            self.kv.decref(rec.shared_pages)
            rec.shared_pages = []
        return rec

    def _drain_estimate_s(self) -> float:
        """Retry-after hint for :class:`PreemptionShed`: the soonest
        retire (min remaining budget across live rows) at the engine's
        EWMA per-token pace — the batcher's queue-drain hint, page-pool
        flavored. Pre-pace (no block run yet) falls back to a half
        second so the client backoff floor still has a number."""
        per_tok = self._block_s_ewma / max(self.block, 1)
        if per_tok <= 0.0:
            return 0.5
        remaining = min(
            (s.request.max_new - len(s.tokens) for s in self._slots.values()),
            default=self.block,
        )
        return per_tok * max(remaining, self.block)

    def _fail_preempted(self, req: _Request, cause: "BaseException | None") -> None:
        err = PreemptionShed(
            "preempted by KV pool exhaustion mid-stream and the spill tier "
            "could not park the row; a sampled stream cannot restart "
            "without splicing draws — retry after the pool drains"
        )
        err.retry_after_s = self._drain_estimate_s()
        if cause is not None:
            err.__cause__ = cause
        self.preempt_failed += 1
        metrics.count("vlm_preempt_failed")
        _fail(req, err)

    def _unpack_spill(self, rec: "_SpillRecord") -> list:
        """The record's payload leaves as host arrays safe to ship to the
        device. Lease views are COPIED out — the lease recycles right
        after resume, and a zero-copy transfer could still be reading its
        pages — after the crc gate turns a torn or recycled-out-from-
        under-us lease into a clean degradation instead of silent token
        corruption."""
        if rec.lease is None:
            if rec.arrays is None:
                raise RuntimeError("spill record has no payload (double resume?)")
            return list(rec.arrays)
        try:
            leaves = migration.unpack_payload(rec.lease.buf[: rec.nbytes], rec.crc)
        except ValueError as e:
            raise RuntimeError(f"spill lease rejected: {e}") from None
        return [leaf.copy() for leaf in leaves]

    def _resume_row(self, req: _Request) -> None:
        """Scatter a parked spill record into a fresh page grant and
        re-install the row — zero re-prefill; greedy continuation is
        token-identical, sampled continuation carries on its own stream.
        Failure anywhere degrades to the spill ladder (requeue-and-redo
        or typed shed); the ONLY re-raise is pool invalidation (the
        donation-based ``_resume`` consumed the pool's buffers before
        dying), which must reach the loop's fail-everything handler."""
        rec: _SpillRecord = req.spill
        slot = granted = None
        try:
            faults.check(KV_RESUME, f"{self.name}:resume")
            if req.migrate_in is not None:
                self._attach_migrate_shared(req, rec)
            leaves = self._unpack_spill(rec)
            payload = jax.tree.unflatten(rec.treedef, leaves)
            slot = self._free_slot()
            # Shared prefix pages re-attach by reference (admit_exact
            # increfs them ahead of the fresh grant); the scatter below
            # only rewrites the PRIVATE suffix, so the resumed table is
            # [shared… | scattered private…] — byte-identical history.
            bt_row = self.kv.admit_exact(
                slot, rec.n_pages, shared_pages=rec.shared_pages or None
            )
            granted = slot
            base = len(rec.shared_pages)
            ids = np.zeros((rec.n_pad,), np.int32)
            ids[: rec.n_pages] = bt_row[base : base + rec.n_pages]
            pages = jax.tree.map(jnp.asarray, payload["pages"])
            self.pool = self.gen._resume(
                self.pool, slot, pages, jnp.asarray(ids),
                jnp.asarray(payload["seen"]), rec.cur_tok, rec.cur_len,
                rec.n_gen, req.max_new, req.temperature, req.top_p,
                req.do_sample, req.repetition_penalty,
            )
        except PoolExhausted:
            # Lost a page race (lane reservation, same-turn admissions):
            # keep the record parked and try again next turn.
            self._requeue_front([req])
            return
        except Exception as e:  # noqa: BLE001 - degrade, never wedge the loop
            if self._pool_invalid():
                raise
            if granted is not None:
                self.kv.release(granted)
            logger.warning("KV resume failed (%s); degrading", e)
            self._drop_spill(req)
            if req.migrate_in is not None:
                # A migrated-in row has no local prompt to redo from —
                # refuse it; the PREFILL host owns the fallback ladder
                # and resumes the row from its own snapshot.
                req.migrate_in = None
                self.migrate_in_rejected += 1
                metrics.count("vlm_migrate_in_rejected")
                _fail(req, e)
            elif not (req.do_sample and req.delivered > 0):
                self.preempt_redone += 1
                metrics.count("vlm_preempt_redone")
                self._requeue_front([req])
            else:
                self._fail_preempted(req, e)
            return
        self._admit_seq += 1
        slot_state = _Slot(
            request=req, prompt_len=rec.prompt_len,
            seq=self._admit_seq, tokens=rec.tokens,
        )
        if self._spec_active():
            slot_state.text_toks = self._text_toks(req)
            slot_state.pending_tok = rec.cur_tok
        with self._cond:
            self._slots[slot] = slot_state
        self.admitted += 1
        self.spill_resumes += 1
        metrics.count("vlm_spill_resumes")
        self._drop_spill(req)
        if req.migrate_in is not None:
            keys, _ = req.migrate_in
            req.migrate_in = None
            self.migrated_in += 1
            metrics.count("vlm_migrated_in")
            if self.prefix is not None and keys:
                # The migrated prompt's pages are cacheable history HERE
                # too: later same-prefix migrations (and local requests)
                # resolve them by reference instead of riding the wire.
                pages = self.kv.owned_pages(slot)[: len(keys)]
                self.prefix.insert(keys[: len(pages)], pages)
        record_event(
            "vlm_resume", self.name,
            f"row resumed into slot {slot}: {rec.n_pages} pages "
            f"re-installed, {len(rec.tokens)} tokens already out",
            min_interval_s=1.0,
            pages=rec.n_pages, tokens=len(rec.tokens),
        )

    # -- KV page migration (disaggregated prefill/decode) --------------------

    def _wire_manifest(self, req: _Request, n: int) -> list:
        """Content-hash chain keys over the prompt's page-aligned prefix
        (capped one page short like the prefix cache's attach cap) — the
        offer leg's reference list. Empty when the request carries no
        content identity; the whole prompt then rides the wire."""
        if req.prefix_content is None:
            return []
        content = np.asarray(req.prefix_content)[:n]
        return chunk_keys(content, self.page_size)[: (n - 1) // self.page_size]

    def _migrate_sweep(self) -> None:
        """Hand freshly prefilled rows tagged for a decode-lane peer to
        the migration dispatcher: export through the spill codepath
        (shared prefix CONTENTS included — the peer may not hold them),
        release the slot, and let the dispatcher run the wire legs
        off-thread. Every failure re-enters via :meth:`resubmit_spilled`
        — the preemption ladder with the peer as one more flaky sink, so
        a dead decode host never loses or duplicates tokens."""
        for idx in list(self._slots):
            slot = self._slots.get(idx)
            if slot is None:
                continue
            req = slot.request
            if not req.migrate_to or slot.tokens or req.cancelled:
                continue
            target, req.migrate_to = req.migrate_to, None  # one attempt
            try:
                # All owned pages export by content (n_shared=0): the
                # record is self-contained; reference-vs-contents is the
                # DISPATCHER's call after the peer answers the offer.
                rec, _ = self._export_state(idx, 0)
            except Exception as e:  # noqa: BLE001 - decode locally instead
                logger.warning(
                    "KV migrate-out export of slot %d failed (%s); "
                    "decoding locally", idx, e,
                )
                continue
            rec.prompt_len = slot.prompt_len
            manifest = self._wire_manifest(req, slot.prompt_len)
            self.pool = dict(
                self.pool,
                done=self.pool["done"].at[jnp.asarray([idx], jnp.int32)].set(True),
            )
            with self._cond:
                self._slots.pop(idx, None)
            self.kv.release(idx)
            self.migrated_out += 1
            metrics.count("vlm_migrated_out")
            try:
                self.migrator(self, req, rec, manifest, target)
            except Exception as e:  # noqa: BLE001 - ladder, not a loss
                logger.warning(
                    "KV migration dispatch to %s failed (%s); resuming "
                    "locally", target, e,
                )
                self.resubmit_spilled(req, rec)

    def resubmit_spilled(self, req: _Request, rec: _SpillRecord) -> None:
        """Thread-safe re-entry for a migration that failed before or
        mid-stream: park the record as a spill and resume locally with
        zero re-prefill (greedy replays are token-identical and the
        ``delivered`` counter suppresses any already-streamed prefix).
        A sampled row whose peer already streamed past the snapshot
        cannot resume without splicing draws — it sheds with the typed
        retryable error, exactly the preemption ladder."""
        self.migrate_out_failed += 1
        metrics.count("vlm_migrate_fallbacks")
        with self._cond:
            closed = self._closed
        if closed:
            _fail(req, RuntimeError("continuous scheduler is closed"))
            return
        if req.do_sample and req.delivered > rec.n_gen:
            self._fail_preempted(req, None)
            return
        req.spill = rec
        self._spill_ledger[id(req)] = rec
        self._spill_bytes_live += rec.nbytes
        self._requeue_front([req])
        with self._cond:
            self._cond.notify()

    def submit_migrated(
        self, req: _Request, rec: _SpillRecord, manifest: list, n_shared: int
    ) -> None:
        """Decode-host entry for a ``fed_kv_put`` commit: park the wire
        record as a parked spill and queue the request — the ordinary
        resume path then re-installs the row with ZERO re-prefill device
        work. ``manifest``/``n_shared`` defer shared-prefix resolution
        to the loop thread (the prefix cache is loop-owned); a lost
        race fails the request with :class:`migration.ChunksMissing`,
        which the wire handler maps to a retryable refusal."""
        need = rec.cur_len + max(int(req.max_new) - rec.n_gen, 0) + 1
        if not self.kv.fits(need):
            raise ValueError(
                f"migrated row needs {need} KV tokens but this pool holds "
                f"at most {min(self.kv.row_capacity(), (self.kv.pages_total - 1) * self.kv.page_size)} "
                "per row"
            )
        req.spill = rec
        req.migrate_in = (list(manifest), int(n_shared))
        if req.trace is None:
            req.trace = current_trace()
        with self._cond:
            if self._closed:
                raise RuntimeError("continuous scheduler is closed")
            self._spill_ledger[id(req)] = rec
            self._spill_bytes_live += rec.nbytes
            self._pending.append(req)
            self._cond.notify()

    def _attach_migrate_shared(self, req: _Request, rec: _SpillRecord) -> None:
        """Resolve a migrated-in row's shared-prefix references against
        the LOCAL prefix cache (loop thread — authoritative, unlike the
        offer leg's advisory peek) and take the record's hold on them.
        Idempotent across page-race requeues: once ``shared_pages`` is
        set the references are held and re-resolution would double-count."""
        keys, n_shared = req.migrate_in
        if n_shared <= 0 or rec.shared_pages:
            return
        got = self.prefix.lookup(keys[:n_shared]) if self.prefix is not None else []
        if len(got) < n_shared:
            raise migration.ChunksMissing(
                f"offer promised {n_shared} cached prefix pages but only "
                f"{len(got)} survive (evicted since the offer)"
            )
        got = got[:n_shared]
        self.kv.incref(got)
        rec.shared_pages = list(got)

    def _row_need(self, slot: "_Slot", horizon: "int | None" = None) -> int:
        """KV tokens a row needs covered before the next block: the
        block's writes (or a speculative verify turn's ``horizon``),
        clamped to the row's own budget (it stops at ``max_new``) and to
        what a block table can address (a row at capacity keeps
        overwriting its clamped last slot — matching the decode program's
        position clamp). Without the clamps, a feasible request ending
        within ``block`` tokens of the pool bound would ask for pages
        past the table and crash the loop."""
        return min(
            slot.prompt_len + len(slot.tokens) + (horizon or self.block),
            slot.prompt_len + slot.request.max_new + 1,
            self.kv.row_capacity(),
        )

    def _ensure_growth(self, horizon: "int | None" = None) -> None:
        """Before a block, every live row's pages must cover the next
        block's writes; cached prefixes yield first (reclaim), then the
        newest rows are preempted until the free list can satisfy the
        rest. A lone row always fits — submit() checked feasibility
        against the whole pool, and any unreclaimable cache page a lone
        row's growth could collide with is, by construction, already in
        that row's own block table (shared prefix pages never grow).

        Growth into a SHARED frontier page would trigger copy-on-write
        inside the pool; the engine's admission paths cap prefix
        attachment one token short of the prompt, so the write frontier
        is always private and a CoW here means an allocator invariant
        broke — surfaced loudly rather than silently remapped."""
        cow: list = []
        for idx in sorted(self._slots, key=lambda i: self._slots[i].seq):
            slot = self._slots.get(idx)
            if slot is None:
                continue
            need = self._row_need(slot, horizon)
            while not self.kv.grow(idx, need, cow):
                if self.prefix is not None and self.prefix.reclaim(1):
                    continue
                if not self._preempt_newest(protect=idx):
                    raise RuntimeError(
                        "paged pool cannot grow a lone row (feasibility bug)"
                    )
                if idx not in self._slots:  # we preempted ourselves? never
                    break
        if cow:
            raise RuntimeError(
                f"unexpected copy-on-write during decode growth: {cow} "
                "(prefix attachment must leave the write frontier private)"
            )

    # -- speculative decoding -----------------------------------------------

    def _spec_active(self) -> bool:
        return self.spec_k > 0 and not self.spec_disabled

    def _draft_row(self, slot: "_Slot") -> list[int]:
        """Prompt-lookup draft for one row: the longest recent n-gram
        (``spec_ngram`` down to 1) whose suffix matches the row's current
        tail is replayed for up to ``spec_k`` tokens. No draft model —
        the prompt plus the row's own output IS the drafter, which is
        exactly the traffic (templates, citations, repetitive captions)
        speculative decoding pays off on. Greedy rows only: verification
        is token-identity against argmax; a sampled row would need draw
        matching the verify program does not implement."""
        req = slot.request
        if req.do_sample or slot.pending_tok is None or slot.text_toks is None:
            return []
        ctx = slot.text_toks + slot.tokens + [slot.pending_tok]
        for n in range(min(self.spec_ngram, len(ctx) - 1), 0, -1):
            pat = ctx[-n:]
            # EARLIEST occurrence: on cycling/template text every match
            # continues identically, and the earliest one has the most
            # room before it runs into the tail being drafted.
            for start in range(len(ctx) - n):
                if ctx[start : start + n] == pat:
                    return ctx[start + n : start + n + self.spec_k]
        return []

    def _spec_try_disable(self) -> None:
        """Permanent auto-off once acceptance proves the traffic wrong:
        below ``LUMEN_VLM_SPEC_MIN_RATE`` after a fair sample every
        verify turn is pure overhead (drafting, wider attention) with no
        accepted tokens to show for it — same autopilot posture as the
        q8 route's calibration gate."""
        if self.spec_disabled or self.spec_proposed < 64:
            return
        if self.spec_accepted < self.spec_min_rate * self.spec_proposed:
            self.spec_disabled = True
            logger.warning(
                "speculative decoding disabled: acceptance %d/%d below floor %.2f",
                self.spec_accepted, self.spec_proposed, self.spec_min_rate,
            )

    def _run_block(self) -> None:
        cancelled = [
            i for i, slot in self._slots.items() if slot.request.cancelled
        ]
        if cancelled:
            idx = jnp.asarray(cancelled, jnp.int32)
            self.pool = dict(self.pool, done=self.pool["done"].at[idx].set(True))
            for i in cancelled:
                with self._cond:
                    slot = self._slots.pop(i)
                self.kv.release(i)
                _retire(slot.request, slot.tokens, eos=False)
            if not self._slots:
                return
        # A verify turn runs only when some row drafted AND every live
        # row's window fits its table capacity — the verify program's
        # position clamp must never engage on a live row (it would
        # overwrite history; rows that near the edge finish on plain
        # blocks whose per-step clamp matches the non-speculative path).
        width = 0
        drafts: dict[int, list[int]] = {}
        if self._spec_active():
            cap = self.kv.row_capacity()
            if all(
                s.prompt_len + len(s.tokens) + self.spec_k + 1 <= cap
                for s in self._slots.values()
            ):
                drafts = {
                    i: d for i, s in self._slots.items() if (d := self._draft_row(s))
                }
                if drafts:
                    width = self.spec_k + 1
        self._ensure_growth(horizon=width or None)
        # Growth may have preempted a drafted row; verify only helps if a
        # surviving row still carries a draft.
        if width:
            drafts = {i: d for i, d in drafts.items() if i in self._slots}
            if not drafts:
                width = 0
        active = len(self._slots)
        t0 = time.perf_counter()
        tm0 = time.monotonic()
        # Ragged page bucketing: ship only a power-of-2 prefix of the
        # block tables covering the longest live row. The CPU reference
        # gathers every table entry it is given, so a pool of short
        # generations must not pay max_seq worth of gather per step (the
        # page-granular twin of attention_cached's ragged KV ladder);
        # bucketing keeps compiled step shapes at log2(max_pages).
        maxp_live = max(
            (
                self.kv.pages_for(self._row_need(s, width or None))
                for s in self._slots.values()
            ),
            default=1,
        )
        bucket = 1
        while bucket < maxp_live:
            bucket *= 2
        bucket = min(bucket, self.kv.max_pages)
        if width:
            q = np.zeros((self.n_slots, width), np.int32)
            ql = np.ones((self.n_slots,), np.int32)
            for i, d in drafts.items():
                q[i, 1 : 1 + len(d)] = d
                ql[i] = 1 + len(d)
            self.pool, self._rng, toks = self.gen._verify(
                self.params, self.pool,
                jnp.asarray(self.kv.block_tables[:, :bucket]),
                self._rng, jnp.asarray(q), jnp.asarray(ql), width=width,
            )
            self.spec_turns += 1
        else:
            ql = None
            self.pool, self._rng, toks = self.gen._step_block(
                self.params, self.pool,
                jnp.asarray(self.kv.block_tables[:, :bucket]),
                self._rng, block=self.block,
            )
        self.blocks_run += 1
        self._occ_rows += active
        self._occ_blocks += 1
        # One fused device->host transfer for everything the bookkeeping
        # below needs (four separate np.asarray calls = four round trips
        # on the per-block hot path). cur_tok rides along ONLY when
        # speculation is configured — the unconfigured transfer is
        # byte-identical to the non-speculative build.
        if self.spec_k > 0:
            toks_np, n_gen, done, eos, cur_tok = jax.device_get(
                (
                    toks, self.pool["n_gen"], self.pool["done"],
                    self.pool["eos"], self.pool["cur_tok"],
                )
            )
        else:
            cur_tok = None
            toks_np, n_gen, done, eos = jax.device_get(
                (toks, self.pool["n_gen"], self.pool["done"], self.pool["eos"])
            )
        t1 = time.perf_counter()
        # Decode pace for the PreemptionShed drain hint (first block seeds
        # the EWMA; compile-heavy first blocks wash out within a few).
        dt = t1 - t0
        if self._step_floor_s > 0.0:
            # Pace BEFORE tokens stream out so first-token latency pays
            # the floor too — a paced block models a slower chip, not a
            # faster chip with delayed bookkeeping.
            lag = self.block * self._step_floor_s - dt
            if lag > 0.0:
                time.sleep(lag)
                dt = time.perf_counter() - t0
        self._block_s_ewma = (
            dt if self._block_s_ewma == 0.0 else 0.8 * self._block_s_ewma + 0.2 * dt
        )
        # Duty credit covers the paced window too: a step floor models a
        # slower chip, and the duty meter should describe that chip.
        telemetry.busy(f"device:{self.name}", tm0, time.monotonic())
        span_meta = {
            "step": self.blocks_run,
            "rows": active,
            "fill_pct": round(100.0 * active / self.n_slots, 1),
            "block": self.block,
        }
        for idx in list(self._slots):
            slot = self._slots[idx]
            req = slot.request
            if req.trace is not None:
                req.trace.add_span("batch.device", t0, t1, dict(span_meta))
            new = int(n_gen[idx]) - len(slot.tokens)
            if width and int(ql[idx]) > 1:
                # First emission of a verify turn is the pending token
                # (not a draft); acceptance counts only the drafted tail.
                prop = int(ql[idx]) - 1
                acc = max(min(new - 1, prop), 0)
                self.spec_proposed += prop
                self.spec_accepted += acc
                req.spec_proposed += prop
                req.spec_accepted += acc
                metrics.count("vlm_spec_proposed", prop)
                metrics.count("vlm_spec_accepted", acc)
            if cur_tok is not None:
                slot.pending_tok = int(cur_tok[idx])
            if new > 0:
                slot.tokens.extend(int(t) for t in toks_np[idx, :new])
                if req.stream_q is not None:
                    for t in slot.tokens[req.delivered :]:
                        req.stream_q.put(t)
                    # max(): after a failed migration the remote relay
                    # has already delivered PAST this replay's position —
                    # moving the watermark backward would re-emit every
                    # token from here to the crash point as duplicates.
                    req.delivered = max(req.delivered, len(slot.tokens))
            if done[idx]:
                with self._cond:
                    del self._slots[idx]
                self.kv.release(idx)
                _retire(req, slot.tokens, bool(eos[idx]))
        if width:
            self._spec_try_disable()
