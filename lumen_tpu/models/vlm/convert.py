"""Checkpoint conversion: HF LLaVA/Qwen2-style VLM -> Flax params.

The reference consumes pre-exported ONNX graphs and never touches raw
checkpoints; we load the source safetensors directly (FastVLM-style repos
ship a Qwen2 language model + vision tower + 2-layer projector). Converted
trees are shape-gated against the module's init tree before serving, same
as the other families (``lumen_tpu/models/clip/convert.py``).
"""

from __future__ import annotations

import logging
import re

import numpy as np

from ...runtime.weights import (
    apply_rules,
    assert_tree_shapes,
    conv_kernel,
    is_native_checkpoint,
    linear_kernel,
    split_collections,
    unflatten,
)

logger = logging.getLogger(__name__)

_QKV = r"(q_proj|k_proj|v_proj)"

DECODER_RULES = [
    (r"model\.embed_tokens\.weight", r"decoder/embed_tokens/embedding", None),
    (rf"model\.layers\.(\d+)\.self_attn\.{_QKV}\.weight", r"decoder/layers_\1/attn/\2/kernel", linear_kernel),
    (rf"model\.layers\.(\d+)\.self_attn\.{_QKV}\.bias", r"decoder/layers_\1/attn/\2/bias", None),
    (r"model\.layers\.(\d+)\.self_attn\.o_proj\.weight", r"decoder/layers_\1/attn/o_proj/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.gate_proj\.weight", r"decoder/layers_\1/mlp/gate_proj/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.up_proj\.weight", r"decoder/layers_\1/mlp/up_proj/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.down_proj\.weight", r"decoder/layers_\1/mlp/down_proj/kernel", linear_kernel),
    # Qwen2-MoE sparse layers: router + per-expert SwiGLU (stacked into
    # [E, ...] banks by _stack_experts below) + sigmoid-gated shared expert.
    (r"model\.layers\.(\d+)\.mlp\.gate\.weight", r"decoder/layers_\1/mlp/router", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.experts\.(\d+)\.gate_proj\.weight", r"decoder/layers_\1/mlp/__expert_gate__/\2", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.experts\.(\d+)\.up_proj\.weight", r"decoder/layers_\1/mlp/__expert_up__/\2", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.experts\.(\d+)\.down_proj\.weight", r"decoder/layers_\1/mlp/__expert_down__/\2", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.shared_expert\.gate_proj\.weight", r"decoder/layers_\1/mlp/shared/gate_proj/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.shared_expert\.up_proj\.weight", r"decoder/layers_\1/mlp/shared/up_proj/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.shared_expert\.down_proj\.weight", r"decoder/layers_\1/mlp/shared/down_proj/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.mlp\.shared_expert_gate\.weight", r"decoder/layers_\1/mlp/shared_gate/kernel", linear_kernel),
    (r"model\.layers\.(\d+)\.input_layernorm\.weight", r"decoder/layers_\1/input_norm/scale", None),
    (r"model\.layers\.(\d+)\.post_attention_layernorm\.weight", r"decoder/layers_\1/post_attn_norm/scale", None),
    (r"model\.norm\.weight", r"decoder/final_norm/scale", None),
    (r"lm_head\.weight", r"decoder/lm_head/kernel", linear_kernel),
]

VISION_RULES = [
    (r"vision_tower\.patch_embed\.weight", r"vision/patch_embed/kernel", conv_kernel),
    (r"vision_tower\.patch_embed\.bias", r"vision/patch_embed/bias", None),
    (r"vision_tower\.position_embedding", r"vision/position_embedding", None),
    (rf"vision_tower\.blocks\.(\d+)\.attn\.{_QKV}\.weight", r"vision/blocks_\1/attn/\2/kernel", linear_kernel),
    (rf"vision_tower\.blocks\.(\d+)\.attn\.{_QKV}\.bias", r"vision/blocks_\1/attn/\2/bias", None),
    (r"vision_tower\.blocks\.(\d+)\.attn\.out_proj\.weight", r"vision/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"vision_tower\.blocks\.(\d+)\.attn\.out_proj\.bias", r"vision/blocks_\1/attn/out_proj/bias", None),
    (r"vision_tower\.blocks\.(\d+)\.norm1\.weight", r"vision/blocks_\1/ln1/scale", None),
    (r"vision_tower\.blocks\.(\d+)\.norm1\.bias", r"vision/blocks_\1/ln1/bias", None),
    (r"vision_tower\.blocks\.(\d+)\.norm2\.weight", r"vision/blocks_\1/ln2/scale", None),
    (r"vision_tower\.blocks\.(\d+)\.norm2\.bias", r"vision/blocks_\1/ln2/bias", None),
    (r"vision_tower\.blocks\.(\d+)\.mlp\.fc1\.weight", r"vision/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"vision_tower\.blocks\.(\d+)\.mlp\.fc1\.bias", r"vision/blocks_\1/mlp/fc1/bias", None),
    (r"vision_tower\.blocks\.(\d+)\.mlp\.fc2\.weight", r"vision/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"vision_tower\.blocks\.(\d+)\.mlp\.fc2\.bias", r"vision/blocks_\1/mlp/fc2/bias", None),
    (r"vision_tower\.post_norm\.weight", r"vision/post_ln/scale", None),
    (r"vision_tower\.post_norm\.bias", r"vision/post_ln/bias", None),
    (r"multi_modal_projector\.linear_1\.weight", r"vision/proj_fc1/kernel", linear_kernel),
    (r"multi_modal_projector\.linear_1\.bias", r"vision/proj_fc1/bias", None),
    (r"multi_modal_projector\.linear_2\.weight", r"vision/proj_fc2/kernel", linear_kernel),
    (r"multi_modal_projector\.linear_2\.bias", r"vision/proj_fc2/bias", None),
    # HF-CLIP-style vision tower naming (llava checkpoints that embed a
    # CLIPVisionModel): map encoder layers onto the same block tree.
    (r"vision_tower\.vision_model\.embeddings\.patch_embedding\.weight", r"vision/patch_embed/kernel", conv_kernel),
    (r"vision_tower\.vision_model\.embeddings\.patch_embedding\.bias", r"vision/patch_embed/bias", None),
    (r"vision_tower\.vision_model\.embeddings\.position_embedding\.weight", r"vision/position_embedding", None),
    (rf"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.self_attn\.{_QKV}\.weight", r"vision/blocks_\1/attn/\2/kernel", linear_kernel),
    (rf"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.self_attn\.{_QKV}\.bias", r"vision/blocks_\1/attn/\2/bias", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.self_attn\.out_proj\.weight", r"vision/blocks_\1/attn/out_proj/kernel", linear_kernel),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.self_attn\.out_proj\.bias", r"vision/blocks_\1/attn/out_proj/bias", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.layer_norm1\.weight", r"vision/blocks_\1/ln1/scale", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.layer_norm1\.bias", r"vision/blocks_\1/ln1/bias", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.layer_norm2\.weight", r"vision/blocks_\1/ln2/scale", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.layer_norm2\.bias", r"vision/blocks_\1/ln2/bias", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.mlp\.fc1\.weight", r"vision/blocks_\1/mlp/fc1/kernel", linear_kernel),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.mlp\.fc1\.bias", r"vision/blocks_\1/mlp/fc1/bias", None),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.mlp\.fc2\.weight", r"vision/blocks_\1/mlp/fc2/kernel", linear_kernel),
    (r"vision_tower\.vision_model\.encoder\.layers\.(\d+)\.mlp\.fc2\.bias", r"vision/blocks_\1/mlp/fc2/bias", None),
    (r"vision_tower\.vision_model\.post_layernorm\.weight", r"vision/post_ln/scale", None),
    (r"vision_tower\.vision_model\.post_layernorm\.bias", r"vision/post_ln/bias", None),
]

DROP = [
    r"rotary_emb\.inv_freq$",
    r"position_ids$",
    r"vision_tower\.vision_model\.embeddings\.class_embedding",
    r"vision_tower\.vision_model\.pre_layrnorm\.",
]


_EXPERT_BANKS = {
    "__expert_gate__": "w_gate",
    "__expert_up__": "w_up",
    "__expert_down__": "w_down",
}


def _stack_experts(flat: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Collapse ``.../mlp/__expert_gate__/<i>`` leaves into one stacked
    ``.../mlp/w_gate`` bank per layer (``[E, ...]``, expert index 0..E-1
    on the leading dim — the layout ``MoEFFN`` and the ``expert``-axis
    sharding rules expect)."""
    groups: dict[tuple[str, str], dict[int, np.ndarray]] = {}
    out: dict[str, np.ndarray] = {}
    for key, val in flat.items():
        parts = key.split("/")
        if len(parts) >= 2 and parts[-2] in _EXPERT_BANKS:
            prefix = "/".join(parts[:-2])
            groups.setdefault((prefix, parts[-2]), {})[int(parts[-1])] = val
        else:
            out[key] = val
    for (prefix, marker), members in groups.items():
        n = len(members)
        if sorted(members) != list(range(n)):
            raise ValueError(
                f"{prefix}/{marker}: non-contiguous expert indices {sorted(members)}"
            )
        out[f"{prefix}/{_EXPERT_BANKS[marker]}"] = np.stack(
            [members[i] for i in range(n)], axis=0
        )
    return out


#: decoder projections QDense replaces when ``weight_quant="int8"`` — must
#: stay in lockstep with modeling._dense call sites (attn q/k/v/o, SwiGLU
#: gate/up/down incl. the MoE shared expert, untied lm_head). MoE expert
#: banks (w_*), router, embeddings, and norms stay full precision.
_QUANT_KERNEL = re.compile(
    r"^decoder/.*(q_proj|k_proj|v_proj|o_proj|gate_proj|up_proj|down_proj|lm_head)/kernel$"
)


def quantize_decoder_int8(params: dict) -> dict:
    """Weight-only int8 for the decoder projections (see
    ``ops.quant.quantize_tree_int8`` for the mechanics; apply AFTER the
    dtype-policy cast so the grid is computed from serving weights)."""
    from ...ops.quant import quantize_tree_int8

    return quantize_tree_int8(params, _QUANT_KERNEL, "decoder")


def convert_vlm_checkpoint(
    state: dict[str, np.ndarray],
    init_params: dict | None = None,
    tie_word_embeddings: bool = True,
) -> dict:
    """Normalize prefixes (``language_model.`` wrappers), convert, and gate
    against the init tree. Native (``/``-pathed) checkpoints pass through."""
    if is_native_checkpoint(state):
        params = split_collections(state)["params"]
        if init_params is not None:
            assert_tree_shapes(params, init_params)
        return params
    normalized: dict[str, np.ndarray] = {}
    for key, val in state.items():
        key = key.removeprefix("language_model.")
        if key.startswith("model.vision_tower."):
            key = key.removeprefix("model.")
        normalized[key] = val
    drop = list(DROP)
    if tie_word_embeddings:
        drop.append(r"^lm_head\.weight$")
    flat = apply_rules(normalized, DECODER_RULES + VISION_RULES, drop=drop)
    params = unflatten(_stack_experts(flat))
    if init_params is not None:
        assert_tree_shapes(params, init_params)
    return params
