"""Install orchestrator: async task machine provisioning a runtime env.

Reference equivalent: ``InstallOrchestrator`` (micromamba download -> env
create -> driver install -> wheel install -> verify,
``lumen-app/src/lumen_app/services/install_orchestrator.py:33-819``).

TPU VMs ship python+jax in the image, so the plan here is: python check ->
[optional venv create] -> [optional pip install] -> import verify ->
[optional model download]. Steps run as subprocesses with their output
bridged into the app log broadcast; cancellation kills the running step and
(matching the reference's cache-wipe semantics,
``install_orchestrator.py:710-763``) clears the partially-populated cache
dir when requested.
"""

from __future__ import annotations

import asyncio
import logging
import shutil
import sys
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from pathlib import Path

logger = logging.getLogger(__name__)

VERIFY_IMPORTS = ["jax", "flax", "optax", "numpy", "grpc", "lumen_tpu"]


class StepStatus(str, Enum):
    PENDING = "pending"
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"
    SKIPPED = "skipped"
    CANCELLED = "cancelled"


@dataclass
class InstallStep:
    name: str
    status: StepStatus = StepStatus.PENDING
    detail: str = ""

    def as_dict(self) -> dict:
        return {"name": self.name, "status": self.status.value, "detail": self.detail}


@dataclass
class InstallOptions:
    venv_path: str | None = None  # None -> use the current interpreter env
    packages: list[str] = field(default_factory=list)  # extra pip installs
    config_path: str | None = None  # when set, download models for it
    cache_dir: str | None = None  # wiped on cancellation (reference parity)
    verify_imports: list[str] = field(default_factory=lambda: list(VERIFY_IMPORTS))
    #: deployment region; cn selects a PyPI mirror for the pip step
    #: (reference MirrorSelector, ``utils/package_resolver.py:19-321``)
    region: str = "other"
    #: package names resolved from the project's GitHub releases (wheel
    #: assets, mirror-aware) and installed from the downloaded files —
    #: reference GitHubPackageResolver flow
    release_packages: list[str] = field(default_factory=list)


@dataclass
class InstallTask:
    task_id: str
    options: InstallOptions
    steps: list[InstallStep]
    status: StepStatus = StepStatus.PENDING
    error: str | None = None
    #: bounded KEEP-RECENT history behind GET /install/logs (clients poll
    #: for current progress/failures, so the newest lines must survive)
    log_lines: "deque[str]" = field(default_factory=lambda: deque(maxlen=2000))
    created_at: float = field(default_factory=time.time)
    _proc: asyncio.subprocess.Process | None = None
    _cancelled: bool = False
    #: local wheel paths produced by the resolve_release_wheels step
    _resolved_wheels: list[str] = field(default_factory=list)
    #: resolved (expanduser'd) cache dir this install CREATED, or None when
    #: it pre-existed / wasn't requested — cancellation may only wipe a dir
    #: this install itself made, never a pre-existing path the
    #: (unauthenticated) API request happened to name
    _owned_cache_dir: Path | None = None

    @property
    def progress(self) -> int:
        """% of steps finished (reference ``install_orchestrator.py:640-645``)."""
        done = sum(
            1
            for s in self.steps
            if s.status in (StepStatus.COMPLETED, StepStatus.SKIPPED)
        )
        return int(100 * done / max(len(self.steps), 1))

    def as_dict(self) -> dict:
        return {
            "task_id": self.task_id,
            "status": self.status.value,
            "progress": self.progress,
            "steps": [s.as_dict() for s in self.steps],
            "error": self.error,
            "created_at": self.created_at,
        }


class InstallOrchestrator:
    def __init__(self, state) -> None:
        self.state = state  # AppState (log broadcast + task store)

    # -- public API -------------------------------------------------------

    def create_task(self, options: InstallOptions) -> InstallTask:
        steps = [InstallStep("check_python")]
        if options.venv_path:
            steps.append(InstallStep("create_venv"))
        if options.release_packages:
            steps.append(InstallStep("resolve_release_wheels"))
        if options.packages or options.release_packages:
            steps.append(InstallStep("install_packages"))
        steps.append(InstallStep("verify_imports"))
        if options.config_path:
            steps.append(InstallStep("download_models"))
        task = InstallTask(task_id=uuid.uuid4().hex[:12], options=options, steps=steps)
        if options.cache_dir:
            cache = Path(options.cache_dir).expanduser()
            if not cache.exists():
                # Create the dir NOW and stamp ownership, so the
                # cancellation wipe has an unambiguous claim: it removes
                # only a dir this task made (no check-then-delete window in
                # which another process's dir could appear at the path).
                cache.mkdir(parents=True)
                (cache / f".lumen-install-{task.task_id}").touch()
                task._owned_cache_dir = cache
        self.state.install_tasks[task.task_id] = task
        return task

    async def run(self, task: InstallTask) -> InstallTask:
        task.status = StepStatus.RUNNING
        self._log(task, f"install task {task.task_id} started ({len(task.steps)} steps)")
        try:
            for step in task.steps:
                if task._cancelled:
                    raise asyncio.CancelledError
                step.status = StepStatus.RUNNING
                self._log(task, f"step {step.name}...")
                await getattr(self, f"_step_{step.name}")(task, step)
                if step.status == StepStatus.RUNNING:
                    step.status = StepStatus.COMPLETED
                self._log(task, f"step {step.name}: {step.status.value}")
            task.status = StepStatus.COMPLETED
            self._log(task, f"install task {task.task_id} completed")
        except asyncio.CancelledError:
            await self._handle_cancellation(task)
        except Exception as e:  # noqa: BLE001 - recorded on the task
            task.status = StepStatus.FAILED
            task.error = str(e)
            for s in task.steps:
                if s.status == StepStatus.RUNNING:
                    s.status = StepStatus.FAILED
                    s.detail = str(e)
            self._log(task, f"install task failed: {e}", level="error")
        finally:
            if task.status != StepStatus.CANCELLED:
                self._drop_ownership_marker(task)
        return task

    async def cancel(self, task: InstallTask) -> None:
        task._cancelled = True
        if task._proc and task._proc.returncode is None:
            task._proc.kill()

    # -- steps ------------------------------------------------------------

    async def _step_check_python(self, task: InstallTask, step: InstallStep) -> None:
        v = sys.version_info
        step.detail = f"python {v.major}.{v.minor}.{v.micro}"
        if (v.major, v.minor) < (3, 11):
            raise RuntimeError(f"python >= 3.11 required, found {step.detail}")

    async def _step_create_venv(self, task: InstallTask, step: InstallStep) -> None:
        path = task.options.venv_path
        rc, out = await self._exec(task, sys.executable, "-m", "venv", "--system-site-packages", path)
        if rc != 0:
            raise RuntimeError(f"venv creation failed: {out[-500:]}")
        step.detail = path

    async def _step_resolve_release_wheels(self, task: InstallTask, step: InstallStep) -> None:
        """Resolve wheels from the project's GitHub releases with CN mirror
        rewriting (reference GitHubPackageResolver,
        ``utils/package_resolver.py:61-321``); downloaded paths are fed to
        the pip step as local files."""
        from lumen_tpu.app.package_resolver import ReleaseWheelResolver

        resolver = ReleaseWheelResolver(region=task.options.region)
        dest = Path(task.options.cache_dir or "~/.lumen-tpu").expanduser() / "wheels"

        def log_from_worker(msg: str) -> None:
            # Runs inside asyncio.to_thread: deque.append is thread-safe,
            # but WS fan-out must hop back to the loop.
            task.log_lines.append(msg)
            self.state.broadcast_log_threadsafe(msg, source="install")

        wheels = await asyncio.to_thread(
            resolver.fetch_packages,
            list(task.options.release_packages),
            dest,
            log_from_worker,
        )
        task._resolved_wheels = [str(w) for w in wheels]
        step.detail = ", ".join(w.name for w in wheels)

    async def _step_install_packages(self, task: InstallTask, step: InstallStep) -> None:
        from lumen_tpu.app.package_resolver import pip_index_args

        python = self._env_python(task)
        # Mirror-first with the official index as fallback, so a mirror
        # outage degrades instead of failing the install.
        index_args = (
            pip_index_args(task.options.region)
            if task.options.region == "cn"
            else []
        )
        targets = list(task._resolved_wheels) + list(task.options.packages)
        rc, out = await self._exec(
            task, python, "-m", "pip", "install", *index_args, *targets
        )
        if rc != 0:
            raise RuntimeError(f"pip install failed: {out[-500:]}")
        step.detail = ", ".join(targets)

    async def _step_verify_imports(self, task: InstallTask, step: InstallStep) -> None:
        """Reference ``InstallationVerifier.verify_imports`` (python -c in
        the target env, ``utils/installation/verifier.py:11-95``)."""
        mods = task.options.verify_imports
        code = "import importlib,sys\n" + "\n".join(
            f"importlib.import_module({m!r})" for m in mods
        )
        rc, out = await self._exec(task, self._env_python(task), "-c", code)
        if rc != 0:
            raise RuntimeError(f"import verification failed: {out[-500:]}")
        step.detail = f"{len(mods)} modules importable"

    async def _step_download_models(self, task: InstallTask, step: InstallStep) -> None:
        code = (
            "from lumen_tpu.core.config import load_config\n"
            "from lumen_tpu.core.downloader import Downloader\n"
            f"report = Downloader(load_config({task.options.config_path!r})).download_all()\n"
            "import sys; sys.exit(0 if report.ok else 1)\n"
        )
        rc, out = await self._exec(task, self._env_python(task), "-c", code)
        if rc != 0:
            raise RuntimeError(f"model download failed: {out[-800:]}")
        step.detail = "models cached"

    # -- helpers ----------------------------------------------------------

    def _env_python(self, task: InstallTask) -> str:
        if task.options.venv_path:
            return f"{task.options.venv_path}/bin/python"
        return sys.executable

    async def _exec(self, task: InstallTask, *cmd: str) -> tuple[int, str]:
        proc = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            limit=1 << 20,  # pip/downloader can emit very long lines
        )
        task._proc = proc
        chunks: list[str] = []
        assert proc.stdout is not None
        async for raw in proc.stdout:
            line = raw.decode(errors="replace").rstrip()
            chunks.append(line)
            self._log(task, line, source="install")
        await proc.wait()
        task._proc = None
        if task._cancelled:
            raise asyncio.CancelledError
        return proc.returncode or 0, "\n".join(chunks)

    async def _handle_cancellation(self, task: InstallTask) -> None:
        task.status = StepStatus.CANCELLED
        for s in task.steps:
            if s.status in (StepStatus.RUNNING, StepStatus.PENDING):
                s.status = StepStatus.CANCELLED
        owned = task._owned_cache_dir
        # Reference semantics: cancellation wipes the partial cache
        # (``install_orchestrator.py:710-763``) — but only a dir this
        # install created (ownership marker stamped in create_task). A
        # pre-existing request-supplied path must survive: the control
        # plane is unauthenticated when bound beyond loopback, and rmtree
        # on an arbitrary path is a deletion primitive.
        if owned is not None and (owned / f".lumen-install-{task.task_id}").exists():
            await asyncio.to_thread(shutil.rmtree, owned, True)
            self._log(task, f"cancelled; cleared cache dir {owned}")
        elif task.options.cache_dir:
            self._log(
                task,
                f"cancelled; left cache dir {task.options.cache_dir} in place "
                "(not created by this install)",
            )
        else:
            self._log(task, "cancelled")

    def _drop_ownership_marker(self, task: InstallTask) -> None:
        """Terminal non-cancelled state: the dir stays, so remove the
        hidden ownership marker rather than leaking it into the user's
        model cache."""
        if task._owned_cache_dir is not None:
            marker = task._owned_cache_dir / f".lumen-install-{task.task_id}"
            try:
                marker.unlink(missing_ok=True)
            except OSError:  # cache dir vanished underneath us — nothing to clean
                pass
            task._owned_cache_dir = None

    def _log(self, task: InstallTask, message: str, level: str = "info", source: str = "install") -> None:
        logger.log(logging.ERROR if level == "error" else logging.INFO, "[%s] %s", task.task_id, message)
        task.log_lines.append(message)  # deque(maxlen): oldest drop first
        self.state.broadcast_log(message, level=level, source=source)
