"""TPU environment checker + pip mirror selection for the control plane.

Reference equivalents: the per-accelerator driver/environment probes
(``lumen-app/src/lumen_app/utils/env_checker.py:27-826`` — nvidia-smi, NPU,
OpenVINO, CoreML checks) and the CN-aware ``MirrorSelector``
(``lumen-app/src/lumen_app/utils/package_resolver.py:19-321``). On a TPU VM
the questions change: is the jax/libtpu stack importable and
version-coherent, are the TPU device nodes present, is there disk for the
model cache — answered from metadata and the filesystem WITHOUT
initializing a JAX backend (that would claim the chip away from the server
this control plane exists to spawn; see ``app/hardware.py`` for the
subprocess device probe).
"""

from __future__ import annotations

import glob
import os
import shutil
import sys
from dataclasses import dataclass
from importlib import metadata

#: TPU device nodes by driver flavor: older gen expose /dev/accel*, newer
#: VMs attach chips through VFIO.
_DEVICE_GLOBS = ("/dev/accel*", "/dev/vfio/*")



@dataclass
class Check:
    name: str
    ok: bool
    detail: str
    required: bool = True

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "detail": self.detail,
            "required": self.required,
        }


def _version_of(*dists: str) -> str | None:
    for dist in dists:
        try:
            return f"{dist} {metadata.version(dist)}"
        except metadata.PackageNotFoundError:
            continue
    return None


def check_python() -> Check:
    v = sys.version_info
    detail = f"python {v.major}.{v.minor}.{v.micro}"
    return Check("python", (v.major, v.minor) >= (3, 11), detail)


def check_jax_stack() -> list[Check]:
    """Importability/version of the compute stack, from dist metadata (no
    imports: importing jax in the control plane is harmless, but keeping
    this metadata-only makes it safe to call from ANY process)."""
    out = []
    for name, dists, required in (
        ("jax", ("jax",), True),
        ("jaxlib", ("jaxlib",), True),
        ("flax", ("flax",), True),
        ("optax", ("optax",), True),
        ("orbax-checkpoint", ("orbax-checkpoint", "orbax"), False),
        ("grpcio", ("grpcio",), True),
        ("safetensors", ("safetensors",), True),
    ):
        ver = _version_of(*dists)
        out.append(Check(name, ver is not None, ver or "not installed", required))
    return out


def check_libtpu() -> Check:
    """TPU runtime library: a libtpu dist, an explicit TPU_LIBRARY_PATH, or
    a tunneled/virtual platform (PJRT plugin) all count."""
    ver = _version_of("libtpu", "libtpu-nightly")
    if ver:
        return Check("libtpu", True, ver, required=False)
    path = os.environ.get("TPU_LIBRARY_PATH")
    if path and os.path.exists(path):
        return Check("libtpu", True, f"TPU_LIBRARY_PATH={path}", required=False)
    plugins = [ep.name for ep in metadata.entry_points(group="jax_plugins")]
    if plugins:
        return Check("libtpu", True, f"PJRT plugin(s): {', '.join(plugins)}", required=False)
    return Check("libtpu", False, "no libtpu dist / TPU_LIBRARY_PATH / PJRT plugin", required=False)


def check_tpu_devices() -> Check:
    nodes = [n for pat in _DEVICE_GLOBS for n in sorted(glob.glob(pat))]
    if nodes:
        return Check("tpu_devices", True, ", ".join(nodes[:8]), required=False)
    return Check(
        "tpu_devices",
        False,
        "no /dev/accel* or /dev/vfio nodes (ok for remote/tunneled TPU or CPU dev)",
        required=False,
    )


def check_disk(cache_dir: str, need_gb: float = 10.0) -> Check:
    """Model cache needs room: the reference's full tier pulls several GB
    of weights (``lumen_resources/downloader.py``)."""
    path = os.path.expanduser(cache_dir)
    probe = path
    while probe and not os.path.exists(probe):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    try:
        free_gb = shutil.disk_usage(probe or "/").free / 1e9
    except OSError as e:
        return Check("disk_space", False, f"cannot stat {probe!r}: {e}")
    return Check(
        "disk_space",
        free_gb >= need_gb,
        f"{free_gb:.1f} GB free at {probe} (need ~{need_gb:.0f} GB)",
    )


def environment_report(cache_dir: str = "~/.lumen-tpu", need_gb: float = 10.0) -> dict:
    """Aggregate check report for ``GET /api/v1/hardware/check``. ``ok``
    requires every *required* check; optional ones (device nodes, libtpu)
    inform the wizard without blocking a CPU/remote-TPU setup."""
    checks: list[Check] = [check_python(), *check_jax_stack(), check_libtpu(),
                           check_tpu_devices(), check_disk(cache_dir, need_gb)]
    return {
        "ok": all(c.ok for c in checks if c.required),
        "checks": [c.as_dict() for c in checks],
    }


def pip_index_url(region: str) -> str | None:
    """Region -> preferred PyPI index (None = default). Delegates to the
    package resolver so ONE module owns the mirror policy (the installer's
    pip step uses the same source via ``pip_index_args``)."""
    from lumen_tpu.app.package_resolver import PYPI_OFFICIAL, pypi_indexes

    preferred = pypi_indexes(region)[0]
    return None if preferred == PYPI_OFFICIAL else preferred
