"""Config generation: preset + region + tier -> full ``LumenConfig``.

Reference equivalent: ``Config`` tier builders ``minimal`` (ocr) /
``light_weight`` (ocr+clip+face) / higher (+vlm) and region-aware CLIP model
defaults (``lumen-app/src/lumen_app/services/config.py:299-682``). Model
repo names keep the reference's catalog so the same model hubs serve both.
"""

from __future__ import annotations

from typing import Any

import yaml

from lumen_tpu.app.presets import PRESETS, DevicePreset
from lumen_tpu.core.config import LumenConfig, validate_config_dict

TIERS = ("minimal", "light_weight", "full")

# Region-aware CLIP default (reference config.py:299-312).
CLIP_MODELS = {"cn": "CN-CLIP_ViT-B-16", "other": "MobileCLIP2-S2"}
FACE_MODEL = "buffalo_l"
OCR_MODEL = "PP-OCRv5_mobile"
VLM_MODEL = "FastVLM-0.5B"

SERVICE_REGISTRY_CLASSES = {
    "clip": "lumen_tpu.serving.services.clip_service.ClipService",
    "face": "lumen_tpu.serving.services.face_service.FaceService",
    "ocr": "lumen_tpu.serving.services.ocr_service.OcrService",
    "vlm": "lumen_tpu.serving.services.vlm_service.VlmService",
}

TIER_SERVICES = {
    "minimal": ["ocr"],
    "light_weight": ["ocr", "clip", "face"],
    "full": ["ocr", "clip", "face", "vlm"],
}


def _service_block(family: str, preset: DevicePreset, region: str) -> dict[str, Any]:
    """Per-family backend settings sized to the preset's chip generation
    (the reference carries one batch size per device preset,
    ``config.py:41-279``; TPU presets size each family separately because
    their device programs differ in arithmetic intensity)."""
    models: dict[str, Any]
    settings: dict[str, Any] = {
        "dtype": preset.dtype,
        "mesh": {"axes": dict(preset.mesh_axes)},
        "max_batch_latency_ms": preset.max_batch_latency_ms,
    }
    if family == "clip":
        models = {"clip": {"model": CLIP_MODELS[region], "runtime": "jax"}}
        settings["batch_size"] = preset.batch_size
    elif family == "face":
        models = {"face": {"model": FACE_MODEL, "runtime": "jax"}}
        settings["batch_size"] = preset.face_batch
    elif family == "ocr":
        models = {"ocr": {"model": OCR_MODEL, "runtime": "jax"}}
        settings["batch_size"] = preset.ocr_batch
        settings["batch_buckets"] = list(preset.ocr_det_buckets)
    elif family == "vlm":
        models = {"vlm": {"model": VLM_MODEL, "runtime": "jax"}}
        settings["batch_size"] = preset.vlm_gen_batch
        settings["batch_buckets"] = list(preset.vlm_prefill_buckets)
    else:
        raise ValueError(f"unknown service family {family!r}")
    return {
        "enabled": True,
        "package": f"lumen_tpu.serving.services.{family}_service",
        "import_info": {"registry_class": SERVICE_REGISTRY_CLASSES[family]},
        "backend_settings": settings,
        "models": models,
    }


def generate_config(
    preset_name: str,
    tier: str = "light_weight",
    region: str = "other",
    cache_dir: str = "~/.lumen-tpu",
    port: int = 50051,
    mdns: bool = True,
) -> LumenConfig:
    if preset_name not in PRESETS:
        raise ValueError(f"unknown preset {preset_name!r}; have {sorted(PRESETS)}")
    if tier not in TIERS:
        raise ValueError(f"unknown tier {tier!r}; have {TIERS}")
    if region not in ("cn", "other"):
        raise ValueError(f"region must be 'cn' or 'other', got {region!r}")
    preset = PRESETS[preset_name]
    # CPU preset can't comfortably run the VLM tier (reference tier gating).
    if tier == "full" and preset.max_tier != "full":
        raise ValueError(f"preset {preset_name!r} supports at most tier {preset.max_tier!r}")
    families = TIER_SERVICES[tier]
    raw = {
        "metadata": {"version": "1.0.0", "region": region, "cache_dir": cache_dir},
        "deployment": {"mode": "hub", "services": list(families)},
        "server": {
            "port": port,
            "host": "0.0.0.0",
            "mdns": {"enabled": mdns, "service_name": "lumen-tpu"},
        },
        "services": {f: _service_block(f, preset, region) for f in families},
    }
    return validate_config_dict(raw)


def config_to_yaml(config: LumenConfig) -> str:
    return yaml.safe_dump(config.model_dump(exclude_none=True), sort_keys=False)
