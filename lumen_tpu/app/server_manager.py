"""Managed gRPC server subprocess (reference ``services/server_manager.py``).

Spawns ``python -m lumen_tpu.serving.server --config <path>`` (the process
boundary of SURVEY.md §3.5), captures merged stdout/stderr into the app log
broadcast, waits for the readiness line, health-checks over gRPC, and
supports stop (SIGTERM -> kill) and restart.
"""

from __future__ import annotations

import asyncio
import logging
import re
import signal
import sys
import time
from enum import Enum

logger = logging.getLogger(__name__)

# Emitted by lumen_tpu.serving.server.serve() once the port is bound.
READY_RE = re.compile(r"serving \d+ service\(s\) on (\S+):(\d+)")
# Emitted by the observability sidecar when --metrics-port is passed.
METRICS_RE = re.compile(r"metrics endpoint on http://(\S+):(\d+)/metrics")


class ServerStatus(str, Enum):
    STOPPED = "stopped"
    STARTING = "starting"
    RUNNING = "running"
    FAILED = "failed"


class ServerManager:
    #: generous StreamReader limit — one over-long child log line must
    #: not kill the capture task before the readiness line is seen
    STREAM_LIMIT = 1 << 20

    def __init__(self, state, ready_timeout: float = 600.0) -> None:
        self.state = state
        self.ready_timeout = ready_timeout  # first jit compile can be slow
        self.proc: asyncio.subprocess.Process | None = None
        self.status = ServerStatus.STOPPED
        self.port: int | None = None
        self.metrics_port: int | None = None
        self.config_path: str | None = None
        self.extra_args: list[str] = []
        self.started_at: float | None = None
        self.exit_code: int | None = None  # last child exit (crash triage)
        self._ready = asyncio.Event()
        self._capture_task: asyncio.Task | None = None
        # Serializes start/stop/restart: two concurrent starts must not both
        # pass the running-check and leak an unmanaged child.
        self._lifecycle = asyncio.Lock()

    # -- lifecycle --------------------------------------------------------

    async def start(self, config_path: str, extra_args: list[str] | None = None) -> dict:
        async with self._lifecycle:
            return await self._start_locked(config_path, extra_args)

    async def _start_locked(self, config_path: str, extra_args: list[str] | None) -> dict:
        if self.proc and self.proc.returncode is None:
            raise RuntimeError("server already running; stop it first")
        self._ready.clear()
        self.status = ServerStatus.STARTING
        self.config_path = config_path
        self.extra_args = list(extra_args or [])
        self.port = None
        self.metrics_port = None
        self.exit_code = None
        cmd = [sys.executable, "-m", "lumen_tpu.serving.server", "--config", config_path]
        cmd += self.extra_args
        self.state.broadcast_log(f"starting server: {' '.join(cmd)}", source="server")
        self.proc = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            limit=self.STREAM_LIMIT,
        )
        self.started_at = time.time()
        self._capture_task = asyncio.ensure_future(self._capture_logs())
        try:
            await asyncio.wait_for(self._ready.wait(), timeout=self.ready_timeout)
        except asyncio.TimeoutError:
            self.status = ServerStatus.FAILED
            await self._stop_locked(force=True)
            raise RuntimeError(f"server not ready within {self.ready_timeout}s") from None
        if self.status != ServerStatus.RUNNING:  # process died before ready
            raise RuntimeError("server exited during startup; see logs")
        return self.info()

    async def _capture_logs(self) -> None:
        """Readiness scan + log bridge (reference ``server_manager.py:317-382``)."""
        assert self.proc and self.proc.stdout
        async for raw in self.proc.stdout:
            line = raw.decode(errors="replace").rstrip()
            self.state.broadcast_log(line, source="server")
            m = READY_RE.search(line)
            if m:
                self.port = int(m.group(2))
                self.status = ServerStatus.RUNNING
                self._ready.set()
            m = METRICS_RE.search(line)
            if m:
                self.metrics_port = int(m.group(2))
        # EOF: process exited.
        rc = await self.proc.wait()
        self.exit_code = rc
        if self.status in (ServerStatus.STARTING, ServerStatus.RUNNING):
            self.status = ServerStatus.FAILED if rc else ServerStatus.STOPPED
        self.state.broadcast_log(f"server exited with code {rc}", source="server")
        self._ready.set()  # unblock any waiter

    async def stop(self, force: bool = False, grace: float = 10.0) -> None:
        async with self._lifecycle:
            await self._stop_locked(force=force, grace=grace)

    async def _stop_locked(self, force: bool = False, grace: float = 10.0) -> None:
        if not self.proc:
            self.status = ServerStatus.STOPPED
            return
        if self.proc.returncode is None:
            self.proc.send_signal(signal.SIGKILL if force else signal.SIGTERM)
            try:
                await asyncio.wait_for(self.proc.wait(), timeout=grace)
            except asyncio.TimeoutError:
                self.proc.kill()
                await self.proc.wait()
        if self._capture_task:
            await self._capture_task
            self._capture_task = None
        self.proc = None
        self.port = None
        self.metrics_port = None
        self.status = ServerStatus.STOPPED

    async def restart(self) -> dict:
        async with self._lifecycle:
            if not self.config_path:
                raise RuntimeError("no previous start to restart")
            path, args = self.config_path, list(self.extra_args)
            await self._stop_locked()
            return await self._start_locked(path, args)

    # -- introspection ----------------------------------------------------

    async def health_check(self, timeout: float = 5.0) -> bool:
        """gRPC ``Health`` probe against the child (requires RUNNING)."""
        if self.status != ServerStatus.RUNNING or not self.port:
            return False

        def _probe() -> bool:
            import grpc
            from google.protobuf import empty_pb2

            from lumen_tpu.serving.proto import ml_service_pb2_grpc

            with grpc.insecure_channel(f"127.0.0.1:{self.port}") as chan:
                stub = ml_service_pb2_grpc.InferenceStub(chan)
                # Health returns Empty and signals unhealthiness via RPC
                # status (proto contract: ml_service.proto:31).
                stub.Health(empty_pb2.Empty(), timeout=timeout)
                return True

        try:
            return await asyncio.to_thread(_probe)
        except Exception:  # noqa: BLE001 - any RPC failure is "unhealthy"
            return False

    async def fetch_metrics(self, timeout: float = 5.0) -> dict | None:
        """Snapshot of the child's per-task latency metrics (requires the
        server to have been started with --metrics-port)."""
        if self.status != ServerStatus.RUNNING or not self.metrics_port:
            return None

        def _fetch() -> dict:
            import json
            import urllib.request

            url = f"http://127.0.0.1:{self.metrics_port}/metrics.json"
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return json.loads(r.read().decode())

        try:
            return await asyncio.to_thread(_fetch)
        except Exception:  # noqa: BLE001 - metrics are best-effort
            return None

    def info(self) -> dict:
        return {
            "status": self.status.value,
            "pid": self.proc.pid if self.proc and self.proc.returncode is None else None,
            "port": self.port,
            "metrics_port": self.metrics_port,
            "config_path": self.config_path,
            "exit_code": self.exit_code,
            "uptime_s": round(time.time() - self.started_at, 1) if self.started_at and self.status == ServerStatus.RUNNING else None,
        }
