"""Control-plane application (reference: ``lumen-app``, SURVEY.md §2.7).

A local web app that walks a user from bare machine to running inference
server: hardware detection -> config generation -> environment install ->
server supervision, over REST (``/api/v1/{config,hardware,install,server}``)
plus a WebSocket log stream (``/ws/logs``).

TPU-flavored rebuild decisions:
- presets describe TPU topologies (v5e/v6e/CPU meshes), not CUDA/CoreML
  driver stacks (reference ``services/config.py:41-279``);
- the installer provisions a plain ``venv`` and verifies imports — the
  reference's micromamba machinery (``utils/installation/``) is unnecessary
  on TPU VMs where python + jax ship with the image;
- the HTTP layer is aiohttp (no FastAPI dependency in the image).
"""

from lumen_tpu.app.state import AppState

__all__ = ["AppState"]
