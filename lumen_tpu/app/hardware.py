"""Hardware detection for the control plane.

Reference equivalent: per-accelerator subprocess probes (nvidia-smi, NPU
driver checks, ``utils/env_checker.py:60-457``). On a TPU VM the authority
is JAX itself: the platform/device-kind/count of ``jax.devices()``, read in
a SUBPROCESS so the control plane never holds the TPU (initializing a
backend in-process would lock the chip away from the server it spawns).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
from dataclasses import dataclass, field

from lumen_tpu.app.presets import (
    chip_spec,
    detect_preset,
    parse_generation,
    supported_presets,
)

logger = logging.getLogger(__name__)

_PROBE = r"""
import json
try:
    import jax
    devs = jax.devices()
    print(json.dumps({
        "platform": devs[0].platform if devs else "none",
        "device_kind": devs[0].device_kind if devs else "",
        "device_count": len(devs),
        "process_count": jax.process_count(),
    }))
except Exception as e:
    print(json.dumps({"platform": "none", "device_kind": "", "device_count": 0,
                      "process_count": 0, "error": str(e)}))
"""


@dataclass
class HardwareInfo:
    platform: str  # "tpu" | "cpu" | "none"
    device_kind: str
    device_count: int
    process_count: int = 1
    cpu_count: int = 1
    memory_gb: float = 0.0
    error: str | None = None
    env: dict[str, str] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "platform": self.platform,
            "device_kind": self.device_kind,
            "device_count": self.device_count,
            "process_count": self.process_count,
            "cpu_count": self.cpu_count,
            "memory_gb": round(self.memory_gb, 2),
            "error": self.error,
            "env": self.env,
        }


def _host_memory_gb() -> float:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal"):
                    return int(line.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


def _env_declared_tpu() -> tuple[str, str, int] | None:
    """(platform, device_kind, device_count) from environment declarations
    alone — used when the live probe can't answer. On a shared pool,
    backend init BLOCKS while no chip is free, so a probe timeout on a TPU
    host means 'TPU present but busy', not 'no TPU'."""
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    if accel:
        # Topology suffix carries the slice size ("v5litepod-8" -> 8); a
        # busy 8-chip slice must not get a 1-chip preset recommendation.
        count = 1
        tail = accel.rsplit("-", 1)
        if len(tail) == 2 and tail[1].isdigit():
            count = max(1, int(tail[1]))
        return "tpu", accel, count
    platforms = os.environ.get("JAX_PLATFORMS", "").split(",")
    if os.environ.get("PALLAS_AXON_POOL_IPS") or "axon" in platforms:
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        # "TPU {gen}" round-trips through presets.parse_generation for
        # every known generation; the axon tunnel claims one chip.
        return "tpu", f"TPU {gen}", 1
    return None


def detect_hardware(timeout: float = 60.0) -> HardwareInfo:
    """Probe accelerators in a subprocess; never initializes a backend in
    the control-plane process. A probe that times out while the
    environment declares a TPU (pool busy — the claim blocks) still
    reports the TPU with device_count=1 and the timeout recorded in
    ``error``, so preset auto-detection doesn't regress to the cpu tier
    on a momentarily-contended host."""
    probe = {"platform": "none", "device_kind": "", "device_count": 0, "process_count": 0}
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True,
            text=True,
            timeout=timeout,
            env={**os.environ},
        )
        for line in (out.stdout or "").strip().splitlines():
            try:
                probe = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    except subprocess.TimeoutExpired:
        # Only a TIMEOUT means "pool busy" — a spawn failure (OSError
        # below) keeps its real message instead of a misdiagnosis.
        declared = _env_declared_tpu()
        if declared is not None:
            probe["platform"], probe["device_kind"], probe["device_count"] = declared
            probe["error"] = (
                f"live probe timed out after {timeout:.0f}s (chip pool busy); "
                "platform taken from environment declaration"
            )
        else:
            probe["error"] = f"probe timed out after {timeout:.0f}s"
    except OSError as e:
        probe["error"] = str(e)

    tpu_env = {
        k: v
        for k, v in os.environ.items()
        if k.startswith(("TPU_", "JAX_", "PALLAS_")) and "KEY" not in k and "TOKEN" not in k
    }
    return HardwareInfo(
        platform=probe.get("platform", "none"),
        device_kind=probe.get("device_kind", ""),
        device_count=int(probe.get("device_count", 0)),
        process_count=int(probe.get("process_count", 0) or 1),
        cpu_count=os.cpu_count() or 1,
        memory_gb=_host_memory_gb(),
        error=probe.get("error"),
        env=tpu_env,
    )


def hardware_report(hw: HardwareInfo | None = None) -> dict:
    """Detection + the preset recommendation the wizard shows."""
    hw = hw or detect_hardware()
    # A proxied PJRT plugin (e.g. the axon tunnel) reports its own platform
    # name while device_kind still carries the real TPU generation string;
    # treat anything with a recognizable TPU kind as TPU.
    plat = (
        "tpu"
        if hw.platform in ("tpu", "axon") or parse_generation(hw.device_kind)
        else "cpu"
    )
    supported = supported_presets(plat, hw.device_count, hw.device_kind)
    best = supported[0] if supported else detect_preset(plat, hw.device_count)
    generation = parse_generation(hw.device_kind)
    spec = chip_spec(generation) if generation else None
    return {
        "hardware": hw.as_dict(),
        "generation": generation,
        "chip": (
            {
                "hbm_gb": spec.hbm_gb,
                "bf16_tflops": spec.bf16_tflops,
                "slice_bf16_tflops": spec.bf16_tflops * max(hw.device_count, 1),
            }
            if spec
            else None
        ),
        "recommended_preset": best.name,
        "supported_presets": [p.name for p in supported],
    }
