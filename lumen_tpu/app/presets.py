"""Hardware presets: TPU topologies instead of GPU driver stacks.

Reference equivalent: 9 ``DeviceConfig`` classmethod presets carrying
runtime + onnx-providers + batch size + micromamba yaml + driver plans
(``lumen-app/src/lumen_app/services/config.py:41-279``) and the
``PresetRegistry`` platform-support/detection-order rules
(``utils/preset_registry.py:16-244``). Here a preset carries what a TPU
deployment actually varies on: chip generation (HBM / peak bf16 FLOPs),
slice topology, mesh axes, compute dtype, and per-service batch + latency
knobs sized to the hardware.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ChipSpec:
    """One TPU generation, keyed by the ``device_kind`` strings JAX
    reports. Peak figures are public per-chip numbers, used for batch
    sizing here and MFU math in ``bench.py``."""

    generation: str
    kind_patterns: tuple[str, ...]  # matched against jax device_kind, lowercased
    hbm_gb: float
    bf16_tflops: float
    base_batch: int  # comfortable per-chip batch the service knobs derive from
    #: measured per-chip CLIP batch override (None -> base_batch); only set
    #: for generations with an on-chip measurement behind the number
    clip_batch: int | None = None


# Ordered so more-specific patterns ("lite") are tested before bare "v5".
CHIP_SPECS: tuple[ChipSpec, ...] = (
    ChipSpec("v6e", ("v6 lite", "v6e"), 32.0, 918.0, base_batch=64),
    # v5e clip_batch=128: a round-3 on-chip run put the ViT-B/32 embed at
    # batch 256 / 5322 images/sec (BASELINE.md; provisional provenance,
    # but the implied 23.5% MFU is exactly where this shape lands on a
    # 197-TFLOP chip), and first principles agree — batch-128 ViT-B/32
    # activations are tens of MB against 16 GB HBM, so 32 was simply
    # starving the MXU. base_batch (which face/OCR batches derive from)
    # stays conservative — those paths haven't been measured on chip yet,
    # and other generations keep the old sizing until measured.
    ChipSpec(
        "v5e", ("v5 lite", "v5litepod", "v5e"), 16.0, 197.0,
        base_batch=32, clip_batch=128,
    ),
    ChipSpec("v5p", ("v5p", "v5"), 95.0, 459.0, base_batch=96),
    ChipSpec("v4", ("v4",), 32.0, 275.0, base_batch=64),
    ChipSpec("v3", ("v3",), 32.0, 123.0, base_batch=32),
    ChipSpec("v2", ("v2",), 16.0, 46.0, base_batch=16),
)


def parse_generation(device_kind: str) -> str | None:
    """``jax.devices()[0].device_kind`` -> generation tag (None if not a
    recognized TPU string)."""
    kind = (device_kind or "").lower()
    if "tpu" not in kind and not kind.startswith("v"):
        return None
    for spec in CHIP_SPECS:
        if any(p in kind for p in spec.kind_patterns):
            return spec.generation
    return None


def chip_spec(generation: str) -> ChipSpec | None:
    for spec in CHIP_SPECS:
        if spec.generation == generation:
            return spec
    return None


@dataclass(frozen=True)
class DevicePreset:
    name: str
    description: str
    platform: str  # "tpu" | "cpu"
    generation: str | None  # chip generation (None = any / cpu)
    chips: int  # devices the mesh expects (0 = use all present)
    mesh_axes: dict[str, int] = field(default_factory=lambda: {"data": -1})
    dtype: str = "bfloat16"
    batch_size: int = 32  # headline (CLIP-class) global batch
    # Per-service knobs (reference presets carry per-device batch sizes;
    # TPU presets also size the static-shape buckets that control compile
    # count and the batching-window latency).
    face_batch: int = 16
    ocr_batch: int = 8
    ocr_det_buckets: tuple[int, ...] = (320, 640, 960)
    vlm_gen_batch: int = 4
    vlm_prefill_buckets: tuple[int, ...] = (64, 128, 256, 512)
    max_batch_latency_ms: float = 5.0
    # Service tiers this preset can comfortably run.
    max_tier: str = "full"


def _tpu_preset(
    name: str,
    generation: str,
    chips: int,
    description: str,
    mesh_axes: dict[str, int] | None = None,
    tier: str = "full",
) -> DevicePreset:
    spec = chip_spec(generation)
    assert spec is not None
    dp = chips
    if mesh_axes and "model" in mesh_axes:
        dp = max(1, chips // mesh_axes["model"])
    return DevicePreset(
        name=name,
        description=description,
        platform="tpu",
        generation=generation,
        chips=chips,
        mesh_axes=dict(mesh_axes or {"data": -1}),
        batch_size=(spec.clip_batch or spec.base_batch) * dp,
        face_batch=max(8, spec.base_batch // 2) * dp,
        ocr_batch=max(4, spec.base_batch // 4),
        vlm_gen_batch=8 if spec.hbm_gb >= 32 else 4,
        # Small-HBM chips trade the longest prompt bucket for KV headroom;
        # the manager additionally drops any bucket that cannot fit its
        # max_seq KV buffer (vlm/manager.py bucket filter).
        vlm_prefill_buckets=(
            (64, 128, 256, 512, 1024) if spec.hbm_gb >= 32 else (64, 128, 256, 512)
        ),
        max_batch_latency_ms=3.0 if spec.bf16_tflops >= 400 else 5.0,
        max_tier=tier,
    )


PRESETS: dict[str, DevicePreset] = {
    p.name: p
    for p in [
        DevicePreset(
            name="cpu",
            description="CPU-only (JAX CPU backend); correctness/dev tier",
            platform="cpu",
            generation=None,
            chips=0,
            dtype="float32",
            batch_size=4,
            face_batch=4,
            ocr_batch=2,
            vlm_gen_batch=2,
            vlm_prefill_buckets=(64, 128),
            max_tier="light_weight",
        ),
        _tpu_preset("tpu_v2_8", "v2", 8, "v2-8 board, data-parallel mesh", tier="light_weight"),
        _tpu_preset("tpu_v3_8", "v3", 8, "v3-8 board, data-parallel mesh"),
        _tpu_preset("tpu_v4_8", "v4", 8, "v4-8 slice, data-parallel mesh"),
        _tpu_preset("tpu_v5e_1", "v5e", 1, "Single v5e chip"),
        _tpu_preset("tpu_v5e_4", "v5e", 4, "v5e-4 slice, data-parallel mesh"),
        _tpu_preset("tpu_v5e_8", "v5e", 8, "v5e-8 slice, data-parallel mesh"),
        _tpu_preset(
            "tpu_v5e_16_dp_tp",
            "v5e",
            16,
            "v5e-16 pod slice, 8-way data x 2-way tensor mesh",
            mesh_axes={"data": -1, "model": 2},
        ),
        _tpu_preset("tpu_v5p_8", "v5p", 8, "v5p-8 slice, data-parallel mesh"),
        _tpu_preset("tpu_v6e_1", "v6e", 1, "Single v6e chip"),
        _tpu_preset("tpu_v6e_8", "v6e", 8, "v6e-8 slice, data-parallel mesh"),
        _tpu_preset(
            "tpu_v6e_16_dp_tp",
            "v6e",
            16,
            "v6e-16 pod slice, 8-way data x 2-way tensor mesh",
            mesh_axes={"data": -1, "model": 2},
        ),
    ]
}

# Order presets are tried during auto-detection: larger slices strictly
# before smaller ones (a 4-chip slice must never auto-pick a single-chip
# preset and idle 3 chips), newer generations first within a size.
DETECTION_ORDER = [
    "tpu_v6e_16_dp_tp",
    "tpu_v5e_16_dp_tp",
    "tpu_v6e_8",
    "tpu_v5p_8",
    "tpu_v4_8",
    "tpu_v5e_8",
    "tpu_v3_8",
    "tpu_v2_8",
    "tpu_v5e_4",
    "tpu_v6e_1",
    "tpu_v5e_1",
    "cpu",
]


def supported_presets(
    platform: str, device_count: int, device_kind: str = ""
) -> list[DevicePreset]:
    """Presets runnable on the detected hardware (reference platform-support
    matrix, ``preset_registry.py:118-170``). When the chip generation is
    recognized, only same-generation presets (plus cpu) qualify; unknown
    kinds fall back to any-TPU matching."""
    generation = parse_generation(device_kind)
    same_gen: list[DevicePreset] = []
    any_gen: list[DevicePreset] = []
    cpu: list[DevicePreset] = []
    for name in DETECTION_ORDER:
        p = PRESETS[name]
        if p.platform == "cpu":
            cpu.append(p)
        elif p.platform == platform and 0 < p.chips <= device_count:
            any_gen.append(p)
            if generation is not None and p.generation == generation:
                same_gen.append(p)
    # A recognized generation narrows the list — but a slice size with no
    # same-generation preset (e.g. v4-4) must still get a TPU preset, not
    # regress to the float32 cpu tier.
    return (same_gen or any_gen) + cpu


def detect_preset(platform: str, device_count: int, device_kind: str = "") -> DevicePreset:
    """Best preset for the hardware; falls back to cpu."""
    matches = supported_presets(platform, device_count, device_kind)
    return matches[0] if matches else PRESETS["cpu"]
