"""Hardware presets: TPU topologies instead of GPU driver stacks.

Reference equivalent: ``DeviceConfig`` classmethod presets carrying
onnx-providers + micromamba yamls (``lumen-app/src/lumen_app/services/
config.py:41-279``) and the ``PresetRegistry`` platform-support rules
(``utils/preset_registry.py:16-244``). Here a preset carries what a TPU
deployment actually varies on: device platform, mesh axes, compute dtype,
and batch size.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class DevicePreset:
    name: str
    description: str
    platform: str  # "tpu" | "cpu"
    chips: int  # devices the mesh expects (0 = use all present)
    mesh_axes: dict[str, int] = field(default_factory=lambda: {"data": -1})
    dtype: str = "bfloat16"
    batch_size: int = 32
    # Service tiers this preset can comfortably run.
    max_tier: str = "full"


PRESETS: dict[str, DevicePreset] = {
    p.name: p
    for p in [
        DevicePreset(
            name="cpu",
            description="CPU-only (JAX CPU backend); correctness/dev tier",
            platform="cpu",
            chips=0,
            dtype="float32",
            batch_size=4,
            max_tier="light_weight",
        ),
        DevicePreset(
            name="tpu_v5e_1",
            description="Single v5e chip",
            platform="tpu",
            chips=1,
            batch_size=32,
        ),
        DevicePreset(
            name="tpu_v5e_4",
            description="v5e-4 slice, data-parallel mesh",
            platform="tpu",
            chips=4,
            mesh_axes={"data": -1},
            batch_size=128,
        ),
        DevicePreset(
            name="tpu_v5e_8",
            description="v5e-8 slice, data-parallel mesh",
            platform="tpu",
            chips=8,
            mesh_axes={"data": -1},
            batch_size=256,
        ),
        DevicePreset(
            name="tpu_v5e_16_dp_tp",
            description="v5e-16 pod slice, 8-way data x 2-way tensor mesh",
            platform="tpu",
            chips=16,
            mesh_axes={"data": -1, "model": 2},
            batch_size=512,
        ),
        DevicePreset(
            name="tpu_v6e_8",
            description="v6e-8 slice, data-parallel mesh",
            platform="tpu",
            chips=8,
            batch_size=384,
        ),
    ]
}

# Order presets are tried during auto-detection (most capable first).
DETECTION_ORDER = [
    "tpu_v5e_16_dp_tp",
    "tpu_v6e_8",
    "tpu_v5e_8",
    "tpu_v5e_4",
    "tpu_v5e_1",
    "cpu",
]


def supported_presets(platform: str, device_count: int) -> list[DevicePreset]:
    """Presets runnable on the detected hardware (reference platform-support
    matrix, ``preset_registry.py:118-170``)."""
    out = []
    for name in DETECTION_ORDER:
        p = PRESETS[name]
        if p.platform == "cpu":
            out.append(p)
        elif p.platform == platform and 0 < p.chips <= device_count:
            out.append(p)
    return out


def detect_preset(platform: str, device_count: int) -> DevicePreset:
    """Best preset for the hardware; falls back to cpu."""
    matches = supported_presets(platform, device_count)
    return matches[0] if matches else PRESETS["cpu"]
