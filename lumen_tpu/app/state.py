"""Process-global application state (reference ``services/state.py:43-265``).

Holds the cached config, the install task store, the managed-server handle,
and the pub/sub log broadcast: every WebSocket subscriber gets its own
bounded ``asyncio.Queue`` fed by ``broadcast_log`` (reference
``state.py:201-237``); slow consumers drop oldest instead of blocking the
producer.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any

logger = logging.getLogger(__name__)

LOG_QUEUE_SIZE = 512


@dataclass
class LogEvent:
    message: str
    level: str = "info"
    source: str = "app"
    ts: float = field(default_factory=time.time)

    def as_dict(self) -> dict[str, Any]:
        return {"message": self.message, "level": self.level, "source": self.source, "ts": self.ts}


class AppState:
    """One instance per app process; handed to every API handler."""

    def __init__(self) -> None:
        from collections import deque

        self.config = None  # LumenConfig | None (last generated/loaded)
        self.config_path: str | None = None
        self.install_tasks: dict[str, Any] = {}  # task_id -> InstallTask
        self.server_manager = None  # set by api.build_app
        self._subscribers: set[asyncio.Queue[LogEvent]] = set()
        #: ring buffer behind GET /server/logs and /install/logs — WS
        #: subscribers only see lines from after they connect; the REST
        #: endpoints (reference api/server.py:21-234, api/install.py:85-243)
        #: serve recent history.
        self.recent_logs: "deque[LogEvent]" = deque(maxlen=500)
        #: server lines separately: a chatty install must not evict the
        #: managed server's history out from under GET /server/logs
        self.server_logs: "deque[LogEvent]" = deque(maxlen=500)
        self._lock = asyncio.Lock()
        self._loop: asyncio.AbstractEventLoop | None = None

    # -- lifecycle --------------------------------------------------------

    def bind_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        """Remember the serving loop so threads can broadcast safely."""
        self._loop = loop

    # -- log pub/sub ------------------------------------------------------

    def subscribe(self) -> asyncio.Queue[LogEvent]:
        q: asyncio.Queue[LogEvent] = asyncio.Queue(maxsize=LOG_QUEUE_SIZE)
        self._subscribers.add(q)
        return q

    def unsubscribe(self, q: asyncio.Queue[LogEvent]) -> None:
        self._subscribers.discard(q)

    def broadcast_log(self, message: str, level: str = "info", source: str = "app") -> None:
        """Fan a log line out to all subscribers. Safe from the event loop;
        threads must use :meth:`broadcast_log_threadsafe`."""
        event = LogEvent(message=message, level=level, source=source)
        self.recent_logs.append(event)
        if source == "server":
            self.server_logs.append(event)
        for q in list(self._subscribers):
            try:
                q.put_nowait(event)
            except asyncio.QueueFull:
                try:  # drop oldest so the stream stays live for slow readers
                    q.get_nowait()
                    q.put_nowait(event)
                except asyncio.QueueEmpty:
                    pass

    def broadcast_log_threadsafe(self, message: str, level: str = "info", source: str = "app") -> None:
        """Bridge for worker threads (reference ``install_orchestrator.py:674-693``)."""
        if self._loop is None or self._loop.is_closed():
            return
        self._loop.call_soon_threadsafe(self.broadcast_log, message, level, source)

    @property
    def subscriber_count(self) -> int:
        return len(self._subscribers)
