// Error presentation (role of the reference's
// web-ui/src/lib/errorPresentation.ts): classify an API failure into a
// kind + title so every view surfaces failures the same way instead of
// raw fetch messages.

export class ApiError extends Error {
  /** @param {string} message @param {number|null} status @param {object|null} data parsed error body (e.g. /config/validate field_errors) */
  constructor(message, status = null, data = null) {
    super(message);
    this.status = status;
    this.data = data;
    this.kind =
      status === null ? "network"
      : status === 401 || status === 403 ? "permission"
      : status >= 500 ? "server"
      : status >= 400 ? "business"
      : "unknown";
  }
}

const TITLES = {
  network: "Control plane unreachable",
  permission: "Permission denied",
  business: "Request rejected",
  server: "Control plane error",
  unknown: "Request failed",
};

/** @returns {{title: string, message: string, kind: string}} */
export function describeUiError(error, fallbackMessage = "something went wrong") {
  if (error instanceof ApiError) {
    return {
      title: TITLES[error.kind] || TITLES.unknown,
      message: error.message || fallbackMessage,
      kind: error.kind,
    };
  }
  if (error instanceof Error) {
    return { title: TITLES.unknown, message: error.message || fallbackMessage, kind: "unknown" };
  }
  return { title: "Unknown error", message: fallbackMessage, kind: "unknown" };
}
