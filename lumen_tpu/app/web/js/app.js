// Wizard shell: stepper rendering, view routing, nav gating (role of the
// reference's App.tsx + WizardProvider wiring).

import { logStream } from "./api.js";
import { STEPS, wizard } from "./wizard.js";
import { renderWelcome } from "./views/welcome.js";
import { renderHardware } from "./views/hardware.js";
import { renderConfig } from "./views/config.js";
import { renderInstall } from "./views/install.js";
import { renderServer } from "./views/server.js";
import { renderOpenPath } from "./views/openpath.js";
import { renderSessionHub } from "./views/sessionhub.js";

const VIEWS = {
  welcome: renderWelcome,
  hardware: renderHardware,
  config: renderConfig,
  install: renderInstall,
  server: renderServer,
  // aux routes outside the setup stepper (reference /open, /session)
  openpath: renderOpenPath,
  sessionhub: renderSessionHub,
};

const viewEl = document.getElementById("view");
const stepperEl = document.getElementById("stepper");
const backBtn = document.getElementById("nav-back");
const nextBtn = document.getElementById("nav-next");
const statusEl = document.getElementById("top-status");

let cleanups = [];

function onLeave(fn) {
  cleanups.push(fn);
}

function render() {
  for (const fn of cleanups.splice(0)) {
    try {
      fn();
    } catch {
      /* view cleanup is best-effort */
    }
  }
  // stepper
  stepperEl.replaceChildren(
    ...STEPS.map((step, i) => {
      const pill = document.createElement("button");
      pill.className = "step-pill";
      if (step.id === wizard.step) pill.classList.add("active");
      if (wizard.complete(step.id) && step.id !== wizard.step) pill.classList.add("done");
      if (!wizard.canEnter(step.id)) pill.disabled = true;
      const num = document.createElement("span");
      num.className = "step-num";
      num.textContent = wizard.complete(step.id) && step.id !== wizard.step ? "✓" : String(i + 1);
      pill.append(num, document.createTextNode(step.title));
      pill.onclick = () => wizard.goto(step.id);
      return pill;
    })
  );
  // view
  viewEl.replaceChildren();
  VIEWS[wizard.step](viewEl, onLeave);
  // nav — aux views (openpath/sessionhub) have no stepper index: Back
  // walks their own chain (wizard.back), Next is hidden.
  const idx = wizard.stepIndex();
  if (idx < 0) {
    backBtn.disabled = false;
    nextBtn.style.visibility = "hidden";
  } else {
    backBtn.disabled = idx === 0;
    const last = idx === STEPS.length - 1;
    nextBtn.style.visibility = last ? "hidden" : "visible";
    nextBtn.disabled = !last && !wizard.canEnter(STEPS[idx + 1].id);
  }
}

backBtn.onclick = () => wizard.back();
nextBtn.onclick = () => wizard.next();

let lastStep = wizard.step;
let lastRev = wizard.state.rev || 0;
wizard.subscribe((state) => {
  // Re-render on step change or reset; within a step only the pieces
  // that gate navigation need a refresh.
  if (state.step !== lastStep || (state.rev || 0) !== lastRev) {
    lastStep = state.step;
    lastRev = state.rev || 0;
    render();
  } else {
    const idx = wizard.stepIndex();
    nextBtn.disabled = idx < STEPS.length - 1 && !wizard.canEnter(STEPS[idx + 1].id);
    stepperEl.querySelectorAll(".step-pill").forEach((pill, i) => {
      pill.disabled = !wizard.canEnter(STEPS[i].id);
    });
  }
});

logStream.onStatus((up) => {
  statusEl.className = `top-status ${up ? "ok" : "err"}`;
  statusEl.title = up ? "log stream connected" : "log stream disconnected";
});
logStream.connect();

render();
