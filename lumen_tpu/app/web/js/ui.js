// Small DOM helpers shared by the views (no framework, no build step).

export function el(tag, attrs = {}, children = []) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    // undefined/null/false mean "attribute absent" — setAttribute would
    // stringify them, and boolean attributes like disabled activate on
    // ANY value.
    if (v === undefined || v === null || v === false) continue;
    if (k === "class") node.className = v;
    else if (k.startsWith("on") && typeof v === "function") node[k] = v;
    else node.setAttribute(k, v);
  }
  if (!Array.isArray(children)) children = [children];
  for (const child of children) {
    if (child === null || child === undefined || child === "") continue;
    node.append(child instanceof Node ? child : document.createTextNode(String(child)));
  }
  return node;
}

import { describeUiError } from "./errors.js";

/** Uniform failure surface: classify the error (network/permission/
 * business/server) and toast "<title>: <message>". */
export function toastError(error, fallback) {
  const d = describeUiError(error, fallback);
  toast(`${d.title}: ${d.message}`, true);
}

let toastTimer = null;

export function toast(message, isError = false) {
  document.querySelectorAll(".toast").forEach((t) => t.remove());
  const node = el("div", { class: `toast${isError ? " err" : ""}` }, message);
  document.body.append(node);
  clearTimeout(toastTimer);
  toastTimer = setTimeout(() => node.remove(), isError ? 6000 : 3000);
}

export function logLine(frame) {
  const t = new Date((frame.ts || Date.now() / 1000) * 1000);
  const hh = t.toTimeString().slice(0, 8);
  const line = el("p", { class: "logline" }, [el("time", {}, hh), frame.message || ""]);
  if (/error|failed|traceback/i.test(frame.message || "")) line.classList.add("err");
  return line;
}

export function attachLogPane(pane, logStream, maxLines = 500) {
  const unsub = logStream.subscribe((frame) => {
    pane.append(logLine(frame));
    while (pane.childElementCount > maxLines) pane.firstElementChild.remove();
    pane.scrollTop = pane.scrollHeight;
  });
  return unsub;
}
