// Wizard state machine (role of the reference's WizardProvider context,
// web-ui/src/context/). Holds cross-view state, persists to localStorage
// so a reload resumes where the operator left off, and gates forward
// navigation on per-step completion.

const STORAGE_KEY = "lumen-tpu-wizard";

export const STEPS = [
  { id: "welcome", title: "Welcome" },
  { id: "hardware", title: "Hardware" },
  { id: "config", title: "Config" },
  { id: "install", title: "Install" },
  { id: "server", title: "Server" },
];

// Routes outside the linear setup flow (reference /open and /session,
// web-ui/src/views/{OpenPath,SessionHub}.tsx): always enterable; Back
// walks sessionhub -> openpath -> welcome.
export const AUX_VIEWS = ["openpath", "sessionhub"];

const DEFAULT_STATE = {
  step: "welcome",
  // hardware
  hardware: null, // /hardware/detect report (not persisted stale: re-fetched)
  preset: null,
  // config
  tier: "light_weight",
  region: "other",
  cacheDir: "~/.lumen-tpu",
  port: 50051,
  mdns: true,
  configGenerated: false,
  configPath: null,
  // install
  installTaskId: null,
  installDone: false,
};

function load() {
  try {
    const raw = localStorage.getItem(STORAGE_KEY);
    if (!raw) return { ...DEFAULT_STATE };
    const saved = JSON.parse(raw);
    const state = { ...DEFAULT_STATE, ...saved, hardware: null };
    // A step id from another version (or corruption) must not crash the
    // boot render — fall back to the first step.
    if (!STEPS.some((s) => s.id === state.step) && !AUX_VIEWS.includes(state.step)) {
      state.step = "welcome";
    }
    return state;
  } catch {
    return { ...DEFAULT_STATE };
  }
}

class Wizard {
  constructor() {
    this.state = load();
    this.listeners = new Set();
  }

  get step() {
    return this.state.step;
  }

  update(patch) {
    Object.assign(this.state, patch);
    const { hardware, ...persist } = this.state;
    try {
      localStorage.setItem(STORAGE_KEY, JSON.stringify(persist));
    } catch {
      /* private mode etc. — state just won't survive reload */
    }
    for (const fn of this.listeners) fn(this.state);
  }

  subscribe(fn) {
    this.listeners.add(fn);
    return () => this.listeners.delete(fn);
  }

  reset() {
    // rev forces a full re-render even though step stays "welcome"
    this.state = { ...DEFAULT_STATE, rev: (this.state.rev || 0) + 1 };
    localStorage.removeItem(STORAGE_KEY);
    for (const fn of this.listeners) fn(this.state);
  }

  stepIndex(id = this.state.step) {
    return STEPS.findIndex((s) => s.id === id);
  }

  // A step is reachable when every prior step is complete.
  complete(id) {
    switch (id) {
      case "welcome":
        return true;
      case "hardware":
        return this.state.preset !== null;
      case "config":
        return this.state.configGenerated;
      case "install":
        return this.state.installDone;
      case "server":
        return false;
      default:
        return false;
    }
  }

  canEnter(id) {
    const idx = this.stepIndex(id);
    for (let i = 0; i < idx; i++) {
      if (!this.complete(STEPS[i].id)) return false;
    }
    return true;
  }

  goto(id) {
    if (AUX_VIEWS.includes(id) || this.canEnter(id)) this.update({ step: id });
  }

  next() {
    const idx = this.stepIndex();
    if (idx >= 0 && idx < STEPS.length - 1) this.goto(STEPS[idx + 1].id);
  }

  back() {
    if (this.state.step === "sessionhub") return this.update({ step: "openpath" });
    if (this.state.step === "openpath") return this.update({ step: "welcome" });
    const idx = this.stepIndex();
    if (idx > 0) this.update({ step: STEPS[idx - 1].id });
  }
}

export const wizard = new Wizard();
