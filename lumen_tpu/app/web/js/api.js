// API client for the lumen-tpu control plane (role of the reference's
// typed web-ui/src/lib/api.ts). One function per endpoint of
// lumen_tpu/app/api.py; errors normalize to Error(message).

const V1 = "/api/v1";

async function request(method, path, body) {
  const opts = { method, headers: {} };
  if (body !== undefined) {
    opts.headers["Content-Type"] = "application/json";
    opts.body = JSON.stringify(body);
  }
  let res;
  try {
    res = await fetch(path, opts);
  } catch (e) {
    throw new Error(`control plane unreachable: ${e.message}`);
  }
  const text = await res.text();
  let data = null;
  try {
    data = text ? JSON.parse(text) : null;
  } catch {
    data = { raw: text };
  }
  if (!res.ok) {
    const err = new Error((data && data.error) || `${method} ${path} -> HTTP ${res.status}`);
    err.status = res.status;
    throw err;
  }
  return data;
}

export const api = {
  health: () => request("GET", "/health"),

  // hardware
  configLoad: (path) => request("POST", `${V1}/config/load`, { path }),
  serverLogs: () => request("GET", `${V1}/server/logs`),
  hardwareInfo: () => request("GET", `${V1}/hardware/info`),
  hardwareDetect: () => request("GET", `${V1}/hardware/detect`),
  hardwareCheck: (cacheDir) =>
    // no client-side default: an absent param uses the server's default
    request("GET", `${V1}/hardware/check` + (cacheDir ? `?cache_dir=${encodeURIComponent(cacheDir)}` : "")),

  // config
  presets: () => request("GET", `${V1}/config/presets`),
  generateConfig: (opts) => request("POST", `${V1}/config/generate`, opts),
  currentConfig: () => request("GET", `${V1}/config/current`),
  validateConfig: (cfg) => request("POST", `${V1}/config/validate`, { config: cfg }),
  saveConfig: (path) => request("POST", `${V1}/config/save`, { path }),
  configYaml: async () => {
    const res = await fetch(`${V1}/config/yaml`);
    if (!res.ok) throw new Error(`no config yet (HTTP ${res.status})`);
    return res.text();
  },

  // install
  installSetup: (opts) => request("POST", `${V1}/install/setup`, opts),
  installTasks: () => request("GET", `${V1}/install/tasks`),
  installStatus: (id) => request("GET", `${V1}/install/status/${id}`),
  installCancel: (id) => request("POST", `${V1}/install/cancel/${id}`),

  // server
  serverStatus: () => request("GET", `${V1}/server/status`),
  serverStart: (opts) => request("POST", `${V1}/server/start`, opts || {}),
  serverStop: () => request("POST", `${V1}/server/stop`),
  serverRestart: () => request("POST", `${V1}/server/restart`),
  metrics: async () => {
    const res = await fetch(`${V1}/metrics`);
    return res.text();
  },
};

// Live log stream over /ws/logs (frames {type: connected|log|heartbeat}).
// Auto-reconnects with backoff; hands every log line to the subscribers.
export class LogStream {
  constructor() {
    this.subscribers = new Set();
    this.statusSubscribers = new Set();
    this.ws = null;
    this.backoff = 500;
    this.closed = false;
  }

  connect() {
    if (this.closed || (this.ws && this.ws.readyState <= 1)) return;
    const proto = location.protocol === "https:" ? "wss" : "ws";
    this.ws = new WebSocket(`${proto}://${location.host}/ws/logs`);
    this.ws.onopen = () => {
      this.backoff = 500;
      this._status(true);
    };
    this.ws.onmessage = (ev) => {
      let frame;
      try {
        frame = JSON.parse(ev.data);
      } catch {
        return;
      }
      if (frame.type === "log") {
        for (const fn of this.subscribers) fn(frame);
      }
    };
    this.ws.onclose = () => {
      this._status(false);
      if (!this.closed) {
        setTimeout(() => this.connect(), this.backoff);
        this.backoff = Math.min(this.backoff * 2, 8000);
      }
    };
    this.ws.onerror = () => this.ws && this.ws.close();
  }

  subscribe(fn) {
    this.subscribers.add(fn);
    this.connect();
    return () => this.subscribers.delete(fn);
  }

  onStatus(fn) {
    this.statusSubscribers.add(fn);
    return () => this.statusSubscribers.delete(fn);
  }

  _status(up) {
    for (const fn of this.statusSubscribers) fn(up);
  }

  close() {
    this.closed = true;
    if (this.ws) this.ws.close();
  }
}

export const logStream = new LogStream();
