// API client for the lumen-tpu control plane (role of the reference's
// typed web-ui/src/lib/api.ts). Every path resolves through the GENERATED
// route manifest (api.generated.js, rebuilt from the live aiohttp app by
// scripts/generate_api_client.py and pinned by tests/test_web.py), so a
// server-side route rename breaks the client loudly instead of 404ing.
// Failures normalize to ApiError with a kind (network/permission/
// business/server) for errors.js to present.

import { ROUTES, fillPath } from "./api.generated.js";
import { ApiError } from "./errors.js";

async function call(routeName, { params, query, body } = {}) {
  const route = ROUTES[routeName];
  // A plain Error: this is a client-side programming bug (typo'd route
  // name), not a network condition — ApiError(null) would present it as
  // "control plane unreachable".
  if (!route) throw new Error(`unknown route: ${routeName}`);
  let path = fillPath(route.path, params || {});
  if (query) {
    const qs = new URLSearchParams(query).toString();
    if (qs) path += `?${qs}`;
  }
  const opts = { method: route.method, headers: {} };
  if (body !== undefined) {
    opts.headers["Content-Type"] = "application/json";
    opts.body = JSON.stringify(body);
  }
  let res;
  try {
    res = await fetch(path, opts);
  } catch (e) {
    throw new ApiError(`control plane unreachable: ${e.message}`, null);
  }
  const text = await res.text();
  let data = null;
  try {
    data = text ? JSON.parse(text) : null;
  } catch {
    data = { raw: text };
  }
  if (!res.ok) {
    throw new ApiError(
      (data && data.error) || `${route.method} ${path} -> HTTP ${res.status}`,
      res.status,
      data
    );
  }
  return data;
}

export const api = {
  health: () => call("health"),

  // hardware
  configLoad: (path) => call("config_load", { body: { path } }),
  serverLogs: () => call("server_logs"),
  hardwareInfo: () => call("hardware_info"),
  hardwareDetect: () => call("hardware_detect"),
  hardwareCheck: (cacheDir) =>
    // no client-side default: an absent param uses the server's default
    call("hardware_check", cacheDir ? { query: { cache_dir: cacheDir } } : {}),

  // config
  presets: () => call("config_presets"),
  /** @param {{preset: string, tier: string, region?: string, cache_dir?: string}} opts */
  generateConfig: (opts) => call("config_generate", { body: opts }),
  /** @returns {Promise<LumenConfig>} (typedef in api.generated.js) */
  currentConfig: () => call("config_current"),
  /** @param {LumenConfig} cfg @param {boolean=} loose */
  validateConfig: (cfg, loose) =>
    call("config_validate", { body: loose ? { config: cfg, loose: true } : { config: cfg } }),
  /** Validate editor YAML text as typed (per-field errors in the response). */
  validateConfigYaml: (yaml, loose) =>
    call("config_validate", { body: loose ? { yaml, loose: true } : { yaml } }),
  /** Reference SessionHub: is the deployment at config_path ready to start as-is? */
  sessionStatus: (configPath) =>
    call("session_status", { body: configPath ? { config_path: configPath } : {} }),
  /** Validate + persist edited YAML and make it the current config. */
  saveConfigYaml: (yaml, path, loose) =>
    call("config_save", { body: loose ? { yaml, path, loose: true } : { yaml, path } }),
  configYaml: async () => {
    const res = await fetch(ROUTES.config_yaml.path);
    if (!res.ok) throw new ApiError(`no config yet (HTTP ${res.status})`, res.status);
    return res.text();
  },

  // install
  installSetup: (opts) => call("install_setup", { body: opts }),
  installTasks: () => call("install_tasks"),
  installStatus: (id) => call("install_status", { params: { task_id: id } }),
  installLogs: (id, limit) =>
    call("install_logs", { params: { task_id: id }, query: limit ? { limit } : undefined }),
  installCancel: (id) => call("install_cancel", { params: { task_id: id } }),
  installCheckPath: (path) => call("install_check_path", { body: { path } }),

  // server
  serverStatus: () => call("server_status"),
  serverStart: (opts) => call("server_start", { body: opts || {} }),
  serverStop: () => call("server_stop"),
  serverRestart: () => call("server_restart"),
  metrics: async () => {
    const res = await fetch(ROUTES.metrics.path);
    return res.text();
  },
};

// Live log stream over /ws/logs (frames {type: connected|log|heartbeat}).
// Auto-reconnects with backoff; hands every log line to the subscribers.
export class LogStream {
  constructor() {
    this.subscribers = new Set();
    this.statusSubscribers = new Set();
    this.ws = null;
    this.backoff = 500;
    this.closed = false;
  }

  connect() {
    if (this.closed || (this.ws && this.ws.readyState <= 1)) return;
    const proto = location.protocol === "https:" ? "wss" : "ws";
    this.ws = new WebSocket(`${proto}://${location.host}/ws/logs`);
    this.ws.onopen = () => {
      this.backoff = 500;
      this._status(true);
    };
    this.ws.onmessage = (ev) => {
      let frame;
      try {
        frame = JSON.parse(ev.data);
      } catch {
        return;
      }
      if (frame.type === "log") {
        for (const fn of this.subscribers) fn(frame);
      }
    };
    this.ws.onclose = () => {
      this._status(false);
      if (!this.closed) {
        setTimeout(() => this.connect(), this.backoff);
        this.backoff = Math.min(this.backoff * 2, 8000);
      }
    };
    this.ws.onerror = () => this.ws && this.ws.close();
  }

  subscribe(fn) {
    this.subscribers.add(fn);
    this.connect();
    return () => this.subscribers.delete(fn);
  }

  onStatus(fn) {
    this.statusSubscribers.add(fn);
    return () => this.statusSubscribers.delete(fn);
  }

  _status(up) {
    for (const fn of this.statusSubscribers) fn(up);
  }

  close() {
    this.closed = true;
    if (this.ws) this.ws.close();
  }
}

export const logStream = new LogStream();
