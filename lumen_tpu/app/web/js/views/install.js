// Install view (reference: web-ui/src/views/Install): start the install
// task (env verify + model downloads), poll step progress, stream logs.

import { api, logStream } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast, toastError, attachLogPane } from "../ui.js";

const STEP_ICONS = {
  pending: "○",
  running: "◌",
  completed: "●",
  skipped: "◌",
  failed: "✕",
  cancelled: "✕",
};

let pollTimer = null;
// Poll chains can be parked in a timer OR awaiting installStatus; a timer
// clear can't cancel the latter, so each chain carries a generation and a
// stale chain drops its response instead of clobbering a newer task's state.
let pollGen = 0;

export function renderInstall(root, onLeave) {
  const s = wizard.state;
  root.append(
    el("h2", { class: "view-title" }, "Install"),
    el("p", { class: "view-sub" },
      "Verifies the runtime environment and downloads the model weights the config needs into the cache."),
    el("div", { class: "card" }, [
      el("div", { class: "checkrow" }, [
        el("input", { type: "checkbox", id: "inst-download", checked: "1" }),
        "download model weights for the saved config",
      ]),
      el("div", { class: "row" }, [
        el("button", { class: "btn primary", id: "inst-start" }, s.installDone ? "Re-run install" : "Start install"),
        el("button", { class: "btn danger", id: "inst-cancel", disabled: "1" }, "Cancel"),
        el("span", { class: "muted", id: "inst-status" }, s.installDone ? "install completed" : ""),
      ]),
      el("div", { class: "progress" }, el("div", { id: "inst-bar", style: "width:0%" })),
      el("ul", { class: "steplist", id: "inst-steps" }),
      el("p", { class: "muted", id: "inst-error" }),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Previous install tasks"),
      el("div", { class: "muted" },
        "Tasks from this control-plane session (reference SessionHub role): click one to resume watching it."),
      el("ul", { class: "steplist", id: "inst-history" }),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Live logs"),
      el("div", { class: "logpane", id: "inst-logs" }),
    ])
  );

  refreshHistory(root);

  const unsubLogs = attachLogPane(root.querySelector("#inst-logs"), logStream);
  onLeave(() => {
    unsubLogs();
    clearTimeout(pollTimer);
  });

  // resume a task in flight (reload mid-install)
  if (s.installTaskId && !s.installDone) poll(root, s.installTaskId, ++pollGen);

  root.querySelector("#inst-start").onclick = async () => {
    const btn = root.querySelector("#inst-start");
    btn.disabled = true;
    try {
      const download = root.querySelector("#inst-download").checked;
      if (download && !wizard.state.configPath) {
        // The server silently skips downloads without a config path; make
        // the operator save first instead of "completing" a no-op install.
        toast("save the config YAML first (Config step) so the install knows which models to download", true);
        btn.disabled = false;
        return;
      }
      if (wizard.state.cacheDir) {
        // Pre-flight the cache target (reference Install view checks the
        // path before starting): surface unwritable/low-disk up front
        // instead of failing minutes into the downloads.
        const check = await api.installCheckPath(wizard.state.cacheDir);
        if (!check.ok) {
          toast(`cache dir ${check.path} is not usable (writable=${check.writable}, free=${check.free_gb}GB)`, true);
          btn.disabled = false;
          return;
        }
        if (check.free_gb < 5) {
          toast(`cache dir has only ${check.free_gb}GB free — model downloads may fail`, true);
        }
      }
      const task = await api.installSetup({
        download,
        config_path: download ? wizard.state.configPath : null,
        cache_dir: wizard.state.cacheDir,
        region: wizard.state.region, // cn routes pip through a mirror
      });
      wizard.update({ installTaskId: task.task_id, installDone: false });
      root.querySelector("#inst-cancel").disabled = false;
      poll(root, task.task_id, ++pollGen);
    } catch (e) {
      toastError(e, "could not start the install");
      btn.disabled = false;
    }
  };

  root.querySelector("#inst-cancel").onclick = async () => {
    if (!wizard.state.installTaskId) return;
    try {
      await api.installCancel(wizard.state.installTaskId);
      toast("cancelling…");
    } catch (e) {
      toastError(e, "could not cancel the install");
    }
  };
}

async function refreshHistory(root) {
  let tasks;
  try {
    tasks = (await api.installTasks()).tasks || [];
  } catch {
    return; // history is best-effort; the live pane still works
  }
  if (!root.isConnected) return;
  const list = root.querySelector("#inst-history");
  if (!list) return;
  if (!tasks.length) {
    list.replaceChildren(el("li", { class: "muted" }, "none yet"));
    return;
  }
  list.replaceChildren(
    ...tasks
      .slice()
      .sort((a, b) => (b.created_at || 0) - (a.created_at || 0))
      .map((t) =>
        el("li", { class: t.status }, [
          el("span", { class: "step-ico" }, STEP_ICONS[t.status] || "○"),
          el(
            "a",
            {
              href: "#",
              onclick: async (ev) => {
                ev.preventDefault();
                // Never detach the UI (and the Cancel button) from the
                // install it is watching — guard FIRST, regardless of what
                // the (possibly stale) list snapshot claims about t.
                const current = wizard.state.installTaskId;
                if (current && !wizard.state.installDone && t.task_id !== current) {
                  toast("an install is in progress — finish or cancel it first", true);
                  return;
                }
                // Decide reattach-vs-inspect from a FRESH status, not the
                // mount-time snapshot (the task may have finished since).
                let fresh;
                try {
                  fresh = await api.installStatus(t.task_id);
                } catch (e) {
                  toastError(e, "could not load the task");
                  return;
                }
                if (!root.isConnected) return;
                if (fresh.status === "running" || fresh.status === "pending") {
                  wizard.update({ installTaskId: t.task_id, installDone: false });
                  poll(root, t.task_id, ++pollGen);
                } else {
                  // Terminal: read-only render, no state writes, no
                  // replayed completion/failure toasts.
                  renderTask(root, fresh);
                }
              },
            },
            t.task_id
          ),
          el("span", { class: "step-detail" }, `${t.status} · ${t.progress ?? 0}%`),
        ])
      )
  );
}

function renderTask(root, task) {
  // task.progress is already a 0-100 percentage (install.py progress).
  root.querySelector("#inst-bar").style.width = `${Math.round(task.progress || 0)}%`;
  const list = root.querySelector("#inst-steps");
  list.replaceChildren(
    ...task.steps.map((step) =>
      el("li", { class: step.status }, [
        el("span", { class: "step-ico" }, STEP_ICONS[step.status] || "○"),
        step.name,
        el("span", { class: "step-detail" }, step.detail || ""),
      ])
    )
  );
  root.querySelector("#inst-status").textContent = `status: ${task.status}`;
  root.querySelector("#inst-error").textContent = task.error || "";
}

async function poll(root, taskId, gen) {
  if (!root.isConnected || gen !== pollGen) return; // view switched / superseded
  clearTimeout(pollTimer); // a Start-triggered poll replaces a stale chain
  let task;
  try {
    task = await api.installStatus(taskId);
  } catch (e) {
    if (gen !== pollGen) return; // superseded while awaiting
    if (e.status === 404) {
      // Install tasks live in the control plane's memory; after a restart
      // a persisted id is gone for good — stop polling, forget it.
      wizard.update({ installTaskId: null });
      root.querySelector("#inst-status").textContent = "previous install task no longer exists";
      root.querySelector("#inst-start").disabled = false;
      root.querySelector("#inst-cancel").disabled = true;
      return;
    }
    // Transient control-plane hiccups must not freeze a running install's
    // progress display — keep polling.
    root.querySelector("#inst-status").textContent = `${e.message} (retrying…)`;
    pollTimer = setTimeout(() => poll(root, taskId, gen), 2000);
    return;
  }
  if (!root.isConnected || gen !== pollGen) return;

  renderTask(root, task);

  if (task.status === "running" || task.status === "pending") {
    root.querySelector("#inst-cancel").disabled = false;
    pollTimer = setTimeout(() => poll(root, taskId, gen), 900);
  } else {
    const startBtn = root.querySelector("#inst-start");
    startBtn.disabled = false;
    root.querySelector("#inst-cancel").disabled = true;
    if (task.status === "completed") {
      startBtn.textContent = "Re-run install";  // clear a stale Retry label
      wizard.update({ installDone: true });
      toast("install complete");
    } else if (task.status === "failed") {
      // Failure state with a one-click retry (reference Install view's
      // error affordance): the failed step is marked ✕ in the list above,
      // the task error is shown, and Start becomes Retry with the same
      // parameters.
      startBtn.textContent = "Retry install";
      const failedStep = (task.steps || []).find((s) => s.status === "failed");
      root.querySelector("#inst-error").textContent =
        (task.error ? `install failed: ${task.error}` : "install failed — see logs") +
        (failedStep ? ` (step: ${failedStep.name})` : "");
      toast(`install failed: ${task.error || "see logs"}`, true);
    } else if (task.status === "cancelled") {
      startBtn.textContent = "Re-run install";
      root.querySelector("#inst-status").textContent = "install cancelled";
    }
    refreshHistory(root); // terminal state: reflect it in the task list
  }
}
