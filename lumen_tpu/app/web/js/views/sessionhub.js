// SessionHub view (reference: web-ui/src/views/SessionHub.tsx): given the
// opened config, offline-check the deployment (models present in the
// cache?) and route to the recommended action — start the server as-is,
// run the installer, or fix the config. First-class route like the
// reference's /session; the status comes from POST /api/v1/session/status.

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el } from "../ui.js";

export function renderSessionHub(root) {
  const configPath = wizard.state.configPath;
  root.append(
    el("div", { class: "hero" }, [
      el("h1", {}, "Session hub"),
      el("p", { class: "muted", id: "hub-path" }, configPath ? `config: ${configPath}` : "no config opened"),
    ]),
    el("div", { class: "row" }, [
      el("button", { class: "btn ghost", id: "hub-switch" }, "← Switch path"),
    ]),
    el("div", { id: "hub-status", class: "card" }, [
      el("p", { class: "muted" }, "checking deployment…"),
    ])
  );
  root.querySelector("#hub-switch").onclick = () => wizard.goto("openpath");

  const box = root.querySelector("#hub-status");
  if (!configPath) {
    box.replaceChildren(
      el("p", { class: "warn-note" }, "no config opened — validate a path first"),
      actionRow([goBtn("openpath", "Open a config →")])
    );
    return;
  }
  renderStatus(box, configPath);
}

async function renderStatus(box, configPath) {
  let s;
  try {
    s = await api.sessionStatus(configPath);
  } catch (e) {
    if (!box.isConnected) return;
    box.replaceChildren(
      el("p", { class: "err-note" }, `could not check the deployment: ${e.message}`),
      actionRow([goBtn("openpath", "← Back to path")])
    );
    return;
  }
  if (!box.isConnected) return;

  const children = [];
  if (s.ready_to_start) {
    children.push(el("p", { class: "ok-note" }, `✓ ${s.message}`));
  } else {
    children.push(el("p", { class: "warn-note" }, `⚠ ${s.message}`));
  }
  if (s.models && s.models.length) {
    children.push(
      el(
        "ul",
        { class: "steplist" },
        s.models.map((m) =>
          el("li", { class: m.present ? "passed" : "failed" }, [
            el("span", { class: "step-ico" }, m.present ? "✓" : "✕"),
            `${m.service}/${m.alias}: ${m.model}`,
            m.present ? "" : el("span", { class: "step-detail" }, m.error || "missing"),
          ])
        )
      )
    );
  }
  // recommended_action: start_existing | run_install | open_config —
  // primary button follows the recommendation, alternatives stay ghost.
  const rec = s.recommended_action;
  const actions = [
    goBtn("server", "Start / manage server →", rec === "start_existing"),
    goBtn("install", "Run installer →", rec === "run_install"),
    goBtn("config", "Open config →", rec === "open_config"),
  ];
  children.push(actionRow(actions));
  box.replaceChildren(...children);
}

function goBtn(step, label, primary = false) {
  const btn = el("button", { class: primary ? "btn primary" : "btn ghost" }, label);
  // Direct jump, not wizard.goto(): the hub routes on the deployment's
  // actual state (models present), which outranks the linear setup gate —
  // e.g. "start_existing" goes straight to Server with no install step.
  btn.onclick = () => wizard.update({ step });
  return btn;
}

function actionRow(buttons) {
  return el("div", { class: "row" }, buttons);
}
