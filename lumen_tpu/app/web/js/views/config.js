// Config view (reference: web-ui/src/views/Config): tier/region/port form
// -> generated YAML preview -> validate -> save to disk.

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast } from "../ui.js";

const TIER_LABELS = {
  minimal: "Minimal — OCR only",
  light_weight: "Light — OCR + CLIP + face",
  full: "Full — OCR + CLIP + face + VLM",
};

export function renderConfig(root) {
  const s = wizard.state;
  root.append(
    el("h2", { class: "view-title" }, "Configuration"),
    el("p", { class: "view-sub" }, [
      "Generate the deployment YAML for preset ",
      el("span", { class: "badge accent" }, s.preset || "—"),
      ". Per-service batch and bucket sizes come from the preset's chip generation.",
    ]),
    el("div", { class: "grid2" }, [
      el("div", { class: "card" }, [
        el("h3", {}, "Deployment options"),
        field("Service tier", tierSelect()),
        field(
          "Model hub region",
          seg("region", [
            ["other", "International (HuggingFace)"],
            ["cn", "China (ModelScope)"],
          ])
        ),
        field("Cache directory", input("cacheDir", "text")),
        field("gRPC port", input("port", "number")),
        el("div", { class: "checkrow" }, [
          checkbox("mdns"),
          "advertise on the LAN via mDNS (_lumen._tcp)",
        ]),
        el("div", { class: "row" }, [
          el("button", { class: "btn primary", id: "cfg-generate" }, "Generate config"),
          el("span", { class: "muted", id: "cfg-status" }, s.configGenerated ? "config generated" : ""),
        ]),
      ]),
      el("div", { class: "card" }, [
        el("h3", {}, "Save & validate"),
        field("Config file path", el("input", { type: "text", id: "cfg-path", value: s.configPath || "lumen-config.yaml" })),
        el("div", { class: "row" }, [
          el("button", { class: "btn", id: "cfg-save" }, "Validate & save"),
          el("button", { class: "btn", id: "cfg-validate" }, "Validate"),
          el("label", { class: "checkrow" }, [
            el("input", { type: "checkbox", id: "cfg-loose" }),
            "loose (unknown fields warn)",
          ]),
          el("span", { class: "muted", id: "cfg-save-status" }, s.configPath ? `saved: ${s.configPath}` : ""),
        ]),
        el("p", { class: "muted" }, "The server step launches the gRPC hub from this saved file."),
      ]),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Config YAML (editable)"),
      el("p", { class: "muted" },
        "Edit freely — Validate checks the text below (per-field errors appear here), Validate & save writes it to the path above and makes it current."),
      el("textarea", { class: "code", id: "cfg-yaml", rows: "18", spellcheck: "false" },
        s.configGenerated ? "loading…" : "# — generate first, or edit YAML here —"),
      el("div", { id: "cfg-errors" }),
    ])
  );

  if (s.configGenerated) loadYaml(root);

  root.querySelector("#cfg-generate").onclick = async () => {
    const btn = root.querySelector("#cfg-generate");
    if (wizard.state.preset === "(existing config)") {
      // The welcome "open existing config" path sets a placeholder that
      // /config/generate would reject; regeneration needs a real preset.
      toast("pick a topology preset on the Hardware step first", true);
      return;
    }
    btn.disabled = true;
    try {
      await api.generateConfig({
        preset: wizard.state.preset,
        tier: wizard.state.tier,
        region: wizard.state.region,
        cache_dir: wizard.state.cacheDir,
        port: Number(wizard.state.port),
        mdns: wizard.state.mdns,
      });
      wizard.update({ configGenerated: true, configPath: null });
      root.querySelector("#cfg-status").textContent = "config generated";
      await loadYaml(root);
      toast("config generated");
    } catch (e) {
      toast(e.message, true);
    } finally {
      btn.disabled = false;
    }
  };

  root.querySelector("#cfg-save").onclick = async () => {
    try {
      const out = await api.saveConfigYaml(
        root.querySelector("#cfg-yaml").value,
        root.querySelector("#cfg-path").value,
        root.querySelector("#cfg-loose").checked
      );
      renderValidation(root, { valid: true, warnings: out.warnings });
      wizard.update({ configPath: out.path, configGenerated: true });
      root.querySelector("#cfg-save-status").textContent = `saved: ${out.path}`;
      toast(`saved ${out.path}`);
    } catch (e) {
      // 400 bodies carry the /config/validate error shape — render the
      // per-field list instead of only toasting the summary string.
      renderValidation(root, e.data && e.data.valid === false ? e.data : { valid: false, error: e.message });
      toast(e.message, true);
    }
  };

  root.querySelector("#cfg-validate").onclick = async () => {
    try {
      const v = await api.validateConfigYaml(
        root.querySelector("#cfg-yaml").value,
        root.querySelector("#cfg-loose").checked
      );
      renderValidation(root, v);
      if (v.valid) toast(`valid — services: ${v.services.join(", ")}`);
      else toast("invalid — see errors below", true);
    } catch (e) {
      toast(e.message, true);
    }
  };
}

// Per-field validation feedback (reference Config view's inline error
// states): one row per pydantic error, anchored by its config path.
function renderValidation(root, v) {
  const box = root.querySelector("#cfg-errors");
  if (!box) return;
  if (v.valid) {
    box.replaceChildren(
      el("p", { class: "ok-note" }, "✓ valid"),
      ...(v.warnings || []).map((w) => el("p", { class: "warn-note" }, `⚠ ${w}`))
    );
    return;
  }
  const rows = (v.field_errors || []).map((fe) =>
    el("p", { class: "err-note" }, [
      el("code", {}, fe.loc || "(config)"),
      ` — ${fe.msg}`,
    ])
  );
  box.replaceChildren(
    el("p", { class: "err-note" }, `✕ ${v.error || "invalid"}`),
    ...rows
  );
}

async function loadYaml(root) {
  try {
    root.querySelector("#cfg-yaml").value = await api.configYaml();
  } catch (e) {
    root.querySelector("#cfg-yaml").value = `# (${e.message})`;
  }
}

function field(labelText, control) {
  return el("label", { class: "field" }, [el("span", {}, labelText), control]);
}

function input(key, type) {
  const node = el("input", { type, value: wizard.state[key] });
  node.onchange = () => wizard.update({ [key]: node.value, configGenerated: false });
  return node;
}

function checkbox(key) {
  const node = el("input", { type: "checkbox" });
  node.checked = Boolean(wizard.state[key]);
  node.onchange = () => wizard.update({ [key]: node.checked, configGenerated: false });
  return node;
}

function tierSelect() {
  const node = el(
    "select",
    {},
    Object.entries(TIER_LABELS).map(([value, label]) => {
      const opt = el("option", { value }, label);
      if (wizard.state.tier === value) opt.selected = true;
      return opt;
    })
  );
  node.onchange = () => wizard.update({ tier: node.value, configGenerated: false });
  return node;
}

function seg(key, options) {
  const wrap = el("div", { class: "seg" });
  for (const [value, label] of options) {
    const btn = el("button", { type: "button" }, label);
    if (wizard.state[key] === value) btn.classList.add("active");
    btn.onclick = () => {
      wizard.update({ [key]: value, configGenerated: false });
      for (const b of wrap.children) b.classList.toggle("active", b === btn);
    };
    wrap.append(btn);
  }
  return wrap;
}
