// Config view (reference: web-ui/src/views/Config): tier/region/port form
// -> generated YAML preview -> validate -> save to disk.

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast } from "../ui.js";

const TIER_LABELS = {
  minimal: "Minimal — OCR only",
  light_weight: "Light — OCR + CLIP + face",
  full: "Full — OCR + CLIP + face + VLM",
};

export function renderConfig(root) {
  const s = wizard.state;
  root.append(
    el("h2", { class: "view-title" }, "Configuration"),
    el("p", { class: "view-sub" }, [
      "Generate the deployment YAML for preset ",
      el("span", { class: "badge accent" }, s.preset || "—"),
      ". Per-service batch and bucket sizes come from the preset's chip generation.",
    ]),
    el("div", { class: "grid2" }, [
      el("div", { class: "card" }, [
        el("h3", {}, "Deployment options"),
        field("Service tier", tierSelect()),
        field(
          "Model hub region",
          seg("region", [
            ["other", "International (HuggingFace)"],
            ["cn", "China (ModelScope)"],
          ])
        ),
        field("Cache directory", input("cacheDir", "text")),
        field("gRPC port", input("port", "number")),
        el("div", { class: "checkrow" }, [
          checkbox("mdns"),
          "advertise on the LAN via mDNS (_lumen._tcp)",
        ]),
        el("div", { class: "row" }, [
          el("button", { class: "btn primary", id: "cfg-generate" }, "Generate config"),
          el("span", { class: "muted", id: "cfg-status" }, s.configGenerated ? "config generated" : ""),
        ]),
      ]),
      el("div", { class: "card" }, [
        el("h3", {}, "Save & validate"),
        field("Config file path", el("input", { type: "text", id: "cfg-path", value: s.configPath || "lumen-config.yaml" })),
        el("div", { class: "row" }, [
          el("button", { class: "btn", id: "cfg-save", disabled: s.configGenerated ? undefined : "1" }, "Save YAML"),
          el("button", { class: "btn", id: "cfg-validate", disabled: s.configGenerated ? undefined : "1" }, "Validate"),
          el("span", { class: "muted", id: "cfg-save-status" }, s.configPath ? `saved: ${s.configPath}` : ""),
        ]),
        el("p", { class: "muted" }, "The server step launches the gRPC hub from this saved file."),
      ]),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Generated YAML"),
      el("pre", { class: "code", id: "cfg-yaml" }, s.configGenerated ? "loading…" : "— generate first —"),
    ])
  );

  if (s.configGenerated) loadYaml(root);

  root.querySelector("#cfg-generate").onclick = async () => {
    const btn = root.querySelector("#cfg-generate");
    if (wizard.state.preset === "(existing config)") {
      // The welcome "open existing config" path sets a placeholder that
      // /config/generate would reject; regeneration needs a real preset.
      toast("pick a topology preset on the Hardware step first", true);
      return;
    }
    btn.disabled = true;
    try {
      await api.generateConfig({
        preset: wizard.state.preset,
        tier: wizard.state.tier,
        region: wizard.state.region,
        cache_dir: wizard.state.cacheDir,
        port: Number(wizard.state.port),
        mdns: wizard.state.mdns,
      });
      wizard.update({ configGenerated: true, configPath: null });
      root.querySelector("#cfg-status").textContent = "config generated";
      root.querySelector("#cfg-save").disabled = false;
      root.querySelector("#cfg-validate").disabled = false;
      await loadYaml(root);
      toast("config generated");
    } catch (e) {
      toast(e.message, true);
    } finally {
      btn.disabled = false;
    }
  };

  root.querySelector("#cfg-save").onclick = async () => {
    try {
      const { path } = await api.saveConfig(root.querySelector("#cfg-path").value);
      wizard.update({ configPath: path });
      root.querySelector("#cfg-save-status").textContent = `saved: ${path}`;
      toast(`saved ${path}`);
    } catch (e) {
      toast(e.message, true);
    }
  };

  root.querySelector("#cfg-validate").onclick = async () => {
    try {
      const cfg = await api.currentConfig();
      const v = await api.validateConfig(cfg);
      if (v.valid) toast(`valid — services: ${v.services.join(", ")}`);
      else toast(`invalid: ${v.error}`, true);
    } catch (e) {
      toast(e.message, true);
    }
  };
}

async function loadYaml(root) {
  try {
    root.querySelector("#cfg-yaml").textContent = await api.configYaml();
  } catch (e) {
    root.querySelector("#cfg-yaml").textContent = `(${e.message})`;
  }
}

function field(labelText, control) {
  return el("label", { class: "field" }, [el("span", {}, labelText), control]);
}

function input(key, type) {
  const node = el("input", { type, value: wizard.state[key] });
  node.onchange = () => wizard.update({ [key]: node.value, configGenerated: false });
  return node;
}

function checkbox(key) {
  const node = el("input", { type: "checkbox" });
  node.checked = Boolean(wizard.state[key]);
  node.onchange = () => wizard.update({ [key]: node.checked, configGenerated: false });
  return node;
}

function tierSelect() {
  const node = el(
    "select",
    {},
    Object.entries(TIER_LABELS).map(([value, label]) => {
      const opt = el("option", { value }, label);
      if (wizard.state.tier === value) opt.selected = true;
      return opt;
    })
  );
  node.onchange = () => wizard.update({ tier: node.value, configGenerated: false });
  return node;
}

function seg(key, options) {
  const wrap = el("div", { class: "seg" });
  for (const [value, label] of options) {
    const btn = el("button", { type: "button" }, label);
    if (wizard.state[key] === value) btn.classList.add("active");
    btn.onclick = () => {
      wizard.update({ [key]: value, configGenerated: false });
      for (const b of wrap.children) b.classList.toggle("active", b === btn);
    };
    wrap.append(btn);
  }
  return wrap;
}
