// OpenPath view (reference: web-ui/src/views/OpenPath.tsx): enter the
// path of an existing deployment config, validate it against the control
// plane, then hand off to the SessionHub view. A first-class route
// outside the linear setup stepper, exactly like the reference's /open.

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast } from "../ui.js";

export function renderOpenPath(root) {
  root.append(
    el("div", { class: "hero" }, [
      el("h1", {}, "Open existing deployment"),
      el(
        "p",
        {},
        "Point at a lumen-config.yaml from a previous setup. The config " +
          "is validated and the session hub shows whether the deployment " +
          "can start as-is or needs the installer."
      ),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Config path"),
      el("div", { class: "row" }, [
        el("input", {
          id: "open-path",
          class: "input",
          placeholder: "/path/to/lumen-config.yaml",
          value: wizard.state.openPath || "",
          style: "flex:1",
        }),
        el("button", { class: "btn primary", id: "open-validate" }, "Validate →"),
      ]),
      el("div", { id: "open-result" }),
    ])
  );

  const input = root.querySelector("#open-path");
  const resultBox = root.querySelector("#open-result");
  const validate = async () => {
    const path = input.value.trim();
    if (!path) {
      resultBox.replaceChildren(el("p", { class: "err-note" }, "enter a config path"));
      return;
    }
    resultBox.replaceChildren(el("p", { class: "muted" }, "validating…"));
    try {
      const out = await api.configLoad(path);
      if (!root.isConnected) return;
      // Mark prior steps complete so stepper gating allows jumps the hub
      // recommends; the placeholder preset is never used for generation.
      wizard.update({
        preset: wizard.state.preset || "(existing config)",
        configGenerated: true,
        configPath: out.path,
        openPath: path,
      });
      resultBox.replaceChildren(
        el("p", { class: "ok-note" }, `✓ valid config (services: ${out.services.join(", ")})`)
      );
      wizard.goto("sessionhub");
    } catch (e) {
      if (!root.isConnected) return;
      resultBox.replaceChildren(el("p", { class: "err-note" }, `✕ ${e.message}`));
    }
  };
  root.querySelector("#open-validate").onclick = validate;
  input.onkeydown = (ev) => {
    if (ev.key === "Enter") validate();
  };

  api.health().catch((e) => toast(`control plane: ${e.message}`, true));
}
