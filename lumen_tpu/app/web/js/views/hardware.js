// Hardware view (reference: web-ui/src/views/Hardware): detect the TPU,
// show generation/slice/HBM/FLOPs, pick a topology preset.

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast } from "../ui.js";

export function renderHardware(root) {
  root.append(
    el("h2", { class: "view-title" }, "Hardware"),
    el("p", { class: "view-sub" }, "Detected accelerators and the topology presets they support."),
    el("div", { class: "card", id: "hw-card" }, [
      el("div", { class: "row" }, [
        el("span", { class: "spin" }, "◌"),
        " probing accelerators (runs in a subprocess; first probe can take ~30s)…",
      ]),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Environment"),
      el("div", { class: "muted", id: "env-card" }, "checking runtime stack…"),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Topology preset"),
      el("div", { class: "muted", id: "preset-hint" }, "Presets load after detection."),
      el("div", { class: "preset-grid", id: "preset-grid" }),
    ])
  );

  detect(root);
  envCheck(root);
}

async function envCheck(root) {
  const card = root.querySelector("#env-card");
  try {
    const report = await api.hardwareCheck(wizard.state.cacheDir);
    card.classList.remove("muted");
    card.replaceChildren(
      el("div", {}, [
        report.ok
          ? el("span", { class: "badge ok" }, "environment ready")
          : el("span", { class: "badge err" }, "missing requirements"),
      ]),
      el(
        "dl",
        { class: "kv" },
        report.checks
          .map((c) =>
            kv(
              (c.ok ? "✓ " : c.required ? "✗ " : "– ") + c.name,
              c.detail
            )
          )
          .flat()
      )
    );
  } catch (e) {
    card.textContent = `environment check failed: ${e.message}`;
  }
}

async function detect(root) {
  const hwCard = root.querySelector("#hw-card");
  let report, presets;
  try {
    [report, presets] = await Promise.all([api.hardwareDetect(), api.presets()]);
  } catch (e) {
    hwCard.replaceChildren(
      el("div", { class: "badge err" }, "detection failed"),
      el("p", { class: "muted" }, e.message),
      el("button", { class: "btn small", onclick: () => { root.replaceChildren(); renderHardware(root); } }, "Retry")
    );
    return;
  }
  wizard.update({ hardware: report });

  const hw = report.hardware;
  const chip = report.chip;
  hwCard.replaceChildren(
    el("h3", {}, [
      "Detected: ",
      hw.platform === "tpu"
        ? el("span", { class: "badge ok" }, `${hw.device_kind || "TPU"} ×${hw.device_count}`)
        : el("span", { class: "badge warn" }, "no TPU — CPU mode"),
    ]),
    el("dl", { class: "kv" }, [
      kv("platform", hw.platform),
      kv("device kind", hw.device_kind || "—"),
      kv("chips", hw.device_count),
      kv("generation", report.generation || "—"),
      chip ? kv("HBM / chip", `${chip.hbm_gb} GB`) : "",
      chip ? kv("peak bf16", `${chip.bf16_tflops} TFLOP/s per chip (${chip.slice_bf16_tflops} slice)`) : "",
      kv("hosts", hw.process_count),
      kv("host CPUs", hw.cpu_count),
      kv("host memory", `${hw.memory_gb} GB`),
      hw.error ? kv("probe error", hw.error) : "",
    ].flat())
  );

  const grid = root.querySelector("#preset-grid");
  const hint = root.querySelector("#preset-hint");
  const supported = new Set(report.supported_presets || []);
  hint.textContent = `Recommended for this machine: ${report.recommended_preset}`;
  if (!wizard.state.preset && report.recommended_preset) {
    wizard.update({ preset: report.recommended_preset });
  }

  for (const [name, p] of Object.entries(presets.presets)) {
    const ok = supported.has(name);
    const card = el(
      "button",
      { class: "preset-card" + (ok ? "" : " unsupported"), disabled: ok ? undefined : "1" },
      [
        el("div", { class: "preset-name" }, [
          name,
          name === report.recommended_preset ? el("span", { class: "badge accent" }, "recommended") : "",
          p.generation ? el("span", { class: "badge" }, p.generation) : "",
        ]),
        el("div", { class: "preset-desc" }, p.description),
        el(
          "div",
          { class: "preset-meta" },
          `mesh ${JSON.stringify(p.mesh_axes)} · ${p.dtype} · clip ${p.batch_size} · ` +
            `face ${p.face_batch} · ocr ${p.ocr_batch} · vlm ${p.vlm_gen_batch} · tier ≤ ${p.max_tier}`
        ),
      ]
    );
    if (ok) {
      card.onclick = () => {
        wizard.update({ preset: name, configGenerated: false });
        refreshSelection(grid);
        toast(`preset: ${name}`);
      };
    }
    card.dataset.preset = name;
    grid.append(card);
  }
  refreshSelection(grid);
}

function refreshSelection(grid) {
  for (const card of grid.children) {
    card.classList.toggle("selected", card.dataset.preset === wizard.state.preset);
  }
}

function kv(k, v) {
  return [el("dt", {}, k), el("dd", {}, String(v))];
}
