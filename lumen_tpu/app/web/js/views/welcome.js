// Welcome view (reference: web-ui/src/views/Welcome).

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast } from "../ui.js";

export function renderWelcome(root) {
  const resume = wizard.state.preset || wizard.state.configGenerated;
  root.append(
    el("div", { class: "hero" }, [
      el("div", { class: "glyph" }, "◳"),
      el("h1", {}, "Welcome to lumen-tpu"),
      el(
        "p",
        {},
        "TPU-native photo-indexing inference: CLIP embeddings, face " +
          "detection and recognition, OCR, and VLM captioning behind one " +
          "gRPC hub. This wizard detects your TPU, generates a deployment " +
          "config, installs model weights, and launches the server."
      ),
    ]),
    el("div", { class: "feature-row" }, [
      feature("Detect", "TPU generation, slice size, HBM and peak FLOPs — with a recommended topology preset."),
      feature("Configure", "Per-service batch and bucket sizing from the preset; single YAML, validated live."),
      feature("Install", "Model weights downloaded and verified into the cache, with live progress."),
      feature("Serve", "The gRPC hub as a supervised subprocess with health checks and live logs."),
    ]),
    el("div", { class: "hero" }, [
      el("button", { class: "btn primary", id: "welcome-start" }, resume ? "Resume setup →" : "Get started →"),
      " ",
      resume ? el("button", { class: "btn ghost", id: "welcome-reset" }, "Start over") : "",
    ]),
    // Reference OpenPath / SessionHub entry: existing deployments go
    // through their own first-class views (views/openpath.js ->
    // views/sessionhub.js), not the setup stepper.
    el("div", { class: "card" }, [
      el("h3", {}, "Already have a config?"),
      el("div", { class: "muted" }, "Open an existing lumen-config.yaml — the session hub checks the deployment and routes to serve or install."),
      el("div", { class: "row" }, [
        el("button", { class: "btn", id: "welcome-open" }, "Open existing deployment →"),
      ]),
    ])
  );

  root.querySelector("#welcome-start").onclick = () => wizard.next();
  const resetBtn = root.querySelector("#welcome-reset");
  if (resetBtn) resetBtn.onclick = () => wizard.reset();
  root.querySelector("#welcome-open").onclick = () => wizard.goto("openpath");

  // connectivity check so a dead control plane is obvious immediately
  api.health().catch((e) => toast(`control plane: ${e.message}`, true));
}

function feature(title, text) {
  return el("div", { class: "card" }, [
    el("h3", {}, title),
    el("div", { class: "muted" }, text),
  ]);
}
