// Welcome view (reference: web-ui/src/views/Welcome).

import { api } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast } from "../ui.js";

export function renderWelcome(root) {
  const resume = wizard.state.preset || wizard.state.configGenerated;
  root.append(
    el("div", { class: "hero" }, [
      el("div", { class: "glyph" }, "◳"),
      el("h1", {}, "Welcome to lumen-tpu"),
      el(
        "p",
        {},
        "TPU-native photo-indexing inference: CLIP embeddings, face " +
          "detection and recognition, OCR, and VLM captioning behind one " +
          "gRPC hub. This wizard detects your TPU, generates a deployment " +
          "config, installs model weights, and launches the server."
      ),
    ]),
    el("div", { class: "feature-row" }, [
      feature("Detect", "TPU generation, slice size, HBM and peak FLOPs — with a recommended topology preset."),
      feature("Configure", "Per-service batch and bucket sizing from the preset; single YAML, validated live."),
      feature("Install", "Model weights downloaded and verified into the cache, with live progress."),
      feature("Serve", "The gRPC hub as a supervised subprocess with health checks and live logs."),
    ]),
    el("div", { class: "hero" }, [
      el("button", { class: "btn primary", id: "welcome-start" }, resume ? "Resume setup →" : "Get started →"),
      " ",
      resume ? el("button", { class: "btn ghost", id: "welcome-reset" }, "Start over") : "",
    ]),
    // Reference OpenPath view: skip generation, run an existing YAML.
    el("div", { class: "card" }, [
      el("h3", {}, "Already have a config?"),
      el("div", { class: "muted" }, "Load an existing lumen-config.yaml and jump straight to install/serve."),
      el("div", { class: "row" }, [
        el("input", { id: "welcome-path", class: "input", placeholder: "/path/to/lumen-config.yaml", style: "flex:1" }),
        el("button", { class: "btn", id: "welcome-open" }, "Open"),
      ]),
      // Reference SessionHub: after opening, the recommendation card says
      // whether this deployment can start as-is or needs the installer.
      el("div", { id: "welcome-session" }),
    ])
  );

  root.querySelector("#welcome-start").onclick = () => wizard.next();
  const resetBtn = root.querySelector("#welcome-reset");
  if (resetBtn) resetBtn.onclick = () => wizard.reset();
  root.querySelector("#welcome-open").onclick = async () => {
    const path = root.querySelector("#welcome-path").value.trim();
    if (!path) return toast("enter a config path", true);
    try {
      const out = await api.configLoad(path);
      // Mark the prior steps complete so nav gating lets the operator
      // jump ahead; the placeholder preset is never used for generation
      // (the loaded YAML already carries the real settings). Stay ON the
      // welcome view: the session card below recommends where to go
      // (jumping immediately would unmount the card before it rendered).
      wizard.update({
        preset: wizard.state.preset || "(existing config)",
        configGenerated: true,
        configPath: out.path,
      });
      toast(`loaded ${out.path} (services: ${out.services.join(", ")})`);
      renderSessionCard(root, out.path);
    } catch (e) {
      toast(e.message, true);
    }
  };

  // connectivity check so a dead control plane is obvious immediately
  api.health().catch((e) => toast(`control plane: ${e.message}`, true));
}

// SessionHub recommendation card: offline-checks the opened config's
// models in the cache and routes — start the server as-is, or run the
// installer for what's missing.
async function renderSessionCard(root, configPath) {
  const box = root.querySelector("#welcome-session");
  if (!box) return;
  box.replaceChildren(el("p", { class: "muted" }, "checking installed models…"));
  let s;
  try {
    s = await api.sessionStatus(configPath);
  } catch (e) {
    box.replaceChildren(el("p", { class: "err-note" }, `could not check the deployment: ${e.message}`));
    return;
  }
  if (!root.isConnected) return;
  const go = (step, label) => {
    const btn = el("button", { class: "btn primary" }, label);
    btn.onclick = () => wizard.update({ step });
    return btn;
  };
  if (s.ready_to_start) {
    box.replaceChildren(
      el("p", { class: "ok-note" }, `✓ ${s.message}`),
      el("div", { class: "row" }, [go("server", "Go to Server →")])
    );
  } else {
    box.replaceChildren(
      el("p", { class: "warn-note" }, `⚠ ${s.message}`),
      el(
        "ul",
        { class: "steplist" },
        (s.models || [])
          .filter((m) => !m.present)
          .map((m) =>
            el("li", { class: "failed" }, [
              el("span", { class: "step-ico" }, "✕"),
              `${m.service}/${m.alias}: ${m.model}`,
              el("span", { class: "step-detail" }, m.error || "missing"),
            ])
          )
      ),
      el("div", { class: "row" }, [go("install", "Run install →")])
    );
  }
}

function feature(title, text) {
  return el("div", { class: "card" }, [
    el("h3", {}, title),
    el("div", { class: "muted" }, text),
  ]);
}
