// Server view (reference: web-ui/src/views/Server + SessionHub): start /
// stop / restart the supervised gRPC hub, watch health + live logs.

import { api, logStream } from "../api.js";
import { wizard } from "../wizard.js";
import { el, toast, attachLogPane, logLine } from "../ui.js";

let pollTimer = null;

export function renderServer(root, onLeave) {
  root.append(
    el("h2", { class: "view-title" }, "Server"),
    el("p", { class: "view-sub" }, "The gRPC hub runs as a supervised subprocess of this control plane."),
    el("div", { class: "grid2" }, [
      el("div", { class: "card" }, [
        el("h3", {}, "Status"),
        el("dl", { class: "kv", id: "srv-kv" }, []),
        el("div", { class: "row", style: "margin-top:12px" }, [
          el("button", { class: "btn primary", id: "srv-start" }, "Start"),
          el("button", { class: "btn", id: "srv-restart" }, "Restart"),
          el("button", { class: "btn danger", id: "srv-stop" }, "Stop"),
        ]),
        el("p", { class: "muted", id: "srv-msg" }),
      ]),
      el("div", { class: "card" }, [
        el("h3", {}, "Serving metrics"),
        el("pre", { class: "code", id: "srv-metrics", style: "max-height:220px" }, "—"),
        el("button", { class: "btn small", id: "srv-metrics-refresh", style: "margin-top:8px" }, "Refresh metrics"),
      ]),
    ]),
    el("div", { class: "card" }, [
      el("h3", {}, "Live logs"),
      el("div", { class: "logpane", id: "srv-logs" }),
    ])
  );

  const logPane = root.querySelector("#srv-logs");
  const unsubLogs = attachLogPane(logPane, logStream);
  // Backfill: the WS stream only carries lines from after this view
  // connected; GET /server/logs serves the earlier history.
  api
    .serverLogs()
    .then((out) => {
      for (const line of (out.lines || []).reverse()) {
        logPane.prepend(logLine({ message: line.message }));
      }
    })
    .catch(() => {});
  onLeave(() => {
    unsubLogs();
    clearTimeout(pollTimer);
  });

  const act = (fn, label) => async () => {
    try {
      await fn();
      toast(label);
      refresh(root);
    } catch (e) {
      toast(e.message, true);
    }
  };
  root.querySelector("#srv-start").onclick = act(
    () =>
      api.serverStart({
        ...(wizard.state.configPath ? { config_path: wizard.state.configPath } : {}),
        // OS-assigned metrics port: without it ServerManager never learns
        // a metrics address and the metrics panel stays empty forever.
        extra_args: ["--metrics-port", "0"],
      }),
    "server starting"
  );
  root.querySelector("#srv-stop").onclick = act(() => api.serverStop(), "server stopped");
  root.querySelector("#srv-restart").onclick = act(() => api.serverRestart(), "server restarting");
  root.querySelector("#srv-metrics-refresh").onclick = () => loadMetrics(root);

  refresh(root);
}

async function refresh(root) {
  if (!root.isConnected) return;
  // One poll chain only: a button-triggered refresh replaces the pending
  // tick instead of stacking a second chain.
  clearTimeout(pollTimer);
  let info;
  try {
    info = await api.serverStatus();
  } catch (e) {
    root.querySelector("#srv-msg").textContent = e.message;
    pollTimer = setTimeout(() => refresh(root), 3000);
    return;
  }
  const kvEl = root.querySelector("#srv-kv");
  kvEl.replaceChildren(
    ...kv("state", badgeFor(info)),
    ...kv("healthy", String(info.healthy)),
    ...kv("pid", info.pid ?? "—"),
    ...kv("config", info.config_path ?? wizard.state.configPath ?? "—"),
    ...kv("gRPC port", info.port ?? "—"),
    ...kv("metrics port", info.metrics_port ?? "—"),
    ...kv("uptime", info.uptime_s != null ? `${Math.round(info.uptime_s)}s` : "—")
  );
  const live = info.status === "running" || info.status === "starting";
  const crashed = info.status === "failed";
  // Crash recovery (reference Server view's failure states): say what
  // happened (exit code) and leave BOTH recovery paths enabled — Restart
  // relaunches with the same config, Start allows picking a new one.
  const msg = root.querySelector("#srv-msg");
  if (crashed) {
    msg.textContent =
      `server exited unexpectedly (exit code ${info.exit_code ?? "?"}) — ` +
      "see the logs below, then Restart to relaunch with the same config.";
    msg.classList.add("err-note");
  } else {
    // #srv-msg only ever carries transient notices (fetch errors, the
    // crash banner) — a successful status poll clears it outright.
    msg.textContent = "";
    msg.classList.remove("err-note");
  }
  root.querySelector("#srv-start").disabled = live;
  root.querySelector("#srv-stop").disabled = !live;
  root.querySelector("#srv-restart").disabled = !(live || crashed);
  pollTimer = setTimeout(() => refresh(root), 2500);
}

async function loadMetrics(root) {
  try {
    const text = await api.metrics();
    root.querySelector("#srv-metrics").textContent = text || "(no metrics yet)";
  } catch (e) {
    root.querySelector("#srv-metrics").textContent = e.message;
  }
}

function badgeFor(info) {
  if (info.status === "running" && info.healthy) return el("span", { class: "badge ok" }, "running");
  if (info.status === "running") return el("span", { class: "badge warn" }, "running (unhealthy)");
  if (info.status === "starting") return el("span", { class: "badge warn" }, "starting");
  if (info.status === "failed") return el("span", { class: "badge err" }, "failed");
  return el("span", { class: "badge" }, "stopped");
}

function kv(k, v) {
  return [el("dt", {}, k), el("dd", {}, v instanceof Node ? v : String(v))];
}
