"""Mirror-aware package/wheel resolution for the installer.

Reference equivalent: ``lumen-app/src/lumen_app/utils/package_resolver.py``
(MirrorSelector + GitHubPackageResolver, :19-321) — region ``cn`` rewrites
GitHub URLs through a proxy mirror and prefers a CN PyPI index, with the
official endpoints always kept as fallback. Here the same policy is a
small, injectable module: network access goes through the ``fetch_json`` /
``urlretrieve`` callables so the logic is fully testable offline (TPU VMs
in CI have no egress).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

logger = logging.getLogger(__name__)

GITHUB_MIRROR_CN = "https://gh-proxy.org/https://github.com"
PYPI_OFFICIAL = "https://pypi.org/simple/"
PYPI_MIRROR_CN = "https://mirrors.aliyun.com/pypi/simple/"

#: GitHub project whose releases carry this framework's wheels.
REPO = "LumilioPhotos/lumen-tpu"
API_BASE = "https://api.github.com"


def github_urls(base_url: str, region: str) -> list[str]:
    """Ordered download candidates: CN mirror first for region=cn, the
    original URL always last (reference ``get_github_urls``)."""
    urls = []
    if region == "cn":
        urls.append(base_url.replace("https://github.com", GITHUB_MIRROR_CN))
    urls.append(base_url)
    return urls


def pypi_indexes(region: str) -> list[str]:
    """Ordered pip indexes: CN mirror first for region=cn, official always
    included as fallback (reference ``get_pypi_indexes``)."""
    indexes = []
    if region == "cn":
        indexes.append(PYPI_MIRROR_CN)
    indexes.append(PYPI_OFFICIAL)
    return indexes


def pip_index_args(region: str) -> list[str]:
    """pip arguments implementing mirror-first-with-fallback: the mirror
    becomes --index-url and the official index rides as --extra-index-url,
    so a mirror outage degrades instead of failing the install."""
    indexes = pypi_indexes(region)
    args = ["--index-url", indexes[0]]
    for fallback in indexes[1:]:
        args += ["--extra-index-url", fallback]
    return args


def _default_fetch_json(url: str, timeout: float = 30.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read().decode())


@dataclass
class ReleaseWheelResolver:
    """Resolve + download this project's wheels from GitHub releases
    (reference ``GitHubPackageResolver``, :61-321): latest tag -> matching
    ``<name>-*-py3-none-any.whl`` asset -> download via the region's URL
    ladder."""

    region: str = "other"
    repo: str = REPO
    fetch_json: Callable[[str], dict] = field(default=_default_fetch_json)
    urlretrieve: Callable[..., object] = field(
        default=urllib.request.urlretrieve  # noqa: S310
    )

    def latest_release(self) -> str:
        data = self.fetch_json(f"{API_BASE}/repos/{self.repo}/releases/latest")
        tag = data.get("tag_name")
        if not tag:
            raise RuntimeError(f"no tag_name in latest release of {self.repo}")
        return tag

    def resolve_wheel_url(self, package: str, tag: str | None = None) -> tuple[str, str]:
        """-> (browser_download_url, tag) for the pure-python wheel of
        ``package`` in the given (default: latest) release."""
        tag = tag or self.latest_release()
        data = self.fetch_json(f"{API_BASE}/repos/{self.repo}/releases/tags/{tag}")
        prefix = f"{package.replace('-', '_')}-"
        for asset in data.get("assets", []):
            name = asset.get("name", "")
            if name.startswith(prefix) and name.endswith("-py3-none-any.whl"):
                url = asset.get("browser_download_url")
                if url:
                    return url, tag
        raise RuntimeError(f"no wheel asset for {package!r} in release {tag}")

    def download(
        self,
        url: str,
        dest_dir: str | Path,
        log: Callable[[str], None] | None = None,
    ) -> Path:
        """Download through the region's URL ladder (mirror first for cn,
        original as fallback); returns the local wheel path."""
        dest_dir = Path(dest_dir)
        dest_dir.mkdir(parents=True, exist_ok=True)
        dest = dest_dir / url.rsplit("/", 1)[-1]
        last_error: Exception | None = None
        for candidate in github_urls(url, self.region):
            try:
                if log:
                    log(f"downloading {dest.name} from {candidate}")
                self.urlretrieve(candidate, dest)
                return dest
            except Exception as e:  # noqa: BLE001 - try the next mirror
                last_error = e
                logger.warning("download failed from %s: %s", candidate, e)
        raise RuntimeError(f"all mirrors failed for {url}: {last_error}")

    def fetch_packages(
        self,
        packages: list[str],
        dest_dir: str | Path,
        log: Callable[[str], None] | None = None,
    ) -> list[Path]:
        """Resolve + download each package's wheel from the latest release;
        one tag lookup shared across packages."""
        if not packages:
            return []
        tag = self.latest_release()
        out = []
        for package in packages:
            url, _ = self.resolve_wheel_url(package, tag)
            out.append(self.download(url, dest_dir, log))
        return out
