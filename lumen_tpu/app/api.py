"""REST + WebSocket API (reference routers ``lumen-app/src/lumen_app/api/``
and WS log stream ``websockets/logs.py``).

Routes (same surface as the reference, ``main.py:64-68``):

- ``GET  /health``
- ``POST /api/v1/config/generate``      {preset, tier, region, cache_dir, port}
- ``GET  /api/v1/config/current``
- ``POST /api/v1/config/validate``      {config: <dict>} | {path}
- ``POST /api/v1/config/validate-path`` {path}
- ``POST /api/v1/config/load``          {path}
- ``POST /api/v1/config/save``          {path}
- ``GET  /api/v1/config/yaml``
- ``GET  /api/v1/config/presets``
- ``GET  /api/v1/hardware/info``
- ``GET  /api/v1/hardware/detect``
- ``GET  /api/v1/hardware/check``      ?cache_dir=...
- ``POST /api/v1/install/setup``        {venv_path?, packages?, config_path?, download?, region?}
- ``POST /api/v1/install/check-path``   {path}
- ``GET  /api/v1/install/tasks``
- ``GET  /api/v1/install/status/{task_id}``
- ``GET  /api/v1/install/logs/{task_id}``
- ``POST /api/v1/install/cancel/{task_id}``
- ``GET  /api/v1/server/status``
- ``POST /api/v1/server/start``         {config_path?}
- ``POST /api/v1/server/stop``
- ``POST /api/v1/server/restart``
- ``GET  /api/v1/server/logs``
- ``GET  /api/v1/metrics``
- ``WS   /ws/logs``  frames {type: connected|log|heartbeat} with 1s heartbeat
  (reference ``websockets/logs.py:18-158``)
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Any

from aiohttp import WSMsgType, web

from lumen_tpu.app.config_gen import TIERS, config_to_yaml, generate_config
from lumen_tpu.app.hardware import detect_hardware, hardware_report
from lumen_tpu.app.install import InstallOptions, InstallOrchestrator
from lumen_tpu.app.presets import PRESETS
from lumen_tpu.app.server_manager import ServerManager
from lumen_tpu.app.state import AppState

logger = logging.getLogger(__name__)

HEARTBEAT_S = 1.0

STATE_KEY: web.AppKey[AppState] = web.AppKey("state", AppState)
ORCHESTRATOR_KEY: web.AppKey[InstallOrchestrator] = web.AppKey(
    "orchestrator", InstallOrchestrator
)
MANAGER_KEY: web.AppKey[ServerManager] = web.AppKey("manager", ServerManager)


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def _int_query(request: web.Request, name: str, default: int) -> int:
    """Parse a non-negative integer query param; raises a 400 on junk."""
    raw = request.query.get(name)
    if raw is None:
        return default
    def bad(msg: str):
        return web.HTTPBadRequest(
            text=json.dumps({"error": msg}), content_type="application/json"
        )

    try:
        value = int(raw)
    except ValueError:
        raise bad(f"{name} must be an integer") from None
    if value < 0:
        raise bad(f"{name} must be >= 0")
    return value


def _bad_request(e: Exception) -> web.Response:
    return _json_error(400, str(e))


async def _body(request: web.Request) -> dict[str, Any]:
    if request.can_read_body:
        try:
            return await request.json()
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": f"invalid JSON: {e}"}))
    return {}


def build_app(state: AppState | None = None) -> web.Application:
    state = state or AppState()
    orchestrator = InstallOrchestrator(state)
    manager = ServerManager(state)
    state.server_manager = manager

    app = web.Application()
    app[STATE_KEY] = state
    app[ORCHESTRATOR_KEY] = orchestrator
    app[MANAGER_KEY] = manager
    _bg_tasks: set[asyncio.Task] = set()

    # -- health -----------------------------------------------------------

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "subscribers": state.subscriber_count})

    # -- config -----------------------------------------------------------

    async def config_generate(request: web.Request) -> web.Response:
        body = await _body(request)
        preset_name = body.get("preset", "cpu")
        if preset_name == "auto":
            # Pick mesh axes + batch defaults from the hardware probe
            # (reference analog: detection-ordered PresetRegistry,
            # ``utils/preset_registry.py:118-170``).
            report = await asyncio.to_thread(hardware_report)
            preset_name = report["recommended_preset"]
            state.broadcast_log(f"hardware probe recommends preset {preset_name}")
        try:
            cfg = generate_config(
                preset_name=preset_name,
                tier=body.get("tier", "light_weight"),
                region=body.get("region", "other"),
                cache_dir=body.get("cache_dir", "~/.lumen-tpu"),
                port=int(body.get("port", 50051)),
                mdns=bool(body.get("mdns", True)),
            )
        except ValueError as e:
            return _bad_request(e)
        state.config = cfg
        # The previous save (if any) no longer matches the new config; a
        # path-less /server/start must not launch the stale YAML.
        state.config_path = None
        state.broadcast_log(f"config generated (preset={preset_name})")
        return web.json_response(cfg.model_dump(exclude_none=True))

    async def config_current(request: web.Request) -> web.Response:
        if state.config is None:
            return _json_error(404, "no config generated or loaded yet")
        return web.json_response(state.config.model_dump(exclude_none=True))

    def _field_errors(e: Exception) -> list[dict] | None:
        """Pydantic ValidationError -> per-field error list the web UI can
        anchor to inputs ({"loc": "services.clip.port", "msg", "type"});
        None for non-pydantic failures (I/O, YAML parse). The core layer
        wraps pydantic in ConfigError (``validate_config_dict ... from e``),
        so follow the cause chain to the ValidationError."""
        errs = None
        seen = 0
        cur: BaseException | None = e
        while cur is not None and seen < 5:
            errs = getattr(cur, "errors", None)
            if callable(errs):
                break
            cur = cur.__cause__
            seen += 1
        if not callable(errs):
            return None
        out = []
        try:
            for err in errs():
                out.append({
                    "loc": ".".join(str(p) for p in err.get("loc", ())),
                    "msg": err.get("msg", ""),
                    "type": err.get("type", ""),
                })
        except Exception:  # noqa: BLE001 - error reporting must not raise
            return None
        return out or None

    def _parse_yaml_body(text: str) -> dict:
        """YAML editor text -> config dict; parse failures carry the
        problem line/column so the UI can point at the spot."""
        import yaml

        try:
            data = yaml.safe_load(text)
        except yaml.YAMLError as e:
            mark = getattr(e, "problem_mark", None)
            at = f" at line {mark.line + 1}, column {mark.column + 1}" if mark else ""
            raise ValueError(f"YAML parse error{at}: {getattr(e, 'problem', e)}") from e
        if not isinstance(data, dict):
            raise ValueError(f"YAML must be a mapping, got {type(data).__name__}")
        return data

    def _validate_yaml_text(text: str, loose: bool):
        """Editor YAML -> (cfg, warnings). Shared by /config/validate and
        /config/save so the two can never diverge on parse/loose
        semantics (the UI promises their verdicts agree)."""
        from lumen_tpu.core.config import (
            validate_config_dict,
            validate_config_loose,
        )

        data = _parse_yaml_body(text)
        if loose:
            return validate_config_loose(data)
        return validate_config_dict(data), []

    def _invalid_body(e: Exception) -> dict:
        """The one error shape both config endpoints return for a failed
        validation: summary string + per-field errors when pydantic."""
        out = {"valid": False, "error": str(e)}
        fe = _field_errors(e)
        if fe:
            out["field_errors"] = fe
        return out

    def _validated(body: dict, require_path: bool = False) -> web.Response:
        from lumen_tpu.core.config import (
            load_config,
            load_config_loose,
            validate_config_dict,
            validate_config_loose,
        )

        loose = bool(body.get("loose"))
        warnings: list[str] = []
        try:
            if "path" in body:
                if loose:
                    cfg, warnings = load_config_loose(body["path"])
                else:
                    cfg = load_config(body["path"])
            elif "yaml" in body and not require_path:
                # The web UI's editable-YAML flow: validate the editor
                # text as typed, before anything touches disk.
                cfg, warnings = _validate_yaml_text(body["yaml"], loose)
            elif "config" in body and not require_path:
                if loose:
                    cfg, warnings = validate_config_loose(body["config"])
                else:
                    cfg = validate_config_dict(body["config"])
            else:
                return _json_error(
                    400, "provide 'path'" if require_path else "provide 'config' (dict), 'yaml' (text), or 'path'"
                )
        except Exception as e:  # noqa: BLE001 - validation errors reported to client
            return web.json_response(_invalid_body(e))
        out = {"valid": True, "services": sorted(cfg.services)}
        if warnings:
            out["warnings"] = warnings
        return web.json_response(out)

    async def config_validate(request: web.Request) -> web.Response:
        return _validated(await _body(request))

    async def config_validate_path(request: web.Request) -> web.Response:
        """Reference ``POST /config/validate-path`` (``api/config.py``) —
        the path-only view of the shared validation helper."""
        return _validated(await _body(request), require_path=True)

    async def config_load(request: web.Request) -> web.Response:
        """Reference ``POST /config/load``: make an on-disk YAML the app's
        current config (the wizard's open-existing path)."""
        from lumen_tpu.core.config import load_config

        body = await _body(request)
        if "path" not in body:
            return _json_error(400, "provide 'path'")
        try:
            cfg = load_config(body["path"])
        except Exception as e:  # noqa: BLE001
            return _json_error(400, f"config load failed: {e}")
        state.config = cfg
        state.config_path = os.path.expanduser(body["path"])
        state.broadcast_log(f"config loaded from {state.config_path}")
        return web.json_response(
            {"path": state.config_path, "services": sorted(cfg.services)}
        )

    async def config_save(request: web.Request) -> web.Response:
        body = await _body(request)
        cfg = state.config
        warnings: list[str] = []
        if "yaml" in body:
            # Editable-YAML flow: the edited text must validate before it
            # becomes the current config or touches disk. Same helper and
            # error shape as /config/validate, so a config the UI just
            # called valid can't flip verdicts at save time.
            try:
                cfg, warnings = _validate_yaml_text(
                    body["yaml"], bool(body.get("loose"))
                )
            except Exception as e:  # noqa: BLE001 - reported to client
                return web.json_response(_invalid_body(e), status=400)
        if cfg is None:
            return _json_error(404, "no config to save")
        path = os.path.expanduser(body.get("path", "lumen-config.yaml"))
        try:
            with open(path, "w", encoding="utf-8") as f:
                f.write(config_to_yaml(cfg))
        except OSError as e:
            # The edited config must NOT become current when the write
            # failed — the client was just told the save didn't happen.
            return _json_error(400, f"could not write {path}: {e}")
        state.config = cfg
        state.config_path = path
        state.broadcast_log(f"config saved to {path}")
        out = {"path": path}
        if warnings:
            out["warnings"] = warnings
        return web.json_response(out)

    async def config_yaml(request: web.Request) -> web.Response:
        if state.config is None:
            return _json_error(404, "no config generated or loaded yet")
        return web.Response(text=config_to_yaml(state.config), content_type="text/yaml")

    async def session_status(request: web.Request) -> web.Response:
        """Reference SessionHub's ``checkInstallationPath``
        (``web-ui/src/views/SessionHub.tsx``): given a saved config, is
        this deployment ready to start as-is? Loads the config,
        offline-checks every enabled model in the cache
        (``Downloader.check_all`` — never downloads), and recommends
        ``start_existing`` vs ``run_install`` vs ``open_config``."""
        from lumen_tpu.core.config import load_config
        from lumen_tpu.core.downloader import Downloader

        body = await _body(request)
        path = body.get("config_path") or state.config_path
        if not path:
            return web.json_response({
                "config_valid": False,
                "ready_to_start": False,
                "recommended_action": "open_config",
                "message": "no config loaded — open or generate one first",
            })
        try:
            cfg = load_config(path)
        except Exception as e:  # noqa: BLE001 - reported as a recommendation
            return web.json_response({
                "config_valid": False,
                "ready_to_start": False,
                "recommended_action": "open_config",
                "message": f"config at {path} does not validate: {e}",
            })
        cache_dir = os.path.expanduser(cfg.metadata.cache_dir)
        if not os.path.isdir(cache_dir):
            # Nothing cached — and constructing the Downloader would
            # os.makedirs the cache dir, a side effect a read-only status
            # check must not have.
            models = [
                {"service": s, "alias": a, "model": m.model, "present": False,
                 "error": f"cache dir {cache_dir} does not exist"}
                for s, svc in cfg.enabled_services().items()
                for a, m in svc.models.items()
            ]
        else:
            try:
                report = await asyncio.to_thread(lambda: Downloader(cfg).check_all())
            except Exception as e:  # noqa: BLE001 - recommend, don't 500
                return web.json_response({
                    "config_valid": True,
                    "config_path": os.path.expanduser(path),
                    "services": sorted(cfg.enabled_services()),
                    "models": [],
                    "ready_to_start": False,
                    "recommended_action": "run_install",
                    "message": f"could not check the cache at {cache_dir}: {e}",
                })
            models = [
                {"service": r.service, "alias": r.alias, "model": r.model,
                 "present": r.ok, **({"error": r.error} if r.error else {})}
                for r in report.results
            ]
        missing = [m for m in models if not m["present"]]
        ready = not missing
        return web.json_response({
            "config_valid": True,
            "config_path": os.path.expanduser(path),
            "services": sorted(cfg.enabled_services()),
            "models": models,
            "ready_to_start": ready,
            "recommended_action": "start_existing" if ready else "run_install",
            "message": (
                "all models present in the cache — the server can start as-is"
                if ready else
                f"{len(missing)} of {len(models)} models missing or invalid — run the installer"
            ),
        })

    async def config_presets(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "presets": {
                    name: {
                        "description": p.description,
                        "platform": p.platform,
                        "generation": p.generation,
                        "chips": p.chips,
                        "mesh_axes": p.mesh_axes,
                        "dtype": p.dtype,
                        "batch_size": p.batch_size,
                        "face_batch": p.face_batch,
                        "ocr_batch": p.ocr_batch,
                        "vlm_gen_batch": p.vlm_gen_batch,
                        "max_batch_latency_ms": p.max_batch_latency_ms,
                        "max_tier": p.max_tier,
                    }
                    for name, p in PRESETS.items()
                },
                "tiers": list(TIERS),
            }
        )

    # -- hardware ---------------------------------------------------------

    async def hardware_info(request: web.Request) -> web.Response:
        hw = await asyncio.to_thread(detect_hardware)
        return web.json_response(hw.as_dict())

    async def hardware_detect(request: web.Request) -> web.Response:
        report = await asyncio.to_thread(hardware_report)
        return web.json_response(report)

    async def hardware_check(request: web.Request) -> web.Response:
        """Environment readiness (reference ``/api/v1/hardware/check``:
        driver/env probes, ``api/hardware.py:115-196``) — TPU-flavored:
        jax stack versions, libtpu/PJRT, device nodes, cache-dir disk."""
        from lumen_tpu.app.env_check import environment_report

        cache_dir = request.query.get("cache_dir", "~/.lumen-tpu")
        report = await asyncio.to_thread(environment_report, cache_dir)
        return web.json_response(report)

    # -- install ----------------------------------------------------------

    async def install_setup(request: web.Request) -> web.Response:
        body = await _body(request)
        options = InstallOptions(
            venv_path=body.get("venv_path"),
            packages=list(body.get("packages", [])),
            release_packages=list(body.get("release_packages", [])),
            config_path=body.get("config_path") if body.get("download") else None,
            cache_dir=body.get("cache_dir"),
            region=body.get("region", "other"),
        )
        try:
            task = orchestrator.create_task(options)
        except OSError as e:  # unwritable/raced cache_dir is a caller error
            return _json_error(400, f"cache_dir unusable: {e}")
        runner = asyncio.ensure_future(orchestrator.run(task))
        # Hold a strong reference: the loop only weak-refs tasks, and a
        # GC'd runner would strand the install at status=running forever.
        _bg_tasks.add(runner)
        runner.add_done_callback(_bg_tasks.discard)
        return web.json_response(task.as_dict(), status=202)

    async def install_check_path(request: web.Request) -> web.Response:
        """Reference ``POST /install/check-path``: is this dir usable as an
        install/cache target (exists or creatable, writable, free space)."""
        body = await _body(request)
        if "path" not in body:
            return _json_error(400, "provide 'path'")
        path = os.path.abspath(os.path.expanduser(body["path"]))
        probe = path
        while not os.path.exists(probe):
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
        exists = os.path.isdir(path)
        # An existing non-directory (regular file) can never become the
        # cache dir; nor can a path whose first existing ancestor is a file.
        blocked = os.path.exists(path) and not os.path.isdir(path) or not os.path.isdir(probe)
        writable = os.access(probe, os.W_OK)
        try:
            import shutil as _sh

            free_gb = _sh.disk_usage(probe if os.path.isdir(probe) else os.path.dirname(probe) or "/").free / 1e9
        except OSError:
            free_gb = 0.0
        return web.json_response({
            "path": path,
            "exists": exists,
            "writable": writable,
            "free_gb": round(free_gb, 1),
            "ok": writable and not blocked,
        })

    async def install_logs(request: web.Request) -> web.Response:
        """Reference ``GET /install/logs/{task_id}``."""
        task = state.install_tasks.get(request.match_info["task_id"])
        if task is None:
            return _json_error(404, "unknown install task")
        limit = _int_query(request, "limit", 200)
        lines = list(task.log_lines)
        if limit:  # limit=0 means "all lines"
            lines = lines[-limit:]
        return web.json_response({"task_id": task.task_id, "lines": lines})

    async def install_tasks(request: web.Request) -> web.Response:
        return web.json_response(
            {"tasks": [t.as_dict() for t in state.install_tasks.values()]}
        )

    async def install_status(request: web.Request) -> web.Response:
        task = state.install_tasks.get(request.match_info["task_id"])
        if task is None:
            return _json_error(404, "unknown task")
        return web.json_response(task.as_dict())

    async def install_cancel(request: web.Request) -> web.Response:
        task = state.install_tasks.get(request.match_info["task_id"])
        if task is None:
            return _json_error(404, "unknown task")
        await orchestrator.cancel(task)
        return web.json_response({"task_id": task.task_id, "cancelling": True})

    # -- server -----------------------------------------------------------

    async def server_status(request: web.Request) -> web.Response:
        info = manager.info()
        info["healthy"] = await manager.health_check()
        return web.json_response(info)

    async def server_start(request: web.Request) -> web.Response:
        body = await _body(request)
        path = body.get("config_path") or state.config_path
        if not path:
            return _json_error(400, "no config_path given and none saved")
        try:
            info = await manager.start(path, extra_args=list(body.get("extra_args", [])))
        except RuntimeError as e:
            return _json_error(409, str(e))
        return web.json_response(info)

    async def server_stop(request: web.Request) -> web.Response:
        await manager.stop()
        return web.json_response(manager.info())

    async def server_restart(request: web.Request) -> web.Response:
        try:
            info = await manager.restart()
        except RuntimeError as e:
            return _json_error(409, str(e))
        return web.json_response(info)

    async def server_logs(request: web.Request) -> web.Response:
        """Reference ``GET /server/logs``: recent managed-server output
        (the WS stream only carries lines from after a client connects)."""
        limit = _int_query(request, "limit", 200)
        lines = [
            {"message": e.message, "level": e.level} for e in list(state.server_logs)
        ]
        if limit:  # limit=0 means "all lines"
            lines = lines[-limit:]
        return web.json_response({"lines": lines})

    # -- metrics ----------------------------------------------------------

    async def metrics(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "subscribers": state.subscriber_count,
                "install_tasks": len(state.install_tasks),
                "server": manager.info(),
                # Per-task latency histograms from the managed server's
                # observability sidecar (None unless started with
                # --metrics-port).
                "inference": await manager.fetch_metrics(),
            }
        )

    # -- websocket log stream --------------------------------------------

    async def ws_logs(request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        q = state.subscribe()
        await ws.send_json({"type": "connected"})

        async def sender() -> None:
            while True:
                try:
                    event = await asyncio.wait_for(q.get(), timeout=HEARTBEAT_S)
                    await ws.send_json({"type": "log", **event.as_dict()})
                except asyncio.TimeoutError:
                    await ws.send_json({"type": "heartbeat"})

        send_task = asyncio.ensure_future(sender())
        try:
            async for msg in ws:  # drain client frames until close
                if msg.type == WSMsgType.ERROR:
                    break
        finally:
            send_task.cancel()
            try:
                await send_task
            except (asyncio.CancelledError, ConnectionResetError, RuntimeError):
                pass
            state.unsubscribe(q)
        return ws

    app.router.add_get("/health", health)
    v1 = "/api/v1"
    app.router.add_post(f"{v1}/config/generate", config_generate)
    app.router.add_get(f"{v1}/config/current", config_current)
    app.router.add_post(f"{v1}/config/validate", config_validate)
    app.router.add_post(f"{v1}/config/validate-path", config_validate_path)
    app.router.add_post(f"{v1}/config/load", config_load)
    app.router.add_post(f"{v1}/config/save", config_save)
    app.router.add_get(f"{v1}/config/yaml", config_yaml)
    app.router.add_get(f"{v1}/config/presets", config_presets)
    app.router.add_post(f"{v1}/session/status", session_status)
    app.router.add_get(f"{v1}/hardware/info", hardware_info)
    app.router.add_get(f"{v1}/hardware/detect", hardware_detect)
    app.router.add_get(f"{v1}/hardware/check", hardware_check)
    app.router.add_post(f"{v1}/install/setup", install_setup)
    app.router.add_post(f"{v1}/install/check-path", install_check_path)
    app.router.add_get(f"{v1}/install/tasks", install_tasks)
    app.router.add_get(f"{v1}/install/status/{{task_id}}", install_status)
    app.router.add_get(f"{v1}/install/logs/{{task_id}}", install_logs)
    app.router.add_post(f"{v1}/install/cancel/{{task_id}}", install_cancel)
    app.router.add_get(f"{v1}/server/status", server_status)
    app.router.add_post(f"{v1}/server/start", server_start)
    app.router.add_post(f"{v1}/server/stop", server_stop)
    app.router.add_post(f"{v1}/server/restart", server_restart)
    app.router.add_get(f"{v1}/server/logs", server_logs)
    app.router.add_get(f"{v1}/metrics", metrics)
    app.router.add_get("/ws/logs", ws_logs)

    # Static SPA (web wizard), if built/present.
    web_dir = os.path.join(os.path.dirname(__file__), "web")
    if os.path.isdir(web_dir):
        async def index(request: web.Request) -> web.FileResponse:
            return web.FileResponse(os.path.join(web_dir, "index.html"))

        app.router.add_get("/", index)
        app.router.add_static("/ui", web_dir)

    async def _on_startup(app: web.Application) -> None:
        state.bind_loop(asyncio.get_running_loop())

    async def _on_cleanup(app: web.Application) -> None:
        await manager.stop(force=True)

    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)
    return app
