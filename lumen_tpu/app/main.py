"""Control-plane entry point: ``python -m lumen_tpu.app.main --port 8000``.

Reference equivalent: uvicorn serving the FastAPI app
(``lumen-app/src/lumen_app/main.py:45-148``).
"""

from __future__ import annotations

import argparse

from aiohttp import web

from lumen_tpu.app.api import build_app
from lumen_tpu.utils.logger import setup_logging


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="lumen-tpu control plane")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    setup_logging(level=args.log_level)
    app = build_app()
    web.run_app(app, host=args.host, port=args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
