"""Test-support subpackage: deterministic fault injection for the serving
stack (importable in production builds — every hook is a no-op until armed).
"""

from .faults import FaultInjected, FaultInjector, FaultRule, faults

__all__ = ["FaultInjected", "FaultInjector", "FaultRule", "faults"]
