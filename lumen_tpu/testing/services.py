"""Importable stand-in services for integration tests.

The built-in :class:`~lumen_tpu.serving.echo.EchoService` hard-codes its
task names, so a hub config with two echo-backed services would collide on
the route table. :class:`SecondaryEchoService` is the same diagnostic
service under distinct task keys — resilience tests point one config
service at each and fault-inject only one of them.
"""

from __future__ import annotations

import json

from ..core.config import ServiceConfig
from ..serving.base_service import BaseService
from ..serving.registry import TaskDefinition, TaskRegistry
from ..serving.services.search_service import SearchService


class SecondaryEchoService(BaseService):
    """Echo semantics under ``echo2*`` task names (see module docstring)."""

    def __init__(self, service_name: str = "echo2"):
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name="echo2",
                handler=self._echo,
                description="return the payload unchanged",
                input_mimes=("application/octet-stream", "text/plain"),
                output_mime="application/octet-stream",
            )
        )
        registry.register(
            TaskDefinition(
                name="echo2_meta",
                handler=self._echo_meta,
                description="return request meta as JSON",
                output_mime="application/json",
            )
        )
        super().__init__(registry)

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        return ["echo2", "echo2_meta"]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "SecondaryEchoService":  # noqa: ARG003
        return cls()

    def capability(self):
        return self.registry.build_capability(model_ids=["echo2"], runtime="none")

    def _echo(self, payload: bytes, mime: str, meta: dict[str, str]):  # noqa: ARG002
        return payload, mime or "application/octet-stream", {}

    def _echo_meta(self, payload: bytes, mime: str, meta: dict[str, str]):  # noqa: ARG002
        return json.dumps(meta, sort_keys=True).encode(), "application/json", {}


class SlowEchoService(BaseService):
    """Echo with a handler-side sleep (``sleep_s`` request meta, default
    0.3s) — the in-flight work the graceful-drain tests hold open across a
    SIGTERM to prove shutdown completes it instead of dropping it."""

    def __init__(self, service_name: str = "slow"):
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name="slow_echo",
                handler=self._slow_echo,
                description="sleep sleep_s (meta), then echo",
                input_mimes=("application/octet-stream", "text/plain"),
                output_mime="application/octet-stream",
            )
        )
        super().__init__(registry)

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        return ["slow_echo"]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "SlowEchoService":  # noqa: ARG003
        return cls()

    def capability(self):
        return self.registry.build_capability(model_ids=["slow-echo"], runtime="none")

    def _slow_echo(self, payload: bytes, mime: str, meta: dict[str, str]):
        import time

        time.sleep(float(meta.get("sleep_s", "0.3")))
        return payload, mime or "application/octet-stream", {"slow": "1"}


class SearchBenchService(SearchService):
    """The REAL :class:`~lumen_tpu.serving.services.search_service.
    SearchService` with a simulated device cost inside each shard's
    batcher dispatch: ``SEARCHBENCH_ROW_NS`` nanoseconds of sleep per
    corpus row the dispatch sweeps. That is where a chip would spend its
    time — per DISPATCH (coalesced queries share one sweep, like one
    matmul), serialized per shard (one device), proportional to the
    shard's committed rows (exact search is memory-bound on the corpus)
    — for a corpus that is sub-millisecond on CPU. Like
    :class:`FederationBenchService` it SLEEPS instead of spinning, so N
    subprocess hosts on one box scale like N hosts and ``bench.py
    --phase search`` can measure sharded fan-out honestly. Everything
    else (upsert, top-k, merge) is the unmodified ANN path, so the
    recall-vs-oracle segment exercises real code; handler threads only
    park on batcher futures, so a bulk upsert flood contends with
    queries exactly where the real system says it must: at the device,
    where upsert's bounded chunk writes interleave between dispatches."""

    def _batcher(self, tenant: str, shard: str):
        import os
        import time

        import numpy as np

        from ..runtime.ann import ann_k_cap
        from ..runtime.batcher import MicroBatcher

        key = (tenant, shard)
        with self._batcher_lock:
            got = self._batchers.get(key)
            if got is None:
                shard_obj = self.index.shard(tenant, shard)
                try:
                    row_ns = int(os.environ.get("SEARCHBENCH_ROW_NS") or 0)
                except ValueError:
                    row_ns = 0

                def fn(batch: np.ndarray, n_valid: int, _s=shard_obj):  # noqa: ARG001
                    if row_ns > 0:
                        time.sleep(_s.count * row_ns / 1e9)
                    scores, idx = _s.query_raw(np.asarray(batch), ann_k_cap())
                    return scores, idx

                got = MicroBatcher(
                    fn,
                    max_batch=self._batch_size,
                    max_latency_ms=self._max_latency_ms,
                    name=f"search:{tenant}:{shard}",
                ).start()
                self._batchers[key] = got
            return got


class FederationBenchService(BaseService):
    """CPU-only federation backend: a content-addressed "model" whose
    compute is a plain sleep (``device_ms`` request meta, default 20) run
    through the REAL result cache — ``get_or_compute`` with single-flight
    and, on peer-aware boots, the cross-host peer-lookup hook. Every
    actual compute bumps the ``fedbench_device_calls`` counter, so
    ``bench.py --phase federation`` can prove a duplicate payload sent to
    two different fleet entry points cost device work exactly once
    fleet-wide, with no model and no chip. The sleep (not a spin) is what
    lets N subprocess hosts on one box scale like N hosts."""

    def __init__(self, service_name: str = "fedbench"):
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name="fedbench_embed",
                handler=self._embed,
                description="sleep device_ms per unique payload, return its digest",
                input_mimes=("application/octet-stream",),
                output_mime="application/json",
            )
        )
        super().__init__(registry)

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        return ["fedbench_embed"]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "FederationBenchService":  # noqa: ARG003
        return cls()

    def capability(self):
        return self.registry.build_capability(model_ids=["fedbench"], runtime="none")

    def _embed(self, payload: bytes, mime: str, meta: dict[str, str]):  # noqa: ARG002
        import hashlib
        import os
        import time

        from ..runtime.result_cache import get_result_cache, make_namespace
        from ..utils import telemetry as tele
        from ..utils.metrics import metrics

        device_ms = float(meta.get("device_ms", "20"))
        # Per-HOST slowdown (a weak or co-tenanted box). Like device_ms it
        # shapes the simulated compute only, so it stays out of the cache
        # key — and being per-host it cannot ride request meta.
        try:
            device_ms *= float(os.environ.get("FEDBENCH_DEVICE_SCALE") or 1.0)
        except ValueError:
            pass
        try:
            pool = int(os.environ.get("LUMEN_GRPC_WORKERS") or 4)
        except ValueError:
            pool = 4

        def compute() -> dict:
            # The fleet-wide dedupe proof: this counter moving is the
            # ONLY evidence of "device" work, so summing it across hosts
            # counts exact computations per unique payload.
            metrics.count("fedbench_device_calls")
            t0 = time.monotonic()
            time.sleep(device_ms / 1e3)
            # Genuine busy-time accounting against the handler-pool
            # capacity: the host's device_duty is what capacity gossip
            # advertises, so a loaded bench host reports real duty.
            tele.set_capacity("device:fedbench", max(1, pool))
            tele.busy("device:fedbench", t0, time.monotonic())
            return {
                "digest": hashlib.sha256(payload).hexdigest(),
                "n_bytes": len(payload),
            }

        # device_ms deliberately stays OUT of the cache key (options=None):
        # it shapes the simulated compute, not the result.
        out = get_result_cache().get_or_compute(
            make_namespace("fedbench", "fedbench_embed", "fedbench", "0"),
            None,
            payload,
            compute,
        )
        return json.dumps(out).encode(), "application/json", {}
