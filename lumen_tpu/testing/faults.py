"""Fault-injection harness for the serving stack's failure paths.

Every resilience claim in this repo — "the hub boots with a broken model
download", "an overloaded batcher sheds instead of queueing", "recovery
kicks in once the fault clears" — is only as good as the test that forces
the failure. Real downloads and device calls fail rarely and
nondeterministically, so the failure-handling code is exactly the code a
normal test run never executes. This module plants named *fault points* on
those paths (``download``, ``model_load``, ``batch_execute``,
``batch_poison``, ``batch_hang``) that are free when disarmed and
deterministic when armed.

Usage (tests):

    from lumen_tpu.testing import faults
    faults.configure("download", times=2)       # fail the next 2 downloads
    ...
    faults.clear()                               # back to healthy

Usage (env, for a live server started by an integration harness):

    LUMEN_FAULTS="download:1:2,batch_execute:0.25" lumen-tpu --config ...

grammar ``point[:rate[:times]][@match]`` — ``rate`` is the per-check
probability (default 1.0, drawn from a seeded RNG: ``LUMEN_FAULTS_SEED``),
``times`` caps total injections (unset = unlimited), ``@match`` restricts
the rule to checks whose detail contains the substring.

The containment points (per-item match support):

- ``batch_poison`` — fails any dispatched batch CONTAINING a matching
  item. The batcher checks it once per item with detail
  ``{batcher}:{fingerprint}``, so ``@match`` on a payload fingerprint (the
  result-cache sha256 key) simulates ONE poison input: every sub-batch
  that still contains the item fails, every sub-batch without it
  succeeds — exactly the signal batch bisection isolates on. Arm it
  without ``times`` (bisection re-checks the point once per probe;
  a capped rule reads as a transient fault that bisection retries away).
  ``LUMEN_FAULTS="batch_poison@clip-image:<sha256-key>"``
- ``batch_hang`` — consulted via :meth:`FaultInjector.fires` (no raise):
  the batcher parks the dispatch where a wedged device call would block,
  until its watchdog (``LUMEN_BATCH_WATCHDOG_S``) fires or the batcher
  closes. ``LUMEN_FAULTS="batch_hang:1:1@vlm"`` hangs one VLM batch.
- ``tenant_flood`` — consulted via :meth:`FaultInjector.fires` by the
  per-tenant quota gate (:class:`~lumen_tpu.utils.qos.TenantQuota`) with
  the tenant id as detail: armed, the matched tenant's token bucket reads
  as exhausted, so every one of its requests sheds with the retry-after
  hint — a deterministic tenant flood with zero generated traffic.
  ``LUMEN_FAULTS="tenant_flood@team-a"`` floods tenant ``team-a`` only.
- ``kv_spill`` / ``kv_resume`` — the paged VLM engine's KV spill tier
  (``models/vlm/continuous.py``): ``kv_spill`` fails the page export of a
  preemption victim (detail ``{engine}:{slot}``), forcing the
  requeue-and-redo / typed-shed degradation ladder; ``kv_resume`` fails
  the page re-install of a parked spill record (detail
  ``{engine}:resume``) — a stand-in for a corrupt lease — which must
  degrade the same way, never hang or leak pages/leases.
  ``LUMEN_FAULTS="kv_spill:0.5"`` makes half of all spills fall back.

Production hooks call :meth:`FaultInjector.check`; its disarmed fast path
is one attribute read, so shipping the hooks costs nothing.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from dataclasses import dataclass, field

from ..core.exceptions import ResourceError

logger = logging.getLogger(__name__)

FAULTS_ENV = "LUMEN_FAULTS"
SEED_ENV = "LUMEN_FAULTS_SEED"

#: Fault points wired into the production stack. ``check`` accepts any
#: string (new points need no registry edit), but tests should prefer these.
DOWNLOAD = "download"
MODEL_LOAD = "model_load"
BATCH_EXECUTE = "batch_execute"
BATCH_POISON = "batch_poison"
BATCH_HANG = "batch_hang"
TENANT_FLOOD = "tenant_flood"
KV_SPILL = "kv_spill"
KV_RESUME = "kv_resume"


class FaultInjected(ResourceError):
    """The error raised at an armed fault point.

    Subclasses :class:`ResourceError` so the downloader's existing
    "never raises, report per model" contract treats an injected download
    failure exactly like a real one — the whole point is exercising the
    real handling path, not a parallel test-only one.
    """

    def __init__(self, point: str, detail: str = ""):
        super().__init__(f"injected fault at {point!r}", detail=detail or None)
        self.point = point


@dataclass
class FaultRule:
    point: str
    rate: float = 1.0
    times: int | None = None  # max injections; None = unlimited
    match: str = ""           # substring filter on the check's detail
    fired: int = 0            # injections so far (telemetry + cap)
    checked: int = 0          # checks that consulted this rule

    def exhausted(self) -> bool:
        return self.times is not None and self.fired >= self.times


class FaultInjector:
    """Thread-safe registry of armed fault rules, keyed by fault point."""

    def __init__(self, seed: int | None = None):
        self._lock = threading.Lock()
        self._rules: dict[str, FaultRule] = {}
        self._rng = random.Random(seed)
        self._env_loaded = False

    # -- configuration ----------------------------------------------------

    def configure(
        self,
        point: str,
        rate: float = 1.0,
        times: int | None = None,
        match: str = "",
    ) -> FaultRule:
        """Arm ``point``; replaces any existing rule for it."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fault rate must be in [0, 1], got {rate}")
        rule = FaultRule(point=point, rate=rate, times=times, match=match)
        with self._lock:
            self._rules[point] = rule
        logger.info("fault armed: %s rate=%s times=%s match=%r", point, rate, times, match)
        return rule

    def clear(self, point: str | None = None) -> None:
        """Disarm one point, or everything (also forgets the env spec so a
        cleared injector stays cleared)."""
        with self._lock:
            if point is None:
                self._rules.clear()
                self._env_loaded = True  # don't resurrect rules from env
            else:
                self._rules.pop(point, None)

    def reset(self) -> None:
        """Full reset: disarm everything AND re-read the env on next check
        (test teardown helper)."""
        with self._lock:
            self._rules.clear()
            self._env_loaded = False

    def load_env(self, spec: str | None = None) -> None:
        """Parse ``LUMEN_FAULTS`` (or an explicit spec string). Malformed
        entries are logged and skipped — a typo'd fault spec must degrade
        the *harness*, never crash the server under test."""
        spec = os.environ.get(FAULTS_ENV, "") if spec is None else spec
        seed = os.environ.get(SEED_ENV)
        if seed is not None:
            try:
                self._rng = random.Random(int(seed))
            except ValueError:
                logger.warning("ignoring malformed %s=%r", SEED_ENV, seed)
        for entry in filter(None, (e.strip() for e in spec.split(","))):
            body, _, match = entry.partition("@")
            parts = body.split(":")
            try:
                point = parts[0]
                rate = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
                times = int(parts[2]) if len(parts) > 2 and parts[2] else None
                if not point:
                    raise ValueError("empty fault point")
                self.configure(point, rate=rate, times=times, match=match)
            except (ValueError, IndexError) as e:
                logger.warning("ignoring malformed fault spec %r: %s", entry, e)

    # -- the production hook ----------------------------------------------

    def check(self, point: str, detail: str = "") -> None:
        """Raise :class:`FaultInjected` if ``point`` is armed for this call.

        Disarmed fast path: one dict read (after a one-time env parse), so
        the hooks are safe on hot paths.
        """
        if not self._env_loaded:
            with self._lock:
                pending = not self._env_loaded
                self._env_loaded = True
            if pending:
                self.load_env()
        if not self._rules:
            return
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return
            rule.checked += 1
            if rule.exhausted():
                return
            if rule.match and rule.match not in detail:
                return
            if rule.rate < 1.0 and self._rng.random() >= rule.rate:
                return
            rule.fired += 1
        logger.warning("injecting fault at %r (%s)", point, detail or "no detail")
        raise FaultInjected(point, detail)

    def fires(self, point: str, detail: str = "") -> bool:
        """Like :meth:`check` but reports instead of raising — for fault
        points whose production behavior is not an exception (e.g.
        ``batch_hang`` parks the thread). Same rule semantics: rate,
        times cap, ``@match`` on detail."""
        try:
            self.check(point, detail)
        except FaultInjected:
            return True
        return False

    # -- introspection ----------------------------------------------------

    def active(self) -> bool:
        with self._lock:
            return any(not r.exhausted() for r in self._rules.values())

    def rule(self, point: str) -> FaultRule | None:
        with self._lock:
            return self._rules.get(point)


#: Process-global injector consulted by the production hooks.
faults = FaultInjector()
