#!/usr/bin/env python
"""Example gRPC client for the lumen-tpu server.

Speaks the same wire protocol as reference Lumen clients
(``src/lumen/proto/ml_service.proto``): one bidi ``Infer`` stream per
request, task keyword on the first message, JSON result bytes back.

Usage (server from `python -m lumen_tpu.serving.server --config ...`):

    python examples/client.py caps
    python examples/client.py topology
    python examples/client.py health
    python examples/client.py stats --metrics-addr 127.0.0.1:9100 --window 60
    python examples/client.py autopilot --metrics-addr 127.0.0.1:9100
    python examples/client.py peers --metrics-addr 127.0.0.1:9100
    python examples/client.py embed-text "a photo of a cat"
    python examples/client.py embed-image photo.jpg
    python examples/client.py classify photo.jpg --top-k 5
    python examples/client.py faces photo.jpg
    python examples/client.py ocr scan.png
    python examples/client.py caption photo.jpg --prompt "Describe this photo."
    python examples/client.py caption photo.jpg --stream
    python examples/client.py bulk clip_image_embed *.jpg
    python examples/client.py upsert batch.json --tenant alice
    python examples/client.py search query_vec.json -k 10 --tenant alice

Large payloads are chunked with the protocol's seq/total/offset framing —
the same reassembly path reference clients use.
"""

from __future__ import annotations

import argparse
import json
import mimetypes
import os
import sys

import grpc
from google.protobuf import empty_pb2

from lumen_tpu.serving.proto import ml_service_pb2 as pb
from lumen_tpu.serving.proto import ml_service_pb2_grpc as pbg
from lumen_tpu.utils import tensorwire
from lumen_tpu.utils import trace as utrace
from lumen_tpu.utils.qos import RETRY_AFTER_META, TENANT_META_KEY

CHUNK = 1 << 20  # 1 MiB


def _as_bytes(part) -> bytes:
    """protobuf insists on ``bytes``: convert a memoryview slice at the
    last moment so an ndarray payload is copied exactly once, at proto
    construction — not once to serialize plus once per chunk."""
    return part if isinstance(part, bytes) else bytes(part)


def _tensor_item(payload, meta: dict[str, str]):
    """Normalize one payload: ndarrays become ``tensor/raw`` wire items
    (flat memoryview + dtype/shape meta merged over the caller's)."""
    import numpy as np

    if isinstance(payload, np.ndarray):
        buf, tmeta = tensorwire.tensor_payload(payload)
        return buf, tensorwire.TENSOR_MIME, {**meta, **tmeta}
    return payload, None, meta


def infer(stub, task: str, payload, mime: str = "application/octet-stream",
          meta: dict[str, str] | None = None, timeout: float = 300.0,
          stream: bool = False, tenant: str | None = None):
    """One Infer call. ``payload`` may be raw bytes (``mime`` describes
    them) or a numpy ndarray — arrays ride the ``tensor/raw`` wire path:
    dtype/shape meta, one serialization copy, and on the server side ZERO
    decode-pool work (the tensor goes straight to the batcher). Validate
    shapes against the service's ``tensor_input:<task>`` capability key
    before bulk traffic; a mismatch answers INVALID_ARGUMENT."""
    payload, tmime, meta = _tensor_item(payload, meta or {})
    return _infer(stub, task, payload, tmime or mime, meta, timeout,
                  stream=stream, tenant=tenant)


def _sidecar_get(metrics_addr: str, path: str, timeout: float = 10.0) -> dict:
    """One JSON GET against the observability sidecar. ``metrics_addr``
    is the sidecar's ``host:port`` (the server's ``--metrics-port``) or a
    full URL — the one place that normalization lives for every sidecar
    subcommand (stats, autopilot, peers)."""
    import urllib.request

    base = metrics_addr if "://" in metrics_addr else f"http://{metrics_addr}"
    with urllib.request.urlopen(f"{base.rstrip('/')}{path}", timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_stats(metrics_addr: str, window: float = 60.0, timeout: float = 10.0) -> dict:
    """Fetch the observability sidecar's rolling-window capacity view
    (``GET /stats?window=N``): last-N-seconds task latencies, device and
    decode-pool duty cycles, batch padding waste, HBM occupancy/headroom
    and the SLO burn summary."""
    return _sidecar_get(metrics_addr, f"/stats?window={int(window)}", timeout)


def _print_stats(stats: dict) -> None:
    """Operator view of the capacity-telemetry layer: one block per
    surface, omitting whatever the window saw nothing of."""
    w = stats.get("window_s", 0)
    print(f"window: last {w:.0f}s (telemetry {'on' if stats.get('enabled') else 'OFF'})")
    tasks = {
        name: s for name, s in stats.get("tasks", {}).items()
        if not name.startswith("stage:")
    }
    if tasks:
        print("tasks:")
        for name, s in tasks.items():
            print(
                f"  {name}: n={s['count']} rps={s.get('rps', 0)} "
                f"p50={s['p50_ms']}ms p95={s['p95_ms']}ms p99={s['p99_ms']}ms"
            )
    duty = stats.get("duty", {})
    if duty:
        print("duty cycles:")
        for name, d in duty.items():
            print(
                f"  {name}: {100 * d['fraction']:.1f}% busy "
                f"({d['busy_s']:.2f}s of {w:.0f}s x {d['capacity']:.0f})"
            )
    for batcher, b in stats.get("batch", {}).items():
        print(
            f"batch {batcher}: items={b.get('items', 0)} "
            f"padded={b.get('padded', 0)} "
            f"waste={b.get('padding_waste_pct', 0.0)}% "
            f"buckets={b.get('distinct_buckets', 0)}"
        )
    comp = stats.get("compile", {})
    if comp.get("compiles"):
        print(f"xla compiles: {comp['compiles']} in window (recompile storm?)")
    for dev, m in stats.get("device_memory", {}).items():
        if "occupancy_pct" in m:
            print(
                f"device {dev}: HBM {m['occupancy_pct']}% used, "
                f"headroom {m['headroom_bytes'] / 2**30:.2f} GiB "
                f"of {m.get('bytes_limit', 0) / 2**30:.2f} GiB"
            )
    slo = stats.get("slo", {})
    if slo:
        print("slo:")
        for task, rec in slo.items():
            print(
                f"  {task}: {rec.get('state')} burn_5m={rec.get('burn_5m')} "
                f"burn_1h={rec.get('burn_1h')}"
            )
    else:
        print("slo: no objectives configured (set LUMEN_SLO_<TASK>_P95_MS)")


def get_autopilot(metrics_addr: str, timeout: float = 10.0) -> dict:
    """Fetch the capacity controller's state from the observability
    sidecar (``GET /autopilot``): per-loop enable flags + latest sensor
    readings, the chip ledger, and the recent actuation decisions with
    the sensor readings that justified them."""
    return _sidecar_get(metrics_addr, "/autopilot", timeout)


def _print_autopilot(out: dict) -> None:
    """Operator view of the autopilot: policy header, one line per loop,
    then the decision tail (newest last)."""
    state = "running" if out.get("running") else (
        "enabled (not running)" if out.get("enabled") else "OFF"
    )
    print(f"autopilot: {state}")
    if not out.get("enabled") and not out.get("running"):
        print("  set LUMEN_AUTOPILOT=1 on the server to close the loops")
    if out.get("running"):
        print(
            f"  tick={out.get('tick_s', 0)}s cooldown={out.get('cooldown_s', 0)}s "
            f"sense={out.get('sense_window_s', 0)}s "
            f"rate<={out.get('rate_limit_per_min', 0)}/min "
            f"ticks={out.get('ticks', 0)} actuations={out.get('actuations', 0)}"
        )
    chips = out.get("chips") or {}
    if chips.get("capacity") is not None:
        print(
            f"  chip ledger: {chips.get('claimed', '?')} claimed "
            f"of {chips['capacity']}"
        )
    loops = out.get("loops") or {}
    for name, loop in loops.items():
        flag = "on" if loop.get("enabled") else "off (manual override)"
        detail = ""
        if name == "scale":
            fams = loop.get("families") or {}
            parts = [
                f"{fam}: duty={r.get('duty')} active={r.get('active')}"
                f"+{r.get('parked', 0)} parked"
                for fam, r in sorted(fams.items())
            ]
            detail = "; ".join(parts)
        elif name == "brownout":
            s = loop.get("sensors") or {}
            detail = f"rung={loop.get('rung', 0)} burn_5m={s.get('burn_5m')}"
        elif name == "window":
            caps = loop.get("batchers") or {}
            detail = "; ".join(
                f"{b}: waste={r.get('waste_pct')}% cap={r.get('cap_ms')}ms"
                for b, r in sorted(caps.items())
            )
        print(f"  loop {name}: {flag}" + (f" — {detail}" if detail else ""))
    decisions = out.get("decisions") or []
    if decisions:
        print(f"decisions (last {len(decisions)}):")
        for d in decisions:
            print(
                f"  [{d.get('loop')}] {d.get('component')}: {d.get('action')} "
                f"— {d.get('reason')}"
            )
    else:
        print("decisions: none recorded")


def get_peers(metrics_addr: str, timeout: float = 10.0) -> dict:
    """Fetch the federation fleet view from the observability sidecar
    (``GET /peers``): per-peer state (serving/ejected), dispatch/failover
    counters, ring ownership share, and the peer-cache hit rate."""
    return _sidecar_get(metrics_addr, "/peers", timeout)


def _print_peers(out: dict) -> None:
    """Operator view of the fleet: one line per peer, worst news first in
    each line (state), then traffic and cache columns."""
    if not out.get("enabled"):
        print("federation: not configured"
              + (f" ({out['detail']})" if out.get("detail") else ""))
        print("  set LUMEN_FED_PEERS (or LUMEN_FED_DISCOVER=1) on the server")
        return
    mode = out.get("mode", "?")
    print(f"federation: {mode} mode"
          + (f", self={out['self']}" if out.get("self") else "")
          + f", hop budget {out.get('hops', '?')}"
          + (f", role={out['role']}" if out.get("role") else ""))
    if out.get("capacity_gossip"):
        print("capacity gossip: on (ring weights follow reported headroom)")
    peers = out.get("peers") or {}
    for name, p in peers.items():
        state = p.get("state", "?")
        if p.get("draining"):
            # Drain leads the line: a planned handoff is the most
            # operator-relevant fact about this peer right now.
            state += " DRAINING"
        line = (
            f"  {name}: {state}"
            f" share={100 * p.get('ring_share', 0):.1f}%"
            f" dispatches={p.get('dispatches', 0)}"
            f" failovers={p.get('failovers', 0)}"
            f" sheds={p.get('sheds', 0)}"
        )
        # Gossiped capacity columns: present only when LUMEN_FED_CAPACITY
        # is armed on the server (the sidecar payload omits them
        # otherwise, so unconfigured output is unchanged).
        if p.get("weight") is not None:
            line += f" weight={p['weight']:.2f}"
        if p.get("duty") is not None:
            line += f" duty={100 * p['duty']:.0f}%"
        if p.get("burn_5m") is not None:
            line += f" burn_5m={p['burn_5m']}"
        if p.get("fed_role"):
            line += f" role={p['fed_role']}"
        hits, misses = p.get("cache_hits", 0), p.get("cache_misses", 0)
        if hits or misses:
            line += f" cache_hits={hits}/{hits + misses}"
        if state != "serving" and p.get("last_error"):
            line += f" last_error={p['last_error']!r}"
        print(line)
    mig = out.get("kv_migration") or {}
    if any(mig.values()):
        print(
            "kv migration:"
            f" out={mig.get('puts', 0)}"
            f" ({mig.get('put_bytes', 0)}B wire,"
            f" {mig.get('ref_pages', 0)} pages by-ref,"
            f" {mig.get('put_failures', 0)} failed,"
            f" {mig.get('lane_busy', 0)} lane-busy)"
            f" in={mig.get('in_commits', 0)}"
            f" ({mig.get('in_bytes', 0)}B,"
            f" {mig.get('in_rejected', 0)} rejected)"
        )
        puts, commits = mig.get("puts", 0), mig.get("in_commits", 0)
        if puts + commits:
            print(
                "duty split:"
                f" prefill {100 * puts / (puts + commits):.0f}%"
                f" / decode {100 * commits / (puts + commits):.0f}%"
            )
    print(f"peer-cache hit rate: {out.get('cache_peer_hit_rate', 0.0)}")


def _with_tenant(md, tenant: str | None):
    """Append the ``lumen-tenant`` request-metadata pair to the (possibly
    None) trace metadata — None stays None when there is nothing to send,
    preserving the exact no-metadata call shape for fakes/stubs."""
    if not tenant:
        return md
    return (*(md or ()), (TENANT_META_KEY, tenant))


def _shed_retry_after_s(meta, call=None) -> float | None:
    """Parse the server's ``lumen-retry-after-ms`` hint (sent on
    quota/queue/breaker/drain sheds) into seconds. Checked in response
    meta first; when absent there and ``call`` is the live RPC, the
    call's TRAILING metadata is scanned too — a federation front tier
    relaying an exhausted failover echoes the last peer's hint in the
    trailer, and the backoff floor must survive that hop exactly like a
    direct shed."""
    try:
        ms = int(meta[RETRY_AFTER_META])
    except (KeyError, TypeError, ValueError):
        ms = None
    if ms is None and call is not None:
        tm = getattr(call, "trailing_metadata", None)
        if callable(tm):
            try:
                for item in tm() or ():
                    key = getattr(item, "key", None)
                    if key is None and isinstance(item, (tuple, list)) and len(item) == 2:
                        key, value = item
                    else:
                        value = getattr(item, "value", None)
                    if key == RETRY_AFTER_META:
                        ms = int(value)
                        break
            except (TypeError, ValueError):
                ms = None
            except Exception:  # noqa: BLE001 - fakes without real metadata
                ms = None
    if ms is None:
        return None
    return ms / 1000.0 if ms > 0 else None


def _begin_client_trace(task: str):
    """Client half of end-to-end tracing (``LUMEN_TRACE_SAMPLE`` > 0 in
    the CLIENT environment): returns ``(trace, grpc_metadata)``. The
    trace id rides the ``lumen-trace`` request-metadata key, so the
    server's ``/traces`` records carry the SAME id as this process's
    recorder — one grep joins both sides of the RPC."""
    tr = utrace.begin_request(f"client:{task}")
    if tr is None:
        return None, None
    return tr, ((utrace.TRACE_META_KEY, tr.trace_id),)


def _requests(task: str, payload, mime: str, meta: dict[str, str]):
    """Yield chunked InferRequests (single message when small)."""
    if len(payload) <= CHUNK:
        yield pb.InferRequest(
            correlation_id="cli", task=task, payload=_as_bytes(payload),
            payload_mime=mime, meta=meta,
        )
        return
    total = (len(payload) + CHUNK - 1) // CHUNK
    for i in range(total):
        part = payload[i * CHUNK : (i + 1) * CHUNK]
        yield pb.InferRequest(
            correlation_id="cli", task=task, payload=_as_bytes(part),
            payload_mime=mime,
            meta=meta if i == 0 else {}, seq=i, total=total, offset=i * CHUNK,
        )


def _bulk_requests(task: str, items, mime: str, meta: dict[str, str]):
    """Chunked requests for N tagged items on ONE stream (correlation_id =
    item index; ``bulk: 1`` meta switches the server onto the concurrent
    fan-out lane). ndarray items ride ``tensor/raw`` with their own
    dtype/shape meta (see :func:`_tensor_item`)."""
    for i, raw_item in enumerate(items):
        payload, item_mime, item_meta = _tensor_item(raw_item, meta)
        cid = str(i)
        tagged = {**item_meta, "bulk": "1"}
        wire_mime = item_mime or mime
        if len(payload) <= CHUNK:
            yield pb.InferRequest(
                correlation_id=cid, task=task, payload=_as_bytes(payload),
                payload_mime=wire_mime, meta=tagged,
            )
            continue
        total = (len(payload) + CHUNK - 1) // CHUNK
        for j in range(total):
            part = payload[j * CHUNK : (j + 1) * CHUNK]
            yield pb.InferRequest(
                correlation_id=cid, task=task, payload=_as_bytes(part),
                payload_mime=wire_mime,
                meta=tagged if j == 0 else {}, seq=j, total=total, offset=j * CHUNK,
            )


def infer_bulk(stub, task: str, payloads=None, mime: str = "application/octet-stream",
               meta: dict[str, str] | None = None, timeout: float = 300.0,
               tenant: str | None = None, tensors=None):
    """Run MANY payloads through ONE ``Infer`` stream (the server's bulk
    fan-out lane): stream setup, admission and context bookkeeping are
    paid once, and the server coalesces the items into full device
    batches.

    ``tensors=`` (instead of, or mixed into, ``payloads``) sends
    pre-decoded ndarrays over the ``tensor/raw`` wire path — per-item
    dtype/shape meta, one serialization copy each, zero server-side
    decode. ``payloads`` items may themselves be ndarrays too.

    Yields ``(index, (result_bytes, mime, meta))`` per item AS RESPONSES
    ARRIVE — out of submission order. A per-item failure yields
    ``(index, ServiceError)`` instead; one poisoned payload never takes
    down its streammates."""
    from lumen_tpu.serving import ServiceError, reassemble_result

    if payloads is None:
        payloads = tensors if tensors is not None else []
    elif tensors is not None:
        payloads = list(payloads) + list(tensors)
    tr, md = _begin_client_trace(task)
    md = _with_tenant(md, tenant)
    # payloads may be any iterable (downstream only enumerates it) — a
    # len() here would make enabling tracing reject generator inputs.
    n_items = str(len(payloads)) if hasattr(payloads, "__len__") else "?"
    rpc_span = tr.begin("rpc.client", {"items": n_items}) if tr else None
    pending: dict[str, list] = {}
    try:
        yield from _infer_bulk_stream(
            stub, task, payloads, mime, meta, timeout, md, pending,
            ServiceError, reassemble_result,
        )
    except BaseException as e:
        if rpc_span is not None:
            rpc_span.end(error=type(e).__name__)
        utrace.finish_request(tr, error=f"{type(e).__name__}: {e}" if tr else None)
        raise
    else:
        if rpc_span is not None:
            rpc_span.end()
        utrace.finish_request(tr)


def _infer_bulk_stream(stub, task, payloads, mime, meta, timeout, md, pending,
                       ServiceError, reassemble_result):
    kwargs = {"timeout": timeout} if md is None else {"timeout": timeout, "metadata": md}
    for resp in stub.Infer(_bulk_requests(task, payloads, mime, meta or {}), **kwargs):
        cid = resp.correlation_id
        if resp.HasField("error") and (resp.error.code or resp.error.message):
            pending.pop(cid, None)
            yield int(cid), ServiceError(resp.error.code, resp.error.message, resp.error.detail)
            continue
        chunks = pending.setdefault(cid, [])
        chunks.append(resp)
        if not resp.is_final:
            continue
        del pending[cid]
        try:
            data, mime_out, meta_out = reassemble_result(chunks)
        except ServiceError as e:
            yield int(cid), e
            continue
        yield int(cid), (data, mime_out, meta_out)


_RETRYABLE_RPC = (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.RESOURCE_EXHAUSTED)


class _InbandUnavailable(Exception):
    """An in-band ERROR_CODE_UNAVAILABLE response: a load shed or degraded
    service that answered BEFORE dispatching the task, so re-sending is
    explicitly safe (the server's own detail says to retry with backoff).
    ``retry_after_s`` carries the server's ``lumen-retry-after-ms``
    response-meta hint when the shed sent one (quota/queue/breaker sheds
    all do) — the shared retry helper floors its backoff on it."""

    def __init__(self, code: int, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.code = code
        self.retry_after_s = retry_after_s


def _transient_rpc(exc: BaseException) -> bool:
    """Retry transport-level failures a backoff can fix: server not up yet,
    connection dropped during stream setup, or an overloaded backend
    shedding load. Anything the server *answered* (INVALID_ARGUMENT,
    INTERNAL, in-band Error responses) is not retried."""
    return isinstance(exc, grpc.RpcError) and exc.code() in _RETRYABLE_RPC


def _client_retry_policy():
    from lumen_tpu.utils.retry import RetryPolicy, policy_from_env

    return policy_from_env(
        "CLIENT", RetryPolicy(attempts=3, base_delay_s=0.5, max_delay_s=5.0)
    )


def _infer(stub, task: str, payload: bytes, mime: str, meta: dict[str, str],
           timeout: float, stream: bool = False, tenant: str | None = None):
    """One Infer attempt with stream-setup retries: an attempt that dies on
    a transient RpcError *before any response arrived* is retried with
    backoff (re-sending the request stream is safe then — the server never
    dispatched it to a handler we saw output from); after first byte the
    error propagates, since blind re-dispatch could double-run a task."""
    from lumen_tpu.utils.retry import retry_call

    state = {"responded": False}

    def attempt():
        return _infer_once(stub, task, payload, mime, meta, timeout, stream, state,
                           tenant=tenant)

    try:
        return retry_call(
            attempt,
            policy=_client_retry_policy(),
            retryable=lambda e: isinstance(e, _InbandUnavailable)
            or (not state["responded"] and _transient_rpc(e)),
            scope="client_infer",
        )
    except _InbandUnavailable as e:
        raise SystemExit(f"server error [{e.code}]: {e}") from e


def _infer_once(stub, task: str, payload: bytes, mime: str, meta: dict[str, str],
                timeout: float, stream: bool, state: dict, tenant: str | None = None):
    tr, md = _begin_client_trace(task)
    md = _with_tenant(md, tenant)
    rpc_span = tr.begin("rpc.client") if tr is not None else None
    try:
        out = _infer_attempt(stub, task, payload, mime, meta, timeout, stream, state, md)
    except BaseException as e:
        if tr is not None:
            rpc_span.end(error=type(e).__name__)
            utrace.finish_request(tr, error=f"{type(e).__name__}: {e}")
        raise
    if tr is not None:
        rpc_span.end()
        utrace.finish_request(tr)
    return out


def _infer_attempt(stub, task: str, payload: bytes, mime: str, meta: dict[str, str],
                   timeout: float, stream: bool, state: dict, md=None):
    from lumen_tpu.serving import ServiceError, reassemble_result

    state["responded"] = False
    # metadata only when tracing is on: fakes/stubs without the kwarg
    # (and the untraced hot path) keep the exact pre-trace call shape.
    kwargs = {"timeout": timeout} if md is None else {"timeout": timeout, "metadata": md}
    responses = stub.Infer(_requests(task, payload, mime, meta), **kwargs)
    chunked: list = []
    saw_deltas = False
    for resp in responses:
        state["responded"] = True
        if resp.error.message:
            if resp.error.code == pb.ERROR_CODE_UNAVAILABLE:
                # Shed / degraded-service answer: retryable by contract
                # (the server refused before dispatch; see _InbandUnavailable).
                # The response meta — or, for a front-tier relay, the RPC
                # trailer — may say exactly when to come back.
                raise _InbandUnavailable(
                    resp.error.code,
                    resp.error.message,
                    retry_after_s=_shed_retry_after_s(resp.meta, call=responses),
                )
            raise SystemExit(f"server error [{resp.error.code}]: {resp.error.message}")
        # Disambiguate the two total>1 shapes on the wire: a STREAMING
        # final message also carries total=n_deltas+1, but its deltas
        # arrived first with total=0 — only a result split by the
        # server's RESPONSE_CHUNK_BYTES starts chunked (total>1, seq 0).
        if (resp.total > 1 and not saw_deltas) or chunked:
            # reassemble_result joins AND enforces completeness — a stream
            # cut short before is_final must error, not return {}.
            chunked.append(resp)
            continue
        if resp.is_final:
            return json.loads(resp.result) if resp.result else {}
        saw_deltas = True
        if stream and resp.result:
            # Delta chunks are raw UTF-8 text (result_mime text/plain);
            # only the final response is JSON.
            print(resp.result.decode("utf-8", errors="replace"), end="", flush=True)
    if chunked:
        try:
            data, _mime, _meta = reassemble_result(chunked)
        except ServiceError as e:
            raise SystemExit(f"server error [{e.code}]: {e}") from e
        return json.loads(data) if data else {}
    return {}


def _read(path: str) -> tuple[bytes, str]:
    with open(path, "rb") as f:
        data = f.read()
    mime = mimetypes.guess_type(path)[0] or "application/octet-stream"
    return data, mime


def _load_json_arg(path: str):
    """Parse a JSON document from a file path or stdin (``-``)."""
    try:
        raw = sys.stdin.read() if path == "-" else open(path, encoding="utf-8").read()
        return json.loads(raw)
    except OSError as e:
        raise SystemExit(f"cannot read {path}: {e}") from e
    except ValueError as e:
        raise SystemExit(f"{path} is not valid JSON: {e}") from e


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description=(__doc__ or "lumen-tpu example client").splitlines()[0]
    )
    ap.add_argument("--addr", default="127.0.0.1:50051")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument(
        "--tenant",
        default=None,
        help="tenant id sent as lumen-tenant request metadata (server-side "
        "weighted-fair queuing + per-tenant quota; default: the 'default' tenant)",
    )
    ap.add_argument(
        "--priority",
        choices=("interactive", "bulk"),
        default=None,
        help="priority lane (interactive > bulk; the bulk command auto-tags bulk)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("caps")
    sub.add_parser(
        "topology",
        help="per-service device topology + replica fleet layout "
        "(StreamCapabilities extra: device_count, mesh_axes, replicas, "
        "dispatch policy, live replica states)",
    )
    sub.add_parser("health")
    p = sub.add_parser(
        "stats",
        help="rolling-window capacity view from the observability sidecar "
        "(windowed p50/p95 per task, device/decode duty cycles, HBM "
        "headroom, SLO burn)",
    )
    p.add_argument(
        "--metrics-addr",
        default="127.0.0.1:9100",
        help="host:port (or URL) of the server's --metrics-port sidecar",
    )
    p.add_argument("--window", type=float, default=60.0, help="window seconds")
    p.add_argument("--json", action="store_true", help="raw JSON instead of the summary")
    p = sub.add_parser(
        "autopilot",
        help="capacity-controller state from the observability sidecar "
        "(per-loop flags + sensors, chip ledger, recent actuation "
        "decisions with their justifying readings)",
    )
    p.add_argument(
        "--metrics-addr",
        default="127.0.0.1:9100",
        help="host:port (or URL) of the server's --metrics-port sidecar",
    )
    p.add_argument("--json", action="store_true", help="raw JSON instead of the summary")
    p = sub.add_parser(
        "peers",
        help="federation fleet view from the observability sidecar "
        "(per-peer serving/ejected state, ring ownership share, "
        "dispatch/failover counters, peer-cache hit rate)",
    )
    p.add_argument(
        "--metrics-addr",
        default="127.0.0.1:9100",
        help="host:port (or URL) of the server's --metrics-port sidecar",
    )
    p.add_argument("--json", action="store_true", help="raw JSON instead of the summary")
    p = sub.add_parser("embed-text"); p.add_argument("text")
    p = sub.add_parser("embed-image"); p.add_argument("image")
    p = sub.add_parser("classify"); p.add_argument("image"); p.add_argument("--top-k", type=int, default=5); p.add_argument("--scene", action="store_true")
    p = sub.add_parser("faces"); p.add_argument("image"); p.add_argument("--embed", action="store_true")
    p = sub.add_parser("ocr"); p.add_argument("image")
    p = sub.add_parser("caption"); p.add_argument("image")
    p.add_argument("--prompt", default="Describe this photo in one sentence.")
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--stream", action="store_true")
    p = sub.add_parser("bulk", help="many images down ONE stream (server bulk lane)")
    p.add_argument("task"); p.add_argument("images", nargs="+")
    p = sub.add_parser(
        "search",
        help="ANN top-k over the tenant's index — the query vector rides "
        "the tensor/raw wire path (zero server-side decode); a federated "
        "front fans it to the tenant's shard owners and merges",
    )
    p.add_argument("vector", help="path to a JSON array of floats ('-' = stdin)")
    p.add_argument("-k", "--top-k", type=int, default=10)
    p.add_argument(
        "--shard", default=None,
        help="pin one named shard (default: the server fans over all of them)",
    )
    p.add_argument("--json", action="store_true", help="raw response JSON instead of the ranked list")
    p = sub.add_parser(
        "upsert",
        help="index a vector batch — packed client-side as a tensor/bundle "
        "([vectors f32, ids as JSON-in-uint8]), the same raw-tensor shape "
        "the fleet-internal hop re-packs per shard",
    )
    p.add_argument(
        "batch",
        help="path to JSON {'ids': [...], 'vectors': [[...]]} ('-' = stdin)",
    )
    p.add_argument("--json", action="store_true", help="raw response JSON instead of the added/updated summary")
    args = ap.parse_args(argv)

    if args.cmd == "stats":
        # Sidecar HTTP, not gRPC: no channel needed (and none opened).
        stats = get_stats(args.metrics_addr, window=args.window)
        if args.json:
            print(json.dumps(stats, indent=2))
        else:
            _print_stats(stats)
        return 0
    if args.cmd == "autopilot":
        # Sidecar HTTP like stats: the controller's state and decision ring.
        out = get_autopilot(args.metrics_addr)
        if args.json:
            print(json.dumps(out, indent=2))
        else:
            _print_autopilot(out)
        return 0
    if args.cmd == "peers":
        # Sidecar HTTP like stats: the federation fleet view.
        out = get_peers(args.metrics_addr)
        if args.json:
            print(json.dumps(out, indent=2))
        else:
            _print_peers(out)
        return 0

    from lumen_tpu.utils.retry import retry_call

    chan = grpc.insecure_channel(args.addr)
    # Channel establishment retries: a server mid-restart (or mid-recovery)
    # comes up within a few backoff steps; a genuinely absent one still
    # fails fast enough to be usable interactively.
    retry_call(
        lambda: grpc.channel_ready_future(chan).result(timeout=min(args.timeout, 10)),
        policy=_client_retry_policy(),
        retryable=(grpc.FutureTimeoutError,),
        scope="client_connect",
    )
    stub = pbg.InferenceStub(chan)

    if args.cmd == "caps":
        caps = stub.GetCapabilities(empty_pb2.Empty(), timeout=args.timeout)
        print(json.dumps({
            "service": caps.service_name,
            "models": list(caps.model_ids),
            "runtime": caps.runtime,
            "tasks": [t.name for t in caps.tasks],
        }, indent=2))
        return 0
    if args.cmd == "topology":
        # The per-service capability records carry the fleet layout in
        # ``extra`` — a fleet-internal client picks its endpoint (and how
        # hard to fan out) from this, with zero Infer probes.
        topo_keys = (
            "device_count", "mesh_axes", "devices_per_replica", "replicas",
            "replica_policy", "replica_states", "breaker",
        )
        out = {}
        for cap in stub.StreamCapabilities(empty_pb2.Empty(), timeout=args.timeout):
            extra = dict(cap.extra)
            out[cap.service_name] = {
                "models": list(cap.model_ids),
                "max_concurrency": cap.max_concurrency,
                **{k: extra[k] for k in topo_keys if k in extra},
            }
        print(json.dumps(out, indent=2))
        return 0
    if args.cmd == "health":
        stub.Health(empty_pb2.Empty(), timeout=args.timeout)
        print("ok")
        return 0

    # QoS identity for every Infer this invocation makes: the tenant rides
    # gRPC request metadata, the priority lane rides request meta.
    qos_meta = {"priority": args.priority} if args.priority else {}

    def run_infer(task, payload, mime, meta, stream=False):
        return _infer(stub, task, payload, mime, {**qos_meta, **meta},
                      args.timeout, stream=stream, tenant=args.tenant)

    if args.cmd == "bulk":
        from lumen_tpu.serving import ServiceError

        payloads, mimes = zip(*(_read(p) for p in args.images))
        failed = 0
        for idx, res in infer_bulk(
            stub, args.task, list(payloads), mime=mimes[0], timeout=args.timeout,
            meta=qos_meta, tenant=args.tenant,
        ):
            name = args.images[idx]
            if isinstance(res, ServiceError):
                failed += 1
                print(f"{name}: ERROR [{res.code}] {res}")
                continue
            data, _mime, meta = res
            out = json.loads(data) if data else {}
            if "vector" in out:
                out["vector"] = f"[{len(out['vector'])} floats]"
            hit = " (cache hit)" if meta.get("cache_hit") == "1" else ""
            print(f"{name}{hit}: {json.dumps(out, ensure_ascii=False)}")
        return 1 if failed else 0

    if args.cmd == "search":
        import numpy as np

        vec = np.asarray(_load_json_arg(args.vector), np.float32)
        if vec.ndim != 1:
            raise SystemExit(f"query vector must be a flat array, got shape {vec.shape}")
        meta = dict(qos_meta)
        meta["k"] = str(args.top_k)
        if args.shard is not None:
            meta["shard"] = args.shard
        out = infer(stub, "search_query", vec, meta=meta,
                    timeout=args.timeout, tenant=args.tenant)
        if args.json:
            print(json.dumps(out, indent=2))
            return 0
        hits = list(zip(out.get("ids", []), out.get("scores", [])))
        if not hits:
            print(f"no hits (searched {out.get('shards', 0)} shards, "
                  f"tenant {out.get('tenant', 'default')!r})")
            return 0
        for rank, (vid, score) in enumerate(hits, 1):
            print(f"{rank:3d}. {score:8.4f}  {vid}")
        return 0
    if args.cmd == "upsert":
        import numpy as np

        body = _load_json_arg(args.batch)
        try:
            ids, vecs = body["ids"], np.asarray(body["vectors"], np.float32)
        except (TypeError, KeyError) as e:
            raise SystemExit(
                "batch must be JSON {'ids': [...], 'vectors': [[...]]}"
            ) from e
        payload = tensorwire.pack_bundle([
            vecs, np.frombuffer(json.dumps(ids).encode("utf-8"), np.uint8),
        ])
        out = run_infer("search_upsert", payload, tensorwire.BUNDLE_MIME, {})
        if args.json:
            print(json.dumps(out, indent=2))
        else:
            print(f"added={out.get('added', 0)} updated={out.get('updated', 0)}"
                  + (f" total={out['total']}" if "total" in out else "")
                  + f" tenant={out.get('tenant', 'default')}")
        return 0

    if args.cmd == "embed-text":
        out = run_infer("clip_text_embed", args.text.encode(), "text/plain", {})
    elif args.cmd == "embed-image":
        data, mime = _read(args.image)
        out = run_infer("clip_image_embed", data, mime, {})
    elif args.cmd == "classify":
        data, mime = _read(args.image)
        task = "clip_scene_classify" if args.scene else "clip_classify"
        out = run_infer(task, data, mime, {"topk": str(args.top_k)})
    elif args.cmd == "faces":
        data, mime = _read(args.image)
        task = "face_detect_and_embed" if args.embed else "face_detect"
        out = run_infer(task, data, mime, {})
    elif args.cmd == "ocr":
        data, mime = _read(args.image)
        out = run_infer("ocr", data, mime, {})
    elif args.cmd == "caption":
        data, mime = _read(args.image)
        meta = {
            "messages": json.dumps([{"role": "user", "content": args.prompt}]),
            "max_new_tokens": str(args.max_new_tokens),
            "do_sample": "false",
        }
        task = "vlm_generate_stream" if args.stream else "vlm_generate"
        out = run_infer(task, data, mime, meta, stream=args.stream)
        if args.stream:
            print()  # newline after streamed chunks
    else:  # pragma: no cover
        raise SystemExit(f"unknown command {args.cmd}")

    # Embeddings are long; print a compact view.
    if "vector" in out:
        vec = out.pop("vector")
        out["vector"] = f"[{len(vec)} floats: {vec[0]:.4f}, {vec[1]:.4f}, ...]"
    print(json.dumps(out, indent=2, ensure_ascii=False))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
