"""Token sampling for autoregressive generation — fully jit-safe.

The reference samples on host per step with numpy
(``lumen_vlm/backends/onnxrt_backend.py:508-533``: greedy, or temperature +
top-p over a sorted copy); here sampling lives inside the compiled decode
loop so generation never round-trips to host per token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """[..., V] -> [...] argmax token ids."""
    return jnp.argmax(logits, axis=-1)


def apply_repetition_penalty(
    logits: jnp.ndarray, token_mask: jnp.ndarray, penalty: float
) -> jnp.ndarray:
    """CTRL-style penalty over tokens already generated (``token_mask``:
    [..., V] bool). Positive logits are divided, negative multiplied."""
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(token_mask, penalized, logits)


def top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray | float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of sorted tokens whose
    cumulative probability reaches ``top_p``; the rest get -inf."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # Position k is kept if the cumulative mass BEFORE it is < top_p; the
    # top-1 token is always kept (top_p=0 must mean greedy, not empty set).
    keep_sorted = (cumulative - sorted_probs) < top_p
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # Threshold logit = smallest kept logit.
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def sample(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: jnp.ndarray | float = 1.0,
    top_p: jnp.ndarray | float = 1.0,
    do_sample: jnp.ndarray | bool = True,
) -> jnp.ndarray:
    """Temperature + top-p categorical sampling; falls back to greedy when
    ``do_sample`` is False or temperature ~ 0. All args may be traced values
    so one compiled program serves every generation config."""
    greedy_ids = greedy(logits)
    safe_temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits.astype(jnp.float32) / safe_temp
    filtered = top_p_filter(scaled, top_p)
    sampled_ids = jax.random.categorical(rng, filtered, axis=-1)
    use_sample = jnp.asarray(do_sample) & (jnp.asarray(temperature, jnp.float32) > 1e-6)
    return jnp.where(use_sample, sampled_ids, greedy_ids)
