"""Token sampling for autoregressive generation — fully jit-safe.

The reference samples on host per step with numpy
(``lumen_vlm/backends/onnxrt_backend.py:508-533``: greedy, or temperature +
top-p over a sorted copy); here sampling lives inside the compiled decode
loop so generation never round-trips to host per token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """[..., V] -> [...] argmax token ids."""
    return jnp.argmax(logits, axis=-1)


def _per_sample(value, logits: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a scalar or per-sample [...] param against [..., V] logits."""
    v = jnp.asarray(value, jnp.float32)
    if v.ndim == logits.ndim - 1 and v.ndim > 0:
        v = v[..., None]
    return v


def apply_repetition_penalty(
    logits: jnp.ndarray, token_mask: jnp.ndarray, penalty
) -> jnp.ndarray:
    """CTRL-style penalty over tokens already generated (``token_mask``:
    [..., V] bool). Positive logits are divided, negative multiplied.
    ``penalty`` may be a scalar or per-sample [B] (batched serving mixes
    request configs in one program)."""
    penalty = _per_sample(penalty, logits)
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(token_mask, penalized, logits)


def top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray | float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of sorted tokens whose
    cumulative probability reaches ``top_p``; the rest get -inf."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # Position k is kept if the cumulative mass BEFORE it is < top_p; the
    # top-1 token is always kept (top_p=0 must mean greedy, not empty set).
    keep_sorted = (cumulative - sorted_probs) < _per_sample(top_p, logits)
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # Threshold logit = smallest kept logit.
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def sample(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: jnp.ndarray | float = 1.0,
    top_p: jnp.ndarray | float = 1.0,
    do_sample: jnp.ndarray | bool = True,
) -> jnp.ndarray:
    """Temperature + top-p categorical sampling; falls back to greedy when
    ``do_sample`` is False or temperature ~ 0. All args may be traced values
    (scalars, or per-sample [B] vectors for batched mixed-config serving)
    so one compiled program serves every generation config."""
    greedy_ids = greedy(logits)
    scaled = logits.astype(jnp.float32) / jnp.maximum(_per_sample(temperature, logits), 1e-6)
    filtered = top_p_filter(scaled, top_p)
    sampled_ids = jax.random.categorical(rng, filtered, axis=-1)
    # [B]-or-scalar shaped, matching the ids
    hot = jnp.asarray(temperature, jnp.float32) > 1e-6
    use_sample = jnp.asarray(do_sample) & hot
    return jnp.where(use_sample, sampled_ids, greedy_ids)
