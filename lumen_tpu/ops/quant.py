"""Int8 linear building blocks shared by the model families.

Two serving motivations, two kernel modes (``QDense``):

- **bandwidth-bound** decode (VLM): weight-only ``dequant`` streams one
  byte per weight element from HBM;
- **compute-bound** batch embedding (CLIP): ``dynamic`` W8A8 runs a
  native ``int8 x int8 -> int32`` MXU dot — TPU int8 peak is ~2x bf16
  (v5e: 394.7 int8 TOPS vs 197.1 bf16 TFLOP/s), so an MXU-bound forward
  can beat bf16 outright, not just save memory.

The reference has no quantized execution path at all (its ONNX sessions
run the exported precision as-is).
"""

from __future__ import annotations

import logging
import re

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .quant_matmul import pallas_usable, w8a16_matmul

logger = logging.getLogger(__name__)


class QDense(nn.Module):
    """Int8 linear over weight-only quantized params (``q: [in, out]
    int8`` + per-output-channel fp32 ``scale``), two execution modes:

    - ``dequant``: ``y = (x @ q.astype(x.dtype)) * scale`` — one byte per
      weight element of HBM traffic IF XLA fuses the convert into the
      dot's operand read. At decode-sized row counts with bf16
      activations this routes to the Pallas w8a16 kernel, which computes
      the dot in bf16 with f32 scale (the bf16 compute contract —
      ``ops/quant_matmul.pallas_usable`` keeps f32 callers and
      tensor-parallel meshes on the XLA fallback, which computes in the
      caller's dtype and shards under GSPMD).
    - ``dynamic``: quantize activations per token (symmetric, abs-max)
      and run a native ``int8 x int8 -> int32`` dot on the MXU —
      ``y = (qx @ q) * sx * scale`` — no weight convert anywhere. Adds
      ~0.4% relative activation-rounding error; quality impact is
      negligible next to the int8 weight grid itself.
    """

    features: int
    use_bias: bool = True
    kernel_mode: str = "dequant"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        q = self.param(
            "q", lambda key, shape: jnp.zeros(shape, jnp.int8), (d, self.features)
        )
        scale = self.param("scale", nn.initializers.ones, (self.features,), jnp.float32)
        rows = 1
        for dim in x.shape[:-1]:
            rows *= dim
        if self.kernel_mode == "dequant" and pallas_usable(rows, d, self.features, x.dtype):
            # Decode-shape dequant: XLA lowers dot(x, convert(s8)) at tiny
            # row counts to a VPU broadcast-multiply-reduce (measured 34x
            # slower than bf16 on v5e — see ops/quant_matmul.py); the
            # Pallas kernel streams s8 tiles and feeds the MXU instead.
            y = w8a16_matmul(x, q, scale)
        elif self.kernel_mode == "dynamic":
            sx = jnp.maximum(
                jnp.max(jnp.abs(x), axis=-1, keepdims=True).astype(jnp.float32) / 127.0,
                1e-8,
            )
            qx = jnp.clip(
                jnp.round(x.astype(jnp.float32) / sx), -127, 127
            ).astype(jnp.int8)
            acc = jax.lax.dot_general(
                qx, q,
                dimension_numbers=(((qx.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            y = (acc.astype(jnp.float32) * sx * scale).astype(x.dtype)
        elif self.kernel_mode == "dequant":
            y = jnp.dot(x, q.astype(x.dtype)) * scale.astype(x.dtype)
        else:
            # A typo'd mode silently running the wrong kernel would
            # mis-attribute every benchmark/serving number it produces.
            raise ValueError(
                f"kernel_mode must be 'dequant' or 'dynamic', got {self.kernel_mode!r}"
            )
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
            y = y + bias.astype(x.dtype)
        return y


def quantize_tree_int8(params: dict, kernel_pattern: re.Pattern, what: str) -> dict:
    """Replace each ``.../kernel`` leaf matching ``kernel_pattern`` with
    ``.../q`` (int8, symmetric) + ``.../scale`` (fp32 per output channel).
    Apply AFTER the dtype-policy cast so the quantization grid is computed
    from the weights serving would otherwise use."""
    from ..runtime.weights import flatten, unflatten

    flat = flatten(params)
    out: dict = {}
    n_quant = 0
    for path, leaf in flat.items():
        if kernel_pattern.match(path):
            w = np.asarray(leaf, np.float32)
            scale = np.maximum(np.abs(w).max(axis=0) / 127.0, 1e-8)  # [out]
            q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
            prefix = path[: -len("kernel")]
            out[prefix + "q"] = q
            out[prefix + "scale"] = scale.astype(np.float32)
            n_quant += 1
        else:
            out[path] = leaf
    logger.info("int8 weight quantization: %d %s projections", n_quant, what)
    return unflatten(out)


def resolve_q8_kernel(default: str) -> str:
    """The ``LUMEN_Q8_KERNEL`` env knob, validated. Defaults differ by
    family — "dequant" for the bandwidth-bound VLM decoder, "dynamic"
    (W8A8) for the compute-bound CLIP towers — so the caller passes its
    own; one knob A/Bs both on chip."""
    import os

    kernel = os.environ.get("LUMEN_Q8_KERNEL", default)
    if kernel not in ("dequant", "dynamic"):
        raise ValueError(
            f"LUMEN_Q8_KERNEL must be 'dequant' or 'dynamic', got {kernel!r}"
        )
    return kernel
