"""Attention ops: XLA reference implementation + Pallas flash-attention.

Used by the CLIP towers (bidirectional), the VLM prefill (causal, long
sequences — this is where flash attention pays, SURVEY.md §7 step 7) and
ring attention (``lumen_tpu.parallel.ring_attention`` wraps the blockwise
math over a ``seq`` mesh axis).

Layouts: ``q/k/v`` are ``[batch, heads, seq, head_dim]``. GQA callers repeat
KV heads before calling (XLA fuses the broadcast).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # VMEM lane width; scratch stats are padded to this

from ..utils.env import env_int
from .pallas_compat import CompilerParams

#: batch*heads and q-block axes carry no state between steps, so megacore
#: chips (v4/v5p: two TensorCores per chip) may split them; the k axis is
#: the online-softmax accumulation and must stay sequential.
_DIM_SEMANTICS = CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)


def _on_tpu() -> bool:
    """True when the default backend executes on TPU hardware. The axon
    PJRT tunnel registers the platform as ``"axon"`` (canonicalized to tpu
    for lowering), so checking for ``"tpu"`` alone misses the real chip."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # backend init can fail; callers fall back to XLA
        return False


def flash_enabled() -> bool:
    """Would :func:`attention` route an unmasked long-sequence call through
    the Pallas kernel right now? (Reported by ``bench.py`` so perf numbers
    record which attention path produced them.)"""
    return _flash_usable(0, None, _min_flash_seq())


def flash_for_seq(sq: int) -> bool:
    """Would :func:`attention` use the Pallas kernel for THIS query length?
    Workload-accurate variant of :func:`flash_enabled` — the CLIP towers
    (seq 50/77) sit below the min-seq gate, so benchmarks must not stamp
    their numbers with the long-sequence answer."""
    return _flash_usable(0, None, sq)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Plain XLA attention. ``mask``: broadcastable to [B,H,Sq,Sk]; True=keep.

    Causal semantics for sq != sk match a KV-cache decode: query i may
    attend keys ``<= i + sk - sq`` (``tril`` offset by ``sk - sq``).
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal_mask, logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


# -- pallas flash attention -------------------------------------------------
#
# Grid: (batch*heads, q_blocks, k_blocks). The TPU grid runs sequentially
# with the last axis fastest, so the online-softmax running stats for one
# (head, q_block) live in VMEM scratch across the k_block steps: only one
# (block_q, d) Q tile and one (block_k, d) K/V tile are VMEM-resident at a
# time — O(block) memory however long the sequence is.


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    causal: bool,
    sm_scale: float,
    offset: int,
    kv_len: int,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: skip blocks entirely above the (offset) diagonal.
    if causal:
        block_live = j * block_k <= (qi + 1) * block_q - 1 + offset
    else:
        block_live = j * block_k < kv_len

    @pl.when(block_live)
    def _update():
        q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [block_q, block_k]
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        live = k_pos < kv_len  # mask K padding
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            live = live & (q_pos + offset >= k_pos)
        s = jnp.where(live, s, NEG_INF)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + p.sum(axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, 0]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention via Pallas on TPU (online softmax, O(block) VMEM).

    Handles ``sq != sk`` (KV-cache decode offset) and sequences that are
    not block multiples (padded K positions are masked inside the kernel).
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)

    block_q_eff = min(block_q, max(sq, 16))
    block_k_eff = min(block_k, max(sk, 16))
    qp = _pad_to(q, 2, block_q_eff)
    kp = _pad_to(k, 2, block_k_eff)
    vp = _pad_to(v, 2, block_k_eff)
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    num_k_blocks = sk_p // block_k_eff

    qkv = (qp.reshape(b * h, sq_p, d), kp.reshape(b * h, sk_p, d), vp.reshape(b * h, sk_p, d))
    grid = (b * h, sq_p // block_q_eff, num_k_blocks)
    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        sm_scale=sm_scale,
        offset=sk - sq,
        kv_len=sk,
        block_q=block_q_eff,
        block_k=block_k_eff,
        num_k_blocks=num_k_blocks,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q_eff, d), lambda i, qi, j: (i, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k_eff, d), lambda i, qi, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k_eff, d), lambda i, qi, j: (i, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q_eff, d), lambda i, qi, j: (i, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q_eff, d), jnp.float32),
            pltpu.VMEM((block_q_eff, _LANES), jnp.float32),
            pltpu.VMEM((block_q_eff, _LANES), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=interpret,
    )(*qkv)
    return out.reshape(b, h, sq_p, d)[:, :, :sq]


# -- cache-aware flash attention (VLM prefill/decode path) ------------------
#
# Same online-softmax scheme, but masking is driven by two [B] scalar-
# prefetch arrays instead of a static causal triangle:
#   q_offsets[b]  absolute position of sample b's FIRST query token
#                 (query i is at q_offsets[b] + i; positions are contiguous)
#   kv_valid[b]   number of live key slots (prefill: prompt length;
#                 decode: cache fill level + 1)
# key j is visible to query i iff  j <= q_offsets[b] + i  AND  j < kv_valid[b]
# — exactly the (live & causal) mask of the VLM cache path
# (models/vlm/modeling.py:228-240), computed in-kernel instead of as a
# [B, 1, S, K] bool tensor in HBM.


def _flash_cache_kernel(
    q_off_ref,  # [B] int32 (SMEM, prefetched)
    kv_valid_ref,  # [B] int32 (SMEM, prefetched)
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    heads: int,
    sm_scale: float,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
):
    i = pl.program_id(0)  # fused batch*heads index
    qi = pl.program_id(1)
    j = pl.program_id(2)
    b = i // heads
    q_off = q_off_ref[b]
    kv_valid = kv_valid_ref[b]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Skip blocks fully above the causal diagonal or past the live slots.
    max_k_this_q = q_off + (qi + 1) * block_q - 1  # largest visible key pos
    block_live = (j * block_k <= max_k_this_q) & (j * block_k < kv_valid)

    @pl.when(block_live)
    def _update():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        q_abs = q_off + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        live = (k_pos < kv_valid) & (k_pos <= q_abs)
        s = jnp.where(live, s, NEG_INF)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + p.sum(axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, 0]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret")
)
def flash_attention_cache(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_offsets: jax.Array,
    kv_valid: jax.Array,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention against a KV buffer with per-sample causal offsets
    and live-slot counts (see block comment above)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)

    block_q_eff = min(block_q, max(sq, 16))
    block_k_eff = min(block_k, max(sk, 16))
    qp = _pad_to(q, 2, block_q_eff)
    kp = _pad_to(k, 2, block_k_eff)
    vp = _pad_to(v, 2, block_k_eff)
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    num_k_blocks = sk_p // block_k_eff
    # Padded key slots beyond sk must never win: kv_valid <= sk by contract.

    kernel = functools.partial(
        _flash_cache_kernel,
        heads=h,
        sm_scale=sm_scale,
        block_q=block_q_eff,
        block_k=block_k_eff,
        num_k_blocks=num_k_blocks,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, sq_p // block_q_eff, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q_eff, d), lambda i, qi, j, *_: (i, qi, 0)),
            pl.BlockSpec((1, block_k_eff, d), lambda i, qi, j, *_: (i, j, 0)),
            pl.BlockSpec((1, block_k_eff, d), lambda i, qi, j, *_: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q_eff, d), lambda i, qi, j, *_: (i, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q_eff, d), jnp.float32),
            pltpu.VMEM((block_q_eff, _LANES), jnp.float32),
            pltpu.VMEM((block_q_eff, _LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        compiler_params=_DIM_SEMANTICS,
        interpret=interpret,
    )(
        q_offsets.astype(jnp.int32),
        kv_valid.astype(jnp.int32),
        qp.reshape(b * h, sq_p, d),
        kp.reshape(b * h, sk_p, d),
        vp.reshape(b * h, sk_p, d),
    )
    return out.reshape(b, h, sq_p, d)[:, :, :sq]


#: Below this query length the whole problem fits one fused XLA attention
#: and the kernel's grid degenerates (CLIP towers are seq 50/77: the grid
#: would be (B*heads, 1, 1) sequential steps of sub-MXU-tile matmuls).
#: Flash pays where online softmax saves HBM traffic — long sequences.
_MIN_FLASH_SEQ_DEFAULT = 256


def _min_flash_seq() -> int:
    return env_int("LUMEN_FLASH_MIN_SEQ", _MIN_FLASH_SEQ_DEFAULT)


#: fallback reasons already logged this process (log ONCE per distinct
#: reason — the dispatch sits inside jitted-model call paths that run per
#: request; a silent fallback is undebuggable but a log-per-call is worse)
_FALLBACK_LOGGED: set[str] = set()


def _log_fallback_once(reason: str) -> None:
    if reason in _FALLBACK_LOGGED:
        return
    _FALLBACK_LOGGED.add(reason)
    import logging

    logging.getLogger(__name__).info(
        "flash attention NOT selected: %s (XLA reference path serves this "
        "shape; set LUMEN_FLASH=1 to force the kernel)", reason
    )


def _flash_usable(head_dim: int, mask, sq: int) -> bool:
    force = os.environ.get("LUMEN_FLASH")
    if force == "0":
        _log_fallback_once("disabled by LUMEN_FLASH=0")
        return False
    if mask is not None:
        _log_fallback_once("explicit attention mask (kernel supports none/causal only)")
        return False
    if head_dim > 256:
        _log_fallback_once(f"head_dim {head_dim} > 256 exceeds the kernel's VMEM tile")
        return False
    if force == "1":  # tests force the kernel on small CPU shapes
        return True
    if not _on_tpu():
        _log_fallback_once("backend is not TPU (Pallas kernel is TPU-only)")
        return False
    if sq < _min_flash_seq():
        _log_fallback_once(
            f"seq {sq} < LUMEN_FLASH_MIN_SEQ ({_min_flash_seq()}): one fused "
            "XLA einsum beats a degenerate one-block kernel grid"
        )
        return False
    return True


def record_flash_ab(ref_ms: float, flash_ms: float, block: str, platform: str) -> dict:
    """Publish a flash-vs-reference A/B verdict as the ``flash-ab`` gauge
    provider (and return the gauge dict). ``bench.py phase_flash_ab``
    calls this so the measured verdict lands on /metrics instead of
    being visible only in the bench JSON tail; a negative verdict
    (``speedup_pct < 100``) alongside ``flash_attention: false`` in the
    capability report says the fallback is MEASURED, not an accident."""
    from ..utils.metrics import metrics

    speedup = ref_ms / flash_ms if flash_ms else 0.0
    verdict = {
        "ref_ms": round(ref_ms, 3),
        "flash_ms": round(flash_ms, 3),
        "speedup_pct": round(speedup * 100, 1),
        "flash_wins": 1 if speedup >= 1.0 else 0,
    }
    import logging

    logging.getLogger(__name__).info(
        "flash A/B verdict (%s, block %s): %.3fx reference", platform, block, speedup
    )
    metrics.register_gauges("flash-ab", lambda: dict(verdict))
    return verdict


def _interpret_mode() -> bool:
    """Pallas ``interpret=True`` when flash is forced on a non-TPU backend
    (tests exercise the kernel path on CPU)."""
    return not _on_tpu()


def _flash_blocks() -> tuple[int, int]:
    """Serving-path flash tile sizes (``LUMEN_FLASH_BLOCK_Q``/``_K``,
    default 128x128): the bench's on-chip block sweep
    (``bench.py phase_flash_ab``) picks the winner per chip generation and
    deployments apply it without a code change."""
    # Parsed independently: a typo in one variable must not discard a
    # valid value in the other. A tuning-knob typo (0, negative, huge)
    # must degrade, not crash the server — clamp to [16, 1024]; above
    # 1024 the q x k tile alone exceeds VMEM on every current TPU.
    def _one(name: str) -> int:
        return env_int(name, 128, minimum=16, maximum=1024)

    return (_one("LUMEN_FLASH_BLOCK_Q"), _one("LUMEN_FLASH_BLOCK_K"))


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Dispatch: Pallas flash kernel on TPU for unmasked/causal attention on
    sequences long enough to pay (``LUMEN_FLASH_MIN_SEQ``, default 256 —
    short-sequence callers like the CLIP towers stay on the fused XLA path,
    where one batched einsum beats a degenerate one-block kernel grid), XLA
    reference elsewhere (CPU tests, explicit masks). ``LUMEN_FLASH=0``
    disables the kernel; ``LUMEN_FLASH=1`` forces it (interpret mode off
    TPU, for tests)."""
    if _flash_usable(q.shape[-1], mask, q.shape[2]):
        bq, bk = _flash_blocks()
        return flash_attention(
            q, k, v, causal=causal, scale=scale,
            block_q=bq, block_k=bk, interpret=_interpret_mode(),
        )
    return attention_reference(q, k, v, mask=mask, causal=causal, scale=scale)


#: decode KV-bucket ladder starts here; caches at or below this length are
#: read whole (the switch overhead wouldn't pay).
_RAGGED_DECODE_MIN = 256


def _ragged_decode_enabled() -> bool:
    return os.environ.get("LUMEN_RAGGED_DECODE", "1") != "0"


def _decode_masked(q, k, v, q_offsets, kv_valid, scale):
    """Masked reference attention for the [Sq small] cache path."""
    sq, sk = q.shape[2], k.shape[2]
    key_slots = jnp.arange(sk)
    q_abs = q_offsets[:, None] + jnp.arange(sq)[None, :]  # [B, Sq]
    live = key_slots[None, :] < kv_valid[:, None]  # [B, Sk]
    causal = key_slots[None, None, :] <= q_abs[:, :, None]  # [B, Sq, Sk]
    mask = (live[:, None, :] & causal)[:, None]  # [B, 1, Sq, Sk]
    return attention_reference(q, k, v, mask=mask, scale=scale)


def attention_cached(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_offsets: jax.Array,
    kv_valid: jax.Array,
    scale: float | None = None,
    min_flash_q: int = 32,
) -> jax.Array:
    """Cache-path dispatch: the Pallas cache kernel when profitable (prefill-
    size query blocks on TPU), else the XLA reference with the equivalent
    [B, 1, Sq, Sk] mask.

    Single-token decode additionally applies RAGGED KV BUCKETING: the
    cache buffer is allocated at ``max_seq`` but a step only needs the
    live prefix, and a decode step's cost IS streaming the KV bytes. A
    ``lax.switch`` over a doubling ladder of static prefix lengths makes
    each step read ~``max(kv_valid)`` worth of cache instead of the whole
    buffer (the XLA-native slice of TPU paged attention's dead-block
    skip; disable with ``LUMEN_RAGGED_DECODE=0``). All branches share
    output shapes, so the switch compiles once inside the decode loop.
    """
    sq, sk = q.shape[2], k.shape[2]
    # Gate on the KEY length, not the query length: prefill chunks are
    # short (sq 64) against a long cache buffer (sk >> sq), and the
    # kernel's win is streaming those keys without a [B,1,Sq,Sk] HBM
    # mask. min_flash_q still keeps near-decode query blocks on the
    # cheaper masked path.
    if _flash_usable(q.shape[-1], None, sk) and sq >= min_flash_q:
        return flash_attention_cache(
            q, k, v, q_offsets, kv_valid, scale=scale, interpret=_interpret_mode()
        )
    if sq == 1 and sk > _RAGGED_DECODE_MIN and _ragged_decode_enabled():
        ladder = []
        length = _RAGGED_DECODE_MIN
        while length < sk:
            ladder.append(length)
            length *= 2
        ladder.append(sk)
        # Keys a decode step may attend: the live prefix (decode writes in
        # order, so slot indices >= kv_valid are dead for every row).
        bound = jnp.max(kv_valid)
        idx = jnp.searchsorted(jnp.asarray(ladder), bound, side="left")

        def branch(prefix_len):
            def run(q, k, v, q_offsets, kv_valid):
                return _decode_masked(
                    q, k[:, :, :prefix_len], v[:, :, :prefix_len],
                    q_offsets, kv_valid, scale,
                )

            return run

        return jax.lax.switch(
            idx, [branch(n) for n in ladder], q, k, v, q_offsets, kv_valid
        )
    return _decode_masked(q, k, v, q_offsets, kv_valid, scale)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, kv_heads, S, D] -> [B, kv_heads*n_rep, S, D] for GQA."""
    if n_rep == 1:
        return x
    b, h, s, d = x.shape
    return jnp.broadcast_to(x[:, :, None], (b, h, n_rep, s, d)).reshape(b, h * n_rep, s, d)


# -- ragged paged-attention decode (paged KV pool path) ---------------------
#
# The continuous VLM engine keeps KV in a pool of fixed-size pages
# ([num_pages, kv_heads, page_size, head_dim] per layer) with a per-row
# block table instead of one contiguous max_seq region per slot, so a
# decode step streams only the pages a row actually owns. The kernel grid
# is (batch*kv_heads, max_pages): the page axis runs sequentially and each
# step DMAs ONE page picked by the scalar-prefetched block table — the
# "ragged" part: row lengths differ, and dead pages (j beyond the row's
# live count) skip their matmul entirely. Per-page partial logits land in
# a VMEM scratch row and V pages in a VMEM V-scratch; the LAST page step
# runs one plain softmax over the assembled row. That finalize order (one
# max, one exp, one sum, one divide — not online rescaling) is what makes
# the kernel EXACTLY equal to the gathered XLA reference below, which the
# interpret-mode tier-1 test asserts bitwise.


def _q_group_pad(g: int) -> int:
    """Query-head group size padded to the f32 sublane (8) so the
    [Gp, ...] VMEM tiles are well-formed on real TPUs. The REFERENCE pads
    too: at g=1, XLA's matvec special-case produces ulp-different logits
    than the kernel's gemm, and the bitwise-equality contract between the
    two paths is worth more than 7 wasted rows of a tiny decode dot."""
    return max(8, -(-g // 8) * 8)


def _paged_decode_kernel(
    bt_ref,  # [B, MAXP] int32 block table (SMEM, prefetched)
    kv_len_ref,  # [B] int32 live tokens per row (SMEM, prefetched)
    q_ref,  # [1, 1, Gp, dh] query-head group for this (b, kv_head)
    k_ref,  # [1, 1, page, dh] one K page
    v_ref,  # [1, 1, page, dh] one V page
    o_ref,  # [1, 1, Gp, dh]
    s_ref,  # VMEM [Gp, MAXP*page] f32 raw logits
    v_acc_ref,  # VMEM [MAXP*page, dh] f32 gathered V row
    *,
    kv_heads: int,
    sm_scale: float,
    page: int,
    num_pages: int,
):
    i = pl.program_id(0)  # fused batch*kv_heads index
    j = pl.program_id(1)  # page slot within the row's block table
    b = i // kv_heads
    kv_len = kv_len_ref[b]

    @pl.when(j == 0)
    def _init():
        s_ref[...] = jnp.full_like(s_ref, NEG_INF)
        v_acc_ref[...] = jnp.zeros_like(v_acc_ref)

    # A page is live iff its first slot is below the row's live length;
    # (partially) live pages mask stale tail slots at finalize.
    @pl.when(j * page < kv_len)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [Gp, dh]
        k = k_ref[0, 0].astype(jnp.float32)  # [page, dh]
        # dot_general with the same (gd, sd -> gs) contraction the
        # reference einsum uses — a q @ k.T spelling lowers to a different
        # gemm microkernel on CPU and breaks the bitwise-equality test.
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        s_ref[:, pl.dslice(j * page, page)] = s
        v_acc_ref[pl.dslice(j * page, page), :] = v_ref[0, 0].astype(jnp.float32)

    @pl.when(j == num_pages - 1)
    def _finalize():
        s = s_ref[...]  # [Gp, MAXP*page]
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < kv_len, s, NEG_INF)
        # One-pass softmax in the reference's op order (max/exp/sum/div
        # then weights @ V) — NEG_INF is finite, so an all-dead row (free
        # slot) degrades to a uniform average of scratch garbage instead
        # of NaN; the scheduler never reads those rows.
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        w = p / l
        o_ref[0, 0] = jnp.dot(
            w, v_acc_ref[...], preferred_element_type=jnp.float32
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_attention_kernel(
    q: jax.Array,  # [B, H, dh] one decode token per row
    k_pages: jax.Array,  # [P, kv_heads, page, dh]
    v_pages: jax.Array,  # [P, kv_heads, page, dh]
    block_tables: jax.Array,  # [B, MAXP] int32 page ids (dead entries: 0)
    kv_lens: jax.Array,  # [B] int32 live tokens (current token included)
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Pallas ragged paged-attention (decode). See block comment above."""
    b, h, d = q.shape
    _, kv_heads, page, _ = k_pages.shape
    maxp = block_tables.shape[1]
    g = h // kv_heads
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    gp = _q_group_pad(g)
    qg = q.reshape(b, kv_heads, g, d)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    kernel = functools.partial(
        _paged_decode_kernel,
        kv_heads=kv_heads,
        sm_scale=sm_scale,
        page=page,
        num_pages=maxp,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * kv_heads, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d), lambda i, j, bt, kl: (i // kv_heads, i % kv_heads, 0, 0)),
            # The block table picks which page the DMA fetches — the ragged
            # indirection lives in the index map, not the kernel body.
            pl.BlockSpec((1, 1, page, d), lambda i, j, bt, kl: (bt[i // kv_heads, j], i % kv_heads, 0, 0)),
            pl.BlockSpec((1, 1, page, d), lambda i, j, bt, kl: (bt[i // kv_heads, j], i % kv_heads, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, gp, d), lambda i, j, bt, kl: (i // kv_heads, i % kv_heads, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((gp, maxp * page), jnp.float32),
            pltpu.VMEM((maxp * page, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv_heads, gp, d), q.dtype),
        compiler_params=CompilerParams(dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        qg,
        k_pages,
        v_pages,
    )
    return out[:, :, :g].reshape(b, h, d)


def paged_attention_reference(
    q: jax.Array,  # [B, H, dh]
    k_pages: jax.Array,  # [P, kv_heads, page, dh]
    v_pages: jax.Array,  # [P, kv_heads, page, dh]
    block_tables: jax.Array,  # [B, MAXP] int32
    kv_lens: jax.Array,  # [B] int32
    scale: float | None = None,
) -> jax.Array:
    """Exact XLA reference for ragged paged decode attention: gather each
    row's pages via its block table, mask slots past the row's live
    length, plain softmax. This is the CPU/tier-1 serving path; the Pallas
    kernel above must match it bitwise (interpret-mode test), which pins
    two choices here: the query-head group is padded like the kernel's
    (see :func:`_q_group_pad`) and the softmax is spelled max/exp/sum/div
    in the kernel's op order. Contract: ``kv_lens >= 1`` per row (the
    engine always counts the just-written token; an all-dead row's output
    is unspecified garbage on both paths)."""
    b, h, d = q.shape
    _, kv_heads, page, _ = k_pages.shape
    maxp = block_tables.shape[1]
    g = h // kv_heads
    gp = _q_group_pad(g)
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B, MAXP, kv_heads, page, dh] -> [B, kv_heads, MAXP*page, dh]
    k = k_pages[block_tables].transpose(0, 2, 1, 3, 4).reshape(b, kv_heads, maxp * page, d)
    v = v_pages[block_tables].transpose(0, 2, 1, 3, 4).reshape(b, kv_heads, maxp * page, d)
    qg = q.reshape(b, kv_heads, g, d).astype(jnp.float32)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    s = jnp.einsum(
        "bkgd,bksd->bkgs", qg, k.astype(jnp.float32), preferred_element_type=jnp.float32
    ) * sm_scale
    live = jnp.arange(maxp * page)[None, :] < kv_lens[:, None]  # [B, S]
    s = jnp.where(live[:, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    w = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bkgs,bksd->bkgd", w, v.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return out[:, :, :g].reshape(b, h, d).astype(q.dtype)


# -- variable-query-length paged attention (speculative verify path) --------
#
# Speculative decoding verifies K drafted tokens in ONE target step: each
# row carries a WINDOW of W = K+1 query tokens written at consecutive
# positions. Same grid and page streaming as the single-token kernel —
# the window folds into the query-row axis ([W*Gp, dh] per (b, kv_head)
# instead of [Gp, dh]) and the finalize mask becomes per-window-position
# causal: window slot t (row r -> t = r // Gp) sees key j iff
# j < kv_lens[b] + t, where kv_lens is the t=0 visibility (cur_len + 1,
# the just-written token included — identical to the single-token
# contract). W == 1 degenerates to the single-token kernel exactly.


def _paged_verify_kernel(
    bt_ref,  # [B, MAXP] int32 block table (SMEM, prefetched)
    kv_len_ref,  # [B] int32 t=0 visibility per row (SMEM, prefetched)
    q_ref,  # [1, 1, W*Gp, dh] window-folded query heads for this (b, kv_head)
    k_ref,  # [1, 1, page, dh] one K page
    v_ref,  # [1, 1, page, dh] one V page
    o_ref,  # [1, 1, W*Gp, dh]
    s_ref,  # VMEM [W*Gp, MAXP*page] f32 raw logits
    v_acc_ref,  # VMEM [MAXP*page, dh] f32 gathered V row
    *,
    kv_heads: int,
    sm_scale: float,
    page: int,
    num_pages: int,
    window: int,
    gp: int,
):
    i = pl.program_id(0)
    j = pl.program_id(1)
    b = i // kv_heads
    kv_len = kv_len_ref[b]

    @pl.when(j == 0)
    def _init():
        s_ref[...] = jnp.full_like(s_ref, NEG_INF)
        v_acc_ref[...] = jnp.zeros_like(v_acc_ref)

    # Live bound for the WIDEST window position: slot W-1 sees
    # kv_len + W - 1 keys, so pages past that are dead for every slot.
    @pl.when(j * page < kv_len + (window - 1))
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [W*Gp, dh]
        k = k_ref[0, 0].astype(jnp.float32)  # [page, dh]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        s_ref[:, pl.dslice(j * page, page)] = s
        v_acc_ref[pl.dslice(j * page, page), :] = v_ref[0, 0].astype(jnp.float32)

    @pl.when(j == num_pages - 1)
    def _finalize():
        s = s_ref[...]  # [W*Gp, MAXP*page]
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # row r belongs to window slot t = r // Gp and sees kv_len + t keys
        t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // gp
        s = jnp.where(pos < kv_len + t, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        w = p / l
        o_ref[0, 0] = jnp.dot(
            w, v_acc_ref[...], preferred_element_type=jnp.float32
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_attention_varq_kernel(
    q: jax.Array,  # [B, W, H, dh] verify window, position-ordered
    k_pages: jax.Array,  # [P, kv_heads, page, dh]
    v_pages: jax.Array,  # [P, kv_heads, page, dh]
    block_tables: jax.Array,  # [B, MAXP] int32 page ids (dead entries: 0)
    kv_lens: jax.Array,  # [B] int32 t=0 visibility (cur token included)
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Pallas ragged paged-attention over a W-token verify window per row
    (see block comment above)."""
    b, w, h, d = q.shape
    _, kv_heads, page, _ = k_pages.shape
    maxp = block_tables.shape[1]
    g = h // kv_heads
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    gp = _q_group_pad(g)
    qg = q.reshape(b, w, kv_heads, g, d)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, gp - g), (0, 0)))
    # Fold the window into the query-row axis: [B, kv_heads, W*Gp, dh].
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(b, kv_heads, w * gp, d)

    kernel = functools.partial(
        _paged_verify_kernel,
        kv_heads=kv_heads,
        sm_scale=sm_scale,
        page=page,
        num_pages=maxp,
        window=w,
        gp=gp,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * kv_heads, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, w * gp, d), lambda i, j, bt, kl: (i // kv_heads, i % kv_heads, 0, 0)),
            pl.BlockSpec((1, 1, page, d), lambda i, j, bt, kl: (bt[i // kv_heads, j], i % kv_heads, 0, 0)),
            pl.BlockSpec((1, 1, page, d), lambda i, j, bt, kl: (bt[i // kv_heads, j], i % kv_heads, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, w * gp, d), lambda i, j, bt, kl: (i // kv_heads, i % kv_heads, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((w * gp, maxp * page), jnp.float32),
            pltpu.VMEM((maxp * page, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv_heads, w * gp, d), q.dtype),
        compiler_params=CompilerParams(dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        qg,
        k_pages,
        v_pages,
    )
    out = out.reshape(b, kv_heads, w, gp, d)[:, :, :, :g]
    return out.transpose(0, 2, 1, 3, 4).reshape(b, w, h, d)


def paged_attention_varq_reference(
    q: jax.Array,  # [B, W, H, dh]
    k_pages: jax.Array,  # [P, kv_heads, page, dh]
    v_pages: jax.Array,  # [P, kv_heads, page, dh]
    block_tables: jax.Array,  # [B, MAXP] int32
    kv_lens: jax.Array,  # [B] int32 t=0 visibility
    scale: float | None = None,
) -> jax.Array:
    """Exact XLA reference for the verify-window kernel: same gather, same
    window-folded [W*Gp, S] logits matrix, same per-slot causal mask and
    max/exp/sum/div softmax order, so the interpret-mode kernel matches it
    bitwise exactly like the single-token pair."""
    b, w, h, d = q.shape
    _, kv_heads, page, _ = k_pages.shape
    maxp = block_tables.shape[1]
    g = h // kv_heads
    gp = _q_group_pad(g)
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    k = k_pages[block_tables].transpose(0, 2, 1, 3, 4).reshape(b, kv_heads, maxp * page, d)
    v = v_pages[block_tables].transpose(0, 2, 1, 3, 4).reshape(b, kv_heads, maxp * page, d)
    qg = q.reshape(b, w, kv_heads, g, d).astype(jnp.float32)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, gp - g), (0, 0)))
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(b, kv_heads, w * gp, d)
    s = jnp.einsum(
        "bkrd,bksd->bkrs", qg, k.astype(jnp.float32), preferred_element_type=jnp.float32
    ) * sm_scale
    t = jnp.arange(w * gp, dtype=jnp.int32) // gp  # window slot per folded row
    live = (
        jnp.arange(maxp * page, dtype=jnp.int32)[None, None, :]
        < kv_lens.astype(jnp.int32)[:, None, None] + t[None, :, None]
    )  # [B, R, S]
    s = jnp.where(live[:, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    wgt = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bkrs,bksd->bkrd", wgt, v.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    out = out.reshape(b, kv_heads, w, gp, d)[:, :, :, :g]
    return out.transpose(0, 2, 1, 3, 4).reshape(b, w, h, d).astype(q.dtype)


def _paged_kernel_usable(head_dim: int, maxp: int, page: int) -> bool:
    force = os.environ.get("LUMEN_PAGED_KERNEL")
    if force == "0":
        _log_fallback_once("paged kernel disabled by LUMEN_PAGED_KERNEL=0")
        return False
    if head_dim > 256:
        _log_fallback_once(
            f"paged kernel: head_dim {head_dim} > 256 exceeds the VMEM tile"
        )
        return False
    if maxp * page > 8192:
        # The finalize softmax keeps the whole assembled row in VMEM:
        # [Gp, MAXP*page] f32 logits + [MAXP*page, dh] f32 V scratch.
        _log_fallback_once(
            f"paged kernel: row capacity {maxp * page} > 8192 exceeds the "
            "VMEM scratch budget"
        )
        return False
    if force == "1":  # tests force interpret mode on CPU
        return True
    if not _on_tpu():
        _log_fallback_once("paged kernel: backend is not TPU")
        return False
    return True


def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    kv_lens: jax.Array,
    scale: float | None = None,
) -> jax.Array:
    """Dispatch: Pallas ragged paged-attention on TPU, exact XLA reference
    elsewhere (CPU tier-1 serves the reference so both paths are covered).
    ``LUMEN_PAGED_KERNEL=0`` disables the kernel; ``=1`` forces it
    (interpret mode off TPU, for tests). A 4-D ``q`` ([B, W, H, dh])
    selects the variable-query-length verify-window path (speculative
    decoding); ``kv_lens`` is then the t=0 visibility and slot t sees
    ``kv_lens + t`` keys."""
    usable = _paged_kernel_usable(q.shape[-1], block_tables.shape[1], k_pages.shape[2])
    if q.ndim == 4:
        if usable:
            return paged_attention_varq_kernel(
                q, k_pages, v_pages, block_tables, kv_lens,
                scale=scale, interpret=_interpret_mode(),
            )
        return paged_attention_varq_reference(
            q, k_pages, v_pages, block_tables, kv_lens, scale=scale
        )
    if usable:
        return paged_attention_kernel(
            q, k_pages, v_pages, block_tables, kv_lens,
            scale=scale, interpret=_interpret_mode(),
        )
    return paged_attention_reference(q, k_pages, v_pages, block_tables, kv_lens, scale=scale)
